"""Replica-axis data parallelism: shard stacked simulation states over a
device mesh and reduce statistics across devices inside one jit.

This is the TPU-native replacement for RunMultipleTimes' sequential
reseeded loop (RunMultipleTimes.java:48-63): R replicas run in lockstep,
sharded R/D per device; the statistics reduction (min/max/mean over the
(replica, node) axes) compiles to on-device partial reductions plus the
cross-device collective XLA chooses for the sharding — no host gather of
per-replica state ever happens.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def shard_replicas(states, mesh: Mesh, axis: str = "replicas"):
    """Place a stacked state pytree with leading replica axis onto the
    mesh, sharded along `axis` (replicated on any other mesh axes)."""
    sharding = NamedSharding(mesh, P(axis))
    return jax.tree_util.tree_map(lambda a: jax.device_put(a, sharding), states)


# compiled-program cache, keyed EXPLICITLY on (net.cache_key(), sim_ms) —
# protocol name + static engine knobs (see BatchedNetwork.cache_key) —
# instead of hashing the network object through lru_cache.  Bounded FIFO
# with a clear hook: long sweep campaigns that churn through many configs
# can flush it (clear_run_cache) rather than pinning 64 full jit programs
# (and the engines/latency tables their closures hold) for process life.
_RUN_CACHE: "OrderedDict[tuple, object]" = OrderedDict()
_RUN_CACHE_MAX = 64


def clear_run_cache() -> None:
    """Drop every cached compiled run program (the lru_cache.cache_clear
    analog for long campaigns)."""
    _RUN_CACHE.clear()


def run_cache_info() -> dict:
    return {"size": len(_RUN_CACHE), "maxsize": _RUN_CACHE_MAX}


def _run_and_reduce(net, sim_ms: int):
    """One compiled program per (net.cache_key(), sim_ms): repeated calls
    with an equivalent network hit the cache instead of re-tracing the
    full simulation."""
    key = (net.cache_key(), int(sim_ms))
    fn = _RUN_CACHE.get(key)
    if fn is not None:
        _RUN_CACHE.move_to_end(key)
        return fn

    @jax.jit
    def fn(s):
        out = net.run_ms_batched(s, sim_ms)
        live = ~out.down
        done = jnp.where(live, out.done_at, 0)
        n_live = jnp.maximum(1, jnp.sum(live.astype(jnp.int32)))
        stats = {
            "done_min": jnp.min(jnp.where(live, out.done_at, jnp.int32(2**31 - 1))),
            "done_max": jnp.max(done),
            "done_avg": jnp.sum(done) / n_live,
            "msg_rcv_avg": jnp.sum(jnp.where(live, out.msg_received, 0)) / n_live,
            "all_done": jnp.all(jnp.where(live, out.done_at > 0, True)),
        }
        return out, stats

    _RUN_CACHE[key] = fn
    while len(_RUN_CACHE) > _RUN_CACHE_MAX:
        _RUN_CACHE.popitem(last=False)
    return fn


def sharded_run_stats(net, states, sim_ms: int) -> Tuple[jax.Array, dict]:
    """Run the batched simulation on whatever sharding `states` carries and
    reduce done/traffic statistics across every device in the same program.
    Returns (final_states, stats dict of scalars)."""
    return _run_and_reduce(net, sim_ms)(states)

"""Replica-axis data parallelism: shard stacked simulation states over a
device mesh and reduce statistics across devices inside one jit.

This is the TPU-native replacement for RunMultipleTimes' sequential
reseeded loop (RunMultipleTimes.java:48-63): R replicas run in lockstep,
sharded R/D per device; the statistics reduction (min/max/mean over the
(replica, node) axes) compiles to on-device partial reductions plus the
cross-device collective XLA chooses for the sharding — no host gather of
per-replica state ever happens.

Cost accounting (ISSUE-7): every program this cache compiles goes
through the explicit AOT path (lower → compile → call), so the compiled
object is in hand to capture `cost_analysis()` / `memory_analysis()`
and the compile wall-clock.  The cache therefore knows, per (protocol,
config, horizon, input geometry): FLOPs, bytes accessed, live/temp HBM,
and compile seconds — run_cache_metrics() exports all of it, and the
hit/miss/eviction/compile-seconds counters feed the server's
witt_run_cache_* Prometheus families.

Warm starts (ISSUE-13): when a durable compile store is installed
(runtime.compile_store — set_compile_store / $WITT_COMPILE_STORE), the
per-geometry compile first consults the store under the engine's
*stable* cache key (net.stable_cache_key(), id()-free) and publishes
fresh compiles back to it.  A store hit bypasses lower().compile()
entirely, so the monotonic "compiles" counter genuinely stays 0 on a
warm restart — the counter-asserted zero-compile contract; store hits
tick "store_hits" instead.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..profiling.xla_cost import compiled_cost_summary
from ..runtime.locks import make_lock, yield_point


def shard_replicas(states, mesh: Mesh, axis: str = "replicas"):
    """Place a stacked state pytree with leading replica axis onto the
    mesh, sharded along `axis` (replicated on any other mesh axes)."""
    sharding = NamedSharding(mesh, P(axis))
    return jax.tree_util.tree_map(lambda a: jax.device_put(a, sharding), states)


# compiled-program cache, keyed EXPLICITLY on (net.cache_key(), sim_ms) —
# protocol name + static engine knobs (see BatchedNetwork.cache_key) —
# instead of hashing the network object through lru_cache.  Bounded FIFO
# with a clear hook: long sweep campaigns that churn through many configs
# can flush it (clear_run_cache) rather than pinning 64 full jit programs
# (and the engines/latency tables their closures hold) for process life.
_RUN_CACHE: "OrderedDict[tuple, _CachedRun]" = OrderedDict()
_RUN_CACHE_MAX = 64
# entry creation is check-then-act; concurrent callers (serve batch
# workers, sweep threads) must not each install their own _CachedRun
# for one key — that duplicates the compile despite the per-entry lock
_CACHE_LOCK = make_lock("runcache.entry")

# the PR-11 guard: recheck the program table AFTER taking the compile
# lock.  Module-level so the regression test can deliberately revert it
# and prove the interleaving harness reproduces the duplicate compile
_RECHECK_UNDER_LOCK = True

# monotonic across clear_run_cache() — Prometheus counters must never
# step backwards just because a campaign flushed the program cache
_COUNTERS = {
    "hits": 0,
    "misses": 0,
    "evictions": 0,
    "compiles": 0,
    "compile_seconds_total": 0.0,
    # durable compile-store integration: programs adopted from /
    # published to the cross-process store (runtime.compile_store)
    "store_hits": 0,
    "store_puts": 0,
}


class _CachedRun:
    """The cached entry for one (net.cache_key(), sim_ms, layout
    geometry): a callable with jit semantics whose compiles are
    explicit.  Per input geometry (leaf shapes/dtypes/shardings) it
    lowers and compiles ONCE, records the compile wall-clock and the
    normalized cost/memory analyses, then dispatches to the compiled
    executable.

    Sharding is a CONSTRUCTOR-TIME layout decision: when a
    mesh2d.MeshLayout is given, every call places the incoming states
    onto that layout before dispatch, and the layout's geometry is part
    of both the in-process cache key and the durable-store key — a
    (2,4) and a (4,2) program over the same devices never collide."""

    def __init__(self, net, sim_ms: int, key: tuple, layout=None):
        self.key = key
        self.net = net
        self.layout = layout
        self.protocol = type(net.protocol).__name__
        self.sim_ms = int(sim_ms)
        # restart-stable identity for the durable compile store; engines
        # predating stable_cache_key simply never use the store.  The
        # layout geometry rides inside the digest so the store cannot
        # serve a program compiled for a different mesh shape.
        stable = getattr(net, "stable_cache_key", None)
        geometry = layout.geometry() if layout is not None else None
        self.stable_key = (
            "run/"
            + hashlib.blake2b(
                repr((stable(), self.sim_ms, geometry)).encode(),
                digest_size=12,
            ).hexdigest()
            if callable(stable)
            else None
        )

        @jax.jit
        def fn(s):
            out = net.run_ms_batched(s, sim_ms)
            live = ~out.down
            done = jnp.where(live, out.done_at, 0)
            n_live = jnp.maximum(1, jnp.sum(live.astype(jnp.int32)))
            stats = {
                "done_min": jnp.min(
                    jnp.where(live, out.done_at, jnp.int32(2**31 - 1))
                ),
                "done_max": jnp.max(done),
                "done_avg": jnp.sum(done) / n_live,
                "msg_rcv_avg": jnp.sum(jnp.where(live, out.msg_received, 0))
                / n_live,
                "all_done": jnp.all(jnp.where(live, out.done_at > 0, True)),
            }
            return out, stats

        self._jit = fn
        self._programs: "OrderedDict[tuple, object]" = OrderedDict()
        self._summaries: "OrderedDict[tuple, dict]" = OrderedDict()
        # XLA compiles release the GIL, so two threads calling with the
        # same input geometry can BOTH observe "not compiled yet" and
        # duplicate a multi-second compile (observed from concurrent
        # serve batches).  Double-checked locking keeps the per-geometry
        # compile a true singleton.
        self._compile_lock = make_lock("runcache.compile")

    @staticmethod
    def _signature(states) -> tuple:
        sig = []
        for leaf in jax.tree_util.tree_leaves(states):
            sharding = getattr(leaf, "sharding", None)
            try:
                hash(sharding)
            except TypeError:  # unhashable placement — fall back to repr
                sharding = repr(sharding)
            sig.append(
                (tuple(leaf.shape), str(getattr(leaf, "dtype", "?")), sharding)
            )
        return tuple(sig)

    def _store_key(self, states) -> "str | None":
        if self.stable_key is None:
            return None
        from ..runtime.compile_store import (
            geometry_signature,
            mesh_geometry_signature,
        )

        return (
            f"{self.stable_key}"
            f"/mesh-{mesh_geometry_signature(states)}"
            f"/geom-{geometry_signature(states)}"
        )

    def __call__(self, states):
        if self.layout is not None:
            states = self.layout.place(self.net, states)
        sig = self._signature(states)
        compiled = self._programs.get(sig)
        if compiled is None:
            # the PR-11 race window: between this unlocked miss and the
            # locked recheck another thread can finish the same compile.
            # The interleaving harness parks threads here to force that
            # schedule deterministically (tests/interleave.py)
            yield_point("runcache.lookup-miss")
            with self._compile_lock:
                if _RECHECK_UNDER_LOCK:
                    compiled = self._programs.get(sig)
                if compiled is None:
                    yield_point("runcache.compile")
                    from ..runtime.compile_store import (
                        get_compile_store,
                        mesh_geometry_signature,
                    )

                    store = get_compile_store()
                    skey = (
                        self._store_key(states)
                        if store is not None
                        else None
                    )
                    mesh_sig = (
                        mesh_geometry_signature(states)
                        if skey is not None
                        else None
                    )
                    if skey is not None:
                        compiled = store.get(skey, mesh_geometry=mesh_sig)
                    if compiled is not None:
                        # adopted from the durable store: no lowering
                        # happened, so "compiles" must NOT tick (the
                        # zero-compile warm-start contract) and there is
                        # no fresh cost analysis to book
                        _COUNTERS["store_hits"] += 1
                        self._summaries[sig] = {
                            "replicas": next(
                                (s[0][0] for s in sig if s[0]), None
                            ),
                            "loaded_from_store": True,
                        }
                    else:
                        t0 = time.perf_counter()
                        compiled = self._jit.lower(states).compile()
                        dt = time.perf_counter() - t0
                        _COUNTERS["compiles"] += 1
                        _COUNTERS["compile_seconds_total"] += dt
                        self._summaries[sig] = {
                            "replicas": next(
                                (s[0][0] for s in sig if s[0]), None
                            ),
                            **compiled_cost_summary(compiled, dt),
                        }
                        if skey is not None and store.put(
                            skey, compiled, mesh_geometry=mesh_sig
                        ):
                            _COUNTERS["store_puts"] += 1
                    self._programs[sig] = compiled
        return compiled(states)

    def summaries(self) -> list:
        return list(self._summaries.values())


def clear_run_cache() -> None:
    """Drop every cached compiled run program (the lru_cache.cache_clear
    analog for long campaigns).  The cost counters survive — they are
    Prometheus counters, monotonic by contract."""
    _RUN_CACHE.clear()


def run_cache_info() -> dict:
    return {"size": len(_RUN_CACHE), "maxsize": _RUN_CACHE_MAX, **_COUNTERS}


def run_cache_metrics() -> dict:
    """The export view (server /metrics + run records): counters plus
    per-entry compiled-program cost/memory summaries."""
    return {
        **_COUNTERS,
        "size": len(_RUN_CACHE),
        "maxsize": _RUN_CACHE_MAX,
        "entries": [
            {
                "protocol": entry.protocol,
                "sim_ms": entry.sim_ms,
                "programs": entry.summaries(),
            }
            for entry in _RUN_CACHE.values()
        ],
    }


def _run_and_reduce(net, sim_ms: int, layout=None):
    """One cached entry per (net.cache_key(), sim_ms, layout geometry):
    repeated calls with an equivalent network AND layout hit the cache
    instead of re-tracing the full simulation.  The layout geometry is
    part of the key — the same network on a (2,4) vs (4,2) mesh is two
    distinct programs."""
    key = (
        net.cache_key(),
        int(sim_ms),
        layout.geometry() if layout is not None else None,
    )
    with _CACHE_LOCK:
        fn = _RUN_CACHE.get(key)
        if fn is not None:
            _COUNTERS["hits"] += 1
            _RUN_CACHE.move_to_end(key)
            return fn

        _COUNTERS["misses"] += 1
        fn = _CachedRun(net, sim_ms, key, layout=layout)
        _RUN_CACHE[key] = fn
        while len(_RUN_CACHE) > _RUN_CACHE_MAX:
            _RUN_CACHE.popitem(last=False)
            _COUNTERS["evictions"] += 1
        return fn


def sharded_run_stats(net, states, sim_ms: int, layout=None
                      ) -> Tuple[jax.Array, dict]:
    """Run the batched simulation and reduce done/traffic statistics
    across every device in the same program.  Without a layout the
    states run on whatever sharding they carry (the legacy contract);
    with a mesh2d.MeshLayout the cached program places them onto that
    layout first and is keyed on its geometry.  Returns (final_states,
    stats dict of scalars)."""
    return _run_and_reduce(net, sim_ms, layout=layout)(states)

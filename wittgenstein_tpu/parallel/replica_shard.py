"""Replica-axis data parallelism: shard stacked simulation states over a
device mesh and reduce statistics across devices inside one jit.

This is the TPU-native replacement for RunMultipleTimes' sequential
reseeded loop (RunMultipleTimes.java:48-63): R replicas run in lockstep,
sharded R/D per device; the statistics reduction (min/max/mean over the
(replica, node) axes) compiles to on-device partial reductions plus the
cross-device collective XLA chooses for the sharding — no host gather of
per-replica state ever happens.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def shard_replicas(states, mesh: Mesh, axis: str = "replicas"):
    """Place a stacked state pytree with leading replica axis onto the
    mesh, sharded along `axis` (replicated on any other mesh axes)."""
    sharding = NamedSharding(mesh, P(axis))
    return jax.tree_util.tree_map(lambda a: jax.device_put(a, sharding), states)


@functools.lru_cache(maxsize=64)
def _run_and_reduce(net, sim_ms: int):
    """One compiled program per (net, sim_ms): repeated calls with the same
    network hit the jit cache instead of re-tracing the full simulation."""

    @jax.jit
    def fn(s):
        out = net.run_ms_batched(s, sim_ms)
        live = ~out.down
        done = jnp.where(live, out.done_at, 0)
        n_live = jnp.maximum(1, jnp.sum(live.astype(jnp.int32)))
        stats = {
            "done_min": jnp.min(jnp.where(live, out.done_at, jnp.int32(2**31 - 1))),
            "done_max": jnp.max(done),
            "done_avg": jnp.sum(done) / n_live,
            "msg_rcv_avg": jnp.sum(jnp.where(live, out.msg_received, 0)) / n_live,
            "all_done": jnp.all(jnp.where(live, out.done_at > 0, True)),
        }
        return out, stats

    return fn


def sharded_run_stats(net, states, sim_ms: int) -> Tuple[jax.Array, dict]:
    """Run the batched simulation on whatever sharding `states` carries and
    reduce done/traffic statistics across every device in the same program.
    Returns (final_states, stats dict of scalars)."""
    return _run_and_reduce(net, sim_ms)(states)

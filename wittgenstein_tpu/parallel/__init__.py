"""Multi-chip parallelism (the SURVEY §5 'distributed communication
backend' analog).

The reference has no distributed execution at all — its scale story is
one Java thread plus sequential reseeded runs (RunMultipleTimes.java:
48-63).  On TPU the same two axes become device axes:

  * replica axis — independent simulations sharded over a
    `jax.sharding.Mesh` with NamedSharding; XLA inserts the collectives
    for cross-device statistics (replica_shard).
  * node axis — the SoA node state of ONE huge simulation sharded over
    the mesh: the real engine's run_ms under XLA's SPMD partitioner
    (node_shard.shard_state_by_node / run_ms_node_sharded), plus a
    fully-explicit shard_map + psum spike of the same pattern
    (node_shard.pingpong_progression).

Both run identically on a virtual CPU mesh
(--xla_force_host_platform_device_count), a TPU pod slice (ICI), or
multi-host (DCN) — the mesh is the only thing that changes.
"""

from .device_groups import DeviceGroup, make_device_groups
from .mesh2d import (
    MeshLayout,
    assert_channel_ownership,
    channel_ownership,
    classify_leaf,
    make_mesh2d,
    make_mesh2d_layout,
)
from .node_shard import (
    enable_node_sharding,
    node_shard_bytes,
    run_ms_node_sharded,
    shard_state_by_node,
)
from .replica_shard import (
    clear_run_cache,
    run_cache_info,
    shard_replicas,
    sharded_run_stats,
)

__all__ = [
    "DeviceGroup",
    "MeshLayout",
    "assert_channel_ownership",
    "channel_ownership",
    "classify_leaf",
    "make_device_groups",
    "make_mesh2d",
    "make_mesh2d_layout",
    "clear_run_cache",
    "enable_node_sharding",
    "node_shard_bytes",
    "run_cache_info",
    "run_ms_node_sharded",
    "shard_state_by_node",
    "shard_replicas",
    "sharded_run_stats",
]

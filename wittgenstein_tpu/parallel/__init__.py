"""Multi-chip parallelism (the SURVEY §5 'distributed communication
backend' analog).

The reference has no distributed execution at all — its scale story is
one Java thread plus sequential reseeded runs (RunMultipleTimes.java:
48-63).  On TPU the same two axes become device axes:

  * replica axis — independent simulations sharded over a
    `jax.sharding.Mesh` with NamedSharding; XLA inserts the collectives
    for cross-device statistics (replica_shard).
  * node axis — the SoA node state of ONE huge simulation sharded with
    `shard_map`, communicating through explicit collectives (psum /
    all_gather) over the mesh axis (node_shard: the working spike).

Both run identically on a virtual CPU mesh
(--xla_force_host_platform_device_count), a TPU pod slice (ICI), or
multi-host (DCN) — the mesh is the only thing that changes.
"""

from .replica_shard import shard_replicas, sharded_run_stats

__all__ = ["shard_replicas", "sharded_run_stats"]

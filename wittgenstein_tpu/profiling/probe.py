"""TPU probe-verdict cache + export surface.

The probe itself lives in bench.py (it must run BEFORE jax is imported
anywhere in the process — a dead tunnel makes jax.devices() hang, not
raise).  What lives here is everything about the verdict that other
layers need:

  * the TTL'd /tmp cache (moved from bench.py r9) so a bench ladder's
    children probe once per process tree;
  * probe_verdict_fields() — the flat run-record view of a verdict
    (attempts, last rc, fallback_reason, cache age) so every BENCH /
    rung JSONL line says WHY it ran where it ran;
  * add_probe_metrics() — the Prometheus families for GET /metrics, so
    a dead-tunnel CPU fallback (every BENCH since r1) shows up on a
    dashboard instead of only in raw JSON tails.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

# cached verdicts older than this are stale (a tunnel can come back)
PROBE_CACHE_TTL_S = 3600


def probe_cache_path() -> str:
    """Per-process-tree probe-verdict cache in /tmp: keyed by uid +
    session id so a bench ladder (parent + --rung subprocesses + helper
    scripts) probes the backend ONCE instead of burning the full probe
    budget in every child when the tunnel is dead."""
    import tempfile

    try:
        scope = os.getsid(0)
    except (AttributeError, OSError):  # non-POSIX / detached
        scope = os.getppid()
    return os.path.join(
        tempfile.gettempdir(), f"witt_bench_probe_{os.getuid()}_{scope}.json"
    )


def read_probe_cache(path: Optional[str] = None) -> Optional[dict]:
    """The cached verdict dict (incl. its write timestamp "ts"), or None
    if absent/stale/invalid."""
    path = path or probe_cache_path()
    try:
        with open(path) as f:
            cached = json.load(f)
        if time.time() - float(cached.get("ts", 0)) > PROBE_CACHE_TTL_S:
            return None
        if not cached.get("platform"):
            return None
        return cached
    except (OSError, ValueError):
        return None


def write_probe_cache(verdict: dict, path: Optional[str] = None) -> None:
    path = path or probe_cache_path()
    tmp = f"{path}.{os.getpid()}.tmp"
    try:
        with open(tmp, "w") as f:
            json.dump({**verdict, "ts": time.time()}, f)
        os.replace(tmp, path)  # atomic: concurrent rungs see old or new
    except OSError:
        pass  # cache is an optimization, never a failure


def probe_cache_age_s(path: Optional[str] = None) -> Optional[float]:
    """Seconds since the cached verdict was written, or None when there
    is no live cache entry."""
    cached = read_probe_cache(path)
    if cached is None:
        return None
    return max(0.0, time.time() - float(cached.get("ts", 0)))


def probe_verdict_fields(probe: dict) -> dict:
    """Flatten a _probe_backend() verdict into the run-record fields the
    ISSUE asks for: platform, attempt count, last rc, fallback reason,
    whether/when the verdict came from the /tmp cache."""
    attempts = probe.get("attempts") or []
    last = attempts[-1] if attempts else {}
    reason = probe.get("fallback_reason")
    return {
        "platform": probe.get("platform"),
        "attempts": len(attempts),
        "last_rc": last.get("rc"),
        "fallback_reason": reason,
        "from_cache": bool(reason and "cached probe verdict" in str(reason)),
        "cache_age_s": (
            round(probe_cache_age_s(), 1)
            if probe_cache_age_s() is not None
            else None
        ),
    }


def add_probe_metrics(prom, path: Optional[str] = None) -> None:
    """Append witt_probe_* families to a telemetry.export.PromText.

    Families: probe_cache_present (0/1), probe_cache_age_seconds, and a
    labelled probe_platform_verdict (one sample, platform label) — all
    read from the /tmp cache, because the serving process never probes
    itself."""
    cached = read_probe_cache(path)
    prom.add(
        "probe_cache_present",
        1 if cached is not None else 0,
        help="1 when a live TTL'd TPU probe verdict exists in /tmp",
        mtype="gauge",
    )
    if cached is None:
        return
    age = max(0.0, time.time() - float(cached.get("ts", 0)))
    prom.add(
        "probe_cache_age_seconds",
        round(age, 1),
        help="seconds since the probe verdict was cached",
        mtype="gauge",
    )
    prom.add(
        "probe_platform_verdict",
        1,
        help="cached probe verdict; the platform label says where runs go",
        mtype="gauge",
        labels={"platform": str(cached.get("platform"))},
    )

"""Config-ablation matrix: price each engine/protocol lever per tick.

The r4→r5 CPU regression (BENCH_r04 1.463 → BENCH_r05 1.174 sims/s at
256x4, ~20%) came from two parity fixes whose per-tick price was never
isolated: CHANNEL_DEPTH 8→32 and the boundary-view selection.  This
module measures each lever alone AND the combined pre-r5 configuration,
so the regression decomposes into named levers plus an interaction
residual instead of folklore.

Every config is a FRESH build (fresh jit identity — static flags are in
cache_key, but a fresh engine keeps the matrix honest even if a lever
forgets to register itself), warmed with a real run_ms_batched pass for
realistic channel occupancy, then timed with the shared
telemetry.phases harness (warmup-discarded, mean+stddev).  A lever's
delta is flagged untrustworthy when it is inside 2x the combined
stddev of the two configs it compares.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

DEFAULT_WARM_MS = 120
WHEEL_LEVER_ROWS = 512  # engine.core.DEFAULT_WHEEL_ROWS


def flagship_params(node_ct: int):
    """The BASELINE.json flagship Handel configuration at `node_ct`
    (shared with bench.py — ONE definition of the headline config)."""
    from ..protocols.handel import HandelParameters

    return HandelParameters(
        node_count=node_ct,
        threshold=int(node_ct * 0.99),
        pairing_time=3,
        level_wait_time=50,
        extra_cycle=10,
        dissemination_period_ms=10,
        fast_path=10,
        nodes_down=0,
    )


def _lever_builders(node_ct: int) -> Dict[str, Callable]:
    """name -> () -> (net, state).  "base" is the CURRENT bench config
    (r5+: D=32, boundary view, flat store, no side-cars, annotations
    on); every other entry flips exactly one lever except "pre_r5",
    which flips both r5 parity levers at once for exact attribution."""
    from ..protocols.handel_batched import make_handel

    def p(channel_depth=None):
        params = flagship_params(node_ct)
        if channel_depth is not None:
            params.channel_depth = channel_depth
        return params

    def base():
        return make_handel(p())

    def channel_depth_8():
        return make_handel(p(channel_depth=8))

    def boundary_view_off():
        return make_handel(p(), boundary_view=False)

    def pre_r5():
        return make_handel(p(channel_depth=8), boundary_view=False)

    def wheel():
        return make_handel(p(), wheel_rows=WHEEL_LEVER_ROWS)

    def score_cache_on():
        return make_handel(p(), score_cache=True)

    def score_cache_off():
        return make_handel(p(), score_cache=False)

    def fuse_step():
        return make_handel(p(), fuse_step=True)

    def wheel_fused():
        return make_handel(p(), wheel_rows=WHEEL_LEVER_ROWS, fuse_step=True)

    def bitops_pallas():
        # the flip happens via LEVER_ENV (WITT_BITOPS=pallas is read at
        # trace time and folded into the engine cache_key)
        return make_handel(p())

    def telemetry_on():
        from ..telemetry import TelemetryConfig

        net, state = make_handel(p())
        return net.with_telemetry(state, TelemetryConfig())

    def faults_on():
        net, state = make_handel(p())
        return net.with_faults(state, plan=None)  # neutral schedule

    def annotations_off():
        return make_handel(p(), annotate=False)

    return {
        "base": base,
        "channel_depth_8": channel_depth_8,
        "boundary_view_off": boundary_view_off,
        "pre_r5": pre_r5,
        "wheel": wheel,
        "score_cache_on": score_cache_on,
        "score_cache_off": score_cache_off,
        "fuse_step": fuse_step,
        "wheel_fused": wheel_fused,
        "bitops_pallas": bitops_pallas,
        "telemetry_on": telemetry_on,
        "faults_on": faults_on,
        "annotations_off": annotations_off,
    }


LEVER_NOTES = {
    "base": "current flagship config (r5+): D=32, boundary view, flat, "
    "bare, score cache backend-auto",
    "channel_depth_8": "r4 channel depth (D=8 vs 32) — the displacement fix's price",
    "boundary_view_off": "pre-r5 same-tick selection (NOT parity-correct)",
    "pre_r5": "both r5 parity levers off — the r4 hot loop",
    "wheel": f"time-wheel store (wheel_rows={WHEEL_LEVER_ROWS}) vs flat",
    "score_cache_on": "carried candidate-score caches PINNED ON (base is "
    "backend-auto: on-TPU only) — on CPU this row prices the cache's "
    "maintenance cost, on TPU it ~= base",
    "score_cache_off": "carried candidate-score caches PINNED OFF — full "
    "popcount recompute (on TPU this row prices lever 1; on CPU it ~= "
    "base)",
    "fuse_step": "delivery+tick fused under one scope (flat: ~0 on CPU — "
    "run-to-run noise dominates; see wheel_fused)",
    "wheel_fused": "fused step on the wheel store — measured against `wheel`, not base",
    "bitops_pallas": "Pallas bitset kernels (interpret-mode penalty off-TPU; real lever on TPU)",
    "telemetry_on": "in-graph counter side-car armed",
    "faults_on": "fault side-car armed, neutral schedule",
    "annotations_off": "named-scope phase markers stripped (overhead bound)",
}

# per-lever env overrides, applied around BOTH the build and the timed
# trace (bitops_backend() is read at trace time) and restored afterwards
LEVER_ENV: Dict[str, Dict[str, str]] = {
    "bitops_pallas": {"WITT_BITOPS": "pallas"},
}

# levers whose delta is measured against a config OTHER than base
# (wheel_fused prices fusion where delivery is wide; against base it
# would mostly re-measure the wheel-vs-flat delta)
LEVER_BASELINE: Dict[str, str] = {
    "wheel_fused": "wheel",
}

SMOKE_LEVERS = (
    "base",
    "channel_depth_8",
    "boundary_view_off",
    "pre_r5",
    "score_cache_on",
    "score_cache_off",
    "fuse_step",
    "bitops_pallas",
)


def smoke_ablation_configs() -> List[str]:
    """The CI-tier subset: the levers the r4→r5 attribution needs."""
    return list(SMOKE_LEVERS)


def ablation_matrix(
    node_ct: int = 256,
    n_replicas: int = 4,
    scans: int = 25,
    repeats: int = 3,
    warm_ms: int = DEFAULT_WARM_MS,
    levers: Optional[List[str]] = None,
    tracer=None,
) -> dict:
    """Measure full-step tick cost for each lever config.  Returns
    {"config", "backend", "configs": {name: {tick_us, std_us, ...}}}."""
    import jax

    from ..engine import replicate_state
    from ..telemetry.phases import scan_phase_seconds

    builders = _lever_builders(node_ct)
    names = levers if levers is not None else list(builders)
    unknown = sorted(set(names) - set(builders))
    if unknown:
        raise ValueError(f"unknown ablation levers: {unknown}")
    if "base" not in names:
        names = ["base"] + list(names)

    import os

    configs: Dict[str, dict] = {}
    for name in names:
        env = LEVER_ENV.get(name, {})
        saved = {k: os.environ.get(k) for k in env}
        os.environ.update(env)
        try:
            net, state = builders[name]()
            states = replicate_state(state, n_replicas)
            states = net.run_ms_batched(states, warm_ms)  # realistic occupancy
            jax.block_until_ready(states)
            t = scan_phase_seconds(
                states, {"full_step": net.step}, scans, tracer, repeats=repeats
            )["full_step"]
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        configs[name] = {
            "tick_us": round(t["mean_s"] * 1e6, 2),
            "std_us": round(t["std_s"] * 1e6, 2),
            "min_us": round(t["min_s"] * 1e6, 2),
            "note": LEVER_NOTES.get(name, ""),
        }
    return {
        "config": {
            "node_count": node_ct,
            "n_replicas": n_replicas,
            "scans": scans,
            "repeats": repeats,
            "warm_ms": warm_ms,
        },
        "backend": jax.default_backend(),
        "configs": configs,
    }


def lever_report(matrix: dict) -> dict:
    """Rank levers by |per-tick delta vs base| and decompose the r4→r5
    regression into its two named levers + interaction residual.

    Sign convention: delta_us > 0 means the LEVER CONFIG is cheaper
    than its comparison config (base, or LEVER_BASELINE[name]) by that
    much per tick — i.e. the comparison config PAYS delta_us for what
    the lever removes."""
    configs = matrix["configs"]
    base = configs["base"]
    levers = []
    for name, c in configs.items():
        if name == "base":
            continue
        cmp_name = LEVER_BASELINE.get(name, "base")
        cmp_cfg = configs.get(cmp_name, base)
        if cmp_name not in configs:
            cmp_name = "base"
        delta = cmp_cfg["tick_us"] - c["tick_us"]
        spread = 2.0 * (cmp_cfg["std_us"] + c["std_us"])
        row = {
            "lever": name,
            "tick_us": c["tick_us"],
            "delta_us": round(delta, 2),
            "delta_pct_of_base": (
                round(delta / cmp_cfg["tick_us"] * 100, 1)
                if cmp_cfg["tick_us"]
                else None
            ),
            "trustworthy": abs(delta) > spread,
            "note": c.get("note", ""),
        }
        if cmp_name != "base":
            row["vs"] = cmp_name
        levers.append(row)
    levers.sort(key=lambda r: -abs(r["delta_us"]))

    report = {
        "base_tick_us": base["tick_us"],
        "base_std_us": base["std_us"],
        "ranked_levers": levers,
    }

    # r4→r5 attribution: base (r5) vs pre_r5 (r4 levers), decomposed
    if "pre_r5" in configs:
        total = base["tick_us"] - configs["pre_r5"]["tick_us"]
        parts = {}
        if "channel_depth_8" in configs:
            parts["channel_depth_32_us"] = round(
                base["tick_us"] - configs["channel_depth_8"]["tick_us"], 2
            )
        if "boundary_view_off" in configs:
            parts["boundary_view_us"] = round(
                base["tick_us"] - configs["boundary_view_off"]["tick_us"], 2
            )
        interaction = total - sum(parts.values())
        report["r4_to_r5_attribution"] = {
            "total_regression_us_per_tick": round(total, 2),
            **parts,
            "interaction_us": round(interaction, 2),
            "note": (
                "positive = the r5 parity config pays this much more per"
                " tick than the r4 config; levers measured one-at-a-time"
                " from base, interaction = total - sum(parts)"
            ),
        }

    if "annotations_off" in configs:
        off = configs["annotations_off"]["tick_us"]
        if off:
            report["annotation_overhead_pct"] = round(
                (base["tick_us"] - off) / off * 100, 2
            )
    return report


def format_lever_report(report: dict) -> str:
    """Human rendering of lever_report() for bench --phase-profile's
    stderr and the CI artifact."""
    lines = [
        f"base full-step: {report['base_tick_us']:.1f} us/tick"
        f" (+-{report['base_std_us']:.1f})",
        f"{'lever':<20} {'us/tick':>9} {'delta':>8} {'%base':>6}  trust note",
    ]
    for r in report["ranked_levers"]:
        trust = "ok " if r["trustworthy"] else "~? "
        vs = f" [vs {r['vs']}]" if r.get("vs") else ""
        lines.append(
            f"{r['lever']:<20} {r['tick_us']:>9.1f} {r['delta_us']:>8.1f}"
            f" {r['delta_pct_of_base'] or 0:>5.1f}%  {trust} {r['note']}{vs}"
        )
    attr = report.get("r4_to_r5_attribution")
    if attr:
        lines.append("r4->r5 regression attribution (us/tick):")
        for k in (
            "total_regression_us_per_tick",
            "channel_depth_32_us",
            "boundary_view_us",
            "interaction_us",
        ):
            if k in attr:
                lines.append(f"  {k:<28} {attr[k]:>8.2f}")
    if "annotation_overhead_pct" in report:
        lines.append(
            f"annotation overhead: {report['annotation_overhead_pct']:+.2f}%"
        )
    return "\n".join(lines)

"""Pytree-leaf HBM footprint model: replicas per chip from the actual
SimState leaves.

The replica-density claim behind the D=32 channel depth ("~106 MiB per
4096-node replica, still 32+ replicas inside a v5e chip's HBM" —
protocols/handel_batched.py) was hand-arithmetic until now.  This model
walks the real init_state() pytree, so any state-layout change (a new
side-car, a wider channel) moves the number automatically.

Model, not measurement: run_ms_batched's true peak adds XLA temp buffers
on top of the live state (double-buffered scan carries, fusion
scratch).  xla_cost.memory_analysis_dict() reports the measured
temp_size for one compiled geometry; replicas_per_chip() takes an
`overhead` factor calibrated from it (default 2.0x — one extra live
copy, the scan carry's worst case with donation off, the
runtime/supervisor default).
"""

from __future__ import annotations

from typing import Optional

# v5e: 16 GiB HBM per chip (the ROADMAP's deployment target)
DEFAULT_HBM_GIB = 16.0
DEFAULT_STATE_OVERHEAD = 2.0


def state_bytes_per_replica(state) -> dict:
    """Total bytes of one replica's SimState pytree and the top
    contributors: {"total_bytes", "n_leaves", "top": [(path, bytes,
    dtype)], "by_dtype": {dtype: bytes}}.

    `state` must be UNREPLICATED (no leading replica axis) — pass the
    init_state() result, not replicate_state()'s.  The dtype axis is the
    density war's ledger: narrow packed leaves (engine.density) show up
    here as int16/int8 bytes that would otherwise be int32."""
    import jax

    leaves_with_paths = jax.tree_util.tree_flatten_with_path(state)[0]
    sizes = []
    by_dtype: dict = {}
    total = 0
    for path, leaf in leaves_with_paths:
        dt = getattr(leaf, "dtype", None)
        nb = int(getattr(leaf, "size", 0)) * int(
            getattr(dt, "itemsize", 0) or 0
        )
        total += nb
        dname = str(dt) if dt is not None else "none"
        by_dtype[dname] = by_dtype.get(dname, 0) + nb
        sizes.append((jax.tree_util.keystr(path), nb, dname))
    sizes.sort(key=lambda kv: -kv[1])
    return {
        "total_bytes": total,
        "n_leaves": len(sizes),
        "top": sizes[:8],
        "by_dtype": dict(sorted(by_dtype.items(), key=lambda kv: -kv[1])),
    }


def replicas_per_chip(
    state,
    hbm_gib: float = DEFAULT_HBM_GIB,
    overhead: float = DEFAULT_STATE_OVERHEAD,
    reserved_gib: float = 0.5,
) -> dict:
    """HBM-bounded replica count for one chip: floor((HBM - reserved) /
    (bytes_per_replica * overhead)).  `reserved_gib` covers compiled
    code + runtime framebuffers."""
    per = state_bytes_per_replica(state)
    usable = max(0.0, (hbm_gib - reserved_gib)) * (1 << 30)
    denom = per["total_bytes"] * max(1.0, overhead)
    return {
        "bytes_per_replica": per["total_bytes"],
        "mib_per_replica": round(per["total_bytes"] / (1 << 20), 1),
        "hbm_gib": hbm_gib,
        "reserved_gib": reserved_gib,
        "overhead_factor": overhead,
        "replicas": int(usable // denom) if denom else 0,
    }


def hbm_report(
    state,
    memory: Optional[dict] = None,
    hbm_gib: float = DEFAULT_HBM_GIB,
) -> dict:
    """The BUDGET.json "hbm" block: leaf model + (when a compiled
    program's memory_analysis is available) the measured-vs-modeled
    cross-check.  `memory` is xla_cost.memory_analysis_dict() output for
    a run_ms program on ONE replica of this state."""
    density = replicas_per_chip(state, hbm_gib=hbm_gib)
    per = state_bytes_per_replica(state)
    out = {
        "model": density,
        "top_leaves": [
            {"path": p, "bytes": b, "dtype": d} for p, b, d in per["top"]
        ],
        # the narrow-dtype ledger: how much of the replica is already
        # packed below int32 (engine.density lane plans + NARROW_LEAVES)
        "bytes_by_dtype": per["by_dtype"],
    }
    if memory:
        # measured live bytes for 1 replica vs the modeled
        # bytes_per_replica * overhead — how honest is the 2x factor?
        # live_bytes = argument + output + temp; the argument/output pair
        # is state-shaped (the two live copies the 2x overhead models),
        # temp is XLA fusion scratch on top.  Both ratios are reported so
        # a temp-heavy compile (ratio gap) is visible instead of folded
        # into one misleading 0.6x number.
        live = memory.get("live_bytes", 0)
        arg_b = memory.get("argument_size_in_bytes", 0)
        out_b = memory.get("output_size_in_bytes", 0)
        temp_b = memory.get("temp_size_in_bytes", 0)
        modeled = density["bytes_per_replica"] * density["overhead_factor"]
        state_shaped = arg_b + out_b
        out["measured"] = {
            "argument_bytes": arg_b,
            "output_bytes": out_b,
            "temp_bytes": temp_b,
            "temp_share_of_live": (
                round(temp_b / live, 3) if live else None
            ),
            "live_bytes_1_replica": live,
            "modeled_bytes": int(modeled),
            "model_over_state_bytes": (
                round(modeled / state_shaped, 2) if state_shaped else None
            ),
            "model_over_measured": (
                round(modeled / live, 2) if live else None
            ),
            "note": (
                "replicas_per_chip is fed by the MODEL"
                " (bytes_per_replica * overhead_factor), never by these"
                " measured numbers; model_over_measured < 1 means XLA"
                " temps exceed the overhead headroom at this geometry"
                " (see temp_share_of_live)"
            ),
        }
    return out

"""Cost-attribution profiling: the machine side of ROADMAP item 1.

PR 2's telemetry counts *protocol* events; this package attributes
*machine cost* and keeps every performance claim a measured artifact:

  xla_cost.py  normalized `compile().cost_analysis()` (FLOPs, bytes,
               transcendentals) + `memory_analysis()` (argument/output/
               temp/code bytes) for any jitted entry point — the
               capture behind the run cache's per-program accounting
               (parallel.replica_shard.run_cache_metrics).
  hbm.py       pytree-leaf HBM footprint model: bytes/replica from the
               actual SimState leaves, HBM-bounded replicas/chip — the
               number behind the "~106 MiB/replica at D=32" claim and
               the feasibility budget's R.
  ablation.py  the config-ablation matrix (channel depth, boundary
               view, wheel, telemetry, faults, annotations) and the
               ranked per-tick lever report that prices each lever —
               bench.py --phase-profile and the r4→r5 attribution.
  probe.py     the TTL'd TPU probe-verdict cache (moved from bench.py)
               + the run-record / Prometheus surface of the verdict, so
               dead-tunnel CPU fallbacks are visible without reading
               raw JSON tails.
  budget.py    the chip-independent feasibility arithmetic: measured
               ticks/sim × HBM-bounded replicas/chip → required tick_µs
               for the 21 sims/s/chip north star (BUDGET.json via
               scripts/budget_report.py).

See docs/profiling.md for the phase map and per-backend caveats.
"""

from .ablation import (
    ablation_matrix,
    flagship_params,
    format_lever_report,
    lever_report,
    smoke_ablation_configs,
)
from .budget import (
    budget_from_parts,
    budget_staleness,
    load_budget,
    required_tick_us,
)
from .hbm import hbm_report, replicas_per_chip, state_bytes_per_replica
from .probe import (
    PROBE_CACHE_TTL_S,
    probe_cache_path,
    probe_verdict_fields,
    read_probe_cache,
    write_probe_cache,
)
from .xla_cost import compiled_cost_summary, cost_analysis_dict, memory_analysis_dict

__all__ = [
    "PROBE_CACHE_TTL_S",
    "ablation_matrix",
    "budget_from_parts",
    "budget_staleness",
    "compiled_cost_summary",
    "cost_analysis_dict",
    "flagship_params",
    "format_lever_report",
    "hbm_report",
    "lever_report",
    "load_budget",
    "memory_analysis_dict",
    "probe_cache_path",
    "probe_verdict_fields",
    "read_probe_cache",
    "replicas_per_chip",
    "required_tick_us",
    "smoke_ablation_configs",
    "state_bytes_per_replica",
    "write_probe_cache",
]

"""Normalized XLA cost/memory accounting for compiled entry points.

jax 0.4.x API quirks this module absorbs so callers never touch them:

  * `compiled.cost_analysis()` returns a LIST of per-computation dicts
    (usually length 1) whose keys mix scalars ("flops", "bytes
    accessed", "transcendentals") with per-operand entries ("bytes
    accessed0{}", "bytes accessedout{}", ...);
  * `compiled.memory_analysis()` returns an opaque CompiledMemoryStats
    object (attrs, not a mapping), and either call may return None or
    raise on backends that don't implement it (the CPU backend DOES
    implement both as of jaxlib 0.4.37 — docs/profiling.md records the
    per-backend caveats).

Everything returned here is plain JSON-able floats/ints, ready for
BENCH records, BUDGET.json, and run_cache_metrics().
"""

from __future__ import annotations

from typing import Any, Optional

_MEMORY_ATTRS = (
    "generated_code_size_in_bytes",
    "argument_size_in_bytes",
    "output_size_in_bytes",
    "alias_size_in_bytes",
    "temp_size_in_bytes",
    "host_generated_code_size_in_bytes",
    "host_argument_size_in_bytes",
    "host_output_size_in_bytes",
    "host_alias_size_in_bytes",
    "host_temp_size_in_bytes",
)


def cost_analysis_dict(compiled) -> Optional[dict]:
    """Scalar totals from compiled.cost_analysis(): {"flops",
    "bytes_accessed", "transcendentals", "optimal_seconds"} summed over
    the returned computations, per-operand breakdown entries dropped.
    None when the backend can't say."""
    try:
        cost = compiled.cost_analysis()
    except Exception:
        return None
    if cost is None:
        return None
    if isinstance(cost, dict):  # jax >= 0.5 flattens the list
        cost = [cost]
    wanted = {
        "flops": "flops",
        "bytes accessed": "bytes_accessed",
        "transcendentals": "transcendentals",
        "optimal_seconds": "optimal_seconds",
    }
    out: dict = {}
    for comp in cost:
        for src, dst in wanted.items():
            if src in comp:
                out[dst] = out.get(dst, 0.0) + float(comp[src])
    return out or None


def memory_analysis_dict(compiled) -> Optional[dict]:
    """CompiledMemoryStats as a plain dict (suffix _in_bytes kept), plus
    "live_bytes" = argument + output + temp — the footprint that must
    fit in device memory for one invocation (code size excluded: HBM vs
    host split varies by backend; aliased/donated bytes excluded since
    they overlap arguments)."""
    try:
        mem = compiled.memory_analysis()
    except Exception:
        return None
    if mem is None:
        return None
    out: dict = {}
    for attr in _MEMORY_ATTRS:
        v = getattr(mem, attr, None)
        if v is not None:
            out[attr] = int(v)
    if not out:
        return None
    out["live_bytes"] = (
        out.get("argument_size_in_bytes", 0)
        + out.get("output_size_in_bytes", 0)
        + out.get("temp_size_in_bytes", 0)
    )
    return out


def compiled_cost_summary(compiled, compile_seconds: Optional[float] = None) -> dict:
    """The record the run cache stores per compiled program: cost +
    memory normalized, compile wall-clock if the caller timed it."""
    out: dict = {
        "cost": cost_analysis_dict(compiled),
        "memory": memory_analysis_dict(compiled),
    }
    if compile_seconds is not None:
        out["compile_seconds"] = round(float(compile_seconds), 3)
    return out


def lower_and_summarize(fn, *args, static_argnums=(), **kw) -> dict:
    """Convenience: jit+lower+compile `fn` on example args and return
    its compiled_cost_summary (with measured compile seconds).  Used by
    scripts/budget_report.py to price run_ms without running it."""
    import time

    import jax

    t0 = time.perf_counter()
    compiled = (
        jax.jit(fn, static_argnums=static_argnums).lower(*args, **kw).compile()
    )
    return compiled_cost_summary(compiled, time.perf_counter() - t0)


def format_bytes(n: Any) -> str:
    """Human side-channel for reports: 111_149_056 -> '106.0 MiB'."""
    try:
        n = float(n)
    except (TypeError, ValueError):
        return str(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0
    return f"{n:.1f} TiB"

"""The chip-independent feasibility budget (VERDICT.md's demand).

North star: 21 sims/s/chip of flagship Handel at 4096 nodes.  The
budget that implies is pure arithmetic once two quantities are measured
instead of assumed:

  ticks_per_sim   how many engine ticks one sim actually executes —
                  SIM_MS with the naive fixed-horizon loop, LESS when
                  the quiescence exit (stop_when_done / the empty-ms
                  jump) cuts the tail after the last node finishes;
  replicas        the HBM-bounded replicas/chip at the flagship state
                  layout (profiling.hbm model, D=32).

Then, with R replicas advancing in lockstep:

  required_tick_us = R / (21 * ticks_per_sim) * 1e6

i.e. each batched tick may take at most that many microseconds of
wall-clock for the chip to emit 21 finished sims per second.
scripts/budget_report.py materializes this as BUDGET.json; bench.py's
target_tick_us derives from the same arithmetic (and from BUDGET.json's
measured ticks_per_sim when present) instead of being hand-set.
"""

from __future__ import annotations

import json
import os
from typing import Optional

NORTH_STAR_SIMS_PER_SEC = 21.0
BUDGET_PATH = "BUDGET.json"
BUDGET_SCHEMA = "witt-budget/v1"


def required_tick_us(
    replicas: int,
    ticks_per_sim: float,
    sims_per_sec: float = NORTH_STAR_SIMS_PER_SEC,
) -> float:
    """Max per-tick wall-clock (µs) for `replicas` lockstep replicas to
    yield `sims_per_sec` finished sims per second when one sim runs
    `ticks_per_sim` ticks."""
    if replicas <= 0 or ticks_per_sim <= 0 or sims_per_sec <= 0:
        raise ValueError(
            f"replicas={replicas}, ticks_per_sim={ticks_per_sim},"
            f" sims_per_sec={sims_per_sec} must all be positive"
        )
    return replicas / (sims_per_sec * ticks_per_sim) * 1e6


def budget_from_parts(
    ticks_per_sim: float,
    hbm: dict,
    measured: Optional[dict] = None,
    sims_per_sec: float = NORTH_STAR_SIMS_PER_SEC,
    config: Optional[dict] = None,
) -> dict:
    """Assemble the BUDGET.json document.  `hbm` is
    profiling.hbm.hbm_report() output (its model.replicas bounds R);
    `measured` optionally carries the current measured tick cost so the
    gap to the budget is stated in the artifact itself."""
    replicas = int(hbm["model"]["replicas"])
    tick_us = required_tick_us(replicas, ticks_per_sim, sims_per_sec)
    doc = {
        "schema": BUDGET_SCHEMA,
        "north_star_sims_per_sec_per_chip": sims_per_sec,
        "config": config or {},
        "ticks_per_sim": round(float(ticks_per_sim), 1),
        "hbm": hbm,
        "replicas_per_chip": replicas,
        "required_tick_us": round(tick_us, 2),
        "derivation": (
            f"required_tick_us = replicas / (sims_per_sec * ticks_per_sim)"
            f" * 1e6 = {replicas} / ({sims_per_sec} * {ticks_per_sim:.0f})"
            f" * 1e6"
        ),
    }
    if measured:
        doc["measured"] = measured
        mt = measured.get("tick_us")
        if mt:
            doc["headroom_factor"] = round(tick_us / mt, 3)
    return doc


def load_budget(path: Optional[str] = None, root: Optional[str] = None) -> Optional[dict]:
    """Read BUDGET.json (repo root by default); None when absent or
    unparseable — callers fall back to the fixed-horizon assumption."""
    if path is None:
        root = root or os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        path = os.path.join(root, BUDGET_PATH)
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if doc.get("schema") != BUDGET_SCHEMA:
        return None
    return doc


def budget_staleness(budget: dict, floor: dict) -> Optional[str]:
    """Why `budget` is stale relative to a BENCH_FLOOR.json doc, or None
    when fresh.  Stale = the floor was recorded after the budget (a
    perf-moving PR re-recorded the floor without regenerating the
    budget), or the budget has no timestamp at all.  The two documents
    deliberately have different geometries — the floor guards the 256x4
    CPU rung, the budget states the 4096 chip target — so only the
    recorded dates are compared (ISO dates order lexicographically)."""
    b_rec = budget.get("recorded")
    f_rec = floor.get("recorded")
    if not b_rec:
        return "budget has no 'recorded' timestamp"
    if f_rec and str(b_rec) < str(f_rec):
        return (
            f"budget recorded {b_rec} predates BENCH_FLOOR.json"
            f" recorded {f_rec} — regenerate scripts/budget_report.py"
        )
    return None

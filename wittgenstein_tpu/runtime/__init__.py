"""Durable-run runtime: supervised chunked execution with
checkpoint/resume, watchdogs, bounded retry, and graceful degradation.

See docs/durability.md for the operational model.
"""

from .errors import (
    DeviceLostError,
    DurableRunError,
    FatalRunError,
    PreemptedError,
    ResumeMismatchError,
    RetriesExhaustedError,
    RunIncompleteError,
    TransientRunError,
    WatchdogTimeoutError,
    classify,
)
from .compile_store import (
    CompileStore,
    DurableJit,
    compile_store_counters,
    durable_jit,
    get_compile_store,
    set_compile_store,
)
from .policy import DegradePolicy, RetryPolicy, WatchdogPolicy, WatchdogWorker
from .supervisor import (
    RunReport,
    Supervisor,
    chunk_time_histogram,
    run_with_deadline,
    stable_run_key,
)

__all__ = [
    "CompileStore",
    "DurableJit",
    "compile_store_counters",
    "durable_jit",
    "get_compile_store",
    "set_compile_store",
    "DegradePolicy",
    "DeviceLostError",
    "DurableRunError",
    "FatalRunError",
    "PreemptedError",
    "ResumeMismatchError",
    "RetriesExhaustedError",
    "RunIncompleteError",
    "RunReport",
    "RetryPolicy",
    "Supervisor",
    "TransientRunError",
    "WatchdogPolicy",
    "WatchdogTimeoutError",
    "WatchdogWorker",
    "chunk_time_histogram",
    "classify",
    "run_with_deadline",
    "stable_run_key",
]

"""Durable-run runtime: supervised chunked execution with
checkpoint/resume, watchdogs, bounded retry, and graceful degradation.

See docs/durability.md for the operational model.
"""

from .errors import (
    RETRYABLE_KINDS,
    DeviceLostError,
    DurableRunError,
    FatalRunError,
    LaneFailedError,
    PoisonRowError,
    PreemptedError,
    ResumeMismatchError,
    RetriesExhaustedError,
    RunIncompleteError,
    TransientRunError,
    WatchdogTimeoutError,
    classify,
    reset_taxonomy_counters,
    taxonomy_counters,
)
from .compile_store import (
    CompileStore,
    DurableJit,
    compile_store_counters,
    durable_jit,
    get_compile_store,
    set_compile_store,
)
from .policy import (
    DegradePolicy,
    RetryPolicy,
    SalvagePolicy,
    WatchdogPolicy,
    WatchdogWorker,
)
from .supervisor import (
    RunReport,
    Supervisor,
    chunk_time_histogram,
    run_with_deadline,
    stable_run_key,
)

__all__ = [
    "CompileStore",
    "DurableJit",
    "compile_store_counters",
    "durable_jit",
    "get_compile_store",
    "set_compile_store",
    "DegradePolicy",
    "DeviceLostError",
    "DurableRunError",
    "FatalRunError",
    "LaneFailedError",
    "PoisonRowError",
    "PreemptedError",
    "RETRYABLE_KINDS",
    "ResumeMismatchError",
    "RetriesExhaustedError",
    "RunIncompleteError",
    "RunReport",
    "RetryPolicy",
    "SalvagePolicy",
    "Supervisor",
    "TransientRunError",
    "WatchdogPolicy",
    "WatchdogTimeoutError",
    "WatchdogWorker",
    "chunk_time_histogram",
    "classify",
    "reset_taxonomy_counters",
    "run_with_deadline",
    "stable_run_key",
    "taxonomy_counters",
]

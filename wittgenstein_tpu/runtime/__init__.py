"""Durable-run runtime: supervised chunked execution with
checkpoint/resume, watchdogs, bounded retry, and graceful degradation.

See docs/durability.md for the operational model.
"""

from .errors import (
    DeviceLostError,
    DurableRunError,
    FatalRunError,
    PreemptedError,
    ResumeMismatchError,
    RetriesExhaustedError,
    RunIncompleteError,
    TransientRunError,
    WatchdogTimeoutError,
    classify,
)
from .policy import DegradePolicy, RetryPolicy, WatchdogPolicy, WatchdogWorker
from .supervisor import (
    RunReport,
    Supervisor,
    chunk_time_histogram,
    run_with_deadline,
    stable_run_key,
)

__all__ = [
    "DegradePolicy",
    "DeviceLostError",
    "DurableRunError",
    "FatalRunError",
    "PreemptedError",
    "ResumeMismatchError",
    "RetriesExhaustedError",
    "RunIncompleteError",
    "RunReport",
    "RetryPolicy",
    "Supervisor",
    "TransientRunError",
    "WatchdogPolicy",
    "WatchdogTimeoutError",
    "WatchdogWorker",
    "chunk_time_histogram",
    "classify",
    "run_with_deadline",
    "stable_run_key",
]

"""Supervised chunked-run executor: the durable loop around run_ms.

The engine is deterministic in (state, tick count), so a chunked run is
bit-identical to a straight one — which makes durability a pure
host-side concern.  The Supervisor wraps any chunk function
(state -> state, typically a jitted ``run_ms_batched`` slice) in a loop

    resume -> [guard -> chunk -> sync -> checkpoint]* -> report

with:

- **checkpoint/resume** through engine.checkpoint.CheckpointManager:
  periodic numbered checkpoints + LATEST pointer, run_key-stamped so a
  checkpoint from a different run refuses to resume
  (ResumeMismatchError); kill-and-resume is bit-identical to an
  uninterrupted run — including telemetry counters and fault side-cars
  — because resume replays the exact remaining chunk schedule;
- **watchdog**: each chunk executes on ONE persistent WatchdogWorker
  thread with a deadline (the first chunk of a cold process gets the
  compile allowance on top); the worker is reused across chunks and
  joined when the run finishes, so thread count is stable across a
  supervised run.  A miss raises WatchdogTimeoutError rather than
  waiting forever on a dead tunnel.  Caveat: Python cannot cancel a
  hung device call — a worker whose call truly hangs is abandoned (and
  replaced); actually killing the process is the job of a process-level
  supervisor (scripts/tpu_campaign.py), because killing mid-device-call
  wedges the tunneled worker (r3/r4 lesson);
- **retry with backoff**: transient failures (classify()) replay
  deterministically from the last host ANCHOR — a numpy snapshot taken
  at checkpoint cadence — so retried chunks produce the exact bytes a
  clean run would have, even with donated device buffers (the donated
  input that the failed call consumed is never needed again);
- **graceful degradation**: on device loss with
  DegradePolicy(cpu_fallback=True) the anchor is re-placed on CPU and
  the run continues there, with {degraded, degraded_at_chunk} stamped
  into provenance — a CPU tail can never masquerade as a TPU number;
- **budget/cap partial stops**: budget_s / max_chunks_this_run exceeded
  between chunks -> checkpoint now, return RunReport(ok=False) — the
  next invocation resumes where this one stopped;
- **observability spine** (obs.*): a TraceContext (run_id / job_id /
  tenant_id) rides provenance, checkpoint-manifest meta, tracer spans,
  and the FlightRecorder event stream.  The run_id SURVIVES kill +
  resume: _save stamps it into the manifest and _resume adopts the
  stored id, so the victim process and the resume process emit one
  joinable run.  On any typed runtime failure the recorder ring is
  dumped atomically beside the checkpoints (and under $WITT_OBS_DIR) —
  the per-run black box scripts/obs_query.py replays.  All host-side:
  sim state stays bit-identical with the recorder armed.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional

import numpy as np

from ..engine.checkpoint import CheckpointManager
from ..obs import FlightRecorder, TraceContext, failure_dump_paths, get_recorder, mint_context
from .errors import (
    RETRYABLE_KINDS,
    DurableRunError,
    FatalRunError,
    ResumeMismatchError,
    RetriesExhaustedError,
    WatchdogTimeoutError,
    classify,
)
from .policy import DegradePolicy, RetryPolicy, WatchdogPolicy, WatchdogWorker


def _sync(state: Any) -> None:
    """Ground-truth chunk completion: host readback of the SMALLEST
    output leaf (one program's outputs materialize together).
    block_until_ready alone acks while a tunneled program is still
    queued — see bench.chunked_pass, same trick."""
    import jax

    leaves = jax.tree_util.tree_leaves(state)
    if leaves:
        np.asarray(min(leaves, key=lambda a: getattr(a, "size", 1 << 62)))


def run_with_deadline(fn: Callable[[], Any], deadline_s: float, phase: str):
    """One-shot deadline guard (compat shim over policy.WatchdogWorker).
    Raises WatchdogTimeoutError(phase) on a miss.  Unlike the original
    per-call daemon thread, a COMPLETED call's worker is joined before
    returning; only a call that truly hangs (an uncancellable device
    call) still abandons its thread — callers that need the hang
    actually killed must supervise at process level.  Loop callers
    (Supervisor) hold one WatchdogWorker across calls instead."""
    worker = WatchdogWorker(name=f"witt-{phase}")
    try:
        return worker.call(fn, deadline_s, phase)
    finally:
        worker.close()


# per-chunk wall-time histogram buckets (seconds): the interesting
# decades between "CPU smoke chunk" and "tunnel watchdog kill"
CHUNK_HIST_BUCKETS_S = (0.1, 0.5, 1.0, 2.0, 5.0, 10.0, 30.0, 60.0, 120.0)


def chunk_time_histogram(times: List[float]) -> dict:
    """Prometheus-style cumulative histogram of chunk wall-times:
    {"buckets": {"0.1": n, ..., "+Inf": n}, "count", "sum_s", "max_s"}.
    Shared by Supervisor provenance and the server/bench exports so one
    bucket layout exists."""
    buckets = {}
    for le in CHUNK_HIST_BUCKETS_S:
        buckets[str(le)] = sum(1 for t in times if t <= le)
    buckets["+Inf"] = len(times)
    return {
        "buckets": buckets,
        "count": len(times),
        "sum_s": round(sum(times), 4),
        "max_s": round(max(times), 4) if times else 0.0,
    }


def stable_run_key(net: Any, template: Any, n_chunks: int, chunk_ms: int) -> str:
    """A run identity that survives process restarts (unlike
    core.cache_key, which hashes object ids): protocol type + chunk
    geometry + the template's leaf signature (paths/shapes/dtypes)."""
    import hashlib

    import jax

    proto = getattr(net, "protocol", net)
    parts = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(template)[0]:
        shape = getattr(leaf, "shape", ())
        dtype = getattr(leaf, "dtype", type(leaf).__name__)
        parts.append(f"{path}:{shape}:{dtype}")
    digest = hashlib.blake2b(
        "|".join(parts).encode(), digest_size=8
    ).hexdigest()
    return f"{type(proto).__name__}:{n_chunks}x{chunk_ms}ms:{digest}"


@dataclass
class RunReport:
    """What a supervised run produced.  ok=False is a CONTROLLED partial
    stop (budget / chunk cap) with a checkpoint on disk; failures raise
    instead."""

    state: Any
    ok: bool
    chunk_seconds: List[float] = field(default_factory=list)
    provenance: dict = field(default_factory=dict)

    @property
    def chunks_done(self) -> int:
        return int(self.provenance.get("chunks_done", 0))


class Supervisor:
    """See module docstring.  `chunk_fn(state) -> state` advances one
    chunk; it may be jitted with donated inputs (retries replay from the
    host anchor, never from a consumed buffer)."""

    def __init__(
        self,
        chunk_fn: Callable[[Any], Any],
        template: Any,
        *,
        n_chunks: int,
        chunk_ms: int = 0,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every: int = 1,
        keep: int = 3,
        retry: Optional[RetryPolicy] = None,
        watchdog: Optional[WatchdogPolicy] = None,
        degrade: Optional[DegradePolicy] = None,
        cpu_chunk_fn: Optional[Callable[[Any], Any]] = None,
        run_key: Optional[str] = None,
        run_meta: Optional[dict] = None,
        heartbeat: Optional[Callable[[int, float], None]] = None,
        budget_s: float = float("inf"),
        max_chunks_this_run: Optional[int] = None,
        should_stop: Optional[Callable[[], bool]] = None,
        sleep: Callable[[float], None] = time.sleep,
        consume_template: bool = False,
        tracer: Any = None,
        ctx: Optional[TraceContext] = None,
        recorder: Optional[FlightRecorder] = None,
        placement: Optional[Callable[[Any], Any]] = None,
        timeseries: Any = None,
        sentinel: Any = None,
        row_watch: Optional[Callable[[Any, int], None]] = None,
    ):
        if n_chunks < 1:
            raise ValueError(f"n_chunks must be >= 1, got {n_chunks}")
        if checkpoint_every < 1:
            raise ValueError(
                f"checkpoint_every must be >= 1, got {checkpoint_every}"
            )
        self.chunk_fn = chunk_fn
        self.template = template
        self.n_chunks = n_chunks
        self.chunk_ms = chunk_ms
        self.manager = (
            CheckpointManager(checkpoint_dir, keep=keep)
            if checkpoint_dir
            else None
        )
        self.checkpoint_every = checkpoint_every
        self.retry = retry or RetryPolicy()
        self.watchdog = watchdog
        self.degrade = degrade
        self.cpu_chunk_fn = cpu_chunk_fn
        self.run_key = run_key
        self.run_meta = dict(run_meta or {})
        self.heartbeat = heartbeat
        self.budget_s = budget_s
        self.max_chunks_this_run = max_chunks_this_run
        # cooperative preemption (serve drain): checked between chunks;
        # True -> checkpoint now and return a controlled partial stop,
        # exactly like a budget/cap stop — resume replays bit-identical
        self.should_stop = should_stop
        self.sleep = sleep
        self.consume_template = consume_template
        # optional telemetry.trace.SpanTracer: chunk spans + instants
        # for retry/degrade/watchdog events land in the Chrome trace
        self.tracer = tracer
        # trace context: minted lazily at run() if the caller didn't
        # pass one AND no checkpoint supplies one (_resume adopts the
        # stored run_id so kill+resume stays one run)
        self.ctx = ctx
        self.recorder = get_recorder() if recorder is None else recorder
        # optional device placement for resumed/anchored host states
        # (a serve lane's device group): applied instead of the default
        # jnp.asarray materialization, never in degraded mode (CPU
        # fallback overrides any group placement)
        self.placement = placement
        # mission control (optional): an obs.TimeSeriesStore fed at the
        # per-chunk sync boundary (history the SLO engine queries) and
        # an obs.InvariantSentinel checked there too.  Both read
        # already-synced host state only — arming them is bitwise-
        # neutral, and neither may ever fail the run (_observe_chunk
        # swallows; sentinel.check never raises by contract)
        self.timeseries = timeseries
        self.sentinel = sentinel
        # done-row watcher (serve's harvesting census): called at the
        # same per-chunk sync with (synced_state, chunk_index).  The
        # per-chunk sync is the ONLY place done_at/all_done are already
        # host-materialized, so mid-batch row observations are free
        # here and nowhere else.  Same contract as the sentinel: reads
        # only, never fails the run (_observe_chunk swallows)
        self.row_watch = row_watch
        self._wd_worker: Optional[WatchdogWorker] = None
        self._first_call_done = False
        self._degraded = False

    # -- state placement ------------------------------------------------

    def _snapshot(self, state: Any):
        """Host anchor: a private numpy copy of every leaf (immune to
        donation consuming the device buffers)."""
        import jax

        return jax.tree_util.tree_map(
            lambda a: np.array(np.asarray(a), copy=True), state
        )

    def _place(self, host_state: Any) -> Any:
        import jax
        import jax.numpy as jnp

        if self._degraded:
            cpu = jax.devices("cpu")[0]
            return jax.tree_util.tree_map(
                lambda a: jax.device_put(a, cpu), host_state
            )
        if self.placement is not None:
            return self.placement(host_state)
        return jax.tree_util.tree_map(jnp.asarray, host_state)

    # -- chunk execution ------------------------------------------------

    def _active_chunk_fn(self) -> Callable[[Any], Any]:
        if self._degraded and self.cpu_chunk_fn is not None:
            return self.cpu_chunk_fn
        return self.chunk_fn

    def _run_chunk(self, state: Any) -> Any:
        fn = self._active_chunk_fn()

        def call():
            out = fn(state)
            _sync(out)
            return out

        if self.watchdog is None:
            out = call()
            self._first_call_done = True
            return out
        deadline = self.watchdog.chunk_deadline_s
        phase = "chunk"
        if not self._first_call_done:
            deadline += self.watchdog.compile_deadline_s
            phase = "compile+chunk"
        # one persistent worker across chunks (closed at run() end); a
        # hung worker is discarded and replaced — see WatchdogWorker
        if self._wd_worker is None or self._wd_worker.hung:
            self._wd_worker = WatchdogWorker()
        out = self._wd_worker.call(call, deadline, phase)
        self._first_call_done = True
        return out

    def _close_watchdog(self) -> None:
        if self._wd_worker is not None:
            self._wd_worker.close()
            self._wd_worker = None

    # -- observability ---------------------------------------------------

    def _record(self, kind: str, chunk: Optional[int] = None, **fields) -> None:
        if self.recorder is None:
            return
        ctx = self.ctx
        if ctx is not None and chunk is not None:
            ctx = ctx.child(chunk_seq=chunk)
        elif chunk is not None:
            fields.setdefault("chunk_seq", chunk)
        self.recorder.record(kind, ctx=ctx, **fields)

    @staticmethod
    def _tick_hwms(state: Any) -> dict:
        """Host-side read of the telemetry loop counters / high-water
        marks for the chunk-end event.  Read-only numpy views of an
        already-synced state — never feeds back into the sim."""
        tele = getattr(state, "tele", None)
        if tele is None or not hasattr(tele, "ticks"):
            return {}
        try:
            return {
                "ticks": int(np.asarray(tele.ticks).sum()),
                "jumps": int(np.asarray(tele.jumps).sum()),
                "jumped_ms": int(np.asarray(tele.jumped_ms).sum()),
                "wheel_fill_hwm": int(np.asarray(tele.wheel_fill_hwm).max()),
                "ovf_hwm": int(np.asarray(tele.ovf_hwm).max()),
            }
        except (TypeError, ValueError, AttributeError):
            return {}

    def _observe_chunk(self, state: Any, chunk: int, dt: float,
                       hwms: dict) -> None:
        """Mission-control hook at the per-chunk sync boundary: feed
        the timeseries history and run the invariant sentinel.  The
        state here is the same synced, host-readable one _tick_hwms
        just read.  Monitoring must never fail the run it watches, so
        everything is swallowed."""
        ctx = (
            self.ctx.child(chunk_seq=chunk) if self.ctx is not None else None
        )
        if self.timeseries is not None:
            try:
                self.timeseries.observe(
                    "supervisor.chunk_seconds", dt, ctx=ctx
                )
                for key in ("wheel_fill_hwm", "ovf_hwm"):
                    if key in hwms:
                        self.timeseries.observe(
                            f"supervisor.{key}", float(hwms[key]), ctx=ctx
                        )
            except Exception:  # noqa: BLE001 — monitoring is best-effort
                pass
        if self.row_watch is not None:
            try:
                self.row_watch(state, chunk)
            except Exception:  # noqa: BLE001 — monitoring is best-effort
                pass
        if self.sentinel is not None:
            self.sentinel.check(
                state, ctx=ctx, chunk=chunk,
                members=self.run_meta.get("members"),
                capacity=self.run_meta.get("capacity"),
            )

    # -- resume ---------------------------------------------------------

    @property
    def _needs_anchor(self) -> bool:
        """Host anchors exist to replay retries and seed checkpoints;
        without either, skip them entirely — a bare supervised pass then
        costs only the loop + sync bench's chunked_pass already paid."""
        return self.manager is not None or self.retry.max_attempts > 1

    def _resume(self):
        """-> (device_state, start_chunk, resumed_from_step, prior_times)."""
        if self.manager is None:
            if self.consume_template:
                # hand the template straight to chunk_fn (bench
                # semantics: a donating chunk_fn consumes it — the
                # caller passed a disposable copy); anchoring, if
                # needed, copies it first
                return self.template, 0, None, []
            return self._place(self._snapshot(self.template)), 0, None, []
        got = self.manager.restore_latest(self.template)
        if got is None:
            if self.consume_template:
                return self.template, 0, None, []
            return self._place(self._snapshot(self.template)), 0, None, []
        state, step, manifest = got
        meta = (manifest or {}).get("meta", {})
        saved_key = meta.get("run_key")
        if (
            self.run_key is not None
            and saved_key is not None
            and saved_key != self.run_key
        ):
            raise ResumeMismatchError(
                f"checkpoint step {step} in {self.manager.directory} "
                f"belongs to run {saved_key!r}, not {self.run_key!r} — "
                "point the supervisor at a fresh checkpoint_dir"
            )
        saved_chunk_ms = meta.get("chunk_ms")
        if (
            self.chunk_ms
            and saved_chunk_ms
            and int(saved_chunk_ms) != int(self.chunk_ms)
        ):
            raise ResumeMismatchError(
                f"checkpoint step {step} was written with "
                f"chunk_ms={saved_chunk_ms}, this run uses "
                f"chunk_ms={self.chunk_ms} — resume would change the "
                "chunk schedule and break bit-identity"
            )
        if step > self.n_chunks:
            raise ResumeMismatchError(
                f"checkpoint step {step} exceeds this run's "
                f"n_chunks={self.n_chunks}"
            )
        # adopt the checkpointed run identity: the ledger's run_id
        # belongs to the RUN, not the process, so a resume after SIGKILL
        # keeps emitting under the id the victim minted — obs_query then
        # reconstructs one timeline across both processes
        saved_run_id = meta.get("run_id")
        if saved_run_id:
            if self.ctx is None:
                self.ctx = TraceContext(
                    run_id=saved_run_id,
                    job_id=meta.get("job_id"),
                    tenant_id=meta.get("tenant_id"),
                )
            elif self.ctx.run_id != saved_run_id:
                self.ctx = self.ctx.child(run_id=saved_run_id)
        prior = list(meta.get("chunk_seconds", []))
        if self.timeseries is not None:
            try:
                # metric history survives kill+resume the same way the
                # run_id does: the manifest is the authority on the past
                self.timeseries.restore(meta.get("timeseries"))
            except Exception:  # noqa: BLE001 — monitoring is best-effort
                pass
        return self._place(self._snapshot(state)), step, step, prior

    def _save(self, state: Any, step: int, times_all: List[float]) -> None:
        meta = {
            **self.run_meta,
            "run_key": self.run_key,
            "chunk_ms": self.chunk_ms,
            "n_chunks": self.n_chunks,
            "chunks_done": step,
            "chunk_seconds": [round(t, 4) for t in times_all],
            "degraded": self._degraded,
        }
        if self.timeseries is not None:
            try:
                meta["timeseries"] = self.timeseries.snapshot()
            except Exception:  # noqa: BLE001 — monitoring is best-effort
                pass
        if self.ctx is not None:
            # trace ids into the manifest meta (checkpoint.save_state
            # surfaces them as manifest["trace"]) — the join key a
            # resume adopts and obs_query correlates on
            meta.setdefault("run_id", self.ctx.run_id)
            if self.ctx.job_id is not None:
                meta.setdefault("job_id", self.ctx.job_id)
            if self.ctx.tenant_id is not None:
                meta.setdefault("tenant_id", self.ctx.tenant_id)
        self.manager.save(state, step, meta=meta)
        self._record("checkpoint", step=step, dir=self.manager.directory)

    # -- the loop -------------------------------------------------------

    def run(self) -> RunReport:
        state, start_chunk, resumed_from, prior_times = self._resume()
        if self.ctx is None:
            # no caller-minted context and no checkpoint to adopt from:
            # this supervisor IS the run's entry point
            self.ctx = mint_context("run")
        if resumed_from is not None:
            self._record(
                "resume", step=resumed_from, run_key=self.run_key
            )
        anchor = self._snapshot(state) if self._needs_anchor else None
        anchor_chunk = start_chunk
        times: List[float] = []  # this run's completed chunks, in order
        i = start_chunk
        fail_streak = 0
        retries_total = 0
        watchdog_timeouts = 0
        checkpoints = 0
        degraded_at = None
        t_start = time.perf_counter()

        def provenance(done: int) -> dict:
            import jax

            return {
                "platform": jax.default_backend(),
                "degraded": self._degraded,
                "degraded_at_chunk": degraded_at,
                "resumed_from_step": resumed_from,
                "retries": retries_total,
                "watchdog_timeouts": watchdog_timeouts,
                "checkpoints": checkpoints,
                "run_key": self.run_key,
                "chunk_ms": self.chunk_ms,
                "n_chunks": self.n_chunks,
                "chunks_done": done,
                "chunk_time_hist": chunk_time_histogram(times),
                **(self.ctx.ids() if self.ctx is not None else {}),
            }

        try:
            while i < self.n_chunks:
                over_budget = time.perf_counter() - t_start > self.budget_s
                over_cap = (
                    self.max_chunks_this_run is not None
                    and len(times) >= self.max_chunks_this_run
                )
                stop_requested = (
                    self.should_stop is not None and self.should_stop()
                )
                if over_budget or over_cap or stop_requested:
                    # controlled partial stop: checkpoint NOW (even
                    # off-cadence — resumability beats cadence) and report
                    if self.manager is not None and i > anchor_chunk:
                        self._save(state, i, prior_times + times)
                        checkpoints += 1
                    self._record(
                        "partial-stop", chunk=i,
                        reason=(
                            "budget" if over_budget
                            else "chunk-cap" if over_cap
                            else "stop-requested"
                        ),
                        chunks_done=i,
                    )
                    return RunReport(
                        state, False, times, provenance(i)
                    )
                try:
                    self._record("chunk-start", chunk=i)
                    t1 = time.perf_counter()
                    state = self._run_chunk(state)
                    dt = time.perf_counter() - t1
                    hwms = self._tick_hwms(state)
                    self._record(
                        "chunk-end", chunk=i, seconds=round(dt, 4),
                        degraded=self._degraded or None,
                        **hwms,
                    )
                    self._observe_chunk(state, i, dt, hwms)
                    if self.tracer is not None:
                        self.tracer.add_span(
                            "chunk", self.tracer.now_us() - dt * 1e6, dt * 1e6,
                            chunk=i, degraded=self._degraded,
                        )
                except BaseException as e:  # noqa: BLE001 — classified below
                    kind = classify(e)
                    if isinstance(e, WatchdogTimeoutError):
                        watchdog_timeouts += 1
                        self._record(
                            "watchdog", chunk=i, phase=e.phase,
                            deadline_s=e.deadline_s,
                        )
                    if self.tracer is not None:
                        self.tracer.instant(
                            "chunk-failed", chunk=i, kind=kind,
                            error=type(e).__name__,
                        )
                    if kind not in RETRYABLE_KINDS:
                        # fatal, poison_row, lane_failed, any future
                        # non-environmental kind: replaying reproduces it
                        raise
                    fail_streak += 1
                    retries_total += 1
                    if fail_streak >= self.retry.max_attempts:
                        raise RetriesExhaustedError(fail_streak, e) from e
                    if (
                        kind == "device_lost"
                        and self.degrade is not None
                        and self.degrade.cpu_fallback
                        and not self._degraded
                    ):
                        self._degraded = True
                        degraded_at = i
                        self._first_call_done = False  # CPU gets a compile
                        self._record("degraded", chunk=i, to="cpu")
                        if self.tracer is not None:
                            self.tracer.instant("degraded-to-cpu", chunk=i)
                    delay = self.retry.delay_s(fail_streak - 1)
                    self._record(
                        "retry", chunk=i, error_kind=kind,
                        error=type(e).__name__, fail_streak=fail_streak,
                        delay_s=round(delay, 4), replay_from=anchor_chunk,
                    )
                    self.sleep(delay)
                    # replay deterministically from the last anchor: the
                    # chunks between anchor_chunk and i re-run and produce
                    # the exact bytes the failed timeline would have
                    state = self._place(anchor)
                    times = times[: anchor_chunk - start_chunk]
                    i = anchor_chunk
                    continue
                fail_streak = 0
                times.append(dt)
                if self.heartbeat is not None:
                    self.heartbeat(i, dt)
                i += 1
                at_cadence = (i - start_chunk) % self.checkpoint_every == 0
                if at_cadence or i == self.n_chunks:
                    if self.manager is not None:
                        self._save(state, i, prior_times + times)
                        checkpoints += 1
                    if self._needs_anchor:
                        anchor = self._snapshot(state)
                        anchor_chunk = i
        except BaseException as e:  # noqa: BLE001 — black-box dump, re-raised
            self._dump_on_failure(e, chunk=i)
            raise
        finally:
            self._close_watchdog()
        self._record("run-complete", chunks_done=self.n_chunks)
        return RunReport(state, True, times, provenance(self.n_chunks))

    def _dump_on_failure(self, exc: BaseException, chunk: int) -> None:
        """The black-box contract: any failure that escapes the retry
        loop dumps the flight-recorder ring atomically beside the
        checkpoints (and under $WITT_OBS_DIR if set) before the
        exception propagates."""
        if self.recorder is None:
            return
        kind = classify(exc)
        self._record(
            "failure", chunk=chunk, error_kind=kind,
            error=type(exc).__name__, message=str(exc)[:500],
            typed=isinstance(exc, DurableRunError),
        )
        ckpt_dir = self.manager.directory if self.manager is not None else None
        for path in failure_dump_paths(ckpt_dir):
            try:
                self.recorder.dump(path)
            except OSError:
                pass  # forensics must never mask the real failure

    # -- convenience ----------------------------------------------------

    @classmethod
    def from_network(
        cls,
        net: Any,
        state: Any,
        *,
        total_ms: int,
        chunk_ms: int,
        batched: bool = True,
        stop_when_done: bool = False,
        donate: bool = False,
        run_key: Optional[str] = None,
        **kw,
    ) -> "Supervisor":
        """Build a supervisor whose chunk_fn is a jitted chunk_ms slice
        of net.run_ms / net.run_ms_batched.

        Donation is SEMANTICALLY safe under the supervisor (retries
        replay from host anchors, never from a consumed buffer) but
        defaults OFF: jit(donate_argnums) chunk loops corrupt the heap
        ("corrupted double-linked list" aborts) on jaxlib 0.4.37 when
        the persistent compilation cache is enabled together with
        --xla_force_host_platform_device_count — exactly the tier-1 test
        configuration.  bench's AOT `lower().compile()` donated chunk fn
        does not exhibit this; callers that need donated buffers (TPU
        memory pressure) should compile that way and pass chunk_fn
        directly, or opt in here deliberately.

        stop_when_done note: the early exit changes which ticks execute
        per chunk boundary, so bit-identity of a chunked vs straight run
        is only guaranteed for the default stop_when_done=False (the
        done_at deliverable is preserved either way — see run_ms)."""
        import jax

        if total_ms % chunk_ms != 0:
            raise ValueError(
                f"total_ms={total_ms} must be a multiple of chunk_ms={chunk_ms}"
            )
        n_chunks = total_ms // chunk_ms
        runner = net.run_ms_batched if batched else net.run_ms
        chunk_fn = jax.jit(
            lambda s: runner(s, chunk_ms, stop_when_done),
            donate_argnums=(0,) if donate else (),
        )
        # the same jitted fn re-traces for CPU-placed inputs, so the
        # degraded path reuses it (jit specializes on input placement)
        if run_key is None:
            run_key = stable_run_key(net, state, n_chunks, chunk_ms)
        # durable compiles: with a compile store installed the chunk fn
        # dispatches through store-backed AOT programs keyed on the
        # engine's stable identity — a restarted process resumes a
        # checkpointed run without re-paying the chunk compile.  Donated
        # buffers keep the plain jit path: serialized executables do not
        # carry donation, and donation is opt-in anyway (the jaxlib
        # 0.4.37 landmine below).  Geometry (incl. placement) is part of
        # the store key, so the degraded CPU re-placement still works.
        if not donate:
            from .compile_store import durable_jit, get_compile_store

            if get_compile_store() is not None:
                stable = getattr(net, "stable_cache_key", None)
                base = (
                    repr(stable()) if callable(stable) else run_key
                )
                import hashlib as _hashlib

                chunk_fn = durable_jit(
                    chunk_fn,
                    "chunk/"
                    + _hashlib.blake2b(
                        f"{base}|{chunk_ms}|{int(stop_when_done)}|"
                        f"{int(batched)}".encode(),
                        digest_size=12,
                    ).hexdigest(),
                )
        return cls(
            chunk_fn,
            state,
            n_chunks=n_chunks,
            chunk_ms=chunk_ms,
            run_key=run_key,
            **kw,
        )

"""The lock registry, hierarchy, and runtime lock tracing (ISSUE 19).

The serving fleet's host-side concurrency surface — scheduler lanes,
the run cache, the compile store, the flight recorder, the HTTP server
— is certified by simlint pass 10 (analysis/concurrency_check.py)
against the declarations in this module:

* ``LOCK_HIERARCHY`` — every named lock in the host tree, in a TOTAL
  acquisition order (rank = position).  A thread holding a lock may
  only acquire locks of STRICTLY HIGHER rank; any two code paths that
  respect the order cannot deadlock on these locks.  SL1301 flags a
  lock construction missing from the registry, SL1302 flags an
  acquisition chain (across function boundaries) that inverts the
  order, SL1306 flags a stale registry row.
* ``no_blocking`` — dispatch-class locks (the scheduler's dispatch
  lock, the run-cache entry lock) under which NO blocking work may run:
  no XLA compiles, no ``block_until_ready``, no file I/O, no HTTP, no
  timeout-less ``queue.get`` (SL1303).  This is the PR-11 race's dual:
  that fix moved compiles OUTSIDE ``_dispatch_lock``; the rule keeps
  them out.
* ``TracedLock`` — the dynamic side.  Zero-cost-when-off (one module
  flag read per acquire); armed via ``WITT_LOCK_TRACE=1`` or
  ``arm_lock_trace()`` it records wait times and the runtime
  acquisition-order graph, and surfaces rank inversions / graph cycles
  as typed ``lock-order-violation`` flight-recorder events plus
  ``witt_runtime_lock_wait_seconds`` metrics (``lock_trace_status()``).
* ``yield_point`` — named interleaving hooks compiled into the
  scheduler / run-cache / compile-store hot paths.  No-ops unless a
  test installs a controller via ``set_interleave`` (tests/interleave.py
  drives them to force specific thread schedules — e.g. the PR-11
  duplicate-compile reproduction).  SL1307 keeps the ``YIELD_POINTS``
  catalog and the call sites in sync.

This module imports only the stdlib (the checker loads it standalone,
outside the package) and is itself exempt from pass 10.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

__all__ = [
    "LOCK_HIERARCHY",
    "LOCK_RANKS",
    "LockSpec",
    "TracedLock",
    "YIELD_POINTS",
    "arm_lock_trace",
    "lock_trace_status",
    "make_lock",
    "reset_lock_trace",
    "set_interleave",
    "yield_point",
]


@dataclass(frozen=True)
class LockSpec:
    """One registry row.  ``sites`` anchors the declaration to the
    actual construction(s) — ``"relpath::Class.attr"`` for instance
    locks, ``"relpath::GLOBAL.name"`` for module-level locks — so the
    static pass can prove the registry matches the tree (SL1301 for an
    undeclared construction, SL1306 for a stale row)."""

    name: str
    sites: Tuple[str, ...]
    no_blocking: bool = False
    doc: str = ""


# The total acquisition order, outermost (rank 0) to innermost.  A
# thread may acquire rank j while holding rank i only when j > i.
# Every verified nesting edge in the tree ascends this table; see
# docs/serving.md ("Lock hierarchy") for the edge inventory and the
# reasoning behind each placement.
LOCK_HIERARCHY: Tuple[LockSpec, ...] = (
    LockSpec(
        "server.run", ("server/ws.py::WServer.run_lock",),
        doc="legacy runMs busy latch; held across whole sliced runs",
    ),
    LockSpec(
        "server.http", ("server/ws.py::WServer.lock",),
        doc="shared simulation lock for locked HTTP routes",
    ),
    LockSpec(
        "serve.worker", ("serve/scheduler.py::BatchScheduler._worker_lock",),
        doc="lane thread spawn/restart bookkeeping",
    ),
    LockSpec(
        "serve.dispatch", ("serve/scheduler.py::BatchScheduler._dispatch_lock",),
        no_blocking=True,
        doc="batch claim + lane binding; compiles stay OUTSIDE (PR 11)",
    ),
    LockSpec(
        "serve.family", ("serve/scheduler.py::BatchScheduler._fam_lock",),
        doc="per-family admission bookkeeping",
    ),
    LockSpec(
        "serve.queue", ("serve/jobs.py::JobQueue._lock",),
        doc="job queue state (+ its _work Condition alias)",
    ),
    LockSpec(
        "serve.metrics", ("serve/metrics.py::ServeMetrics._lock",),
        doc="serve counters/quantile rings",
    ),
    LockSpec(
        "obs.sentinel", ("obs/monitor.py::InvariantSentinel._lock",),
        doc="invariant sentinel fired-set latch",
    ),
    LockSpec(
        "obs.slo", ("obs/slo.py::SLOEngine._lock",),
        doc="SLO burn-rate engine state",
    ),
    LockSpec(
        "runcache.entry", ("parallel/replica_shard.py::GLOBAL._CACHE_LOCK",),
        no_blocking=True,
        doc="run-cache entry map + counters; never held across a compile",
    ),
    LockSpec(
        "runcache.compile", ("parallel/replica_shard.py::_CachedRun._compile_lock",),
        doc="per-entry compile serialization (the PR-11 guard)",
    ),
    LockSpec(
        "store.jit", ("runtime/compile_store.py::DurableJit._lock",),
        doc="DurableJit per-geometry program map",
    ),
    LockSpec(
        "store.entry", ("runtime/compile_store.py::CompileStore._lock",),
        doc="compile-store payload+manifest writes",
    ),
    LockSpec(
        "store.default", ("runtime/compile_store.py::GLOBAL._DEFAULT_LOCK",),
        doc="process-default store singleton latch",
    ),
    LockSpec(
        "store.counters", ("runtime/compile_store.py::GLOBAL._COUNTER_LOCK",),
        doc="store hit/miss counters",
    ),
    LockSpec(
        "runtime.taxonomy", ("runtime/errors.py::GLOBAL._TAXONOMY_LOCK",),
        doc="error taxonomy counters",
    ),
    LockSpec(
        "obs.timeseries", ("obs/timeseries.py::TimeSeriesStore._lock",),
        doc="in-process time-series ring",
    ),
    LockSpec(
        "telemetry.trace", ("telemetry/trace.py::SpanTracer._lock",),
        doc="span tracer event list",
    ),
    LockSpec(
        "obs.recorder_default", ("obs/recorder.py::GLOBAL._default_lock",),
        doc="process-default recorder singleton latch",
    ),
    LockSpec(
        "obs.recorder", ("obs/recorder.py::FlightRecorder._lock",),
        doc="flight-recorder ring; holds its own fsync I/O by design "
        "(tail-safety beats latency), so it is the INNERMOST rank",
    ),
)

LOCK_RANKS: Dict[str, int] = {
    spec.name: rank for rank, spec in enumerate(LOCK_HIERARCHY)
}
_SPECS: Dict[str, LockSpec] = {spec.name: spec for spec in LOCK_HIERARCHY}


def _env_armed() -> bool:
    return os.environ.get("WITT_LOCK_TRACE", "") not in ("", "0", "off")


# -- trace state --------------------------------------------------------------
_armed: bool = _env_armed()
_tls = threading.local()
#: guards every module-level structure below.  Internal to the tracer
#: (not a registry lock): it is only ever the innermost acquisition and
#: never held across a callback, so it cannot participate in a cycle.
_state_lock = threading.Lock()
_edges: Dict[Tuple[str, str], int] = {}
_violations: List[dict] = []
_violation_pairs: set = set()
_wait_stats: Dict[str, List[float]] = {}  # name -> [count, total_s, max_s]
_wait_samples: deque = deque(maxlen=4096)


def arm_lock_trace(on: bool = True) -> None:
    """Flip tracing at runtime (tests).  The env var ``WITT_LOCK_TRACE``
    sets the process default at import time."""
    global _armed
    _armed = bool(on)


def reset_lock_trace() -> None:
    """Clear the recorded graph, violations, and wait metrics (the armed
    flag is untouched).  Call between test phases."""
    with _state_lock:
        _edges.clear()
        _violations.clear()
        _violation_pairs.clear()
        _wait_stats.clear()
        _wait_samples.clear()


def _held_stack() -> list:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
    return stack


def _has_path(src: str, dst: str) -> bool:
    """DFS over the observed edge graph: is dst reachable from src?"""
    seen = set()
    frontier = [src]
    while frontier:
        node = frontier.pop()
        if node == dst:
            return True
        if node in seen:
            continue
        seen.add(node)
        frontier.extend(b for (a, b) in _edges if a == node)
    return False


class TracedLock:
    """A named, hierarchy-ranked ``threading.Lock`` wrapper.

    Unarmed, ``acquire``/``release`` delegate with a single module-flag
    read — measured indistinguishable from a bare lock.  Armed, each
    acquisition is timed, pushed on a thread-local held stack, and
    checked against every held lock: a rank inversion (or a cycle the
    new edge closes in the cross-thread acquisition graph) is recorded
    once per (held, acquiring) pair and emitted as a
    ``lock-order-violation`` flight-recorder event.
    """

    __slots__ = ("name", "rank", "_lock")

    def __init__(self, name: str):
        if name not in LOCK_RANKS:
            raise ValueError(
                f"lock {name!r} is not in LOCK_HIERARCHY; register it "
                "in runtime/locks.py before constructing it"
            )
        self.name = name
        self.rank = LOCK_RANKS[name]
        self._lock = threading.Lock()

    # threading.Lock signature, Condition-compatible
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if not _armed or getattr(_tls, "tracing", False):
            return self._lock.acquire(blocking, timeout)
        _tls.tracing = True
        try:
            held = _held_stack()
            if held:
                self._audit(held)
            t0 = time.perf_counter()
        finally:
            _tls.tracing = False
        ok = self._lock.acquire(blocking, timeout)
        if not _armed:
            return ok
        _tls.tracing = True
        try:
            if ok:
                waited = time.perf_counter() - t0
                _held_stack().append(self)
                with _state_lock:
                    st = _wait_stats.setdefault(self.name, [0, 0.0, 0.0])
                    st[0] += 1
                    st[1] += waited
                    st[2] = max(st[2], waited)
                    _wait_samples.append(waited)
        finally:
            _tls.tracing = False
        return ok

    def release(self) -> None:
        self._lock.release()
        held = getattr(_tls, "held", None)
        if held:
            for i in range(len(held) - 1, -1, -1):
                if held[i] is self:
                    del held[i]
                    break

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "TracedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"TracedLock({self.name!r}, rank={self.rank})"

    def _audit(self, held: list) -> None:
        """Record edges held->self; a rank inversion or a closed cycle
        is a violation (deduped per pair).  Called with tracing=True so
        the recorder emission below cannot recurse."""
        fresh: List[dict] = []
        with _state_lock:
            for h in held:
                pair = (h.name, self.name)
                _edges[pair] = _edges.get(pair, 0) + 1
                bad = None
                if self.rank <= h.rank:
                    bad = (
                        "rank inversion" if self.rank < h.rank
                        else "re-acquisition of a held non-reentrant lock"
                    )
                elif _has_path(self.name, h.name):
                    bad = "acquisition-graph cycle"
                if bad and pair not in _violation_pairs:
                    _violation_pairs.add(pair)
                    v = {
                        "held": h.name,
                        "heldRank": h.rank,
                        "acquiring": self.name,
                        "acquiringRank": self.rank,
                        "kind": bad,
                        "thread": threading.current_thread().name,
                    }
                    _violations.append(v)
                    fresh.append(v)
        for v in fresh:
            _emit_violation(v)


def _emit_violation(v: dict) -> None:
    """Typed flight-recorder event; best-effort (the tracer must never
    take the fleet down).  Absolute import: this module is also loaded
    standalone by the static checker, where the package may be absent —
    there no violations are ever emitted."""
    try:
        from wittgenstein_tpu.obs.recorder import get_recorder

        get_recorder().record(
            "lock-order-violation",
            held=v["held"],
            acquiring=v["acquiring"],
            held_rank=v["heldRank"],
            acquiring_rank=v["acquiringRank"],
            cycle_kind=v["kind"],
            thread=v["thread"],
        )
    except Exception:
        pass


def make_lock(name: str) -> TracedLock:
    """Construct the registered lock ``name``.  The static pass accepts
    only registered names here (SL1301)."""
    return TracedLock(name)


def lock_trace_status() -> dict:
    """The ``witt_runtime_lock_wait_seconds`` surface: armed flag,
    violation count (+ the deduped violation rows), max/p99 observed
    wait, per-lock acquisition counts.  Cheap enough for /w/health."""
    with _state_lock:
        samples = sorted(_wait_samples)
        per_lock = {
            name: {
                "acquisitions": int(st[0]),
                "waitSecondsTotal": round(st[1], 6),
                "maxWaitS": round(st[2], 6),
            }
            for name, st in sorted(_wait_stats.items())
        }
        violations = [dict(v) for v in _violations]
    p99 = samples[min(len(samples) - 1, int(0.99 * len(samples)))] if samples else 0.0
    return {
        "armed": _armed,
        "violationCount": len(violations),
        "violations": violations,
        "maxWaitS": round(max((s[2] for s in _wait_stats.values()), default=0.0), 6),
        "waitP99S": round(p99, 6),
        "perLock": per_lock,
    }


# -- deterministic interleaving hooks ----------------------------------------
#: every named yield point compiled into a hot path.  SL1307 asserts
#: this catalog and the yield_point() call sites stay in sync.
YIELD_POINTS: Tuple[str, ...] = (
    "runcache.lookup-miss",   # after an unlocked run-cache program miss
    "runcache.compile",       # inside the compile lock, recheck missed
    "store.get",              # compile-store payload read
    "store.put",              # compile-store payload publish
    "serve.claim",            # lane about to claim a batch
    "serve.dispatch",         # batch about to execute on its lane
    "serve.harvest",          # done-row harvest decision point
    "serve.lane-failure",     # lane failover about to rebind
)

_interleave: Optional[Callable[[str], None]] = None


def set_interleave(controller: Optional[Callable[[str], None]]) -> None:
    """Install (or clear, with None) the interleaving controller.  The
    controller is called with the yield-point name from the thread that
    reached it and may block to impose a schedule (tests/interleave.py)."""
    global _interleave
    _interleave = controller


def yield_point(name: str) -> None:
    """A named scheduling hook: no-op (one global read) unless a
    controller is installed."""
    c = _interleave
    if c is not None:
        c(name)

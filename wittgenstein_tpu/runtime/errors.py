"""Structured error taxonomy for the durable-run supervisor.

The split that matters operationally is TRANSIENT vs FATAL:

- **Transient** failures (device lost, preemption, tunnel resets) are
  the supervisor's to handle — bounded retry with exponential backoff,
  replaying deterministically from the last host anchor so the retried
  run is bit-identical to one that never failed.
- **Fatal** failures (watchdog deadline, shape/layout mismatch on
  resume, retries exhausted) stop the run with a typed exception the
  caller can route — never a bare RuntimeError three frames into jax.

`classify` maps arbitrary exceptions (including jax/XLA runtime errors,
which arrive as generic Exception subclasses with backend-specific
messages) onto the taxonomy using message markers collected from the
r3-r5 TPU-tunnel postmortems.
"""

from __future__ import annotations

import threading
from collections import Counter


class DurableRunError(Exception):
    """Base for every structured supervisor failure."""


class TransientRunError(DurableRunError):
    """Worth retrying: the failure is environmental, not semantic."""


class FatalRunError(DurableRunError):
    """Retrying cannot help; the run stops with this as the reason."""


class DeviceLostError(TransientRunError):
    """The accelerator went away mid-run (tunnel reset, worker crash,
    preemption of the device)."""


class PreemptedError(TransientRunError):
    """The host/process was asked to stop (scheduler preemption); state
    up to the last checkpoint survives."""


class WatchdogTimeoutError(FatalRunError):
    """A compile or chunk exceeded its deadline.  Fatal IN-PROCESS: a
    hung device call cannot be cancelled from Python (killing mid-call
    wedges the tunneled worker — r3/r4 lesson), so the in-process
    supervisor stops issuing work and reports; process-level supervisors
    (tpu_campaign) own the actual kill."""

    def __init__(self, phase: str, deadline_s: float):
        self.phase = phase
        self.deadline_s = deadline_s
        super().__init__(
            f"{phase} exceeded its {deadline_s:.0f}s watchdog deadline"
        )


class RetriesExhaustedError(FatalRunError):
    """The retry policy's attempt budget ran out on transient failures."""

    def __init__(self, attempts: int, last: BaseException):
        self.attempts = attempts
        self.last = last
        super().__init__(
            f"gave up after {attempts} attempts; last failure: "
            f"{type(last).__name__}: {last}"
        )


class ResumeMismatchError(FatalRunError):
    """A checkpoint exists but belongs to a different run (run_key or
    chunk geometry mismatch) — resuming would silently mix runs."""


class PoisonRowError(FatalRunError):
    """One row of a packed batch is semantically poisonous: the batch
    failed WITH it and succeeded WITHOUT it (scheduler salvage
    bisection), or its row could not even be built.  Quarantining the
    carrying job is the only fix — retrying the batch replays the same
    poison.  Carries the job id and the original failure so the job's
    terminal status stays honest."""

    def __init__(self, job_id: str, cause: BaseException):
        self.job_id = job_id
        self.cause = cause
        super().__init__(
            f"job {job_id} poisons its batch: "
            f"{type(cause).__name__}: {cause}"
        )


class LaneFailedError(TransientRunError):
    """A dispatch lane's worker thread died (escaped exception or an
    injected chaos kill).  Transient at fleet level: the scheduler
    restarts the lane and re-binds its sticky families to a healthy
    one; no job is lost (undispatched work stays queued, parked batches
    keep their checkpoints)."""

    def __init__(self, lane: int, reason: str = "lane worker died"):
        self.lane = lane
        super().__init__(f"lane {lane}: {reason}")


class RunIncompleteError(DurableRunError):
    """A controlled partial stop (budget exhausted / chunk cap reached).
    Carries the partial RunReport so callers can checkpoint-and-requeue."""

    def __init__(self, message: str, report=None):
        self.report = report
        super().__init__(message)


# lowercase substrings that mark an environmental (retryable) failure in
# backend exception text; collected from real tunnel failures (r3-r5)
# and the jax/XLA status-code vocabulary
_TRANSIENT_MARKERS = (
    "deadline_exceeded",
    "deadline exceeded",
    "unavailable",
    "resource_exhausted",
    "resource exhausted",
    "preempt",
    "worker crashed",
    "worker process crashed",
    "connection reset",
    "connection refused",
    "broken pipe",
    "socket closed",
    "transport closed",
    "heartbeat",
)

_DEVICE_LOST_MARKERS = (
    "device lost",
    "worker crashed",
    "worker process crashed",
    "tpu is dead",
    "failed to connect",
    "transport closed",
)


# process-wide taxonomy counters: every classify() call increments its
# kind, so /w/health and the chaos harness can report how failures
# distributed without re-walking the flight recorder
_TAXONOMY_LOCK = threading.Lock()
_TAXONOMY_COUNTS: Counter = Counter()


def taxonomy_counters() -> dict:
    """Snapshot of {kind: count} over every classify() call since
    process start (or the last reset)."""
    with _TAXONOMY_LOCK:
        return dict(_TAXONOMY_COUNTS)


def reset_taxonomy_counters() -> None:
    with _TAXONOMY_LOCK:
        _TAXONOMY_COUNTS.clear()


def _classify(exc: BaseException) -> str:
    if isinstance(exc, PoisonRowError):
        return "poison_row"
    if isinstance(exc, LaneFailedError):
        return "lane_failed"
    if isinstance(exc, DeviceLostError):
        return "device_lost"
    if isinstance(exc, TransientRunError):
        return "transient"
    if isinstance(exc, FatalRunError):
        return "fatal"
    if isinstance(exc, (KeyboardInterrupt, SystemExit)):
        return "fatal"
    text = str(exc).lower()
    if any(m in text for m in _DEVICE_LOST_MARKERS):
        return "device_lost"
    if any(m in text for m in _TRANSIENT_MARKERS):
        return "transient"
    return "fatal"


#: kinds the supervisor may retry; everything else ('fatal',
#: 'poison_row', future additions) must propagate — replaying a
#: semantic failure reproduces it.  lane_failed IS retryable: a lane
#: death says nothing about the work it carried (the fleet restarts
#: the lane and the jobs re-run elsewhere, bitwise-identical).
RETRYABLE_KINDS = frozenset({"transient", "device_lost", "lane_failed"})


def classify(exc: BaseException) -> str:
    """Map an exception to a taxonomy kind: 'transient' | 'device_lost'
    | 'fatal' | 'poison_row' | 'lane_failed'.

    device_lost is a sub-case of transient that additionally makes the
    current backend suspect — the degradation policy keys off it.
    poison_row / lane_failed are fleet-level kinds (serve scheduler);
    only RETRYABLE_KINDS are safe to replay.
    """
    kind = _classify(exc)
    with _TAXONOMY_LOCK:
        _TAXONOMY_COUNTS[kind] += 1
    return kind

"""Durable compiled-executable store: zero-compile warm starts.

The run cache (parallel.replica_shard) and the Supervisor chunk fn make
compiles a per-process cost: every restart re-pays multi-second XLA
compiles for programs whose static inputs have not changed.  The
checkpoint manager already made the *state* restart-proof; this module
does the same for the *programs*.  A compiled executable is
AOT-serialized (jax.experimental.serialize_executable — the
`lower().compile()` object round-trips bitwise, proven by the warm-start
smoke) and written under a content-addressed entry:

    <store>/<blake2b(program key)>.bin        pickled (bytes, in_tree,
                                              out_tree) serialize payload
    <store>/<blake2b(program key)>.json       manifest

The manifest mirrors engine/checkpoint.py's discipline: a format stamp,
every key component spelled out (so staleness is *diagnosable*, not just
a cache miss), a payload checksum, and atomic pid-tmp + os.replace
writes so a torn entry can never be observed.  ``get`` validates
backend, jaxlib/jax versions, ENGINE_LAYOUT and the payload checksum
before deserializing; ANY mismatch or decode failure falls back to a
fresh compile — a corrupt store can cost time, never correctness.

Keying: the caller supplies a *stable* program key (restart-stable, the
`stable_run_key` family of digests — NEVER `net.cache_key()`, whose
``id(protocol)`` components die with the process) plus the input
geometry signature.  The entry filename hashes only the program key +
geometry; the environment components (backend, versions, layout) live in
the manifest, so an entry written by an older jaxlib is *detected* as
stale (counted, logged) rather than silently shadowed by a new key.

The store is deliberately NOT the JAX persistent compilation cache: that
cache still pays lowering + cache lookup inside ``lower().compile()``,
so the run cache's "compiles" counter ticks and the cost-attribution
path books a compile.  A store hit bypasses lowering entirely — the
counter-asserted contract is *zero* fresh compiles on a warm restart.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import threading
from typing import Any, Callable, Dict, Optional

from .locks import make_lock, yield_point

STORE_FORMAT = "witt-compile-store/v1"

#: monotonic per-process counters (Prometheus discipline: survive
#: clear/close, never step backwards)
_COUNTERS = {
    "hits": 0,
    "misses": 0,
    "stores": 0,
    "stale": 0,
    "corrupt": 0,
    "errors": 0,
}
_COUNTER_LOCK = threading.Lock()


def _count(key: str) -> None:
    with _COUNTER_LOCK:
        _COUNTERS[key] += 1


def compile_store_counters() -> dict:
    with _COUNTER_LOCK:
        return dict(_COUNTERS)


def _environment() -> Dict[str, str]:
    """The compile-validity environment: everything that can change the
    meaning of a serialized executable without changing the program key.
    ENGINE_LAYOUT rides along so an engine-generation bump (which changes
    every state layout) bulk-invalidates the store exactly like it
    invalidates checkpoints."""
    import jax
    import jaxlib

    from ..engine.checkpoint import ENGINE_LAYOUT

    return {
        "backend": jax.default_backend(),
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "engine_layout": ENGINE_LAYOUT,
        "device_count": str(jax.device_count()),
    }


class CompileStore:
    """One directory of durable executables.  Thread-safe; every public
    method is best-effort — storage failures count and return, they
    never raise into a dispatch path."""

    def __init__(self, directory: str):
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._lock = make_lock("store.entry")

    # -- keying ---------------------------------------------------------

    @staticmethod
    def entry_name(stable_key: str) -> str:
        return hashlib.blake2b(
            stable_key.encode(), digest_size=16
        ).hexdigest()

    def _paths(self, stable_key: str):
        name = self.entry_name(stable_key)
        return (
            os.path.join(self.directory, name + ".json"),
            os.path.join(self.directory, name + ".bin"),
        )

    # -- write ----------------------------------------------------------

    def put(self, stable_key: str, compiled: Any,
            mesh_geometry: Optional[str] = None) -> bool:
        """Serialize one compiled executable under ``stable_key``.
        ``mesh_geometry`` (mesh_geometry_signature of the program's
        inputs) is recorded in the manifest so a stale-by-mesh entry is
        diagnosable, not just a miss.  Returns False (counted as an
        error) when the executable refuses to serialize or the
        filesystem refuses the write."""
        yield_point("store.put")
        from jax.experimental import serialize_executable

        try:
            payload = pickle.dumps(serialize_executable.serialize(compiled))
        except Exception:  # noqa: BLE001 — unserializable program
            _count("errors")
            return False
        manifest = {
            "format": STORE_FORMAT,
            "stable_key": stable_key,
            "payload_sha256": hashlib.sha256(payload).hexdigest(),
            "payload_bytes": len(payload),
            **_environment(),
        }
        if mesh_geometry is not None:
            manifest["mesh_geometry"] = mesh_geometry
        man_path, bin_path = self._paths(stable_key)
        pid = os.getpid()
        try:
            with self._lock:
                # payload first, manifest last: the manifest is the
                # commit point (get() reads it first), so a crash
                # between the two replaces leaves no visible entry
                for path, data in (
                    (bin_path, payload),
                    (man_path, json.dumps(manifest, sort_keys=True).encode()),
                ):
                    tmp = f"{path}.tmp.{pid}"
                    try:
                        with open(tmp, "wb") as f:
                            f.write(data)
                        os.replace(tmp, path)
                    finally:
                        if os.path.exists(tmp):
                            os.remove(tmp)
        except OSError:
            _count("errors")
            return False
        _count("stores")
        return True

    # -- read -----------------------------------------------------------

    def get(self, stable_key: str,
            mesh_geometry: Optional[str] = None) -> Optional[Any]:
        """Load the executable stored under ``stable_key``, or None.
        None means "compile fresh": missing entry (miss), environment
        mismatch (stale) or undecodable entry (corrupt) all degrade the
        same way and are counted separately.  When ``mesh_geometry`` is
        given, an entry recorded under a different mesh shape — same
        device COUNT, different (axis, size) factorization, e.g. (2,4)
        vs (4,2) of 8 devices — is stale, never served."""
        yield_point("store.get")
        man_path, bin_path = self._paths(stable_key)
        try:
            with open(man_path, "rb") as f:
                manifest = json.loads(f.read())
        except FileNotFoundError:
            _count("misses")
            return None
        except (OSError, ValueError):
            _count("corrupt")
            return None
        if not isinstance(manifest, dict):
            _count("corrupt")
            return None
        if manifest.get("format") != STORE_FORMAT or manifest.get(
            "stable_key"
        ) != stable_key:
            _count("stale")
            return None
        env = _environment()
        if any(manifest.get(k) != v for k, v in env.items()):
            _count("stale")
            return None
        # symmetric: an entry recorded under a mesh shape is stale for a
        # caller that declares none, and vice versa — "I don't know the
        # mesh" must never adopt a partitioned executable
        if manifest.get("mesh_geometry") != mesh_geometry:
            _count("stale")
            return None
        try:
            with open(bin_path, "rb") as f:
                payload = f.read()
        except OSError:
            _count("corrupt")
            return None
        if (
            len(payload) != manifest.get("payload_bytes")
            or hashlib.sha256(payload).hexdigest()
            != manifest.get("payload_sha256")
        ):
            _count("corrupt")
            return None
        try:
            from jax.experimental import serialize_executable

            loaded = serialize_executable.deserialize_and_load(
                *pickle.loads(payload)
            )
        except Exception:  # noqa: BLE001 — any decode failure degrades
            _count("corrupt")
            return None
        _count("hits")
        return loaded

    # -- exposition ------------------------------------------------------

    def entries(self) -> list:
        """Manifest snapshots of every committed entry (diagnostics)."""
        out = []
        try:
            names = sorted(os.listdir(self.directory))
        except OSError:
            return out
        for name in names:
            if not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(self.directory, name), "rb") as f:
                    out.append(json.loads(f.read()))
            except (OSError, ValueError):
                continue
        return out

    def stats(self) -> dict:
        return {
            "directory": self.directory,
            "entries": sum(
                1
                for n in (
                    os.listdir(self.directory)
                    if os.path.isdir(self.directory)
                    else ()
                )
                if n.endswith(".json")
            ),
            **compile_store_counters(),
        }


# ---------------------------------------------------------------------------
# process default

ENV_STORE = "WITT_COMPILE_STORE"

_DEFAULT: Optional[CompileStore] = None
_DEFAULT_RESOLVED = False
_DEFAULT_LOCK = threading.Lock()


def set_compile_store(store: "CompileStore | str | None") -> Optional[CompileStore]:
    """Install (or clear, with None) the process-wide store used by the
    run cache and durable chunk fns.  A string is a directory."""
    global _DEFAULT, _DEFAULT_RESOLVED
    with _DEFAULT_LOCK:
        _DEFAULT = CompileStore(store) if isinstance(store, str) else store
        _DEFAULT_RESOLVED = True
        return _DEFAULT


def get_compile_store() -> Optional[CompileStore]:
    """The process-wide store: whatever set_compile_store installed,
    else $WITT_COMPILE_STORE (resolved once), else None (store off)."""
    global _DEFAULT, _DEFAULT_RESOLVED
    with _DEFAULT_LOCK:
        if not _DEFAULT_RESOLVED:
            path = os.environ.get(ENV_STORE)
            if path:
                try:
                    _DEFAULT = CompileStore(path)
                except OSError:
                    _DEFAULT = None
            _DEFAULT_RESOLVED = True
        return _DEFAULT


# ---------------------------------------------------------------------------
# durable jit: the Supervisor chunk-fn integration


def geometry_signature(args: Any) -> str:
    """Restart-stable digest of an input pytree's geometry: leaf paths,
    shapes, dtypes and placements.  str(sharding) is deterministic for a
    given device topology (the device ids XLA mints under a fixed
    --xla_force_host_platform_device_count are stable), and topology
    itself is part of the store environment (device_count)."""
    import jax

    parts = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(args)[0]:
        sharding = getattr(leaf, "sharding", None)
        parts.append(
            f"{path}:{getattr(leaf, 'shape', ())}"
            f":{getattr(leaf, 'dtype', type(leaf).__name__)}"
            f":{sharding}"
        )
    return hashlib.blake2b(
        "|".join(parts).encode(), digest_size=12
    ).hexdigest()


def mesh_geometry_signature(args: Any) -> str:
    """Canonical tag of the mesh SHAPES an input pytree is committed to:
    every distinct (axis_names × axis_sizes) among the leaves' mesh-
    backed shardings, sorted, or ``"unmeshed"`` when no leaf carries
    one.  This is the key component _environment()'s ``device_count``
    cannot express: a (2,4) and a (4,2) mesh of the same 8 devices have
    equal device counts but partition a program differently, so their
    executables must never share a store entry."""
    import jax

    shapes = set()
    for leaf in jax.tree_util.tree_leaves(args):
        sharding = getattr(leaf, "sharding", None)
        mesh = getattr(sharding, "mesh", None)
        if mesh is None:
            continue
        try:
            shapes.add(
                ",".join(
                    f"{name}={int(mesh.shape[name])}"
                    for name in mesh.axis_names
                )
            )
        except (AttributeError, TypeError, KeyError):
            continue
    if not shapes:
        return "unmeshed"
    return ";".join(sorted(shapes))


class DurableJit:
    """jit semantics with store-backed compiles: per input geometry,
    try the compile store, else ``lower().compile()`` and publish.  The
    Supervisor's chunk fn uses this so a restarted server resumes a
    checkpointed batch without re-paying the chunk program's compile.

    ``compiles`` counts FRESH XLA compiles only (store hits don't tick
    it) — the warm-start smoke asserts on exactly this.
    """

    def __init__(self, fn: Callable, stable_key: str,
                 store: "CompileStore | None" = None):
        import jax

        self._jit = fn if hasattr(fn, "lower") else jax.jit(fn)
        self.stable_key = stable_key
        self._store = store
        self._programs: Dict[str, Any] = {}
        self._lock = threading.Lock()
        self.compiles = 0

    def _resolve_store(self) -> Optional[CompileStore]:
        return self._store if self._store is not None else get_compile_store()

    def __call__(self, *args):
        sig = geometry_signature(args)
        compiled = self._programs.get(sig)
        if compiled is None:
            with self._lock:
                compiled = self._programs.get(sig)
                if compiled is None:
                    store = self._resolve_store()
                    mesh_sig = mesh_geometry_signature(args)
                    key = (
                        f"{self.stable_key}/mesh-{mesh_sig}/geom-{sig}"
                    )
                    if store is not None:
                        compiled = store.get(key, mesh_geometry=mesh_sig)
                    if compiled is None:
                        compiled = self._jit.lower(*args).compile()
                        self.compiles += 1
                        if store is not None:
                            store.put(key, compiled,
                                      mesh_geometry=mesh_sig)
                    self._programs[sig] = compiled
        return compiled(*args)


def durable_jit(fn: Callable, stable_key: str,
                store: "CompileStore | None" = None) -> DurableJit:
    """Wrap ``fn`` (or an existing jit) in store-backed AOT dispatch."""
    return DurableJit(fn, stable_key, store)

"""Supervisor policies: retry/backoff, watchdog deadlines, degradation.

All three are frozen dataclasses so they hash/compare cleanly and can be
stamped into run provenance.  Backoff jitter is DETERMINISTIC (hashed
from seed + attempt) — a resumed supervisor replays the same delays,
keeping kill-and-resume runs reproducible end to end, and tests can pin
exact delay sequences without mocking random.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded retry with exponential backoff + deterministic jitter.

    attempt n (0-based retry count) sleeps
      min(backoff_max_s, backoff_base_s * backoff_factor**n) * (1 ± jitter)
    where jitter is a hash of (seed, n) in [-jitter_frac, +jitter_frac].
    max_attempts counts EXECUTIONS, not retries: 3 means one initial try
    plus two retries.
    """

    max_attempts: int = 3
    backoff_base_s: float = 0.5
    backoff_factor: float = 2.0
    backoff_max_s: float = 30.0
    jitter_frac: float = 0.25
    seed: int = 0

    def delay_s(self, attempt: int) -> float:
        """Backoff delay before retry number `attempt` (0-based)."""
        base = min(
            self.backoff_max_s,
            self.backoff_base_s * (self.backoff_factor ** attempt),
        )
        if self.jitter_frac <= 0:
            return base
        h = hashlib.blake2b(
            f"{self.seed}:{attempt}".encode(), digest_size=8
        ).digest()
        unit = int.from_bytes(h, "big") / float(1 << 64)  # [0, 1)
        return base * (1.0 + self.jitter_frac * (2.0 * unit - 1.0))


@dataclass(frozen=True)
class WatchdogPolicy:
    """Per-phase deadlines.  A chunk that misses its deadline is treated
    as a hung device and raises WatchdogTimeoutError; the first chunk of
    a cold process gets compile_deadline_s ON TOP of chunk_deadline_s
    (jit compiles lazily inside the first call).  Defaults mirror
    scripts/tpu_campaign.py's process-level limits."""

    chunk_deadline_s: float = 180.0
    compile_deadline_s: float = 780.0


@dataclass(frozen=True)
class DegradePolicy:
    """What to do when the device is lost: with cpu_fallback, the
    supervisor re-places the last anchor on CPU and continues there,
    stamping {degraded, degraded_at_chunk} into provenance so a CPU
    number can never masquerade as a TPU number."""

    cpu_fallback: bool = False

"""Supervisor policies: retry/backoff, watchdog deadlines, degradation —
plus the WatchdogWorker that executes guarded calls.

The policies are frozen dataclasses so they hash/compare cleanly and can
be stamped into run provenance.  Backoff jitter is DETERMINISTIC (hashed
from seed + attempt) — a resumed supervisor replays the same delays,
keeping kill-and-resume runs reproducible end to end, and tests can pin
exact delay sequences without mocking random.
"""

from __future__ import annotations

import hashlib
import queue
import threading
from dataclasses import dataclass
from typing import Any, Callable

from .errors import WatchdogTimeoutError


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded retry with exponential backoff + deterministic jitter.

    attempt n (0-based retry count) sleeps
      min(backoff_max_s, backoff_base_s * backoff_factor**n) * (1 ± jitter)
    where jitter is a hash of (seed, n) in [-jitter_frac, +jitter_frac].
    max_attempts counts EXECUTIONS, not retries: 3 means one initial try
    plus two retries.
    """

    max_attempts: int = 3
    backoff_base_s: float = 0.5
    backoff_factor: float = 2.0
    backoff_max_s: float = 30.0
    jitter_frac: float = 0.25
    seed: int = 0

    def delay_s(self, attempt: int) -> float:
        """Backoff delay before retry number `attempt` (0-based)."""
        base = min(
            self.backoff_max_s,
            self.backoff_base_s * (self.backoff_factor ** attempt),
        )
        if self.jitter_frac <= 0:
            return base
        h = hashlib.blake2b(
            f"{self.seed}:{attempt}".encode(), digest_size=8
        ).digest()
        unit = int.from_bytes(h, "big") / float(1 << 64)  # [0, 1)
        return base * (1.0 + self.jitter_frac * (2.0 * unit - 1.0))


@dataclass(frozen=True)
class WatchdogPolicy:
    """Per-phase deadlines.  A chunk that misses its deadline is treated
    as a hung device and raises WatchdogTimeoutError; the first chunk of
    a cold process gets compile_deadline_s ON TOP of chunk_deadline_s
    (jit compiles lazily inside the first call).  Defaults mirror
    scripts/tpu_campaign.py's process-level limits."""

    chunk_deadline_s: float = 180.0
    compile_deadline_s: float = 780.0


class WatchdogWorker:
    """Persistent deadline-guarded executor: ONE worker thread reused
    across every guarded call of a run, joined on completion.

    This fixes the documented watchdog thread leak: the old
    run_with_deadline spawned a fresh daemon thread per chunk, so a
    watchdog-armed N-chunk run churned N threads and a completed run
    still had its last worker unaccounted for.  Here the same thread
    serves every chunk and ``close()`` joins it when the run finishes —
    thread count is stable across an arbitrarily long supervised run
    (pinned by a tier-1 regression test).

    The one unfixable case remains unfixable: Python cannot cancel a
    call that truly hangs inside a device tunnel (r3/r4 lesson).  A
    deadline miss marks the worker ``hung``; it is abandoned (daemonic,
    never reused — a late result cannot be mistaken for a fresh one
    because the whole worker, result queue included, is discarded) and
    the caller creates a replacement.  Actually killing the hang stays a
    process-level supervisor's job (scripts/tpu_campaign.py).
    """

    # single-writer by construction: only the owning caller thread ever
    # touches the worker handle or the hung latch (the worker thread
    # itself writes neither), so neither needs a lock (SL1305)
    UNGUARDED_OK = ("_thread", "hung")

    def __init__(self, name: str = "witt-watchdog"):
        self._name = name
        self._requests: "queue.Queue" = queue.Queue()
        self._results: "queue.Queue" = queue.Queue()
        self._thread: threading.Thread | None = None
        self.hung = False

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name=self._name
            )
            self._thread.start()

    def _loop(self) -> None:
        while True:
            fn = self._requests.get()
            if fn is None:
                return
            try:
                self._results.put(("ok", fn()))
            except BaseException as e:  # noqa: BLE001 — forwarded to caller
                self._results.put(("err", e))

    def call(self, fn: Callable[[], Any], deadline_s: float, phase: str):
        """Run fn() on the worker with a deadline; raise
        WatchdogTimeoutError(phase) on a miss (and mark the worker hung
        — callers must discard it and build a fresh one)."""
        if self.hung:
            raise RuntimeError(
                f"WatchdogWorker {self._name!r} is hung; build a new one"
            )
        self._ensure_thread()
        self._requests.put(fn)
        try:
            status, payload = self._results.get(timeout=deadline_s)
        except queue.Empty:
            self.hung = True
            # pre-queue the shutdown sentinel: if the stuck call ever
            # returns, the abandoned worker exits instead of parking on
            # the request queue forever — the leak lasts exactly as long
            # as the hang itself
            self._requests.put(None)
            raise WatchdogTimeoutError(phase, deadline_s) from None
        if status == "err":
            raise payload
        return payload

    def close(self, timeout_s: float = 5.0) -> bool:
        """Join the worker thread (call on run completion).  Returns
        True when the thread is gone; a hung worker is abandoned
        immediately (returns False) rather than blocking the caller."""
        th = self._thread
        self._thread = None
        if th is None or not th.is_alive():
            return True
        if self.hung:
            return False
        self._requests.put(None)
        th.join(timeout_s)
        return not th.is_alive()


@dataclass(frozen=True)
class SalvagePolicy:
    """How the serve scheduler responds to a failed packed batch.

    With ``enabled`` the scheduler bisects the live rows: a failing
    subset splits in half, a passing subset's results are KEPT (padding
    to the fixed capacity means every subset re-run is the same
    compiled program, and replica rows are lane-independent under vmap
    — a surviving row's bytes equal its singleton run's).  Rows that
    fail alone are quarantined as PoisonRowError; with one poison among
    k rows identification costs ~log2(k) re-runs.  ``max_probe_runs``
    bounds the salvage work per batch — past it, still-unresolved rows
    fail with the original batch error (honest FAILED, not a guessed
    quarantine).  Disabled, a batch failure fails every live row (the
    pre-resilience blast-radius behavior)."""

    enabled: bool = True
    max_probe_runs: int = 16


@dataclass(frozen=True)
class DegradePolicy:
    """What to do when the device is lost: with cpu_fallback, the
    supervisor re-places the last anchor on CPU and continues there,
    stamping {degraded, degraded_at_chunk} into provenance so a CPU
    number can never masquerade as a TPU number."""

    cpu_fallback: bool = False

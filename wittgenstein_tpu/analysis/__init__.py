"""simlint: static contract checking for batched protocols and jit paths.

The engine's correctness rests on conventions no runtime check enforces:
`deliver` must not touch the engine-owned `msg_*` store, `tick_beat` must
make exactly `BEAT_SEND_CALLS` latency draws so beat gating never perturbs
the RNG stream, telemetry must leave sim state bit-identical, and every
kernel must be shape/dtype-stable under jit.  A violation surfaces — if at
all — as a distribution-parity failure hours into a TPU campaign.  This
package turns those conventions into machine-checked rules that fail in
seconds on CPU CI:

  * `ast_lint`       — AST rules over the whole package (tracer-unsafe
                       Python, host impurity in jit paths, dtype-drift
                       hazards, protocol-contract rules);
  * `contracts`      — abstract-eval checks over every registered batched
                       protocol (`jax.eval_shape`/`jax.make_jaxpr`):
                       SimState tree/shape/dtype/weak-type preservation,
                       msg-store ownership, telemetry neutrality, and a
                       recompilation sentry;
  * `rng_audit`      — trace-level audit counting `latency_arrivals`
                       draws in `tick_beat` against `BEAT_SEND_CALLS`;
  * `registry_check` — registry/test coverage meta-rule for
                       `protocols/*_batched.py`.

Run locally: `python -m wittgenstein_tpu.analysis --strict`
(see docs/static_analysis.md for the rule catalog and suppression syntax).
"""

from .findings import Finding, RULES, Severity  # noqa: F401

__all__ = ["Finding", "RULES", "Severity"]

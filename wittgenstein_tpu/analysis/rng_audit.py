"""Beat RNG audit (SL405).

The beat-gating optimization in `run_ms_batched` only preserves the
per-event RNG stream if BEAT_SEND_CALLS is exact: off-beat ticks advance
`send_ctr` by that declared amount instead of executing `tick_beat`, so a
protocol whose `tick_beat` actually makes a different number of
`latency_arrivals` draws silently de-synchronizes the stream — the beat
path and the generic path then simulate DIFFERENT runs, which no shape
check can see.

This auditor counts the draws at trace time: it shadows the engine's
`latency_arrivals` with a counting wrapper (an instance attribute, so
`self.latency_arrivals` calls inside `apply_emission` route through it)
and traces `tick_beat` once with `jax.make_jaxpr`.  Python-level counting
during the trace is exact — every draw site executes exactly once while
tracing, regardless of the masks applied to it.
"""

from __future__ import annotations

import copy
from typing import List

from .contracts import _cpu_jax, _mk, _proto_location


def audit_entry(entry, root: str = ".") -> List["Finding"]:
    """SL405 for one registry entry; [] when clean, exempt, or beat-free."""
    import os

    jax = _cpu_jax()
    if not entry.contract_checks:
        return []
    net, state = entry.factory()
    proto = net.protocol
    path, line = _proto_location(proto)
    try:
        path = os.path.relpath(path, root)
    except ValueError:
        pass
    suppress = set(getattr(proto, "SIMLINT_SUPPRESS", ()) or ())

    contract = proto.contract()
    period = contract["beat_period"]
    declared = contract["beat_send_calls"]
    if period is None:
        if declared:
            f = _mk("SL405", path, line,
                    f"[{entry.name}] BEAT_SEND_CALLS={declared} but "
                    "BEAT_PERIOD is unset — the declaration is dead and "
                    "will mislead a future beat-gating change", suppress)
            return [f] if f else []
        return []

    counted = {"n": 0}
    orig = net.latency_arrivals  # bound to the original net; same tables

    def counting_latency_arrivals(*args, **kwargs):
        counted["n"] += 1
        return orig(*args, **kwargs)

    net2 = copy.copy(net)
    # instance attribute shadows the class method, so internal
    # self.latency_arrivals(...) calls (apply_emission) are counted too
    net2.latency_arrivals = counting_latency_arrivals
    try:
        jax.make_jaxpr(lambda s: proto.tick_beat(net2, s))(state)
    except Exception as e:
        f = _mk("SL405", path, line,
                f"[{entry.name}] tick_beat() failed tracing for the RNG "
                f"audit: {type(e).__name__}: {e}", suppress)
        return [f] if f else []

    if counted["n"] != declared:
        f = _mk("SL405", path, line,
                f"[{entry.name}] tick_beat() makes {counted['n']} "
                f"latency_arrivals draw(s) but declares "
                f"BEAT_SEND_CALLS={declared}; off-beat ticks advance "
                "send_ctr by the declared amount, so the mismatch "
                "de-synchronizes the RNG stream between the beat-gated "
                "and generic run paths", suppress)
        return [f] if f else []
    return []


def audit_all(root: str = ".", names=None) -> List["Finding"]:
    from ..core.registries import registry_batched_protocols

    findings = []
    for entry in registry_batched_protocols.entries():
        if names and entry.name not in names:
            continue
        findings.extend(audit_entry(entry, root=root))
    return findings

"""AST lint rules over the package (SL1xx tracer/purity/dtype, SL2xx
protocol contract).

The rules only fire inside KERNEL SCOPE — code that runs under a jax
trace — so host-side construction (factories, oracle init, exports) can
keep using plain Python freely.  Kernel scope is:

  * kernel hooks of batched-protocol classes (engine.protocol.KERNEL_HOOKS)
    plus their underscore helper methods (helpers are called from hooks);
  * methods of the engine's BatchedNetwork except host-side construction
    (everything it runs is inside its own jit entry points);
  * any function/method decorated with `jax.jit` (bare or via
    functools.partial);
  * everything in `wittgenstein_tpu/ops/` (pure kernel helpers).

Protocol classes are recognized by a base-name fixpoint seeded with
{BatchedProtocol, BitsetAggBase}, so `class X(BatchedHandel)` in the same
file is covered too.  The field lists the contract rules check against come
from engine.protocol's machine-readable metadata, not from copies here.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..engine.protocol import ENGINE_OWNED_FIELDS, HOST_HOOKS, KERNEL_HOOKS
from .findings import Finding, Severity, apply_suppressions

# base-class names that mark a batched-protocol class (extended per file by
# fixpoint over local inheritance)
PROTOCOL_BASE_SEEDS = {"BatchedProtocol", "BitsetAggBase"}

# protocol methods that are host-side even though they live on the class
HOST_METHODS = set(HOST_HOOKS) | {"__init__", "contract"}

# BatchedNetwork methods that are host-side construction/dispatch
ENGINE_HOST_METHODS = {
    "__init__",
    "init_state",
    "cache_key",
    "with_telemetry",
    "with_faults",
    "run_ms",
    "run_ms_batched",
    "_window",
}

# SimState fields whose attribute access marks an expression as
# tracer-valued inside kernel code (import would drag jax in; the engine's
# contract metadata covers the owned subset, node columns complete it)
_SIMSTATE_FIELDS = set(ENGINE_OWNED_FIELDS) | {
    "down",
    "done_at",
    "msg_received",
    "msg_sent",
    "bytes_received",
    "bytes_sent",
    "extra_latency",
    "city_idx",
    "partition_x",
    "proto",
}
# too generic to key a traced-ref on their own (state.x/state.y exist, but
# `b.x` on host objects is everywhere)
_SIMSTATE_FIELDS -= {"x", "y"}

_TRACED_NAMES = {"state", "vstate", "pstate", "states", "deliver_mask"}

_IMPURE_CALLS = {
    ("time", "time"),
    ("time", "perf_counter"),
    ("time", "monotonic"),
    ("time", "sleep"),
}

_DTYPELESS_CTORS = {"zeros", "ones", "arange", "empty"}
# ctor -> positional index where dtype may appear
_CTOR_DTYPE_POS = {"zeros": 1, "ones": 1, "empty": 1, "full": 2, "array": 1,
                   "asarray": 1, "arange": 3}


def _dotted(node: ast.AST) -> Optional[str]:
    """`a.b.c` -> "a.b.c" (None for non-trivial expressions)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _has_traced_ref(node: ast.AST) -> bool:
    """Does the expression reference a (likely) traced value: a SimState
    field access, a known traced name, a `proto[...]` subscript, or a
    jnp/lax call?"""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in _SIMSTATE_FIELDS:
            # self.MSG_TYPES-style class config is not traced
            if not (
                isinstance(sub.value, ast.Name) and sub.value.id == "self"
            ):
                return True
        if isinstance(sub, ast.Name) and sub.id in _TRACED_NAMES:
            return True
        if isinstance(sub, ast.Subscript):
            base = sub.value
            if isinstance(base, ast.Name) and base.id == "proto":
                return True
        if isinstance(sub, ast.Call):
            name = _dotted(sub.func) or ""
            root = name.split(".")[0]
            if root in ("jnp", "lax"):
                return True
    return False


def _is_dtype_expr(node: ast.AST) -> bool:
    """Positional arg that plausibly IS a dtype (jnp.int32, np.uint8, bool)."""
    name = _dotted(node)
    if name is None:
        return isinstance(node, ast.Constant) and isinstance(node.value, str)
    root = name.split(".")[0]
    if root in ("jnp", "np", "numpy", "jax"):
        return "." in name  # jnp.int32, np.float32, ...
    return name in ("bool", "int", "float", "complex")


def _numeric_literal(node: ast.AST) -> bool:
    if isinstance(node, ast.UnaryOp) and isinstance(
        node.op, (ast.USub, ast.UAdd)
    ):
        node = node.operand
    return isinstance(node, ast.Constant) and isinstance(
        node.value, (int, float)
    ) and not isinstance(node.value, bool)


def _has_jit_decorator(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = _dotted(target) or ""
        if name.endswith("jax.jit") or name == "jit":
            return True
        if isinstance(dec, ast.Call) and (
            (_dotted(dec.func) or "").endswith("partial")
        ):
            for a in dec.args:
                if (_dotted(a) or "").endswith("jax.jit"):
                    return True
    return False


class _ClassInfo:
    def __init__(self, node: ast.ClassDef, is_protocol: bool):
        self.node = node
        self.is_protocol = is_protocol
        self.msg_types: Optional[List[str]] = None  # literal list, if any
        self.payload_width: Optional[int] = None  # literal int, if any
        self.defines_payload_width = False
        self.direct_protocol_base = any(
            isinstance(b, ast.Name) and b.id == "BatchedProtocol"
            for b in node.bases
        )
        for stmt in node.body:
            tgt = None
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                tgt = stmt.targets[0]
                val = stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                tgt = stmt.target
                val = stmt.value
            else:
                continue
            if not isinstance(tgt, ast.Name):
                continue
            if tgt.id == "MSG_TYPES" and isinstance(val, (ast.List, ast.Tuple)):
                elems = []
                ok = True
                for e in val.elts:
                    if isinstance(e, ast.Constant) and isinstance(e.value, str):
                        elems.append(e.value)
                    else:
                        ok = False
                if ok:
                    self.msg_types = elems
            if tgt.id == "PAYLOAD_WIDTH":
                self.defines_payload_width = True
                if isinstance(val, ast.Constant) and isinstance(val.value, int):
                    self.payload_width = val.value
        # dynamic width: `self.PAYLOAD_WIDTH = ...` anywhere in the class
        # (instance-level, value unknowable statically — disables the
        # width-dependent checks rather than guessing)
        for sub in ast.walk(node):
            if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                tgts = sub.targets if isinstance(sub, ast.Assign) else [sub.target]
                for t in tgts:
                    if (
                        isinstance(t, ast.Attribute)
                        and t.attr == "PAYLOAD_WIDTH"
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        self.defines_payload_width = True
                        self.payload_width = None


def _protocol_classes(tree: ast.Module) -> Dict[str, _ClassInfo]:
    """Name -> info for every class, with protocol-ness by base fixpoint."""
    classes = {
        n.name: n for n in tree.body if isinstance(n, ast.ClassDef)
    }
    protocol: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for name, node in classes.items():
            if name in protocol:
                continue
            for b in node.bases:
                bname = b.id if isinstance(b, ast.Name) else (_dotted(b) or "")
                bname = bname.split(".")[-1]
                if bname in PROTOCOL_BASE_SEEDS or bname in protocol:
                    protocol.add(name)
                    changed = True
                    break
    return {
        name: _ClassInfo(node, name in protocol)
        for name, node in classes.items()
    }


def _module_declares_beat(tree: ast.Module) -> bool:
    """Any binding of BEAT_PERIOD or BEAT_SEND_CALLS in the module: a class
    attribute, or a `proto.BEAT_PERIOD = ...` factory assignment."""
    for node in ast.walk(tree):
        targets: Iterable[ast.AST] = ()
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = (node.target,)
        for t in targets:
            name = t.id if isinstance(t, ast.Name) else getattr(t, "attr", "")
            if name in ("BEAT_PERIOD", "BEAT_SEND_CALLS"):
                return True
    return False


def _is_trivial_body(fn: ast.FunctionDef) -> bool:
    """Docstring + bare `return state`-style body (the base-class no-op)."""
    body = [
        s
        for s in fn.body
        if not (
            isinstance(s, ast.Expr)
            and isinstance(s.value, ast.Constant)
            and isinstance(s.value.value, str)
        )
    ]
    if len(body) != 1:
        return False
    s = body[0]
    return isinstance(s, (ast.Return, ast.Pass))


class _KernelRuleVisitor(ast.NodeVisitor):
    """Applies the SL1xx/SL2xx body rules inside ONE kernel function."""

    def __init__(
        self,
        path: str,
        findings: List[Finding],
        cls: Optional[_ClassInfo],
        fn_name: str,
    ):
        self.path = path
        self.findings = findings
        self.cls = cls
        self.fn_name = fn_name

    def _add(self, rule: str, node: ast.AST, msg: str):
        self.findings.append(
            Finding(rule, self.path, getattr(node, "lineno", 1), msg)
        )

    # -- SL101: tracer-unsafe control flow -----------------------------------
    def _check_test(self, node, test):
        if _has_traced_ref(test):
            self._add(
                "SL101",
                node,
                f"`{type(node).__name__.lower()}` on a traced expression in "
                f"kernel `{self.fn_name}` — use jnp.where/lax.cond/masks",
            )

    def visit_If(self, node: ast.If):
        self._check_test(node, node.test)
        self.generic_visit(node)

    def visit_While(self, node: ast.While):
        self._check_test(node, node.test)
        self.generic_visit(node)

    def visit_IfExp(self, node: ast.IfExp):
        self._check_test(node, node.test)
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert):
        self._check_test(node, node.test)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        name = _dotted(node.func) or ""
        parts = tuple(name.split("."))
        attr = parts[-1]

        # -- SL102: host impurity -------------------------------------------
        if (
            parts[:2] in (("np", "random"), ("numpy", "random"))
            or parts[0] == "random"
            and len(parts) > 1
            or parts in _IMPURE_CALLS
            or name in ("print", "input", "breakpoint")
        ):
            self._add(
                "SL102",
                node,
                f"host-impure call `{name}` inside kernel `{self.fn_name}` "
                "(traced code must be pure; use jax.debug.print / the "
                "counter RNG)",
            )

        # -- SL103: host conversions of traced values ------------------------
        if name in ("float", "int", "bool") and node.args and _has_traced_ref(
            node.args[0]
        ):
            self._add(
                "SL103",
                node,
                f"`{name}()` on a traced value in kernel `{self.fn_name}` "
                "forces a host sync / fails under jit",
            )
        if attr == "item" and not node.args and isinstance(
            node.func, ast.Attribute
        ):
            self._add(
                "SL103",
                node,
                f"`.item()` in kernel `{self.fn_name}` forces a host sync "
                "/ fails under jit",
            )
        if parts[0] in ("np", "numpy") and len(parts) > 1 and any(
            _has_traced_ref(a) for a in list(node.args)
        ):
            self._add(
                "SL103",
                node,
                f"`{name}` applied to a traced value in kernel "
                f"`{self.fn_name}` — use the jnp equivalent",
            )

        # -- SL104: dtype-drift hazards --------------------------------------
        if parts[0] == "jnp" and len(parts) == 2:
            ctor = parts[1]
            kw_dtype = any(k.arg == "dtype" for k in node.keywords)
            pos = _CTOR_DTYPE_POS.get(ctor)
            pos_dtype = (
                pos is not None
                and len(node.args) > pos
                and _is_dtype_expr(node.args[pos])
            ) or any(_is_dtype_expr(a) for a in node.args[1:])
            if ctor in _DTYPELESS_CTORS and not kw_dtype and not pos_dtype:
                self._add(
                    "SL104",
                    node,
                    f"`jnp.{ctor}` without an explicit dtype in kernel "
                    f"`{self.fn_name}` (defaults drift: zeros/ones give "
                    "float, arange widths depend on inputs)",
                )
            if (
                ctor in ("array", "asarray", "full")
                and not kw_dtype
                and not pos_dtype
            ):
                lit_arg = node.args[1] if ctor == "full" and len(
                    node.args
                ) > 1 else (node.args[0] if node.args else None)
                if lit_arg is not None and _numeric_literal(lit_arg):
                    self._add(
                        "SL104",
                        node,
                        f"weak-typed numeric literal via `jnp.{ctor}` in "
                        f"kernel `{self.fn_name}` — pin the dtype "
                        "(weak-type promotion recompiles / drifts dtypes)",
                    )

        # -- SL201: deliver writing engine-owned columns ---------------------
        if (
            attr == "_replace"
            and self.cls is not None
            and self.cls.is_protocol
            and self.fn_name == "deliver"
        ):
            owned = set(ENGINE_OWNED_FIELDS)
            for k in node.keywords:
                if k.arg in owned:
                    self._add(
                        "SL201",
                        node,
                        f"deliver() writes engine-owned field `{k.arg}` "
                        "(return emissions instead; the engine owns the "
                        "message store)",
                    )

        # -- SL203: mtype name not in MSG_TYPES ------------------------------
        if (
            attr == "mtype"
            and self.cls is not None
            and self.cls.msg_types is not None
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
            and node.args[0].value not in self.cls.msg_types
        ):
            self._add(
                "SL203",
                node,
                f"mtype({node.args[0].value!r}) not in MSG_TYPES "
                f"{self.cls.msg_types}",
            )

        # -- SL204: payload against PAYLOAD_WIDTH ----------------------------
        if (
            isinstance(node.func, ast.Name)
            and node.func.id == "Emission"
            and self.cls is not None
            and self.cls.is_protocol
        ):
            width = self.cls.payload_width
            if width is None and not self.cls.defines_payload_width and (
                self.cls.direct_protocol_base
            ):
                width = 0  # inherited default
            if width == 0:
                for k in node.keywords:
                    if k.arg == "payload" and not (
                        isinstance(k.value, ast.Constant)
                        and k.value.value is None
                    ):
                        self._add(
                            "SL204",
                            node,
                            "Emission(payload=...) but PAYLOAD_WIDTH is 0 "
                            "— the engine drops the payload silently",
                        )
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript):
        # SL204: constant msg_payload index past PAYLOAD_WIDTH
        if (
            isinstance(node.value, ast.Attribute)
            and node.value.attr == "msg_payload"
            and self.cls is not None
            and self.cls.payload_width is not None
        ):
            width = self.cls.payload_width
            idx = node.slice
            elems = idx.elts if isinstance(idx, ast.Tuple) else [idx]
            last = elems[-1]
            if (
                isinstance(last, ast.Constant)
                and isinstance(last.value, int)
                and not isinstance(last.value, bool)
                and last.value >= width
                and len(elems) > 1  # [..., k] / [:, k] style column access
            ):
                self._add(
                    "SL204",
                    node,
                    f"msg_payload column {last.value} >= PAYLOAD_WIDTH "
                    f"{width}",
                )
        self.generic_visit(node)


def _kernel_functions(
    path: str, tree: ast.Module, classes: Dict[str, _ClassInfo]
):
    """Yield (fn_node, class_info_or_None, fn_name) for kernel scope."""
    rel = path.replace(os.sep, "/")
    in_engine = rel.endswith("engine/core.py")
    in_ops = "/ops/" in rel

    for cname, info in classes.items():
        is_engine_cls = in_engine and cname == "BatchedNetwork"
        for item in info.node.body:
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            name = item.name
            if info.is_protocol:
                if name in HOST_METHODS:
                    continue
                if name in KERNEL_HOOKS or name.startswith("_"):
                    yield item, info, name
            elif is_engine_cls:
                if name not in ENGINE_HOST_METHODS:
                    yield item, info, name
            elif _has_jit_decorator(item):
                yield item, info, name

    for item in tree.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if in_ops or _has_jit_decorator(item):
                yield item, None, item.name
            else:
                # module-level host function: still scan for NESTED
                # jit-decorated functions (chunked-run helpers)
                for sub in ast.walk(item):
                    if sub is not item and isinstance(
                        sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ) and _has_jit_decorator(sub):
                        yield sub, None, sub.name


def lint_source(source: str, path: str = "<string>") -> List[Finding]:
    """Lint one file's source; returns suppression-filtered findings."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [
            Finding(
                "SL101",
                path,
                e.lineno or 1,
                f"syntax error prevents linting: {e.msg}",
                Severity.ERROR,
            )
        ]
    classes = _protocol_classes(tree)
    findings: List[Finding] = []

    for fn, cls, name in _kernel_functions(path, tree, classes):
        v = _KernelRuleVisitor(path, findings, cls, name)
        for stmt in fn.body:
            v.visit(stmt)

    # SL202: tick_beat override without beat metadata in the module
    for cname, info in classes.items():
        if not info.is_protocol:
            continue
        for item in info.node.body:
            if (
                isinstance(item, ast.FunctionDef)
                and item.name == "tick_beat"
                and not _is_trivial_body(item)
                and not _module_declares_beat(tree)
            ):
                findings.append(
                    Finding(
                        "SL202",
                        path,
                        item.lineno,
                        f"{cname}.tick_beat overridden but the module never "
                        "binds BEAT_PERIOD/BEAT_SEND_CALLS — beat gating "
                        "would desynchronize the RNG stream",
                    )
                )

    return apply_suppressions(findings, source)


def lint_file(path: str) -> List[Finding]:
    with open(path, "r") as f:
        return lint_source(f.read(), path)


def iter_package_files(root: str) -> List[str]:
    """Python files of the package tree (skips caches and data)."""
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [
            d for d in dirnames if d not in ("__pycache__", "data")
        ]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                out.append(os.path.join(dirpath, fn))
    return out


def lint_package(root: str) -> List[Finding]:
    findings: List[Finding] = []
    for path in iter_package_files(root):
        findings.extend(lint_file(path))
    return findings

"""simlint pass 10: the concurrency contract checker (SL1301-SL1307).

A pure-AST audit of the HOST-side tree (serve/, runtime/, obs/,
server/, parallel/, telemetry/) against the lock registry declared in
``runtime/locks.py`` — the concurrency dual of the kernel-side passes:
the fleet's locks, threads, and shared attributes are contracts, and
contracts get checkers.

Rules:

* **SL1301** — undeclared lock.  Every ``threading.Lock/RLock/
  Condition`` construction must anchor to a registry site
  (``relpath::Class.attr`` / ``relpath::GLOBAL.name``), and every
  ``make_lock``/``TracedLock`` name must be registered.
* **SL1302** — lock-order inversion (the deadlock-order audit).  With a
  TOTAL order over named locks, deadlock needs a descending edge
  somewhere; this rule finds acquisition chains — direct or across
  function boundaries via call-graph inference — that take a lock at or
  below the rank of one already held.  The inference is a deliberate
  under-approximation (only unambiguously resolvable calls contribute),
  so every report is a real descending edge.
* **SL1303** — blocking work under a dispatch-class lock
  (``no_blocking`` in the registry): ``.lower(...).compile()``,
  ``block_until_ready``, file I/O, HTTP, ``time.sleep``, timeout-less
  ``get()/wait()/join()``.  The PR-11 race's dual: that fix moved
  compiles OUTSIDE ``_dispatch_lock``; this rule keeps them out.
* **SL1304** — thread lifecycle (the PR-12 leak class).  Every spawned
  ``threading.Thread`` must be daemonized or joined, and a resolvable
  worker loop must have a shutdown path: a loop exit (``return``/
  ``break``) or a stop-event whose ``.set()`` some method calls.
* **SL1305** — unguarded shared write.  In classes that spawn threads
  or own registered locks, every attribute written outside ``__init__``
  must be written under the SAME named lock at every write site —
  lexically, via an all-call-sites-hold-the-lock caller contract, or
  via a ``@route``-style locked-dispatch decorator — unless listed in
  the class's ``UNGUARDED_OK`` tuple (documented single-writer fields)
  or line-suppressed.
* **SL1306** — stale registry: a declared site matching no live
  construction.
* **SL1307** — yield-point drift: ``yield_point()`` call sites and the
  ``YIELD_POINTS`` catalog must agree in both directions.

``check_concurrency(root)`` audits a real tree; ``check_files`` takes a
``{relpath: source}`` dict plus an explicit registry so tests can prove
each rule live on crafted bad fixtures.
"""

from __future__ import annotations

import ast
import dataclasses
import importlib.util
import os
import sys
from typing import Dict, List, Optional, Sequence, Tuple

from .findings import Finding, apply_suppressions

#: host-side packages pass 10 audits (kernel code has its own passes)
HOST_DIRS = ("serve", "runtime", "obs", "server", "parallel", "telemetry")
#: the registry itself is the declaration channel, not a subject
EXEMPT = ("runtime/locks.py",)

_LOCK_CTORS = ("Lock", "RLock")
_TRACED_CTORS = ("make_lock", "TracedLock")
#: attribute calls that block by nature (``.lower`` only with args —
#: ``str.lower()`` takes none, ``jit.lower(states)`` does not)
_BLOCKING_ATTRS = ("compile", "block_until_ready", "urlopen")
#: zero-arg forms of these block without a timeout
_TIMEOUTLESS_ATTRS = ("get", "wait", "join")


@dataclasses.dataclass(frozen=True)
class LockRegistry:
    """What the checker needs from runtime/locks.py."""

    ranks: Dict[str, int]
    sites: Dict[str, str]  # site string -> lock name
    no_blocking: frozenset
    yield_points: Tuple[str, ...]

    @classmethod
    def empty(cls) -> "LockRegistry":
        return cls({}, {}, frozenset(), ())


def load_registry(locks_path: str) -> LockRegistry:
    """Load the registry by executing runtime/locks.py STANDALONE
    (stdlib-only by contract) — no package import, so the fast simlint
    passes stay jax-free."""
    if not os.path.isfile(locks_path):
        return LockRegistry.empty()
    spec = importlib.util.spec_from_file_location(
        "_witt_locks_registry", locks_path
    )
    mod = importlib.util.module_from_spec(spec)
    # dataclasses resolves field types through sys.modules[__module__],
    # so the standalone module must be registered while it executes
    sys.modules[spec.name] = mod
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.modules.pop(spec.name, None)
    ranks, sites, no_blocking = {}, {}, set()
    for rank, row in enumerate(mod.LOCK_HIERARCHY):
        ranks[row.name] = rank
        for site in row.sites:
            sites[site] = row.name
        if row.no_blocking:
            no_blocking.add(row.name)
    return LockRegistry(
        ranks, sites, frozenset(no_blocking),
        tuple(getattr(mod, "YIELD_POINTS", ())),
    )


# -- per-file model -----------------------------------------------------------
@dataclasses.dataclass
class FuncInfo:
    path: str
    class_name: Optional[str]
    name: str
    node: ast.AST
    decorators: List[ast.expr]
    # (lock name, line, held lock names at acquisition)
    acquires: List[Tuple[str, int, Tuple[str, ...]]] = dataclasses.field(
        default_factory=list
    )
    # (call ref, line, held lock names at call)
    calls: List[Tuple[tuple, int, Tuple[str, ...]]] = dataclasses.field(
        default_factory=list
    )
    # (description, line, held lock names)
    blocking: List[Tuple[str, int, Tuple[str, ...]]] = dataclasses.field(
        default_factory=list
    )
    # (attr, line, held lock names at write)
    writes: List[Tuple[str, int, Tuple[str, ...]]] = dataclasses.field(
        default_factory=list
    )

    @property
    def qualname(self) -> str:
        return (
            f"{self.class_name}.{self.name}" if self.class_name else self.name
        )


@dataclasses.dataclass
class ClassInfo:
    path: str
    name: str
    node: ast.ClassDef
    methods: Dict[str, FuncInfo] = dataclasses.field(default_factory=dict)
    #: attr -> registered lock name (self.x = make_lock(...)/threading.Lock()
    #: whose site is declared)
    attr_locks: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: attr -> aliased lock attr (self._work = threading.Condition(self._lock))
    cond_aliases: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: attr -> class name (from __init__ constructor calls / annotated
    #: factory returns) for call-graph resolution
    attr_types: Dict[str, str] = dataclasses.field(default_factory=dict)
    unguarded_ok: Tuple[str, ...] = ()
    spawns_thread: bool = False


@dataclasses.dataclass
class FileInfo:
    path: str
    source: str
    tree: ast.Module
    classes: Dict[str, ClassInfo] = dataclasses.field(default_factory=dict)
    functions: Dict[str, FuncInfo] = dataclasses.field(default_factory=dict)
    #: module-global var -> registered lock name
    global_locks: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: every construction's site string (for SL1306 liveness)
    constructed_sites: List[str] = dataclasses.field(default_factory=list)
    #: (line, message) undeclared-lock findings
    sl1301: List[Tuple[int, str]] = dataclasses.field(default_factory=list)
    #: threading.Thread spawn records
    spawns: List[dict] = dataclasses.field(default_factory=list)
    #: terminal names seen in ``<...>.join(...)`` calls
    join_targets: set = dataclasses.field(default_factory=set)
    #: (name literal or None, line) of yield_point() calls
    yield_calls: List[Tuple[Optional[str], int]] = dataclasses.field(
        default_factory=list
    )


def _is_threading_ctor(call: ast.Call, names: Sequence[str]) -> Optional[str]:
    f = call.func
    if (
        isinstance(f, ast.Attribute)
        and isinstance(f.value, ast.Name)
        and f.value.id == "threading"
        and f.attr in names
    ):
        return f.attr
    return None


def _is_traced_ctor(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Name) and f.id in _TRACED_CTORS:
        return True
    return isinstance(f, ast.Attribute) and f.attr in _TRACED_CTORS


def _str_const(node: ast.expr) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _ctor_class_name(value: ast.expr) -> Optional[str]:
    """The class a constructor-ish RHS produces: ``C(...)``, ``x or
    C(...)``, or a call to an annotated factory (resolved later)."""
    if isinstance(value, ast.BoolOp):
        for v in value.values:
            got = _ctor_class_name(v)
            if got:
                return got
        return None
    if isinstance(value, ast.Call) and isinstance(value.func, ast.Name):
        name = value.func.id
        if name and name[0].isupper():
            return name
    return None


def _factory_call_name(value: ast.expr) -> Optional[str]:
    """``self.x = get_recorder()`` -> "get_recorder" (type filled from
    the factory's return annotation in the link phase)."""
    if isinstance(value, ast.BoolOp):
        for v in value.values:
            got = _factory_call_name(v)
            if got:
                return got
        return None
    if isinstance(value, ast.Call) and isinstance(value.func, ast.Name):
        name = value.func.id
        if name and not name[0].isupper():
            return name
    return None


class _Analyzer:
    """One pass over one file tree, building the FileInfo model."""

    def __init__(self, path: str, source: str, registry: LockRegistry):
        self.reg = registry
        self.fi = FileInfo(path, source, ast.parse(source))

    # -- lock-expression resolution ------------------------------------------
    def _resolve_lock(
        self, expr: ast.expr, cls: Optional[ClassInfo], depth: int = 0
    ) -> Optional[str]:
        if depth > 4:
            return None
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and cls is not None
        ):
            attr = expr.attr
            alias = cls.cond_aliases.get(attr)
            if alias is not None:
                fake = ast.Attribute(
                    value=ast.Name(id="self", ctx=ast.Load()),
                    attr=alias, ctx=ast.Load(),
                )
                return self._resolve_lock(fake, cls, depth + 1)
            site = f"{self.fi.path}::{cls.name}.{attr}"
            if site in self.reg.sites:
                return self.reg.sites[site]
            return cls.attr_locks.get(attr)
        if isinstance(expr, ast.Name):
            site = f"{self.fi.path}::GLOBAL.{expr.id}"
            if site in self.reg.sites:
                return self.reg.sites[site]
            return self.fi.global_locks.get(expr.id)
        return None

    # -- construction inventory (SL1301 / SL1306 / aliases / types) ----------
    def collect(self) -> FileInfo:
        for node in self.fi.tree.body:
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self._register_ctor(
                            node.value, f"GLOBAL.{tgt.id}", None, tgt.id,
                            node.lineno,
                        )
            elif isinstance(node, ast.ClassDef):
                self._collect_class(node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.fi.functions[node.name] = FuncInfo(
                    self.fi.path, None, node.name, node,
                    list(node.decorator_list),
                )
        # anonymous / nested lock constructions + joins + spawns + yields
        self._sweep_calls()
        # behavioral scan (needs aliases/locks from above)
        for func in self.fi.functions.values():
            self._scan_func(func, None)
        for cls in self.fi.classes.values():
            for meth in cls.methods.values():
                self._scan_func(meth, cls)
        return self.fi

    def _collect_class(self, node: ast.ClassDef) -> None:
        cls = ClassInfo(self.fi.path, node.name, node)
        self.fi.classes[node.name] = cls
        for item in node.body:
            if isinstance(item, ast.Assign):
                for tgt in item.targets:
                    if isinstance(tgt, ast.Name) and tgt.id == "UNGUARDED_OK":
                        vals = (
                            item.value.elts
                            if isinstance(item.value, (ast.Tuple, ast.List))
                            else []
                        )
                        cls.unguarded_ok = tuple(
                            v for v in (_str_const(e) for e in vals) if v
                        )
            elif isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                cls.methods[item.name] = FuncInfo(
                    self.fi.path, node.name, item.name, item,
                    list(item.decorator_list),
                )
        # attribute inventory from every method (locks usually live in
        # __init__, but lazily-created ones count too)
        for meth in cls.methods.values():
            for sub in ast.walk(meth.node):
                if not isinstance(sub, ast.Assign):
                    continue
                for tgt in sub.targets:
                    if (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    ):
                        self._note_self_assign(cls, tgt.attr, sub)

    def _note_self_assign(
        self, cls: ClassInfo, attr: str, assign: ast.Assign
    ) -> None:
        value = assign.value
        if isinstance(value, ast.Call):
            kind = _is_threading_ctor(value, ("Condition",))
            if kind:
                arg = value.args[0] if value.args else None
                if (
                    isinstance(arg, ast.Attribute)
                    and isinstance(arg.value, ast.Name)
                    and arg.value.id == "self"
                ):
                    cls.cond_aliases[attr] = arg.attr
                    return
                # a bare Condition owns a fresh lock: registry rules apply
                self._register_ctor(
                    value, f"{cls.name}.{attr}", cls, None, assign.lineno
                )
                return
            if _is_threading_ctor(value, _LOCK_CTORS) or _is_traced_ctor(
                value
            ):
                self._register_ctor(
                    value, f"{cls.name}.{attr}", cls, None, assign.lineno
                )
                return
        ctor = _ctor_class_name(value)
        if ctor:
            cls.attr_types.setdefault(attr, ctor)
        else:
            factory = _factory_call_name(value)
            if factory:
                # resolved to a class via return annotation in link phase
                cls.attr_types.setdefault(attr, f"()->{factory}")

    def _register_ctor(
        self,
        call: ast.Call,
        local_site: str,
        cls: Optional[ClassInfo],
        global_name: Optional[str],
        line: int,
    ) -> None:
        """One lock construction: match it to the registry (SL1301) and
        record the site as live (SL1306)."""
        site = f"{self.fi.path}::{local_site}"
        if _is_traced_ctor(call):
            name = _str_const(call.args[0]) if call.args else None
            if name is None:
                self.fi.sl1301.append(
                    (line, "traced-lock name must be a string literal")
                )
                return
            if name not in self.reg.ranks:
                self.fi.sl1301.append(
                    (line, f"lock name {name!r} is not in LOCK_HIERARCHY")
                )
                return
            self.fi.constructed_sites.append(site)
            declared = self.reg.sites.get(site)
            if declared is not None and declared != name:
                self.fi.sl1301.append(
                    (
                        line,
                        f"site {site} constructs {name!r} but the registry "
                        f"declares it as {declared!r}",
                    )
                )
            self._bind(cls, global_name, local_site, name)
            return
        if _is_threading_ctor(call, _LOCK_CTORS + ("Condition",)):
            self.fi.constructed_sites.append(site)
            name = self.reg.sites.get(site)
            if name is None:
                self.fi.sl1301.append(
                    (
                        line,
                        f"undeclared lock at {site}: add a LOCK_HIERARCHY "
                        "row in runtime/locks.py (or migrate to make_lock)",
                    )
                )
                return
            self._bind(cls, global_name, local_site, name)

    def _bind(
        self,
        cls: Optional[ClassInfo],
        global_name: Optional[str],
        local_site: str,
        lock_name: str,
    ) -> None:
        if cls is not None:
            cls.attr_locks[local_site.split(".", 1)[1]] = lock_name
        elif global_name is not None:
            self.fi.global_locks[global_name] = lock_name

    def _sweep_calls(self) -> None:
        """File-wide sweep with parent links: thread spawns (and their
        assignment targets), join evidence, yield_point sites, and lock
        constructions that never land in a trackable slot."""
        parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.fi.tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
        tracked: set = set()
        for node in ast.walk(self.fi.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if _is_threading_ctor(node, ("Thread",)):
                self.fi.spawns.append(self._spawn_record(node, parents))
            elif isinstance(f, ast.Attribute) and f.attr == "join":
                base = f.value
                if isinstance(base, ast.Name):
                    self.fi.join_targets.add(base.id)
                elif isinstance(base, ast.Attribute):
                    self.fi.join_targets.add(base.attr)
            elif isinstance(f, ast.Name) and f.id == "yield_point" or (
                isinstance(f, ast.Attribute) and f.attr == "yield_point"
            ):
                arg = _str_const(node.args[0]) if node.args else None
                self.fi.yield_calls.append((arg, node.lineno))
            elif (
                _is_threading_ctor(node, _LOCK_CTORS) or _is_traced_ctor(node)
            ):
                parent = parents.get(node)
                while isinstance(parent, ast.BoolOp):
                    parent = parents.get(parent)
                if isinstance(parent, ast.Assign):
                    tgt = parent.targets[0] if parent.targets else None
                    trackable = isinstance(tgt, ast.Name) or (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    )
                    if trackable and node not in tracked:
                        continue  # handled by collect()/_collect_class()
                self.fi.sl1301.append(
                    (
                        node.lineno,
                        "lock constructed outside a trackable slot "
                        "(module global or self attribute) — the registry "
                        "cannot anchor it",
                    )
                )

    def _spawn_record(self, call: ast.Call, parents: dict) -> dict:
        rec = {
            "line": call.lineno,
            "daemon": False,
            "target": None,
            "assigned": None,
        }
        for kw in call.keywords:
            if kw.arg == "daemon":
                rec["daemon"] = (
                    isinstance(kw.value, ast.Constant)
                    and kw.value.value is True
                )
            elif kw.arg == "target":
                rec["target"] = kw.value
        parent = parents.get(call)
        if isinstance(parent, ast.Assign) and parent.targets:
            tgt = parent.targets[0]
            if isinstance(tgt, ast.Name):
                rec["assigned"] = tgt.id
            elif isinstance(tgt, ast.Attribute):
                rec["assigned"] = tgt.attr
        # the enclosing class (for loop/shutdown resolution)
        node = call
        while node in parents:
            node = parents[node]
            if isinstance(node, ast.ClassDef):
                rec["class"] = node.name
                break
        return rec

    # -- behavioral scan (held-stack walk) -----------------------------------
    def _scan_func(self, fi: FuncInfo, cls: Optional[ClassInfo]) -> None:
        held: List[str] = []
        body = getattr(fi.node, "body", [])
        self._scan_body(body, held, fi, cls)

    def _scan_body(self, stmts, held, fi, cls) -> None:
        for st in stmts:
            self._scan_stmt(st, held, fi, cls)

    def _scan_stmt(self, st, held, fi, cls) -> None:
        if isinstance(st, ast.With):
            pushed = 0
            for item in st.items:
                self._scan_expr(item.context_expr, held, fi, cls)
                name = self._resolve_lock(item.context_expr, cls)
                if name is not None:
                    fi.acquires.append((name, st.lineno, tuple(held)))
                    held.append(name)
                    pushed += 1
            self._scan_body(st.body, held, fi, cls)
            for _ in range(pushed):
                held.pop()
        elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            pass  # closures run later, under whatever locks THEY see
        elif isinstance(st, ast.ClassDef):
            pass
        elif isinstance(st, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                st.targets if isinstance(st, ast.Assign) else [st.target]
            )
            for tgt in targets:
                self._note_write_target(tgt, st.lineno, held, fi)
                self._scan_expr(tgt, held, fi, cls)
            if st.value is not None:
                self._scan_expr(st.value, held, fi, cls)
        else:
            for field_name, value in ast.iter_fields(st):
                if isinstance(value, list):
                    if value and isinstance(value[0], ast.stmt):
                        self._scan_body(value, held, fi, cls)
                    else:
                        for v in value:
                            if isinstance(v, ast.expr):
                                self._scan_expr(v, held, fi, cls)
                            elif isinstance(v, (ast.excepthandler,)):
                                self._scan_body(v.body, held, fi, cls)
                            elif isinstance(v, ast.withitem):
                                self._scan_expr(
                                    v.context_expr, held, fi, cls
                                )
                elif isinstance(value, ast.expr):
                    self._scan_expr(value, held, fi, cls)
                elif isinstance(value, ast.stmt):
                    self._scan_stmt(value, held, fi, cls)

    def _note_write_target(self, tgt, line, held, fi) -> None:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._note_write_target(el, line, held, fi)
            return
        if isinstance(tgt, ast.Subscript):
            tgt = tgt.value
        if isinstance(tgt, ast.Starred):
            tgt = tgt.value
        if (
            isinstance(tgt, ast.Attribute)
            and isinstance(tgt.value, ast.Name)
            and tgt.value.id == "self"
        ):
            fi.writes.append((tgt.attr, line, tuple(held)))

    def _scan_expr(self, expr, held, fi, cls) -> None:
        if expr is None:
            return
        for node in ast.walk(expr):
            if isinstance(node, (ast.Lambda,)):
                continue
            if not isinstance(node, ast.Call):
                continue
            self._note_call(node, held, fi, cls)

    def _note_call(self, call: ast.Call, held, fi: FuncInfo, cls) -> None:
        f = call.func
        snapshot = tuple(held)
        # lock acquisition without `with`
        if isinstance(f, ast.Attribute) and f.attr == "acquire":
            name = self._resolve_lock(f.value, cls)
            if name is not None:
                fi.acquires.append((name, call.lineno, snapshot))
                return
        # blocking-op inventory
        if isinstance(f, ast.Name) and f.id == "open":
            fi.blocking.append(("open() file I/O", call.lineno, snapshot))
        elif isinstance(f, ast.Attribute):
            if f.attr in _BLOCKING_ATTRS and not (
                f.attr == "compile"
                and isinstance(f.value, ast.Name)
                and f.value.id == "re"
            ):
                fi.blocking.append(
                    (f".{f.attr}()", call.lineno, snapshot)
                )
            elif f.attr == "lower" and call.args:
                fi.blocking.append(
                    (".lower(...) [jit lowering]", call.lineno, snapshot)
                )
            elif f.attr == "sleep" and isinstance(f.value, ast.Name) and (
                f.value.id == "time"
            ):
                fi.blocking.append(
                    ("time.sleep()", call.lineno, snapshot)
                )
            elif (
                f.attr in _TIMEOUTLESS_ATTRS
                and not call.args
                and not call.keywords
            ):
                fi.blocking.append(
                    (f"timeout-less .{f.attr}()", call.lineno, snapshot)
                )
        # call-graph references
        ref = self._call_ref(f)
        if ref is not None:
            fi.calls.append((ref, call.lineno, snapshot))

    @staticmethod
    def _call_ref(f: ast.expr) -> Optional[tuple]:
        if isinstance(f, ast.Name):
            return ("name", f.id)
        if isinstance(f, ast.Attribute):
            base = f.value
            if isinstance(base, ast.Name):
                if base.id == "self":
                    return ("self", f.attr)
                return None
            if (
                isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self"
            ):
                return ("attr", base.attr, f.attr)
            if isinstance(base, ast.Call) and isinstance(
                base.func, ast.Name
            ):
                return ("callret", base.func.id, f.attr)
        return None


# -- cross-file linking -------------------------------------------------------
class _Program:
    def __init__(self, files: Dict[str, FileInfo], registry: LockRegistry):
        self.files = files
        self.reg = registry
        self.class_index: Dict[str, List[ClassInfo]] = {}
        self.func_index: Dict[str, List[FuncInfo]] = {}
        for f in files.values():
            for c in f.classes.values():
                self.class_index.setdefault(c.name, []).append(c)
            for fn in f.functions.values():
                self.func_index.setdefault(fn.name, []).append(fn)
        self._resolve_factory_types()
        self._acq_memo: Dict[int, Dict[str, tuple]] = {}
        self._blk_memo: Dict[int, Dict[str, tuple]] = {}

    def _unique_class(self, name: str) -> Optional[ClassInfo]:
        hits = self.class_index.get(name, [])
        return hits[0] if len(hits) == 1 else None

    def _annotated_return_class(self, fname: str) -> Optional[str]:
        hits = self.func_index.get(fname, [])
        if len(hits) != 1:
            return None
        returns = getattr(hits[0].node, "returns", None)
        if isinstance(returns, ast.Name):
            return returns.id
        if isinstance(returns, ast.Constant) and isinstance(
            returns.value, str
        ):
            return returns.value.split("[")[0].strip()
        if isinstance(returns, ast.Subscript) and isinstance(
            returns.value, ast.Name
        ) and returns.value.id == "Optional":
            inner = returns.slice
            if isinstance(inner, ast.Name):
                return inner.id
        return None

    def _resolve_factory_types(self) -> None:
        for f in self.files.values():
            for c in f.classes.values():
                for attr, tname in list(c.attr_types.items()):
                    if tname.startswith("()->"):
                        got = self._annotated_return_class(tname[4:])
                        if got:
                            c.attr_types[attr] = got
                        else:
                            del c.attr_types[attr]

    def resolve_call(
        self, ref: tuple, caller: FuncInfo
    ) -> Optional[FuncInfo]:
        kind = ref[0]
        if kind == "self" and caller.class_name:
            cls = self.files[caller.path].classes.get(caller.class_name)
            return cls.methods.get(ref[1]) if cls else None
        if kind == "attr" and caller.class_name:
            cls = self.files[caller.path].classes.get(caller.class_name)
            if cls is None:
                return None
            tname = cls.attr_types.get(ref[1])
            target_cls = self._unique_class(tname) if tname else None
            return target_cls.methods.get(ref[2]) if target_cls else None
        if kind == "name":
            fname = ref[1]
            same_file = self.files[caller.path].functions.get(fname)
            if same_file is not None:
                return same_file
            hits = self.func_index.get(fname, [])
            if len(hits) == 1:
                return hits[0]
            ctor_cls = self._unique_class(fname)
            if ctor_cls is not None:
                return ctor_cls.methods.get("__init__")
            return None
        if kind == "callret":
            tname = self._annotated_return_class(ref[1])
            target_cls = self._unique_class(tname) if tname else None
            return target_cls.methods.get(ref[2]) if target_cls else None
        return None

    def _transitive(self, fi: FuncInfo, memo, direct, visiting=None) -> dict:
        key = id(fi)
        if key in memo:
            return memo[key]
        if visiting is None:
            visiting = set()
        if key in visiting:
            return {}
        visiting.add(key)
        out: Dict[str, tuple] = {}
        for item in direct(fi):
            out.setdefault(item[0], (fi.qualname, item[1]))
        for ref, line, _held in fi.calls:
            target = self.resolve_call(ref, fi)
            if target is None:
                continue
            for name, prov in self._transitive(
                target, memo, direct, visiting
            ).items():
                out.setdefault(name, prov)
        visiting.discard(key)
        memo[key] = out
        return out

    def acquires_of(self, fi: FuncInfo) -> Dict[str, tuple]:
        """lock name -> (qualname, line) of every lock fi may acquire,
        transitively through resolvable calls."""
        return self._transitive(
            fi, self._acq_memo, lambda f: [(a[0], a[1]) for a in f.acquires]
        )

    def blocking_of(self, fi: FuncInfo) -> Dict[str, tuple]:
        return self._transitive(
            fi, self._blk_memo, lambda f: [(b[0], b[1]) for b in f.blocking]
        )


# -- rule evaluation ----------------------------------------------------------
def _iter_funcs(files: Dict[str, FileInfo]):
    for f in files.values():
        for fn in f.functions.values():
            yield f, None, fn
        for c in f.classes.values():
            for fn in c.methods.values():
                yield f, c, fn


def _check_orders(prog: _Program, out: List[Finding]) -> None:
    ranks = prog.reg.ranks
    for f, _cls, fn in _iter_funcs(prog.files):
        for name, line, held in fn.acquires:
            for h in held:
                if name in ranks and h in ranks and ranks[name] <= ranks[h]:
                    out.append(Finding(
                        "SL1302", f.path, line,
                        f"acquires {name!r} (rank {ranks[name]}) while "
                        f"holding {h!r} (rank {ranks[h]}) — inverts "
                        "LOCK_HIERARCHY",
                    ))
        seen = set()
        for ref, line, held in fn.calls:
            if not held:
                continue
            target = prog.resolve_call(ref, fn)
            if target is None or target is fn:
                continue
            for name, (qual, at) in prog.acquires_of(target).items():
                for h in held:
                    if (
                        name in ranks and h in ranks
                        and ranks[name] <= ranks[h]
                        and (line, h, name) not in seen
                    ):
                        seen.add((line, h, name))
                        out.append(Finding(
                            "SL1302", f.path, line,
                            f"holding {h!r} (rank {ranks[h]}), this call "
                            f"reaches {qual} which acquires {name!r} "
                            f"(rank {ranks[name]}) at line {at} — "
                            "inverts LOCK_HIERARCHY",
                        ))


def _check_blocking(prog: _Program, out: List[Finding]) -> None:
    hot = prog.reg.no_blocking
    if not hot:
        return
    for f, _cls, fn in _iter_funcs(prog.files):
        for desc, line, held in fn.blocking:
            locked = [h for h in held if h in hot]
            if locked:
                out.append(Finding(
                    "SL1303", f.path, line,
                    f"blocking op {desc} while holding dispatch-class "
                    f"lock {locked[0]!r} — compiles/I/O must move outside "
                    "(the PR-11 contract)",
                ))
        seen = set()
        for ref, line, held in fn.calls:
            locked = [h for h in held if h in hot]
            if not locked:
                continue
            target = prog.resolve_call(ref, fn)
            if target is None or target is fn:
                continue
            for desc, (qual, at) in prog.blocking_of(target).items():
                if (line, desc) in seen:
                    continue
                seen.add((line, desc))
                out.append(Finding(
                    "SL1303", f.path, line,
                    f"holding dispatch-class lock {locked[0]!r}, this "
                    f"call reaches {qual} which does {desc} at line {at}",
                ))


def _loop_has_shutdown(
    cls: Optional[ClassInfo], target_fn: FuncInfo
) -> Optional[str]:
    """None when the worker loop can exit; else a complaint."""
    for node in ast.walk(target_fn.node):
        if not isinstance(node, ast.While):
            continue
        if isinstance(node.test, ast.Constant) and node.test.value is True:
            for sub in ast.walk(node):
                if isinstance(sub, (ast.Return, ast.Break)):
                    break
            else:
                return (
                    f"worker loop in {target_fn.qualname} is `while True` "
                    "with no return/break — no shutdown path"
                )
        else:
            # stop-event loops: some method must call .set() on the event
            evt = None
            for sub in ast.walk(node.test):
                if (
                    isinstance(sub, ast.Attribute)
                    and sub.attr == "is_set"
                    and isinstance(sub.value, ast.Attribute)
                    and isinstance(sub.value.value, ast.Name)
                    and sub.value.value.id == "self"
                ):
                    evt = sub.value.attr
            if evt is not None and cls is not None:
                for meth in cls.methods.values():
                    for sub in ast.walk(meth.node):
                        if (
                            isinstance(sub, ast.Call)
                            and isinstance(sub.func, ast.Attribute)
                            and sub.func.attr == "set"
                            and isinstance(sub.func.value, ast.Attribute)
                            and isinstance(
                                sub.func.value.value, ast.Name
                            )
                            and sub.func.value.value.id == "self"
                            and sub.func.value.attr == evt
                        ):
                            return None
                return (
                    f"worker loop in {target_fn.qualname} waits on "
                    f"self.{evt} but no method ever calls "
                    f"self.{evt}.set() — stop() cannot reach it"
                )
    return None


def _check_threads(prog: _Program, out: List[Finding]) -> None:
    for f in prog.files.values():
        for spawn in f.spawns:
            joined = (
                spawn["assigned"] is not None
                and spawn["assigned"] in f.join_targets
            )
            if not spawn["daemon"] and not joined:
                out.append(Finding(
                    "SL1304", f.path, spawn["line"],
                    "spawned Thread is neither daemon=True nor joined "
                    "anywhere in this file — it outlives shutdown "
                    "(the PR-12 leak class)",
                ))
            target = spawn.get("target")
            cls = f.classes.get(spawn.get("class", ""))
            target_fn = None
            if (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
                and cls is not None
            ):
                target_fn = cls.methods.get(target.attr)
            elif isinstance(target, ast.Name):
                target_fn = f.functions.get(target.id)
            if target_fn is not None:
                complaint = _loop_has_shutdown(cls, target_fn)
                if complaint:
                    out.append(Finding(
                        "SL1304", f.path, spawn["line"], complaint
                    ))


def _route_locked(fn: FuncInfo) -> bool:
    """True for methods behind a locked-dispatch decorator (``@route``
    without ``locked=False``): the dispatcher holds the class's ``lock``
    around the call, a real-but-non-lexical guard."""
    for deco in fn.decorators:
        if not isinstance(deco, ast.Call):
            continue
        name = (
            deco.func.id if isinstance(deco.func, ast.Name)
            else deco.func.attr if isinstance(deco.func, ast.Attribute)
            else None
        )
        if name != "route":
            continue
        for kw in deco.keywords:
            if kw.arg == "locked" and isinstance(kw.value, ast.Constant):
                return bool(kw.value.value)
        return True
    return False


def _check_shared_writes(prog: _Program, out: List[Finding]) -> None:
    for f in prog.files.values():
        spawning = {s.get("class") for s in f.spawns if s.get("class")}
        for cls in f.classes.values():
            in_scope = cls.name in spawning or bool(cls.attr_locks) or any(
                f"{f.path}::{cls.name}.{attr}" in prog.reg.sites
                for meth in cls.methods.values()
                for attr in [None]  # placeholder; sites checked below
            )
            declared_attrs = {
                site.split("::", 1)[1].split(".", 1)[1]: name
                for site, name in prog.reg.sites.items()
                if site.startswith(f"{f.path}::{cls.name}.")
            }
            in_scope = cls.name in spawning or bool(cls.attr_locks) or bool(
                declared_attrs
            )
            if not in_scope:
                continue
            own_locks = dict(cls.attr_locks)
            own_locks.update(declared_attrs)
            # guard evidence per attribute: lock name or None per write
            per_attr: Dict[str, List[Tuple[Optional[str], int]]] = {}
            for meth in cls.methods.values():
                if meth.name in ("__init__", "__post_init__", "__new__"):
                    continue
                contract = None
                if _route_locked(meth) and "lock" in own_locks:
                    contract = own_locks["lock"]
                if contract is None:
                    contract = _caller_held_guard(prog, f, cls, meth)
                for attr, line, held in meth.writes:
                    if attr in own_locks or attr in cls.cond_aliases:
                        continue  # the locks themselves
                    guard = next(
                        (h for h in held if h in prog.reg.ranks), None
                    )
                    if guard is None:
                        guard = contract
                    per_attr.setdefault(attr, []).append((guard, line))
            for attr, sites in sorted(per_attr.items()):
                if attr in cls.unguarded_ok:
                    continue
                unguarded = [line for g, line in sites if g is None]
                names = {g for g, _line in sites if g is not None}
                if unguarded:
                    out.append(Finding(
                        "SL1305", f.path, unguarded[0],
                        f"{cls.name}.{attr} is written without holding a "
                        "registered lock (class "
                        + ("spawns threads" if cls.name in spawning
                           else "owns registered locks")
                        + ") — guard it, or declare it in UNGUARDED_OK "
                        "with the single-writer argument",
                    ))
                elif len(names) > 1:
                    out.append(Finding(
                        "SL1305", f.path, sites[0][1],
                        f"{cls.name}.{attr} is guarded by different locks "
                        f"at different sites ({sorted(names)}) — mutual "
                        "exclusion does not compose across locks",
                    ))


def _caller_held_guard(
    prog: _Program, f: FileInfo, cls: ClassInfo, meth: FuncInfo
) -> Optional[str]:
    """'Caller holds the lock' contract: if EVERY same-class call site
    of this method runs under one common registered lock, that lock
    guards the method's writes."""
    common: Optional[set] = None
    for other in cls.methods.values():
        if other is meth:
            continue
        for ref, _line, held in other.calls:
            if ref[0] == "self" and ref[1] == meth.name:
                locks = {h for h in held if h in prog.reg.ranks}
                common = locks if common is None else (common & locks)
    if common:
        return sorted(common)[0]
    return None


def _check_registry_liveness(
    prog: _Program, files: Dict[str, FileInfo], out: List[Finding]
) -> None:
    constructed = set()
    for f in files.values():
        constructed.update(f.constructed_sites)
    scanned_paths = set(files)
    for site, name in sorted(prog.reg.sites.items()):
        path = site.split("::", 1)[0]
        if path not in scanned_paths:
            continue  # file outside this (possibly synthetic) tree
        if site not in constructed:
            out.append(Finding(
                "SL1306", path, 1,
                f"registry row {name!r} declares site {site} but no lock "
                "is constructed there — stale declaration",
            ))


def _check_yield_points(
    prog: _Program, files: Dict[str, FileInfo], out: List[Finding]
) -> None:
    catalog = set(prog.reg.yield_points)
    seen = set()
    for f in files.values():
        for name, line in f.yield_calls:
            if name is None:
                out.append(Finding(
                    "SL1307", f.path, line,
                    "yield_point() name must be a string literal",
                ))
            elif name not in catalog:
                out.append(Finding(
                    "SL1307", f.path, line,
                    f"yield point {name!r} is not in the YIELD_POINTS "
                    "catalog (runtime/locks.py)",
                ))
            else:
                seen.add(name)
    if any(f.yield_calls for f in files.values()):
        for name in sorted(catalog - seen):
            out.append(Finding(
                "SL1307", "runtime/locks.py", 1,
                f"YIELD_POINTS entry {name!r} has no yield_point() call "
                "site in the tree — stale catalog row",
            ))


# -- entry points -------------------------------------------------------------
def check_files(
    files: Dict[str, str], registry: LockRegistry
) -> List[Finding]:
    """Audit a ``{relpath: source}`` tree (paths package-relative, e.g.
    ``serve/scheduler.py``) against an explicit registry.  The fixture
    entry point; ``check_concurrency`` wraps it for a real tree."""
    infos: Dict[str, FileInfo] = {}
    findings: List[Finding] = []
    for path, source in sorted(files.items()):
        try:
            infos[path] = _Analyzer(path, source, registry).collect()
        except SyntaxError as e:
            findings.append(Finding(
                "SL1301", path, e.lineno or 1,
                f"unparseable file: {e.msg}",
            ))
    prog = _Program(infos, registry)
    for f in infos.values():
        for line, msg in f.sl1301:
            findings.append(Finding("SL1301", f.path, line, msg))
    _check_orders(prog, findings)
    _check_blocking(prog, findings)
    _check_threads(prog, findings)
    _check_shared_writes(prog, findings)
    _check_registry_liveness(prog, infos, findings)
    _check_yield_points(prog, infos, findings)
    kept: List[Finding] = []
    for path, group in _group_by_path(findings).items():
        src = files.get(path)
        if src is None:
            kept.extend(group)
        else:
            kept.extend(apply_suppressions(group, src))
    kept.sort(key=lambda x: (x.path, x.line, x.rule))
    return kept


def _group_by_path(findings: List[Finding]) -> Dict[str, List[Finding]]:
    groups: Dict[str, List[Finding]] = {}
    for f in findings:
        groups.setdefault(f.path, []).append(f)
    return groups


def check_concurrency(root: str) -> List[Finding]:
    """Pass-10 entry for a real tree rooted at ``root`` (the repo
    checkout).  Findings come back with paths relative to ``root`` so
    the CLI's remapping applies uniformly."""
    pkg = os.path.join(root, "wittgenstein_tpu")
    files: Dict[str, str] = {}
    for sub in HOST_DIRS:
        base = os.path.join(pkg, sub)
        if not os.path.isdir(base):
            continue
        for dirpath, _dirs, names in os.walk(base):
            for name in sorted(names):
                if not name.endswith(".py"):
                    continue
                full = os.path.join(dirpath, name)
                rel = os.path.relpath(full, pkg).replace(os.sep, "/")
                if rel in EXEMPT:
                    continue
                with open(full, "r", encoding="utf-8") as fh:
                    files[rel] = fh.read()
    registry = load_registry(os.path.join(pkg, "runtime", "locks.py"))
    findings = check_files(files, registry)
    return [
        dataclasses.replace(
            f,
            path=os.path.join(root, "wittgenstein_tpu", f.path)
            if not os.path.isabs(f.path)
            else f.path,
        )
        for f in findings
    ]

"""simlint SL801: the serving scheduler's batching contract.

The serve layer's throughput story rests on one invariant: every job
packed into a batch shares the EXACT static-config digest (protocol +
traced params + horizon + chunk schedule + template leaf signature), so
a steady workload is served from a fixed number of compiled programs.
A per-job knob silently leaking into the trace — a params field that
should split the compatibility key but doesn't, or a rebuilt engine
object defeating the run cache's id()-keyed entries — turns "one
compile per family" into "one compile per job" without any test
failing on correctness.  This pass pins the contract dynamically:

  1. **digest purity** — plan a mixed pending set (seed sweep, fault
     plan, a traced-param variant); every planned batch's jobs must
     resolve to ONE full family digest, and the traced variant must
     land in a DIFFERENT batch with a different digest;
  2. **row uniformity** — the packed rows of a planned batch must share
     one leaf signature (shapes/dtypes), or the stacked program would
     differ from the family's;
  3. **compile amortization** — dispatching a second identical batch
     must be a pure run-cache HIT: any new miss is the
     recompile-per-batch regression this rule exists to catch.

Like the other dynamic passes this builds a real (tiny) engine and runs
real dispatches on CPU.
"""

from __future__ import annotations

import inspect
import os
from typing import List, Optional

from .findings import Finding, Severity


def _anchor(root: str):
    """(repo-relative path, line) of the BatchScheduler definition —
    every SL801 finding points at the scheduler."""
    from ..serve.scheduler import BatchScheduler

    path = inspect.getsourcefile(BatchScheduler) or "wittgenstein_tpu/serve/scheduler.py"
    try:
        line = inspect.getsourcelines(BatchScheduler)[1]
    except OSError:
        line = 1
    try:
        rel = os.path.relpath(path, root)
        if not rel.startswith(".."):
            path = rel
    except ValueError:
        pass
    return path, line


def _finding(path: str, line: int, msg: str) -> Finding:
    return Finding("SL801", path, line, msg, Severity.ERROR)


def check_serve_scheduler(
    root: str = ".", names: Optional[List[str]] = None
) -> List[Finding]:
    """SL801 over a synthetic mixed workload (PingPong fixture)."""
    if names and "PingPong" not in names:
        return []
    from ..parallel.replica_shard import run_cache_info
    from ..serve.jobs import JobState
    from ..serve.scheduler import BatchScheduler, _leaf_signature

    path, line = _anchor(root)
    findings: List[Finding] = []

    sched = BatchScheduler(auto_start=False, max_batch_replicas=4)
    base = {"protocol": "PingPong", "params": {"node_ct": 32}, "simMs": 60}
    specs = [
        {**base, "seed": 0},
        {**base, "seed": 1},
        {**base, "seed": 1,
         "faults": [{"op": "crash", "nodes": [1], "at": 10}]},
        # traced param change: MUST split the batch
        {"protocol": "PingPong", "params": {"node_ct": 48}, "simMs": 60,
         "seed": 0},
    ]
    jobs = [sched.submit(s) for s in specs]
    by_id = {j.id: j for j in jobs}
    split_job = jobs[-1]

    plans = sched.plan_batches()

    # 1. digest purity within every planned batch, split across batches
    for plan in plans:
        digests = set()
        sigs = set()
        for jid in plan["jobs"]:
            job = by_id[jid]
            fam = sched.family_for(job.spec)
            digests.add(fam.digest)
            # 2. row uniformity: the packed row's leaf signature must
            # match the family template's
            sigs.add(_leaf_signature(sched._row(fam, job.spec)))
        if len(digests) > 1:
            findings.append(_finding(
                path, line,
                f"batch {plan['jobs']} mixes static-config digests "
                f"{sorted(digests)} — jobs packed together must share "
                "one compiled program",
            ))
        if len(sigs) > 1:
            findings.append(_finding(
                path, line,
                f"batch {plan['jobs']} packs rows with differing leaf "
                "signatures — the stacked state would not match the "
                "family's compiled program",
            ))
        if (
            split_job.id in plan["jobs"]
            and len(plan["jobs"]) > 1
        ):
            findings.append(_finding(
                path, line,
                "a traced-param variant (node_ct=48) was planned into "
                "the same batch as node_ct=32 jobs — the compatibility "
                "key ignores a trace-shaping param",
            ))
    fam_a = sched.family_for(jobs[0].spec)
    fam_b = sched.family_for(split_job.spec)
    if fam_a.digest == fam_b.digest:
        findings.append(_finding(
            path, line,
            "node_ct=32 and node_ct=48 resolved to the same family "
            "digest — traced params are not part of the compatibility "
            "key",
        ))
    if findings:
        return findings

    # 3. compile amortization: run everything, then an identical second
    # wave — the second wave must be pure cache hits
    while sched.drain_once():
        pass
    for j in jobs:
        if j.state is not JobState.DONE:
            findings.append(_finding(
                path, line,
                f"fixture job {j.id} finished {j.state.value} "
                f"({j.error}) — the contract run itself failed",
            ))
            return findings
    before = run_cache_info()
    wave2 = [sched.submit(s) for s in specs]
    while sched.drain_once():
        pass
    after = run_cache_info()
    for j in wave2:
        if j.state is not JobState.DONE:
            findings.append(_finding(
                path, line,
                f"second-wave job {j.id} finished {j.state.value} "
                f"({j.error})",
            ))
            return findings
    new_misses = after["misses"] - before["misses"]
    new_compiles = after["compiles"] - before["compiles"]
    if new_misses or new_compiles:
        findings.append(_finding(
            path, line,
            f"re-dispatching an identical workload cost {new_misses} "
            f"run-cache miss(es) / {new_compiles} compile(s) — the "
            "scheduler is recompiling per batch instead of serving "
            "steady workloads from cached programs",
        ))
    return findings

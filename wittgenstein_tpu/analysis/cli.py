"""simlint command line: `python -m wittgenstein_tpu.analysis [opts]`.

Runs up to ten passes and prints findings as `path:line: RULE [sev] msg`
(or JSONL with --format json):

  1. AST lint over every wittgenstein_tpu/*.py  (SL1xx/SL2xx)
  2. registry/test coverage meta-rule           (SL301)
  3. SLO alert catalog audit                    (SL1101)
  4. concurrency contract checker               (SL1301-SL1307)
  5. pinned-regression audit                    (SL1401)
  6. abstract-eval contract checks              (SL401-SL404)
  7. beat RNG audit                             (SL405)
  8. checkpoint completeness                    (SL501)
  9. phase-annotation presence + neutrality     (SL601)
 10. serve scheduler batching contract          (SL801)
 11. 2D-mesh replicated-leaf audit              (SL1001)

Exit status: 0 when clean; 1 when any ERROR finding (or, with --strict,
any finding at all) survives suppression; 2 on usage errors.  Passes 6-10
build every registered protocol and trace real kernels, so they take tens
of seconds — `--skip-contracts` runs just the fast text-level passes
(1-5; no JAX import; the SL1401 audit then checks structure only,
skipping its plan-lowering depth); `--skip-concurrency` drops the
lock-discipline pass from either mode.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from .findings import Finding, Severity


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m wittgenstein_tpu.analysis",
        description="simlint: static + abstract-eval contract checker for "
        "batched protocols and jit paths",
    )
    p.add_argument("--root", default=".",
                   help="repo root containing wittgenstein_tpu/ (default .)")
    p.add_argument("--strict", action="store_true",
                   help="exit nonzero on ANY finding, warnings included")
    p.add_argument("--format", choices=("text", "json"), default="text",
                   help="findings as text lines or JSONL")
    p.add_argument("-o", "--output", default=None,
                   help="also write findings (JSONL) to this file")
    p.add_argument("--skip-contracts", action="store_true",
                   help="skip the abstract-eval + RNG passes (AST and "
                   "registry rules only; no JAX import)")
    p.add_argument("--skip-concurrency", action="store_true",
                   help="skip the concurrency contract checker "
                   "(SL1301-SL1307)")
    p.add_argument("--protocol", action="append", default=None,
                   metavar="NAME",
                   help="restrict contract/RNG passes to this registered "
                   "protocol (repeatable)")
    return p


def _rel(path: str, root: str) -> str:
    try:
        rel = os.path.relpath(path, root)
    except ValueError:
        return path
    return path if rel.startswith("..") else rel


def run(root: str, skip_contracts: bool = False,
        protocols: Optional[List[str]] = None,
        skip_concurrency: bool = False) -> List[Finding]:
    """All passes over `root`; returns the surviving findings."""
    import dataclasses

    from .ast_lint import lint_package
    from .registry_check import check_registry_coverage

    # the AST pass covers the package tree only: tests/ hosts deliberately
    # bad fixtures for simlint's own test suite
    findings = list(lint_package(os.path.join(root, "wittgenstein_tpu")))
    findings += check_registry_coverage(root)
    from .slo_check import check_slo_catalog

    findings += check_slo_catalog(root)
    if not skip_concurrency:
        from .concurrency_check import check_concurrency

        findings += check_concurrency(root)
    if skip_contracts:
        from .regressions_check import check_regressions

        # pinned-regression audit (SL1401) at structural depth — the
        # lowering depth runs in the contracts block below instead (one
        # call either way, so a bad pin is reported exactly once)
        findings += check_regressions(root, lower=False)
    findings = [
        dataclasses.replace(f, path=_rel(f.path, root)) for f in findings
    ]

    if not skip_contracts:
        # pin the platform BEFORE anything imports jax: the contract
        # passes must run identically on a CPU-only CI box
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from .annotations_check import check_annotations
        from .checkpoint_check import check_checkpoints
        from .contracts import check_all
        from .rng_audit import audit_all

        if protocols:
            from ..core.registries import registry_batched_protocols

            unknown = set(protocols) - set(registry_batched_protocols.names())
            if unknown:
                raise SystemExit(
                    "simlint: unknown protocol(s): "
                    + ", ".join(sorted(unknown))
                    + " (known: "
                    + ", ".join(registry_batched_protocols.names())
                    + ")"
                )
        findings += check_all(root=root, names=protocols)
        findings += audit_all(root=root, names=protocols)
        findings += check_checkpoints(root=root, names=protocols)
        findings += check_annotations(root=root, names=protocols)
        from .serve_check import check_serve_scheduler

        findings += check_serve_scheduler(root=root, names=protocols)
        from .mesh_check import check_mesh_layout

        findings += check_mesh_layout(root=root, names=protocols)
        from .regressions_check import check_regressions

        findings += [
            dataclasses.replace(f, path=_rel(f.path, root))
            for f in check_regressions(root, lower=True)
        ]
    return findings


def main(argv=None) -> int:
    args = _parser().parse_args(argv)
    root = os.path.abspath(args.root)
    if not os.path.isdir(os.path.join(root, "wittgenstein_tpu")):
        print(f"simlint: no wittgenstein_tpu/ package under {root}",
              file=sys.stderr)
        return 2

    findings = run(root, skip_contracts=args.skip_contracts,
                   protocols=args.protocol,
                   skip_concurrency=args.skip_concurrency)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))

    lines = [
        f.to_json() if args.format == "json" else f.format()
        for f in findings
    ]
    for ln in lines:
        print(ln)
    if args.output:
        with open(args.output, "w", encoding="utf-8") as fh:
            for f in findings:
                fh.write(f.to_json() + "\n")

    n_err = sum(1 for f in findings if f.severity is Severity.ERROR)
    n_warn = len(findings) - n_err
    tail = f"simlint: {n_err} error(s), {n_warn} warning(s)"
    print(tail if findings else "simlint: clean", file=sys.stderr)

    if n_err or (args.strict and findings):
        return 1
    return 0

"""simlint SL1401: the pinned-regression audit.

`scenarios/regressions/*.json` files are executable claims: each one
says "this genome, lowered against THIS registered protocol, scored
THIS value and beat the static baselines" — and tests/CI replay them
bitwise.  A pin that no longer loads, names an unregistered protocol or
unknown objective, or carries a genome outside its own declared bounds
is a regression test that silently stopped testing anything.

Two depths, matching the CLI's fast/contracts split:

  - structural (`lower=False`, part of `--skip-contracts`): JSON loads,
    schema/required fields, protocol registered in
    core.registries.registry_batched_protocols, objective registered in
    search.objectives.OBJECTIVES, genome validates against its pinned
    GeneSpec bounds, and a pinned baseline block is strictly beaten by
    the pinned objective value.  No JAX import anywhere on this path.
  - lowering (`lower=True`, contracts mode): additionally rebuild the
    (net, state) from the registry factory, decode the genome against
    the live mask, lower the plan, and require the lowered FaultState
    digest to equal the pinned `plan_digest` — the "still means the
    same attack" check.  The full bitwise SCORE replay stays in
    tests/test_search.py and scripts/adversary_smoke.py (it runs the
    engine; too slow for a lint pass).

Findings anchor at line 1 of the offending file.
"""

from __future__ import annotations

import json
import os
from typing import List

from .findings import Finding, Severity

RULE = "SL1401"


def _finding(path: str, msg: str) -> Finding:
    return Finding(rule=RULE, path=path, line=1, message=msg,
                   severity=Severity.ERROR)


def check_regressions(root: str, lower: bool = False) -> List[Finding]:
    """Audit every checked-in regression pin under `root` (see module
    docstring for the two depths)."""
    from ..scenarios.regressions import check_regression_doc

    reg_dir = os.path.join(
        root, "wittgenstein_tpu", "scenarios", "regressions"
    )
    findings: List[Finding] = []
    if not os.path.isdir(reg_dir):
        return findings
    for name in sorted(os.listdir(reg_dir)):
        if not name.endswith(".json"):
            continue
        path = os.path.join(reg_dir, name)
        try:
            doc = json.loads(open(path, encoding="utf-8").read())
        except (OSError, json.JSONDecodeError) as e:
            findings.append(_finding(path, f"does not load as JSON: {e}"))
            continue
        if not isinstance(doc, dict):
            findings.append(
                _finding(path, "top-level JSON value is not an object")
            )
            continue
        for problem in check_regression_doc(doc):
            findings.append(_finding(path, problem))
        if lower and not check_regression_doc(doc):
            findings.extend(_check_lowering(path, doc))
    return findings


def _check_lowering(path: str, doc: dict) -> List[Finding]:
    import numpy as np

    from ..core.registries import registry_batched_protocols
    from ..search.genome import FaultGenome

    try:
        net, state = registry_batched_protocols.get(doc["protocol"]).factory()
    except NotImplementedError:
        # registered name without a batched factory yet (ethpow's
        # stub): structural checks passed, nothing to lower against
        return []
    try:
        genome = FaultGenome(
            doc["sim_ms"], net.n_nodes, live=~np.asarray(state.down)
        )
        digest = genome.digest(
            np.asarray(doc["genome"]["vec"], np.float64),
            net.protocol.n_msg_types(),
        )
    except Exception as e:  # any decode/lower failure is the finding
        return [
            _finding(
                path,
                f"pinned genome fails to lower against the rebuilt "
                f"{doc['protocol']!r} state: {e}",
            )
        ]
    if digest != doc["plan_digest"]:
        return [
            _finding(
                path,
                f"lowered-plan digest {digest} != pinned "
                f"{doc['plan_digest']} — the pin no longer names the "
                "attack it was frozen from",
            )
        ]
    return []

"""Registry-coverage meta-rule (SL301).

Every batched protocol implementation must be (a) registered in
`core.registries.registry_batched_protocols` so the abstract-eval passes
enumerate it, and (b) exercised by at least one test module.  This is the
rule that keeps the OTHER rules honest: a new `protocols/foo_batched.py`
that never registers would silently escape the contract checks, and CI
would go green on an unchecked kernel.

Underscore-prefixed modules (`_agg_batched.py`) are shared bases, not
protocols, and are exempt.
"""

from __future__ import annotations

import glob
import os
from typing import List

from .findings import Finding, Severity


def check_registry_coverage(root: str = ".") -> List[Finding]:
    findings: List[Finding] = []
    proto_dir = os.path.join(root, "wittgenstein_tpu", "protocols")
    tests_dir = os.path.join(root, "tests")
    if not os.path.isdir(proto_dir):
        return findings

    modules = sorted(
        os.path.basename(p)[:-3]
        for p in glob.glob(os.path.join(proto_dir, "*_batched.py"))
        if not os.path.basename(p).startswith("_")
    )

    try:
        from ..core.registries import registry_batched_protocols

        registered = set(registry_batched_protocols.modules())
    except Exception as e:
        findings.append(Finding(
            rule="SL301",
            path=os.path.join("wittgenstein_tpu", "core", "registries.py"),
            line=1,
            message=f"batched-protocol registry failed to import: "
                    f"{type(e).__name__}: {e}",
            severity=Severity.ERROR,
        ))
        return findings

    # one pass over the test sources; mention of the module name (import
    # or factory reference) counts as coverage
    test_sources = {}
    for tp in sorted(glob.glob(os.path.join(tests_dir, "test_*.py"))):
        try:
            with open(tp, "r", encoding="utf-8") as fh:
                test_sources[tp] = fh.read()
        except OSError:
            continue

    for mod in modules:
        relpath = os.path.join("wittgenstein_tpu", "protocols", mod + ".py")
        if mod not in registered:
            findings.append(Finding(
                rule="SL301",
                path=relpath,
                line=1,
                message=f"protocols/{mod}.py is not registered in "
                        "core.registries.registry_batched_protocols — the "
                        "abstract-eval contract checks cannot see it",
                severity=Severity.ERROR,
            ))
        if not any(mod in src for src in test_sources.values()):
            findings.append(Finding(
                rule="SL301",
                path=relpath,
                line=1,
                message=f"protocols/{mod}.py has no tests/test_*.py "
                        "referencing it (parity coverage missing)",
                severity=Severity.ERROR,
            ))

    # dangling registrations: a registry entry whose module file is gone
    for mod in sorted(registered - set(modules)):
        if mod.startswith("_"):
            continue
        findings.append(Finding(
            rule="SL301",
            path=os.path.join("wittgenstein_tpu", "core", "registries.py"),
            line=1,
            message=f"registry lists module '{mod}' but "
                    f"protocols/{mod}.py does not exist",
            severity=Severity.ERROR,
        ))
    return findings

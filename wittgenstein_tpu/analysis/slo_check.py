"""simlint SL1101: the SLO alert catalog audit.

Mission control's promise is that a dashboard keyed on the registered
SLO names (obs.slo.REGISTERED_SLOS) sees EVERY alert the codebase can
emit.  That promise breaks silently: a new invariant check that fires
``fire_violation("wheel-headroom")`` under a name nobody registered
still alerts at runtime — into a counter label no dashboard row
matches.  (fire_violation raises on unknown names at runtime, but only
when that path actually executes; SLOSpec validates at construction,
but sentinel-style direct violations are strings until fired.)

This pass closes the gap statically: it parses every module under
``wittgenstein_tpu/`` and ``scripts/`` and audits each alert-capable
call site whose SLO name is a string literal —

  - ``fire_violation("...")`` / ``_alert("...")`` / ``alert("...")``
    first arguments (the sentinel's emission chain),
  - ``SLOSpec(name="...")`` constructions,
  - ``slo="..."`` keyword arguments on any call (recorder events,
    engine internals)

— against REGISTERED_SLOS.  A literal outside the catalog is an ERROR
anchored at the call site.  Dynamic names (variables) are left to the
runtime guards.  Pure text/AST: no JAX import, so the pass runs under
``--skip-contracts`` too.
"""

from __future__ import annotations

import ast
import os
from typing import Iterable, List

from .findings import Finding, Severity, apply_suppressions

#: call-ee names whose FIRST positional string argument is an SLO name
_NAME_ARG_CALLEES = ("fire_violation", "_alert", "alert")

#: files that define the catalog / validators themselves (docstrings and
#: error messages there mention hypothetical names)
_EXEMPT_SUFFIXES = (
    os.path.join("analysis", "slo_check.py"),
)


def _callee_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def _literal(node) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _audit_source(path: str, source: str, registered: set) -> List[Finding]:
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return []
    findings: List[Finding] = []

    def bad(node, name: str, where: str) -> None:
        findings.append(Finding(
            "SL1101", path, node.lineno,
            f"{where} names SLO {name!r}, which is not in "
            "obs.slo.REGISTERED_SLOS — register it (and its dashboard "
            "row) before emitting under it",
            Severity.ERROR,
        ))

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        callee = _callee_name(node)
        if callee in _NAME_ARG_CALLEES and node.args:
            name = _literal(node.args[0])
            if name is not None and name not in registered:
                bad(node, name, f"{callee}() call")
        if callee == "SLOSpec":
            for kw in node.keywords:
                if kw.arg == "name":
                    name = _literal(kw.value)
                    if name is not None and name not in registered:
                        bad(node, name, "SLOSpec(name=...)")
        for kw in node.keywords:
            if kw.arg == "slo":
                name = _literal(kw.value)
                if name is not None and name not in registered:
                    bad(node, name, f"{callee}(slo=...) keyword")
    return apply_suppressions(findings, source)


def _py_files(root: str) -> Iterable[str]:
    for sub in ("wittgenstein_tpu", "scripts"):
        base = os.path.join(root, sub)
        if not os.path.isdir(base):
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [
                d for d in dirnames
                if d != "__pycache__" and not d.startswith(".")
            ]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def check_slo_catalog(root: str) -> List[Finding]:
    """SL1101 over the package + scripts trees.  See module docstring."""
    from ..obs.slo import REGISTERED_SLOS

    registered = set(REGISTERED_SLOS)
    findings: List[Finding] = []
    for path in _py_files(root):
        if path.endswith(_EXEMPT_SUFFIXES):
            continue
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except OSError:
            continue
        findings += _audit_source(path, source, registered)
    return findings

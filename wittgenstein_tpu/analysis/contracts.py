"""Abstract-eval contract checks (SL401-SL404, SL406-SL407, SL701,
SL901, SL1201).

These rules run the real engine code under JAX's abstract interpreter
instead of reading its text: every protocol registered in
`core.registries.registry_batched_protocols` is built at a small analysis
scale and its kernels are traced with `jax.eval_shape` / `jax.make_jaxpr`.
That catches the contract violations an AST pass cannot see — a `deliver`
that rewrites an engine-owned store column three calls deep, a `tick`
whose output dtypes drift from its input (forcing a recompile every
chained `run_ms`), a telemetry side-car that perturbs sim dynamics.

Rules:

SL401  step() must preserve the SimState tree: same treedef, and every
       leaf keeps its shape and dtype (no silent f32->f64 or weak-type
       promotion through a full tick).
SL402  deliver() must not write engine-owned fields: tracing it to a
       jaxpr, the outvar for every engine-owned leaf must be the SAME
       variable as the invar (a pure passthrough), unless the field is
       declared in DELIVER_MAY_TOUCH.
SL403  telemetry must be bit-neutral: with_telemetry() must leave every
       non-tele leaf's aval unchanged under eval_shape AND one concrete
       step must produce bit-identical non-tele leaves.
SL404  recompile sentry: step() output avals (including weak_type) must
       equal input avals so chained run_ms calls hit the jit cache, and
       two independent traces must yield the same jaxpr (no
       trace-nondeterminism from unordered Python iteration).
SL406  fault-off neutrality: a fault-enabled engine running the neutral
       FaultState must leave every non-fault leaf's aval unchanged AND
       one concrete step must be bit-identical (the fault twin of
       SL403; wittgenstein_tpu.faults).
SL407  fault-lane ownership: tracing deliver() on a fault-ENABLED
       delivery view, every state.faults leaf must be a pure
       passthrough — the engine owns the schedule and its counters.
SL701  derived-cache consistency: a protocol declaring
       DERIVED_CACHE_LEAVES (carried score/cardinality caches, the PR-8
       hot-loop lever) must keep them equal to recompute_caches()'s
       from-scratch values.  The entry is stepped concretely for several
       ticks (so deliver, commits and periodic work all execute) and
       every declared leaf is compared bitwise against the oracle — a
       stale-cache bug cannot ship silently.
SL1201 jump-safety audit: TICK_INTERVAL=None promises every
       inter-arrival tick is empty (the next-arrival jump paths —
       singleton _step_jump and the batched consensus jump — skip them
       outright), so the protocol's tick_beat must trace to a structural
       no-op and BEAT_PERIOD must stay undeclared.
SL901  narrow-dtype overflow audit: the engine's message-lane plan must
       cover (N-1, n_msg_types-1), every NARROW_LEAVES declaration
       (engine.density) must match its live leaf's dtype with the
       sentinel slot kept free, and after concrete steps every
       non-sentinel value must stay inside [0, declared_max] — the bound
       the storage dtype was chosen by.

Protocol-level suppression: list rule ids in the protocol class's
SIMLINT_SUPPRESS tuple (the dynamic analog of `# simlint: disable=`).
"""

from __future__ import annotations

import inspect
import os
from typing import Any, List, Optional, Tuple

from .findings import Finding, Severity

_MAX_LEAF_REPORTS = 4  # per rule per protocol; the rest are summarized


def _cpu_jax():
    """Import jax pinned to CPU (the analysis pass must not grab an
    accelerator or depend on one being present)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass  # platform already locked in is fine
    return jax


def _proto_location(protocol) -> Tuple[str, int]:
    """(source file, class def line) of a protocol instance's class —
    where contract findings anchor."""
    cls = type(protocol)
    try:
        path = inspect.getsourcefile(cls) or "<unknown>"
        _, line = inspect.getsourcelines(cls)
    except (OSError, TypeError):
        path, line = "<unknown>", 1
    return path, line


def _leaf_paths(jax, tree) -> List[Tuple[str, Any]]:
    """[(dotted path, leaf)] in flatten order."""
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    return [(jax.tree_util.keystr(kp), leaf) for kp, leaf in flat]


def _aval(leaf) -> Tuple[tuple, str, bool]:
    shape = tuple(getattr(leaf, "shape", ()))
    dtype = str(getattr(leaf, "dtype", type(leaf).__name__))
    weak = bool(getattr(leaf, "weak_type", False))
    return shape, dtype, weak


def _fingerprint(jax, tree) -> List[Tuple[str, tuple, str, bool]]:
    return [(p,) + _aval(l) for p, l in _leaf_paths(jax, tree)]


def _diff_fingerprints(fp_in, fp_out) -> List[str]:
    """Human-readable per-leaf diffs (path-keyed; structure mismatch is
    reported separately via treedef)."""
    by_path = {p: rest for p, *rest in fp_in}
    msgs = []
    for p, *rest in fp_out:
        if p not in by_path:
            msgs.append(f"{p}: leaf appears only in output")
        elif by_path[p] != rest:
            si, di, wi = by_path[p]
            so, do, wo = rest
            msgs.append(
                f"{p}: {si}/{di}{'(weak)' if wi else ''} -> "
                f"{so}/{do}{'(weak)' if wo else ''}"
            )
    out_paths = {p for p, *_ in fp_out}
    for p in by_path:
        if p not in out_paths:
            msgs.append(f"{p}: leaf disappears in output")
    return msgs


def _mk(rule, path, line, msg, suppress) -> Optional[Finding]:
    if rule in suppress:
        return None
    return Finding(rule=rule, path=path, line=line, message=msg,
                   severity=Severity.ERROR)


def _check_structure(jax, name, net, state, path, line, suppress):
    """SL401: step preserves tree structure + leaf shape/dtype."""
    findings = []
    try:
        out = jax.eval_shape(net.step, state)
    except Exception as e:  # abstract eval itself failing IS the finding
        f = _mk("SL401", path, line,
                f"[{name}] step() failed abstract evaluation: "
                f"{type(e).__name__}: {e}", suppress)
        return [f] if f else [], None
    tin = jax.tree_util.tree_structure(state)
    tout = jax.tree_util.tree_structure(out)
    if tin != tout:
        f = _mk("SL401", path, line,
                f"[{name}] step() changes the SimState tree structure "
                f"(in={tin}, out={tout})", suppress)
        return [f] if f else [], out
    diffs = _diff_fingerprints(
        [(p,) + _aval(l)[:2] + (False,) for p, l in _leaf_paths(jax, state)],
        [(p,) + _aval(l)[:2] + (False,) for p, l in _leaf_paths(jax, out)],
    )
    for d in diffs[:_MAX_LEAF_REPORTS]:
        f = _mk("SL401", path, line,
                f"[{name}] step() changes leaf shape/dtype: {d}", suppress)
        if f:
            findings.append(f)
    if len(diffs) > _MAX_LEAF_REPORTS:
        f = _mk("SL401", path, line,
                f"[{name}] ... and {len(diffs) - _MAX_LEAF_REPORTS} more "
                f"leaf shape/dtype changes", suppress)
        if f:
            findings.append(f)
    return findings, out


def _check_msg_ownership(jax, name, net, state, path, line, suppress):
    """SL402: deliver() leaves engine-owned leaves as pure passthroughs."""
    from ..engine.core import SimState
    from ..engine.protocol import ENGINE_OWNED_FIELDS

    vstate, _due, deliver, _ctx = net.delivery_view(state)

    def deliver_state(vs, mask):
        pstate, _em = net.protocol.deliver(net, vs, mask)
        return pstate

    try:
        closed, out_shape = jax.make_jaxpr(deliver_state, return_shape=True)(
            vstate, deliver
        )
    except Exception as e:
        f = _mk("SL402", path, line,
                f"[{name}] deliver() failed tracing on the delivery view: "
                f"{type(e).__name__}: {e}", suppress)
        return [f] if f else []
    if jax.tree_util.tree_structure(out_shape) != jax.tree_util.tree_structure(
        vstate
    ):
        f = _mk("SL402", path, line,
                f"[{name}] deliver() changes the SimState tree structure, "
                "so field ownership cannot be checked", suppress)
        return [f] if f else []

    # leaf index ranges per SimState field (NamedTuple flattens in field
    # order, and the output tree matches, so invar k <-> outvar k)
    offsets = {}
    i = 0
    for fname, sub in zip(SimState._fields, vstate):
        n = len(jax.tree_util.tree_leaves(sub))
        offsets[fname] = (i, i + n)
        i += n
    invars = closed.jaxpr.invars
    outvars = closed.jaxpr.outvars

    allowed = set(getattr(net.protocol, "DELIVER_MAY_TOUCH", ()) or ())
    findings = []
    for fname in ENGINE_OWNED_FIELDS:
        if fname in allowed:
            continue
        a, b = offsets[fname]
        touched = [k for k in range(a, b) if outvars[k] is not invars[k]]
        if touched:
            f = _mk("SL402", path, line,
                    f"[{name}] deliver() writes engine-owned field "
                    f"'{fname}' ({len(touched)} leaf(s) are not input "
                    "passthroughs); return emissions instead, or declare "
                    "it in DELIVER_MAY_TOUCH", suppress)
            if f:
                findings.append(f)
    return findings


def _check_telemetry_neutral(jax, name, net, state, path, line, suppress):
    """SL403: instrumentation leaves non-tele leaves bit-identical."""
    import numpy as np

    from ..telemetry.state import TelemetryConfig

    findings = []
    try:
        tnet, tstate = net.with_telemetry(state, TelemetryConfig(snapshots=0))
        out_plain = jax.eval_shape(net.step, state)
        out_tele = jax.eval_shape(tnet.step, tstate)
    except Exception as e:
        f = _mk("SL403", path, line,
                f"[{name}] telemetry instrumentation failed: "
                f"{type(e).__name__}: {e}", suppress)
        return [f] if f else []
    fp_p = [x for x in _fingerprint(jax, out_plain._replace(tele=()))]
    fp_t = [x for x in _fingerprint(jax, out_tele._replace(tele=()))]
    diffs = _diff_fingerprints(fp_p, fp_t)
    for d in diffs[:_MAX_LEAF_REPORTS]:
        f = _mk("SL403", path, line,
                f"[{name}] telemetry changes a non-tele leaf aval: {d}",
                suppress)
        if f:
            findings.append(f)
    if diffs:
        return findings

    # concrete one-step cross-check: the side-car must be bit-neutral
    s_plain = net.step(state)
    s_tele = tnet.step(tstate)
    for (p, a), (_, b) in zip(
        _leaf_paths(jax, s_plain._replace(tele=())),
        _leaf_paths(jax, s_tele._replace(tele=())),
    ):
        if not np.array_equal(np.asarray(a), np.asarray(b)):
            f = _mk("SL403", path, line,
                    f"[{name}] telemetry perturbs sim dynamics: leaf {p} "
                    "differs bitwise after one instrumented step", suppress)
            if f:
                findings.append(f)
            break
    return findings


def _check_fault_neutral(jax, name, net, state, path, line, suppress):
    """SL406: a fault-enabled engine on the neutral schedule leaves
    non-fault leaves bit-identical (the fault twin of SL403).  Entries
    that are ALREADY fault-enabled (the fault-lane registry entries)
    are skipped — their schedule is deliberately non-neutral and their
    neutrality is covered by the base entry."""
    import numpy as np

    from ..faults.state import FaultConfig

    if getattr(net, "faults", None) is not None:
        return []
    findings = []
    try:
        fnet, fstate = net.with_faults(state, FaultConfig())
        out_plain = jax.eval_shape(net.step, state)
        out_fault = jax.eval_shape(fnet.step, fstate)
    except Exception as e:
        f = _mk("SL406", path, line,
                f"[{name}] fault instrumentation failed: "
                f"{type(e).__name__}: {e}", suppress)
        return [f] if f else []
    fp_p = _fingerprint(jax, out_plain._replace(faults=()))
    fp_f = _fingerprint(jax, out_fault._replace(faults=()))
    diffs = _diff_fingerprints(fp_p, fp_f)
    for d in diffs[:_MAX_LEAF_REPORTS]:
        f = _mk("SL406", path, line,
                f"[{name}] fault side-car changes a non-fault leaf aval: "
                f"{d}", suppress)
        if f:
            findings.append(f)
    if diffs:
        return findings

    # concrete one-step cross-check: the neutral schedule must be
    # bit-neutral (every fault predicate constant-false, every latency
    # an exact passthrough)
    s_plain = net.step(state)
    s_fault = fnet.step(fstate)
    for (p, a), (_, b) in zip(
        _leaf_paths(jax, s_plain._replace(faults=())),
        _leaf_paths(jax, s_fault._replace(faults=())),
    ):
        if not np.array_equal(np.asarray(a), np.asarray(b)):
            f = _mk("SL406", path, line,
                    f"[{name}] neutral fault schedule perturbs sim "
                    f"dynamics: leaf {p} differs bitwise after one "
                    "fault-enabled step", suppress)
            if f:
                findings.append(f)
            break
    return findings


def _check_fault_deliver_ownership(jax, name, net, state, path, line, suppress):
    """SL407: deliver() must leave the fault lane alone, checked on a
    fault-ENABLED delivery view (on a plain entry state.faults has zero
    leaves, so SL402's ownership scan is vacuous there)."""
    from ..engine.core import SimState
    from ..faults.state import FaultConfig

    if getattr(net, "faults", None) is None:
        try:
            net, state = net.with_faults(state, FaultConfig())
        except Exception as e:
            f = _mk("SL407", path, line,
                    f"[{name}] fault instrumentation failed: "
                    f"{type(e).__name__}: {e}", suppress)
            return [f] if f else []
    vstate, _due, deliver, _ctx = net.delivery_view(state)

    def deliver_state(vs, mask):
        pstate, _em = net.protocol.deliver(net, vs, mask)
        return pstate

    try:
        closed, out_shape = jax.make_jaxpr(deliver_state, return_shape=True)(
            vstate, deliver
        )
    except Exception as e:
        f = _mk("SL407", path, line,
                f"[{name}] deliver() failed tracing on the fault-enabled "
                f"delivery view: {type(e).__name__}: {e}", suppress)
        return [f] if f else []
    if jax.tree_util.tree_structure(out_shape) != jax.tree_util.tree_structure(
        vstate
    ):
        f = _mk("SL407", path, line,
                f"[{name}] deliver() changes the SimState tree structure "
                "on the fault-enabled view", suppress)
        return [f] if f else []

    offsets = {}
    i = 0
    for fname, sub in zip(SimState._fields, vstate):
        n = len(jax.tree_util.tree_leaves(sub))
        offsets[fname] = (i, i + n)
        i += n
    invars = closed.jaxpr.invars
    outvars = closed.jaxpr.outvars
    allowed = set(getattr(net.protocol, "DELIVER_MAY_TOUCH", ()) or ())
    if "faults" in allowed:
        return []
    a, b = offsets["faults"]
    touched = [k for k in range(a, b) if outvars[k] is not invars[k]]
    if touched:
        leaf_names = [p for p, _ in _leaf_paths(jax, vstate.faults)]
        names = ", ".join(
            leaf_names[k - a] if k - a < len(leaf_names) else f"leaf {k - a}"
            for k in touched[:_MAX_LEAF_REPORTS]
        )
        f = _mk("SL407", path, line,
                f"[{name}] deliver() writes the fault lane "
                f"(state.faults leaves not passed through: {names}); the "
                "engine owns the fault schedule and its counters", suppress)
        return [f] if f else []
    return []


def _check_derived_cache(jax, name, net, state, path, line, suppress):
    """SL701: carried derived-cache leaves stay consistent with their
    from-scratch recompute after concrete traced steps.  Skipped (clean)
    when the protocol declares no DERIVED_CACHE_LEAVES."""
    import numpy as np

    leaves = tuple(getattr(net.protocol, "DERIVED_CACHE_LEAVES", ()) or ())
    if not leaves:
        return []
    findings = []
    proto = state.proto
    if not isinstance(proto, dict):
        f = _mk("SL701", path, line,
                f"[{name}] declares DERIVED_CACHE_LEAVES {leaves} but "
                "state.proto is not a dict, so the leaves cannot exist",
                suppress)
        return [f] if f else []
    missing = [lf for lf in leaves if lf not in proto]
    if missing:
        f = _mk("SL701", path, line,
                f"[{name}] DERIVED_CACHE_LEAVES {missing} not present in "
                "the initial state.proto (proto_init must seed every "
                "declared cache leaf)", suppress)
        return [f] if f else []
    try:
        oracle = net.protocol.recompute_caches(state)
    except Exception as e:
        f = _mk("SL701", path, line,
                f"[{name}] recompute_caches() failed on the initial "
                f"state: {type(e).__name__}: {e}", suppress)
        return [f] if f else []
    uncovered = [lf for lf in leaves if lf not in oracle]
    if uncovered:
        f = _mk("SL701", path, line,
                f"[{name}] recompute_caches() does not cover declared "
                f"leaves {uncovered}; every DERIVED_CACHE_LEAVES entry "
                "needs a from-scratch oracle", suppress)
        return [f] if f else []

    # concrete stepped consistency: enough ticks that delivery, commits
    # and periodic beats all execute at least once at analysis scale
    try:
        stepped = state
        for _ in range(8):
            stepped = net.step(stepped)
        fresh = net.protocol.recompute_caches(stepped)
    except Exception as e:
        f = _mk("SL701", path, line,
                f"[{name}] concrete stepping for the cache-consistency "
                f"check failed: {type(e).__name__}: {e}", suppress)
        return [f] if f else []
    for lf in leaves:
        if lf not in stepped.proto or lf not in fresh:
            f = _mk("SL701", path, line,
                    f"[{name}] derived cache '{lf}' DISAPPEARED during "
                    "stepping: a kernel hook rebuilt state.proto without "
                    "carrying the declared cache leaf through", suppress)
            if f:
                findings.append(f)
            continue
        if not np.array_equal(
            np.asarray(stepped.proto[lf]), np.asarray(fresh[lf])
        ):
            f = _mk("SL701", path, line,
                    f"[{name}] derived cache '{lf}' is STALE: after 8 "
                    "concrete steps the carried leaf differs bitwise from "
                    "recompute_caches() — an update path (deliver/commit/"
                    "select) forgot to maintain it", suppress)
            if f:
                findings.append(f)
    return findings


def _check_narrow_overflow(jax, name, net, state, path, line, suppress):
    """SL901: narrow packed dtypes must have provable headroom.  Audits
    (a) the engine's message-lane plan against the config's actual
    bounds, (b) every declared NarrowLeaf statically (live dtype matches
    the declaration; declared_max leaves the sentinel slot free), and
    (c) the declaration dynamically: after concrete steps every
    non-sentinel value must sit in [0, declared_max].  Skipped (clean)
    for protocols that declare no NARROW_LEAVES and run int32 lanes."""
    import numpy as np

    findings = []
    # (a) engine lanes: the plan is computed from (N, n_msg_types), so a
    # mismatch means someone forced narrow_lanes past the bounds
    lanes = getattr(net, "lanes", None)
    if lanes is not None:
        bounds = (
            ("idx", max(0, net.n_nodes - 1), "node index"),
            ("mtype", max(0, net.protocol.n_msg_types() - 1),
             "message type"),
        )
        for attr, bound, what in bounds:
            dt = np.dtype(getattr(lanes, attr))
            if np.issubdtype(dt, np.integer) and bound > np.iinfo(dt).max:
                f = _mk("SL901", path, line,
                        f"[{name}] engine lane '{attr}' stores {what} "
                        f"values up to {bound} in {dt} (max "
                        f"{np.iinfo(dt).max}) — the lane plan was "
                        "overridden past its bound", suppress)
                if f:
                    findings.append(f)
    specs = tuple(getattr(net.protocol, "NARROW_LEAVES", ()) or ())
    if not specs:
        return findings
    proto = state.proto
    if not isinstance(proto, dict):
        f = _mk("SL901", path, line,
                f"[{name}] declares NARROW_LEAVES but state.proto is not "
                "a dict, so the leaves cannot exist", suppress)
        return findings + ([f] if f else [])
    # (b) static: declaration vs the live initial state
    for spec in specs:
        want = np.dtype(spec.dtype)
        info = np.iinfo(want)
        headroom = info.max - (1 if spec.sentinel else 0)
        if int(spec.declared_max) > headroom:
            f = _mk("SL901", path, line,
                    f"[{name}] NarrowLeaf '{spec.name}' declares max "
                    f"{spec.declared_max} but {want} holds only "
                    f"{headroom}"
                    f"{' (top value reserved for the sentinel)' if spec.sentinel else ''}",
                    suppress)
            if f:
                findings.append(f)
        if spec.name not in proto:
            f = _mk("SL901", path, line,
                    f"[{name}] NarrowLeaf '{spec.name}' is declared but "
                    "absent from the initial state.proto (config-gated "
                    "leaves are fine at runtime, but the registry entry "
                    "should exercise every declaration)", suppress)
            if f:
                findings.append(f)
            continue
        live = np.dtype(proto[spec.name].dtype)
        if live != want:
            f = _mk("SL901", path, line,
                    f"[{name}] NarrowLeaf '{spec.name}' declares {want} "
                    f"but the live leaf is {live} — proto_init forgot "
                    "narrow_proto(), or the declaration is stale",
                    suppress)
            if f:
                findings.append(f)
    if findings:
        return findings
    # (c) dynamic: concrete steps must keep every non-sentinel value in
    # the declared range (the bound the static audit trusted)
    try:
        stepped = state
        for _ in range(8):
            stepped = net.step(stepped)
    except Exception as e:
        f = _mk("SL901", path, line,
                f"[{name}] concrete stepping for the narrow-range check "
                f"failed: {type(e).__name__}: {e}", suppress)
        return [f] if f else []
    for spec in specs:
        if spec.name not in stepped.proto:
            continue  # disappearance is SL401's finding
        arr = np.asarray(stepped.proto[spec.name])
        if spec.sentinel:
            arr = arr[arr != np.iinfo(arr.dtype).max]
        if arr.size and (
            int(arr.min()) < 0 or int(arr.max()) > int(spec.declared_max)
        ):
            f = _mk("SL901", path, line,
                    f"[{name}] NarrowLeaf '{spec.name}' observed values "
                    f"in [{int(arr.min())}, {int(arr.max())}] after 8 "
                    f"concrete steps, outside its declared "
                    f"[0, {spec.declared_max}] — the bound the dtype "
                    "choice rests on is wrong", suppress)
            if f:
                findings.append(f)
    return findings


def _check_jump_safety(jax, name, net, state, path, line, suppress):
    """SL1201: TICK_INTERVAL=None is the jump-safety declaration — the
    singleton next-arrival fast path (_step_jump) and the batched
    consensus jump both skip inter-arrival ticks OUTRIGHT on its
    strength.  A skipped tick has empty occupancy by construction, but
    tick_beat does not read occupancy: anything it writes would have run
    on those ticks in the ungated loop, so the declaration is only sound
    when the traced tick_beat is a structural no-op (every output leaf
    the SAME jaxpr variable as its input — the SL402 passthrough
    criterion).  Declaring BEAT_PERIOD alongside TICK_INTERVAL=None is
    the same contradiction stated twice and is flagged on its own."""
    if net.protocol.TICK_INTERVAL is not None:
        return []
    findings = []
    if getattr(net.protocol, "BEAT_PERIOD", None) is not None:
        f = _mk("SL1201", path, line,
                f"[{name}] declares TICK_INTERVAL=None (jumpable) AND "
                f"BEAT_PERIOD={net.protocol.BEAT_PERIOD}: periodic beat "
                "work contradicts the empty-tick declaration the jump "
                "paths rely on", suppress)
        if f:
            findings.append(f)
    try:
        closed, out_shape = jax.make_jaxpr(
            lambda s: net.protocol.tick_beat(net, s), return_shape=True
        )(state)
    except Exception as e:
        f = _mk("SL1201", path, line,
                f"[{name}] tick_beat failed tracing for the jump-safety "
                f"audit: {type(e).__name__}: {e}", suppress)
        return findings + ([f] if f else [])
    if jax.tree_util.tree_structure(out_shape) != jax.tree_util.tree_structure(
        state
    ):
        f = _mk("SL1201", path, line,
                f"[{name}] tick_beat changes the SimState tree structure "
                "on a TICK_INTERVAL=None protocol — the jump paths skip "
                "its per-tick effects entirely", suppress)
        return findings + ([f] if f else [])
    invars = closed.jaxpr.invars
    outvars = closed.jaxpr.outvars
    touched = [k for k in range(len(outvars)) if outvars[k] is not invars[k]]
    if touched:
        leaf_names = [p for p, _ in _leaf_paths(jax, state)]
        names = ", ".join(
            leaf_names[k] if k < len(leaf_names) else f"leaf {k}"
            for k in touched[:_MAX_LEAF_REPORTS]
        )
        more = (
            "" if len(touched) <= _MAX_LEAF_REPORTS
            else f" (+{len(touched) - _MAX_LEAF_REPORTS} more)"
        )
        f = _mk("SL1201", path, line,
                f"[{name}] declares TICK_INTERVAL=None but tick_beat is "
                f"not a no-op: {len(touched)} leaf(s) are not input "
                f"passthroughs ({names}{more}).  The next-arrival jump "
                "skips empty-occupancy ticks wholesale, so this per-tick "
                "work would silently vanish on the jumped path; declare "
                "TICK_INTERVAL/BEAT_PERIOD instead", suppress)
        if f:
            findings.append(f)
    return findings


def _check_recompile(jax, name, net, state, out_shape, path, line, suppress):
    """SL404: step output avals == input avals (jit-cache stability) and
    trace determinism."""
    findings = []
    if out_shape is not None:
        diffs = _diff_fingerprints(
            _fingerprint(jax, state), _fingerprint(jax, out_shape)
        )
        for d in diffs[:_MAX_LEAF_REPORTS]:
            f = _mk("SL404", path, line,
                    f"[{name}] step() output aval drifts from input "
                    f"(chained run_ms will recompile every call): {d}",
                    suppress)
            if f:
                findings.append(f)
        if diffs:
            return findings
    try:
        j1 = str(jax.make_jaxpr(net.step)(state))
        j2 = str(jax.make_jaxpr(net.step)(state))
    except Exception as e:
        f = _mk("SL404", path, line,
                f"[{name}] step() failed tracing: {type(e).__name__}: {e}",
                suppress)
        return [f] if f else []
    if j1 != j2:
        f = _mk("SL404", path, line,
                f"[{name}] step() traces to different jaxprs on identical "
                "inputs (nondeterministic trace: unordered dict/set "
                "iteration in a kernel?)", suppress)
        if f:
            findings.append(f)
    return findings


def check_entry(entry, root: str = ".") -> List[Finding]:
    """Run SL401-SL404 + SL406-SL407 + SL701 + SL901 + SL1201 for one
    registry entry; []
    when clean or when the entry opts out of contract checks (standalone
    engines)."""
    jax = _cpu_jax()
    if not entry.contract_checks:
        return []
    net, state = entry.factory()
    path, line = _proto_location(net.protocol)
    try:
        path = os.path.relpath(path, root)
    except ValueError:
        pass
    suppress = set(getattr(net.protocol, "SIMLINT_SUPPRESS", ()) or ())

    findings, out_shape = _check_structure(
        jax, entry.name, net, state, path, line, suppress
    )
    findings += _check_msg_ownership(
        jax, entry.name, net, state, path, line, suppress
    )
    findings += _check_telemetry_neutral(
        jax, entry.name, net, state, path, line, suppress
    )
    findings += _check_fault_neutral(
        jax, entry.name, net, state, path, line, suppress
    )
    findings += _check_fault_deliver_ownership(
        jax, entry.name, net, state, path, line, suppress
    )
    findings += _check_derived_cache(
        jax, entry.name, net, state, path, line, suppress
    )
    findings += _check_narrow_overflow(
        jax, entry.name, net, state, path, line, suppress
    )
    findings += _check_jump_safety(
        jax, entry.name, net, state, path, line, suppress
    )
    findings += _check_recompile(
        jax, entry.name, net, state, out_shape, path, line, suppress
    )
    return findings


def check_all(root: str = ".", names=None) -> List[Finding]:
    """Contract-check every registered batched protocol (or the named
    subset).  Imports the registry lazily so `--skip-contracts` runs
    never pay for protocol imports."""
    from ..core.registries import registry_batched_protocols

    findings: List[Finding] = []
    for entry in registry_batched_protocols.entries():
        if names and entry.name not in names:
            continue
        findings.extend(check_entry(entry, root=root))
    return findings

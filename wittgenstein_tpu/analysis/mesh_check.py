"""2D-mesh replicated-leaf audit (SL1001).

The composed (replicas, nodes) mesh (parallel.mesh2d) places every
state leaf by ONE classification rule: node columns shard on the node
axis, everything else replicates along it, with the engine-owned
message store / telemetry / fault side-cars excluded BY NAME
(node_shard._MESSAGE_STORE_FIELDS) because a wheel dimension can
coincide with n_nodes.  That name-based exclusion is the audit surface:
it silently mis-places a leaf the day a protocol mints a proto-dict
field whose path contains an engine store-field name (the substring
match would REPLICATE a genuinely node-indexed array — correctness
survives, the 1/P memory win silently dies for that leaf), or the day a
store field is renamed and its exclusion entry goes stale (exempting
nothing, while a future field reusing the name inherits the exemption).

SL1001 closes the loop per registered protocol, at the same small
analysis scale the other dynamic passes use:

- **classification totality + stacked/single agreement** — every leaf
  of the entry's state classifies identically whether viewed as a
  single simulation or as a stacked replica batch (a disagreement means
  the leading-axis offset logic broke for that shape);
- **proto-dict name collisions** — no protocol-owned leaf (under
  ``.proto[``) may match a _MESSAGE_STORE_FIELDS exclusion: the
  side-car names belong to the engine, and a colliding protocol field
  would be silently replicated along the node axis;
- **stale exclusions** — every _MESSAGE_STORE_FIELDS entry must still
  name at least one live leaf across the audited states (checked once
  over the whole registry sweep, anchored at node_shard.py).

Protocol-level suppression: list "SL1001" in the class's
SIMLINT_SUPPRESS tuple (same mechanism as the other dynamic rules).
"""

from __future__ import annotations

import os
from typing import List

from .contracts import _cpu_jax, _mk, _proto_location
from .findings import Finding

_MAX_LEAF_REPORTS = 4


def check_entry_mesh(entry, root: str = ".", _stale_seen=None) -> List[Finding]:
    """SL1001 for one registry entry; [] when clean or when the entry
    opts out of contract checks (standalone engines have no generic
    SimState to place on the mesh)."""
    jax = _cpu_jax()
    if not entry.contract_checks:
        return []

    from ..parallel.mesh2d import classify_leaf
    from ..parallel.node_shard import _MESSAGE_STORE_FIELDS

    net, state = entry.factory()
    path, line = _proto_location(net.protocol)
    try:
        path = os.path.relpath(path, root)
    except ValueError:
        pass
    suppress = set(getattr(net.protocol, "SIMLINT_SUPPRESS", ()) or ())
    if "SL1001" in suppress:
        return []

    findings: List[Finding] = []
    n = net.n_nodes
    flat = list(jax.tree_util.tree_flatten_with_path(state)[0])
    # plain entries carry empty tele/fault side-cars (zero leaves), so
    # the audit arms telemetry the way checkpoint_check does: the tele
    # counter rows must classify as replicated-along-nodes and their
    # exclusion entries must register as live, not stale
    if getattr(net, "telemetry", None) is None:
        from ..telemetry.state import TelemetryConfig

        try:
            _tnet, tstate = net.with_telemetry(
                state, TelemetryConfig(snapshots=0)
            )
            flat += list(jax.tree_util.tree_flatten_with_path(tstate)[0])
        except Exception as e:  # noqa: BLE001 — instrumentation failure
            f = _mk("SL1001", path, line,
                    f"[{entry.name}] telemetry instrumentation failed "
                    f"while arming the side-car mesh audit: "
                    f"{type(e).__name__}: {e}", suppress)
            if f:
                findings.append(f)

    disagree, collide = [], []
    for kp, leaf in flat:
        key = jax.tree_util.keystr(kp)
        shape = tuple(getattr(leaf, "shape", ()))
        if _stale_seen is not None:
            for f in _MESSAGE_STORE_FIELDS:
                if f in key:
                    _stale_seen.add(f)
        single = classify_leaf(key, shape, n, stacked=False)
        stacked = classify_leaf(key, (2,) + shape, n, stacked=True)
        # the single-state classes map 1:1 onto the stacked ones:
        # node-column stays node-column, replicated becomes replica-row
        want = "node-column" if single == "node-column" else "replica-row"
        if stacked != want:
            disagree.append((key, single, stacked))
        if key.startswith(".proto[") and any(
            f in key for f in _MESSAGE_STORE_FIELDS
        ):
            collide.append(key)

    for key, single, stacked in disagree[:_MAX_LEAF_REPORTS]:
        f = _mk("SL1001", path, line,
                f"[{entry.name}] leaf {key!r} classifies as {single!r} "
                f"single-state but {stacked!r} stacked — the mesh2d "
                "leading-axis offset logic mis-places this shape",
                suppress)
        if f:
            findings.append(f)
    for key in collide[:_MAX_LEAF_REPORTS]:
        f = _mk("SL1001", path, line,
                f"[{entry.name}] protocol-owned leaf {key!r} collides "
                "with an engine _MESSAGE_STORE_FIELDS name — mesh2d "
                "would silently REPLICATE it along the node axis, "
                "forfeiting its 1/P share of the memory budget; rename "
                "the protocol field", suppress)
        if f:
            findings.append(f)
    return findings


def check_mesh_layout(root: str = ".", names=None) -> List[Finding]:
    """SL1001 over every registered batched protocol (or the named
    subset), plus the registry-wide stale-exclusion sweep."""
    from ..core.registries import registry_batched_protocols
    from ..parallel import node_shard
    from ..parallel.node_shard import _MESSAGE_STORE_FIELDS
    from .findings import Severity

    findings: List[Finding] = []
    seen: set = set()
    audited = False
    for entry in registry_batched_protocols.entries():
        if names and entry.name not in names:
            continue
        if entry.contract_checks:
            audited = True
        findings.extend(check_entry_mesh(entry, root=root, _stale_seen=seen))
    # stale exclusions only mean something over the FULL sweep: a name
    # subset legitimately misses side-car fields of unselected entries
    if audited and not names:
        ns_path = node_shard.__file__
        try:
            ns_path = os.path.relpath(ns_path, root)
        except ValueError:
            pass
        for field in _MESSAGE_STORE_FIELDS:
            if field in seen:
                continue
            findings.append(Finding(
                rule="SL1001", path=ns_path, line=1,
                message=(
                    f"_MESSAGE_STORE_FIELDS entry {field!r} matched no "
                    "leaf of any registered protocol's state — a stale "
                    "exclusion exempts nothing today and silently "
                    "exempts a future leaf that reuses the name"
                ),
                severity=Severity.ERROR,
            ))
    return findings

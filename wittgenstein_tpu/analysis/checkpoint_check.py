"""Checkpoint completeness check (SL501).

Durable runs (wittgenstein_tpu.runtime) rest on one claim: a checkpoint
holds EVERYTHING the engine needs to resume bit-identically.  That claim
silently breaks the day someone adds a SimState field and forgets that
`engine.checkpoint.save_state` flattens whatever the pytree exposes — a
leaf hidden behind a custom flatten, or one declared ephemeral years ago
for a reason that no longer holds, resumes as its template value and the
divergence surfaces three experiments later as "the resumed sweep
doesn't match".

SL501 closes the loop per registered protocol, at the same small
analysis scale the other dynamic passes use:

- **save coverage** — every leaf of the entry's state tree must land in
  the saved archive under its tree path, or be declared in
  `engine.checkpoint.EPHEMERAL_LEAVES`;
- **stale declarations** — every EPHEMERAL_LEAVES entry must still name
  a real leaf (a stale declaration would silently exempt a future field
  that reuses the name);
- **bitwise roundtrip** — save -> load must reproduce every persisted
  leaf bit-for-bit (shape, dtype, and payload bytes).

Fault-enabled registry entries exercise the fault side-car lane; for
plain entries the check additionally arms telemetry
(`with_telemetry`, snapshots=0) so the tele side-car's persistence is
covered even though no registry entry ships instrumented by default.

Protocol-level suppression: list "SL501" in the class's
SIMLINT_SUPPRESS tuple (same mechanism as the other dynamic rules).
"""

from __future__ import annotations

import os
import tempfile
from typing import List

from .contracts import _cpu_jax, _leaf_paths, _mk, _proto_location
from .findings import Finding

_MAX_LEAF_REPORTS = 4


def _check_state_checkpoints(
    jax, name, state, tag, path, line, suppress
) -> List[Finding]:
    """Save `state`, assert key coverage and a bitwise roundtrip."""
    import numpy as np

    from ..engine import checkpoint as ck

    findings: List[Finding] = []
    # _leaf_paths uses keystr ('.a.b' / '[0]'); save_state keys by its own
    # _path_str — compare with the real keying so the check is against
    # what actually lands in the archive
    flat = jax.tree_util.tree_flatten_with_path(state)[0]
    keys = [ck._path_str(p) for p, _ in flat]

    with tempfile.TemporaryDirectory(prefix="simlint_sl501_") as td:
        dest = os.path.join(td, "state.npz")
        try:
            ck.save_state(state, dest)
        except Exception as e:
            f = _mk("SL501", path, line,
                    f"[{name}] save_state failed on the {tag} state: "
                    f"{type(e).__name__}: {e}", suppress)
            return [f] if f else []

        with np.load(dest, allow_pickle=False) as data:
            stored = set(data.files)
        missing = [k for k in keys
                   if k not in stored and k not in ck.EPHEMERAL_LEAVES]
        for k in missing[:_MAX_LEAF_REPORTS]:
            f = _mk("SL501", path, line,
                    f"[{name}] {tag} state leaf {k!r} is not persisted by "
                    "save_state and not declared in "
                    "checkpoint.EPHEMERAL_LEAVES — a resumed run would "
                    "silently reset it to the template value", suppress)
            if f:
                findings.append(f)
        if len(missing) > _MAX_LEAF_REPORTS:
            f = _mk("SL501", path, line,
                    f"[{name}] ... and {len(missing) - _MAX_LEAF_REPORTS} "
                    "more unpersisted leaves", suppress)
            if f:
                findings.append(f)

        stale = [e for e in sorted(ck.EPHEMERAL_LEAVES) if e not in keys]
        for e in stale[:_MAX_LEAF_REPORTS]:
            f = _mk("SL501", path, line,
                    f"[{name}] EPHEMERAL_LEAVES declares {e!r} but the "
                    f"{tag} state has no such leaf — remove the stale "
                    "declaration before it exempts a future field",
                    suppress)
            if f:
                findings.append(f)
        if missing:
            return findings  # roundtrip would only re-report the gap

        try:
            restored = ck.load_state(state, dest)
        except Exception as e:
            f = _mk("SL501", path, line,
                    f"[{name}] load_state failed roundtripping the {tag} "
                    f"state: {type(e).__name__}: {e}", suppress)
            if f:
                findings.append(f)
            return findings

    for (p, a), (_, b) in zip(
        _leaf_paths(jax, state), _leaf_paths(jax, restored)
    ):
        na, nb = np.asarray(a), np.asarray(b)
        if (na.shape != nb.shape or na.dtype != nb.dtype
                or na.tobytes() != nb.tobytes()):
            f = _mk("SL501", path, line,
                    f"[{name}] {tag} state leaf {p} does not roundtrip "
                    "bitwise through save_state/load_state", suppress)
            if f:
                findings.append(f)
            break
    return findings


def check_entry_checkpoint(entry, root: str = ".") -> List[Finding]:
    """SL501 for one registry entry; [] when clean or when the entry
    opts out of contract checks (standalone engines checkpoint through
    the same save_state path but have no generic SimState contract)."""
    jax = _cpu_jax()
    if not entry.contract_checks:
        return []
    net, state = entry.factory()
    path, line = _proto_location(net.protocol)
    try:
        path = os.path.relpath(path, root)
    except ValueError:
        pass
    suppress = set(getattr(net.protocol, "SIMLINT_SUPPRESS", ()) or ())
    if "SL501" in suppress:
        return []

    findings = _check_state_checkpoints(
        jax, entry.name, state, "plain", path, line, suppress
    )

    # plain entries also get the telemetry side-car armed, so the tele
    # lane's persistence is checked; fault-lane entries already carry
    # their side-car from the factory
    if getattr(net, "tele", None) is None and hasattr(net, "with_telemetry"):
        from ..telemetry.state import TelemetryConfig

        try:
            _tnet, tstate = net.with_telemetry(
                state, TelemetryConfig(snapshots=0)
            )
        except Exception as e:
            f = _mk("SL501", path, line,
                    f"[{entry.name}] telemetry instrumentation failed "
                    f"while arming the side-car checkpoint check: "
                    f"{type(e).__name__}: {e}", suppress)
            return findings + ([f] if f else [])
        findings += _check_state_checkpoints(
            jax, entry.name, tstate, "telemetry-armed", path, line, suppress
        )
    return findings


def check_checkpoints(root: str = ".", names=None) -> List[Finding]:
    """SL501 over every registered batched protocol (or the named
    subset)."""
    from ..core.registries import registry_batched_protocols

    findings: List[Finding] = []
    for entry in registry_batched_protocols.entries():
        if names and entry.name not in names:
            continue
        findings.extend(check_entry_checkpoint(entry, root=root))
    return findings

"""SL601: engine phase annotations — present AND bit-neutral.

The cost-attribution layer (profiling/, bench --phase-profile) only
works if (a) every engine kernel phase is wrapped in its
`jax.named_scope` marker (engine.core.ENGINE_PHASE_SCOPES), so jaxprs /
HLO metadata / device profiles can attribute ops to phases, and (b) the
markers are trace-time metadata ONLY — flipping `annotate` off must not
change a single computed bit, or the profile measures a different
program than production runs.

Presence is checked on the real trace: `net.step` is traced to a jaxpr
and every equation's `source_info.name_stack` is collected, recursing
into sub-jaxprs (scan/while/cond bodies carry the scopes; the outer
control-flow equation's own stack is empty).  A phase scope is required
only when the corresponding protocol hook actually traces equations —
a trivial `tick_beat` that returns its input adds no ops, so there is
nothing to attribute and no scope to demand.

Neutrality mirrors SL406's two-level check: abstract (`eval_shape`
fingerprints of the annotated vs. un-annotated step must match) and
concrete (one full step must be bitwise identical with `annotate`
flipped off).

If this jax version exposes no `name_stack` on source_info, the
presence half is skipped (API drift guard) — neutrality still runs.
"""

from __future__ import annotations

import copy
import os
from typing import List, Optional, Set

from .contracts import (
    _cpu_jax,
    _diff_fingerprints,
    _fingerprint,
    _leaf_paths,
    _mk,
    _proto_location,
)
from .findings import Finding

# scopes every annotated step must carry; the rest (telemetry, faults,
# jump, post) appear only when the matching feature / hook traces ops
_ALWAYS_REQUIRED = ("witt.delivery",)


def _sub_jaxprs(params: dict):
    """Sub-jaxprs reachable from an equation's params: scan/while/cond
    carry theirs as ClosedJaxpr (`.jaxpr`) or raw Jaxpr (`.eqns`) values,
    sometimes inside tuples (cond branches)."""
    stack = list(params.values())
    while stack:
        x = stack.pop()
        if isinstance(x, (tuple, list)):
            stack.extend(x)
        elif hasattr(x, "eqns"):
            yield x
        elif hasattr(x, "jaxpr") and hasattr(getattr(x, "jaxpr"), "eqns"):
            yield x.jaxpr


def _collect_scopes(jaxpr, out: Set[str]) -> bool:
    """Gather every equation's name-stack string into `out`, recursing
    through control-flow sub-jaxprs.  Returns False when this jax build
    exposes no name_stack at all (presence check must be skipped)."""
    saw_attr = not jaxpr.eqns  # vacuously fine on an empty body
    for eqn in jaxpr.eqns:
        ns = getattr(eqn.source_info, "name_stack", None)
        if ns is not None:
            saw_attr = True
            s = str(ns)
            if s:
                out.add(s)
        for sub in _sub_jaxprs(eqn.params):
            if _collect_scopes(sub, out):
                saw_attr = True
    return saw_attr


def _hook_traces_ops(jax, fn, state) -> bool:
    """Does `fn(state)` trace to at least one equation?  A hook that is
    a pure passthrough (pingpong's tick_beat) contributes no ops, so its
    phase scope cannot appear in the step jaxpr and must not be
    required.  Errors count as 'yes' — the step trace below will anchor
    the real finding."""
    try:
        closed = jax.make_jaxpr(fn)(state)
    except Exception:
        return True
    return bool(closed.jaxpr.eqns)


def _check_presence(jax, name, net, state, path, line, suppress):
    """Every live engine phase appears as a named scope in step()'s
    jaxpr (nested scopes substring-match, per ENGINE_PHASE_SCOPES)."""
    findings = []
    if not getattr(net, "annotate", True):
        f = _mk("SL601", path, line,
                f"[{name}] engine built with annotate=False by its "
                "registry factory — phase attribution is dark for this "
                "protocol; construct with annotate=True (the default)",
                suppress)
        return [f] if f else []
    try:
        closed = jax.make_jaxpr(net.step)(state)
    except Exception as e:
        f = _mk("SL601", path, line,
                f"[{name}] step() failed tracing for the annotation "
                f"scan: {type(e).__name__}: {e}", suppress)
        return [f] if f else []
    scopes: Set[str] = set()
    if not _collect_scopes(closed.jaxpr, scopes):
        return []  # jax without name stacks: nothing to assert against
    required = list(_ALWAYS_REQUIRED)
    if _hook_traces_ops(jax, lambda s: net.protocol.tick(net, s), state):
        required.append("witt.protocol_tick")
    if _hook_traces_ops(jax, lambda s: net.protocol.tick_beat(net, s), state):
        required.append("witt.beat")
    for want in required:
        if not any(want in s for s in scopes):
            f = _mk("SL601", path, line,
                    f"[{name}] engine phase scope '{want}' is missing "
                    f"from step()'s jaxpr (saw: {sorted(scopes)[:6]}); "
                    "the phase body must run under "
                    "BatchedNetwork._scope(...)", suppress)
            if f:
                findings.append(f)
    return findings


def _check_neutrality(jax, name, net, state, path, line, suppress):
    """Annotations must be bit-neutral: the annotate=False twin of the
    same engine must produce identical avals (abstract) and identical
    bits after one concrete step (the SL406 pattern)."""
    import numpy as np

    findings = []
    net_off = copy.copy(net)
    net_off.annotate = False
    try:
        out_on = jax.eval_shape(net.step, state)
        out_off = jax.eval_shape(net_off.step, state)
    except Exception as e:
        f = _mk("SL601", path, line,
                f"[{name}] annotate-off step failed abstract "
                f"evaluation: {type(e).__name__}: {e}", suppress)
        return [f] if f else []
    diffs = _diff_fingerprints(_fingerprint(jax, out_on),
                               _fingerprint(jax, out_off))
    for d in diffs[:4]:
        f = _mk("SL601", path, line,
                f"[{name}] annotations change a leaf aval: {d}", suppress)
        if f:
            findings.append(f)
    if diffs:
        return findings

    s_on = net.step(state)
    s_off = net_off.step(state)
    for (p, a), (_, b) in zip(_leaf_paths(jax, s_on),
                              _leaf_paths(jax, s_off)):
        if not np.array_equal(np.asarray(a), np.asarray(b)):
            f = _mk("SL601", path, line,
                    f"[{name}] annotations are not bit-neutral: leaf "
                    f"{p} differs bitwise between annotate=True and "
                    "annotate=False after one step (a named_scope body "
                    "must not change computation)", suppress)
            if f:
                findings.append(f)
            break
    return findings


def check_annotations_entry(entry, root: str = ".") -> List[Finding]:
    """SL601 for one registry entry; [] when clean or when the entry
    opts out of contract checks (standalone engines have no phase
    scopes to audit)."""
    jax = _cpu_jax()
    if not entry.contract_checks:
        return []
    net, state = entry.factory()
    path, line = _proto_location(net.protocol)
    try:
        path = os.path.relpath(path, root)
    except ValueError:
        pass
    suppress = set(getattr(net.protocol, "SIMLINT_SUPPRESS", ()) or ())

    findings = _check_presence(jax, entry.name, net, state, path, line,
                               suppress)
    findings += _check_neutrality(jax, entry.name, net, state, path, line,
                                  suppress)
    return findings


def check_annotations(root: str = ".",
                      names: Optional[List[str]] = None) -> List[Finding]:
    """SL601 over every registered batched protocol (or the subset)."""
    from ..core.registries import registry_batched_protocols

    findings: List[Finding] = []
    for entry in registry_batched_protocols.entries():
        if names and entry.name not in names:
            continue
        findings.extend(check_annotations_entry(entry, root=root))
    return findings

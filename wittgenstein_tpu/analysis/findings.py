"""Finding model, rule catalog, and suppression comments.

Every check in the package reports through `Finding`: a rule id from the
catalog below, a file:line anchor, and a message.  AST findings anchor at
the offending node; abstract-eval findings anchor at the protocol class's
definition line so the report is always clickable.

Suppression is per-line (`# simlint: disable=SL104` on the flagged line,
comma-separated for several rules) or per-file
(`# simlint: disable-file=SL104` anywhere in the file).  Dynamic checks
(SL4xx) accept class-level suppression via the protocol's
`SIMLINT_SUPPRESS` contract metadata (engine/protocol.py).
"""

from __future__ import annotations

import dataclasses
import enum
import json
import re
from typing import Dict, List, Optional


class Severity(enum.Enum):
    ERROR = "error"
    WARNING = "warning"


# rule id -> one-line description (the catalog; docs/static_analysis.md is
# the prose version and tests assert the two stay in sync)
RULES: Dict[str, str] = {
    # -- AST: tracer safety / host purity / dtype drift ----------------------
    "SL101": "tracer-unsafe branch: Python `if`/`while`/`bool()` on a "
    "traced value inside kernel code",
    "SL102": "host impurity in a jit path: time.*/random.*/np.random/print "
    "inside kernel code",
    "SL103": "host conversion of a traced value: float()/int()/.item()/"
    "np.asarray(state...) inside kernel code",
    "SL104": "dtype-drift hazard: dtype-less jnp constructor "
    "(zeros/ones/arange) or weak-typed numeric literal in kernel code",
    # -- AST: protocol contract ---------------------------------------------
    "SL201": "deliver() writes an engine-owned msg_*/ovf_*/wheel column "
    "(the engine owns the message store)",
    "SL202": "tick_beat override without a BEAT_PERIOD/BEAT_SEND_CALLS "
    "declaration in the module (beat gating would corrupt the RNG stream)",
    "SL203": "self.mtype(name) with a name missing from the class's "
    "MSG_TYPES literal",
    "SL204": "payload contract mismatch: Emission(payload=...) with "
    "PAYLOAD_WIDTH 0, or msg_payload indexed past PAYLOAD_WIDTH",
    # -- registry / test coverage meta-rule ----------------------------------
    "SL301": "batched protocol not registered in core/registries.py or "
    "missing a tests/test_* parity file",
    # -- abstract-eval contract checks ---------------------------------------
    "SL401": "kernel hook does not preserve the SimState tree structure, "
    "shapes, or dtypes (weak-type promotion counts)",
    "SL402": "deliver() output msg store is not a passthrough of its input "
    "(jaxpr-level ownership check)",
    "SL403": "telemetry side-car perturbs non-tele state (instrumented run "
    "would not be bit-identical)",
    "SL404": "recompilation sentry: a second trace would miss the jit "
    "cache (output avals drift or trace is not reproducible)",
    "SL405": "RNG-stream audit: tick_beat's latency_arrivals draw count "
    "does not match the declared BEAT_SEND_CALLS",
    "SL406": "fault side-car is not neutral when idle: a fault-enabled "
    "engine on the neutral schedule perturbs non-fault state",
    "SL407": "deliver() writes the fault lane: state.faults leaves must "
    "be pure passthroughs on a fault-enabled delivery view",
    # -- checkpoint durability -----------------------------------------------
    "SL501": "checkpoint completeness: a state leaf is not persisted by "
    "save_state (and not declared in EPHEMERAL_LEAVES), an "
    "EPHEMERAL_LEAVES declaration is stale, or save/load does not "
    "roundtrip bitwise",
    # -- phase annotations ----------------------------------------------------
    "SL601": "engine phase annotations: a live kernel phase is missing its "
    "named-scope marker in the step jaxpr, or annotations are not "
    "bit-neutral (annotate=False twin diverges)",
    # -- derived-cache consistency --------------------------------------------
    "SL701": "derived-cache consistency: a DERIVED_CACHE_LEAVES leaf is "
    "stale after concrete steps (carried cache differs bitwise from "
    "recompute_caches()), missing from proto_init, or uncovered by the "
    "recompute oracle",
    # -- serving scheduler contract -------------------------------------------
    "SL801": "serve batching contract: jobs packed into one batch must "
    "share the exact static-config digest and row leaf signature, and "
    "re-dispatching an identical workload must be a pure run-cache hit "
    "(no recompile-per-batch regression)",
    # -- narrow-dtype overflow audit -------------------------------------------
    "SL901": "narrow-dtype overflow audit: an engine message lane or a "
    "declared NARROW_LEAVES leaf (engine.density) cannot hold its bound "
    "— lane plan overridden past (N-1, n_msg_types-1), declared_max "
    "over the dtype's headroom (sentinel slot included), live leaf "
    "dtype diverging from its declaration, or concrete steps producing "
    "values outside [0, declared_max]",
    # -- 2D-mesh replicated-leaf audit -----------------------------------------
    "SL1001": "mesh replicated-leaf audit (parallel.mesh2d): a state "
    "leaf classifies differently single-state vs stacked, a "
    "protocol-owned proto-dict leaf collides with an engine "
    "_MESSAGE_STORE_FIELDS exclusion name (silently replicated along "
    "the node axis, forfeiting its 1/P memory share), or a store-field "
    "exclusion entry matches no live leaf of any registered protocol "
    "(stale exemption)",
    # -- SLO alert catalog audit ------------------------------------------------
    "SL1101": "SLO alert catalog audit (obs.slo): an alert-capable call "
    "site — fire_violation()/alert() first argument, SLOSpec(name=...), "
    "or an slo=... keyword — names a string literal missing from "
    "REGISTERED_SLOS, so a dashboard keyed on the catalog would "
    "silently miss its alerts",
    # -- jump-safety audit --------------------------------------------------------
    "SL1201": "jump-safety audit: a protocol declaring TICK_INTERVAL=None "
    "whose tick_beat jaxpr is not a no-op (or that also declares "
    "BEAT_PERIOD) — the next-arrival jump paths skip empty-occupancy "
    "ticks wholesale, so per-tick beat work would silently vanish",
    # -- concurrency contract checker (pass 10) ---------------------------------
    "SL1301": "undeclared lock: a threading.Lock/RLock/Condition "
    "construction site missing from the runtime/locks.py registry, or a "
    "make_lock/TracedLock name absent from LOCK_HIERARCHY",
    "SL1302": "lock-order inversion: an acquisition chain — direct or "
    "across function boundaries via call-graph inference — takes a lock "
    "at or below the rank of one already held, inverting the declared "
    "LOCK_HIERARCHY total order (the deadlock-order audit)",
    "SL1303": "blocking work under a dispatch-class lock: compile/lower/"
    "block_until_ready, file I/O, HTTP, time.sleep, or a timeout-less "
    "get()/wait()/join() reachable while a no_blocking lock is held "
    "(the PR-11 compile-race dual: compiles stay OUTSIDE _dispatch_lock)",
    "SL1304": "thread lifecycle: a spawned threading.Thread is neither "
    "daemonized nor joined, or its worker loop has no shutdown path "
    "reachable from stop()/drain (the PR-12 watchdog-leak class)",
    "SL1305": "unguarded shared write: a mutable attribute of a "
    "thread-spawning or lock-owning class is written without holding its "
    "class's named lock at every site, or guarded by different locks at "
    "different sites (UNGUARDED_OK declares documented single-writer "
    "fields)",
    "SL1306": "stale lock registry: a runtime/locks.py site declaration "
    "matches no live lock construction in the tree",
    "SL1307": "yield-point catalog drift: a yield_point() call site names "
    "a point missing from YIELD_POINTS, or a catalog entry has no call "
    "site left in the tree",
    "SL1401": "pinned-regression audit: a scenarios/regressions/*.json "
    "attack pin fails to load, names an unregistered protocol or unknown "
    "objective, carries a genome outside its declared bounds, no longer "
    "strictly beats its pinned baselines, or (contracts mode) lowers to "
    "a FaultState whose digest differs from the pinned plan_digest",
}


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative when produced by the CLI
    line: int
    message: str
    severity: Severity = Severity.ERROR

    def format(self) -> str:
        return (
            f"{self.path}:{self.line}: {self.rule} "
            f"[{self.severity.value}] {self.message}"
        )

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "severity": self.severity.value,
            "message": self.message,
            "summary": RULES.get(self.rule, ""),
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True)


_DISABLE_RE = re.compile(r"#\s*simlint:\s*disable=([A-Z0-9, ]+)")
_DISABLE_FILE_RE = re.compile(r"#\s*simlint:\s*disable-file=([A-Z0-9, ]+)")


def _ids(match_text: str) -> List[str]:
    return [t.strip() for t in match_text.split(",") if t.strip()]


def file_suppressions(source: str) -> List[str]:
    """Rule ids disabled for the whole file."""
    out: List[str] = []
    for m in _DISABLE_FILE_RE.finditer(source):
        out.extend(_ids(m.group(1)))
    return out


def line_suppressions(source_line: str) -> List[str]:
    out: List[str] = []
    for m in _DISABLE_RE.finditer(source_line):
        out.extend(_ids(m.group(1)))
    return out


def apply_suppressions(
    findings: List[Finding], source: str, lines: Optional[List[str]] = None
) -> List[Finding]:
    """Drop findings suppressed by file- or line-level comments."""
    if lines is None:
        lines = source.splitlines()
    file_off = set(file_suppressions(source))
    kept = []
    for f in findings:
        if f.rule in file_off:
            continue
        line = lines[f.line - 1] if 0 < f.line <= len(lines) else ""
        if f.rule in line_suppressions(line):
            continue
        kept.append(f)
    return kept

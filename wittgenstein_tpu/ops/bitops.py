"""Packed-bitset kernels for aggregation protocols.

The batched Handel/GSF state keeps per-node contribution bitsets in an
XOR-relative layout: bit j of node i's vector refers to node (i ^ j).
Under that layout the binary-split level structure (Handel.allSigsAtLevel,
Handel.java:634-647) becomes uniform across nodes — level l occupies bit
block [2^(l-1), 2^l) for every node — and re-addressing a contribution
from sender s's space into receiver r's space is the bit permutation
j -> j ^ (r ^ s), implemented below as a word gather (high bits) plus a
5-stage butterfly (low bits).  All ops are jnp-traceable and vmap over
leading axes.
"""

from __future__ import annotations

import os

import jax.numpy as jnp
import numpy as np
from jax import lax

WORD = 32
_BUTTERFLY_MASKS = np.array(
    [0x55555555, 0x33333333, 0x0F0F0F0F, 0x00FF00FF, 0x0000FFFF], dtype=np.uint32
)

BITOPS_ENV = "WITT_BITOPS"  # "lax" | "pallas" (anything else = auto)


def bitops_backend() -> str:
    """The bitset-kernel backend for the NEXT trace: "lax" or "pallas".

    `WITT_BITOPS=lax|pallas` overrides; otherwise pallas is auto-selected
    on a TPU backend only (the kernels interpret rather than compile
    anywhere else — correct but slow, so CPU/GPU default to lax).  Read
    at trace time, so it is a static program property; the engine folds
    it into `cache_key()` so a flipped env var cannot hit a stale jit
    cache."""
    env = os.environ.get(BITOPS_ENV, "").strip().lower()
    if env in ("lax", "pallas"):
        return env
    try:
        import jax

        return "pallas" if jax.default_backend() == "tpu" else "lax"
    except Exception:  # no backend yet — the safe default
        return "lax"


def _popcount_words_lax(words) -> jnp.ndarray:
    return jnp.sum(
        lax.population_count(words.astype(jnp.uint32)).astype(jnp.int32), axis=-1
    )


def popcount_words(words) -> jnp.ndarray:
    """Total set bits over the last axis of packed uint32 words."""
    if bitops_backend() == "pallas":
        from .bitops_pallas import popcount_words_pallas

        return popcount_words_pallas(words)
    return _popcount_words_lax(words)


def _pack_bool_words_lax(bits) -> jnp.ndarray:
    bits = jnp.asarray(bits, bool)
    w = bits.shape[-1]
    pad = (-w) % WORD
    if pad:
        bits = jnp.concatenate(
            [bits, jnp.zeros(bits.shape[:-1] + (pad,), bool)], axis=-1
        )
    grouped = bits.reshape(bits.shape[:-1] + ((w + pad) // WORD, WORD))
    weights = (jnp.uint32(1) << jnp.arange(WORD, dtype=jnp.uint32))
    return jnp.sum(grouped.astype(jnp.uint32) * weights, axis=-1).astype(
        jnp.uint32
    )


def pack_bool_words(bits) -> jnp.ndarray:
    """Pack a bool vector into uint32 words over the last axis:
    [..., W] bool -> [..., ceil(W/32)] uint32, bit j of word k = element
    32k + j.  (The engine's wheel-occupancy summary; pairs with
    popcount_words / lowest_set_bit.)"""
    if bitops_backend() == "pallas":
        from .bitops_pallas import pack_bool_words_pallas

        return pack_bool_words_pallas(bits)
    return _pack_bool_words_lax(bits)


def _lowest_set_bit_lax(words) -> jnp.ndarray:
    words = words.astype(jnp.uint32)
    word_nz = words != 0
    widx = jnp.argmax(word_nz, axis=-1).astype(jnp.int32)
    wval = jnp.take_along_axis(words, widx[..., None], axis=-1)[..., 0]
    lowbit = _popcount_words_lax(
        ((wval & (-wval).astype(jnp.uint32)) - 1)[..., None]
    )
    return widx * WORD + lowbit


def lowest_set_bit(words) -> jnp.ndarray:
    """Index of the lowest set bit over the last axis of packed [..., w]
    uint32 vectors (undefined when empty — gate on popcount > 0)."""
    if bitops_backend() == "pallas":
        from .bitops_pallas import lowest_set_bit_pallas

        return lowest_set_bit_pallas(words)
    return _lowest_set_bit_lax(words)


def xor_shuffle(words, v):
    """Permute bit positions j -> j ^ v of packed vectors.

    words: [..., W] uint32; v: int32 scalar or [...] batch of xor values
    (dynamic).  Word-level part uses a gather on index ^ (v >> 5); bit-level
    part applies 5 conditional butterfly stages for v & 31.
    """
    words = words.astype(jnp.uint32)
    w = words.shape[-1]
    v = jnp.asarray(v, jnp.int32)
    v_hi = lax.shift_right_logical(v, 5)
    v_lo = v & 31

    idx = jnp.arange(w, dtype=jnp.int32)
    # broadcast v over the leading axes: gather words[..., idx ^ v_hi]
    gathered = jnp.take_along_axis(
        words,
        jnp.broadcast_to(
            idx ^ v_hi[..., None] if v.ndim else idx ^ v_hi,
            words.shape,
        ),
        axis=-1,
    )

    x = gathered
    for b in range(5):
        m = jnp.uint32(_BUTTERFLY_MASKS[b])
        sh = jnp.uint32(1 << b)
        swapped = ((x & m) << sh) | (lax.shift_right_logical(x, sh) & m)
        bit = lax.shift_right_logical(v_lo, b) & 1
        cond = (bit == 1) if v.ndim == 0 else (bit == 1)[..., None]
        x = jnp.where(cond, swapped, x)
    return x


def block_mask(start: int, end: int, n_words: int) -> np.ndarray:
    """Static mask with bits [start, end) set, as packed uint32 words."""
    bits = ((1 << end) - 1) ^ ((1 << start) - 1)
    out = np.zeros(n_words, dtype=np.uint32)
    for w in range(n_words):
        out[w] = (bits >> (32 * w)) & 0xFFFFFFFF
    return out


def level_block_mask(level: int, n_words: int) -> np.ndarray:
    """Mask of level `level`'s block in the XOR layout: bit 0 for level 0,
    bits [2^(l-1), 2^l) for level l >= 1."""
    if level == 0:
        return block_mask(0, 1, n_words)
    return block_mask(1 << (level - 1), 1 << level, n_words)

"""Pallas kernels for the packed-bitset hot path (ops.bitops).

`ops.bitops` auto-selects these on a TPU backend (or when forced with
`WITT_BITOPS=pallas`); the lax implementations remain the
always-available fallback and the bit-identity reference.  Every kernel
here must produce bit-identical results to its lax twin — pinned by
tests/test_bitops_pallas.py, which runs the kernels in interpret mode
on CPU over odd shapes and the all-zero / all-ones edge cases.

Geometry: callers pass arbitrary leading axes over a packed word axis
(`[..., w]` uint32).  The wrappers flatten to `[M, w]` rows and tile the
grid over row blocks only — flagship word widths (w_pad ∈ {1..128} at
4096 nodes) fit a VMEM row comfortably, so the word axis stays whole
per block.  Row blocks are sized to the next power of two up to
`MAX_ROW_BLOCK`; on a real TPU the word axis is additionally padded to
the 128-lane tile (zero words are neutral for all three kernels), which
is what "block specs sized for the flagship shapes" means in practice.

Inside kernels, population counts use the SWAR ladder instead of
`lax.population_count` — Mosaic has no popcount primitive, and the SWAR
form lowers on every backend with identical integer results.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

WORD = 32
MIN_ROW_BLOCK = 8
MAX_ROW_BLOCK = 512
LANE = 128  # TPU minor-dim tile


def _interpret() -> bool:
    """Interpret off-TPU: these kernels only compile under Mosaic."""
    return jax.default_backend() != "tpu"


def _row_block(m: int) -> int:
    """Power-of-two row-block size for M rows, in [MIN, MAX]_ROW_BLOCK."""
    b = 1 << max(0, m - 1).bit_length()
    return max(MIN_ROW_BLOCK, min(MAX_ROW_BLOCK, b))


def _swar_popcount(v):
    """Per-word set-bit count of uint32 lanes (SWAR ladder) -> int32."""
    v = v - ((v >> 1) & jnp.uint32(0x55555555))
    v = (v & jnp.uint32(0x33333333)) + ((v >> 2) & jnp.uint32(0x33333333))
    v = (v + (v >> 4)) & jnp.uint32(0x0F0F0F0F)
    return ((v * jnp.uint32(0x01010101)) >> 24).astype(jnp.int32)


def _rows(x, pad_value, lane_pad: bool):
    """Flatten [..., w] to a row-block-padded [M', w'] plus the slicing
    info to undo it."""
    lead, w = x.shape[:-1], x.shape[-1]
    m = 1
    for d in lead:
        m *= d
    flat = x.reshape(m, w)
    if lane_pad and w % LANE:
        flat = jnp.concatenate(
            [flat, jnp.full((m, (-w) % LANE), pad_value, x.dtype)], axis=-1
        )
    bm = _row_block(m)
    rpad = (-m) % bm
    if rpad:
        flat = jnp.concatenate(
            [flat, jnp.full((rpad, flat.shape[-1]), pad_value, x.dtype)]
        )
    return flat, bm, m, lead


def _popcount_kernel(x_ref, o_ref):
    o_ref[...] = jnp.sum(_swar_popcount(x_ref[...]), axis=-1)


def popcount_words_pallas(words, lane_pad=None) -> jnp.ndarray:
    """Pallas twin of bitops.popcount_words: [..., w] uint32 -> [...]
    int32 total set bits.  Zero lane padding is count-neutral."""
    interpret = _interpret()
    if lane_pad is None:
        lane_pad = not interpret
    flat, bm, m, lead = _rows(
        words.astype(jnp.uint32), jnp.uint32(0), lane_pad
    )
    out = pl.pallas_call(
        _popcount_kernel,
        out_shape=jax.ShapeDtypeStruct((flat.shape[0],), jnp.int32),
        in_specs=[pl.BlockSpec((bm, flat.shape[1]), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((bm,), lambda i: (i,)),
        grid=(flat.shape[0] // bm,),
        interpret=interpret,
    )(flat)
    return out[:m].reshape(lead)


def _pack_kernel(x_ref, o_ref):
    b = x_ref[...]
    bm, wp = b.shape
    grouped = b.reshape(bm, wp // WORD, WORD)
    weights = jnp.uint32(1) << jnp.arange(WORD, dtype=jnp.uint32)
    o_ref[...] = jnp.sum(grouped.astype(jnp.uint32) * weights, axis=-1).astype(
        jnp.uint32
    )


def pack_bool_words_pallas(bits, lane_pad=None) -> jnp.ndarray:
    """Pallas twin of bitops.pack_bool_words: [..., W] bool ->
    [..., ceil(W/32)] uint32.  The bit axis is padded to a word multiple
    exactly like the lax path (extra zero bits pack to zero words, and
    extra lane-pad words are sliced off the output)."""
    interpret = _interpret()
    if lane_pad is None:
        lane_pad = not interpret
    bits = jnp.asarray(bits, bool)
    w = bits.shape[-1]
    nw = (w + WORD - 1) // WORD
    pad = nw * WORD - w
    if pad:
        bits = jnp.concatenate(
            [bits, jnp.zeros(bits.shape[:-1] + (pad,), bool)], axis=-1
        )
    # lane padding happens on the BIT axis (32 bits per output word)
    flat, bm, m, lead = _rows(bits, False, False)
    if lane_pad and nw % LANE:
        wpad = ((-nw) % LANE) * WORD
        flat = jnp.concatenate(
            [flat, jnp.zeros((flat.shape[0], wpad), bool)], axis=-1
        )
    nw_p = flat.shape[1] // WORD
    out = pl.pallas_call(
        _pack_kernel,
        out_shape=jax.ShapeDtypeStruct((flat.shape[0], nw_p), jnp.uint32),
        in_specs=[pl.BlockSpec((bm, flat.shape[1]), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((bm, nw_p), lambda i: (i, 0)),
        grid=(flat.shape[0] // bm,),
        interpret=interpret,
    )(flat)
    return out[:m, :nw].reshape(lead + (nw,))


def _lowest_kernel(x_ref, o_ref):
    v = x_ref[...]
    w = v.shape[-1]
    # per-word lowest-bit index; a zero word yields 32 (popcount of ~0)
    low = v & (~v + jnp.uint32(1))
    lowbit = _swar_popcount(low - jnp.uint32(1))
    idx = jnp.arange(w, dtype=jnp.int32) * WORD + lowbit
    # zero words can't shadow the first set word: any candidate from a
    # later word j > j0 is >= 32*j > 32*j0 + 31
    cand = jnp.where(v != jnp.uint32(0), idx, jnp.int32(WORD * (w + 1)))
    best = jnp.min(cand, axis=-1)
    # empty vectors: the lax path lands on word 0 -> 0*32 + 32
    o_ref[...] = jnp.where(
        jnp.any(v != jnp.uint32(0), axis=-1), best, jnp.int32(WORD)
    )


def lowest_set_bit_pallas(words, lane_pad=None) -> jnp.ndarray:
    """Pallas twin of bitops.lowest_set_bit: [..., w] uint32 -> [...]
    int32 index of the lowest set bit (32 for the all-zero vector,
    matching the lax path's argmax-of-nothing behavior).  Zero lane
    padding is neutral: padded words never win the min."""
    interpret = _interpret()
    if lane_pad is None:
        lane_pad = not interpret
    flat, bm, m, lead = _rows(
        words.astype(jnp.uint32), jnp.uint32(0), lane_pad
    )
    out = pl.pallas_call(
        _lowest_kernel,
        out_shape=jax.ShapeDtypeStruct((flat.shape[0],), jnp.int32),
        in_specs=[pl.BlockSpec((bm, flat.shape[1]), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((bm,), lambda i: (i,)),
        grid=(flat.shape[0] // bm,),
        interpret=interpret,
    )(flat)
    return out[:m].reshape(lead)

"""Vectorized bit-level ops for the batched engine (packed uint32 bitsets)."""

from .bitops import (
    block_mask,
    level_block_mask,
    popcount_words,
    xor_shuffle,
)

__all__ = ["block_mask", "level_block_mask", "popcount_words", "xor_shuffle"]

"""Replica-density dtype plans: narrow carried-state integers, int32 compute.

sims/s/chip is linear in R = replicas-per-chip, and R is bounded by
bytes/replica (profiling/hbm.py) — so every carried `SimState` integer
that fits a narrower dtype is a direct multiplier on the north star.
The house rule that keeps this free of correctness risk:

  * STORAGE is narrow: message-lane columns (`msg_from/msg_to/msg_type`
    and their overflow twins) and protocol leaves declared via
    `BatchedProtocol.NARROW_LEAVES` are carried at the narrowest dtype
    their declared bound fits;
  * COMPUTE is int32: the engine widens the lanes at the delivery-view
    gather and protocols widen declared leaves at kernel-hook entry
    (`widen_tree`) / re-narrow at exit (`narrow_tree`), so every kernel
    body still sees exactly the int32 program it was verified against —
    narrowing is bit-identical by construction, not by luck.

Sentinel mapping: several protocol leaves use INT32_MAX as an "empty"
sentinel (e.g. Handel's `cand_rank`).  A narrowed leaf stores the narrow
dtype's own max instead, and the widen/narrow pair maps the two
loss-lessly; the dtype's max value is therefore RESERVED and the leaf's
declared_max must stay strictly below it (audited by simlint SL901).

The per-protocol capacity sizing that rides with the dtype plan lives in
engine/capacity.py; docs/density.md is the user-facing story.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

INT32_MAX = np.int32(2**31 - 1)

# lanes never narrow below int16: the (8,128)-tile padding on TPU makes
# sub-int16 message lanes a wash, and int8 ids would cap N at 127
_LANE_DTYPES = (np.int16, np.int32)
_LEAF_DTYPES = (np.int8, np.int16, np.int32)


def narrowest_int(max_value: int, *, reserve_sentinel: bool = False,
                  candidates=_LEAF_DTYPES) -> np.dtype:
    """Narrowest signed dtype whose range holds [0, max_value] (plus the
    reserved sentinel slot when asked)."""
    for dt in candidates:
        hi = np.iinfo(dt).max - (1 if reserve_sentinel else 0)
        if max_value <= hi:
            return np.dtype(dt)
    raise ValueError(f"max_value {max_value} does not fit int32")


@dataclasses.dataclass(frozen=True)
class LanePlan:
    """Storage dtypes for the engine's message-lane columns."""

    idx: np.dtype  # msg_from / msg_to / ovf_from / ovf_to
    mtype: np.dtype  # msg_type / ovf_type

    def key(self) -> tuple:
        return (self.idx.name, self.mtype.name)


def lane_plan(n_nodes: int, n_msg_types: int,
              narrow: "bool | None" = None) -> LanePlan:
    """The engine's dtype plan for one (N, mtype-count) config.

    narrow=None means auto (narrow whenever the bound fits); False pins
    the historical all-int32 lanes — the baseline side of the
    narrow-vs-int32 bit-identity sweep."""
    if narrow is None:
        narrow = True
    if not narrow:
        return LanePlan(np.dtype(np.int32), np.dtype(np.int32))
    idx = narrowest_int(max(0, n_nodes - 1), candidates=_LANE_DTYPES)
    mtype = narrowest_int(max(0, n_msg_types - 1))
    return LanePlan(idx, mtype)


@dataclasses.dataclass(frozen=True)
class NarrowLeaf:
    """One protocol leaf's narrowing declaration (the NARROW_LEAVES
    contract): carried at `dtype`, every non-sentinel value provably in
    [0, declared_max] given the protocol's static geometry (N, levels,
    window bounds ...).  simlint SL901 audits the declaration statically
    (headroom incl. the sentinel slot) and dynamically (concrete steps
    must keep every value in range)."""

    name: str
    dtype: str  # "int8" | "int16"
    declared_max: int
    sentinel: bool = False  # INT32_MAX <-> iinfo(dtype).max mapping

    def key(self) -> tuple:
        return (self.name, self.dtype, int(self.declared_max),
                bool(self.sentinel))


def narrow_leaf(x, spec: NarrowLeaf):
    """int32 -> declared storage dtype (sentinel-mapped)."""
    dt = jnp.dtype(spec.dtype)
    y = x.astype(dt)
    if spec.sentinel:
        y = jnp.where(x == INT32_MAX,
                      jnp.asarray(np.iinfo(dt).max, dt), y)
    return y


def widen_leaf(x, spec: NarrowLeaf):
    """Declared storage dtype -> int32 compute (sentinel-mapped)."""
    y = x.astype(jnp.int32)
    if spec.sentinel:
        y = jnp.where(x == np.iinfo(np.dtype(spec.dtype)).max,
                      jnp.asarray(INT32_MAX, jnp.int32), y)
    return y


def narrow_tree(proto: dict, specs) -> dict:
    """Re-narrow declared leaves of a proto dict (absent leaves — e.g.
    config-gated caches — are skipped; everything else passes through)."""
    if not specs:
        return proto
    out = dict(proto)
    for spec in specs:
        if spec.name in out:
            out[spec.name] = narrow_leaf(out[spec.name], spec)
    return out


def widen_tree(proto: dict, specs) -> dict:
    """Widen declared leaves of a proto dict to int32 compute."""
    if not specs:
        return proto
    out = dict(proto)
    for spec in specs:
        if spec.name in out:
            out[spec.name] = widen_leaf(out[spec.name], spec)
    return out

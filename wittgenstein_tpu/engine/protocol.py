"""Batched protocol contract.

The batched analog of core Protocol.java + Message.action: a protocol is a
set of vectorized kernels over the SoA state instead of per-object
callbacks.  `deliver` sees ALL due messages at once (masked rows of the
message ring) and must apply commutative updates; `tick` hosts
periodic-task masks ((t - start) % period == 0 — PeriodicTask.java:40-47
without the queue) and conditional-task predicates (Network.java:543-566)."""

from __future__ import annotations

from typing import Any, List, Tuple

import jax.numpy as jnp

# ---------------------------------------------------------------------------
# Machine-readable contract metadata (consumed by wittgenstein_tpu.analysis).
# These tuples ARE the contract prose above, in checkable form: simlint's
# AST rules and abstract-eval passes import them instead of hard-coding
# field lists, so an engine refactor that moves a column updates the
# checker automatically.
# ---------------------------------------------------------------------------

# SimState fields owned by the ENGINE: protocol hooks must never write them
# (`deliver` returns emissions instead of touching the store; the engine
# ticks counters and the clock).  A protocol with a genuine exception
# declares it in DELIVER_MAY_TOUCH.
ENGINE_OWNED_FIELDS = (
    "time",
    "seed",
    "send_ctr",
    "msg_valid",
    "msg_arrival",
    "msg_from",
    "msg_to",
    "msg_type",
    "msg_payload",
    "whl_fill",
    "ovf_valid",
    "ovf_arrival",
    "ovf_from",
    "ovf_to",
    "ovf_type",
    "ovf_payload",
    "msg_head",
    "dropped",
    "tele",
    "faults",
)

# Hooks traced under jit (tracer-safety rules apply) vs host-side
# construction hooks (plain Python allowed).
KERNEL_HOOKS = ("deliver", "tick", "tick_beat", "tick_post", "all_done")
HOST_HOOKS = ("proto_init", "initial_emissions", "msg_size", "n_msg_types", "mtype")


class BatchedProtocol:
    """Subclass and override.  MSG_TYPES maps message-type names to the int
    codes stored in the ring."""

    MSG_TYPES: List[str] = []
    PAYLOAD_WIDTH: int = 0
    # None = tick() does nothing time-sensitive, so the engine may skip
    # empty milliseconds (jump to the next arrival).  Protocols with
    # periodic/conditional work must set 1 (or their smallest period).
    TICK_INTERVAL: int | None = 1
    # Time coarsening for event-driven protocols (TICK_INTERVAL None):
    # arrivals are delivered together at the next multiple of this grid,
    # delaying each by < TIME_QUANTUM ms.  For protocols whose observables
    # live at the seconds scale (ENR's record propagation), a quantum of
    # a few ms cuts loop iterations by that factor with distortion far
    # inside the distribution-parity tolerance.  1 = exact arrival times.
    TIME_QUANTUM: int = 1
    # Optional beat structure: periodic work (the PeriodicTask analog) that
    # fires only when t % BEAT_PERIOD is in BEAT_RESIDUES goes in
    # tick_beat().  Because every replica advances time in lockstep,
    # run_ms_batched hoists the time loop outside vmap and guards
    # tick_beat with a REAL lax.cond on the (replica-uniform) tick index —
    # off-beat ticks skip the work entirely instead of executing it
    # masked.  tick() must NOT include the beat work when these are set;
    # the generic paths (run_ms, fallback run_ms_batched) call tick_beat
    # every tick, relying on its own on-beat masks for exactness.
    BEAT_PERIOD: int | None = None
    BEAT_RESIDUES: tuple | None = None
    # Number of latency_arrivals calls tick_beat makes.  On off-beat ticks
    # the engine advances send_ctr by this amount so the per-event RNG
    # stream is IDENTICAL to the ungated path (where the masked beat call
    # still ticked the counter) — beat gating changes cost, never draws.
    BEAT_SEND_CALLS: int = 0
    # Engine-owned SimState fields this protocol's deliver() is ALLOWED to
    # write (empty for every current protocol; a future exception must be
    # declared here so simlint's ownership check stays exact).
    DELIVER_MAY_TOUCH: tuple = ()
    # simlint rule ids (e.g. "SL404") suppressed for this protocol's
    # abstract-eval checks — the dynamic analog of the per-line
    # `# simlint: disable=RULE` comment.  Use sparingly, with a comment.
    SIMLINT_SUPPRESS: tuple = ()
    # Names of state.proto leaves that are DERIVED caches: redundant
    # values (candidate-score caches, cached cardinalities) recomputable
    # from the authoritative leaves at any tick boundary.  A protocol
    # declaring leaves here must also override recompute_caches();
    # simlint SL701 steps the protocol concretely and asserts the carried
    # caches match a from-scratch recompute bitwise, so stale-cache bugs
    # can't ship silently.
    DERIVED_CACHE_LEAVES: tuple = ()
    # Narrow-storage declarations (engine.density.NarrowLeaf): proto
    # leaves CARRIED below int32, each with the dtype, the provable value
    # bound given the protocol's static geometry, and whether the leaf
    # uses the INT32_MAX "empty" sentinel (stored as the narrow dtype's
    # max, which is then reserved).  Kernel hooks must call
    # widen_proto()/narrow_proto() at their boundary so every kernel body
    # still computes in int32 — the narrowing is bit-identical by
    # construction.  simlint SL901 audits the declarations (static
    # headroom + concrete-step range check); docs/density.md is the
    # full story.  Usually set per-INSTANCE (the bounds depend on N).
    NARROW_LEAVES: tuple = ()

    def contract(self) -> dict:
        """Machine-readable contract summary (instance-level: factories may
        set BEAT_* dynamically).  This is what simlint audits against."""
        msg_types = self.MSG_TYPES
        return {
            "protocol": type(self).__name__,
            "msg_types": list(msg_types) if msg_types else [],
            "n_msg_types": self.n_msg_types(),
            "payload_width": int(self.PAYLOAD_WIDTH),
            "tick_interval": self.TICK_INTERVAL,
            "time_quantum": int(self.TIME_QUANTUM),
            "beat_period": self.BEAT_PERIOD,
            "beat_residues": (
                tuple(self.BEAT_RESIDUES) if self.BEAT_RESIDUES else None
            ),
            "beat_send_calls": int(self.BEAT_SEND_CALLS),
            "engine_owned_fields": list(ENGINE_OWNED_FIELDS),
            "deliver_may_touch": list(self.DELIVER_MAY_TOUCH),
            "simlint_suppress": list(self.SIMLINT_SUPPRESS),
            "derived_cache_leaves": list(self.DERIVED_CACHE_LEAVES),
            "narrow_leaves": [s.key() for s in self.NARROW_LEAVES],
        }

    def n_msg_types(self) -> int:
        return max(1, len(self.MSG_TYPES))

    def mtype(self, name: str) -> int:
        return self.MSG_TYPES.index(name)

    def msg_size(self, mtype: int) -> int:
        """Bytes per message type (Message.size, Message.java:28 default 1)."""
        return 1

    # -- hooks ---------------------------------------------------------------
    def proto_init(self, n_nodes: int) -> Any:
        """Protocol-state pytree for a fresh replica (Protocol.init)."""
        return ()

    def initial_emissions(self, net, state) -> List:
        """Messages injected at t=0 (the protocol's init() sends)."""
        return []

    def deliver(self, net, state, deliver_mask) -> Tuple[Any, List]:
        """Handle all due messages.  Returns (new state, emissions) — the
        state may update proto and node columns (done_at, down, ...) but must
        not touch msg_* (the engine owns the ring).  `deliver_mask` is
        bool[C] over the message ring; read message fields from state.msg_*."""
        return state, []

    def tick(self, net, state):
        """Per-millisecond hook after delivery (periodic/conditional tasks).
        Returns the full state (may emit via net.apply_emission)."""
        return state

    def tick_beat(self, net, state):
        """Beat-gated periodic work (see BEAT_PERIOD above).  Must be a
        no-op on off-beat ticks (its own masks), since the generic engine
        paths call it every tick."""
        return state

    def tick_post(self, net, state):
        """Per-tick work that must run AFTER tick_beat (protocols whose
        phase order interleaves dense and beat-gated phases, e.g.
        HandelEth2's commit -> start/stop+dissemination -> select)."""
        return state

    def widen_proto(self, proto):
        """NARROW_LEAVES -> int32 compute view of a proto dict (kernel-hook
        entry).  Identity when nothing is declared."""
        if not self.NARROW_LEAVES:
            return proto
        from .density import widen_tree

        return widen_tree(proto, self.NARROW_LEAVES)

    def narrow_proto(self, proto):
        """int32 compute view -> declared storage dtypes (kernel-hook exit
        and proto_init).  Identity when nothing is declared."""
        if not self.NARROW_LEAVES:
            return proto
        from .density import narrow_tree

        return narrow_tree(proto, self.NARROW_LEAVES)

    def recompute_caches(self, state) -> dict:
        """From-scratch values for every DERIVED_CACHE_LEAVES leaf, as a
        {leaf_name: array} dict computed from the authoritative proto
        leaves only.  The consistency oracle for simlint SL701 and the
        cache-equivalence tests; must be traceable."""
        return {}

    # -- termination ----------------------------------------------------------
    def all_done(self, state) -> jnp.ndarray:
        """bool scalar: replica finished (used by sweep drivers to stop)."""
        return jnp.asarray(False)

"""Checkpoint / resume for batched simulation states.

The reference has no checkpointing at all — Protocol.copy() + reseed
gives re-runs, not resume (Protocol.java:13-17; Envelope.java:55 only
muses about on-disk serialization).  Here the whole simulation state is
a pytree of arrays, so checkpointing is a flatten + np.savez: save at
any tick, load, continue — bit-identical to an uninterrupted run (the
engine is deterministic in (state, tick count)).

Format v2 adds durability on top of the bare flatten:

- an embedded JSON **manifest** (``__manifest__``) recording the engine
  layout generation, the side-car signature (telemetry / fault state
  attached or not), per-leaf crc32/shape/dtype, and caller metadata;
- **integrity checksums** — a flipped bit surfaces as
  ``CheckpointCorruptError`` with the offending leaf named, never as a
  numpy shape trace three frames deep;
- **atomic writes** (pid-suffixed temp + ``os.replace``) so a killed
  writer can never leave a torn checkpoint under the final name;
- ``CheckpointManager``: numbered checkpoints, an atomic LATEST
  pointer, bounded retention, and a restore that walks back past
  corrupt files to the newest loadable state.

Works for any pytree whose leaves are arrays/scalars and whose structure
is reproducible from a template state (SimState with nested proto dicts,
EthPowState, stacked/replicated variants).

Layout-stamp compatibility rules (also in docs/durability.md):

- ``ENGINE_LAYOUT`` names the current message-store generation and is
  stamped into every checkpoint.
- A checkpoint stamped with an unknown layout never loads.
- ``timewheel-v1`` (pre-side-car) checkpoints load **only** into a
  template with no telemetry/fault side-cars attached; against an
  instrumented template they fail with ``CheckpointLayoutError`` naming
  the reason, because the side-car counters they lack are part of the
  bit-identity contract.
- A v2 checkpoint whose side-car signature differs from the template's
  (e.g. saved with telemetry ON, loaded with telemetry OFF) fails the
  same way before any leaf is touched.
- ``timewheel-v2`` (pre-narrow-dtype) checkpoints store int32 where the
  v3 layout packs int16/int8 (engine.density); leaves whose shape
  matches cast on load under a range check, with the stored INT32_MAX
  sentinel remapped to the narrow dtype's max.  Handel-family v2
  checkpoints fail on SHAPE instead (``CheckpointShapeError``): the same
  generation regrouped their channel buckets to exact widths, so their
  in_sig leaves genuinely cannot resume — re-run those.
"""

from __future__ import annotations


import json
import os
import time
import zipfile
import zlib
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "name"):
            parts.append(str(p.name))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "key"):
            parts.append(str(p.key))
        else:
            parts.append(str(p))
    return "/".join(parts)


# message-store layout generation stamped into every checkpoint: the
# time-wheel rewrite changed SimState's ring fields ([C] flat ring ->
# [W, B] wheel + [V] overflow lane), so a checkpoint from the flat-ring
# era can never resume on this engine — fail with the reason, not with a
# leaf-by-leaf shape mismatch.  v2 = v1 wheel layout + side-car aware
# manifest (telemetry/fault state signatures + per-leaf checksums).
# v3 = v2 + narrow packed dtypes (engine.density): message lanes and
# declared NARROW_LEAVES store int16/int8 where int32 used to live, with
# INT32_MAX sentinels remapped to the narrow dtype's own max.
LAYOUT_KEY = "__engine_layout__"
MANIFEST_KEY = "__manifest__"
ENGINE_LAYOUT = "timewheel-v3"
# older stamps this engine can still load, with restrictions enforced in
# load_state (v1 predates the side-car signature, so it only loads into
# an uninstrumented template; v2 stored int32 where the template may now
# be narrow — leaves whose SHAPE matches cast on load under a range
# check, sentinel-mapped; a v2 Handel checkpoint fails on shape instead,
# because the exact-width channel buckets regrouped its in_sig leaves)
COMPAT_LAYOUTS = ("timewheel-v1", "timewheel-v2")
MANIFEST_FORMAT = 2

# SimState leaves that a checkpoint may legitimately omit (none today:
# every leaf participates in the bit-identity contract).  simlint SL501
# asserts save/restore completeness against this set — a new SimState
# field must either checkpoint bitwise or be declared here with a reason.
EPHEMERAL_LEAVES: frozenset = frozenset()


class CheckpointError(Exception):
    """Base for every structured checkpoint failure."""


class CheckpointLayoutError(CheckpointError, ValueError):
    """Engine-layout or side-car signature mismatch: the checkpoint was
    written by an incompatible engine generation/configuration."""


class CheckpointCorruptError(CheckpointError, ValueError):
    """The checkpoint file is truncated, unreadable, or fails its
    integrity checksum."""


class CheckpointMissingLeafError(CheckpointError, KeyError):
    """The checkpoint lacks a leaf the template requires."""


class CheckpointShapeError(CheckpointError, ValueError):
    """A stored leaf's shape/dtype disagrees with the template."""


def _sidecar_name(leaf: Any) -> Optional[str]:
    """Side-car signature entry: the attached state's type name, or None
    when the side-car is disabled (an empty-tuple leaf)."""
    if isinstance(leaf, tuple) and len(leaf) == 0:
        return None
    return type(leaf).__name__


def _sidecar_signature(state: Any) -> Dict[str, Optional[str]]:
    sig: Dict[str, Optional[str]] = {}
    for name in ("tele", "faults"):
        if hasattr(state, name):
            sig[name] = _sidecar_name(getattr(state, name))
    return sig


def manifest_trace(manifest: Optional[dict]) -> dict:
    """The correlation ids of a manifest: its explicit ``trace`` block
    when present, else the run_id/job_id/tenant_id keys of its meta
    (how the supervisor stamps them).  Empty dict when untraced."""
    if not manifest:
        return {}
    block = manifest.get("trace")
    if block:
        return dict(block)
    meta = manifest.get("meta") or {}
    return {
        k: meta[k]
        for k in ("run_id", "job_id", "tenant_id")
        if meta.get(k) is not None
    }


def save_state(state: Any, dest: str, meta: Optional[dict] = None) -> dict:
    """Write a state pytree to `dest` (.npz), keyed by tree path.

    Embeds a manifest (layout stamp, side-car signature, per-leaf
    crc32/shape/dtype, caller `meta`) and writes atomically: a crashed
    writer leaves at most a stray temp file, never a torn `dest`.
    Returns the manifest dict.
    """
    leaves = jax.tree_util.tree_flatten_with_path(state)[0]
    arrays = {LAYOUT_KEY: np.asarray(ENGINE_LAYOUT)}
    leaf_info: Dict[str, dict] = {}
    for path, leaf in leaves:
        key = _path_str(path)
        arr = np.asarray(leaf)
        arrays[key] = arr
        leaf_info[key] = {
            "crc32": zlib.crc32(arr.tobytes()) & 0xFFFFFFFF,
            "shape": list(arr.shape),
            "dtype": str(arr.dtype),
        }
    manifest = {
        "format": MANIFEST_FORMAT,
        "layout": ENGINE_LAYOUT,
        "sidecars": _sidecar_signature(state),
        "leaves": leaf_info,
        "meta": dict(meta or {}),
        "created_unix": time.time(),
    }
    # first-class trace block: the obs correlation ids (run_id / job_id
    # / tenant_id) the supervisor stamps into meta, surfaced so ledger
    # tooling (scripts/obs_query.py) can join checkpoints to flight
    # recorder events without knowing the meta layout.  Absent when the
    # writer carried no trace context (format stays 2 — additive key).
    trace = manifest_trace(manifest)
    if trace:
        manifest["trace"] = trace
    arrays[MANIFEST_KEY] = np.asarray(json.dumps(manifest))
    # stream straight to a temp file (savez appends .npz when missing),
    # then atomically replace — never a torn checkpoint, no in-RAM copy;
    # pid suffix keeps concurrent writers off each other's temp file
    tmp = f"{dest}.tmp.{os.getpid()}.npz"
    try:
        np.savez_compressed(tmp, **arrays)
        os.replace(tmp, dest)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)
    return manifest


def _open_npz(src: str):
    try:
        return np.load(src, allow_pickle=False)
    except FileNotFoundError:
        raise
    except (zipfile.BadZipFile, ValueError, OSError, EOFError) as e:
        raise CheckpointCorruptError(
            f"checkpoint {src} is unreadable (truncated or not an npz): {e}"
        ) from e


def read_manifest(src: str) -> Optional[dict]:
    """Return the embedded manifest dict, or None for a pre-manifest
    (v1) checkpoint.  Raises CheckpointCorruptError on unreadable files."""
    with _open_npz(src) as data:
        if MANIFEST_KEY not in data:
            return None
        try:
            return json.loads(str(data[MANIFEST_KEY]))
        except (json.JSONDecodeError, zlib.error, zipfile.BadZipFile) as e:
            raise CheckpointCorruptError(
                f"checkpoint {src} has a corrupt manifest: {e}"
            ) from e


def _check_layout(src: str, found: str, template: Any) -> None:
    if found == ENGINE_LAYOUT:
        return
    if found in COMPAT_LAYOUTS:
        # v1 predates the side-car signature: it can only resume an
        # uninstrumented run — telemetry/fault counters it never stored
        # are part of the bit-identity contract when armed
        sig = _sidecar_signature(template)
        armed = [k for k, v in sig.items() if v is not None]
        if armed:
            raise CheckpointLayoutError(
                f"checkpoint {src} was written by pre-side-car engine "
                f"layout {found!r}, but the template has "
                f"{'/'.join(armed)} side-car state attached; it cannot "
                "resume an instrumented run — re-run instead of resuming"
            )
        return
    raise CheckpointLayoutError(
        f"checkpoint {src} was written by engine layout {found!r}; this "
        f"engine is {ENGINE_LAYOUT!r} (compat: {COMPAT_LAYOUTS}) — "
        "re-run the simulation instead of resuming"
    )


def _coerce_dtype(src: str, key: str, arr, want_dtype):
    """The v2->v3 restore shim: cast a compat-era int32 leaf onto the
    template's narrow dtype (engine.density pattern).

    Valid only for integer->narrower-integer casts where every stored
    value is exactly representable: the source dtype's own max (the
    INT32_MAX "never"/empty sentinel) maps to the narrow dtype's max —
    the value the narrow layout reserves for the same role — and every
    other value must already fit the narrow range.  Anything else is a
    real layout mismatch and keeps the hard CheckpointShapeError."""
    a, w = arr.dtype, np.dtype(want_dtype)
    if not (
        np.issubdtype(a, np.integer)
        and np.issubdtype(w, np.integer)
        and np.iinfo(a).max > np.iinfo(w).max
    ):
        raise CheckpointShapeError(
            f"leaf {key!r}: checkpoint {src} stores dtype {a}, template "
            f"wants {w} — not a compat-era widening to cast down"
        )
    src_max = np.iinfo(a).max
    dst = np.iinfo(w)
    is_sent = arr == src_max
    rest = arr[~is_sent]
    if rest.size and (
        int(rest.min()) < dst.min or int(rest.max()) > dst.max
    ):
        raise CheckpointShapeError(
            f"leaf {key!r}: checkpoint {src} holds values in "
            f"[{int(rest.min())}, {int(rest.max())}] that do not fit the "
            f"template's {w} — the narrow layout cannot represent this "
            "state; re-run instead of resuming"
        )
    out = arr.astype(w)
    out[is_sent] = dst.max
    return out


def load_state(template: Any, src: str, verify: bool = True) -> Any:
    """Rebuild a state pytree with `template`'s structure from `src`.

    Shapes must match the template's leaves; dtypes must match too,
    except when a COMPAT-era checkpoint stores a wider integer than the
    template's narrow leaf (the timewheel-v3 dtype shrink) — those cast
    on load under a range check with sentinel remapping
    (``_coerce_dtype``).  With `verify` (default) every leaf is also
    checked against its manifest crc32 — computed on the STORED bytes,
    before any cast — so silent bit-rot surfaces as
    CheckpointCorruptError naming the leaf.

    Mesh portability: checkpoints store plain host bytes (np.asarray
    gathers every shard), so the file itself carries no mesh — a
    checkpoint written under a 1D replica mesh restores bitwise into a
    2D (replicas, nodes) mesh and back.  Resharding happens HERE, on
    load: when a template leaf is committed to a NamedSharding, the
    restored leaf is device_put onto that same sharding; an unsharded
    template restores exactly as before.  Geometry conflicts stay loud:
    shape/dtype mismatches raise CheckpointShapeError regardless of
    either side's mesh.
    """

    def _restore(arr, tmpl):
        sharding = getattr(tmpl, "sharding", None)
        if isinstance(sharding, jax.sharding.NamedSharding):
            return jax.device_put(jax.numpy.asarray(arr), sharding)
        return jax.numpy.asarray(arr)

    with _open_npz(src) as data:
        found_layout = str(data[LAYOUT_KEY]) if LAYOUT_KEY in data else None
        if found_layout is not None:
            _check_layout(src, found_layout, template)
        compat = found_layout in COMPAT_LAYOUTS
        manifest = None
        if MANIFEST_KEY in data:
            try:
                manifest = json.loads(str(data[MANIFEST_KEY]))
            except (json.JSONDecodeError, zlib.error, zipfile.BadZipFile) as e:
                raise CheckpointCorruptError(
                    f"checkpoint {src} has a corrupt manifest: {e}"
                ) from e
            want_sig = _sidecar_signature(template)
            have_sig = manifest.get("sidecars", {})
            for name, want in want_sig.items():
                have = have_sig.get(name)
                if have != want:
                    raise CheckpointLayoutError(
                        f"checkpoint {src} side-car mismatch on {name!r}: "
                        f"saved with {have!r}, template expects {want!r} — "
                        "arm the run the same way it was saved"
                    )
        leaves_t, _ = jax.tree_util.tree_flatten_with_path(template)
        leaves = []
        for path, leaf in leaves_t:
            key = _path_str(path)
            if key not in data:
                if key in EPHEMERAL_LEAVES:
                    leaves.append(_restore(np.asarray(leaf), leaf))
                    continue
                raise CheckpointMissingLeafError(
                    f"checkpoint {src} is missing leaf {key!r}"
                )
            try:
                arr = data[key]
            except (zipfile.BadZipFile, zlib.error, ValueError, EOFError) as e:
                raise CheckpointCorruptError(
                    f"checkpoint {src} leaf {key!r} is unreadable "
                    f"(truncated archive?): {e}"
                ) from e
            want = np.asarray(leaf)
            if arr.shape != want.shape or (
                arr.dtype != want.dtype and not compat
            ):
                raise CheckpointShapeError(
                    f"leaf {key!r}: checkpoint has {arr.shape}/{arr.dtype}, "
                    f"template wants {want.shape}/{want.dtype}"
                )
            if verify and manifest is not None:
                info = manifest.get("leaves", {}).get(key)
                if info is not None:
                    crc = zlib.crc32(arr.tobytes())
                    if (crc & 0xFFFFFFFF) != info.get("crc32"):
                        raise CheckpointCorruptError(
                            f"checkpoint {src} leaf {key!r} failed its "
                            f"integrity checksum (stored crc32 "
                            f"{info.get('crc32')}, recomputed {crc}) — "
                            "the file is corrupt; falling back to an "
                            "older checkpoint is safe, this one is not"
                        )
            if arr.dtype != want.dtype:
                arr = _coerce_dtype(src, key, arr, want.dtype)
            leaves.append(_restore(arr, leaf))
        return jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(template), leaves
        )


# ---------------------------------------------------------------------------
# CheckpointManager: numbered checkpoints + LATEST pointer + retention


LATEST_NAME = "LATEST"
_CKPT_FMT = "ckpt_{step:08d}.npz"


class CheckpointManager:
    """Numbered checkpoints in one directory with bounded retention.

    - ``save(state, step, meta)`` writes ``ckpt_{step:08d}.npz``
      atomically, then atomically updates the ``LATEST`` pointer file,
      then prunes to the ``keep`` newest files.  A crash between any two
      of those steps leaves a fully consistent directory.
    - ``restore_latest(template)`` walks newest -> oldest, skipping
      checkpoints that fail to load (corrupt / truncated / wrong
      side-car signature), and returns ``(state, step, manifest)`` for
      the newest loadable one, or ``None`` when nothing usable exists.
    """

    def __init__(self, directory: str, keep: int = 3):
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self.directory = directory
        self.keep = keep
        os.makedirs(directory, exist_ok=True)

    def path_for(self, step: int) -> str:
        return os.path.join(self.directory, _CKPT_FMT.format(step=step))

    def steps(self) -> list:
        out = []
        for name in os.listdir(self.directory):
            if name.startswith("ckpt_") and name.endswith(".npz"):
                try:
                    out.append(int(name[len("ckpt_"):-len(".npz")]))
                except ValueError:
                    continue
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        """Step named by the LATEST pointer, falling back to the newest
        file on disk when the pointer is missing/stale."""
        ptr = os.path.join(self.directory, LATEST_NAME)
        try:
            with open(ptr) as f:
                name = f.read().strip()
            step = int(name[len("ckpt_"):-len(".npz")])
            if os.path.exists(self.path_for(step)):
                return step
        except (OSError, ValueError):
            pass
        steps = self.steps()
        return steps[-1] if steps else None

    def save(self, state: Any, step: int, meta: Optional[dict] = None) -> dict:
        manifest = save_state(state, self.path_for(step), meta=meta)
        ptr = os.path.join(self.directory, LATEST_NAME)
        tmp = f"{ptr}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(_CKPT_FMT.format(step=step))
        os.replace(tmp, ptr)
        self._prune()
        return manifest

    def _prune(self) -> None:
        steps = self.steps()
        for step in steps[: max(0, len(steps) - self.keep)]:
            try:
                os.remove(self.path_for(step))
            except OSError:
                pass

    def restore_latest(
        self, template: Any
    ) -> Optional[Tuple[Any, int, Optional[dict]]]:
        errors = []
        for step in reversed(self.steps()):
            path = self.path_for(step)
            try:
                state = load_state(template, path)
                return state, step, read_manifest(path)
            except FileNotFoundError:
                continue
            except CheckpointError as e:
                errors.append((path, e))
                continue
        return None

"""Checkpoint / resume for batched simulation states.

The reference has no checkpointing at all — Protocol.copy() + reseed
gives re-runs, not resume (Protocol.java:13-17; Envelope.java:55 only
muses about on-disk serialization).  Here the whole simulation state is
a pytree of arrays, so checkpointing is a flatten + np.savez: save at
any tick, load, continue — bit-identical to an uninterrupted run (the
engine is deterministic in (state, tick count)).

Works for any pytree whose leaves are arrays/scalars and whose structure
is reproducible from a template state (SimState with nested proto dicts,
EthPowState, stacked/replicated variants).
"""

from __future__ import annotations


import os
from typing import Any

import jax
import numpy as np


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "name"):
            parts.append(str(p.name))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "key"):
            parts.append(str(p.key))
        else:
            parts.append(str(p))
    return "/".join(parts)


# message-store layout generation stamped into every checkpoint: the
# time-wheel rewrite changed SimState's ring fields ([C] flat ring ->
# [W, B] wheel + [V] overflow lane), so a checkpoint from the flat-ring
# era can never resume on this engine — fail with the reason, not with a
# leaf-by-leaf shape mismatch
LAYOUT_KEY = "__engine_layout__"
ENGINE_LAYOUT = "timewheel-v1"


def save_state(state: Any, dest: str) -> None:
    """Write a state pytree to `dest` (.npz), keyed by tree path."""
    leaves = jax.tree_util.tree_flatten_with_path(state)[0]
    arrays = {LAYOUT_KEY: np.asarray(ENGINE_LAYOUT)}
    for path, leaf in leaves:
        arrays[_path_str(path)] = np.asarray(leaf)
    # stream straight to a temp file (savez appends .npz when missing),
    # then atomically replace — never a torn checkpoint, no in-RAM copy
    tmp = dest + ".tmp.npz"
    np.savez_compressed(tmp, **arrays)
    os.replace(tmp, dest)


def load_state(template: Any, src: str) -> Any:
    """Rebuild a state pytree with `template`'s structure from `src`.
    Shapes and dtypes must match the template's leaves."""
    with np.load(src) as data:
        if LAYOUT_KEY in data:
            found = str(data[LAYOUT_KEY])
            if found != ENGINE_LAYOUT:
                raise ValueError(
                    f"checkpoint {src} was written by engine layout "
                    f"{found!r}; this engine is {ENGINE_LAYOUT!r} — re-run "
                    "the simulation instead of resuming"
                )
        leaves_t, treedef = jax.tree_util.tree_flatten_with_path(template)
        leaves = []
        for path, leaf in leaves_t:
            key = _path_str(path)
            if key not in data:
                raise KeyError(f"checkpoint {src} is missing leaf {key!r}")
            arr = data[key]
            want = np.asarray(leaf)
            if arr.shape != want.shape or arr.dtype != want.dtype:
                raise ValueError(
                    f"leaf {key!r}: checkpoint has {arr.shape}/{arr.dtype}, "
                    f"template wants {want.shape}/{want.dtype}"
                )
            leaves.append(jax.numpy.asarray(arr))
        return jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(template), leaves
        )

"""The batched time-stepped TPU engine.

This is the TPU-native re-expression of the reference's discrete-event loop
(core Network.java:318-338 `runMs` / :586 `receiveUntil`): instead of an
event queue drained one message at a time on one thread, every (replica,
node) pair applies masked state transitions once per simulated millisecond,
under `jax.lax.scan`, `jax.vmap` over replicas, and `jax.sharding` over
devices.
"""

from .capacity import (
    CapacityEntry,
    load_capacity,
    lookup,
    size_from_hwm,
    sized_overrides,
    validate_table,
)
from .core import BatchedNetwork, Emission, SimState, replicate_state, stack_states
from .density import LanePlan, NarrowLeaf, lane_plan, narrowest_int
from .protocol import (
    ENGINE_OWNED_FIELDS,
    HOST_HOOKS,
    KERNEL_HOOKS,
    BatchedProtocol,
)
from .rng import hash32, pseudo_delta

__all__ = [
    "BatchedNetwork",
    "BatchedProtocol",
    "CapacityEntry",
    "Emission",
    "LanePlan",
    "NarrowLeaf",
    "SimState",
    "hash32",
    "lane_plan",
    "load_capacity",
    "lookup",
    "narrowest_int",
    "pseudo_delta",
    "replicate_state",
    "size_from_hwm",
    "sized_overrides",
    "stack_states",
    "validate_table",
]

"""Batched time-stepped simulation core.

Re-expression of the reference DES (core Network.java) as a synchronous
per-millisecond state transition suitable for TPUs:

  * node state is a struct-of-arrays pytree of `[N]` columns
    (Node.java:22-88 fields become columns);
  * in-flight messages live in a fixed-capacity ring `[C]` of
    (arrival, from, to, type, payload) with a validity mask — the
    static-shape analog of MessageStorage (Network.java:116-299);
  * per-destination latency jitter comes from the reference's own xorshift
    counter hash (rng.pseudo_delta), so multicast costs no per-dest state,
    exactly like MultipleDestEnvelope (Envelope.java:46-56);
  * the event loop is `lax.scan` over milliseconds; one step delivers every
    due message, runs the protocol's vectorized handlers, fires periodic
    masks, and appends emissions (receiveUntil/nextMessage,
    Network.java:533-632, without the queue);
  * `jax.vmap` over the leading replica axis replaces RunMultipleTimes'
    sequential reseeded runs (RunMultipleTimes.java:48-63).

Semantics deltas vs the oracle (documented, by design — SURVEY §7):
  * same-millisecond deliveries are simultaneous (no LIFO order inside a
    ms); protocols must use commutative per-tick updates;
  * `run_ms(ms)` processes ticks [time, time+ms) — arrivals at exactly
    time+ms land at the start of the next call (the oracle includes the
    boundary tick in the earlier call);
  * randomness is counter-based, so message *distributions* match the
    oracle but individual draws differ.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.latency import LatencyStatic, NetworkLatency, vec_latency
from .rng import hash32, pseudo_delta

MAX_PARTITIONS = 4
INT_MAX = np.int32(2**31 - 1)


class SimState(NamedTuple):
    """Per-replica simulation state; every field is a jnp array so the whole
    thing is a pytree (checkpointable for free — an upgrade over the
    reference, whose Envelope.java:55 only muses about serialization)."""

    time: jnp.ndarray  # int32 scalar, ms (Network.java:46-49)
    seed: jnp.ndarray  # int32 scalar, per-replica base seed
    send_ctr: jnp.ndarray  # int32 scalar: per-send-event counter (seeds)
    # node columns (Node.java:22-88)
    down: jnp.ndarray  # bool[N]
    done_at: jnp.ndarray  # int32[N]
    msg_received: jnp.ndarray  # int32[N]
    msg_sent: jnp.ndarray  # int32[N]
    bytes_received: jnp.ndarray  # int32[N]
    bytes_sent: jnp.ndarray  # int32[N]
    # latency inputs (per replica so vmap covers heterogeneous layouts)
    x: jnp.ndarray  # int32[N]
    y: jnp.ndarray  # int32[N]
    extra_latency: jnp.ndarray  # int32[N]
    city_idx: jnp.ndarray  # int32[N]
    # partitions (Network.java:639-707)
    partition_x: jnp.ndarray  # int32[MAX_PARTITIONS], INT_MAX = unused
    # message ring
    msg_valid: jnp.ndarray  # bool[C]
    msg_arrival: jnp.ndarray  # int32[C]
    msg_from: jnp.ndarray  # int32[C]
    msg_to: jnp.ndarray  # int32[C]
    msg_type: jnp.ndarray  # int32[C]
    msg_payload: jnp.ndarray  # int32[C, P]
    msg_head: jnp.ndarray  # int32 scalar: next write cursor
    dropped: jnp.ndarray  # int32 scalar: ring-overflow count (must stay 0)
    proto: Any  # protocol-defined pytree


@dataclasses.dataclass
class Emission:
    """A batched send request: K candidate messages (the analog of one
    Network.send call, Network.java:341-447).

    mask[K] selects real sends; from_idx/to_idx[K] are node ids; payload is
    [K, P] (or None when P=0).  mtype may be a static int or a per-row
    [K] array (protocols with per-level message types).  arrival, when
    given, bypasses the latency model AND sender counters (the analog of
    sendArriveAt, Network.java:419-422, used for task-style self-messages);
    declare such types with msg_size 0 so receiver counters skip them too."""

    mask: jnp.ndarray
    from_idx: jnp.ndarray
    to_idx: jnp.ndarray
    mtype: "int | jnp.ndarray"
    payload: Optional[jnp.ndarray] = None
    send_time: Optional[jnp.ndarray] = None  # default: state.time + 1
    arrival: Optional[jnp.ndarray] = None  # explicit arrival times [K]


class BatchedNetwork:
    """The engine: binds a latency model + protocol to compiled step/run
    functions.  One instance is reusable across replica counts (everything
    batched lives in SimState)."""

    def __init__(
        self,
        protocol: "BatchedProtocol",
        latency: NetworkLatency,
        n_nodes: int,
        capacity: int = 1 << 14,
        msg_discard_time: int = int(INT_MAX),
        throughput=None,  # optional core.throughput.MathisNetworkThroughput
    ):
        self.protocol = protocol
        self.latency = latency
        self.n_nodes = n_nodes
        self.capacity = capacity
        self.msg_discard_time = msg_discard_time
        self.throughput = throughput
        self.payload_width = protocol.PAYLOAD_WIDTH
        sizes = [protocol.msg_size(t) for t in range(protocol.n_msg_types())]
        self._msg_sizes = np.asarray(sizes, dtype=np.int32)

    # -- state construction (host-side) -------------------------------------
    def init_state(self, cols: dict, seed: int, proto: Any, down=None) -> SimState:
        """Build a fresh single-replica state from node columns
        (core.node.build_node_columns output).  `down` marks nodes dead from
        t=0 — applied before the protocol's initial emissions so sends to
        them are dropped like the oracle's send-time check."""
        n, c, p = self.n_nodes, self.capacity, self.payload_width
        zi = lambda shape: jnp.zeros(shape, dtype=jnp.int32)
        state = SimState(
            time=jnp.int32(0),
            seed=jnp.int32(np.int64(seed) & 0x7FFFFFFF),
            send_ctr=jnp.int32(0),
            down=(
                jnp.zeros(n, dtype=bool)
                if down is None
                else jnp.asarray(down, dtype=bool)
            ),
            done_at=zi(n),
            msg_received=zi(n),
            msg_sent=zi(n),
            bytes_received=zi(n),
            bytes_sent=zi(n),
            x=jnp.asarray(cols["x"], jnp.int32),
            y=jnp.asarray(cols["y"], jnp.int32),
            extra_latency=jnp.asarray(cols["extra_latency"], jnp.int32),
            city_idx=jnp.asarray(cols.get("city_idx", np.full(n, -1)), jnp.int32),
            partition_x=jnp.full(MAX_PARTITIONS, INT_MAX, dtype=jnp.int32),
            msg_valid=jnp.zeros(c, dtype=bool),
            msg_arrival=jnp.full(c, INT_MAX, dtype=jnp.int32),
            msg_from=zi(c),
            msg_to=zi(c),
            msg_type=zi(c),
            msg_payload=zi((c, p)),
            msg_head=jnp.int32(0),
            dropped=jnp.int32(0),
            proto=proto,
        )
        for em in self.protocol.initial_emissions(self, state):
            state = self.apply_emission(state, em)
        return state

    # -- partitions (Network.partition, Network.java:693-707) ----------------
    @staticmethod
    def partition_id(state: SimState, x_col) -> jnp.ndarray:
        """pid = number of partition lines at or left of the node
        (Network.partitionId, Network.java:639-649)."""
        return jnp.sum(
            state.partition_x[None, :] <= x_col[:, None], axis=-1
        ).astype(jnp.int32)

    # -- the send path (createMessageArrival, Network.java:469-487) ----------
    def latency_arrivals(self, state, mask, from_idx, to_idx, send_time, mtype):
        """The createMessageArrival kernel shared by the generic ring and
        protocol-specific message channels: ticks sender counters (even for
        dropped sends, Network.java:476-477), samples the latency model via
        the counter RNG, applies partition/down/discard filters.  Returns
        (state, ok, arrival)."""
        k = mask.shape[0]
        from_idx = from_idx.astype(jnp.int32)
        to_idx = to_idx.astype(jnp.int32)
        mtype = jnp.asarray(mtype, jnp.int32)  # scalar or per-row [K]
        size = jnp.asarray(self._msg_sizes, jnp.int32)[mtype]
        state = state._replace(
            msg_sent=state.msg_sent.at[from_idx].add(mask.astype(jnp.int32)),
            bytes_sent=state.bytes_sent.at[from_idx].add(
                mask.astype(jnp.int32) * size
            ),
            send_ctr=state.send_ctr + 1,
        )
        # per-event seed: the batched analog of rd.nextInt() per send;
        # send_ctr + row index decorrelate same-tick same-type sends
        seed = hash32(
            state.seed,
            send_time,
            from_idx,
            mtype,
            state.send_ctr,
            jnp.arange(k, dtype=jnp.int32),
        )
        delta = pseudo_delta(to_idx, seed)
        static = LatencyStatic(state.x, state.y, state.extra_latency, state.city_idx)
        if self.throughput is not None:
            # size-dependent Mathis delay (vectorized twin of the oracle's
            # transit_ms throughput path), priced off THIS network's latency
            lat = self.throughput.vec_delay(
                static, from_idx, to_idx, delta, size, nl=self.latency
            )
        else:
            lat = vec_latency(self.latency, static, from_idx, to_idx, delta)
        arrival = jnp.asarray(send_time, jnp.int32) + lat
        pid_f = self.partition_id(state, state.x[from_idx])
        pid_t = self.partition_id(state, state.x[to_idx])
        ok = (
            mask
            & ~state.down[from_idx]
            & ~state.down[to_idx]
            & (pid_f == pid_t)
            & (lat < self.msg_discard_time)
        )
        return state, ok, arrival

    def apply_emission(self, state: SimState, em: Emission) -> SimState:
        k = em.mask.shape[0]
        send_time = em.send_time if em.send_time is not None else state.time + 1
        mask = em.mask
        from_idx = em.from_idx.astype(jnp.int32)
        to_idx = em.to_idx.astype(jnp.int32)

        mtype = jnp.asarray(em.mtype, jnp.int32)  # scalar or per-row [K]
        if em.arrival is not None:
            # sendArriveAt path: explicit arrival, no latency model and no
            # sender counters (Network.sendArriveAt, Network.java:419-422,
            # bypasses createMessageArrival's counter ticks)
            arrival = em.arrival.astype(jnp.int32)
            ok = mask
        else:
            state, ok, arrival = self.latency_arrivals(
                state, mask, from_idx, to_idx, send_time, mtype
            )

        # pack the ok-messages into FREE ring slots: the k-th ok row takes
        # the k-th invalid slot.  (A head cursor would clobber still-pending
        # long-lived messages — ENR's birth/exit wakes, scheduled tasks —
        # as soon as cumulative traffic wraps the capacity, even with most
        # slots free.)  Only a genuinely full ring drops, and it drops the
        # NEW rows, counted in `dropped`.
        free = ~state.msg_valid  # [C]
        free_rank = jnp.cumsum(free.astype(jnp.int32)) - 1
        slot_of_rank = jnp.full(self.capacity + 1, self.capacity, jnp.int32)
        slot_of_rank = slot_of_rank.at[
            jnp.where(free, free_rank, self.capacity)
        ].set(jnp.arange(self.capacity, dtype=jnp.int32), mode="drop")
        n_free = jnp.sum(free.astype(jnp.int32))
        slot_rank = jnp.cumsum(ok.astype(jnp.int32)) - 1
        fits = ok & (slot_rank < n_free)
        pos = jnp.where(
            fits,
            slot_of_rank[jnp.clip(slot_rank, 0, self.capacity)],
            jnp.int32(self.capacity),  # OOB -> dropped
        )
        n_ok = jnp.sum(ok.astype(jnp.int32))
        overwritten = jnp.sum((ok & ~fits).astype(jnp.int32))
        payload = em.payload
        if self.payload_width and payload is None:
            payload = jnp.zeros((k, self.payload_width), dtype=jnp.int32)
        new = state._replace(
            msg_valid=state.msg_valid.at[pos].set(True, mode="drop"),
            msg_arrival=state.msg_arrival.at[pos].set(arrival, mode="drop"),
            msg_from=state.msg_from.at[pos].set(from_idx, mode="drop"),
            msg_to=state.msg_to.at[pos].set(to_idx, mode="drop"),
            msg_type=state.msg_type.at[pos].set(
                jnp.broadcast_to(mtype, (k,)), mode="drop"
            ),
            # head is no longer an allocator (free-slot packing above); kept
            # as a monotone sent-message counter for observability
            msg_head=state.msg_head + n_ok,
            dropped=state.dropped + overwritten,
        )
        if self.payload_width:
            new = new._replace(
                msg_payload=new.msg_payload.at[pos].set(payload, mode="drop")
            )
        return new

    def apply_emissions(self, state: SimState, emissions) -> SimState:
        for em in emissions:
            state = self.apply_emission(state, em)
        return state

    # -- one millisecond (receiveUntil body, Network.java:586-632) -----------
    def _step_core(self, state: SimState) -> SimState:
        """One tick WITHOUT the time advance and WITHOUT tick_beat: ring
        delivery + protocol.tick.  run_ms_batched's beat path guards
        tick_beat separately with a real branch."""
        t = state.time
        due = state.msg_valid & (state.msg_arrival <= t)
        # delivery-time checks: down destination or cross-partition messages
        # are discarded on arrival (Network.java:606, :518-520)
        pid_f = self.partition_id(state, state.x[state.msg_from])
        pid_t = self.partition_id(state, state.x[state.msg_to])
        deliver = due & ~state.down[state.msg_to] & (pid_f == pid_t)

        # receiver counters skip size-0 (task-style) types, mirroring the
        # Task exemption at Network.java:522-526
        sizes = jnp.asarray(self._msg_sizes, jnp.int32)[state.msg_type]
        dm = (deliver & (sizes > 0)).astype(jnp.int32)
        state = state._replace(
            msg_received=state.msg_received.at[state.msg_to].add(dm, mode="drop"),
            bytes_received=state.bytes_received.at[state.msg_to].add(
                dm * sizes, mode="drop"
            ),
        )

        state, emissions = self.protocol.deliver(self, state, deliver)
        state = state._replace(msg_valid=state.msg_valid & ~due)
        state = self.apply_emissions(state, emissions)

        return self.protocol.tick(self, state)

    def step(self, state: SimState) -> SimState:
        state = self._step_core(state)
        state = self.protocol.tick_beat(self, state)
        state = self.protocol.tick_post(self, state)
        return state._replace(time=state.time + 1)

    def _step_jump(self, state: SimState, end) -> SimState:
        """step() plus empty-ms skipping: when the protocol has no per-ms
        tick work (TICK_INTERVAL None), jump straight to the next arrival —
        the batched analog of the oracle's event loop skipping idle time
        (nextMessage's per-ms poll, Network.java:533-545, exists only
        because conditional tasks poll empty milliseconds).  A protocol
        TIME_QUANTUM > 1 additionally rounds the jump target UP to the
        quantum grid, so a whole window of arrivals is delivered in one
        step (each delayed < quantum ms)."""
        state = self.step(state)
        if self.protocol.TICK_INTERVAL is None:
            q = self.protocol.TIME_QUANTUM
            next_arrival = jnp.min(
                jnp.where(state.msg_valid, state.msg_arrival, INT_MAX)
            )
            t = jnp.clip(next_arrival, state.time, end).astype(jnp.int32)
            if q > 1:
                t = jnp.minimum(
                    (t + q - 1) // q * q, jnp.asarray(end, jnp.int32)
                ).astype(jnp.int32)
            state = state._replace(time=t)
        return state

    # -- the loop ------------------------------------------------------------
    @functools.partial(jax.jit, static_argnums=(0, 2, 3))
    def run_ms(self, state: SimState, ms: int, stop_when_done: bool = False) -> SimState:
        """Advance `ms` simulated milliseconds (ticks [time, time+ms)).

        stop_when_done=True adds the protocol's `all_done` predicate to the
        loop condition: once the observable outcome is decided (e.g. every
        live Handel node aggregated), remaining ticks are skipped and the
        clock jumps to `end` — the batched analog of the oracle DES going
        quiescent when no events remain.  Post-done side effects (periodic
        re-offers' traffic counters) are NOT simulated, so keep the default
        for traffic-parity runs."""
        end = state.time + ms

        def cond(s):
            c = s.time < end
            if stop_when_done:
                c = c & ~self.protocol.all_done(s)
            return c

        def body(s):
            return self._step_jump(s, end)

        state = lax.while_loop(cond, body, state)
        return state._replace(time=end)

    @functools.partial(jax.jit, static_argnums=(0, 2, 3))
    def run_ms_batched(
        self, states: SimState, ms: int, stop_when_done: bool = False
    ) -> SimState:
        """vmapped run over the leading replica axis — the TPU replacement
        for RunMultipleTimes' sequential reseeded loop.

        When the protocol declares a sparse beat structure (BEAT_PERIOD +
        BEAT_RESIDUES), the time loop runs OUTSIDE the vmap: replicas
        advance time in lockstep, so the tick index is replica-uniform and
        tick_beat can be guarded by a real lax.cond — off-beat ticks skip
        the periodic work instead of executing it masked (a vmapped
        lax.cond would execute both branches).

        stop_when_done stops the LOCKSTEP loop once every replica's
        all_done holds (see run_ms).  On the ungated fallback path the
        flag is semantics-only: vmapped while_loops mask finished lanes
        rather than skip them, so the body runs until the SLOWEST replica
        finishes either way."""
        proto = self.protocol
        period, residues = proto.BEAT_PERIOD, proto.BEAT_RESIDUES
        if (
            proto.TICK_INTERVAL != 1
            or not period
            or residues is None
            or len(residues) >= period
        ):
            return jax.vmap(lambda s: self.run_ms(s, ms, stop_when_done))(states)

        step_v = jax.vmap(self._step_core)
        beat_v = jax.vmap(lambda s: proto.tick_beat(self, s))
        post_v = jax.vmap(lambda s: proto.tick_post(self, s))
        res = jnp.asarray(sorted(residues), jnp.int32)

        def skip_beat(s):
            # keep the per-event RNG stream identical to the ungated path,
            # where the masked beat call still advanced send_ctr
            return s._replace(send_ctr=s.send_ctr + proto.BEAT_SEND_CALLS)

        def body(_, s):
            # any-over-replicas: for the normal lockstep batch this equals
            # replica 0's beat test; for a batch with non-uniform clocks
            # (stacked mid-run states) tick_beat fires whenever ANY replica
            # beats, and its per-node masks no-op the others — correct
            # either way, and send_ctr advances by exactly 1 on every path
            is_beat = jnp.any(
                lax.rem(s.time.reshape(-1)[:, None], jnp.int32(period))
                == res[None, :]
            )
            s = step_v(s)
            s = lax.cond(is_beat, beat_v, skip_beat, s)
            s = post_v(s)
            return s._replace(time=s.time + 1)

        if not stop_when_done:
            return lax.fori_loop(0, ms, body, states)

        def w_cond(carry):
            i, s = carry
            return (i < ms) & ~jnp.all(jax.vmap(proto.all_done)(s))

        def w_body(carry):
            i, s = carry
            return i + 1, body(i, s)

        i_fin, states = lax.while_loop(w_cond, w_body, (jnp.int32(0), states))
        # normalize the lockstep clocks to the full horizon, like run_ms
        return states._replace(time=states.time + (ms - i_fin))


def replicate_state(state: SimState, n_replicas: int, seeds=None) -> SimState:
    """Tile a single-replica state along a new leading replica axis, giving
    each replica its own dynamics seed.  (Distinct node layouts per replica
    can be had by stacking init_state outputs instead.)"""
    if seeds is None:
        seeds = np.arange(n_replicas, dtype=np.int32)
    seeds = jnp.asarray(seeds, jnp.int32)
    tiled = jax.tree_util.tree_map(
        lambda a: jnp.broadcast_to(a, (n_replicas,) + a.shape), state
    )
    return tiled._replace(seed=seeds)


def stack_states(states) -> SimState:
    """Stack independently-built single-replica states (heterogeneous node
    layouts, the exact analog of RunMultipleTimes' per-seed re-init)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *states)

"""Batched time-stepped simulation core.

Re-expression of the reference DES (core Network.java) as a synchronous
per-millisecond state transition suitable for TPUs:

  * node state is a struct-of-arrays pytree of `[N]` columns
    (Node.java:22-88 fields become columns);
  * in-flight messages live in a TIME WHEEL — `[W, B]` buckets keyed by
    `arrival mod W` plus a small `[V]` overflow lane for beyond-horizon
    arrivals — the calendar-queue analog of MessageStorage
    (Network.java:116-299, which exists precisely so the reference never
    scans an unsorted event list).  A tick's delivery reads only its own
    bucket row(s) and the overflow lane: O(B + V) per tick instead of
    O(C) over a flat ring (see docs/engine_timewheel.md);
  * per-destination latency jitter comes from the reference's own xorshift
    counter hash (rng.pseudo_delta), so multicast costs no per-dest state,
    exactly like MultipleDestEnvelope (Envelope.java:46-56);
  * the event loop is `lax.scan` over milliseconds; one step delivers every
    due message, runs the protocol's vectorized handlers, fires periodic
    masks, and appends emissions (receiveUntil/nextMessage,
    Network.java:533-632, without the queue);
  * `jax.vmap` over the leading replica axis replaces RunMultipleTimes'
    sequential reseeded runs (RunMultipleTimes.java:48-63).

Semantics deltas vs the oracle (documented, by design — SURVEY §7):
  * same-millisecond deliveries are simultaneous (no LIFO order inside a
    ms); protocols must use commutative per-tick updates;
  * `run_ms(ms)` processes ticks [time, time+ms) — arrivals at exactly
    time+ms land at the start of the next call (the oracle includes the
    boundary tick in the earlier call);
  * randomness is counter-based, so message *distributions* match the
    oracle but individual draws differ.

Protocols see the wheel only through the delivery VIEW: `deliver` still
receives `state.msg_*` columns aligned with `deliver_mask` — the engine
gathers the due bucket rows + the overflow lane into flat `[D]` arrays
before the call and restores the wheel storage afterwards, so protocol
delivery kernels are layout-agnostic.
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.latency import LatencyStatic, NetworkLatency, vec_latency
from ..faults.state import (
    FaultConfig,
    deliver_suppress,
    inflate_latency,
    neutral_fault_state,
    send_suppress,
)
from ..ops.bitops import (
    bitops_backend,
    lowest_set_bit,
    pack_bool_words,
    popcount_words,
)
from ..telemetry.state import (
    TelemetryConfig,
    count_by_type,
    init_telemetry,
    record_snapshot,
)
from .density import lane_plan
from .rng import hash32, pseudo_delta

MAX_PARTITIONS = 4
INT_MAX = np.int32(2**31 - 1)

# default wheel horizon, ms: covers the WAN latency models' bulk; rarer
# longer delays (heavy jitter tails, Mathis throughput delays, protocol
# timeouts) spill to the overflow lane, which stays exact — the wheel is
# a fast path, never a correctness boundary
DEFAULT_WHEEL_ROWS = 512

# named-scope phase map (docs/profiling.md): every engine phase is wrapped
# in jax.named_scope so jaxprs, HLO metadata and device profiles attribute
# ops to the phase that traced them.  Scopes are TRACE-TIME metadata only —
# they cannot change a single computed bit (simlint SL601 pins this with a
# concrete annotate-on vs annotate-off bitwise cross-check).  Sub-phases
# nest (e.g. a fault send check inside the send path shows up as
# "witt.send/witt.faults.send"), so consumers should substring-match.
ENGINE_PHASE_SCOPES = {
    "delivery": "witt.delivery",
    "fused_step": "witt.fused_step",
    "protocol_deliver": "witt.protocol_deliver",
    "send": "witt.send",
    "protocol_tick": "witt.protocol_tick",
    "beat": "witt.beat",
    "post": "witt.post",
    "telemetry": "witt.telemetry",
    "jump": "witt.jump",
    "faults_send": "witt.faults.send",
    "faults_deliver": "witt.faults.deliver",
}


class SimState(NamedTuple):
    """Per-replica simulation state; every field is a jnp array so the whole
    thing is a pytree (checkpointable for free — an upgrade over the
    reference, whose Envelope.java:55 only muses about serialization)."""

    time: jnp.ndarray  # int32 scalar, ms (Network.java:46-49)
    seed: jnp.ndarray  # int32 scalar, per-replica base seed
    send_ctr: jnp.ndarray  # int32 scalar: per-send-event counter (seeds)
    # node columns (Node.java:22-88)
    down: jnp.ndarray  # bool[N]
    done_at: jnp.ndarray  # int32[N]
    msg_received: jnp.ndarray  # int32[N]
    msg_sent: jnp.ndarray  # int32[N]
    bytes_received: jnp.ndarray  # int32[N]
    bytes_sent: jnp.ndarray  # int32[N]
    # latency inputs (per replica so vmap covers heterogeneous layouts)
    x: jnp.ndarray  # int32[N]
    y: jnp.ndarray  # int32[N]
    extra_latency: jnp.ndarray  # int32[N]
    city_idx: jnp.ndarray  # int32[N]
    # partitions (Network.java:639-707)
    partition_x: jnp.ndarray  # int32[MAX_PARTITIONS], INT_MAX = unused
    # time wheel [W, B]: row r holds messages with eff-arrival ≡ r (mod W).
    # The msg_* names are shared with the delivery view handed to
    # protocol.deliver (flat [D] gathers of the due rows + overflow).
    # id/type lanes are STORED at the engine's lane_plan dtypes (int16
    # ids when N fits, int8/int16 types per the mtype count) and widened
    # to int32 at the delivery-view gather — see engine.density
    msg_valid: jnp.ndarray  # bool[W, B]
    msg_arrival: jnp.ndarray  # int32[W, B]
    msg_from: jnp.ndarray  # lanes.idx[W, B]
    msg_to: jnp.ndarray  # lanes.idx[W, B]
    msg_type: jnp.ndarray  # lanes.mtype[W, B]
    msg_payload: jnp.ndarray  # int32[W, B, P]
    whl_fill: jnp.ndarray  # int32[W]: valid entries per row (dense prefix)
    # overflow lane [V]: beyond-horizon arrivals + full-row spill; scanned
    # (arrival <= t) every tick like the old flat ring, but V << W*B
    ovf_valid: jnp.ndarray  # bool[V]
    ovf_arrival: jnp.ndarray  # int32[V]
    ovf_from: jnp.ndarray  # lanes.idx[V]
    ovf_to: jnp.ndarray  # lanes.idx[V]
    ovf_type: jnp.ndarray  # lanes.mtype[V]
    ovf_payload: jnp.ndarray  # int32[V, P]
    msg_head: jnp.ndarray  # int32 scalar: monotone sent-message counter
    dropped: jnp.ndarray  # int32 scalar: wheel+overflow overflow count
    proto: Any  # protocol-defined pytree
    # telemetry side-car: () when the engine's TelemetryConfig is unset
    # (zero pytree leaves, zero traced ops), a telemetry.TelemetryState
    # of pure counters otherwise — never read by sim dynamics, so an
    # instrumented run is bit-identical in every other field
    tele: Any = ()
    # fault side-car: () when the engine's FaultConfig is unset, a
    # faults.FaultState schedule + counters otherwise.  Unlike tele it IS
    # read by sim dynamics (that is its job) — but the neutral schedule
    # makes every fault predicate constant-false, so a fault-enabled run
    # on neutral_fault_state is bit-identical too (simlint SL406)
    faults: Any = ()


@dataclasses.dataclass
class Emission:
    """A batched send request: K candidate messages (the analog of one
    Network.send call, Network.java:341-447).

    mask[K] selects real sends; from_idx/to_idx[K] are node ids; payload is
    [K, P] (or None when P=0).  mtype may be a static int or a per-row
    [K] array (protocols with per-level message types).  arrival, when
    given, bypasses the latency model AND sender counters (the analog of
    sendArriveAt, Network.java:419-422, used for task-style self-messages);
    declare such types with msg_size 0 so receiver counters skip them too."""

    mask: jnp.ndarray
    from_idx: jnp.ndarray
    to_idx: jnp.ndarray
    mtype: "int | jnp.ndarray"
    payload: Optional[jnp.ndarray] = None
    send_time: Optional[jnp.ndarray] = None  # default: state.time + 1
    arrival: Optional[jnp.ndarray] = None  # explicit arrival times [K]


class BatchedNetwork:
    """The engine: binds a latency model + protocol to compiled step/run
    functions.  One instance is reusable across replica counts (everything
    batched lives in SimState).

    Message storage is a time wheel `[wheel_rows, wheel_slots]` plus an
    `[overflow_capacity]` lane (see module docstring).  `wheel_rows=0`
    selects FLAT mode: everything goes through the overflow lane, which
    reproduces the old full-scan ring exactly — used by protocols whose
    scheduling is dominated by far-future explicit arrivals (Casper's 8 s
    slots, ENR's wake calendar) and by the agg protocols whose messaging
    bypasses the generic ring entirely.  `capacity` keeps its historical
    meaning (total in-flight budget) and sizes the wheel/overflow defaults.
    """

    def __init__(
        self,
        protocol: "BatchedProtocol",
        latency: NetworkLatency,
        n_nodes: int,
        capacity: int = 1 << 14,
        msg_discard_time: int = int(INT_MAX),
        throughput=None,  # optional core.throughput.MathisNetworkThroughput
        wheel_rows: Optional[int] = None,
        wheel_slots: Optional[int] = None,
        overflow_capacity: Optional[int] = None,
        telemetry: Optional[TelemetryConfig] = None,
        faults: Optional["FaultConfig"] = None,
        annotate: bool = True,
        fuse_step: bool = False,
        narrow_lanes: Optional[bool] = None,
        batched_jumps: bool = False,
    ):
        self.protocol = protocol
        self.latency = latency
        self.n_nodes = n_nodes
        self.capacity = capacity
        self.msg_discard_time = msg_discard_time
        self.throughput = throughput
        # STATIC switch for the named-scope phase annotations (see
        # ENGINE_PHASE_SCOPES): True wraps every phase in jax.named_scope
        # (trace-time metadata, zero runtime ops); False traces the bare
        # program — kept only so simlint SL601 can prove the two are
        # bit-identical and bench can price the (nominally zero) overhead
        self.annotate = bool(annotate)
        # STATIC switch for the fused delivery+tick step (_step_core_fused,
        # docs/engine_fused_step.md): one traced phase instead of
        # delivery -> send -> tick with full-state round-trips between
        # them, plus a static empty-row clear that replaces the generic
        # sort/repack when the delivery window is a single row.
        # Bit-identical to the unfused path by construction (pinned by
        # tests/test_step_fusion.py); the unfused path stays the default
        # because its per-phase scopes are what --phase-profile and the
        # SL601 annotation checks attribute against.
        self.fuse_step = bool(fuse_step)
        # STATIC switch for the batched consensus-jump loop
        # (_run_ms_batched_jumps, docs/engine_timewheel.md): replicas
        # advance time in lockstep and the whole batch jumps to the
        # minimum next-arrival across the replica axis.  Bit-identical to
        # the ungated vmapped fallback by construction (each lane steps
        # at exactly its own singleton tick set); default-off pending the
        # paired A/B in BENCH_FLOOR.json (profiling.md lever ledger)
        self.batched_jumps = bool(batched_jumps)
        # STATIC switch: None compiles the exact pre-telemetry program
        # (state.tele is an empty pytree); a TelemetryConfig threads the
        # counter side-car through every send/deliver/jump site below
        self.telemetry = telemetry
        # STATIC switch for the fault-injection lanes (faults/state.py),
        # same pattern: None leaves state.faults an empty pytree and the
        # two choke points below trace zero fault ops
        self.faults = faults
        self.payload_width = protocol.PAYLOAD_WIDTH
        sizes = [protocol.msg_size(t) for t in range(protocol.n_msg_types())]
        self._msg_sizes = np.asarray(sizes, dtype=np.int32)
        # STATIC storage dtype plan for the message lanes (engine.density,
        # docs/density.md): ids/types are CARRIED narrow and widened back
        # to int32 at the delivery-view gather, so every protocol kernel
        # still sees the exact int32 program it was verified against.
        # narrow_lanes=False pins the historical all-int32 lanes — the
        # baseline side of the bit-identity sweep (tests/test_density.py)
        self.lanes = lane_plan(n_nodes, protocol.n_msg_types(), narrow_lanes)

        if wheel_rows is None:
            wheel_rows = DEFAULT_WHEEL_ROWS
        self.flat = wheel_rows == 0
        if self.flat:
            # degenerate 1x1 wheel keeps the pytree shape uniform; inserts
            # never target it, so per-tick cost is the overflow scan = the
            # old flat-ring behavior, bit for bit
            self.wheel_rows = 1
            self.wheel_slots = 1
            self.overflow_capacity = (
                capacity if overflow_capacity is None else overflow_capacity
            )
        else:
            if wheel_rows % 32:
                raise ValueError(
                    f"wheel_rows={wheel_rows} must be a multiple of 32 "
                    "(occupancy is scanned as packed uint32 words)"
                )
            self.wheel_rows = wheel_rows
            self.wheel_slots = (
                max(64, -(-2 * capacity // wheel_rows))
                if wheel_slots is None
                else wheel_slots
            )
            # capped: the lane serves far-future arrivals + full-row spill,
            # and it is scanned every tick — per-tick delivery cost must
            # not scale with total capacity C (the wheel's whole point)
            self.overflow_capacity = (
                max(128, min(1024, capacity // 8))
                if overflow_capacity is None
                else overflow_capacity
            )

    # -- state construction (host-side) -------------------------------------
    def init_state(self, cols: dict, seed: int, proto: Any, down=None) -> SimState:
        """Build a fresh single-replica state from node columns
        (core.node.build_node_columns output).

        `down` (bool[N], default all-up) marks nodes dead for the WHOLE
        run — the batched twin of the oracle nodes `choose_bad_nodes`
        selects, which `Network.run_ms` never start()s.  Because the mask
        is set before the protocol's initial emissions are applied, a
        down node (a) never sends: its initial and later emissions fail
        `latency_arrivals`' send-time check, exactly like the oracle's
        `from_node.is_down()` (Network.java:476-487) — though msg_sent
        still ticks for the *attempts other protocols make toward it*,
        never for its own, since a node that receives nothing emits
        nothing; (b) never receives: the delivery view discards due rows
        addressed to it (Network.java:606); and (c) never reaches
        done_at > 0, so done counts and CDFs exclude it.  Pinned
        cross-protocol by tests/test_faults.py::test_statically_down_nodes.
        For crash/recovery *during* a run, see wittgenstein_tpu.faults."""
        n, p = self.n_nodes, self.payload_width
        w, b, v = self.wheel_rows, self.wheel_slots, self.overflow_capacity
        zi = lambda shape: jnp.zeros(shape, dtype=jnp.int32)
        state = SimState(
            time=jnp.int32(0),
            seed=jnp.int32(np.int64(seed) & 0x7FFFFFFF),
            send_ctr=jnp.int32(0),
            down=(
                jnp.zeros(n, dtype=bool)
                if down is None
                else jnp.asarray(down, dtype=bool)
            ),
            done_at=zi(n),
            msg_received=zi(n),
            msg_sent=zi(n),
            bytes_received=zi(n),
            bytes_sent=zi(n),
            x=jnp.asarray(cols["x"], jnp.int32),
            y=jnp.asarray(cols["y"], jnp.int32),
            extra_latency=jnp.asarray(cols["extra_latency"], jnp.int32),
            city_idx=jnp.asarray(cols.get("city_idx", np.full(n, -1)), jnp.int32),
            partition_x=jnp.full(MAX_PARTITIONS, INT_MAX, dtype=jnp.int32),
            msg_valid=jnp.zeros((w, b), dtype=bool),
            msg_arrival=jnp.full((w, b), INT_MAX, dtype=jnp.int32),
            msg_from=jnp.zeros((w, b), dtype=self.lanes.idx),
            msg_to=jnp.zeros((w, b), dtype=self.lanes.idx),
            msg_type=jnp.zeros((w, b), dtype=self.lanes.mtype),
            msg_payload=zi((w, b, p)),
            whl_fill=zi(w),
            ovf_valid=jnp.zeros(v, dtype=bool),
            ovf_arrival=jnp.full(v, INT_MAX, dtype=jnp.int32),
            ovf_from=jnp.zeros(v, dtype=self.lanes.idx),
            ovf_to=jnp.zeros(v, dtype=self.lanes.idx),
            ovf_type=jnp.zeros(v, dtype=self.lanes.mtype),
            ovf_payload=zi((v, p)),
            msg_head=jnp.int32(0),
            dropped=jnp.int32(0),
            proto=proto,
            tele=(
                init_telemetry(self.telemetry, self.protocol.n_msg_types())
                if self.telemetry is not None
                else ()
            ),
            faults=(
                neutral_fault_state(n, self.protocol.n_msg_types())
                if self.faults is not None
                else ()
            ),
        )
        for em in self.protocol.initial_emissions(self, state):
            state = self.apply_emission(state, em)
        return state

    def cache_key(self) -> tuple:
        """Explicit identity for compiled-program caches (parallel
        .replica_shard): protocol name + the static knobs that shape the
        trace.  id(protocol)/id(latency) disambiguate instances carrying
        different behavior params; cached programs keep those objects
        alive, so the ids cannot be recycled while an entry lives."""
        mesh = getattr(self, "node_mesh", None)
        return (
            type(self.protocol).__name__,
            repr(getattr(self.protocol, "params", None)),
            id(self.protocol),
            id(self.latency),
            str(self.latency),
            self.n_nodes,
            self.capacity,
            self.wheel_rows,
            self.wheel_slots,
            self.overflow_capacity,
            int(self.msg_discard_time),
            type(self.throughput).__name__ if self.throughput else None,
            getattr(self, "node_axis", None),
            id(mesh) if mesh is not None else None,
            self.telemetry.key() if self.telemetry is not None else None,
            self.faults.key() if self.faults is not None else None,
            self.annotate,
            self.fuse_step,
            self.batched_jumps,
            self.lanes.key(),
            # the bitset-kernel backend is read from the environment at
            # trace time (WITT_BITOPS) — fold it in so a flipped override
            # can't be served a stale compiled program
            bitops_backend(),
        )

    def stable_cache_key(self) -> tuple:
        """cache_key minus the process-lifetime id() components: the
        cross-process identity the durable compile store keys on.  Two
        engines with equal stable keys trace the same program *provided*
        their behavior params round-trip through repr/str — true for the
        dataclass params and named latency models this codebase builds;
        an exotic latency whose str() hides state must not be served
        from the store (give it a distinguishing __str__)."""
        return (
            type(self.protocol).__name__,
            repr(getattr(self.protocol, "params", None)),
            str(self.latency),
            self.n_nodes,
            self.capacity,
            self.wheel_rows,
            self.wheel_slots,
            self.overflow_capacity,
            int(self.msg_discard_time),
            type(self.throughput).__name__ if self.throughput else None,
            getattr(self, "node_axis", None),
            self.telemetry.key() if self.telemetry is not None else None,
            self.faults.key() if self.faults is not None else None,
            self.annotate,
            self.fuse_step,
            self.batched_jumps,
            self.lanes.key(),
            bitops_backend(),
        )

    def _scope(self, name: str):
        """jax.named_scope for engine phase `name` (ENGINE_PHASE_SCOPES)
        when annotation is on; a no-op context otherwise."""
        if self.annotate:
            return jax.named_scope(ENGINE_PHASE_SCOPES[name])
        return contextlib.nullcontext()

    def with_telemetry(
        self, state: SimState, telemetry: TelemetryConfig
    ) -> "tuple[BatchedNetwork, SimState]":
        """Instrument an ALREADY-BUILT simulation: returns an engine copy
        carrying the TelemetryConfig (fresh jit identity, like
        enable_node_sharding's copy) and the state with a counter
        side-car attached.  The side-car's per-mtype `sent` is seeded
        with the current store census, so the store invariant
        (sent == delivered + discarded + dropped + pending) holds from
        the first tick even when initial emissions predate
        instrumentation.  Works on single and batched states (leading
        axes broadcast)."""
        import copy

        net = copy.copy(self)
        net.telemetry = telemetry
        t = self.protocol.n_msg_types()
        tele = init_telemetry(telemetry, t)
        lead = tuple(jnp.shape(state.time))
        if lead:
            tele = jax.tree_util.tree_map(
                lambda a: jnp.broadcast_to(a, lead + a.shape), tele
            )
        # store census per mtype via one-hot (T is small): [..., W, B, T]
        # and [..., V, T] reduced over the store axes
        t_arr = jnp.arange(t, dtype=jnp.int32)
        in_wheel = (
            (state.msg_type[..., None] == t_arr) & state.msg_valid[..., None]
        ).sum((-3, -2))
        in_ovf = (
            (state.ovf_type[..., None] == t_arr) & state.ovf_valid[..., None]
        ).sum(-2)
        tele = tele._replace(sent=(in_wheel + in_ovf).astype(jnp.int32))
        return net, state._replace(tele=tele)

    def with_faults(
        self, state: SimState, faults: "FaultConfig | None" = None, plan=None
    ) -> "tuple[BatchedNetwork, SimState]":
        """Arm fault injection on an ALREADY-BUILT simulation: returns an
        engine copy carrying the (static) FaultConfig and the state with
        a FaultState side-car attached.  `plan` may be a host-side
        FaultPlan (lowered here), an already-lowered FaultState — e.g. a
        `lower_plans` stack for a per-replica heterogeneous sweep — or
        None for the neutral do-nothing schedule.  Works on single and
        batched states: an unstacked schedule broadcasts over the
        leading replica axes; a pre-stacked one is used as-is."""
        import copy

        from ..faults.state import FaultConfig, FaultState

        net = copy.copy(self)
        net.faults = FaultConfig() if faults is None else faults
        t = self.protocol.n_msg_types()
        if plan is None:
            fs = neutral_fault_state(self.n_nodes, t)
        elif isinstance(plan, FaultState):
            fs = plan
        else:
            fs = plan.lower(self.n_nodes, t)
        lead = tuple(jnp.shape(state.time))
        if lead and jnp.ndim(fs.crash_at) < 1 + len(lead):
            fs = jax.tree_util.tree_map(
                lambda a: jnp.broadcast_to(a, lead + tuple(jnp.shape(a))), fs
            )
        return net, state._replace(faults=fs)

    def with_fuse_step(self, fuse: bool = True) -> "BatchedNetwork":
        """Engine copy with the fused delivery+tick step toggled (fresh
        jit identity via cache_key, same pattern as with_telemetry).
        Fusion is a pure trace restructure — the returned engine accepts
        the same states and produces bit-identical results."""
        import copy

        net = copy.copy(self)
        net.fuse_step = bool(fuse)
        return net

    def with_batched_jumps(self, jumps: bool = True) -> "BatchedNetwork":
        """Engine copy with the batched consensus-jump loop toggled
        (fresh jit identity via cache_key, same pattern as
        with_fuse_step).  Only changes which program run_ms_batched
        traces for TICK_INTERVAL-None protocols; results are
        bit-identical either way."""
        import copy

        net = copy.copy(self)
        net.batched_jumps = bool(jumps)
        return net

    # -- partitions (Network.partition, Network.java:693-707) ----------------
    @staticmethod
    def partition_id(state: SimState, x_col) -> jnp.ndarray:
        """pid = number of partition lines at or left of the node
        (Network.partitionId, Network.java:639-649)."""
        return jnp.sum(
            state.partition_x[None, :] <= x_col[:, None], axis=-1
        ).astype(jnp.int32)

    # -- the send path (createMessageArrival, Network.java:469-487) ----------
    def latency_arrivals(self, state, mask, from_idx, to_idx, send_time, mtype):
        """The createMessageArrival kernel shared by the generic ring and
        protocol-specific message channels: ticks sender counters (even for
        dropped sends, Network.java:476-477), samples the latency model via
        the counter RNG, applies partition/down/discard filters.  Returns
        (state, ok, arrival)."""
        k = mask.shape[0]
        from_idx = from_idx.astype(jnp.int32)
        to_idx = to_idx.astype(jnp.int32)
        mtype = jnp.asarray(mtype, jnp.int32)  # scalar or per-row [K]
        size = jnp.asarray(self._msg_sizes, jnp.int32)[mtype]
        state = state._replace(
            msg_sent=state.msg_sent.at[from_idx].add(mask.astype(jnp.int32)),
            bytes_sent=state.bytes_sent.at[from_idx].add(
                mask.astype(jnp.int32) * size
            ),
            send_ctr=state.send_ctr + 1,
        )
        # per-event seed: the batched analog of rd.nextInt() per send;
        # send_ctr decorrelates same-tick emissions, to_idx the rows of
        # one emission.  The destination id — NOT the row position — is
        # the per-row key so the draw is invariant to message-store
        # layout (flat ring vs time wheel order the delivery view
        # differently; a position-keyed seed would make reply latencies
        # depend on storage slots).  Known approximation: duplicate
        # (from, to, type) rows within ONE emission share a draw, where
        # the reference would draw twice — same-dest duplicate sends in
        # a single multicast, which the protocols don't emit.
        seed = hash32(
            state.seed,
            send_time,
            from_idx,
            mtype,
            state.send_ctr,
            to_idx,
        )
        delta = pseudo_delta(to_idx, seed)
        static = LatencyStatic(state.x, state.y, state.extra_latency, state.city_idx)
        if self.throughput is not None:
            # size-dependent Mathis delay (vectorized twin of the oracle's
            # transit_ms throughput path), priced off THIS network's latency
            lat = self.throughput.vec_delay(
                static, from_idx, to_idx, delta, size, nl=self.latency
            )
        else:
            lat = vec_latency(self.latency, static, from_idx, to_idx, delta)
        arrival = jnp.asarray(send_time, jnp.int32) + lat
        pid_f = self.partition_id(state, state.x[from_idx])
        pid_t = self.partition_id(state, state.x[to_idx])
        ok = (
            mask
            & ~state.down[from_idx]
            & ~state.down[to_idx]
            & (pid_f == pid_t)
            & (lat < self.msg_discard_time)
        )
        if self.faults is not None:
            # fault choke point 1 (send): crash/partition/silence/drop
            # suppress rows AFTER the counters ticked above (the oracle
            # ticks msg_sent before its down check too), and the
            # inflation/Byzantine-delay lanes rewrite the sampled
            # latency.  With the neutral schedule supp is constant-false
            # and lat_f == lat, so ok/arrival are bit-identical — the
            # SL406 contract.  The drop draw uses its own hash32 stream
            # without advancing send_ctr, leaving base RNG untouched.
            with self._scope("faults_send"):
                fs = state.faults
                mrows = jnp.broadcast_to(mtype, mask.shape).astype(jnp.int32)
                lat_f = inflate_latency(
                    self.faults, fs, state.time, from_idx, mrows, lat
                )
                supp = send_suppress(
                    self.faults, fs, state.time, from_idx, to_idx, mrows,
                    state.seed, state.send_ctr, send_time,
                )
                ok_f = (
                    mask
                    & ~state.down[from_idx]
                    & ~state.down[to_idx]
                    & (pid_f == pid_t)
                    & ~supp
                    & (lat_f < self.msg_discard_time)
                )
                state = state._replace(
                    faults=fs._replace(
                        dropped_by_fault=count_by_type(
                            fs.dropped_by_fault, ok & supp, mrows
                        ),
                        delayed_by_fault=count_by_type(
                            fs.delayed_by_fault, ok_f & (lat_f != lat), mrows
                        ),
                    )
                )
                ok = ok_f
                arrival = jnp.asarray(send_time, jnp.int32) + lat_f
        if self.telemetry is not None:
            # the latency kernel is the one choke point EVERY send crosses
            # (generic store and the agg protocols' channel commits alike),
            # so per-mtype traffic is counted here, not in apply_emission
            with self._scope("telemetry"):
                mrows = jnp.broadcast_to(mtype, mask.shape).astype(jnp.int32)
                tele = state.tele
                state = state._replace(
                    tele=tele._replace(
                        lat_sent=count_by_type(tele.lat_sent, ok, mrows),
                        lat_filtered=count_by_type(
                            tele.lat_filtered, mask & ~ok, mrows
                        ),
                    )
                )
        return state, ok, arrival

    def apply_emission(self, state: SimState, em: Emission) -> SimState:
        """Scatter an emission's ok-rows into the message store: wheel
        bucket `eff_arrival mod W` when the arrival is inside the horizon
        (t, t+W], overflow lane otherwise (or on full-row spill).  Wheel
        rows stay a dense prefix — a row is only ever cleared whole (or
        repacked) at delivery, so the next free slot is whl_fill[row] plus
        this call's same-row rank.  Only a genuinely full store drops, and
        it drops the NEW rows, counted in `dropped`."""
        with self._scope("send"):
            return self._apply_emission_impl(state, em)

    def _apply_emission_impl(self, state: SimState, em: Emission) -> SimState:
        k = em.mask.shape[0]
        send_time = em.send_time if em.send_time is not None else state.time + 1
        mask = em.mask
        from_idx = em.from_idx.astype(jnp.int32)
        to_idx = em.to_idx.astype(jnp.int32)

        mtype = jnp.asarray(em.mtype, jnp.int32)  # scalar or per-row [K]
        if em.arrival is not None:
            # sendArriveAt path: explicit arrival, no latency model and no
            # sender counters (Network.sendArriveAt, Network.java:419-422,
            # bypasses createMessageArrival's counter ticks)
            arrival = em.arrival.astype(jnp.int32)
            ok = mask
        else:
            state, ok, arrival = self.latency_arrivals(
                state, mask, from_idx, to_idx, send_time, mtype
            )

        payload = em.payload
        if self.payload_width and payload is None:
            payload = jnp.zeros((k, self.payload_width), dtype=jnp.int32)
        mtype_rows = jnp.broadcast_to(mtype, (k,)).astype(jnp.int32)
        n_ok = jnp.sum(ok.astype(jnp.int32))
        t = state.time
        w, b, v = self.wheel_rows, self.wheel_slots, self.overflow_capacity

        if self.flat:
            to_ovf = ok
        else:
            # routing tick: stale arrivals (<= t, possible via explicit
            # arrivals after a clock skip) deliver next tick like the flat
            # ring; arrival == t + W is safe because the current row is
            # delivered/cleared before emissions are applied
            eff = jnp.maximum(arrival, t + 1)
            cand = ok & (eff <= t + w)
            row = jnp.remainder(eff, w)
            # same-row rank via sort (ties broadcast to distinct slots)
            rkey = jnp.where(cand, row, w)
            order = jnp.argsort(rkey)
            rsort = rkey[order]
            pos_sorted = jnp.arange(k, dtype=jnp.int32) - jnp.searchsorted(
                rsort, rsort, side="left"
            ).astype(jnp.int32)
            rank = jnp.zeros(k, jnp.int32).at[order].set(pos_sorted)
            slot = state.whl_fill[jnp.where(cand, row, 0)] + rank
            fits = cand & (slot < b)
            w_row = jnp.where(fits, row, w)  # OOB -> dropped scatter
            w_slot = jnp.where(fits, slot, 0)
            state = state._replace(
                msg_valid=state.msg_valid.at[w_row, w_slot].set(True, mode="drop"),
                msg_arrival=state.msg_arrival.at[w_row, w_slot].set(
                    arrival, mode="drop"
                ),
                msg_from=state.msg_from.at[w_row, w_slot].set(
                    from_idx.astype(self.lanes.idx), mode="drop"
                ),
                msg_to=state.msg_to.at[w_row, w_slot].set(
                    to_idx.astype(self.lanes.idx), mode="drop"
                ),
                msg_type=state.msg_type.at[w_row, w_slot].set(
                    mtype_rows.astype(self.lanes.mtype), mode="drop"
                ),
                whl_fill=state.whl_fill.at[w_row].add(
                    fits.astype(jnp.int32), mode="drop"
                ),
            )
            if self.payload_width:
                state = state._replace(
                    msg_payload=state.msg_payload.at[w_row, w_slot].set(
                        payload, mode="drop"
                    )
                )
            to_ovf = ok & ~fits  # beyond horizon, or full-row spill

        # overflow lane: pack into FREE slots, k-th ok row takes the k-th
        # invalid slot (a head cursor would clobber still-pending long-lived
        # messages — ENR's wakes, Casper's slot calendar — once cumulative
        # traffic wraps the capacity, even with most slots free)
        free = ~state.ovf_valid  # [V]
        free_rank = jnp.cumsum(free.astype(jnp.int32)) - 1
        slot_of_rank = jnp.full(v + 1, v, jnp.int32)
        slot_of_rank = slot_of_rank.at[
            jnp.where(free, free_rank, v)
        ].set(jnp.arange(v, dtype=jnp.int32), mode="drop")
        n_free = jnp.sum(free.astype(jnp.int32))
        orank = jnp.cumsum(to_ovf.astype(jnp.int32)) - 1
        ofits = to_ovf & (orank < n_free)
        pos = jnp.where(
            ofits,
            slot_of_rank[jnp.clip(orank, 0, v)],
            jnp.int32(v),  # OOB -> dropped
        )
        overwritten = jnp.sum((to_ovf & ~ofits).astype(jnp.int32))
        state = state._replace(
            ovf_valid=state.ovf_valid.at[pos].set(True, mode="drop"),
            ovf_arrival=state.ovf_arrival.at[pos].set(arrival, mode="drop"),
            ovf_from=state.ovf_from.at[pos].set(
                from_idx.astype(self.lanes.idx), mode="drop"
            ),
            ovf_to=state.ovf_to.at[pos].set(
                to_idx.astype(self.lanes.idx), mode="drop"
            ),
            ovf_type=state.ovf_type.at[pos].set(
                mtype_rows.astype(self.lanes.mtype), mode="drop"
            ),
            # head is not an allocator; kept as a monotone sent-message
            # counter for observability
            msg_head=state.msg_head + n_ok,
            dropped=state.dropped + overwritten,
        )
        if self.payload_width:
            state = state._replace(
                ovf_payload=state.ovf_payload.at[pos].set(payload, mode="drop")
            )
        if self.telemetry is not None:
            # store accounting: every ok row is either inserted (wheel or
            # overflow) or dropped (to_ovf & ~ofits — the rows behind the
            # scalar `overwritten` above), so sent - dropped rows are live.
            # HWMs sample post-insert, the only moment occupancy can peak.
            with self._scope("telemetry"):
                tele = state.tele
                state = state._replace(
                    tele=tele._replace(
                        sent=count_by_type(tele.sent, ok, mtype_rows),
                        dropped=count_by_type(
                            tele.dropped, to_ovf & ~ofits, mtype_rows
                        ),
                        wheel_fill_hwm=jnp.maximum(
                            tele.wheel_fill_hwm, jnp.max(state.whl_fill)
                        ),
                        ovf_hwm=jnp.maximum(
                            tele.ovf_hwm,
                            jnp.sum(state.ovf_valid.astype(jnp.int32)),
                        ),
                    )
                )
        return state

    def apply_emissions(self, state: SimState, emissions) -> SimState:
        for em in emissions:
            state = self.apply_emission(state, em)
        return state

    # -- delivery ------------------------------------------------------------
    def _window(self) -> int:
        """Wheel rows gathered per step: TIME_QUANTUM consecutive rows so a
        quantum-coarsened step delivers its whole window (t-q, t] at once;
        1 in flat mode (the overflow scan is already exact)."""
        if self.flat:
            return 1
        q = max(1, int(self.protocol.TIME_QUANTUM))
        if q > self.wheel_rows:
            raise ValueError(
                f"TIME_QUANTUM={q} exceeds wheel_rows={self.wheel_rows}; "
                "raise wheel_rows or use flat mode (wheel_rows=0)"
            )
        return q

    def delivery_view(self, state: SimState):
        """Build the flat delivery VIEW protocol.deliver sees: msg_* columns
        are `[D]` gathers of the due wheel window rows + the overflow lane
        (see the module docstring).  Returns (vstate, due, deliver, ctx):
        `due` is bool[D] (arrival <= t), `deliver` additionally applies the
        delivery-time down/partition discards, and `ctx` carries the wheel
        internals `_deliver_and_clear` needs for the post-deliver repack.
        Exposed as API so the static checker (wittgenstein_tpu.analysis)
        can trace `deliver` against the exact view contract."""
        t = state.time
        w, b = self.wheel_rows, self.wheel_slots
        q = self._window()
        rows = jnp.remainder(
            t - q + 1 + jnp.arange(q, dtype=jnp.int32), jnp.int32(w)
        )  # [q] distinct rows covering ticks (t-q, t]
        wv = state.msg_valid[rows]  # [q, B]
        wa = state.msg_arrival[rows]
        wf = state.msg_from[rows]
        wt = state.msg_to[rows]
        wk = state.msg_type[rows]
        wp = state.msg_payload[rows]  # [q, B, P]

        view_valid = jnp.concatenate([wv.reshape(-1), state.ovf_valid])
        view_arrival = jnp.concatenate([wa.reshape(-1), state.ovf_arrival])
        # the ONE widening point of the narrow-lane plan: protocols (and
        # every engine consumer below) see int32 ids/types regardless of
        # the storage dtypes, so kernels are unchanged by the plan
        view_from = jnp.concatenate(
            [wf.reshape(-1), state.ovf_from]
        ).astype(jnp.int32)
        view_to = jnp.concatenate(
            [wt.reshape(-1), state.ovf_to]
        ).astype(jnp.int32)
        view_type = jnp.concatenate(
            [wk.reshape(-1), state.ovf_type]
        ).astype(jnp.int32)
        view_payload = jnp.concatenate(
            [wp.reshape(q * b, -1), state.ovf_payload], axis=0
        )

        due = view_valid & (view_arrival <= t)
        # delivery-time checks: down destination or cross-partition messages
        # are discarded on arrival (Network.java:606, :518-520)
        pid_f = self.partition_id(state, state.x[view_from])
        pid_t = self.partition_id(state, state.x[view_to])
        deliver = due & ~state.down[view_to] & (pid_f == pid_t)
        if self.faults is not None:
            # fault choke point 2 (arrival): suppress delivery to
            # fault-crashed destinations and across an active group
            # partition.  Recovery needs no extra work — the crash
            # predicate simply stops holding at recover_at.  The
            # suppression mask rides in ctx so _deliver_and_clear can
            # count the rows; they still leave the store like any other
            # due row (the store invariant is fault-agnostic).
            with self._scope("faults_deliver"):
                fault_supp = due & deliver_suppress(
                    self.faults, state.faults, t, view_from, view_to
                )
                deliver = deliver & ~fault_supp
        else:
            fault_supp = None

        vstate = state._replace(
            msg_valid=view_valid,
            msg_arrival=view_arrival,
            msg_from=view_from,
            msg_to=view_to,
            msg_type=view_type,
            msg_payload=view_payload,
        )
        ctx = (rows, wv, wa, wf, wt, wk, wp, q, b, fault_supp)
        return vstate, due, deliver, ctx

    def _deliver_and_clear(self, state: SimState):
        """One tick's delivery: gather the due view (window rows + overflow
        lane), update receiver counters, run protocol.deliver on the view,
        then clear delivered entries and repack the visited rows to a dense
        prefix.  Returns (state, emissions)."""
        with self._scope("delivery"):
            return self._deliver_and_clear_impl(state)

    def _deliver_and_clear_impl(self, state: SimState):
        vview, due, deliver, ctx = self.delivery_view(state)
        rows, wv, wa, wf, wt, wk, wp, q, b, fault_supp = ctx
        view_to = vview.msg_to
        view_type = vview.msg_type

        # receiver counters skip size-0 (task-style) types, mirroring the
        # Task exemption at Network.java:522-526
        sizes = jnp.asarray(self._msg_sizes, jnp.int32)[view_type]
        dm = (deliver & (sizes > 0)).astype(jnp.int32)
        state = state._replace(
            msg_received=state.msg_received.at[view_to].add(dm, mode="drop"),
            bytes_received=state.bytes_received.at[view_to].add(
                dm * sizes, mode="drop"
            ),
        )
        if self.telemetry is not None:
            # due rows leave the store exactly once, as delivered or as
            # delivery-time discards (down dest / cross-partition) — the
            # split the store invariant needs
            with self._scope("telemetry"):
                tele = state.tele
                state = state._replace(
                    tele=tele._replace(
                        delivered=count_by_type(
                            tele.delivered, deliver, view_type
                        ),
                        discarded=count_by_type(
                            tele.discarded, due & ~deliver, view_type
                        ),
                    )
                )
        if self.faults is not None:
            # delivery-time fault discards (crashed destination / active
            # partition window); telemetry already folded them into
            # `discarded` above, this is the per-lane attribution
            fs = state.faults
            state = state._replace(
                faults=fs._replace(
                    dropped_by_fault=count_by_type(
                        fs.dropped_by_fault, fault_supp, view_type
                    )
                )
            )

        # hand the protocol a view-state whose msg_* columns are the flat
        # [D] gathers; protocols must not touch msg_* (the engine owns the
        # store), so the wheel fields are restored below
        vstate = state._replace(
            msg_valid=vview.msg_valid,
            msg_arrival=vview.msg_arrival,
            msg_from=vview.msg_from,
            msg_to=vview.msg_to,
            msg_type=vview.msg_type,
            msg_payload=vview.msg_payload,
        )
        with self._scope("protocol_deliver"):
            pstate, emissions = self.protocol.deliver(self, vstate, deliver)

        state = self._clear_visited_rows(pstate, state, ctx, due)
        return state, emissions

    def _clear_visited_rows(self, pstate, state, ctx, due) -> SimState:
        """Clear due entries from the visited window rows + overflow lane;
        surviving entries (a row visited early by a quantum window) repack
        to the slot prefix so whl_fill stays the next-free-slot index.
        `pstate` carries the protocol's post-deliver columns; the wheel
        fields are taken from the pre-view `state`."""
        rows, wv, wa, wf, wt, wk, wp, q, b, _ = ctx
        keep = wv & ~due[: q * b].reshape(q, b)
        pos = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
        tgt = jnp.where(keep, pos, b)  # OOB -> dropped scatter
        ii = jnp.arange(q, dtype=jnp.int32)[:, None]
        nv = jnp.zeros_like(wv).at[ii, tgt].set(keep, mode="drop")
        na = jnp.full_like(wa, INT_MAX).at[ii, tgt].set(wa, mode="drop")
        nf = jnp.zeros_like(wf).at[ii, tgt].set(wf, mode="drop")
        nt = jnp.zeros_like(wt).at[ii, tgt].set(wt, mode="drop")
        nk = jnp.zeros_like(wk).at[ii, tgt].set(wk, mode="drop")
        state = pstate._replace(
            msg_valid=state.msg_valid.at[rows].set(nv),
            msg_arrival=state.msg_arrival.at[rows].set(na),
            msg_from=state.msg_from.at[rows].set(nf),
            msg_to=state.msg_to.at[rows].set(nt),
            msg_type=state.msg_type.at[rows].set(nk),
            msg_payload=state.msg_payload,
            whl_fill=state.whl_fill.at[rows].set(
                jnp.sum(keep.astype(jnp.int32), axis=1)
            ),
            ovf_valid=state.ovf_valid & ~due[q * b :],
        )
        if self.payload_width:
            np_ = jnp.zeros_like(wp).at[ii, tgt].set(wp, mode="drop")
            state = state._replace(
                msg_payload=state.msg_payload.at[rows].set(np_)
            )
        return state

    # -- one millisecond (receiveUntil body, Network.java:586-632) -----------
    def _step_core(self, state: SimState) -> SimState:
        """One tick WITHOUT the time advance and WITHOUT tick_beat: wheel
        delivery + protocol.tick.  run_ms_batched's beat path guards
        tick_beat separately with a real branch."""
        if self.fuse_step:
            return self._step_core_fused(state)
        state, emissions = self._deliver_and_clear(state)
        state = self.apply_emissions(state, emissions)
        with self._scope("protocol_tick"):
            return self.protocol.tick(self, state)

    def _step_core_fused(self, state: SimState) -> SimState:
        """The fuse_step fast path (docs/engine_fused_step.md): the whole
        deliver -> clear -> send -> tick sequence traced under ONE scope,
        with the intermediate full-state round-trips removed — receiver
        counters, telemetry and fault attribution land in a single
        _replace together with the delivery view, and when the delivery
        window is one row the post-deliver repack collapses to a static
        empty-row fill (every valid entry in a singly-visited row is due:
        eff-arrival ≡ row (mod W) and eff ∈ (insert, insert+W] pin the
        visit tick to eff exactly, and jumps never overshoot an occupied
        row).  Bit-identical to _step_core by construction; pinned across
        every registered protocol by tests/test_step_fusion.py."""
        with self._scope("fused_step"):
            vview, due, deliver, ctx = self.delivery_view(state)
            q, b = ctx[7], ctx[8]
            fault_supp = ctx[9]
            view_to = vview.msg_to
            view_type = vview.msg_type
            sizes = jnp.asarray(self._msg_sizes, jnp.int32)[view_type]
            dm = (deliver & (sizes > 0)).astype(jnp.int32)
            upd = dict(
                msg_received=state.msg_received.at[view_to].add(
                    dm, mode="drop"
                ),
                bytes_received=state.bytes_received.at[view_to].add(
                    dm * sizes, mode="drop"
                ),
            )
            if self.telemetry is not None:
                tele = state.tele
                upd["tele"] = tele._replace(
                    delivered=count_by_type(tele.delivered, deliver, view_type),
                    discarded=count_by_type(
                        tele.discarded, due & ~deliver, view_type
                    ),
                )
            if self.faults is not None:
                fs = state.faults
                upd["faults"] = fs._replace(
                    dropped_by_fault=count_by_type(
                        fs.dropped_by_fault, fault_supp, view_type
                    )
                )
            # one _replace: counters + side-cars + the flat delivery view
            vstate = state._replace(
                msg_valid=vview.msg_valid,
                msg_arrival=vview.msg_arrival,
                msg_from=vview.msg_from,
                msg_to=vview.msg_to,
                msg_type=vview.msg_type,
                msg_payload=vview.msg_payload,
                **upd,
            )
            with self._scope("protocol_deliver"):
                pstate, emissions = self.protocol.deliver(
                    self, vstate, deliver
                )
            if q == 1:
                # all-due invariant: the visited row empties entirely, so
                # the sort/cumsum/scatter repack is a constant fill (in
                # flat mode the degenerate 1x1 row is never occupied and
                # the same constants are what it already holds)
                w_shape = (q, b)
                state = pstate._replace(
                    msg_valid=state.msg_valid.at[ctx[0]].set(
                        jnp.zeros(w_shape, bool)
                    ),
                    msg_arrival=state.msg_arrival.at[ctx[0]].set(
                        jnp.full(w_shape, INT_MAX, jnp.int32)
                    ),
                    msg_from=state.msg_from.at[ctx[0]].set(
                        jnp.zeros(w_shape, dtype=self.lanes.idx)
                    ),
                    msg_to=state.msg_to.at[ctx[0]].set(
                        jnp.zeros(w_shape, dtype=self.lanes.idx)
                    ),
                    msg_type=state.msg_type.at[ctx[0]].set(
                        jnp.zeros(w_shape, dtype=self.lanes.mtype)
                    ),
                    msg_payload=(
                        state.msg_payload.at[ctx[0]].set(
                            jnp.zeros(
                                w_shape + (self.payload_width,), jnp.int32
                            )
                        )
                        if self.payload_width
                        else state.msg_payload
                    ),
                    whl_fill=state.whl_fill.at[ctx[0]].set(
                        jnp.zeros(q, jnp.int32)
                    ),
                    ovf_valid=state.ovf_valid & ~due[q * b :],
                )
            else:
                state = self._clear_visited_rows(pstate, state, ctx, due)
            state = self.apply_emissions(state, emissions)
            with self._scope("protocol_tick"):
                return self.protocol.tick(self, state)

    # -- phase hooks (bench --phase-profile) ---------------------------------
    def _phase_deliver(self, state: SimState) -> SimState:
        """Delivery + clear only (emissions discarded) — the per-tick cost
        that the time wheel bounds at O(window*B + V) instead of O(C)."""
        state, _ = self._deliver_and_clear(state)
        return state

    def _phase_deliver_apply(self, state: SimState) -> SimState:
        """Delivery + emission apply (protocol.tick excluded)."""
        state, emissions = self._deliver_and_clear(state)
        return self.apply_emissions(state, emissions)

    def _tele_tick(self, state: SimState) -> SimState:
        """Per-executed-tick telemetry: tick census + (optionally) the
        progress-snapshot write, keyed by the tick just executed (called
        BEFORE the time advance, from both run paths)."""
        if self.telemetry is None:
            return state
        with self._scope("telemetry"):
            tele = state.tele._replace(ticks=state.tele.ticks + 1)
            if self.telemetry.snapshots:
                tele = record_snapshot(tele, self.telemetry, state)
            return state._replace(tele=tele)

    def step(self, state: SimState) -> SimState:
        state = self._step_core(state)
        with self._scope("beat"):
            state = self.protocol.tick_beat(self, state)
        with self._scope("post"):
            state = self.protocol.tick_post(self, state)
        state = self._tele_tick(state)
        return state._replace(time=state.time + 1)

    # -- occupancy summaries --------------------------------------------------
    def _wheel_next_arrival(self, state: SimState) -> jnp.ndarray:
        """Earliest tick >= state.time with an occupied wheel row: the
        occupancy bitmap (whl_fill > 0, packed uint32 words) rotated to
        start at the current tick, then a first-set-bit scan over W/32
        words — O(W) instead of a min over all W*B slots.  Row candidates
        equal the true arrival for in-horizon entries and never overshoot
        for stale ones, so jumps never skip a pending message."""
        t = state.time
        w = self.wheel_rows
        occ = state.whl_fill > 0  # [W]
        rot = occ[jnp.remainder(t + jnp.arange(w, dtype=jnp.int32), jnp.int32(w))]
        words = pack_bool_words(rot)
        d = lowest_set_bit(words)
        return jnp.where(jnp.any(rot), t + d, INT_MAX).astype(jnp.int32)

    def pending_messages(self, state: SimState) -> jnp.ndarray:
        """Quiescence summary: occupied wheel rows (popcount over the
        packed occupancy words) + live overflow entries.  Zero iff no
        message is pending — the DES "event queue empty" test."""
        ovf = jnp.sum(state.ovf_valid.astype(jnp.int32))
        if self.flat:
            return ovf
        return popcount_words(pack_bool_words(state.whl_fill > 0)) + ovf

    def occupancy(self, state: SimState) -> dict:
        """Observability: wheel fill high-water and overflow census of the
        CURRENT state (bench's occupancy probe samples this per tick)."""
        return {
            "wheel_fill_max": jnp.max(state.whl_fill),
            "overflow_count": jnp.sum(state.ovf_valid.astype(jnp.int32)),
        }

    def _step_jump(self, state: SimState, end) -> SimState:
        """step() plus empty-ms skipping: when the protocol has no per-ms
        tick work (TICK_INTERVAL None), jump straight to the next arrival —
        the batched analog of the oracle's event loop skipping idle time
        (nextMessage's per-ms poll, Network.java:533-545, exists only
        because conditional tasks poll empty milliseconds).  The next
        arrival comes from the wheel's occupancy-word scan plus a min over
        the small overflow lane — O(W + V), not O(C).  A protocol
        TIME_QUANTUM > 1 additionally rounds the jump target UP to the
        quantum grid, so a whole window of arrivals is delivered in one
        step (each delayed < quantum ms)."""
        state = self.step(state)
        if self.protocol.TICK_INTERVAL is None:
            with self._scope("jump"):
                q = self.protocol.TIME_QUANTUM
                ovf_next = jnp.min(
                    jnp.where(state.ovf_valid, state.ovf_arrival, INT_MAX)
                )
                if self.flat:
                    next_arrival = ovf_next
                else:
                    next_arrival = jnp.minimum(
                        self._wheel_next_arrival(state), ovf_next
                    )
                t = jnp.clip(next_arrival, state.time, end).astype(jnp.int32)
                if q > 1:
                    t = jnp.minimum(
                        (t + q - 1) // q * q, jnp.asarray(end, jnp.int32)
                    ).astype(jnp.int32)
                if self.telemetry is not None:
                    with self._scope("telemetry"):
                        tele = state.tele
                        state = state._replace(
                            tele=tele._replace(
                                jumps=tele.jumps
                                + (t > state.time).astype(jnp.int32),
                                jumped_ms=tele.jumped_ms + (t - state.time),
                            )
                        )
                state = state._replace(time=t)
        return state

    # -- the loop ------------------------------------------------------------
    def _run_ms_impl(self, state: SimState, ms: int, stop_when_done: bool) -> SimState:
        end = state.time + ms

        def cond(s):
            c = s.time < end
            if stop_when_done:
                c = c & ~self.protocol.all_done(s)
                if self.protocol.TICK_INTERVAL is None:
                    # quiescence: no pending message and no per-ms tick
                    # work means nothing can ever change — stop scanning
                    c = c & (self.pending_messages(s) > 0)
            return c

        def body(s):
            return self._step_jump(s, end)

        state = lax.while_loop(cond, body, state)
        return state._replace(time=end)

    @functools.partial(jax.jit, static_argnums=(0, 2, 3))
    def _run_ms(self, state: SimState, ms: int, stop_when_done: bool) -> SimState:
        return self._run_ms_impl(state, ms, stop_when_done)

    @functools.partial(jax.jit, static_argnums=(0, 2, 3), donate_argnums=(1,))
    def _run_ms_donated(
        self, state: SimState, ms: int, stop_when_done: bool
    ) -> SimState:
        return self._run_ms_impl(state, ms, stop_when_done)

    def run_ms(
        self,
        state: SimState,
        ms: int,
        stop_when_done: bool = False,
        donate: bool = False,
    ) -> SimState:
        """Advance `ms` simulated milliseconds (ticks [time, time+ms)).

        stop_when_done=True adds the protocol's `all_done` predicate to the
        loop condition: once the observable outcome is decided (e.g. every
        live Handel node aggregated), remaining ticks are skipped and the
        clock jumps to `end` — the batched analog of the oracle DES going
        quiescent when no events remain.  Post-done side effects (periodic
        re-offers' traffic counters) are NOT simulated, so keep the default
        for traffic-parity runs.

        donate=True donates the input state's buffers to the compiled call
        (chunked drivers that overwrite `state` each chunk stop paying a
        full state copy per chunk).  The input is INVALID afterwards —
        callers that reuse it must keep the default."""
        fn = self._run_ms_donated if donate else self._run_ms
        return fn(state, ms, stop_when_done)

    def _run_ms_batched_jumps(
        self, states: SimState, ms: int, stop_when_done: bool
    ) -> SimState:
        """Consensus-jump loop for TICK_INTERVAL-None protocols: the time
        loop runs OUTSIDE the vmap and every iteration executes ONE
        replica-uniform tick — the minimum clock over still-running lanes
        — then each lane's own `_step_jump` advances it past its empty
        milliseconds exactly as on the singleton path.

        Bitwise identity with the ungated vmapped fallback is by
        construction, not by an emptiness argument: a lane steps iff the
        consensus tick equals its own clock, and lane clocks only move
        when the lane steps, so each lane executes exactly its singleton
        tick set (same per-event RNG stream — every executed tick burns
        one send_ctr).  Lanes not at the consensus tick are computed and
        discarded by the element-wise select, like any masked vmap lane.

        What the gate buys over the fallback: `time` is carried as a
        loop-scalar, so the wheel-row addressing inside the step
        (delivery gather, occupancy rotation) is replica-uniform —
        shared dynamic slices instead of per-lane gathers.  Iterations
        count the UNION of lane tick sets rather than the per-lane max,
        so the lever is priced by the paired A/B (profiling.md), not
        assumed."""
        proto = self.protocol
        ends = states.time + ms  # per-lane horizon, like _run_ms_impl

        def lane_alive(s, e):
            c = s.time < e
            if stop_when_done:
                c = c & ~proto.all_done(s)
                # quiescence: no pending message and no per-ms tick work
                # means nothing can ever change — stop scanning
                c = c & (self.pending_messages(s) > 0)
            return c

        alive_v = jax.vmap(lane_alive)
        # time rides as an UNBATCHED scalar through the step: every lane
        # that executes does so at the shared consensus tick, so wheel
        # addressing is replica-uniform (the whole point of the gate)
        axes = SimState(
            **{f: (None if f == "time" else 0) for f in SimState._fields}
        )
        jump_v = jax.vmap(self._step_jump, in_axes=(axes, 0), out_axes=0)

        def w_cond(ss):
            return jnp.any(alive_v(ss, ends))

        def w_body(ss):
            alive = alive_v(ss, ends)
            t = jnp.min(
                jnp.where(alive, ss.time, jnp.int32(INT_MAX))
            ).astype(jnp.int32)
            active = alive & (ss.time == t)
            stepped = jump_v(ss._replace(time=t), ends)
            return jax.tree_util.tree_map(
                lambda new, old: jnp.where(
                    active.reshape(active.shape + (1,) * (old.ndim - 1)),
                    new,
                    old,
                ),
                stepped,
                ss,
            )

        states = lax.while_loop(w_cond, w_body, states)
        return states._replace(time=ends)

    def _run_ms_batched_impl(
        self, states: SimState, ms: int, stop_when_done: bool
    ) -> SimState:
        proto = self.protocol
        if self.batched_jumps and proto.TICK_INTERVAL is None:
            return self._run_ms_batched_jumps(states, ms, stop_when_done)
        period, residues = proto.BEAT_PERIOD, proto.BEAT_RESIDUES
        if (
            proto.TICK_INTERVAL != 1
            or not period
            or residues is None
            or len(residues) >= period
        ):
            return jax.vmap(
                lambda s: self._run_ms_impl(s, ms, stop_when_done)
            )(states)

        step_v = jax.vmap(self._step_core)

        def _beat(s):
            with self._scope("beat"):
                return proto.tick_beat(self, s)

        def _post(s):
            with self._scope("post"):
                return proto.tick_post(self, s)

        beat_v = jax.vmap(_beat)
        post_v = jax.vmap(_post)
        res = jnp.asarray(sorted(residues), jnp.int32)

        def skip_beat(s):
            # keep the per-event RNG stream identical to the ungated path,
            # where the masked beat call still advanced send_ctr
            return s._replace(send_ctr=s.send_ctr + proto.BEAT_SEND_CALLS)

        def body(_, s):
            # any-over-replicas: for the normal lockstep batch this equals
            # replica 0's beat test; for a batch with non-uniform clocks
            # (stacked mid-run states) tick_beat fires whenever ANY replica
            # beats, and its per-node masks no-op the others — correct
            # either way, and send_ctr advances by exactly 1 on every path
            is_beat = jnp.any(
                lax.rem(s.time.reshape(-1)[:, None], jnp.int32(period))
                == res[None, :]
            )
            s = step_v(s)
            s = lax.cond(is_beat, beat_v, skip_beat, s)
            s = post_v(s)
            if self.telemetry is not None:
                s = jax.vmap(self._tele_tick)(s)
            return s._replace(time=s.time + 1)

        if not stop_when_done:
            return lax.fori_loop(0, ms, body, states)

        def w_cond(carry):
            i, s = carry
            return (i < ms) & ~jnp.all(jax.vmap(proto.all_done)(s))

        def w_body(carry):
            i, s = carry
            return i + 1, body(i, s)

        i_fin, states = lax.while_loop(w_cond, w_body, (jnp.int32(0), states))
        # normalize the lockstep clocks to the full horizon, like run_ms
        return states._replace(time=states.time + (ms - i_fin))

    @functools.partial(jax.jit, static_argnums=(0, 2, 3))
    def _run_ms_batched(
        self, states: SimState, ms: int, stop_when_done: bool
    ) -> SimState:
        return self._run_ms_batched_impl(states, ms, stop_when_done)

    @functools.partial(jax.jit, static_argnums=(0, 2, 3), donate_argnums=(1,))
    def _run_ms_batched_donated(
        self, states: SimState, ms: int, stop_when_done: bool
    ) -> SimState:
        return self._run_ms_batched_impl(states, ms, stop_when_done)

    def run_ms_batched(
        self,
        states: SimState,
        ms: int,
        stop_when_done: bool = False,
        donate: bool = False,
    ) -> SimState:
        """vmapped run over the leading replica axis — the TPU replacement
        for RunMultipleTimes' sequential reseeded loop.

        When the protocol declares a sparse beat structure (BEAT_PERIOD +
        BEAT_RESIDUES), the time loop runs OUTSIDE the vmap: replicas
        advance time in lockstep, so the tick index is replica-uniform and
        tick_beat can be guarded by a real lax.cond — off-beat ticks skip
        the periodic work instead of executing it masked (a vmapped
        lax.cond would execute both branches).

        stop_when_done stops the LOCKSTEP loop once every replica's
        all_done holds (see run_ms).  On the ungated fallback path the
        flag is semantics-only: vmapped while_loops mask finished lanes
        rather than skip them, so the body runs until the SLOWEST replica
        finishes either way.

        donate=True: see run_ms — the input pytree is consumed."""
        fn = self._run_ms_batched_donated if donate else self._run_ms_batched
        return fn(states, ms, stop_when_done)

    @functools.partial(jax.jit, static_argnums=(0, 2))
    def run_ms_occupancy(self, state: SimState, ms: int):
        """Instrumented single-replica run: `ms` plain per-tick steps (no
        empty-ms jumps, so every tick's occupancy is sampled) returning
        (state, {wheel_fill_hwm, overflow_hwm}) — the wheel's high-water
        marks for bench's --phase-profile record."""

        def body(_, carry):
            s, hw_fill, hw_ovf = carry
            s = self.step(s)
            hw_fill = jnp.maximum(hw_fill, jnp.max(s.whl_fill))
            hw_ovf = jnp.maximum(
                hw_ovf, jnp.sum(s.ovf_valid.astype(jnp.int32))
            )
            return (s, hw_fill, hw_ovf)

        state, hw_fill, hw_ovf = lax.fori_loop(
            0, ms, body, (state, jnp.int32(0), jnp.int32(0))
        )
        return state, {"wheel_fill_hwm": hw_fill, "overflow_hwm": hw_ovf}


def replicate_state(state: SimState, n_replicas: int, seeds=None) -> SimState:
    """Tile a single-replica state along a new leading replica axis, giving
    each replica its own dynamics seed.  (Distinct node layouts per replica
    can be had by stacking init_state outputs instead.)"""
    if seeds is None:
        seeds = np.arange(n_replicas, dtype=np.int32)
    seeds = jnp.asarray(seeds, jnp.int32)
    tiled = jax.tree_util.tree_map(
        lambda a: jnp.broadcast_to(a, (n_replicas,) + a.shape), state
    )
    return tiled._replace(seed=seeds)


def stack_states(states) -> SimState:
    """Stack independently-built single-replica states (heterogeneous node
    layouts, the exact analog of RunMultipleTimes' per-seed re-init)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *states)

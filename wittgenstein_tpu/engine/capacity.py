"""Telemetry-sized message-store capacities (the density war's second
front, next to engine.density's narrow dtypes).

The engine's wheel/overflow defaults (core.BatchedNetwork.__init__) are
sized for "never drop", which at flagship scale means paying for slots
no run ever fills.  This module is the contract between the measured
occupancy high-water marks and the knobs the constructors accept:

  scripts/density_autotune.py   probes each registered protocol config
                                with run_ms_occupancy() (wheel/overflow
                                HWMs) plus the Handel candidate-slot
                                occupancy probe, and writes the results
                                into CAPACITY.json at the repo root.
  engine/capacity.py (here)     loads/validates that table and turns an
                                entry into constructor overrides
                                (sized_overrides()).
  state.dropped                 remains the RUNTIME guard: a sized run
                                that ever hits its ceiling shows up as a
                                nonzero dropped counter, and the
                                capacity regression test fails.

Sizing rule: sized = max(floor, ceil(hwm * margin)) rounded up to a
multiple of 8 (friendly to the bitset word layout and vector lanes).
The margin (default 1.5x) covers seed-to-seed occupancy variance; the
probe records which seeds/horizon produced the HWM so a stale table is
auditable.  Handel's cand_slots uses hwm + 1 instead — the top-K buffer
is re-sorted every tick, so any K' strictly above the post-tick
occupancy HWM is bit-identical to the engine default (see
docs/density.md); one spare slot is the guard band.
"""

from __future__ import annotations

import json
import math
import os
from dataclasses import dataclass, field
from typing import Dict, Optional

CAPACITY_SCHEMA = "witt-capacity/v1"
CAPACITY_BASENAME = "CAPACITY.json"

# seed-to-seed occupancy variance guard for wheel/overflow sizing
DEFAULT_MARGIN = 1.5
# never size below these, however empty the probe ran: the engine
# rejects degenerate stores and tiny pads cost nothing
MIN_WHEEL_SLOTS = 8
MIN_OVERFLOW = 16


def capacity_path(root: Optional[str] = None) -> str:
    """Repo-root CAPACITY.json (root defaults to the package parent)."""
    if root is None:
        root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
    return os.path.join(root, CAPACITY_BASENAME)


def size_from_hwm(
    hwm: int, margin: float = DEFAULT_MARGIN, floor: int = MIN_OVERFLOW
) -> int:
    """hwm -> capacity: margin, floor, then round up to a multiple of 8."""
    sized = max(int(floor), int(math.ceil(int(hwm) * float(margin))))
    return -(-sized // 8) * 8


@dataclass(frozen=True)
class CapacityEntry:
    """One probed (protocol, n_nodes) config from CAPACITY.json."""

    protocol: str
    n_nodes: int
    hwms: Dict[str, int]
    sized: Dict[str, int]
    margin: float = DEFAULT_MARGIN
    probe: Dict = field(default_factory=dict)
    dropped: int = 0

    @property
    def key(self) -> str:
        return f"{self.protocol}@{self.n_nodes}"

    def to_json(self) -> dict:
        return {
            "protocol": self.protocol,
            "n_nodes": self.n_nodes,
            "hwms": dict(self.hwms),
            "sized": dict(self.sized),
            "margin": self.margin,
            "probe": dict(self.probe),
            "dropped": self.dropped,
        }


def _entry_problems(key: str, e: dict) -> list:
    """Schema/consistency findings for one table entry (strings)."""
    out = []
    for f in ("protocol", "n_nodes", "hwms", "sized"):
        if f not in e:
            out.append(f"{key}: missing field {f!r}")
    if out:
        return out
    if key != f"{e['protocol']}@{e['n_nodes']}":
        out.append(f"{key}: key does not match protocol@n_nodes fields")
    if int(e.get("dropped", 0)) != 0:
        out.append(
            f"{key}: probe recorded dropped={e['dropped']} — sized run"
            " lost messages; re-probe with larger capacity"
        )
    margin = float(e.get("margin", DEFAULT_MARGIN))
    hwms, sized = e["hwms"], e["sized"]
    # every sized wheel/overflow knob must still satisfy the margin rule
    # against its recorded HWM (a hand-edited number fails loudly)
    for knob, hwm_key, floor in (
        ("wheel_slots", "wheel_fill_hwm", MIN_WHEEL_SLOTS),
        ("overflow_capacity", "overflow_hwm", MIN_OVERFLOW),
    ):
        if knob in sized:
            if hwm_key not in hwms:
                out.append(f"{key}: sized {knob} without recorded {hwm_key}")
            elif int(sized[knob]) < size_from_hwm(
                int(hwms[hwm_key]), margin, floor
            ):
                out.append(
                    f"{key}: sized {knob}={sized[knob]} below the margin"
                    f" rule for {hwm_key}={hwms[hwm_key]} (margin {margin})"
                )
    if "cand_slots" in sized:
        if "cand_occ_hwm" not in hwms:
            out.append(f"{key}: sized cand_slots without cand_occ_hwm")
        elif int(sized["cand_slots"]) < int(hwms["cand_occ_hwm"]) + 1:
            out.append(
                f"{key}: cand_slots={sized['cand_slots']} leaves no guard"
                f" slot over cand_occ_hwm={hwms['cand_occ_hwm']}"
                " (bit-identity needs occupancy < K)"
            )
    return out


def validate_table(doc: dict) -> list:
    """All schema problems in a loaded CAPACITY.json doc ([] = valid)."""
    if not isinstance(doc, dict):
        return ["capacity table is not a JSON object"]
    if doc.get("schema") != CAPACITY_SCHEMA:
        return [
            f"schema is {doc.get('schema')!r}, expected {CAPACITY_SCHEMA!r}"
        ]
    entries = doc.get("entries")
    if not isinstance(entries, dict):
        return ["entries missing or not an object"]
    problems = []
    for key, e in entries.items():
        problems.extend(_entry_problems(key, e))
    return problems


def load_capacity(root: Optional[str] = None) -> Optional[dict]:
    """Parsed CAPACITY.json, or None when absent/unreadable/invalid.
    Callers treat None as "no table": constructors keep their defaults,
    so a deleted table degrades to the safe over-provisioned sizing."""
    path = capacity_path(root)
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc if not validate_table(doc) else None


def lookup(
    table: Optional[dict], protocol: str, n_nodes: int
) -> Optional[CapacityEntry]:
    """The CapacityEntry for protocol@n_nodes, or None."""
    if not table:
        return None
    e = table.get("entries", {}).get(f"{protocol}@{int(n_nodes)}")
    if e is None:
        return None
    return CapacityEntry(
        protocol=e["protocol"],
        n_nodes=int(e["n_nodes"]),
        hwms={k: int(v) for k, v in e["hwms"].items()},
        sized={k: int(v) for k, v in e["sized"].items()},
        margin=float(e.get("margin", DEFAULT_MARGIN)),
        probe=dict(e.get("probe", {})),
        dropped=int(e.get("dropped", 0)),
    )


ENGINE_KNOBS = ("wheel_slots", "overflow_capacity")
PROTOCOL_KNOBS = ("cand_slots",)


def sized_overrides(
    entry: Optional[CapacityEntry],
) -> Dict[str, Dict[str, int]]:
    """Split an entry's sized knobs into the two constructor surfaces:
    {"engine": {wheel_slots/overflow_capacity...},
     "protocol": {cand_slots...}}.  Empty dicts when entry is None —
    callers can always ** the result."""
    out: Dict[str, Dict[str, int]] = {"engine": {}, "protocol": {}}
    if entry is None:
        return out
    for k, v in entry.sized.items():
        if k in ENGINE_KNOBS:
            out["engine"][k] = int(v)
        elif k in PROTOCOL_KNOBS:
            out["protocol"][k] = int(v)
    return out

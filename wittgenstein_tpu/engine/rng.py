"""Counter-based RNG for the batched engine.

The reference derives per-destination latency jitter from a single random
seed and the destination id via an xorshift hash (Network.getPseudoRandom,
Network.java:493-503) precisely so that one multicast envelope never has to
store per-destination state.  That trick *is* counter-based RNG, so the
batched engine keeps the exact same hash, vectorized, and derives the
per-event seeds from (replica_seed, time, stream, counter) with a murmur3
finalizer instead of a sequential java.util.Random stream (which cannot be
consumed in parallel).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def _i32(x):
    return jnp.asarray(x).astype(jnp.int32)


def pseudo_delta(dest_id, seed):
    """Deterministic delta in [0, 99] from (destId, seed) — bit-exact
    vectorization of Network.getPseudoRandom (Network.java:493-503)."""
    a = _i32(dest_id)
    a = a ^ (a << 13)
    a = a ^ lax.shift_right_logical(a, 17)
    a = a ^ (a << 5)
    x = a ^ _i32(seed)
    return jnp.abs(lax.rem(x, jnp.int32(100)))


def _mix32(x):
    """murmur3 fmix32 avalanche on uint32."""
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x


def hash32(*parts):
    """Combine integer parts into one well-mixed int32 (the batched stand-in
    for `rd.nextInt()` seeds; order-sensitive, collision-resistant)."""
    h = jnp.uint32(0x9E3779B9)
    for p in parts:
        p = jnp.asarray(p).astype(jnp.uint32)
        h = _mix32(h ^ (p + jnp.uint32(0x9E3779B9) + (h << 6) + (h >> 2)))
    return h.astype(jnp.int32)


def uniform_u01(*parts):
    """Deterministic float32 uniform in [0, 1) from integer parts."""
    bits = hash32(*parts).astype(jnp.uint32) >> jnp.uint32(8)
    return bits.astype(jnp.float32) * jnp.float32(1.0 / (1 << 24))

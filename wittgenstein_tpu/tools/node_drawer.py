"""World-map node visualization + animated GIF.

Reference semantics: tools/NodeDrawer.java:24-286 (+ GifSequenceWriter):
nodes drawn as SIZE x SIZE dots on the world map, colored red -> yellow ->
green by a protocol-provided value, 'special' nodes marked, positions
allocated once on first sight via an outward spiral so dots never overlap
and never move between frames.  Frames accumulate palette-quantized and
are written as an animated GIF by PIL on close() (the reference bundles a
CC-BY GifSequenceWriter for the same job).

The NodeStatus plug-in interface is the reference's
(NodeDrawer.NodeStatus, :30-48): get_val / is_special / get_max / get_min.
"""

from __future__ import annotations

import math
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core.geo import MAX_X, MAX_Y

SIZE = 5  # dot size in pixels (NodeDrawer.java:25)
_MAP = os.path.join(os.path.dirname(__file__), os.pardir, "data", "world_map_2000px.png")


class NodeStatus:
    """Protocol-status plug-in (NodeDrawer.NodeStatus, :30-48)."""

    def get_val(self, n) -> int:
        raise NotImplementedError

    def is_special(self, n) -> bool:
        raise NotImplementedError

    def get_max(self) -> int:
        raise NotImplementedError

    def get_min(self) -> int:
        raise NotImplementedError


def _make_color(value: int) -> Tuple[int, int, int]:
    """Red -> yellow -> green ramp over [0, 510] (NodeDrawer.java:208-230)."""
    value = min(max(0, value), 510)
    if value < 255:
        red = 255
        green = int(math.sqrt(value) * 16)
    else:
        green = 255
        value = value - 255
        red = 255 - (value * value // 255)
    return red, green, 0


class NodeDrawer:
    """Draw per-tick node states; optionally stream frames to a GIF."""

    def __init__(self, node_status: NodeStatus, animated_dest: Optional[str] = None, frequency_ms: int = 10):
        from PIL import Image

        self.status = node_status
        self.min = node_status.get_min() - 1  # avoid division by zero (:88)
        self.max = node_status.get_max()
        if self.min >= self.max or self.min < -1:
            raise ValueError(f"bad values for min={node_status.get_min()} or max={node_status.get_max()}")
        self.background = Image.open(_MAP).convert("RGB")
        self.dots = np.zeros((MAX_X, MAX_Y), dtype=bool)
        self.node_pos: Dict[int, Tuple[int, int]] = {}
        self.last_img = None
        self._dest = animated_dest
        self._frequency_ms = frequency_ms
        self._frames: List = []  # palette-quantized to bound memory

    # -- stable non-overlapping dot allocation (NodeDrawer.java:117-205) ----
    def _is_free(self, x: int, y: int) -> bool:
        if x < 1 or x >= MAX_X - SIZE or y < 1 or y >= MAX_Y - SIZE:
            return False
        return not self.dots[x : x + SIZE, y : y + SIZE].any()

    def _find_pos(self, n) -> Tuple[int, int]:
        pos = self.node_pos.get(n.node_id)
        if pos is not None:
            return pos
        delta_x = delta_y = 0
        was_x = False
        distance = 0
        while distance < 200:
            for x in range(max(1, n.x - delta_x), min(MAX_X, n.x + delta_x), SIZE):
                for y in range(max(1, n.y - delta_y), min(MAX_Y, n.y + delta_y), SIZE):
                    d = math.hypot((x - n.x) * SIZE, (y - n.y) * SIZE)
                    if d <= distance * SIZE and self._is_free(x, y):
                        self.dots[x : x + SIZE, y : y + SIZE] = True
                        self.node_pos[n.node_id] = (x, y)
                        return x, y
            if was_x:
                delta_y += SIZE
            else:
                delta_x += SIZE
            was_x = not was_x
            distance += 1
        raise RuntimeError(f"No free room for node {n.node_id}, x={n.x}, y={n.y}")

    # -- frames --------------------------------------------------------------
    def draw_new_state(self, time_ms: int, live_nodes: List) -> None:
        from PIL import ImageDraw

        img = self.background.copy()
        draw = ImageDraw.Draw(img)
        for n in live_nodes:
            x, y = self._find_pos(n)
            val = self.status.get_val(n)
            ratio = (val - self.min) / (self.max - self.min)
            color = _make_color(int(510 * ratio))
            draw.rectangle([x, y, x + SIZE - 1, y + SIZE - 1], fill=color)
            if self.status.is_special(n):
                draw.point((x + SIZE // 2, y + SIZE // 2), fill=(0, 0, 255))
        # white-on-dark with a shadow so the stamp reads on the map corner
        draw.text((11, 11), f"{time_ms} ms", fill=(0, 0, 0))
        draw.text((10, 10), f"{time_ms} ms", fill=(255, 255, 255))
        self.last_img = img
        if self._dest is not None:
            self._frames.append(img.convert("P", palette="ADAPTIVE"))

    def write_last_to_png(self, dest: str) -> None:
        if self.last_img is None:
            raise RuntimeError("no frame drawn yet")
        self.last_img.save(dest)

    def close(self) -> None:
        if self._dest is not None and self._frames:
            self._frames[0].save(
                self._dest,
                save_all=True,
                append_images=self._frames[1:],
                duration=self._frequency_ms,
                loop=0,
            )
            self._dest = None
            self._frames = []

    def __enter__(self) -> "NodeDrawer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

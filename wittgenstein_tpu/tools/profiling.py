"""Profiling helpers (the SURVEY §5 'tracing/profiling' upgrade — the
reference's observability is counters + stdout; here device-level traces
come from jax.profiler).

Usage:

    from wittgenstein_tpu.tools.profiling import trace
    with trace("/tmp/witt-trace"):
        out = net.run_ms_batched(states, 1000)
        jax.block_until_ready(out)

The trace directory opens in TensorBoard's profile plugin / Perfetto.
`bench.py` exposes the same via WITT_BENCH_PROFILE=<dir>.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """jax.profiler trace over the with-block (always stopped, even on
    failure — a leaked active profiler poisons every later start_trace)."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named region inside a trace (shows up on the TraceMe track)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield


class WallClock:
    """Tiny host-side timer for compile/run splits (the pattern bench.py
    uses): `with WallClock() as w: ...; w.seconds`."""

    def __enter__(self) -> "WallClock":
        self._t0 = time.perf_counter()
        self.seconds: Optional[float] = None
        return self

    def __exit__(self, *exc) -> None:
        self.seconds = time.perf_counter() - self._t0

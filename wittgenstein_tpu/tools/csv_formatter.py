"""Column-ordered CSV emitter for scenario results.

Reference semantics: tools/CSVFormatter.java — fixed field order given at
construction, rows appended as dicts, missing values empty."""

from __future__ import annotations

import io
from typing import Dict, List


class CSVFormatter:
    def __init__(self, name: str, fields: List[str]):
        self.name = name
        self.fields = list(fields)
        self.rows: List[Dict] = []

    def add(self, row: Dict) -> None:
        self.rows.append(dict(row))

    def to_string(self) -> str:
        out = io.StringIO()
        out.write(f"{self.name}\n")
        out.write(",".join(self.fields) + "\n")
        for row in self.rows:
            out.write(
                ",".join(
                    "" if row.get(f) is None else str(row.get(f)) for f in self.fields
                )
                + "\n"
            )
        return out.getvalue()

    def save(self, dest: str) -> None:
        with open(dest, "w") as f:
            f.write(self.to_string())

    def __str__(self) -> str:
        return self.to_string()

"""Series-to-PNG charting.

Reference semantics: tools/Graph.java (xchart) re-done with matplotlib:
Series of (x, y) report lines, statSeries min/max/avg envelope across
same-x series (Graph.java:214-250), cleanSeries flat-tail trimming
(Graph.java:167-192).
"""

from __future__ import annotations

from typing import List, Optional

EPS = 1e-9


class ReportLine:
    __slots__ = ("x", "y")

    def __init__(self, x: float, y: float):
        self.x = float(x)
        self.y = float(y)


class Series:
    def __init__(self, description: str = ""):
        self.description = description
        self.vals: List[ReportLine] = []

    def add_line(self, line: ReportLine) -> None:
        self.vals.append(line)


class StatSeries:
    def __init__(self, min_s: Series, max_s: Series, avg_s: Series):
        self.min = min_s
        self.max = max_s
        self.avg = avg_s


def stat_series(title: str, series: List[Series]) -> StatSeries:
    """Per-index min/max/avg across series; indexes must share x values.
    Exhausted (shorter) series carry their last value into the average but
    not min/max, and the divisor is the full series count — exactly
    Graph.statSeries (Graph.java:214-250)."""
    s_min = Series(f"{title}(min)")
    s_max = Series(f"{title}(max)")
    s_avg = Series(f"{title}(avg)")
    largest = max(series, key=lambda s: len(s.vals), default=None)
    for i in range(len(largest.vals) if largest else 0):
        x = largest.vals[i].x
        tot = 0.0
        mn, mx = float("inf"), float("-inf")
        for s in series:
            if i < len(s.vals):
                if abs(s.vals[i].x - x) > EPS:
                    raise ValueError(
                        f"We need the indexes to be the same, x={x}, lx={s.vals[i].x}"
                    )
                y = s.vals[i].y
                tot += y
                mn = min(mn, y)
                mx = max(mx, y)
            else:
                tot += s.vals[-1].y
        s_min.add_line(ReportLine(x, mn))
        s_max.add_line(ReportLine(x, mx))
        s_avg.add_line(ReportLine(x, tot / len(series)))
    return StatSeries(s_min, s_max, s_avg)


class Graph:
    def __init__(self, graph_title: str, x_name: str, y_name: str):
        self.graph_title = graph_title
        self.x_name = x_name
        self.y_name = y_name
        self.series: List[Series] = []
        self.forced_min_y: Optional[float] = None

    def add_serie(self, s: Series) -> None:
        self.series.append(s)

    def set_forced_min_y(self, y: float) -> None:
        self.forced_min_y = y

    def clean_series(self) -> None:
        """Trim trailing entries where every series has gone flat
        (Graph.java:167-192); all series must share one length."""
        if not self.series:
            return
        unique_size = len(self.series[0].vals)
        for s in self.series:
            if len(s.vals) != unique_size:
                raise ValueError(
                    f"different size uniqueSize={unique_size}, size={len(s.vals)}"
                )
        last = [s.vals[unique_size - 1].y for s in self.series]
        for i in range(unique_size - 2, 1, -1):
            for ii, s in enumerate(self.series):
                if abs(last[ii] - s.vals[i].y) > EPS:
                    return
            for s in self.series:
                s.vals.pop()

    def save(self, dest: str) -> None:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        fig, ax = plt.subplots(figsize=(12, 8))
        for s in self.series:
            ax.plot(
                [v.x for v in s.vals],
                [v.y for v in s.vals],
                label=s.description or None,
                linewidth=1.2,
            )
        ax.set_title(self.graph_title)
        ax.set_xlabel(self.x_name)
        ax.set_ylabel(self.y_name)
        if self.forced_min_y is not None:
            ax.set_ylim(bottom=self.forced_min_y)
        if any(s.description for s in self.series):
            ax.legend(loc="best", fontsize=8)
        ax.grid(True, alpha=0.3)
        fig.savefig(dest, dpi=150, bbox_inches="tight")
        plt.close(fig)

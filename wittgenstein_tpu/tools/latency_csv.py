"""City-to-city ping matrix loader.

Reference semantics: tools/CSVLatencyReader.java — loads per-city
wondernetwork ping CSVs (`Data/<City>/<City>Ping.csv`), builds an
(asymmetric-source, symmetric-fallback) city->city->ms map with
SAME_CITY_LATENCY=30, and drops cities for which some pair has no
measurement in either direction.

This module reads the baked dense matrix from wittgenstein_tpu/data when
present (produced by tools/bake_data.py from the reference's public data
files), otherwise parses the CSV tree directly.
"""

from __future__ import annotations

import csv
import os
from typing import Dict, List, Optional

import numpy as np

SAME_CITY_LATENCY = 30.0

_DATA_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "data")
_REFERENCE_DATA = "/root/reference/core/src/main/resources/Data"
_BAKED = os.path.join(_DATA_DIR, "city_latency.npz")


class CSVLatencyReader:
    """API parity with the reference: .cities() and .get_latency(from, to)."""

    def __init__(self, data_dir: Optional[str] = None):
        if data_dir is None and os.path.exists(_BAKED):
            z = np.load(_BAKED, allow_pickle=False)
            self._names = [str(s) for s in z["names"]]
            self._matrix = z["matrix"].astype(np.float32)
        else:
            if data_dir is None:
                data_dir = _REFERENCE_DATA
            names, matrix = build_matrix_from_csvs(data_dir)
            self._names = names
            self._matrix = matrix
        self._index = {n: i for i, n in enumerate(self._names)}

    def cities(self) -> List[str]:
        return list(self._names)

    def city_index(self) -> Dict[str, int]:
        return dict(self._index)

    def matrix(self) -> np.ndarray:
        """Dense [C, C] float32, resolved (from-side value, else to-side),
        diagonal == SAME_CITY_LATENCY."""
        return self._matrix

    def get_latency(self, city_from: str, city_to: str) -> float:
        return float(self._matrix[self._index[city_from], self._index[city_to]])

    def get_latency_matrix(self) -> Dict[str, Dict[str, float]]:
        return {
            a: {b: float(self._matrix[i, j]) for j, b in enumerate(self._names)}
            for i, a in enumerate(self._names)
        }


def _city_from_row(city_and_location: str, all_cities: List[str]) -> Optional[str]:
    """Longest city name (spaces form) contained in the CSV's 'City Country,
    Region' column (CSVLatencyReader.processCityName)."""
    best = None
    for c in all_cities:
        if c.replace("+", " ") in city_and_location:
            if best is None or len(c) > len(best):
                best = c
    return best


def build_matrix_from_csvs(data_dir: str):
    """Parse the per-city ping CSV tree into (names, resolved dense matrix)."""
    cities = sorted(os.listdir(data_dir))
    raw: Dict[str, Dict[str, float]] = {}
    for city in cities:
        path = os.path.join(data_dir, city, city + "Ping.csv")
        if not os.path.exists(path):
            continue
        row_map: Dict[str, float] = {}
        with open(path, newline="", encoding="utf-8", errors="replace") as f:
            reader = csv.reader(f)
            next(reader)  # header
            for row in reader:
                if len(row) < 5:
                    continue
                target = _city_from_row(row[0], cities)
                if target is not None:
                    try:
                        row_map[target] = float(row[4])
                    except ValueError:
                        pass
        row_map[city] = SAME_CITY_LATENCY
        raw[city] = row_map

    # Drop cities where some pair has no measurement in either direction
    names = list(raw.keys())
    missing = set()
    for a in names:
        for b in names:
            if b not in raw[a] and a not in raw[b]:
                missing.add(a)
                break
    names = [n for n in names if n not in missing]

    c = len(names)
    matrix = np.zeros((c, c), dtype=np.float32)
    for i, a in enumerate(names):
        for j, b in enumerate(names):
            v = raw[a].get(b)
            if v is None:
                v = raw[b][a]
            matrix[i, j] = v
    return names, matrix

"""Bake the public geographic/latency data into dense npz arrays.

Run once (requires the reference data tree or any same-format data tree):

    python -m wittgenstein_tpu.tools.bake_data [--src DIR]

Produces:
  wittgenstein_tpu/data/geo_cities.npz    names, merc_x, merc_y, population
  wittgenstein_tpu/data/city_latency.npz  names, matrix[C,C] float32
"""

from __future__ import annotations

import argparse
import os

import numpy as np

from ..core.geo import parse_cities_csv
from .latency_csv import build_matrix_from_csvs

_DATA_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "data")


def bake(src: str = "/root/reference/core/src/main/resources", out_dir: str = _DATA_DIR):
    os.makedirs(out_dir, exist_ok=True)

    cities = parse_cities_csv(os.path.join(src, "cities.csv"))
    names = list(cities.keys())
    np.savez_compressed(
        os.path.join(out_dir, "geo_cities.npz"),
        names=np.array(names),
        merc_x=np.array([cities[n][0] for n in names], dtype=np.int32),
        merc_y=np.array([cities[n][1] for n in names], dtype=np.int32),
        population=np.array([cities[n][2] for n in names], dtype=np.int64),
    )

    lat_names, matrix = build_matrix_from_csvs(os.path.join(src, "Data"))
    np.savez_compressed(
        os.path.join(out_dir, "city_latency.npz"),
        names=np.array(lat_names),
        matrix=matrix,
    )
    print(f"baked {len(names)} cities, latency matrix {matrix.shape}")


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--src", default="/root/reference/core/src/main/resources")
    ap.add_argument("--out", default=_DATA_DIR)
    args = ap.parse_args()
    bake(args.src, args.out)

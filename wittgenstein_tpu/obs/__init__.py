"""wittgenstein_tpu.obs — the correlated observability spine.

One TraceContext (run_id / job_id / tenant_id / chunk_seq) minted at
serve admission or bench entry and threaded through the scheduler, the
supervisor, checkpoint manifests, SpanTracer spans, and serve metrics;
one FlightRecorder ring of structured events replayable by
scripts/obs_query.py; per-tenant attribution sliced from the packed
replica axis.  Host-side only — sim state is bit-identical with all of
it armed.  See docs/observability.md for the id-join map.
"""

from .attribution import batch_attribution, replica_rows
from .context import TraceContext, mint_context, new_run_id
from .monitor import InvariantSentinel, load_capacity_table
from .recorder import (
    DUMP_BASENAME,
    ENV_DIR,
    LIVE_BASENAME,
    FlightRecorder,
    failure_dump_paths,
    get_recorder,
    read_events,
    reset_default_recorder,
)
from .slo import (
    REGISTERED_SLOS,
    SLOEngine,
    SLOSpec,
    default_serve_specs,
)
from .timeseries import TimeSeriesStore

__all__ = [
    "TraceContext",
    "mint_context",
    "new_run_id",
    "FlightRecorder",
    "get_recorder",
    "reset_default_recorder",
    "read_events",
    "failure_dump_paths",
    "batch_attribution",
    "replica_rows",
    "TimeSeriesStore",
    "SLOSpec",
    "SLOEngine",
    "REGISTERED_SLOS",
    "default_serve_specs",
    "InvariantSentinel",
    "load_capacity_table",
    "LIVE_BASENAME",
    "DUMP_BASENAME",
    "ENV_DIR",
]

"""Trace context: the correlated identity spine of a run.

Every layer of the stack already emits records — SpanTracer Chrome
traces (telemetry/trace.py), JSONL run records (telemetry/export.py),
supervisor provenance + checkpoint manifests (runtime/supervisor.py,
engine/checkpoint.py), serve metrics (serve/metrics.py) — but until
this module they were uncorrelated: a failed job could not be
reconstructed end-to-end without hand-joining logs (the r3-r5 tunnel
postmortems).  A TraceContext is minted ONCE, at serve admission or
bench entry, and threaded through everything; every record that
carries ``run_id`` can be joined.

Identity semantics:

- ``run_id``    — one durable *run* of work.  Survives SIGKILL + resume:
                  the supervisor writes it into the checkpoint manifest
                  and ADOPTS the stored id when resuming, so the victim
                  process and the resume process share one run_id.
- ``job_id``    — the serve-layer job (``job-NNNNNN``) when the run came
                  through /w/jobs; None for bench / campaign runs.
- ``tenant_id`` — the submitting tenant (serve multi-tenancy).
- ``chunk_seq`` — the chunk index inside a supervised run; stamped by
                  the supervisor per chunk event, not at mint time.

The context is frozen: derive narrowed copies with ``child()``.  It is
pure host-side metadata — nothing here ever touches sim state, so the
telemetry-neutrality standard (bit-identical sim state with tracing
armed) holds by construction.
"""

from __future__ import annotations

import binascii
import dataclasses
import os
import time
from typing import Optional


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """Immutable bundle of correlation ids carried by every obs record."""

    run_id: str
    job_id: Optional[str] = None
    tenant_id: Optional[str] = None
    chunk_seq: Optional[int] = None

    def child(self, **overrides) -> "TraceContext":
        """A copy with some ids narrowed (e.g. ``ctx.child(chunk_seq=3)``)."""
        return dataclasses.replace(self, **overrides)

    def ids(self) -> dict:
        """The non-None ids as a flat dict — the join key set for any
        record (flight-recorder event, span args, run-record field)."""
        out = {"run_id": self.run_id}
        if self.job_id is not None:
            out["job_id"] = self.job_id
        if self.tenant_id is not None:
            out["tenant_id"] = self.tenant_id
        if self.chunk_seq is not None:
            out["chunk_seq"] = self.chunk_seq
        return out


def new_run_id(prefix: str = "run") -> str:
    """A fresh globally-unique-enough run id: ``prefix-SSSSSSSS-RRRRRRRR``
    (unix seconds + 4 random bytes).  Readable in a timeline, sortable
    by mint time, collision-safe across hosts without coordination."""
    stamp = format(int(time.time()) & 0xFFFFFFFF, "08x")
    rand = binascii.hexlify(os.urandom(4)).decode("ascii")
    return f"{prefix}-{stamp}-{rand}"


def mint_context(
    prefix: str = "run",
    job_id: Optional[str] = None,
    tenant_id: Optional[str] = None,
) -> TraceContext:
    """Mint a new root context.  Call this exactly once per unit of
    admitted work — serve admission or bench entry — and thread the
    result; never mint twice for the same run (resume paths must adopt
    the checkpointed id instead, see Supervisor._resume)."""
    return TraceContext(run_id=new_run_id(prefix), job_id=job_id, tenant_id=tenant_id)

"""Flight recorder: a bounded host-side ring of structured run events.

The recorder answers the question the r3-r5 tunnel postmortems had to
answer by hand: *what happened to this run, in order?*  Producers
(serve scheduler, supervisor, smokes, bench) record small dict events —
admission / 429s, batch packing decisions, chunk start/end with tick
high-water marks, retries with the classified error, watchdog fires,
degradations, checkpoint writes, kills, resumes — each stamped with a
wall-clock ``ts``, a monotone ``seq``, and the TraceContext ids.

Two persistence modes, both host-side only (sim state stays
bit-identical with the recorder armed — same neutrality standard as
telemetry):

- **ring only** (default): a ``deque(maxlen=capacity)`` holding the
  last N events; ``dump(path)`` writes them atomically (pid-tmp +
  ``os.replace``, same convention as engine/checkpoint.py).  The
  supervisor dumps the ring beside the checkpoints on any typed
  runtime/errors.py failure.
- **armed path**: when constructed with ``path=``, every event is ALSO
  appended + flushed to that JSONL file at record time, so the tail
  survives SIGKILL (same tail-safe convention as RunRecordWriter).
  durable_smoke relies on this to reconstruct the kill itself.

Event volume is one-per-chunk scale (not per-tick), so the append+flush
cost is noise next to the device sync that precedes every chunk event.

``scripts/obs_query.py`` replays dumps into a per-run timeline and a
merged Chrome trace.
"""

from __future__ import annotations

import collections
import itertools
import json
import os
import threading
import time
from typing import Iterable, List, Optional

from .context import TraceContext

# File names the CI forensics collector (scripts/obs_query.py collect)
# looks for: the armed live file and the atomic failure dump.
LIVE_BASENAME = "flight_recorder.jsonl"
DUMP_BASENAME = "flight_recorder_dump.jsonl"

# When set, the process-default recorder (get_recorder) persists there
# and supervisor failure dumps land there too; tier1.yml exports it so
# a failing test leaves forensics for the artifact step.
ENV_DIR = "WITT_OBS_DIR"

DEFAULT_CAPACITY = 4096

# The event vocabulary, for dashboards and assertions (record() does
# NOT enforce membership — producers may add kinds, this tuple is the
# documented catalog).  Grouped by producer:
#   admission/dispatch (serve.scheduler): admission, admission-rejected,
#     pack, batch-failed
#   durable execution (runtime.supervisor): chunk, retry, watchdog,
#     degrade, checkpoint, resume, kill, run-start, run-end
#   fleet resilience (serve.scheduler, this PR's additions):
#     lane-failed      a lane worker thread died (error_kind, streak)
#     lane-restart     its supervised replacement thread started
#     lane-abandoned   restart limit reached; lane left down
#     family-rebound   sticky family→lane binding moved off a dead lane
#     binding-expired  idle sticky binding reaped (binding_ttl_s)
#     salvage-start    a failed packed batch enters bisection
#     salvage-run      one bisection probe (rows, ok, error)
#     quarantine       a poison row gets its terminal disposition
#     salvage-done     bisection verdict (salvaged/quarantined/failed)
#     drain-start      graceful drain engaged (admission now refuses)
#     drain-end        undrain — admission + claiming resume
#   mission control (obs.slo / obs.monitor):
#     slo-alert            a burn-rate SLO started firing (slo, severity,
#                          burn_fast/burn_slow, measured, victim ids)
#     slo-resolved         that SLO returned to ok
#     invariant-violation  the runtime sentinel caught a broken
#                          invariant (slo names it; replica/mtype named)
KNOWN_KINDS = (
    "admission",
    "admission-rejected",
    "pack",
    "batch-failed",
    "chunk",
    "retry",
    "watchdog",
    "degrade",
    "checkpoint",
    "resume",
    "kill",
    "run-start",
    "run-end",
    "lane-failed",
    "lane-restart",
    "lane-abandoned",
    "family-rebound",
    "binding-expired",
    "salvage-start",
    "salvage-run",
    "quarantine",
    "salvage-done",
    "drain-start",
    "drain-end",
    "slo-alert",
    "slo-resolved",
    "invariant-violation",
    "lock-order-violation",
    # adversary search campaigns (search/driver.py)
    "search-generation",
    "search-resume",
    "search-complete",
    "search-pinned",
)


class FlightRecorder:
    """Thread-safe bounded event ring with optional tail-safe JSONL."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY, path: Optional[str] = None):
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self.capacity = int(capacity)
        self.path = path
        self._ring: collections.deque = collections.deque(maxlen=self.capacity)
        self._seq = itertools.count()
        # deferred import: runtime/__init__ -> supervisor -> obs ->
        # recorder would cycle if this sat at module level; recorders
        # are only ever constructed after imports settle
        from ..runtime.locks import make_lock

        self._lock = make_lock("obs.recorder")
        if path:
            parent = os.path.dirname(path)
            if parent:
                os.makedirs(parent, exist_ok=True)

    def record(self, kind: str, ctx: Optional[TraceContext] = None, **fields) -> dict:
        """Append one event.  ``ctx`` ids land as top-level fields so a
        grep for a run_id finds every event of the run.  Returns the
        event dict (callers may log or assert on it)."""
        ev = {"ts": round(time.time(), 6), "kind": str(kind)}
        if ctx is not None:
            ev.update(ctx.ids())
        for key, val in fields.items():
            # reserved envelope keys cannot be clobbered by payloads
            if val is not None and key not in ("ts", "kind", "seq"):
                ev[key] = val
        with self._lock:
            ev["seq"] = next(self._seq)
            self._ring.append(ev)
            if self.path:
                # append+flush per event: the tail survives SIGKILL.
                with open(self.path, "a", encoding="utf-8") as fh:
                    fh.write(json.dumps(ev, sort_keys=True) + "\n")
                    fh.flush()
                    os.fsync(fh.fileno())
        return ev

    def events(self, run_id: Optional[str] = None) -> List[dict]:
        """Snapshot of the ring (oldest first), optionally one run only."""
        with self._lock:
            evs = list(self._ring)
        if run_id is not None:
            evs = [e for e in evs if e.get("run_id") == run_id]
        return evs

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def dump(self, path: str) -> str:
        """Write the ring to ``path`` as JSONL, atomically (pid-tmp +
        os.replace) so a dump raced by a crash is intact-or-absent.
        Returns the path."""
        evs = self.events()
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as fh:
            for ev in evs:
                fh.write(json.dumps(ev, sort_keys=True) + "\n")
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        return path


def read_events(paths) -> List[dict]:
    """Load flight-recorder JSONL file(s), skipping torn tail lines
    (the armed file may end mid-write after SIGKILL — same tolerance as
    telemetry.read_run_records).  Events are merged and ordered by
    (ts, seq) so multi-process runs (victim + resume) interleave
    correctly."""
    if isinstance(paths, (str, os.PathLike)):
        paths = [paths]
    out: List[dict] = []
    for path in paths:
        try:
            with open(path, "r", encoding="utf-8") as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        ev = json.loads(line)
                    except json.JSONDecodeError:
                        continue  # torn tail line
                    if isinstance(ev, dict):
                        out.append(ev)
        except OSError:
            continue
    out.sort(key=lambda e: (e.get("ts", 0.0), e.get("seq", 0)))
    return out


# ---------------------------------------------------------------------------
# Process-default recorder.
#
# Components that are not handed an explicit recorder (Supervisor,
# BatchScheduler) fall back to one shared per-process ring so forensics
# exist even for callers that never opted in.  With WITT_OBS_DIR set
# the default recorder is armed (tail-safe JSONL under that dir) —
# tier1.yml uses this so any test failure leaves a dump to upload.
# ---------------------------------------------------------------------------

_default_recorder: Optional[FlightRecorder] = None
_default_lock = threading.Lock()


def get_recorder() -> FlightRecorder:
    """The lazily-created process-default recorder (see module note)."""
    global _default_recorder
    with _default_lock:
        if _default_recorder is None:
            obs_dir = os.environ.get(ENV_DIR)
            path = os.path.join(obs_dir, LIVE_BASENAME) if obs_dir else None
            _default_recorder = FlightRecorder(path=path)
        return _default_recorder


def reset_default_recorder() -> None:
    """Drop the process-default recorder (tests; env-var changes)."""
    global _default_recorder
    with _default_lock:
        _default_recorder = None


def failure_dump_paths(checkpoint_dir: Optional[str] = None) -> List[str]:
    """Where a failure dump should land: beside the checkpoints (the
    durable place a resume will look) and under WITT_OBS_DIR (the place
    CI collects from).  Either or both may be absent."""
    paths = []
    if checkpoint_dir:
        paths.append(os.path.join(checkpoint_dir, DUMP_BASENAME))
    obs_dir = os.environ.get(ENV_DIR)
    if obs_dir:
        paths.append(os.path.join(obs_dir, DUMP_BASENAME))
    return paths

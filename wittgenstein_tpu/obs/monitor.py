"""Runtime invariant sentinel: watch live what simlint proves static.

simlint's abstract-eval passes prove the telemetry contracts hold for
the registered small-scale factories at trace time; CAPACITY.json
promises the autotuned store sizings drop nothing at the probed scale.
Neither watches an actual production run.  The sentinel does: a
host-side hook the Supervisor calls at its per-chunk sync boundary
(the state is already synced and host-readable there — the same
proven-neutral window `_tick_hwms` uses), checking:

1. **store invariant** — ``sent == delivered + discarded + dropped +
   pending`` in aggregate, and per-mtype ``sent >= delivered +
   discarded + dropped`` (a per-mtype overshoot names the exact
   message type whose accounting broke);
2. **capacity promise** — if CAPACITY.json has an entry for this
   protocol@N with ``dropped: 0``, the live run must also drop zero;
   a violation names the protocol, the worst mtype, and the worst
   replica row (the autotuned sizing was wrong for THIS workload);
3. **HWM headroom** — the observed wheel/overflow high-water marks
   must stay below the capacity entry's sized limits (hwm == sized
   means the run is saturating exactly at the promise boundary);
4. **attribution reconciliation** — per-replica tick counts must sum
   exactly to the loop's total ticks (the invariant per-tenant
   attribution depends on).

Violations ALERT — a typed ``invariant-violation`` flight-recorder
event via SLOEngine.fire_violation (counted in
``witt_obs_alerts_total``) — and never raise: a monitoring bug or a
genuinely broken invariant must not kill the run it is watching.
Each invariant fires at most once per sentinel (latched), so a
persistent violation costs one event, not one per chunk.

Everything here is read-only numpy views of synced state: arming the
sentinel is bitwise-neutral, pinned by tests/test_mission_control.py.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, List, Optional

import numpy as np

from .attribution import batch_attribution, replica_rows

CAPACITY_FILE = "CAPACITY.json"


def load_capacity_table(root: Optional[str] = None) -> Dict[str, dict]:
    """CAPACITY.json's entries dict ({'protocol@N': {...}}), or {}."""
    if root is None:  # the repo root, wherever the process started
        root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
    path = os.path.join(root, CAPACITY_FILE)
    try:
        with open(path) as f:
            rec = json.load(f)
    except (OSError, ValueError):
        return {}
    return rec.get("entries", {}) if isinstance(rec, dict) else {}


class InvariantSentinel:
    """Per-run invariant watcher; see module docstring.

    ``net`` is the (Batched)Network whose protocol names the mtypes;
    without it (or with telemetry unarmed) the telemetry-tier checks
    degrade to the always-available ``state.dropped`` capacity check.
    ``engine`` is an obs.slo.SLOEngine used to count + type the
    alerts; ``recorder`` alone also works (events only, no counter).
    """

    def __init__(self, net: Any = None, protocol: Optional[str] = None,
                 capacity_table: Optional[Dict[str, dict]] = None,
                 engine=None, recorder=None):
        self.net = net
        proto = protocol
        if proto is None and net is not None:
            proto = type(getattr(net, "protocol", net)).__name__
            # kernel classes are named BatchedPingPong etc.; CAPACITY.json
            # keys on the plain protocol name (pingpong@N)
            if proto.startswith("Batched"):
                proto = proto[len("Batched"):]
        self.protocol = proto
        self.capacity_table = (
            capacity_table if capacity_table is not None
            else load_capacity_table()
        )
        self.engine = engine
        self.recorder = recorder
        self._lock = threading.Lock()
        self._fired: set = set()  # invariant names already alerted
        self.violations: List[dict] = []

    # -- reporting -----------------------------------------------------

    def _alert(self, invariant: str, ctx=None, **fields) -> None:
        with self._lock:
            if invariant in self._fired:
                return
            self._fired.add(invariant)
            self.violations.append({"slo": invariant, **fields})
        if self.engine is not None:
            self.engine.fire_violation(
                invariant, severity="page", ctx=ctx,
                protocol=self.protocol, **fields,
            )
        elif self.recorder is not None:
            ids = ctx.ids() if hasattr(ctx, "ids") else {}
            self.recorder.record(
                "invariant-violation", slo=invariant, severity="page",
                protocol=self.protocol, **ids, **fields,
            )

    # -- capacity-table lookup ----------------------------------------

    def _entry(self, n_nodes: int) -> Optional[dict]:
        if not self.protocol:
            return None
        return self.capacity_table.get(
            f"{self.protocol.lower()}@{int(n_nodes)}"
        )

    # -- the per-chunk hook --------------------------------------------

    def check(self, state: Any, ctx=None, chunk: Optional[int] = None,
              members: Optional[List[dict]] = None,
              capacity: Optional[int] = None) -> List[dict]:
        """Run every invariant against a synced state.  ``members`` /
        ``capacity`` (the scheduler's batch packing) arm the per-tenant
        attribution reconciliation.  Returns the violations found THIS
        call (already alerted).  Never raises — the sentinel must not
        kill the run it watches."""
        try:
            return self._check(state, ctx, chunk, members, capacity)
        except Exception as e:  # noqa: BLE001 — monitoring must not kill
            self._alert(
                "store-invariant", ctx, chunk=chunk,
                detail=f"sentinel error: {type(e).__name__}: {e}"[:300],
            )
            return []

    def _check(self, state: Any, ctx, chunk, members, capacity
               ) -> List[dict]:
        found: List[dict] = []

        def alert(invariant: str, **fields) -> None:
            found.append({"slo": invariant, **fields})
            self._alert(invariant, ctx, chunk=chunk, **fields)

        done_at = np.asarray(state.done_at)
        n_nodes = int(done_at.shape[-1])
        entry = self._entry(n_nodes)
        mtypes = self._mtype_names()

        # always-available tier: store-overflow drop counter
        dropped_rows = np.asarray(state.dropped).reshape(-1)
        dropped_total = int(dropped_rows.sum())

        tele = getattr(state, "tele", None)
        armed = tele is not None and hasattr(tele, "sent")

        # 1. store invariant (telemetry armed only: sent/delivered/
        #    discarded/dropped are side-car counters)
        if armed:
            sent = self._per_mtype(tele.sent)
            delivered = self._per_mtype(tele.delivered)
            discarded = self._per_mtype(tele.discarded)
            t_dropped = self._per_mtype(tele.dropped)
            pending = int(
                np.asarray(state.msg_valid).sum()
                + np.asarray(state.ovf_valid).sum()
            )
            accounted = delivered + discarded + t_dropped
            if int(sent.sum()) != int(accounted.sum()) + pending:
                alert(
                    "store-invariant",
                    sent=int(sent.sum()), delivered=int(delivered.sum()),
                    discarded=int(discarded.sum()),
                    dropped=int(t_dropped.sum()), pending=pending,
                    detail="sent != delivered + discarded + dropped "
                           "+ pending",
                )
            over = np.nonzero(accounted > sent)[0]
            if over.size:
                m = int(over[0])
                alert(
                    "store-invariant", mtype=self._mtype(mtypes, m),
                    sent=int(sent[m]), accounted=int(accounted[m]),
                    detail="per-mtype delivered+discarded+dropped "
                           "exceeds sent",
                )

        # 2. the CAPACITY.json dropped == 0 promise
        if entry is not None and entry.get("dropped") == 0 and dropped_total:
            replica = int(dropped_rows.argmax())
            fields = {
                "dropped": dropped_total, "replica": replica,
                "n_nodes": n_nodes,
                "detail": "store dropped messages under a CAPACITY.json "
                          "sizing that promises dropped == 0",
            }
            if armed:
                per_m = self._per_mtype(tele.dropped)
                fields["mtype"] = self._mtype(mtypes, int(per_m.argmax()))
            alert("capacity-dropped", **fields)

        # 3. HWM headroom vs the sized capacities
        if entry is not None and armed:
            sized = entry.get("sized", {})
            for hwm_key, cap_key, leaf in (
                ("wheel_fill_hwm", "wheel_slots", "wheel_fill_hwm"),
                ("overflow_hwm", "overflow_capacity", "ovf_hwm"),
            ):
                cap = sized.get(cap_key)
                arr = getattr(tele, leaf, None)
                if cap is None or arr is None:
                    continue
                hwm = int(np.asarray(arr).max())
                if hwm >= int(cap):
                    alert(
                        "hwm-headroom", hwm=hwm, sized=int(cap),
                        which=hwm_key, n_nodes=n_nodes,
                        detail=f"{hwm_key} reached the sized "
                               f"{cap_key} — zero headroom left",
                    )

        # 4. attribution reconciliation.  With the scheduler's packing
        #    known: per-tenant ticks must sum EXACTLY to ticks_live
        #    (the invariant every device-time share rests on).
        #    Without members: the per-replica rows must still sum to
        #    the loop total the shares would be derived from.
        if armed and members:
            att = batch_attribution(
                self.net, state, members, capacity or len(members)
            )
            ticks_live = att["batch"]["ticks_live"]
            tenant_sum = sum(
                t["ticks"] or 0 for t in att["tenants"].values()
            )
            if ticks_live is not None and tenant_sum != ticks_live:
                alert(
                    "attribution-reconcile",
                    tenant_ticks=tenant_sum, ticks_live=ticks_live,
                    tenants=sorted(att["tenants"]),
                    detail="per-tenant ticks do not sum to ticks_live",
                )
        elif armed and hasattr(tele, "ticks"):
            rows = replica_rows(self.net, state)
            per_replica = rows["ticks"]
            total = int(np.asarray(tele.ticks).sum())
            if per_replica is not None and int(per_replica.sum()) != total:
                alert(
                    "attribution-reconcile",
                    per_replica_sum=int(per_replica.sum()), total=total,
                    detail="per-replica tick rows do not sum to the "
                           "loop total",
                )

        return found

    # -- helpers -------------------------------------------------------

    @staticmethod
    def _per_mtype(a) -> np.ndarray:
        """Sum a per-mtype telemetry leaf over every replica axis,
        keeping the trailing [T] mtype axis."""
        a = np.asarray(a)
        if a.ndim == 0:
            return a.reshape(1)
        return a.reshape(-1, a.shape[-1]).sum(axis=0)

    def _mtype_names(self) -> Optional[List[str]]:
        proto = getattr(self.net, "protocol", None)
        names = getattr(proto, "MSG_TYPES", None)
        return list(names) if names else None

    @staticmethod
    def _mtype(names: Optional[List[str]], idx: int) -> str:
        if names and 0 <= idx < len(names):
            return names[idx]
        return f"mtype{idx}"

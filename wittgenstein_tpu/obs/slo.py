"""Declarative SLOs evaluated as multi-window burn rates, in-process.

An SLOSpec names a metric in a TimeSeriesStore, how to reduce it over
a window (quantile / rate / mean / last), and the objective it must
meet.  The SLOEngine evaluates every spec over TWO windows — the fast
window (default 5 min) and the slow window (default 1 h), the Google
SRE multi-window pattern — and computes a *burn rate* per window:

    direction "le"  (latency, error rate):  burn = measured / objective
    direction "ge"  (throughput floors):    burn = objective / measured

burn >= 1.0 means the objective is being violated at that window's
timescale.  Both windows over threshold -> **page** (it is bad AND
still happening); only the slow window over -> **warn** (a past burst
still inside the 1-h memory); fast-only never fires on its own (a
blip that the slow window hasn't confirmed is noise).  A spec whose
metric has no samples in the slow window reports ``no_data`` and never
fires — the sims/s floor SLO stays silent in a serve fleet that never
feeds a sims/s series.

Firing is edge-triggered: an alert is emitted once per
inactive->active transition (typed ``slo-alert`` flight-recorder event
+ ``witt_obs_alerts_total{slo,severity}`` tick), then latched until
the engine observes it clear, which emits ``slo-resolved``.  The alert
event carries the trace ids of the newest contributing sample, so a
quarantine alert names the poison job's run.

Zero objectives are the degenerate-but-useful case: "error rate <= 0"
fires on ANY error in the window (burn is reported as BURN_CAP).  The
fault-free loadgen benchmark and chaos_smoke both key off this.

``REGISTERED_SLOS`` is the catalog the SL1101 simlint pass audits
against: every alert-capable call site (SLOSpec construction,
``fire_violation``) must name an entry here, so a dashboard keyed on
slo names can never silently miss an alert source.
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional

from .timeseries import TimeSeriesStore

# Burn rates are capped here for JSON-safety (a zero objective makes
# the true burn infinite).
BURN_CAP = 1e9

FAST_WINDOW_S = 300.0  # 5 min: "is it still happening?"
SLOW_WINDOW_S = 3600.0  # 1 h:   "is it significant?"

#: The registered SLO catalog — the only names an alert may carry.
#: Window-evaluated serve/campaign SLOs first, then the runtime
#: invariants the sentinel (obs/monitor.py) fires directly.  The
#: SL1101 simlint pass fails any emission site naming anything else.
REGISTERED_SLOS = (
    "queue-wait-p95",
    "ttfr-p95",
    "sims-per-sec-floor",
    "error-kind-rate",
    "lane-restart-rate",
    "store-invariant",
    "capacity-dropped",
    "hwm-headroom",
    "attribution-reconcile",
)


@dataclass(frozen=True)
class SLOSpec:
    """One declarative objective over one metric series."""

    name: str  # must be in REGISTERED_SLOS (SL1101)
    metric: str  # series name in the TimeSeriesStore
    objective: float  # the threshold
    #: how to reduce the window's samples to one measured value
    reduce: str = "quantile"  # quantile | rate | mean | last
    q: float = 0.95  # for reduce="quantile"
    #: "le": measured must stay <= objective; "ge": >= objective
    direction: str = "le"
    fast_window_s: float = FAST_WINDOW_S
    slow_window_s: float = SLOW_WINDOW_S
    #: burn >= this fires (1.0 = objective exactly met is the edge)
    burn_threshold: float = 1.0
    description: str = ""

    def __post_init__(self):
        if self.name not in REGISTERED_SLOS:
            raise ValueError(
                f"SLO {self.name!r} is not in REGISTERED_SLOS — register "
                "it in obs/slo.py (the SL1101 catalog) first"
            )
        if self.reduce not in ("quantile", "rate", "mean", "last"):
            raise ValueError(f"unknown reduce {self.reduce!r}")
        if self.direction not in ("le", "ge"):
            raise ValueError(f"unknown direction {self.direction!r}")
        if self.fast_window_s <= 0 or self.slow_window_s < self.fast_window_s:
            raise ValueError(
                "need 0 < fast_window_s <= slow_window_s, got "
                f"{self.fast_window_s}/{self.slow_window_s}"
            )


def _burn(measured: Optional[float], objective: float,
          direction: str) -> Optional[float]:
    """Burn rate (>= 1.0 means violating), capped for JSON-safety."""
    if measured is None:
        return None
    if direction == "le":
        if objective <= 0:
            return BURN_CAP if measured > 0 else 0.0
        return min(BURN_CAP, measured / objective)
    # "ge": a floor — burning when measured falls below it
    if measured <= 0:
        return BURN_CAP if objective > 0 else 0.0
    return min(BURN_CAP, objective / measured)


class SLOEngine:
    """Evaluate specs against a TimeSeriesStore; latch + count alerts.

    Thread-safe: evaluate() may be called from lane workers, the HTTP
    handler, and tests concurrently.  Cheap enough to run on every
    error observation (a handful of window scans over bounded rings).
    """

    def __init__(self, store: TimeSeriesStore,
                 specs: Optional[List[SLOSpec]] = None,
                 recorder=None, clock=None):
        self.store = store
        self.specs = list(specs or [])
        self.recorder = recorder
        self._clock = clock or store._clock
        self._lock = threading.Lock()
        self._active: Dict[str, dict] = {}  # slo name -> firing alert
        self._alerts_total: Dict[tuple, int] = {}  # (slo, severity) -> n
        self._last_eval: List[dict] = []

    # -- evaluation ----------------------------------------------------

    def _measure(self, spec: SLOSpec, window_s: float,
                 now: float) -> Optional[float]:
        if spec.reduce == "quantile":
            vals = self.store.values(spec.metric, window_s, now)
            if not vals:
                return None
            return self.store.quantile(spec.metric, spec.q, window_s, now)
        if spec.reduce == "rate":
            if self.store.count(spec.metric, window_s, now) == 0 and \
                    self.store.last(spec.metric) is None:
                return None
            return self.store.rate(spec.metric, window_s, now)
        if spec.reduce == "mean":
            return self.store.mean(spec.metric, window_s, now)
        return self.store.last(spec.metric)  # "last"

    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        """Evaluate every spec; emit edge-triggered alerts; return the
        per-spec status rows (/w/slo's payload)."""
        t = self._clock() if now is None else now
        rows = []
        fired, resolved = [], []
        with self._lock:
            for spec in self.specs:
                fast = self._measure(spec, spec.fast_window_s, t)
                slow = self._measure(spec, spec.slow_window_s, t)
                burn_fast = _burn(fast, spec.objective, spec.direction)
                burn_slow = _burn(slow, spec.objective, spec.direction)
                if burn_slow is None:
                    state, severity = "no_data", None
                elif burn_slow >= spec.burn_threshold and (
                    burn_fast is not None
                    and burn_fast >= spec.burn_threshold
                ):
                    state, severity = "firing", "page"
                elif burn_slow >= spec.burn_threshold:
                    state, severity = "firing", "warn"
                else:
                    state, severity = "ok", None
                row = {
                    "slo": spec.name,
                    "metric": spec.metric,
                    "objective": spec.objective,
                    "direction": spec.direction,
                    "reduce": spec.reduce,
                    "state": state,
                    "severity": severity,
                    "measured_fast": fast,
                    "measured_slow": slow,
                    "burn_fast": burn_fast,
                    "burn_slow": burn_slow,
                    "fast_window_s": spec.fast_window_s,
                    "slow_window_s": spec.slow_window_s,
                }
                rows.append(row)
                was = self._active.get(spec.name)
                if state == "firing":
                    if was is None or was.get("severity") != severity:
                        ids = self.store.latest_ctx(
                            spec.metric, spec.slow_window_s, t
                        )
                        alert = {**row, "ts": t, "ctx": ids}
                        self._active[spec.name] = alert
                        key = (spec.name, severity)
                        self._alerts_total[key] = (
                            self._alerts_total.get(key, 0) + 1
                        )
                        fired.append(alert)
                elif was is not None and state == "ok":
                    self._active.pop(spec.name, None)
                    resolved.append({**row, "ts": t})
            self._last_eval = rows
        # recorder I/O outside the lock (armed recorders fsync)
        if self.recorder is not None:
            for alert in fired:
                self.recorder.record(
                    "slo-alert",
                    slo=alert["slo"], severity=alert["severity"],
                    metric=alert["metric"], objective=alert["objective"],
                    burn_fast=alert["burn_fast"],
                    burn_slow=alert["burn_slow"],
                    measured=alert["measured_fast"],
                    **(alert.get("ctx") or {}),
                )
            for row in resolved:
                self.recorder.record(
                    "slo-resolved", slo=row["slo"], metric=row["metric"],
                )
        return rows

    # -- direct violations (the invariant sentinel's path) -------------

    def fire_violation(self, slo: str, severity: str = "page",
                       ctx=None, **fields) -> dict:
        """Fire one alert directly, bypassing window evaluation — the
        runtime invariant sentinel's path (an invariant is boolean, not
        a rate).  Still registered, still counted, still typed."""
        if slo not in REGISTERED_SLOS:
            raise ValueError(
                f"SLO {slo!r} is not in REGISTERED_SLOS (SL1101)"
            )
        alert = {
            "slo": slo, "severity": severity, "state": "firing",
            "ts": self._clock(), **fields,
        }
        with self._lock:
            key = (slo, severity)
            self._alerts_total[key] = self._alerts_total.get(key, 0) + 1
            self._active[slo] = alert
        if self.recorder is not None:
            ids = ctx.ids() if hasattr(ctx, "ids") else (ctx or {})
            self.recorder.record(
                "invariant-violation", slo=slo, severity=severity,
                **ids, **fields,
            )
        return alert

    # -- exposition ----------------------------------------------------

    def alert_counts(self) -> dict:
        """{"total": n, "by_slo": {name: n}, "by_severity": {sev: n}}."""
        with self._lock:
            items = list(self._alerts_total.items())
        by_slo: Dict[str, int] = {}
        by_sev: Dict[str, int] = {}
        for (slo, sev), n in items:
            by_slo[slo] = by_slo.get(slo, 0) + n
            by_sev[sev] = by_sev.get(sev, 0) + n
        return {
            "total": sum(n for _, n in items),
            "by_slo": dict(sorted(by_slo.items())),
            "by_severity": dict(sorted(by_sev.items())),
        }

    def status(self, evaluate: bool = True) -> dict:
        """The /w/slo payload: spec rows, active alerts, counters."""
        rows = self.evaluate() if evaluate else list(self._last_eval)
        with self._lock:
            active = [dict(a) for a in self._active.values()]
        return {
            "slos": rows,
            "activeAlerts": active,
            "alerts": self.alert_counts(),
            "series": self.store.summary(),
        }

    def add_prometheus(self, p) -> None:
        """witt_obs_alerts_total{slo,severity} + firing gauge."""
        with self._lock:
            totals = dict(self._alerts_total)
            active = {a["slo"]: a for a in self._active.values()}
        for (slo, sev), n in sorted(totals.items()):
            p.add("obs_alerts_total", n,
                  "SLO burn-rate + invariant alerts fired (edge-"
                  "triggered transitions)", "counter",
                  {"slo": slo, "severity": sev})
        for spec in self.specs:
            p.add("obs_slo_firing",
                  1 if spec.name in active else 0,
                  "1 while the named SLO is latched firing", "gauge",
                  {"slo": spec.name})


# -- the default serve-fleet spec set ---------------------------------------


def _bench_floor(root: Optional[str] = None) -> Optional[dict]:
    if root is None:  # the repo root, wherever the process started
        root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
    path = os.path.join(root, "BENCH_FLOOR.json")
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def default_serve_specs(
    floor: Optional[float] = None,
    fast_window_s: float = FAST_WINDOW_S,
    slow_window_s: float = SLOW_WINDOW_S,
) -> List[SLOSpec]:
    """The serve fleet's standing objectives.  Queue-wait and TTFR
    bounds are deliberately generous (CI hosts are slow and shared);
    the zero-objective error/restart SLOs are the sharp ones — any
    error kind or lane restart inside the window fires.  The sims/s
    floor arms only where a sims_per_sec series is actually fed
    (tpu_campaign rungs; the serve path never feeds it)."""
    specs = [
        SLOSpec(
            name="queue-wait-p95", metric="serve.queue_wait_s",
            objective=30.0, reduce="quantile", q=0.95,
            fast_window_s=fast_window_s, slow_window_s=slow_window_s,
            description="p95 admission->dispatch wait stays under 30 s",
        ),
        SLOSpec(
            name="ttfr-p95", metric="serve.ttfr_s",
            objective=60.0, reduce="quantile", q=0.95,
            fast_window_s=fast_window_s, slow_window_s=slow_window_s,
            description="p95 submit->first-result stays under 60 s",
        ),
        SLOSpec(
            name="error-kind-rate", metric="serve.errors_total",
            objective=0.0, reduce="rate",
            fast_window_s=fast_window_s, slow_window_s=slow_window_s,
            description="zero failed/quarantined jobs (any error fires)",
        ),
        SLOSpec(
            name="lane-restart-rate", metric="serve.lane_restarts_total",
            objective=0.0, reduce="rate",
            fast_window_s=fast_window_s, slow_window_s=slow_window_s,
            description="zero lane deaths (any supervised restart fires)",
        ),
    ]
    if floor is None:
        rec = _bench_floor()
        floor = rec.get("floor") if rec else None
    if floor:
        specs.append(
            SLOSpec(
                name="sims-per-sec-floor", metric="campaign.sims_per_sec",
                objective=float(floor), reduce="mean", direction="ge",
                fast_window_s=fast_window_s, slow_window_s=slow_window_s,
                description="measured sims/s stays above the committed "
                            "BENCH_FLOOR.json floor",
            )
        )
    return specs

"""Bounded in-process metric history: the scraper we don't have.

Prometheus exposition (/metrics) is instantaneous — a counter value
with no past.  Production stacks get history from an external scraper;
this repo's CI smokes, campaign rungs, and single-process fleets have
nowhere to scrape FROM, so the history has to live in-process.  A
``TimeSeriesStore`` is that history: one bounded ring of (ts, value)
samples per metric family, fed by ServeMetrics observations, the
Supervisor's chunk-end sync point, and tpu_campaign rungs, and queried
by the SLO burn-rate engine (obs/slo.py) with rate / delta / quantile
over sliding windows.

Design constraints, in order:

- **host-side and bitwise-neutral** — the store only ever receives
  Python floats read from already-synced states (the same standard as
  the flight recorder: arming it changes zero sim bytes);
- **bounded** — ``capacity`` samples per series (default 512), so a
  week-long fleet cannot grow the ring.  Burn-rate windows only need
  the recent past;
- **monotonic timestamps** — wall-clock can step backwards (NTP); a
  sample's ts is clamped to its series' last ts so window queries never
  see time run in reverse;
- **checkpoint-portable** — ``snapshot()``/``restore()`` round-trip
  through JSON, and the Supervisor threads them through the checkpoint
  manifest meta: a killed-and-resumed run keeps its history the same
  way it keeps its run_id.

Two sample flavors share the ring: ``observe()`` records a gauge
sample (a measured value: seconds, sims/s, an HWM), ``inc()`` records
a cumulative counter (errors, restarts) whose windowed ``delta``/
``rate`` are the interesting queries.  Samples optionally carry the
TraceContext ids of the event that produced them, so an alert fired
off a window can name the victim run.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

from .context import TraceContext

DEFAULT_CAPACITY = 512

#: snapshot() trims each series to this many newest samples so the
#: checkpoint manifest meta stays small (manifests are JSON files read
#: on every resume)
SNAPSHOT_SAMPLES = 64

SNAPSHOT_SCHEMA = "witt-timeseries/v1"


def _quantile(values: List[float], q: float) -> float:
    """Nearest-rank quantile of a non-empty list (0 for empty) — same
    estimator as serve.metrics.quantile so /w/slo and /metrics agree."""
    if not values:
        return 0.0
    xs = sorted(values)
    idx = min(len(xs) - 1, max(0, int(round(q * (len(xs) - 1)))))
    return xs[idx]


class _Series:
    """One metric family's ring: (ts, value, ctx_ids|None) triples,
    ts non-decreasing.  ``kind`` is 'gauge' or 'counter'; a counter
    series stores the CUMULATIVE value at each sample."""

    __slots__ = ("kind", "samples", "cum")

    def __init__(self, kind: str, capacity: int):
        self.kind = kind
        self.samples: deque = deque(maxlen=capacity)
        self.cum = 0.0


class TimeSeriesStore:
    """Thread-safe bounded multi-series ring.  See module docstring."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 clock=time.time):
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self.capacity = int(capacity)
        self._clock = clock
        self._series: Dict[str, _Series] = {}
        self._lock = threading.Lock()

    # -- feeding -------------------------------------------------------

    def _series_for(self, name: str, kind: str) -> _Series:
        """Caller holds the lock."""
        s = self._series.get(name)
        if s is None:
            s = self._series[name] = _Series(kind, self.capacity)
        elif s.kind != kind:
            raise ValueError(f"series {name!r} is a {s.kind}, not a {kind}")
        return s

    def _stamp(self, s: _Series, ts: Optional[float]) -> float:
        t = float(self._clock() if ts is None else ts)
        if s.samples and t < s.samples[-1][0]:
            t = s.samples[-1][0]  # monotonic within the series
        return t

    def observe(self, name: str, value: float, ts: Optional[float] = None,
                ctx=None) -> None:
        """Record one gauge sample (a measured value at a moment)."""
        ids = ctx.ids() if isinstance(ctx, TraceContext) else ctx
        with self._lock:
            s = self._series_for(name, "gauge")
            s.samples.append((self._stamp(s, ts), float(value), ids or None))

    def inc(self, name: str, amount: float = 1.0,
            ts: Optional[float] = None, ctx=None) -> None:
        """Advance a cumulative counter and record the new total."""
        ids = ctx.ids() if isinstance(ctx, TraceContext) else ctx
        with self._lock:
            s = self._series_for(name, "counter")
            s.cum += float(amount)
            s.samples.append((self._stamp(s, ts), s.cum, ids or None))

    # -- queries -------------------------------------------------------

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def _window(self, name: str, window_s: Optional[float],
                now: Optional[float]):
        """(in-window samples, baseline sample or None).  The baseline
        is the newest sample OLDER than the window — the counter value
        the window's delta is measured against."""
        with self._lock:
            s = self._series.get(name)
            samples = list(s.samples) if s is not None else []
        if not samples:
            return [], None
        if window_s is None:
            return samples, None
        t = self._clock() if now is None else now
        cut = t - window_s
        inside = [x for x in samples if x[0] >= cut]
        before = [x for x in samples if x[0] < cut]
        return inside, (before[-1] if before else None)

    def last(self, name: str) -> Optional[float]:
        with self._lock:
            s = self._series.get(name)
            return s.samples[-1][1] if s is not None and s.samples else None

    def count(self, name: str, window_s: Optional[float] = None,
              now: Optional[float] = None) -> int:
        inside, _ = self._window(name, window_s, now)
        return len(inside)

    def values(self, name: str, window_s: Optional[float] = None,
               now: Optional[float] = None) -> List[float]:
        inside, _ = self._window(name, window_s, now)
        return [v for _, v, _ in inside]

    def delta(self, name: str, window_s: float,
              now: Optional[float] = None) -> float:
        """Counter growth inside the window: newest value minus the
        pre-window baseline (0 when the series began inside the
        window — in-process stores start from zero)."""
        inside, baseline = self._window(name, window_s, now)
        if not inside:
            return 0.0
        base = baseline[1] if baseline is not None else 0.0
        return inside[-1][1] - base

    def rate(self, name: str, window_s: float,
             now: Optional[float] = None) -> float:
        """Counter delta per second over the window."""
        if window_s <= 0:
            raise ValueError(f"window_s must be positive, got {window_s}")
        return self.delta(name, window_s, now) / window_s

    def quantile(self, name: str, q: float,
                 window_s: Optional[float] = None,
                 now: Optional[float] = None) -> float:
        return _quantile(self.values(name, window_s, now), q)

    def mean(self, name: str, window_s: Optional[float] = None,
             now: Optional[float] = None) -> Optional[float]:
        vals = self.values(name, window_s, now)
        return sum(vals) / len(vals) if vals else None

    def latest_ctx(self, name: str, window_s: Optional[float] = None,
                   now: Optional[float] = None) -> Optional[dict]:
        """Trace ids of the newest in-window sample that carried any —
        how a burn-rate alert names the victim run."""
        inside, _ = self._window(name, window_s, now)
        for _, _, ids in reversed(inside):
            if ids:
                return dict(ids)
        return None

    # -- checkpoint round-trip -----------------------------------------

    def snapshot(self, max_samples: int = SNAPSHOT_SAMPLES) -> dict:
        """JSON-serializable state: per-series kind + cumulative total +
        the newest ``max_samples`` samples (ctx ids included)."""
        with self._lock:
            series = {
                name: {
                    "kind": s.kind,
                    "cum": s.cum,
                    "samples": [
                        [t, v, ids] for t, v, ids in
                        list(s.samples)[-max_samples:]
                    ],
                }
                for name, s in self._series.items()
            }
        return {"schema": SNAPSHOT_SCHEMA, "series": series}

    def restore(self, snap: dict) -> None:
        """Adopt a snapshot's series (resume path).  A snapshot series
        replaces the live one ONLY when the live one isn't strictly
        newer: a fresh process resuming a killed run adopts the
        checkpointed past wholesale, but a same-process resume (a serve
        scheduler continuing a parked batch against its shared store)
        keeps its own, more current, history."""
        if not snap or snap.get("schema") != SNAPSHOT_SCHEMA:
            return
        with self._lock:
            for name, rec in (snap.get("series") or {}).items():
                rows = rec.get("samples", [])
                live = self._series.get(name)
                if live is not None and live.samples and (
                    not rows
                    or live.samples[-1][0] >= float(rows[-1][0])
                ):
                    continue
                s = _Series(rec.get("kind", "gauge"), self.capacity)
                s.cum = float(rec.get("cum", 0.0))
                for row in rec.get("samples", []):
                    t, v = float(row[0]), float(row[1])
                    ids = row[2] if len(row) > 2 else None
                    if s.samples and t < s.samples[-1][0]:
                        t = s.samples[-1][0]
                    s.samples.append((t, v, ids or None))
                self._series[name] = s

    def summary(self) -> dict:
        """Small per-series digest for /w/slo and the watch."""
        with self._lock:
            return {
                name: {
                    "kind": s.kind,
                    "samples": len(s.samples),
                    "last": s.samples[-1][1] if s.samples else None,
                }
                for name, s in sorted(self._series.items())
            }

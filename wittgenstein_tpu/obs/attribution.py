"""Per-tenant attribution over the packed replica axis.

The serve scheduler packs one job per replica row (padding rows fill
the family's fixed capacity), so every per-replica telemetry / fault
counter is per-JOB attribution for free — this module just slices the
final batched state along axis 0 and re-groups rows by tenant.

Device-time share semantics: the batched engine runs replicas in
LOCKSTEP — one device tick executes every row — so a tenant's share of
device time is its share of executed row-ticks (rows x ticks of those
rows over the live total).  That is exact for today's engine (all rows
tick together) and remains the honest first-order attribution if rows
ever ticked unevenly.  Padding rows tick too; their cost is reported
separately (``batch.ticks_padding``) rather than silently smeared over
tenants, so per-tenant ticks always sum to ``batch.ticks_live`` and
live shares sum to 1.

Everything here is a read-only numpy view of a final state — nothing
feeds back into the sim, preserving bit-identity with attribution on.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np


def _per_replica(leaf) -> Optional[np.ndarray]:
    """Sum a batched leaf over everything but the leading replica axis.
    Returns None for absent side-cars (telemetry/faults disabled)."""
    if leaf is None:
        return None
    a = np.asarray(leaf)
    if a.ndim == 0:  # unbatched scalar — caller is on a single replica
        return a.reshape(1)
    return a.reshape(a.shape[0], -1).sum(axis=1)


def replica_rows(net, state) -> dict:
    """Per-replica counter rows from a (possibly batched) final state.

    Returns arrays of length R (the replica axis):
      ticks / jumps        — engine loop counters (telemetry armed only)
      sent / delivered     — store counters (telemetry armed only)
      dropped              — store-overflow drops (always available)
      fault_dropped/_delayed — fault-lane counters (fault plan armed only)
      done_nodes           — nodes finished per row (always available)
    """
    tele = getattr(state, "tele", None)
    armed = tele is not None and hasattr(tele, "ticks")
    done_at = np.asarray(state.done_at)
    if done_at.ndim == 1:
        done_at = done_at[None, :]
    faults = getattr(state, "faults", None)
    have_faults = faults is not None and hasattr(faults, "dropped_by_fault")
    return {
        "replicas": int(done_at.shape[0]),
        "ticks": _per_replica(tele.ticks) if armed else None,
        "jumps": _per_replica(tele.jumps) if armed else None,
        "sent": _per_replica(tele.sent) if armed else None,
        "delivered": _per_replica(tele.delivered) if armed else None,
        "dropped": _per_replica(state.dropped),
        "fault_dropped": (
            _per_replica(faults.dropped_by_fault) if have_faults else None
        ),
        "fault_delayed": (
            _per_replica(faults.delayed_by_fault) if have_faults else None
        ),
        "done_nodes": (done_at > 0).sum(axis=1),
    }


def _row_val(arr, i) -> Optional[int]:
    return int(arr[i]) if arr is not None else None


def batch_attribution(net, state, members: List[dict], capacity: int) -> dict:
    """Attribute a packed batch's counters to its member jobs/tenants.

    ``members`` — one dict per live row, in replica-row order (the
    scheduler's packing order): ``{"job_id", "run_id", "tenant"}``.
    Rows ``len(members)..capacity`` are padding.

    Returns::

        {"batch":   {replicas, live_rows, padding_rows,
                     ticks_live, ticks_padding, ticks_total, dropped, ...},
         "jobs":    {job_id: {run_id, tenant, replica, ticks,
                              device_time_share, dropped, fault_dropped,
                              fault_delayed, done_nodes, ...}},
         "tenants": {tenant: {jobs, replicas:[...], ticks,
                              device_time_share, dropped, ...}}}

    Per-tenant ``ticks`` sum to ``batch.ticks_live`` exactly (ints);
    ``device_time_share`` is ticks / ticks_live (floats summing to 1.0
    when telemetry is armed, None otherwise).
    """
    rows = replica_rows(net, state)
    n_live = len(members)
    n_rows = rows["replicas"]
    ticks = rows["ticks"]

    def live_sum(arr):
        return int(arr[:n_live].sum()) if arr is not None else None

    ticks_live = live_sum(ticks)
    ticks_total = int(ticks.sum()) if ticks is not None else None

    batch = {
        "replicas": n_rows,
        "capacity": int(capacity),
        "live_rows": n_live,
        "padding_rows": n_rows - n_live,
        "ticks_live": ticks_live,
        "ticks_padding": (
            ticks_total - ticks_live if ticks_total is not None else None
        ),
        "ticks_total": ticks_total,
        "dropped": live_sum(rows["dropped"]),
        "fault_dropped": live_sum(rows["fault_dropped"]),
        "fault_delayed": live_sum(rows["fault_delayed"]),
        "done_nodes": live_sum(rows["done_nodes"]),
    }

    def share(i) -> Optional[float]:
        if ticks is None or not ticks_live:
            return None
        return float(ticks[i]) / float(ticks_live)

    jobs = {}
    tenants: dict = {}
    for i, m in enumerate(members):
        tenant = m.get("tenant") or "default"
        job = {
            "run_id": m.get("run_id"),
            "tenant": tenant,
            "replica": i,
            "ticks": _row_val(ticks, i),
            "device_time_share": share(i),
            "dropped": _row_val(rows["dropped"], i),
            "fault_dropped": _row_val(rows["fault_dropped"], i),
            "fault_delayed": _row_val(rows["fault_delayed"], i),
            "done_nodes": _row_val(rows["done_nodes"], i),
        }
        jobs[m["job_id"]] = job
        t = tenants.setdefault(
            tenant,
            {
                "jobs": 0,
                "replicas": [],
                "ticks": 0 if ticks is not None else None,
                "device_time_share": 0.0 if ticks is not None else None,
                "dropped": 0,
                "fault_dropped": 0 if rows["fault_dropped"] is not None else None,
                "fault_delayed": 0 if rows["fault_delayed"] is not None else None,
                "done_nodes": 0,
            },
        )
        t["jobs"] += 1
        t["replicas"].append(i)
        for key in ("ticks", "dropped", "fault_dropped", "fault_delayed", "done_nodes"):
            if job[key] is not None and t[key] is not None:
                t[key] += job[key]
        if job["device_time_share"] is not None and t["device_time_share"] is not None:
            t["device_time_share"] += job["device_time_share"]

    return {"batch": batch, "jobs": jobs, "tenants": tenants}

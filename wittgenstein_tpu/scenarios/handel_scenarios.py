"""HandelScenarios on the batched engine (HandelScenarios.java:22).

One command reproduces a scenario battery as CSV + stdout lines in the
reference's `id, nodes, value, BasicStats` shape — but each battery is a
single stacked batched computation instead of sequential reseeded runs:

    python -m wittgenstein_tpu.scenarios.handel_scenarios tor \
        --nodes 128 --replicas 4 --out tor.csv

Scenarios (HandelScenarios.java refs):
  tor             impact of the ratio of nodes behind Tor (:177-190)
  byzantine       byzantineSuicide dead-ratio sweep 0-50% (:204-236)
  hidden          hiddenByzantine dead-ratio sweep (:259-287)
  desync          desynchronized start impact (:192-202 noSyncStart)
  log             node-count scaling sweep + PNG pair (:324-363)
  logErrors       node sweep at a fail-silent ratio + PNGs (:365-431)
  logPeriodTime   dissemination-period sweep + PNGs (:433-473)
  logDelayedStart desynchronizedStart sweep + PNGs (:475-520)
  logStartTime    levelWaitTime sweep + PNGs (:522-563)
  logExtraCycle   extraCycle sweep (:565-586)
  logContactedNode fastPath sweep + PNGs (:588-632)
  window          windowInitial sweep (WindowParameters, Handel.java:150-210)
  delayedStart    the delayedStartImpact arithmetic (:300-322)
  all             allScenarios battery (:633-656): the four log* sweeps at
                  (dead, tor) in {(0,0), (.2,0), (.2,.2)} with the
                  reference's CSV ids
  genAnim         world-map GIF (:291)

The reference runs every battery at n=4096 with CITIES placement; the
CLI keeps n a flag (--nodes) so the full-size battery is one command on
the chip while CI smoke uses small n.  PNGs use the reference's file
names (handel_log_time.png, handel_period_time.png, ...).
"""

from __future__ import annotations

import argparse
from typing import List, Optional

from ..tools.csv_formatter import CSVFormatter
from .sweep import BasicStats, SweepConfig, default_params, run_sweep

CSV_FIELDS = [
    "id",
    "nodes",
    "value",
    "done_at_min",
    "done_at_avg",
    "done_at_max",
    "msg_rcv_min",
    "msg_rcv_avg",
    "msg_rcv_max",
    "msg_filtered_avg",
    "sigs_checked_avg",
]


def tor_configs(nodes: int) -> List[SweepConfig]:
    from ..core.registries import TOR_RATIOS

    return [
        SweepConfig("tor", tor, default_params(nodes, dead_ratio=0.0, tor=tor))
        for tor in TOR_RATIOS
    ]


def byzantine_configs(nodes: int, hidden: bool = False) -> List[SweepConfig]:
    sid = "byzHidden" if hidden else "byzSuicide"
    out = []
    for dr in (0.0, 0.10, 0.20, 0.30, 0.40, 0.50):
        out.append(
            SweepConfig(
                sid,
                dr,
                default_params(
                    nodes,
                    dead_ratio=dr,
                    byzantine_suicide=not hidden and dr > 0,
                    hidden_byzantine=hidden and dr > 0,
                ),
            )
        )
    return out


def desync_configs(nodes: int) -> List[SweepConfig]:
    return [
        SweepConfig(
            "noSyncStart", s, default_params(nodes, dead_ratio=0.0, desynchronized_start=s)
        )
        for s in (0, 50, 100, 200, 400, 800)
    ]


# -- the deep log* battery (HandelScenarios.java:324-632) -------------------
CITIES = "CITIES"


def log_configs(nodes: int, dead: float = 0.0, tor: float = 0.0) -> List[SweepConfig]:
    """log() (:324-363): node-count doubling sweep; expect log time and
    polylog messages.  `nodes` is the sweep CEILING (reference: 8192)."""
    out, n = [], 64
    while n <= max(nodes, 64):
        out.append(
            SweepConfig("log", n, default_params(n, dead_ratio=dead, tor=tor, loc=CITIES))
        )
        n *= 2
    return out


def log_errors_configs(nodes: int, dead: float = 0.0, tor: float = 0.0) -> List[SweepConfig]:
    """logErrors (:365-431): node sweep at a fail-silent dead ratio
    (`dead` = the errorRate argument) with byzantineSuicide signatures and
    a 100 ms desynchronized start."""
    out, n = [], 32
    while n <= max(nodes, 32):
        out.append(
            SweepConfig(
                f"fail-silent:{dead}",
                n,
                default_params(
                    n,
                    dead_ratio=dead,
                    tor=tor,
                    desynchronized_start=100,
                    byzantine_suicide=dead > 0,
                    loc=CITIES,
                ),
            )
        )
        n *= 2
    return out


def log_period_configs(nodes: int, dead: float = 0.0, tor: float = 0.0, sid: str = "period") -> List[SweepConfig]:
    """logPeriodTime (:433-473): dissemination-period sweep at fixed n."""
    return [
        SweepConfig(
            sid,
            pt,
            default_params(
                nodes, dead_ratio=dead, tor=tor, period_time=pt,
                extra_cycle=10, desynchronized_start=100, loc=CITIES,
            ),
        )
        for pt in (1, 5, 10, 15, 20, 40, 80, 160, 320, 640)
    ]


def log_delayed_start_configs(nodes: int, dead: float = 0.0, tor: float = 0.0) -> List[SweepConfig]:
    """logDelayedStart (:475-520): desynchronizedStart sweep."""
    return [
        SweepConfig(
            "delayedStart",
            s,
            default_params(nodes, dead_ratio=dead, tor=tor, desynchronized_start=s, loc=CITIES),
        )
        for s in (0, 10, 20, 30, 50, 70, 100)
    ]


def log_start_time_configs(nodes: int, dead: float = 0.0, tor: float = 0.0, sid: str = "startTime") -> List[SweepConfig]:
    """logStartTime (:522-563): levelWaitTime sweep."""
    return [
        SweepConfig(
            sid,
            s,
            default_params(
                nodes, dead_ratio=dead, tor=tor, desynchronized_start=100,
                level_wait_time=s, loc=CITIES,
            ),
        )
        for s in (0, 25, 50, 75, 100)
    ]


def log_extra_cycle_configs(nodes: int, dead: float = 0.0, tor: float = 0.0, sid: str = "extraCycle") -> List[SweepConfig]:
    """logExtraCycle (:565-586): extraCycle sweep."""
    return [
        SweepConfig(
            sid,
            ec,
            default_params(
                nodes, dead_ratio=dead, tor=tor, extra_cycle=ec,
                desynchronized_start=100, loc=CITIES,
            ),
        )
        for ec in (10, 15, 20, 30, 40, 50)
    ]


def log_contacted_configs(nodes: int, dead: float = 0.0, tor: float = 0.0, sid: str = "fastPath") -> List[SweepConfig]:
    """logContactedNode (:588-632): fastPath peer-count sweep."""
    return [
        SweepConfig(
            sid,
            fp,
            default_params(
                nodes, dead_ratio=dead, tor=tor, desynchronized_start=100,
                fast_path=fp, loc=CITIES,
            ),
        )
        for fp in (0, 5, 10, 20, 40)
    ]


def window_configs(nodes: int, dead: float = 0.0, tor: float = 0.0) -> List[SweepConfig]:
    """Window-parameter exploration (WindowParameters/ScoringExp,
    Handel.java:150-210): the batteries' missing knob — sweep the initial
    window size through the adaptation range."""
    return [
        SweepConfig(
            "window",
            w,
            default_params(nodes, dead_ratio=dead, tor=tor, window_initial=w, loc=CITIES),
        )
        for w in (1, 4, 16, 64, 128)
    ]


def delayed_start_impact(n: int, wait_time: int, period: int) -> tuple:
    """delayedStartImpact (:300-322): pure arithmetic — how many sends the
    levelWaitTime gating saves over the first second."""
    from ..utils.more_math import log2

    m_f = m_s = 0
    for time in range(0, 1001, period):
        for level in range(1, log2(n) + 1):
            m_f += 1
            if time >= (level - 1) * wait_time:
                m_s += 1
    saved = m_f - m_s
    print(
        f"Sent w/o waitTime: {m_f}, w/ waitTime:{m_s}, "
        f"saved= {saved} - {saved / m_s}"
    )
    return m_f, m_s


SCENARIOS = {
    "tor": tor_configs,
    "byzantine": byzantine_configs,
    "hidden": lambda n, **kw: byzantine_configs(n, hidden=True),
    "desync": desync_configs,
    "log": log_configs,
    "logErrors": log_errors_configs,
    "logPeriodTime": log_period_configs,
    "logDelayedStart": log_delayed_start_configs,
    "logStartTime": log_start_time_configs,
    "logExtraCycle": log_extra_cycle_configs,
    "logContactedNode": log_contacted_configs,
    "window": window_configs,
}

# which batteries take (dead, tor) CLI knobs
_DEAD_TOR = {
    "log", "logErrors", "logPeriodTime", "logDelayedStart",
    "logStartTime", "logExtraCycle", "logContactedNode", "window",
}

# battery -> (png stem, x-axis label) for the reference's graph pairs
_GRAPHS = {
    "log": ("handel_log", "number of nodes"),
    "logErrors": ("handel_log_errors", "number of nodes"),
    "logPeriodTime": ("handel_period", "period time in ms"),
    "logDelayedStart": ("handel_delayedStart", "delay in ms"),
    "logStartTime": ("handel_startTime", "start time in ms"),
    "logContactedNode": ("handel_fastpath", "fast path peer count"),
}


def save_battery_graphs(name: str, configs: List[SweepConfig], stats: List[BasicStats], out_dir: str = ".") -> List[str]:
    """The reference's PNG pair per battery: avg time vs the swept value,
    avg messages vs the swept value (Graph usage, e.g. :345-363)."""
    import os

    from ..tools.graph import Graph, ReportLine, Series

    if name not in _GRAPHS:
        return []
    stem, x_name = _GRAPHS[name]
    t_a = Series("average time")
    m_a = Series("average number of messages")
    for c, bs in zip(configs, stats):
        t_a.add_line(ReportLine(float(c.value), bs.done_at_avg))
        m_a.add_line(ReportLine(float(c.value), bs.msg_rcv_avg))
    paths = []
    g = Graph(f"time vs. {x_name}", x_name, "time in milliseconds")
    g.add_serie(t_a)
    p = os.path.join(out_dir, f"{stem}_time.png")
    g.save(p)
    paths.append(p)
    g = Graph(f"messages vs. {x_name}", x_name, "number of messages")
    g.add_serie(m_a)
    p = os.path.join(out_dir, f"{stem}_msg.png")
    g.save(p)
    paths.append(p)
    return paths


# allScenarios (:633-656): the four parameter sweeps at three (dead, tor)
# corners, with the reference's CSV id per block.  Note the period ids are
# the reference's own quirk — "301" tags the CLEAN corner and "30" the
# dead corner (:638-639), inverted vs the other sweeps' base/base+1
# pattern; kept verbatim so CSVs line up with the reference's output.
ALL_BATTERY = [
    (log_period_configs, 0.0, 0.0, "301"),
    (log_period_configs, 0.2, 0.0, "30"),
    (log_extra_cycle_configs, 0.0, 0.0, "40"),
    (log_extra_cycle_configs, 0.2, 0.0, "401"),
    (log_start_time_configs, 0.0, 0.0, "10"),
    (log_start_time_configs, 0.2, 0.0, "101"),
    (log_contacted_configs, 0.0, 0.0, "20"),
    (log_contacted_configs, 0.2, 0.0, "201"),
    (log_extra_cycle_configs, 0.2, 0.2, "41"),
    (log_start_time_configs, 0.2, 0.2, "111"),
    (log_contacted_configs, 0.2, 0.2, "211"),
    (log_period_configs, 0.2, 0.2, "311"),
]


def run_all(nodes: int, replicas: int, sim_ms: int, out: Optional[str], battery=None) -> None:
    """allScenarios: every sweep in ALL_BATTERY, one combined CSV."""
    csv = CSVFormatter("allScenarios", CSV_FIELDS)
    print("type, node, analyzed, msg, msgFiltered, sigsChecked, time")
    for fn, dead, tor, sid in battery or ALL_BATTERY:
        configs = fn(nodes, dead=dead, tor=tor, sid=sid)
        stats = run_sweep(configs, replicas=replicas, sim_ms=sim_ms)
        for c, bs in zip(configs, stats):
            print(
                f"{sid}, {nodes}, {c.value}, {bs.msg_rcv_avg}, "
                f"{bs.msg_filtered_avg}, {bs.sigs_checked_avg}, {bs.done_at_avg}"
            )
            csv.add({"id": sid, "nodes": nodes, "value": c.value, **bs.row()})
    if out:
        csv.save(out)
        print(f"wrote {out}")


def gen_anim(
    nodes: int = 128,
    sim_ms: int = 3000,
    frequency_ms: int = 10,
    dest: str = "handel.gif",
) -> str:
    """HandelScenarios.genAnim (:291) via Handel.drawImgs (:700-768): one
    batched run rendered as a GIF — each node a map dot colored by its
    aggregate signature count (red->green ramp), done nodes marked."""
    from types import SimpleNamespace

    import numpy as np

    from ..ops.bitops import popcount_words
    from ..protocols.handel_batched import make_handel
    from ..tools.node_drawer import NodeDrawer, NodeStatus

    net, state = make_handel(default_params(nodes, dead_ratio=0.0))

    class HStatus(NodeStatus):
        # Handel's HNodeStatus: value = signatures held, special = done
        def get_val(self, n):
            return n.val

        def is_special(self, n):
            return n.special

        def get_max(self):
            return nodes

        def get_min(self):
            return 0

    xs = np.asarray(state.x)
    ys = np.asarray(state.y)
    with NodeDrawer(HStatus(), dest, frequency_ms) as drawer:
        t = 0
        while t < sim_ms:
            state = net.run_ms(state, frequency_ms)
            t += frequency_ms
            held = np.asarray(popcount_words(state.proto["inc"]))
            done = np.asarray(state.done_at) > 0
            down = np.asarray(state.down)
            live = [
                SimpleNamespace(
                    node_id=i,
                    x=int(xs[i]),
                    y=int(ys[i]),
                    val=int(held[i]),
                    special=bool(done[i]),
                )
                for i in range(nodes)
                if not down[i]
            ]
            drawer.draw_new_state(t, live)
    return dest


def run_scenario(
    name: str,
    nodes: int = 128,
    replicas: int = 4,
    sim_ms: int = 4000,
    out: Optional[str] = None,
    dead: float = 0.0,
    tor: float = 0.0,
    graphs_dir: Optional[str] = None,
) -> List[BasicStats]:
    kw = {"dead": dead, "tor": tor} if name in _DEAD_TOR else {}
    configs = SCENARIOS[name](nodes, **kw)
    stats = run_sweep(configs, replicas=replicas, sim_ms=sim_ms)
    csv = CSVFormatter(name, CSV_FIELDS)
    for c, bs in zip(configs, stats):
        n_cfg = c.params.node_count
        print(f"{c.label}, {n_cfg}, {c.value}, {bs}")
        csv.add({"id": c.label, "nodes": n_cfg, "value": c.value, **bs.row()})
    if out:
        csv.save(out)
        print(f"wrote {out}")
    if graphs_dir is not None:
        for p in save_battery_graphs(name, configs, stats, graphs_dir):
            print(f"wrote {p}")
    return stats


def _honor_jax_platforms_env() -> None:
    """Apply JAX_PLATFORMS at the CONFIG level: some environments pin the
    platform in sitecustomize, where the env var alone is silently ignored
    and a CPU-intended CLI run hangs on a dead accelerator tunnel
    (docs/TPU_NOTES.md, config-level platform pinning gotcha)."""
    import os

    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)


def main(argv=None) -> None:
    _honor_jax_platforms_env()
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "scenario", choices=sorted(SCENARIOS) + ["genAnim", "delayedStart", "all"]
    )
    ap.add_argument("--nodes", type=int, default=128)
    ap.add_argument("--replicas", type=int, default=4)
    ap.add_argument("--sim-ms", type=int, default=4000)
    ap.add_argument("--out", default=None)
    ap.add_argument("--frequency-ms", type=int, default=10)
    ap.add_argument("--dead", type=float, default=0.0)
    from ..core.registries import TOR_RATIOS

    ap.add_argument(
        "--tor", type=float, default=0.0, choices=TOR_RATIOS,
        help="fraction of nodes behind Tor (registry-backed ratios only)",
    )
    ap.add_argument("--graphs-dir", default=None,
                    help="write the reference's PNG pair for this battery here")
    ap.add_argument("--wait-time", type=int, default=50)
    ap.add_argument("--period", type=int, default=20)
    a = ap.parse_args(argv)
    if a.scenario == "genAnim":
        dest = gen_anim(a.nodes, a.sim_ms, a.frequency_ms, a.out or "handel.gif")
        print(f"wrote {dest}")
        return
    if a.scenario == "delayedStart":
        delayed_start_impact(a.nodes, a.wait_time, a.period)
        return
    if a.scenario == "all":
        run_all(a.nodes, a.replicas, a.sim_ms, a.out)
        return
    run_scenario(
        a.scenario, a.nodes, a.replicas, a.sim_ms, a.out,
        dead=a.dead, tor=a.tor, graphs_dir=a.graphs_dir,
    )


if __name__ == "__main__":
    main()

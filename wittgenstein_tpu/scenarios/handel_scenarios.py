"""HandelScenarios on the batched engine (HandelScenarios.java:22).

One command reproduces a scenario battery as CSV + stdout lines in the
reference's `id, nodes, value, BasicStats` shape — but each battery is a
single stacked batched computation instead of sequential reseeded runs:

    python -m wittgenstein_tpu.scenarios.handel_scenarios tor \
        --nodes 128 --replicas 4 --out tor.csv

Scenarios (HandelScenarios.java refs):
  tor        impact of the ratio of nodes behind Tor (:177-190)
  byzantine  byzantineSuicide dead-ratio sweep 0-50% (:204-236)
  hidden     hiddenByzantine dead-ratio sweep (:259-287)
  desync     desynchronized start impact (:192-202 noSyncStart)
"""

from __future__ import annotations

import argparse
from typing import List, Optional

from ..tools.csv_formatter import CSVFormatter
from .sweep import BasicStats, SweepConfig, default_params, run_sweep

CSV_FIELDS = [
    "id",
    "nodes",
    "value",
    "done_at_min",
    "done_at_avg",
    "done_at_max",
    "msg_rcv_min",
    "msg_rcv_avg",
    "msg_rcv_max",
    "msg_filtered_avg",
    "sigs_checked_avg",
]


def tor_configs(nodes: int) -> List[SweepConfig]:
    from ..core.registries import TOR_RATIOS

    return [
        SweepConfig("tor", tor, default_params(nodes, dead_ratio=0.0, tor=tor))
        for tor in TOR_RATIOS
    ]


def byzantine_configs(nodes: int, hidden: bool = False) -> List[SweepConfig]:
    sid = "byzHidden" if hidden else "byzSuicide"
    out = []
    for dr in (0.0, 0.10, 0.20, 0.30, 0.40, 0.50):
        out.append(
            SweepConfig(
                sid,
                dr,
                default_params(
                    nodes,
                    dead_ratio=dr,
                    byzantine_suicide=not hidden and dr > 0,
                    hidden_byzantine=hidden and dr > 0,
                ),
            )
        )
    return out


def desync_configs(nodes: int) -> List[SweepConfig]:
    return [
        SweepConfig(
            "noSyncStart", s, default_params(nodes, dead_ratio=0.0, desynchronized_start=s)
        )
        for s in (0, 50, 100, 200, 400, 800)
    ]


SCENARIOS = {
    "tor": tor_configs,
    "byzantine": byzantine_configs,
    "hidden": lambda n: byzantine_configs(n, hidden=True),
    "desync": desync_configs,
}


def gen_anim(
    nodes: int = 128,
    sim_ms: int = 3000,
    frequency_ms: int = 10,
    dest: str = "handel.gif",
) -> str:
    """HandelScenarios.genAnim (:291) via Handel.drawImgs (:700-768): one
    batched run rendered as a GIF — each node a map dot colored by its
    aggregate signature count (red->green ramp), done nodes marked."""
    from types import SimpleNamespace

    import numpy as np

    from ..ops.bitops import popcount_words
    from ..protocols.handel_batched import make_handel
    from ..tools.node_drawer import NodeDrawer, NodeStatus

    net, state = make_handel(default_params(nodes, dead_ratio=0.0))

    class HStatus(NodeStatus):
        # Handel's HNodeStatus: value = signatures held, special = done
        def get_val(self, n):
            return n.val

        def is_special(self, n):
            return n.special

        def get_max(self):
            return nodes

        def get_min(self):
            return 0

    xs = np.asarray(state.x)
    ys = np.asarray(state.y)
    with NodeDrawer(HStatus(), dest, frequency_ms) as drawer:
        t = 0
        while t < sim_ms:
            state = net.run_ms(state, frequency_ms)
            t += frequency_ms
            held = np.asarray(popcount_words(state.proto["inc"]))
            done = np.asarray(state.done_at) > 0
            down = np.asarray(state.down)
            live = [
                SimpleNamespace(
                    node_id=i,
                    x=int(xs[i]),
                    y=int(ys[i]),
                    val=int(held[i]),
                    special=bool(done[i]),
                )
                for i in range(nodes)
                if not down[i]
            ]
            drawer.draw_new_state(t, live)
    return dest


def run_scenario(
    name: str,
    nodes: int = 128,
    replicas: int = 4,
    sim_ms: int = 4000,
    out: Optional[str] = None,
) -> List[BasicStats]:
    configs = SCENARIOS[name](nodes)
    stats = run_sweep(configs, replicas=replicas, sim_ms=sim_ms)
    csv = CSVFormatter(name, CSV_FIELDS)
    for c, bs in zip(configs, stats):
        print(f"{c.label}, {nodes}, {c.value}, {bs}")
        csv.add({"id": c.label, "nodes": nodes, "value": c.value, **bs.row()})
    if out:
        csv.save(out)
        print(f"wrote {out}")
    return stats


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("scenario", choices=sorted(SCENARIOS) + ["genAnim"])
    ap.add_argument("--nodes", type=int, default=128)
    ap.add_argument("--replicas", type=int, default=4)
    ap.add_argument("--sim-ms", type=int, default=4000)
    ap.add_argument("--out", default=None)
    ap.add_argument("--frequency-ms", type=int, default=10)
    a = ap.parse_args(argv)
    if a.scenario == "genAnim":
        dest = gen_anim(a.nodes, a.sim_ms, a.frequency_ms, a.out or "handel.gif")
        print(f"wrote {dest}")
        return
    run_scenario(a.scenario, a.nodes, a.replicas, a.sim_ms, a.out)


if __name__ == "__main__":
    main()

"""GSFSignature scenario mains (GSFSignature.java:668-768) as CLI
subcommands on the oracle engine, like the P2PHandel suites:

    python -m wittgenstein_tpu.scenarios.gsf_scenarios sigsPerTime \
        --nodes 64 --out gsf_sigs.png
    python -m wittgenstein_tpu.scenarios.gsf_scenarios drawImgs \
        --nodes 64 --out gsf_anim.gif

The reference's configuration (newProtocol, :684-697): 4096 nodes, 10%
dead, threshold 85%, AWS placement with a third of nodes behind Tor,
AwsRegionNetworkLatency.  `--nodes` scales it down for smoke runs.
"""

from __future__ import annotations

import argparse
from types import SimpleNamespace
from typing import Optional

from ..core import stats as SH
from ..protocols.gsf import GSFSignature, GSFSignatureParameters


def new_protocol(nodes: int = 4096) -> GSFSignature:
    """newProtocol (:684-697): the canonical GSF scenario config."""
    from ..core.registries import AWS, builder_name

    dead_r, ts_r = 0.10, 0.85
    params = GSFSignatureParameters(
        node_count=nodes,
        threshold=int(ts_r * nodes),
        pairing_time=4,
        timeout_per_level_ms=50,
        period_duration_ms=20,
        accelerated_calls_count=10,
        nodes_down=int(dead_r * nodes),
        node_builder_name=builder_name(AWS, False, 0.33),
        network_latency_name="AwsRegionNetworkLatency",
    )
    return GSFSignature(params)


def new_cont_if():
    """newConfIf (:670-681): continue while any live node is below the
    threshold."""

    def cont(p: GSFSignature) -> bool:
        for n in p.network().all_nodes:
            if not n.is_down() and _card(n.verified_signatures) < p.params.threshold:
                return True
        return False

    return cont


def _card(bits: int) -> int:
    return bin(bits).count("1")


def sigs_per_time(nodes: int = 4096, out: Optional[str] = "gsf_sigs.png") -> None:
    """sigsPerTime (:722-765): ProgressPerTime series of the verified-
    signature count, with the end-of-run speedRatio / sigChecked /
    queue-size stat lines."""
    from ..core.runners import ProgressPerTime

    p = new_protocol(nodes)

    class SigsGetter(SH.StatsGetter):
        def fields(self):
            return SH.SimpleStats(0, 0, 0).fields()

        def get(self, live_nodes):
            return SH.get_stats_on(live_nodes, lambda n: _card(n.verified_signatures))

    def end_cb(proto):
        live = proto.network().live_nodes()
        ss = SH.get_stats_on(live, lambda n: int(n.speed_ratio))
        print(f"min/avg/max speedRatio={ss.min}/{ss.avg}/{ss.max}")
        ss = SH.get_stats_on(live, lambda n: n.sig_checked)
        print(f"min/avg/max sigChecked={ss.min}/{ss.avg}/{ss.max}")
        # the reference's own diagnostic (:751-755) divides the
        # INSTANTANEOUS toVerify.size() by the cumulative sigChecked with
        # Java int division, so it reads 0 there too — kept verbatim
        ss = SH.get_stats_on(
            live, lambda n: n.sig_queue_size // max(n.sig_checked, 1)
        )
        print(f"min/avg/max queueSize={ss.min}/{ss.avg}/{ss.max}")

    ppt = ProgressPerTime(
        p, "", "number of signatures", SigsGetter(), 1, end_cb, 10
    )
    ppt.run(new_cont_if(), graph_path=out)


def draw_imgs(nodes: int = 4096, out: str = "gsf_anim.gif", freq: int = 10) -> str:
    """drawImgs (:699-720): world-map GIF of per-node verified-signature
    counts while the aggregation runs (GFSNodeStatus ramp)."""
    from ..tools.node_drawer import NodeDrawer, NodeStatus

    p = new_protocol(nodes)
    p.init()
    cont = new_cont_if()

    class GSFStatus(NodeStatus):
        def get_val(self, n):
            return n.val

        def is_special(self, n):
            return n.special

        def get_max(self):
            return nodes

        def get_min(self):
            return 0

    with NodeDrawer(GSFStatus(), out, freq) as nd:
        while cont(p):
            p.network().run_ms(freq)
            live = [
                SimpleNamespace(
                    node_id=n.node_id,
                    x=n.x,
                    y=n.y,
                    val=_card(n.verified_signatures),
                    special=n.done_at > 0,
                )
                for n in p.network().live_nodes()
            ]
            nd.draw_new_state(p.network().time, live)
    print(f"{out} written - ffmpeg -f gif -i {out} handel.mp4")
    return out


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("scenario", choices=["sigsPerTime", "drawImgs"])
    ap.add_argument("--nodes", type=int, default=4096)
    ap.add_argument("--out", default=None)
    ap.add_argument("--frequency-ms", type=int, default=10)
    a = ap.parse_args(argv)
    if a.scenario == "sigsPerTime":
        sigs_per_time(a.nodes, a.out or "gsf_sigs.png")
    else:
        draw_imgs(a.nodes, a.out or "gsf_anim.gif", a.frequency_ms)


if __name__ == "__main__":
    main()

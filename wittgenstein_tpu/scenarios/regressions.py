"""Pinned adversary regressions: every discovered attack stays a test.

When the search driver (search/driver.py) finds a FaultPlan that beats
the static baselines, `pin_regression` freezes it as a
`witt-regression/v1` JSON file under `scenarios/regressions/`: the
GENOME (vector + gene-spec bounds), the lowered-plan digest, the seed
its rows ran with, the objective value it scored, and the baseline
scores it strictly beat.  The file is the attack's complete identity —
everything else (node population, live mask, network) rebuilds from the
registered protocol factory, which is why `protocol` must name a
`core.registries.registry_batched_protocols` entry.

`verify_regression` replays the file BITWISE: rebuild (net, state) from
the registry, decode the genome against the rebuilt live mask, assert
the lowered digest matches the pinned one (the plan still means what it
meant), re-run the sweep with the pinned seed, and require the exact
pinned objective value (the engine is deterministic in (state, tick
count) and JSON round-trips floats exactly).  When a baseline block is
pinned, the static 5-plan sweep is re-scored too and the champion must
STRICTLY beat every plan in it — so a protocol change that blunts the
attack (or re-arms the baselines) fails the regression suite instead of
silently rotting the pin.

`check_regression_doc` is the JAX-free structural half (simlint SL1401
runs it in the fast pass): schema, registered protocol, known
objective, and genome-in-bounds, without lowering anything.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Dict, List, Optional, Union

import numpy as np

SCHEMA = "witt-regression/v1"
REGRESSIONS_DIR = Path(__file__).resolve().parent / "regressions"

_REQUIRED = (
    "schema",
    "label",
    "protocol",
    "objective",
    "sim_ms",
    "seed0",
    "replicas_per_plan",
    "genome",
    "plan_digest",
    "objective_value",
)


def pin_regression(driver, path: Union[str, Path],
                   with_baseline: bool = True) -> dict:
    """Freeze `driver.champion` at `path` (atomic tmp + os.replace).
    Called through SearchDriver.pin_champion, which also books the
    counter and flight-recorder event."""
    from ..search.driver import baseline_scores

    champ = driver.champion
    if champ is None:
        raise RuntimeError("driver has no champion to pin")
    cfg = driver.config
    doc = {
        "schema": SCHEMA,
        "label": cfg.label,
        "protocol": cfg.protocol,
        "objective": cfg.objective,
        "sim_ms": cfg.sim_ms,
        "seed0": int(champ["seed0"]),
        "replicas_per_plan": int(champ["replicas_per_plan"]),
        "genome": {
            "vec": [float(x) for x in champ["vec"]],
            "spec": driver.genome.spec.to_json(),
            "describe": driver.genome.describe(champ["vec"]),
        },
        "plan_digest": champ["plan_digest"],
        "objective_value": float(champ["score"]),
        "availability": float(champ["availability"]),
        "provenance": {
            "optimizer": cfg.optimizer,
            "population": cfg.population,
            "generations_run": driver.generation,
            "found_at_generation": int(champ["generation"]),
            "config_digest": cfg.digest(),
            "config_seed": cfg.seed,
        },
    }
    if with_baseline:
        doc["baseline"] = {
            "seed0": 0,
            "scores": baseline_scores(
                driver.net, driver.state, cfg.sim_ms, cfg.objective, seed0=0
            ),
        }
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_name(f".{path.name}.{os.getpid()}.tmp")
    tmp.write_text(json.dumps(doc, indent=2, sort_keys=True) + "\n")
    os.replace(tmp, path)
    return doc


def load_regression(path: Union[str, Path]) -> dict:
    doc = json.loads(Path(path).read_text())
    if doc.get("schema") != SCHEMA:
        raise ValueError(
            f"{path}: schema {doc.get('schema')!r} != {SCHEMA!r}"
        )
    return doc


def list_regressions(directory: Optional[Union[str, Path]] = None) -> List[Path]:
    d = Path(directory) if directory else REGRESSIONS_DIR
    return sorted(d.glob("*.json")) if d.is_dir() else []


def check_regression_doc(doc: dict) -> List[str]:
    """JAX-free structural audit; returns problem strings (empty = ok).
    The full replay (lowering + digest + bitwise score) lives in
    verify_regression."""
    from ..core.registries import registry_batched_protocols
    from ..search.genome import GenomeSpec
    from ..search.objectives import OBJECTIVES

    problems: List[str] = []
    for key in _REQUIRED:
        if key not in doc:
            problems.append(f"missing required field {key!r}")
    if problems:
        return problems
    if doc["schema"] != SCHEMA:
        problems.append(f"schema {doc['schema']!r} != {SCHEMA!r}")
    if doc["protocol"] not in registry_batched_protocols.names():
        problems.append(
            f"protocol {doc['protocol']!r} is not a registered batched "
            "protocol"
        )
    if doc["objective"] not in OBJECTIVES:
        problems.append(f"objective {doc['objective']!r} is not registered")
    if not (isinstance(doc["sim_ms"], int) and doc["sim_ms"] >= 2):
        problems.append(f"sim_ms={doc['sim_ms']!r} must be an int >= 2")
    if not (isinstance(doc["replicas_per_plan"], int)
            and doc["replicas_per_plan"] >= 1):
        problems.append(
            f"replicas_per_plan={doc['replicas_per_plan']!r} must be an "
            "int >= 1"
        )
    genome = doc["genome"]
    if not isinstance(genome, dict) or "vec" not in genome or "spec" not in genome:
        problems.append("genome must carry 'vec' and 'spec'")
        return problems
    try:
        spec = GenomeSpec.from_json(genome["spec"])
        spec.validate(np.asarray(genome["vec"], np.float64))
    except (ValueError, KeyError, TypeError) as e:
        problems.append(f"genome does not validate against its spec: {e}")
    base = doc.get("baseline")
    if base is not None:
        scores = base.get("scores")
        if not isinstance(scores, dict) or not scores:
            problems.append("baseline block present but has no scores")
        elif not all(
            float(doc["objective_value"]) > float(s) for s in scores.values()
        ):
            problems.append(
                "pinned objective_value does not strictly beat every "
                "pinned baseline score"
            )
    return problems


def verify_regression(path_or_doc: Union[str, Path, dict],
                      check_baseline: bool = True) -> dict:
    """Full bitwise replay (module docstring).  Raises AssertionError on
    any drift; returns {'objective_value', 'plan_digest', 'record',
    'baseline_scores'} from the replay."""
    from ..core.registries import registry_batched_protocols
    from ..search.driver import baseline_scores
    from ..search.genome import FaultGenome
    from ..search.objectives import score_records
    from .sweep import run_fault_sweep

    doc = (
        path_or_doc
        if isinstance(path_or_doc, dict)
        else load_regression(path_or_doc)
    )
    problems = check_regression_doc(doc)
    if problems:
        raise AssertionError(
            "regression doc is structurally invalid: " + "; ".join(problems)
        )
    net, state = registry_batched_protocols.get(doc["protocol"]).factory()
    genome = FaultGenome(
        doc["sim_ms"], net.n_nodes, live=~np.asarray(state.down)
    )
    vec = np.asarray(doc["genome"]["vec"], np.float64)
    genome.spec.validate(vec)
    digest = genome.digest(vec, net.protocol.n_msg_types())
    assert digest == doc["plan_digest"], (
        f"lowered-plan digest drifted: replay {digest} != pinned "
        f"{doc['plan_digest']} — the genome no longer lowers to the "
        "attack that was pinned"
    )
    plan = genome.to_plan(vec, label=doc["label"])
    _, records = run_fault_sweep(
        net,
        state,
        [plan],
        doc["sim_ms"],
        replicas_per_plan=doc["replicas_per_plan"],
        seed0=doc["seed0"],
    )
    score = float(
        score_records(records, doc["objective"], doc["sim_ms"])[0]
    )
    assert score == float(doc["objective_value"]), (
        f"replayed objective {score!r} != pinned "
        f"{doc['objective_value']!r} (bitwise replay broken)"
    )
    out = {
        "objective_value": score,
        "plan_digest": digest,
        "record": records[0],
        "baseline_scores": None,
    }
    if check_baseline and doc.get("baseline") is not None:
        base = baseline_scores(
            net, state, doc["sim_ms"], doc["objective"],
            seed0=int(doc["baseline"]["seed0"]),
        )
        out["baseline_scores"] = base
        weaker = {k: v for k, v in base.items() if not score > v}
        assert not weaker, (
            "champion no longer strictly beats the static baselines: "
            + ", ".join(f"{k}={v}" for k, v in weaker.items())
        )
    return out

"""Oracle-side scenario suites: P2PHandelScenarios and
OptimisticP2PSignatureScenarios (P2PHandelScenarios.java:17-283,
OptimisticP2PSignatureScenarios.java:15-107).

These protocols run on the oracle engine (no batched twin), so the suites
keep the reference's RunMultipleTimes shape: `run(rounds, params)` ->
BasicStats, a node-count scaling battery (logErrors), and the
signatures-per-time Graph series (sigsPerTime).

    python -m wittgenstein_tpu.scenarios.oracle_scenarios p2phandel-scaling
    python -m wittgenstein_tpu.scenarios.oracle_scenarios optimistic-scaling
    python -m wittgenstein_tpu.scenarios.oracle_scenarios p2phandel-sigs --out sigs.png
"""

from __future__ import annotations

import argparse
import dataclasses
from typing import List, Optional

from ..core import stats as SH
from ..core.runners import RunMultipleTimes
from ..tools.graph import Graph, ReportLine, Series


@dataclasses.dataclass
class BasicStats:
    """(P2PHandelScenarios.BasicStats / OptimisticP2PSignatureScenarios)."""

    done_at_min: int
    done_at_avg: int
    done_at_max: int
    msg_rcv_min: int
    msg_rcv_avg: int
    msg_rcv_max: int
    bytes_rcv_avg: int = 0

    def __str__(self) -> str:
        return (
            f"; doneAtAvg={self.done_at_avg}; msgRcvAvg={self.msg_rcv_avg}"
            f", bytesRcvAvg={self.bytes_rcv_avg}"
        )


class _BytesReceivedGetter(SH.SimpleStatsGetter):
    def get(self, live_nodes):
        return SH.get_stats_on(live_nodes, lambda n: n.bytes_received)


def run_protocol(protocol, rounds: int) -> BasicStats:
    """RunMultipleTimes battery with the reference's getters."""
    getters: List[SH.StatsGetter] = [
        SH.DoneAtStatGetter(),
        SH.MsgReceivedStatGetter(),
        _BytesReceivedGetter(),
    ]
    rmt = RunMultipleTimes(protocol, rounds, 0, getters)
    res = rmt.run(RunMultipleTimes.cont_until_done())
    return BasicStats(
        res[0].get("min"),
        res[0].get("avg"),
        res[0].get("max"),
        res[1].get("min"),
        res[1].get("avg"),
        res[1].get("max"),
        res[2].get("avg"),
    )


# -- P2PHandel ---------------------------------------------------------------
def p2phandel_params(
    n: int,
    dead_ratio: float = 0.0,
    connections: int = 8,
    threshold: Optional[int] = None,
    strategy: str = "dif",
):
    from ..core.registries import RANDOM, builder_name
    from ..protocols.p2phandel import P2PHandel, P2PHandelParameters

    params = P2PHandelParameters(
        signing_node_count=n,
        relaying_node_count=0,
        threshold=threshold or int(n * (1 - dead_ratio) * 0.99) or 1,
        connection_count=connections,
        pairing_time=3,
        sigs_send_period=50,
        double_aggregate_strategy=True,
        send_sigs_strategy=strategy,
        send_state=False,
        node_builder_name=builder_name(RANDOM, True, 0),
        network_latency_name="NetworkLatencyByDistanceWJitter",
    )
    return P2PHandel(params)


def p2phandel_scaling(rounds: int = 3, max_nodes: int = 256) -> List[BasicStats]:
    """logErrors (P2PHandelScenarios.java:81-104): behavior as the node
    count doubles."""
    out = []
    n = 32
    while n <= max_nodes:
        bs = run_protocol(p2phandel_params(n), rounds)
        print(f"{n} nodes: 0.0{bs}")
        out.append(bs)
        n *= 2
    return out


def p2phandel_sigs_per_time(
    node_ct: int = 128, series: int = 3, out: Optional[str] = None
) -> Graph:
    """sigsPerTime (P2PHandelScenarios.java:106-180): per-run min/max/avg
    verified-signature series over time, rendered with Graph.  The
    reference's configuration: full-threshold, strategy 'all',
    15 connections (:115-126)."""
    template = p2phandel_params(node_ct, connections=15, threshold=node_ct, strategy="all")
    g = Graph(
        f"number of signatures per time (n={node_ct})",
        "time in ms",
        "number of signatures",
    )
    for i in range(series):
        cur_min = Series(f"signatures count - worse node{i}")
        cur_max = Series(f"signatures count - best node{i}")
        cur_avg = Series(f"signatures count - average{i}")
        p = template.copy()
        p.network().rd.set_seed(i)
        p.init()
        while True:
            p.network().run_ms(10)
            s = SH.get_stats_on(
                p.network().all_nodes,
                lambda n: n.verified_signatures.cardinality(),
            )
            cur_min.add_line(ReportLine(p.network().time, s.min))
            cur_max.add_line(ReportLine(p.network().time, s.max))
            cur_avg.add_line(ReportLine(p.network().time, s.avg))
            if s.min == template.params.signing_node_count:
                break
            if p.network().time > 60_000:
                raise RuntimeError("sigsPerTime did not converge")
        g.add_serie(cur_min)
        g.add_serie(cur_max)
        g.add_serie(cur_avg)
    if out:
        g.save(out)
        print(f"wrote {out}")
    return g


# -- OptimisticP2PSignature --------------------------------------------------
def optimistic_params(n: int):
    from ..core.registries import RANDOM, builder_name
    from ..protocols.optimistic_p2p_signature import (
        OptimisticP2PSignature,
        OptimisticP2PSignatureParameters,
    )

    params = OptimisticP2PSignatureParameters(
        node_count=n,
        threshold=int(n * 0.99) or 1,
        connection_count=13,
        pairing_time=3,
        node_builder_name=builder_name(RANDOM, True, 0),
        network_latency_name="NetworkLatencyByDistanceWJitter",
    )
    return OptimisticP2PSignature(params)


def optimistic_scaling(rounds: int = 3, max_nodes: int = 512) -> List[BasicStats]:
    """logErrors (OptimisticP2PSignatureScenarios.java:60-85)."""
    out = []
    n = 64
    while n <= max_nodes:
        bs = run_protocol(optimistic_params(n), rounds)
        print(f"{n} nodes: 0.0{bs}")
        out.append(bs)
        n *= 2
    return out


SCENARIOS = {
    "p2phandel-scaling": lambda a: p2phandel_scaling(a.rounds, a.nodes),
    "optimistic-scaling": lambda a: optimistic_scaling(a.rounds, a.nodes),
    "p2phandel-sigs": lambda a: p2phandel_sigs_per_time(a.nodes, out=a.out),
}


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("scenario", choices=sorted(SCENARIOS))
    ap.add_argument("--nodes", type=int, default=256)
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--out", default=None)
    a = ap.parse_args(argv)
    SCENARIOS[a.scenario](a)


if __name__ == "__main__":
    main()

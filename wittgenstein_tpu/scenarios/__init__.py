"""Scenario drivers (L4) on the batched engine.

The reference's research drivers (HandelScenarios.java:22,
P2PHandelScenarios.java, OptimisticP2PSignatureScenarios.java) run one
configuration at a time through RunMultipleTimes' sequential reseeded
loop.  Here a whole sweep — (configuration x replica) — is ONE stacked
batched computation (`jax.vmap` over the leading axis), reduced to
BasicStats rows on the device and emitted as the same CSV shape the
reference prints.

Pinned adversary regressions (regressions.py) ride along: discovered
attacks frozen as replayable `scenarios/regressions/*.json` files.

Attribute access is LAZY (PEP 562): `regressions`'s structural half is
part of simlint's JAX-free fast pass (rule SL1401), so importing this
package must not pull `sweep`'s JAX dependency until a sweep symbol is
actually touched.
"""

_SWEEP = (
    "BasicStats",
    "SweepConfig",
    "run_sweep",
    "run_fault_sweep",
    "sweep_counters",
    "SWEEP_COUNTERS",
)
_REGRESSIONS = (
    "SCHEMA",
    "REGRESSIONS_DIR",
    "pin_regression",
    "load_regression",
    "list_regressions",
    "check_regression_doc",
    "verify_regression",
)

__all__ = sorted(_SWEEP + _REGRESSIONS)


def __getattr__(name):
    if name in _SWEEP:
        from . import sweep

        return getattr(sweep, name)
    if name in _REGRESSIONS:
        from . import regressions

        return getattr(regressions, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(__all__))

"""Scenario drivers (L4) on the batched engine.

The reference's research drivers (HandelScenarios.java:22,
P2PHandelScenarios.java, OptimisticP2PSignatureScenarios.java) run one
configuration at a time through RunMultipleTimes' sequential reseeded
loop.  Here a whole sweep — (configuration x replica) — is ONE stacked
batched computation (`jax.vmap` over the leading axis), reduced to
BasicStats rows on the device and emitted as the same CSV shape the
reference prints.
"""

from .sweep import BasicStats, SweepConfig, run_sweep

__all__ = ["BasicStats", "SweepConfig", "run_sweep"]

"""The batched sweep runner: stacked configurations x replicas in one
`run_ms_batched` call.

This is the TPU replacement for HandelScenarios.run
(HandelScenarios.java:140-160): where the reference runs `rounds`
sequential reseeded simulations per configuration and averages
StatsHelper outputs, here every (config, replica) pair is one row of a
stacked state pytree and the whole sweep executes in lockstep.  Configs
sharing one traced program (same node count and attack-mode flags — the
static branches of the batched protocol) are grouped into one jit;
statistics reduce on-device over the (replica, node) axes.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import jax
import numpy as np

from ..core import stats as SH
from ..engine import stack_states
from ..protocols.handel import HandelParameters
from ..protocols.handel_batched import make_handel


@dataclasses.dataclass
class BasicStats:
    """The reference's per-configuration summary (HandelScenarios.java:60-90):
    doneAt and msgReceived min/avg/max over live nodes, plus the
    msgFiltered and sigsChecked averages."""

    done_at_min: int
    done_at_avg: int
    done_at_max: int
    msg_rcv_min: int
    msg_rcv_avg: int
    msg_rcv_max: int
    msg_filtered_avg: int
    sigs_checked_avg: int

    def __str__(self) -> str:
        return (
            f"doneAtAvg={self.done_at_avg}, doneAtMin={self.done_at_min}"
            f", doneAtMax={self.done_at_max}, msgRcvAvg={self.msg_rcv_avg}"
            f", msgFilteredAvg={self.msg_filtered_avg}"
            f", sigsCheckedAvg={self.sigs_checked_avg}"
        )

    def row(self) -> Dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class SweepConfig:
    """One sweep point: a Handel configuration plus its sweep label."""

    label: str
    value: object  # the swept variable's value (tor %, byz fraction, ...)
    params: HandelParameters


# Parameter fields that live in the per-row STATE (down set, start times,
# node positions/speeds) rather than the traced program; ONLY these may
# differ between configs sharing one compiled sweep.  Everything else —
# including any future field — splits the group by default, so a new
# traced knob can never silently run under another config's program.
_STATE_ONLY_FIELDS = frozenset(
    {"nodes_down", "bad_nodes", "desynchronized_start", "node_builder_name"}
)


def _group_key(p: HandelParameters):
    return tuple(
        (f.name, getattr(p, f.name))
        for f in dataclasses.fields(p)
        if f.name not in _STATE_ONLY_FIELDS
    )


def _host_done_cdf(done_cols: np.ndarray, sim_ms: int, every: int) -> dict:
    """Done-node counts at each window end, computed host-side from the
    final done_at columns ([R, N]) — the classic post-hoc reconstruction
    of the time-to-aggregation CDF."""
    qts = list(range(every - 1, sim_ms, every))
    counts = [
        [int(((dc > 0) & (dc <= t)).sum()) for t in qts] for dc in done_cols
    ]
    return {"times": qts, "counts": counts}


def run_sweep(
    configs: List[SweepConfig],
    replicas: int = 4,
    sim_ms: int = 3000,
    seed0: int = 0,
    stop_when_done: bool = False,
    telemetry=None,
    telemetry_out: Optional[list] = None,
) -> List[BasicStats]:
    """Run every (config x replica) in stacked batches; one BasicStats per
    config, reduced over live nodes of all its replicas.

    stop_when_done skips ticks once EVERY stacked row's aggregation
    completed (engine early exit) — doneAt stats are unchanged, but the
    msgRcv/msgFiltered counters stop at completion, so leave it off when
    comparing traffic against the oracle.

    telemetry takes a telemetry.TelemetryConfig: the sweep then runs
    instrumented (bit-identical sim state, counter side-car on device)
    and, when `telemetry_out` is a list, appends one record per config —
    StatsGetter-shaped doneAt/msgReceived reductions, per-mtype traffic
    counters, and the per-replica progress series decoded from the
    on-device snapshot ring (the done-at CDF without per-window host
    reads)."""
    results: Dict[int, BasicStats] = {}
    tele_records: Dict[int, dict] = {}

    # group by traced-program shape so each group is ONE compiled sweep
    groups: Dict[tuple, List[int]] = {}
    for i, c in enumerate(configs):
        groups.setdefault(_group_key(c.params), []).append(i)

    for idxs in groups.values():
        states, net = [], None
        for i in idxs:
            # one net serves the whole group (identical traced programs)
            group_net, st = make_handel(configs[i].params, telemetry=telemetry)
            net = net or group_net
            for r in range(replicas):
                states.append(
                    st._replace(seed=st.seed * 0 + (seed0 + 1000 * i + r))
                )
        stacked = stack_states(states)
        out = net.run_ms_batched(stacked, sim_ms, stop_when_done)

        down = np.asarray(out.down)
        done = np.asarray(out.done_at)
        rcv = np.asarray(out.msg_received)
        filt = np.asarray(out.proto["msg_filtered"])
        checked = np.asarray(out.proto["sigs_checked"])
        for gpos, i in enumerate(idxs):
            sl = slice(gpos * replicas, (gpos + 1) * replicas)
            live = ~down[sl]
            d = done[sl][live]
            r = rcv[sl][live]
            results[i] = BasicStats(
                int(d.min()),
                int(d.mean()),
                int(d.max()),
                int(r.min()),
                int(r.mean()),
                int(r.max()),
                int(filt[sl][live].mean()),
                int(checked[sl][live].mean()),
            )
            if telemetry is not None and telemetry_out is not None:
                sub = jax.tree_util.tree_map(lambda a: a[sl], out)
                fields = ("min", "max", "avg")
                cnt = lambda f: SH.TelemetryCounterStatGetter(f).get(sub).get(
                    "count"
                )
                from ..telemetry import progress_series

                tele_records[i] = {
                    "label": configs[i].label,
                    "value": configs[i].value,
                    # StatsGetter-shaped reductions (same field contract
                    # as the host-side DoneAt/MsgReceived getters)
                    "doneAt": {
                        f: SH.DoneAtBatchedStatGetter().get(sub).get(f)
                        for f in fields
                    },
                    "msgReceived": {
                        f: SH.MsgReceivedBatchedStatGetter().get(sub).get(f)
                        for f in fields
                    },
                    # per-run traffic counters (telemetry side-car sums)
                    "msgSentTotal": cnt("lat_sent"),
                    "msgFilteredTotal": cnt("lat_filtered"),
                    "storeDropped": cnt("dropped"),
                    "ticks": cnt("ticks"),
                    # one progress series per replica row of this config
                    "progress": progress_series(sub),
                    # host-side done-at CDF from the final state (the
                    # post-hoc path the snapshot ring replaces; kept in
                    # the record so the two can be diffed — the parity
                    # test pins them equal)
                    "doneAtCdfHost": _host_done_cdf(
                        done[sl], sim_ms, telemetry.snapshot_every_ms
                    ),
                }

    if telemetry is not None and telemetry_out is not None:
        telemetry_out.extend(tele_records[i] for i in range(len(configs)))
    return [results[i] for i in range(len(configs))]


# Monotonic dedupe accounting for run_fault_sweep: identical plans in
# one population (degenerate ES generations, converged SHA rungs) are
# evaluated ONCE and their records fanned back out; these counters are
# the observable for that contract (tests assert the deltas).
SWEEP_COUNTERS = {
    "plans_in": 0,
    "plans_evaluated": 0,
    "plans_deduped": 0,
}


def sweep_counters() -> Dict[str, int]:
    return dict(SWEEP_COUNTERS)


def run_fault_sweep(
    net,
    state,
    plans: list,
    sim_ms: int,
    replicas_per_plan: int = 1,
    faults=None,
    seed0: int = 0,
    stop_when_done: bool = False,
    done_cdf_every: int = 0,
    checkpoint_dir: Optional[str] = None,
    chunk_ms: Optional[int] = None,
    supervisor_kw: Optional[dict] = None,
    use_run_cache: bool = False,
):
    """The fault-axis sweep: one `run_ms_batched` call where replica row
    `r` runs fault plan `plans[r // replicas_per_plan]` (None entries =
    fault-free control rows).  Takes any built (net, state) — the fault
    lanes are protocol-agnostic — and returns (out, records): the final
    stacked state plus one JSON-friendly record per plan with
    availability (done fraction of statically-live nodes), done-at
    quantiles over done nodes, and the per-plan fault counters.

    Every plan shares ONE compiled program: the schedules are data
    (FaultState rows), not traced branches, so sweeping crash vs
    partition vs drop costs one jit like sweeping seeds does.

    checkpoint_dir makes the sweep RESUMABLE: the pass runs chunked
    (chunk_ms, default 100) under runtime.Supervisor with periodic
    checkpoints; an interrupted sweep re-invoked with the same arguments
    resumes at its last checkpoint and produces a report bitwise-equal
    to the uninterrupted sweep (the engine is deterministic in (state,
    tick count); keep stop_when_done=False for the bitwise claim — the
    early exit is chunk-boundary dependent).  A controlled partial stop
    (supervisor_kw budget_s / max_chunks_this_run) raises
    RunIncompleteError carrying the partial RunReport.

    Identical plans within one population are DEDUPED by lowered-plan
    digest: each distinct schedule runs once (its `replicas_per_plan`
    rows, seeded at its FIRST occurrence's position) and the resulting
    record is fanned back out to every duplicate, so a degenerate
    optimizer generation does not waste replica rows.  `out` therefore
    stacks `n_unique * replicas_per_plan` rows; each record carries its
    `plan_digest` and the `seed0_row` its first evaluated row ran with
    (the seed a single-plan bitwise replay must pass as seed0).  With
    all plans distinct — every existing caller — rows, seeds, and
    results are unchanged.

    use_run_cache evaluates through parallel.replica_shard's cached
    compiled-program path (sharded_run_stats) instead of a direct
    run_ms_batched call: repeated sweeps of the same (protocol, sim_ms,
    row geometry) — an optimizer generation per call — are run-cache
    HITS, observable on run_cache_info()'s hits/misses/compiles
    counters (the one-compile-per-generation contract).  Requires
    stop_when_done=False (the cached program has no early-exit variant)
    and is mutually exclusive with checkpoint_dir."""
    from ..engine.core import replicate_state
    from ..faults import FaultConfig
    from ..faults.plan import fault_state_digest
    from ..faults.state import neutral_fault_state, stack_fault_states

    if not plans:
        raise ValueError("run_fault_sweep needs at least one plan")
    rpp = int(replicas_per_plan)
    if rpp < 1:
        raise ValueError(f"replicas_per_plan={rpp} must be >= 1")
    if use_run_cache and stop_when_done:
        raise ValueError(
            "use_run_cache evaluates a fixed-horizon cached program; "
            "stop_when_done is not supported on that path"
        )
    if use_run_cache and checkpoint_dir is not None:
        raise ValueError(
            "use_run_cache and checkpoint_dir are mutually exclusive "
            "(the resumable path runs chunked under the Supervisor)"
        )
    fnet, fstate = net.with_faults(state, faults or FaultConfig())
    n_nodes, n_mt = net.n_nodes, net.protocol.n_msg_types()
    lowered = [
        neutral_fault_state(n_nodes, n_mt)
        if p is None
        else p.lower(n_nodes, n_mt)
        for p in plans
    ]
    digests = [fault_state_digest(low) for low in lowered]
    # dedupe by digest, first occurrence wins (keeps seeds/rows bitwise
    # identical to the pre-dedupe sweep whenever all plans are distinct)
    unique_pos: Dict[str, int] = {}
    fan: List[int] = []
    for i, dig in enumerate(digests):
        if dig not in unique_pos:
            unique_pos[dig] = len(unique_pos)
        fan.append(unique_pos[dig])
    n_unique = len(unique_pos)
    SWEEP_COUNTERS["plans_in"] += len(plans)
    SWEEP_COUNTERS["plans_evaluated"] += n_unique
    SWEEP_COUNTERS["plans_deduped"] += len(plans) - n_unique
    first_of = {u: i for i, u in reversed(list(enumerate(fan)))}
    n_rep = n_unique * rpp
    fs = stack_fault_states(
        [lowered[first_of[u]] for u in range(n_unique) for _ in range(rpp)]
    )
    batched = replicate_state(
        fstate, n_rep, seeds=np.arange(seed0, seed0 + n_rep, dtype=np.int64)
    )._replace(faults=fs)
    if checkpoint_dir is not None:
        from ..runtime import RunIncompleteError, Supervisor

        cms = int(chunk_ms or min(sim_ms, 100))
        if sim_ms % cms != 0:
            raise ValueError(
                f"chunk_ms={cms} must divide sim_ms={sim_ms} for a "
                "resumable sweep"
            )
        sup = Supervisor.from_network(
            fnet,
            batched,
            total_ms=sim_ms,
            chunk_ms=cms,
            stop_when_done=stop_when_done,
            checkpoint_dir=checkpoint_dir,
            **(supervisor_kw or {}),
        )
        report = sup.run()
        if not report.ok:
            raise RunIncompleteError(
                f"fault sweep stopped after {report.chunks_done}/"
                f"{sup.n_chunks} chunks (budget/cap reached); checkpoint "
                "saved — re-invoke with the same arguments to resume",
                report=report,
            )
        out = report.state
    elif use_run_cache:
        from ..parallel.replica_shard import sharded_run_stats

        out, _ = sharded_run_stats(fnet, batched, sim_ms)
    else:
        out = fnet.run_ms_batched(batched, sim_ms, stop_when_done)

    done = np.asarray(out.done_at)
    down = np.asarray(out.down)
    dropped = np.asarray(out.faults.dropped_by_fault)
    delayed = np.asarray(out.faults.delayed_by_fault)
    records = []
    for i, plan in enumerate(plans):
        u = fan[i]
        sl = slice(u * rpp, (u + 1) * rpp)
        live = ~down[sl]
        d = done[sl][live]
        fin = d[d > 0]
        rec = {
            "plan": (
                {"label": "control"} if plan is None else plan.describe()
            ),
            "plan_digest": digests[i],
            "seed0_row": int(seed0 + u * rpp),
            "replicas": rpp,
            "live_nodes": int(live.sum()),
            "done_nodes": int(fin.size),
            "availability": round(float(fin.size) / max(1, live.sum()), 4),
            "done_at_ms": (
                {
                    "p10": int(np.percentile(fin, 10)),
                    "p50": int(np.percentile(fin, 50)),
                    "p90": int(np.percentile(fin, 90)),
                    "max": int(fin.max()),
                }
                if fin.size
                else None
            ),
            "dropped_by_fault": dropped[sl].sum(axis=0).tolist(),
            "delayed_by_fault": delayed[sl].sum(axis=0).tolist(),
        }
        if done_cdf_every:
            rec["done_cdf"] = _host_done_cdf(done[sl], sim_ms, done_cdf_every)
        records.append(rec)
    return out, records


def default_params(
    nodes: int,
    dead_ratio: Optional[float] = None,
    tor: Optional[float] = None,
    period_time: Optional[int] = None,
    extra_cycle: Optional[int] = None,
    desynchronized_start: Optional[int] = None,
    byzantine_suicide: bool = False,
    hidden_byzantine: bool = False,
    loc: Optional[str] = None,
    level_wait_time: Optional[int] = None,
    fast_path: Optional[int] = None,
    window_initial: Optional[int] = None,
) -> HandelParameters:
    """HandelScenarios.defaultParams (HandelScenarios.java:65-122), full
    signature.  loc=None keeps the repo battery's original RANDOM
    placement with the default latency; "AWS"/"CITIES"/"RANDOM" mirror
    the reference's Location -> (builder, latency) mapping (:84-90)."""
    from ..core.registries import AWS, CITIES, RANDOM, builder_name

    dead_ratio = 0.10 if dead_ratio is None else dead_ratio
    dead = int(nodes * dead_ratio)
    threshold = int(nodes * (1.0 - dead_ratio) * 0.99)
    threshold = max(2, min(threshold, nodes - dead))
    if loc is None:
        nb_name = builder_name(RANDOM, True, tor or 0.0)
        lat_name = None
    else:
        # the reference builds RegistryNodeBuilders.name(loc, false, tor)
        nb_name = builder_name(loc, False, tor or 0.0)
        lat_name = {
            AWS: "AwsRegionNetworkLatency",
            CITIES: "NetworkLatencyByCityWJitter",
            RANDOM: "NetworkLatencyByDistanceWJitter",
        }[loc]
    kw = {} if window_initial is None else {"window_initial": window_initial}
    return HandelParameters(
        node_count=nodes,
        threshold=threshold,
        pairing_time=4,
        level_wait_time=50 if level_wait_time is None else level_wait_time,
        extra_cycle=10 if extra_cycle is None else extra_cycle,
        dissemination_period_ms=20 if period_time is None else period_time,
        fast_path=10 if fast_path is None else fast_path,
        nodes_down=dead,
        node_builder_name=nb_name,
        network_latency_name=lat_name,
        desynchronized_start=desynchronized_start or 0,
        byzantine_suicide=byzantine_suicide,
        hidden_byzantine=hidden_byzantine,
        **kw,
    )

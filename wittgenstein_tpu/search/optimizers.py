"""Batched black-box optimizers over a GenomeSpec box.

All three share one contract shaped for the engine's free population
evaluator: `ask()` returns the WHOLE generation as an [λ, n_genes]
array, the driver evaluates it in ONE `run_fault_sweep` call, and
`tell(pop, scores)` (higher = better) advances the optimizer.  Row
geometry is the run cache's compile key, so `ask()` always returns the
same number of rows × `replicas_per_plan(base)` replicas — random
search and the ES keep λ fixed, successive halving shrinks the
candidate count and grows replicas by the same power of two.

Everything is host-side numpy and DETERMINISTIC given the seed: the
PCG64 stream is part of `state_meta()`, selection ties break by stable
sort order, and the best-so-far updates on strict improvement only —
so checkpoint/restore (driver.py) reproduces a bitwise-identical
champion, which is what makes kill-and-resume and regression pinning
claims testable.
"""

from __future__ import annotations

import json
from typing import Dict, Optional, Tuple

import numpy as np

from .genome import GenomeSpec


class SearchOptimizer:
    """Common ask/tell + checkpoint surface (see module docstring)."""

    kind = "base"

    def __init__(self, spec: GenomeSpec, population: int, seed: int = 0):
        if population < 2:
            raise ValueError(f"population={population} must be >= 2")
        self.spec = spec
        self.population = int(population)
        self.seed = int(seed)
        self._rng = np.random.Generator(np.random.PCG64(self.seed))
        self.generation = 0
        self.best_vec: Optional[np.ndarray] = None
        self.best_score = -np.inf

    # -- the ask/tell contract ----------------------------------------------
    def ask(self) -> np.ndarray:
        raise NotImplementedError

    def tell(self, pop: np.ndarray, scores: np.ndarray) -> None:
        """Book the generation: strict-improvement champion update +
        subclass-specific adaptation via _adapt."""
        pop = np.asarray(pop, np.float64)
        scores = np.asarray(scores, np.float64)
        if pop.shape[0] != scores.shape[0]:
            raise ValueError(
                f"{pop.shape[0]} genomes but {scores.shape[0]} scores"
            )
        j = int(np.argmax(scores))  # first index on ties: deterministic
        if scores[j] > self.best_score:
            self.best_score = float(scores[j])
            self.best_vec = pop[j].copy()
        self._adapt(pop, scores)
        self.generation += 1

    def _adapt(self, pop: np.ndarray, scores: np.ndarray) -> None:
        pass

    def replicas_per_plan(self, base: int) -> int:
        """Replica rows per candidate this generation (SHA grows it as
        the candidate count halves, keeping row geometry constant)."""
        return int(base)

    # -- checkpoint surface (driver.py persists these) -----------------------
    def state_arrays(self) -> Dict[str, np.ndarray]:
        """Fixed-structure numpy pytree (CheckpointManager template)."""
        n = self.spec.n_genes
        return {
            "best_vec": (
                np.zeros(n) if self.best_vec is None else self.best_vec.copy()
            ),
        }

    def state_meta(self) -> dict:
        """JSON side-car: everything state_arrays can't hold."""
        return {
            "kind": self.kind,
            "generation": self.generation,
            "best_score": (
                None if self.best_vec is None else self.best_score
            ),
            "rng": json.loads(json.dumps(self._rng.bit_generator.state)),
        }

    def load_state(self, arrays: Dict[str, np.ndarray], meta: dict) -> None:
        if meta["kind"] != self.kind:
            raise ValueError(
                f"checkpoint is a {meta['kind']!r} optimizer, this is "
                f"{self.kind!r}"
            )
        self.generation = int(meta["generation"])
        if meta["best_score"] is None:
            self.best_vec, self.best_score = None, -np.inf
        else:
            self.best_score = float(meta["best_score"])
            self.best_vec = np.asarray(arrays["best_vec"], np.float64).copy()
        self._rng.bit_generator.state = meta["rng"]


class RandomSearch(SearchOptimizer):
    """Seeded uniform sampling of the box — the coverage baseline every
    structured optimizer must beat, and the diversity engine for short
    CI searches (a fresh λ-sample per generation never collapses)."""

    kind = "random"

    def ask(self) -> np.ndarray:
        return self.spec.random(self._rng, self.population)


class EvolutionStrategy(SearchOptimizer):
    """(μ,λ) evolution strategy with diagonal covariance (CMA-lite):
    log-weighted recombination of the top μ, per-dimension step sizes
    re-estimated from the selected parents' spread and blended with the
    carried sigma (no evolution paths — the genome is ~15-dimensional
    and the budget is a handful of generations)."""

    kind = "es"

    def __init__(self, spec: GenomeSpec, population: int, seed: int = 0,
                 mu: Optional[int] = None, sigma0_frac: float = 0.25,
                 sigma_blend: float = 0.3):
        super().__init__(spec, population, seed)
        self.mu = int(mu) if mu is not None else max(2, self.population // 2)
        if not 2 <= self.mu <= self.population:
            raise ValueError(
                f"mu={self.mu} outside [2, population={self.population}]"
            )
        w = np.log(self.mu + 0.5) - np.log(np.arange(1, self.mu + 1))
        self._weights = w / w.sum()
        self._sigma_blend = float(sigma_blend)
        self._sigma_floor = spec.width() * 1e-3
        self.mean = spec.center()
        self.sigma = spec.width() * float(sigma0_frac)

    def ask(self) -> np.ndarray:
        z = self._rng.standard_normal((self.population, self.spec.n_genes))
        return self.spec.clip(self.mean + z * self.sigma)

    def _adapt(self, pop: np.ndarray, scores: np.ndarray) -> None:
        order = np.argsort(-scores, kind="stable")[: self.mu]
        parents = pop[order]
        old_mean = self.mean
        self.mean = self._weights @ parents
        spread = np.sqrt(
            self._weights @ (parents - old_mean) ** 2
        )
        self.sigma = np.maximum(
            (1.0 - self._sigma_blend) * self.sigma
            + self._sigma_blend * spread,
            self._sigma_floor,
        )

    def state_arrays(self) -> Dict[str, np.ndarray]:
        return {
            **super().state_arrays(),
            "mean": self.mean.copy(),
            "sigma": self.sigma.copy(),
        }

    def load_state(self, arrays, meta) -> None:
        super().load_state(arrays, meta)
        self.mean = np.asarray(arrays["mean"], np.float64).copy()
        self.sigma = np.asarray(arrays["sigma"], np.float64).copy()


class SuccessiveHalving(SearchOptimizer):
    """Successive-halving bandit: rung 0 screens λ fresh candidates at
    `base` replicas each; each rung keeps the top half and doubles the
    replicas per survivor, so every rung is the SAME row count (and the
    same compiled program).  After `rungs` rungs the ladder restarts
    with a fresh sample.  `population` must be a power of two ≥ 4."""

    kind = "sha"

    def __init__(self, spec: GenomeSpec, population: int, seed: int = 0,
                 rungs: Optional[int] = None):
        super().__init__(spec, population, seed)
        if self.population < 4 or self.population & (self.population - 1):
            raise ValueError(
                f"population={self.population} must be a power of two >= 4"
            )
        max_rungs = int(np.log2(self.population)) + 1
        self.rungs = min(int(rungs), max_rungs) if rungs else max_rungs - 1
        if self.rungs < 2:
            raise ValueError(f"rungs={self.rungs} must be >= 2")
        self.rung = 0
        self._candidates = self.spec.random(self._rng, self.population)

    def _n_this_rung(self) -> int:
        return self.population >> self.rung

    def replicas_per_plan(self, base: int) -> int:
        return int(base) << self.rung

    def ask(self) -> np.ndarray:
        return self._candidates.copy()

    def _adapt(self, pop: np.ndarray, scores: np.ndarray) -> None:
        keep = max(2, pop.shape[0] // 2)
        order = np.argsort(-scores, kind="stable")[:keep]
        self.rung += 1
        if self.rung >= self.rungs:
            # ladder exhausted: restart with a fresh screening sample
            self.rung = 0
            self._candidates = self.spec.random(self._rng, self.population)
        else:
            self._candidates = pop[np.sort(order)].copy()

    def state_arrays(self) -> Dict[str, np.ndarray]:
        # fixed geometry: pad the surviving candidates back to [λ, n]
        cand = np.zeros((self.population, self.spec.n_genes))
        cand[: len(self._candidates)] = self._candidates
        return {**super().state_arrays(), "candidates": cand}

    def state_meta(self) -> dict:
        return {
            **super().state_meta(),
            "rung": self.rung,
            "n_candidates": len(self._candidates),
        }

    def load_state(self, arrays, meta) -> None:
        super().load_state(arrays, meta)
        self.rung = int(meta["rung"])
        self._candidates = np.asarray(
            arrays["candidates"], np.float64
        )[: int(meta["n_candidates"])].copy()


_KINDS = {
    "random": RandomSearch,
    "es": EvolutionStrategy,
    "sha": SuccessiveHalving,
}


def make_optimizer(kind: str, spec: GenomeSpec, population: int,
                   seed: int = 0, **kw) -> SearchOptimizer:
    try:
        cls = _KINDS[kind]
    except KeyError:
        raise KeyError(
            f"unknown optimizer {kind!r} (known: "
            + ", ".join(sorted(_KINDS)) + ")"
        ) from None
    return cls(spec, population, seed=seed, **kw)

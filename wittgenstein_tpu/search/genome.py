"""Bounded genome over the FaultPlan space.

A genome is a flat float vector, one entry per `GeneSpec`, each bounded
to `[lo, hi]`; integer genes carry real values in the vector and round
at DECODE time, so every optimizer works in one continuous box and the
decoded plan is a pure function of the stored vector (the bitwise-
replay property regression pinning relies on).  `FaultGenome` is the
standard encoding: crash window (which block of live nodes, when, how
long), partition window (minority-group size and timing), per-send drop
rate, latency inflation, and a Byzantine silence mask with its window —
every lane the fault engine exposes.  Lanes whose genes decode to
neutral values (zero crash fraction, drop_pm 0, multiplier 1000 with
add 0 ...) are simply omitted from the built plan, so the genome space
contains the fault-free schedule and every single-lane attack as
corners.

Module-import discipline: numpy only — `to_plan`/`digest` import the
faults package (and transitively JAX) lazily, so simlint's fast pass
can bounds-check pinned genomes without a JAX runtime.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np


@dataclasses.dataclass(frozen=True)
class GeneSpec:
    """One bounded gene.  `integer` genes round at decode time."""

    name: str
    lo: float
    hi: float
    integer: bool = False

    def __post_init__(self):
        if not self.lo < self.hi:
            raise ValueError(
                f"gene {self.name!r}: lo={self.lo} must be < hi={self.hi}"
            )

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "lo": self.lo,
            "hi": self.hi,
            "integer": self.integer,
        }

    @classmethod
    def from_json(cls, doc: dict) -> "GeneSpec":
        return cls(
            str(doc["name"]),
            float(doc["lo"]),
            float(doc["hi"]),
            bool(doc.get("integer", False)),
        )


class GenomeSpec:
    """An ordered, named box of genes: the optimizer's search domain."""

    def __init__(self, genes: Sequence[GeneSpec]):
        if not genes:
            raise ValueError("GenomeSpec needs at least one gene")
        names = [g.name for g in genes]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate gene names in {names}")
        self.genes: List[GeneSpec] = list(genes)
        self.names: List[str] = names
        self.lo = np.array([g.lo for g in genes], np.float64)
        self.hi = np.array([g.hi for g in genes], np.float64)

    @property
    def n_genes(self) -> int:
        return len(self.genes)

    def clip(self, vec) -> np.ndarray:
        return np.clip(np.asarray(vec, np.float64), self.lo, self.hi)

    def validate(self, vec) -> np.ndarray:
        """The strict twin of clip(): shape/finiteness/bounds or raise.
        Used on vectors that claim to already be genomes (pinned
        regression files), where silent clipping would mask drift."""
        v = np.asarray(vec, np.float64)
        if v.shape != (self.n_genes,):
            raise ValueError(
                f"genome shape {v.shape} != ({self.n_genes},) for genes "
                f"{self.names}"
            )
        if not np.all(np.isfinite(v)):
            raise ValueError(f"genome has non-finite entries: {v.tolist()}")
        bad = (v < self.lo) | (v > self.hi)
        if np.any(bad):
            culprits = [
                f"{self.names[i]}={v[i]} outside [{self.lo[i]},{self.hi[i]}]"
                for i in np.flatnonzero(bad)
            ]
            raise ValueError("genome out of bounds: " + "; ".join(culprits))
        return v

    def decode(self, vec) -> Dict[str, float]:
        """Named view of a validated vector; integer genes round half
        away from zero bias-free (np.rint) and clamp back into bounds."""
        v = self.validate(vec)
        out: Dict[str, float] = {}
        for i, g in enumerate(self.genes):
            x = float(v[i])
            if g.integer:
                x = int(min(max(np.rint(x), g.lo), g.hi))
            out[g.name] = x
        return out

    def random(self, rng: np.random.Generator, n: int = 1) -> np.ndarray:
        """n uniform samples from the box, shape [n, n_genes]."""
        return rng.uniform(self.lo, self.hi, size=(int(n), self.n_genes))

    def center(self) -> np.ndarray:
        return (self.lo + self.hi) / 2.0

    def width(self) -> np.ndarray:
        return self.hi - self.lo

    def to_json(self) -> list:
        return [g.to_json() for g in self.genes]

    @classmethod
    def from_json(cls, doc: list) -> "GenomeSpec":
        return cls([GeneSpec.from_json(g) for g in doc])


class FaultGenome:
    """The standard FaultPlan encoding for an `n_nodes` population over
    a `sim_ms` horizon.  `live` (bool mask or None = all) fixes which
    nodes the crash/silence fractions index into — it must match the
    built state's `~down` for the decoded plan to mean what the search
    saw, which is why regression replays rebuild it from the registry
    factory's state rather than storing node lists."""

    def __init__(self, sim_ms: int, n_nodes: int, live=None):
        sim_ms = int(sim_ms)
        if sim_ms < 2:
            raise ValueError(f"sim_ms={sim_ms} too short for a window")
        self.sim_ms = sim_ms
        self.n_nodes = int(n_nodes)
        self.live = (
            np.ones(self.n_nodes, bool)
            if live is None
            else np.asarray(live, bool).copy()
        )
        if self.live.shape != (self.n_nodes,):
            raise ValueError(
                f"live mask shape {self.live.shape} != ({self.n_nodes},)"
            )
        self._live_ids = np.flatnonzero(self.live)
        t_hi = float(sim_ms - 1)
        self.spec = GenomeSpec(
            [
                # crash lane: a contiguous block of live nodes, placed by
                # crash_off, for [crash_at, crash_at + crash_dur)
                GeneSpec("crash_frac", 0.0, 0.45),
                GeneSpec("crash_off", 0.0, 1.0),
                GeneSpec("crash_at", 0.0, t_hi, integer=True),
                GeneSpec("crash_dur", 1.0, float(sim_ms), integer=True),
                # partition lane: minority group of part_frac * n nodes
                GeneSpec("part_frac", 0.0, 0.5),
                GeneSpec("part_start", 0.0, t_hi, integer=True),
                GeneSpec("part_dur", 1.0, float(sim_ms), integer=True),
                # probabilistic drop lane (all mtypes)
                GeneSpec("drop_pm", 0.0, 1000.0, integer=True),
                GeneSpec("drop_start", 0.0, t_hi, integer=True),
                GeneSpec("drop_dur", 1.0, float(sim_ms), integer=True),
                # latency inflation lane (whole horizon when active)
                GeneSpec("infl_pm", 1000.0, 5000.0, integer=True),
                GeneSpec("infl_add", 0.0, 60.0, integer=True),
                # Byzantine silence lane: a block of live nodes from the
                # TOP of the live list (disjoint from small crash blocks)
                GeneSpec("silence_frac", 0.0, 0.3),
                GeneSpec("byz_start", 0.0, t_hi, integer=True),
                GeneSpec("byz_dur", 1.0, float(sim_ms), integer=True),
            ]
        )

    # -- node-set selections (pure functions of the decoded genome) ----------
    def _crash_nodes(self, g: Dict[str, float]) -> np.ndarray:
        ids = self._live_ids
        k = int(round(g["crash_frac"] * len(ids)))
        if k <= 0:
            return np.empty(0, np.int64)
        start = int(round(g["crash_off"] * (len(ids) - k))) if k < len(ids) else 0
        return ids[start : start + k]

    def _silence_nodes(self, g: Dict[str, float]) -> np.ndarray:
        ids = self._live_ids
        k = int(round(g["silence_frac"] * len(ids)))
        return ids[len(ids) - k :] if k > 0 else np.empty(0, np.int64)

    def to_plan(self, vec, label: str = "genome"):
        """Decode + build the FaultPlan (lazy faults import; see module
        note).  Neutral lanes are omitted, so a mid-box genome exercises
        every lane and a corner genome reduces to a single fault."""
        from ..faults.plan import FaultPlan

        g = self.spec.decode(vec)
        end = lambda start, dur: min(int(start) + int(dur), self.sim_ms)
        plan = FaultPlan(label)
        crash = self._crash_nodes(g)
        if crash.size:
            plan.crash(crash, at=g["crash_at"],
                       recover=end(g["crash_at"], g["crash_dur"]))
        k_part = int(round(g["part_frac"] * self.n_nodes))
        if 0 < k_part < self.n_nodes:
            groups = (np.arange(self.n_nodes) < k_part).astype(np.int32)
            plan.partition(groups, start=g["part_start"],
                           end=end(g["part_start"], g["part_dur"]))
        if g["drop_pm"] > 0:
            plan.drop(g["drop_pm"], start=g["drop_start"],
                      end=end(g["drop_start"], g["drop_dur"]))
        if g["infl_pm"] > 1000 or g["infl_add"] > 0:
            plan.inflate(g["infl_pm"], add_ms=g["infl_add"], start=0)
        silent = self._silence_nodes(g)
        if silent.size:
            plan.silence(silent, start=g["byz_start"],
                         end=end(g["byz_start"], g["byz_dur"]))
        return plan

    def digest(self, vec, n_msg_types: int) -> str:
        """Lowered-plan digest of the decoded genome — the identity a
        pinned regression stores and a replay re-derives."""
        from ..faults.plan import plan_digest

        return plan_digest(
            self.to_plan(vec), self.n_nodes, n_msg_types
        )

    def describe(self, vec) -> dict:
        """JSON-friendly decoded view (reports, regression files)."""
        g = self.spec.decode(vec)
        return {
            **g,
            "crash_nodes": int(self._crash_nodes(g).size),
            "silence_nodes": int(self._silence_nodes(g).size),
        }

"""Attacker objectives over existing result surfaces.

Every objective maps ONE `run_fault_sweep` record (availability, done-at
quantiles, fault counters — scenarios/sweep.py) plus the sweep horizon
to a scalar where HIGHER = stronger attack; optimizers maximize.  The
env-policy path (protocols/handel_env.py rollouts) reuses the same
registry through records shaped `{"reward_ratio": x}` — miner revenue
for the ethpow BatchedMinerEnv, final undone fraction for the Handel
attacker.  The registry is the namespace simlint SL1401 audits pinned
regression files against, so it stays importable without JAX.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Sequence, Tuple

import numpy as np


def _p90_or_horizon(record: dict, sim_ms: int) -> float:
    q = record.get("done_at_ms")
    return float(q["p90"]) if q else float(sim_ms)


def _done_at(record: dict, sim_ms: int) -> float:
    # the canonical latency-damage score: p90 completion time with the
    # undone fraction censored at the horizon — monotone in BOTH axes
    # the north-star cares about (later completion, lower availability),
    # so "strictly beats the static sweep" means strictly more damage
    return (
        (1.0 - float(record["availability"])) * float(sim_ms)
        + _p90_or_horizon(record, sim_ms)
    )


def _unavailability(record: dict, sim_ms: int) -> float:
    return 1.0 - float(record["availability"])


def _done_at_max(record: dict, sim_ms: int) -> float:
    q = record.get("done_at_ms")
    return float(q["max"]) if q else float(sim_ms)


def _dropped_total(record: dict, sim_ms: int) -> float:
    return float(sum(record["dropped_by_fault"]))


def _delayed_total(record: dict, sim_ms: int) -> float:
    return float(sum(record["delayed_by_fault"]))


def _reward_ratio(record: dict, sim_ms: int) -> float:
    # env-policy records (miner revenue share / attacker rollout reward)
    return float(record["reward_ratio"])


@dataclasses.dataclass(frozen=True)
class Objective:
    """name -> scalar score(record, sim_ms); higher = stronger attack."""

    name: str
    doc: str
    fn: Callable[[dict, int], float]

    def __call__(self, record: dict, sim_ms: int) -> float:
        return self.fn(record, sim_ms)


OBJECTIVES: Dict[str, Objective] = {
    o.name: o
    for o in (
        Objective(
            "done_at",
            "p90 done-at ms with undone nodes censored at the horizon "
            "(latency damage; the CI-gated default)",
            _done_at,
        ),
        Objective(
            "unavailability",
            "fraction of statically-live nodes NOT done by the deadline",
            _unavailability,
        ),
        Objective(
            "done_at_max",
            "slowest completed node's done-at ms (horizon when none)",
            _done_at_max,
        ),
        Objective(
            "dropped_total",
            "messages the fault lanes dropped (drop + partition)",
            _dropped_total,
        ),
        Objective(
            "delayed_total",
            "messages the fault lanes delayed (inflate + Byzantine)",
            _delayed_total,
        ),
        Objective(
            "reward_ratio",
            "adversary reward share from an env-policy rollout (miner "
            "revenue for ethpow, undone fraction for the Handel attacker)",
            _reward_ratio,
        ),
    )
}


def get_objective(name: str) -> Objective:
    try:
        return OBJECTIVES[name]
    except KeyError:
        raise KeyError(
            f"unknown objective {name!r} (known: "
            + ", ".join(sorted(OBJECTIVES)) + ")"
        ) from None


def score_records(
    records: Sequence[dict], objective: str, sim_ms: int
) -> np.ndarray:
    """One score per sweep record, as float64 (optimizer input)."""
    obj = get_objective(objective)
    return np.array([obj(r, sim_ms) for r in records], np.float64)


def pareto_frontier(
    points: Sequence[Tuple[float, float]],
    maximize: Tuple[bool, bool] = (True, True),
) -> List[int]:
    """Indices of the non-dominated points, in input order (ties kept:
    a point equal to a frontier member on both axes is on the
    frontier).  Used for the availability-vs-latency report: attacker
    view is maximize (unavailability, done-at), one frontier entry per
    distinct trade-off the search discovered."""
    pts = np.asarray(points, np.float64)
    if pts.ndim != 2 or pts.shape[1] != 2:
        raise ValueError(f"points must be [n,2], got {pts.shape}")
    sign = np.array([1.0 if m else -1.0 for m in maximize])
    v = pts * sign  # now maximize both
    keep = []
    for i in range(len(v)):
        dominated = np.any(
            np.all(v >= v[i], axis=1) & np.any(v > v[i], axis=1)
        )
        if not dominated:
            keep.append(i)
    return keep

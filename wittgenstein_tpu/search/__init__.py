"""Adversary search: batched black-box optimization over FaultPlan space.

The fault engine made per-replica schedules DATA (faults/state.py), and
`scenarios.sweep.run_fault_sweep` already evaluates a heterogeneous list
of FaultPlans in one `run_ms_batched` program — a free population
evaluator.  This package closes the loop: a bounded genome lowers to a
FaultPlan (genome.py), per-protocol scalar objectives read the sweep
records (objectives.py), and batched optimizers — seeded random search,
a (μ,λ) diagonal-covariance ES, a successive-halving bandit — spend one
`run_fault_sweep` call per generation (optimizers.py, driver.py), so a
whole search campaign costs ONE compile after warm-up.  Discovered
attacks are pinned as replayable regression scenarios
(`scenarios/regressions/*.json`, audited by simlint SL1401).  See
docs/search.md.

Import discipline: genome/objectives/optimizers are numpy-only at
module import (simlint's fast pass loads them without JAX); anything
that lowers plans or runs the engine imports lazily.
"""

from .driver import (
    SEARCH_COUNTERS,
    SearchConfig,
    SearchDriver,
    baseline_scores,
    optimize_env_policy,
    search_metrics,
    static_baseline_plans,
)
from .genome import FaultGenome, GeneSpec, GenomeSpec
from .objectives import (
    OBJECTIVES,
    Objective,
    get_objective,
    pareto_frontier,
    score_records,
)
from .optimizers import (
    EvolutionStrategy,
    RandomSearch,
    SuccessiveHalving,
    make_optimizer,
)

__all__ = [
    "EvolutionStrategy",
    "FaultGenome",
    "GeneSpec",
    "GenomeSpec",
    "OBJECTIVES",
    "Objective",
    "RandomSearch",
    "SEARCH_COUNTERS",
    "SearchConfig",
    "SearchDriver",
    "SuccessiveHalving",
    "baseline_scores",
    "get_objective",
    "make_optimizer",
    "optimize_env_policy",
    "pareto_frontier",
    "score_records",
    "search_metrics",
    "static_baseline_plans",
]

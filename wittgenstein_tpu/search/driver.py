"""Resumable search driver: optimizer generations over run_fault_sweep.

One generation = ONE `run_fault_sweep(use_run_cache=True)` call: the
optimizer's whole population lowers to FaultState rows of a single
cached compiled program, so generation 2..G are pure run-cache hits
(`run_cache_info()["compiles"]` is the witness — the contract
tests/test_search.py and scripts/adversary_smoke.py counter-assert).

Durability rides the engine's checkpoint discipline: after every
`tell`, the optimizer state (arrays via CheckpointManager's atomic
numbered .npz, scalars/RNG/history in its meta side-car) lands under
`config.checkpoint_dir`; a SIGKILLed search re-invoked with the same
config resumes at the next generation and reaches a bitwise-identical
champion, because every seed is a pure function of (config.seed,
generation) and the optimizer stream is part of the checkpoint.

Per-generation flight-recorder events (`search-generation`, plus
resume/complete/pinned) and monotonic `witt_search_*` counters
(SEARCH_COUNTERS, exported by the control server's /metrics) make a
campaign observable the same way serve/supervisor runs are.

Champions pin through `scenarios.regressions` as witt-regression/v1
JSON — genome, lowered-plan digest, seed, objective value, and the
static-baseline scores they strictly beat — replayed bitwise by
`scenarios.regressions.verify_regression` in tests and CI.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import time
from typing import List, Optional

import numpy as np

from .genome import FaultGenome, GeneSpec, GenomeSpec
from .objectives import get_objective, pareto_frontier, score_records
from .optimizers import make_optimizer

# monotonic per-process counters -> witt_search_* metric families
# (server/server.py renders them best-effort, like witt_run_cache_*)
SEARCH_COUNTERS = {
    "generations_total": 0,
    "evals_total": 0,
    "eval_seconds_total": 0.0,
    "pinned_total": 0,
    "best_objective": 0.0,  # gauge: last champion objective seen
}


def search_metrics() -> dict:
    return dict(SEARCH_COUNTERS)


@dataclasses.dataclass
class SearchConfig:
    """One search campaign.  `protocol` must be a
    core.registries.registry_batched_protocols name — the registry
    factory is how a regression replay rebuilds the exact (net, state)
    the campaign attacked."""

    protocol: str
    objective: str = "done_at"
    sim_ms: int = 1000
    generations: int = 3
    population: int = 8
    replicas_per_plan: int = 1
    seed: int = 0
    optimizer: str = "es"
    checkpoint_dir: Optional[str] = None
    label: str = "search"

    def digest(self) -> str:
        """Identity of the campaign (resume guard): a checkpoint from a
        different config must not silently seed this one."""
        doc = dataclasses.asdict(self)
        doc.pop("checkpoint_dir")  # the directory is where, not what
        return hashlib.blake2b(
            json.dumps(doc, sort_keys=True).encode(), digest_size=8
        ).hexdigest()


def static_baseline_plans(net, state) -> list:
    """The static 5-plan sweep (control + four single-lane faults) every
    discovered champion must strictly beat — one canonical definition
    shared by scripts/fault_sweep.py, the regression verifier, and the
    adversary smoke."""
    from ..faults import FaultPlan

    n = net.n_nodes
    live = np.flatnonzero(~np.asarray(state.down))
    crash_ids = live[len(live) // 4 :][: max(1, len(live) // 5)]  # 20% of live
    groups = np.arange(n) % 2
    return [
        None,  # fault-free control row
        FaultPlan("crash20@200").crash(crash_ids, at=200),
        FaultPlan("split@100-600").partition(groups, start=100, end=600),
        FaultPlan("drop30%").drop(300, start=0),
        FaultPlan("slow3x").inflate(3000, add_ms=20, start=0),
    ]


class SearchDriver:
    """ask -> one batched sweep -> tell, resumably (module docstring)."""

    def __init__(self, config: SearchConfig, net=None, state=None,
                 recorder=None):
        self.config = config
        if net is None or state is None:
            from ..core.registries import registry_batched_protocols

            net, state = registry_batched_protocols.get(
                config.protocol
            ).factory()
        self.net, self.state = net, state
        self.genome = FaultGenome(
            config.sim_ms, net.n_nodes, live=~np.asarray(state.down)
        )
        self.objective = get_objective(config.objective)
        self.opt = make_optimizer(
            config.optimizer, self.genome.spec, config.population,
            seed=config.seed,
        )
        if recorder is None:
            from ..obs.recorder import get_recorder

            recorder = get_recorder()
        self.recorder = recorder
        self.history: List[dict] = []  # one row per completed generation
        self.points: List[dict] = []   # every evaluated candidate
        self.champion: Optional[dict] = None
        self._ckpt = None
        if config.checkpoint_dir:
            from ..engine.checkpoint import CheckpointManager

            self._ckpt = CheckpointManager(config.checkpoint_dir)
            self._maybe_resume()

    # -- durability ----------------------------------------------------------
    @property
    def generation(self) -> int:
        return self.opt.generation

    @staticmethod
    def _pack(arrays: dict) -> dict:
        """float64 optimizer arrays as raw-byte uint8 views: the engine's
        checkpoint restore round-trips leaves through jax (float32 under
        the default no-x64 config), and a champion genome that loses
        low bits can decode to a DIFFERENT plan — so ship bytes, which
        every dtype config preserves exactly."""
        return {
            k: np.ascontiguousarray(v, np.float64).view(np.uint8)
            for k, v in arrays.items()
        }

    @staticmethod
    def _unpack(arrays: dict) -> dict:
        return {
            k: np.ascontiguousarray(np.asarray(v, np.uint8)).view(np.float64)
            for k, v in arrays.items()
        }

    def _checkpoint(self) -> None:
        if self._ckpt is None:
            return
        meta = {
            "config_digest": self.config.digest(),
            "opt": self.opt.state_meta(),
            "history": self.history,
            "points": self.points,
            "champion": self.champion,
        }
        self._ckpt.save(
            self._pack(self.opt.state_arrays()), self.generation, meta=meta
        )
        self.recorder.record(
            "checkpoint", search=self.config.label, gen=self.generation
        )

    def _maybe_resume(self) -> None:
        got = self._ckpt.restore_latest(self._pack(self.opt.state_arrays()))
        if got is None:
            return
        arrays, step, manifest = got
        meta = (manifest or {}).get("meta") or {}
        if meta.get("config_digest") != self.config.digest():
            raise ValueError(
                f"checkpoint in {self.config.checkpoint_dir} belongs to a "
                "different search config — refusing to resume from it"
            )
        self.opt.load_state(self._unpack(arrays), meta["opt"])
        self.history = list(meta["history"])
        self.points = list(meta["points"])
        self.champion = meta["champion"]
        self.recorder.record(
            "search-resume", search=self.config.label, gen=self.generation
        )

    # -- one generation = one compile-cached sweep ---------------------------
    def _gen_seed0(self, gen: int) -> int:
        # disjoint seed blocks per generation, pure in (config, gen)
        rows = self.config.population * self.config.replicas_per_plan
        return self.config.seed + 1 + gen * rows

    def run_generation(self) -> dict:
        from ..scenarios.sweep import run_fault_sweep

        cfg = self.config
        gen = self.generation
        pop = self.opt.ask()
        rpp = self.opt.replicas_per_plan(cfg.replicas_per_plan)
        plans = [
            self.genome.to_plan(vec, label=f"{cfg.label}-g{gen}c{j}")
            for j, vec in enumerate(pop)
        ]
        seed0 = self._gen_seed0(gen)
        t0 = time.perf_counter()
        _, records = run_fault_sweep(
            self.net, self.state, plans, cfg.sim_ms,
            replicas_per_plan=rpp, seed0=seed0, use_run_cache=True,
        )
        eval_s = time.perf_counter() - t0
        scores = score_records(records, cfg.objective, cfg.sim_ms)
        self.opt.tell(pop, scores)

        j_best = int(np.argmax(scores))
        if self.champion is None or scores[j_best] > self.champion["score"]:
            rec = records[j_best]
            self.champion = {
                "score": float(scores[j_best]),
                "vec": [float(x) for x in pop[j_best]],
                "plan_digest": rec["plan_digest"],
                "seed0": rec["seed0_row"],
                "replicas_per_plan": rpp,
                "availability": rec["availability"],
                "generation": gen,
                "record": rec,
            }
            SEARCH_COUNTERS["best_objective"] = self.champion["score"]
        for j, rec in enumerate(records):
            self.points.append(
                {
                    "gen": gen,
                    "unavailability": round(1.0 - rec["availability"], 4),
                    "done_p90": (
                        rec["done_at_ms"]["p90"]
                        if rec["done_at_ms"]
                        else cfg.sim_ms
                    ),
                    "score": float(scores[j]),
                    "plan_digest": rec["plan_digest"],
                }
            )
        row = {
            "gen": gen,
            "evals": len(plans),
            "replicas_per_plan": rpp,
            "eval_s": round(eval_s, 4),
            "best_gen_score": float(scores[j_best]),
            "champion_score": self.champion["score"],
        }
        self.history.append(row)
        SEARCH_COUNTERS["generations_total"] += 1
        SEARCH_COUNTERS["evals_total"] += len(plans) * rpp
        SEARCH_COUNTERS["eval_seconds_total"] += eval_s
        self.recorder.record(
            "search-generation", search=cfg.label, **row
        )
        self._checkpoint()
        return row

    def run(self) -> dict:
        while self.generation < self.config.generations:
            self.run_generation()
        report = self.report()
        self.recorder.record(
            "search-complete",
            search=self.config.label,
            generations=self.generation,
            champion_score=self.champion["score"] if self.champion else None,
        )
        return report

    # -- outputs -------------------------------------------------------------
    def frontier(self) -> List[dict]:
        """Availability-vs-latency Pareto frontier over every evaluated
        candidate (attacker view: maximize unavailability AND done-at
        p90), deduped by plan digest."""
        if not self.points:
            return []
        seen, pts = set(), []
        for p in self.points:
            if p["plan_digest"] not in seen:
                seen.add(p["plan_digest"])
                pts.append(p)
        keep = pareto_frontier(
            [(p["unavailability"], p["done_p90"]) for p in pts]
        )
        front = [pts[i] for i in keep]
        front.sort(key=lambda p: (-p["unavailability"], -p["done_p90"]))
        return front

    def report(self) -> dict:
        return {
            "schema": "witt-search-report/v1",
            "config": dataclasses.asdict(self.config),
            "config_digest": self.config.digest(),
            "champion": self.champion,
            "frontier": self.frontier(),
            "history": self.history,
            "metrics": search_metrics(),
        }

    def pin_champion(self, path: str, with_baseline: bool = True) -> dict:
        """Pin the champion as a replayable witt-regression/v1 file (see
        scenarios.regressions); returns the written document."""
        from ..scenarios.regressions import pin_regression

        if self.champion is None:
            raise RuntimeError("no champion yet — run at least one generation")
        doc = pin_regression(self, path, with_baseline=with_baseline)
        SEARCH_COUNTERS["pinned_total"] += 1
        self.recorder.record(
            "search-pinned", search=self.config.label, path=path,
            plan_digest=doc["plan_digest"],
        )
        return doc


def baseline_scores(net, state, sim_ms: int, objective: str,
                    seed0: int = 0) -> dict:
    """Objective score of every static baseline plan (label -> score),
    evaluated at replicas_per_plan=1 — the bar a champion must clear."""
    from ..scenarios.sweep import run_fault_sweep

    plans = static_baseline_plans(net, state)
    _, records = run_fault_sweep(net, state, plans, sim_ms, seed0=seed0)
    scores = score_records(records, objective, sim_ms)
    return {
        rec["plan"]["label"]: float(s) for rec, s in zip(records, scores)
    }


def optimize_env_policy(env, generations: int = 3, seed: int = 0,
                        optimizer: str = "es", objective: str = "reward_ratio",
                        recorder=None):
    """Drive the SAME optimizers against an in-protocol adversary
    policy: each replica of a vectorized attack env (protocols/
    handel_env.BatchedAttackEnv, or the ethpow BatchedMinerEnv wrapped
    the same way) rolls out ONE candidate's attack window, so a whole
    generation is a single batched rollout.  The policy genome is the
    (start, duration) of the Byzantine window; actions at each decision
    step are 1 inside the candidate's window.  Returns the optimizer
    (best_vec/best_score are the discovered policy).  `optimizer` must
    keep the population fixed ('random'/'es' — SHA varies candidate
    count, which an R-replica env cannot fan out)."""
    if optimizer == "sha":
        raise ValueError(
            "optimize_env_policy needs a fixed population per rollout; "
            "sha varies the candidate count"
        )
    horizon = int(env.horizon_ms)
    spec = GenomeSpec(
        [
            GeneSpec("attack_start", 0.0, float(horizon - 1), integer=True),
            GeneSpec("attack_dur", 1.0, float(horizon), integer=True),
        ]
    )
    opt = make_optimizer(optimizer, spec, env.n_replicas, seed=seed)
    obj = get_objective(objective)
    if recorder is None:
        from ..obs.recorder import get_recorder

        recorder = get_recorder()
    n_steps = horizon // env.decision_ms
    for _ in range(generations):
        pop = opt.ask()
        windows = np.stack(
            [
                [spec.decode(v)["attack_start"] for v in pop],
                [
                    spec.decode(v)["attack_start"] + spec.decode(v)["attack_dur"]
                    for v in pop
                ],
            ],
            axis=1,
        )
        env.reset()
        reward = np.zeros(env.n_replicas)
        t = 0
        for _step in range(n_steps):
            active = (windows[:, 0] <= t) & (t < windows[:, 1])
            _obs, reward, _info = env.step(active.astype(np.int32))
            t += env.decision_ms
        scores = np.array(
            [obj({"reward_ratio": float(r)}, horizon) for r in reward]
        )
        opt.tell(pop, scores)
        recorder.record(
            "search-generation", search="env-policy", gen=opt.generation - 1,
            evals=len(pop), best_gen_score=float(scores.max()),
            champion_score=float(opt.best_score),
        )
        SEARCH_COUNTERS["generations_total"] += 1
        SEARCH_COUNTERS["evals_total"] += len(pop)
    return opt

"""Typed parameter objects + protocol registry.

The reference uses `WParameters` value-objects (JSON-polymorphic) and a
reflection-scanned protocol registry for its REST server
(reference: core WParameters.java:11, wserver Server.java:37-103).  Here the
same contract is explicit: protocols register themselves under a name, their
parameter dataclass must be default-constructible (that is what lets the API
layer discover default parameters), and parameters round-trip through JSON
with a `type` tag.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Callable, Dict, Type


@dataclasses.dataclass
class WParameters:
    """Base class for protocol parameters.  Subclasses are dataclasses with
    defaults for every field (default-constructible contract)."""

    def to_json(self) -> str:
        d = {"type": type(self).__name__}
        d.update(dataclasses.asdict(self))
        return json.dumps(d)

    @classmethod
    def from_json(cls, s: str) -> "WParameters":
        d = json.loads(s)
        return cls.from_dict(d)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "WParameters":
        d = dict(d)
        typ = d.pop("type", None)
        klass = cls
        if typ is not None and typ != cls.__name__:
            klass = _params_types.get(typ)
            if klass is None:
                raise KeyError(f"unknown parameters type {typ!r}")
        fields = {f.name for f in dataclasses.fields(klass) if f.init}
        return klass(**{k: v for k, v in d.items() if k in fields})

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        _params_types[cls.__name__] = cls

    def __str__(self) -> str:  # reflective toString parity (Strings.java:7-23)
        inner = ", ".join(
            f"{f.name}={getattr(self, f.name)}" for f in dataclasses.fields(self)
        )
        return f"{type(self).__name__}{{{inner}}}"


_params_types: Dict[str, Type[WParameters]] = {}

# ---------------------------------------------------------------------------
# Protocol registry: name -> (protocol factory, parameters class).
# The factory takes a single parameters instance, mirroring the reference
# contract "public constructor taking WParameters" (Protocol.java:9-22).
# ---------------------------------------------------------------------------

protocol_registry: Dict[str, "RegisteredProtocol"] = {}


@dataclasses.dataclass(frozen=True)
class RegisteredProtocol:
    name: str
    factory: Callable[[WParameters], Any]
    params_cls: Type[WParameters]

    def default_params(self) -> WParameters:
        return self.params_cls()


def register_protocol(name: str, params_cls: Type[WParameters]):
    """Class decorator: @register_protocol("Handel", HandelParameters)."""

    def deco(klass):
        protocol_registry[name] = RegisteredProtocol(name, klass, params_cls)
        klass.protocol_name = name
        return klass

    return deco

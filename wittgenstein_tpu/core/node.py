"""Node identity & per-node state + node builders.

Reference semantics: core Node.java (identity, position, aspects, traffic
counters) and NodeBuilder.java (id allocation, SHA-256 hash, random or
city-weighted positions).  The oracle engine uses these objects directly;
the batched engine converts a built node population into struct-of-arrays
columns via `build_node_columns`.
"""

from __future__ import annotations

import hashlib
import math
from typing import Dict, List, Optional

import numpy as np

from ..utils.gpd import GeneralizedParetoDistribution
from ..utils.javaops import i32, java_abs, java_mod, lshift32
from ..utils.javarand import JavaRandom
from .geo import DEFAULT_CITY, MAX_X, MAX_Y, CityInfo, Geo

MAX_DIST = int(math.sqrt((MAX_X / 2.0) ** 2 + (MAX_Y / 2.0) ** 2))


# ---------------------------------------------------------------------------
# Aspects: optional per-node attribute samplers (Node.java:145-244)
# ---------------------------------------------------------------------------


class Aspect:
    def get_value(self, rd: JavaRandom):
        return None


class ExtraLatencyAspect(Aspect):
    """Tor-style extra latency: 500 ms with probability `ratio`."""

    def __init__(self, ratio: float):
        self.ratio = ratio

    def get_value(self, rd: JavaRandom):
        return 500 if rd.next_double() < self.ratio else 0


class SpeedRatioAspect(Aspect):
    def __init__(self, speed_model: "SpeedModel"):
        self.sm = speed_model

    def get_value(self, rd: JavaRandom):
        return self.sm.get_speed_ratio(rd)


class SpeedModel:
    def get_speed_ratio(self, rd: JavaRandom) -> float:
        raise NotImplementedError


class ParetoSpeed(SpeedModel):
    def __init__(self, shape: float, location: float, scale: float, max_: float):
        self.gpd = GeneralizedParetoDistribution(shape, location, scale)
        self.max = max_

    def get_speed_ratio(self, rd: JavaRandom) -> float:
        return min(self.max, 1.0 + self.gpd.inverse_f(rd.next_double()))


class GaussianSpeed(SpeedModel):
    def get_speed_ratio(self, rd: JavaRandom) -> float:
        return max(0.33, rd.next_gaussian() + 1)


class UniformSpeed(SpeedModel):
    """Uniform from 3x faster to 3x slower (Node.java:233-244)."""

    def get_speed_ratio(self, rd: JavaRandom) -> float:
        if rd.next_boolean():
            return (rd.next_int(67) + 33) / 100.0
        return (rd.next_int(200) + 100) / 100.0


def _aspect_value(aspect_cls, aspects: List[Aspect], rd: JavaRandom, default):
    for a in aspects:
        if type(a) is aspect_cls:
            return a.get_value(rd)
    return default


# ---------------------------------------------------------------------------
# Node
# ---------------------------------------------------------------------------


class Node:
    MAX_X = MAX_X
    MAX_Y = MAX_Y
    MAX_DIST = MAX_DIST
    DEFAULT_CITY = DEFAULT_CITY

    __slots__ = (
        "node_id",
        "hash256",
        "x",
        "y",
        "extra_latency",
        "byzantine",
        "speed_ratio",
        "city_name",
        "_down",
        "done_at",
        "msg_received",
        "msg_sent",
        "bytes_sent",
        "bytes_received",
        "_builder",
        "external",
    )

    def __init__(self, rd: JavaRandom, nb: "NodeBuilder", byzantine: bool = False):
        self.node_id = nb.allocate_node_id()
        if self.node_id < 0:
            raise ValueError(f"bad nodeId: {self.node_id}")
        rd_node = rd.next_int()
        self.city_name = nb.get_city_name(rd_node)
        self.x = nb.get_x(rd_node)
        self.y = nb.get_y(rd_node)
        if not (0 < self.x <= MAX_X):
            raise ValueError(f"bad x={self.x}")
        if not (0 < self.y <= MAX_Y):
            raise ValueError(f"bad y={self.y}")
        self.byzantine = byzantine
        self.hash256 = nb.get_hash(self.node_id)
        # aspect sampling order matters for RNG-stream parity (Node.java:265-266)
        self.speed_ratio = float(
            _aspect_value(SpeedRatioAspect, nb.aspects, rd, 1.0)
        )
        self.extra_latency = int(
            _aspect_value(ExtraLatencyAspect, nb.aspects, rd, 0)
        )
        if self.speed_ratio <= 0:
            raise ValueError(f"speedRatio={self.speed_ratio}")
        self._down = False
        self.done_at = 0
        self.msg_received = 0
        self.msg_sent = 0
        self.bytes_sent = 0
        self.bytes_received = 0
        self._builder = nb
        self.external = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._down = False

    def stop(self) -> None:
        self._down = True

    def is_down(self) -> bool:
        return self._down

    def generate_new_unique_int_id(self) -> int:
        return self._builder.next_unique_int_id()

    def dist(self, other: "Node") -> int:
        """Toroidal map distance (Node.java:278-282)."""
        dx = min(abs(self.x - other.x), MAX_X - abs(self.x - other.x))
        dy = min(abs(self.y - other.y), MAX_Y - abs(self.y - other.y))
        return int(math.sqrt(dx * dx + dy * dy))

    def __repr__(self) -> str:
        return f"Node{{nodeId={self.node_id}}}"

    def __eq__(self, other) -> bool:
        return isinstance(other, Node) and other.node_id == self.node_id

    def __hash__(self) -> int:
        return self.node_id


# ---------------------------------------------------------------------------
# Builders (NodeBuilder.java)
# ---------------------------------------------------------------------------


class NodeBuilder:
    def __init__(self):
        self._node_ids = 0
        self._uint_id = 0
        self.aspects: List[Aspect] = []

    def copy(self) -> "NodeBuilder":
        """Same builder with node ids reset (NodeBuilder.java:42-52); aspects
        and the unique-int counter are shared, like the Java shallow clone."""
        import copy as _copy

        nb = _copy.copy(self)
        nb._node_ids = 0
        return nb

    def allocate_node_id(self) -> int:
        nid = self._node_ids
        self._node_ids += 1
        return nid

    def next_unique_int_id(self) -> int:
        self._uint_id += 1
        return self._uint_id

    def get_x(self, rd_int: int) -> int:
        return 1

    def get_y(self, rd_int: int) -> int:
        return 1

    def get_city_name(self, rd_int: int) -> str:
        return DEFAULT_CITY

    def get_hash(self, node_id: int) -> bytes:
        return hashlib.sha256(node_id.to_bytes(4, "big", signed=True)).digest()


class NodeBuilderWithRandomPosition(NodeBuilder):
    """Position from the high/low 16 bits of one random int
    (NodeBuilder.java:77-96, including the int32 overflow on the y path)."""

    def get_x(self, rd_int: int) -> int:
        r = abs(rd_int >> 16)  # arithmetic shift, then abs as 64-bit
        return r % MAX_X + 1

    def get_y(self, rd_int: int) -> int:
        r = abs(lshift32(rd_int, 16))
        return r % MAX_Y + 1


class NodeBuilderWithCity(NodeBuilder):
    """Weighted-random city selection (NodeBuilder.java:98-148)."""

    def __init__(self, cities: List[str], geo: Geo):
        super().__init__()
        self.cities = [c.upper() for c in cities]
        wanted = set(self.cities)
        self.cities_info: Dict[str, CityInfo] = {
            k: v for k, v in geo.cities_position().items() if k.upper() in wanted
        }

    def get_city_name(self, rd_int: int) -> str:
        name = self._random_city(rd_int)
        if name is None:
            raise ValueError("no city matched")
        return name

    def _random_city(self, rd_int: int) -> Optional[str]:
        size = len(self.cities)
        rand = java_mod(java_abs(i32(rd_int)), size)
        p = rand / size
        for name, info in self.cities_info.items():
            if p <= info.cumulative_probability:
                return name
        return None

    def _pos(self, rd_int: int):
        info = self.cities_info[self.get_city_name(rd_int)]
        return info.merc_x, info.merc_y

    def get_x(self, rd_int: int) -> int:
        return self._pos(rd_int)[0]

    def get_y(self, rd_int: int) -> int:
        return self._pos(rd_int)[1]


# ---------------------------------------------------------------------------
# SoA conversion for the batched engine
# ---------------------------------------------------------------------------


def build_node_columns(nodes: List[Node], city_index: Dict[str, int] | None = None):
    """Convert built Node objects into the static struct-of-arrays columns the
    batched engine consumes.  city_index maps cityName -> int for city-matrix
    latency models (absent cities map to -1)."""
    n = len(nodes)
    cols = {
        "x": np.array([nd.x for nd in nodes], dtype=np.int32),
        "y": np.array([nd.y for nd in nodes], dtype=np.int32),
        "extra_latency": np.array([nd.extra_latency for nd in nodes], dtype=np.int32),
        "speed_ratio": np.array([nd.speed_ratio for nd in nodes], dtype=np.float32),
        "byzantine": np.array([nd.byzantine for nd in nodes], dtype=bool),
        "city_idx": np.full(n, -1, dtype=np.int32),
    }
    if city_index:
        for idx, nd in enumerate(nodes):
            cols["city_idx"][idx] = city_index.get(nd.city_name, -1)
    return cols

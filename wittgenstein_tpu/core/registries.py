"""Name-keyed registries for latency models and node builders.

Reference semantics: core RegistryNetworkLatencies.java (FIXED/UNIFORM
pre-registered at 0..8000 + by-class-name fallback) and
RegistryNodeBuilders.java (the 54-entry {AWS, CITIES, RANDOM} x
{CONSTANT, GAUSSIAN speed} x tor-ratio cross-product).  The reflection
fallback becomes an explicit class map.
"""

from __future__ import annotations

from typing import Dict, Optional

from . import latency as L
from .geo import GeoAllCities, GeoAWS
from .node import (
    ExtraLatencyAspect,
    NodeBuilder,
    NodeBuilderWithCity,
    NodeBuilderWithRandomPosition,
    SpeedRatioAspect,
    UniformSpeed,
)

# ---------------------------------------------------------------------------
# Latency registry
# ---------------------------------------------------------------------------

_LATENCY_CLASSES = {
    "NetworkLatencyByDistanceWJitter": L.NetworkLatencyByDistanceWJitter,
    "AwsRegionNetworkLatency": L.AwsRegionNetworkLatency,
    "NetworkLatencyByCity": L.NetworkLatencyByCity,
    "NetworkLatencyByCityWJitter": L.NetworkLatencyByCityWJitter,
    "NetworkNoLatency": L.NetworkNoLatency,
    "EthScanNetworkLatency": L.EthScanNetworkLatency,
    "IC3NetworkLatency": L.IC3NetworkLatency,
}


class RegistryNetworkLatencies:
    FIXED = "FIXED"
    UNIFORM = "UNIFORM"

    def __init__(self):
        self._registry: Dict[str, L.NetworkLatency] = {}
        for f in (0, 100, 200, 500, 1000, 2000, 4000, 8000):
            self._registry[self.name(self.FIXED, f)] = L.NetworkFixedLatency(f)
            self._registry[self.name(self.UNIFORM, f)] = L.NetworkUniformLatency(f)

    @staticmethod
    def name(type_: str, fixed: int) -> str:
        if type_ == RegistryNetworkLatencies.FIXED:
            return f"NetworkFixedLatency({fixed})"
        if type_ == RegistryNetworkLatencies.UNIFORM:
            return f"NetworkUniformLatency({fixed})"
        raise ValueError(type_)

    def get_by_name(self, name: Optional[str]) -> L.NetworkLatency:
        if name is None:
            name = "NetworkLatencyByDistanceWJitter"
        nl = self._registry.get(name)
        if nl is not None:
            return nl
        cls = _LATENCY_CLASSES.get(name)
        if cls is None:
            raise ValueError(f"unknown latency model {name!r}")
        return cls()


registry_network_latencies = RegistryNetworkLatencies()

# ---------------------------------------------------------------------------
# Node-builder registry
# ---------------------------------------------------------------------------

AWS = "AWS"
CITIES = "CITIES"
RANDOM = "RANDOM"

TOR_RATIOS = (0.0, 0.01, 0.10, 0.20, 0.33, 0.5, 0.6, 0.8, 1.0)
LOCATIONS = (AWS, CITIES, RANDOM)


def builder_name(location: str, speed_constant: bool, tor: float) -> str:
    """Exact name format of RegistryNodeBuilders.name (note: the non-constant
    speed model is UniformSpeed but the name says GAUSSIAN, matching the
    reference's quirk at RegistryNodeBuilders.java:24-27)."""
    speed = "CONSTANT" if speed_constant else "GAUSSIAN"
    tor_s = (_java_double_str(tor) + "000")[:4]
    return f"{location}_speed={speed}_tor={tor_s}".upper()


def _java_double_str(d: float) -> str:
    s = repr(float(d))
    return s


class RegistryNodeBuilders:
    def __init__(self):
        self._specs = {}
        for loc in LOCATIONS:
            for speed_constant in (True, False):
                for tor in TOR_RATIOS:
                    self._specs[builder_name(loc, speed_constant, tor)] = (
                        loc,
                        speed_constant,
                        tor,
                    )
        self._cache: Dict[str, NodeBuilder] = {}

    def names(self):
        return list(self._specs.keys())

    def get_by_name(self, name: Optional[str]) -> NodeBuilder:
        if name is None or not name.strip():
            name = builder_name(RANDOM, True, 0.0)
        if name not in self._specs:
            raise ValueError(f"{name} not in the registry")
        if name not in self._cache:
            self._cache[name] = self._build(*self._specs[name])
        return self._cache[name].copy()

    @staticmethod
    def _build(loc: str, speed_constant: bool, tor: float) -> NodeBuilder:
        if loc == AWS:
            nb = NodeBuilderWithCity(L.AwsRegionNetworkLatency.cities(), GeoAWS())
        elif loc == CITIES:
            from ..tools.latency_csv import CSVLatencyReader

            nb = NodeBuilderWithCity(CSVLatencyReader().cities(), GeoAllCities())
        elif loc == RANDOM:
            nb = NodeBuilderWithRandomPosition()
        else:
            raise ValueError(loc)
        if not speed_constant:
            nb.aspects.append(SpeedRatioAspect(UniformSpeed()))
        if tor > 0.001:
            nb.aspects.append(ExtraLatencyAspect(tor))
        return nb


registry_node_builders = RegistryNodeBuilders()

"""Name-keyed registries for latency models and node builders.

Reference semantics: core RegistryNetworkLatencies.java (FIXED/UNIFORM
pre-registered at 0..8000 + by-class-name fallback) and
RegistryNodeBuilders.java (the 54-entry {AWS, CITIES, RANDOM} x
{CONSTANT, GAUSSIAN speed} x tor-ratio cross-product).  The reflection
fallback becomes an explicit class map.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import latency as L
from .geo import GeoAllCities, GeoAWS
from .node import (
    ExtraLatencyAspect,
    NodeBuilder,
    NodeBuilderWithCity,
    NodeBuilderWithRandomPosition,
    SpeedRatioAspect,
    UniformSpeed,
)

# ---------------------------------------------------------------------------
# Latency registry
# ---------------------------------------------------------------------------

_LATENCY_CLASSES = {
    "NetworkLatencyByDistanceWJitter": L.NetworkLatencyByDistanceWJitter,
    "AwsRegionNetworkLatency": L.AwsRegionNetworkLatency,
    "NetworkLatencyByCity": L.NetworkLatencyByCity,
    "NetworkLatencyByCityWJitter": L.NetworkLatencyByCityWJitter,
    "NetworkNoLatency": L.NetworkNoLatency,
    "EthScanNetworkLatency": L.EthScanNetworkLatency,
    "IC3NetworkLatency": L.IC3NetworkLatency,
}


class RegistryNetworkLatencies:
    FIXED = "FIXED"
    UNIFORM = "UNIFORM"

    def __init__(self):
        self._registry: Dict[str, L.NetworkLatency] = {}
        for f in (0, 100, 200, 500, 1000, 2000, 4000, 8000):
            self._registry[self.name(self.FIXED, f)] = L.NetworkFixedLatency(f)
            self._registry[self.name(self.UNIFORM, f)] = L.NetworkUniformLatency(f)

    @staticmethod
    def name(type_: str, fixed: int) -> str:
        if type_ == RegistryNetworkLatencies.FIXED:
            return f"NetworkFixedLatency({fixed})"
        if type_ == RegistryNetworkLatencies.UNIFORM:
            return f"NetworkUniformLatency({fixed})"
        raise ValueError(type_)

    def get_by_name(self, name: Optional[str]) -> L.NetworkLatency:
        if name is None:
            name = "NetworkLatencyByDistanceWJitter"
        nl = self._registry.get(name)
        if nl is not None:
            return nl
        cls = _LATENCY_CLASSES.get(name)
        if cls is None:
            raise ValueError(f"unknown latency model {name!r}")
        return cls()


registry_network_latencies = RegistryNetworkLatencies()

# ---------------------------------------------------------------------------
# Node-builder registry
# ---------------------------------------------------------------------------

AWS = "AWS"
CITIES = "CITIES"
RANDOM = "RANDOM"

TOR_RATIOS = (0.0, 0.01, 0.10, 0.20, 0.33, 0.5, 0.6, 0.8, 1.0)
LOCATIONS = (AWS, CITIES, RANDOM)


def builder_name(location: str, speed_constant: bool, tor: float) -> str:
    """Exact name format of RegistryNodeBuilders.name (note: the non-constant
    speed model is UniformSpeed but the name says GAUSSIAN, matching the
    reference's quirk at RegistryNodeBuilders.java:24-27)."""
    speed = "CONSTANT" if speed_constant else "GAUSSIAN"
    tor_s = (_java_double_str(tor) + "000")[:4]
    return f"{location}_speed={speed}_tor={tor_s}".upper()


def _java_double_str(d: float) -> str:
    s = repr(float(d))
    return s


class RegistryNodeBuilders:
    def __init__(self):
        self._specs = {}
        for loc in LOCATIONS:
            for speed_constant in (True, False):
                for tor in TOR_RATIOS:
                    self._specs[builder_name(loc, speed_constant, tor)] = (
                        loc,
                        speed_constant,
                        tor,
                    )
        self._cache: Dict[str, NodeBuilder] = {}

    def names(self):
        return list(self._specs.keys())

    def get_by_name(self, name: Optional[str]) -> NodeBuilder:
        if name is None or not name.strip():
            name = builder_name(RANDOM, True, 0.0)
        if name not in self._specs:
            raise ValueError(f"{name} not in the registry")
        if name not in self._cache:
            self._cache[name] = self._build(*self._specs[name])
        return self._cache[name].copy()

    @staticmethod
    def _build(loc: str, speed_constant: bool, tor: float) -> NodeBuilder:
        if loc == AWS:
            nb = NodeBuilderWithCity(L.AwsRegionNetworkLatency.cities(), GeoAWS())
        elif loc == CITIES:
            from ..tools.latency_csv import CSVLatencyReader

            nb = NodeBuilderWithCity(CSVLatencyReader().cities(), GeoAllCities())
        elif loc == RANDOM:
            nb = NodeBuilderWithRandomPosition()
        else:
            raise ValueError(loc)
        if not speed_constant:
            nb.aspects.append(SpeedRatioAspect(UniformSpeed()))
        if tor > 0.001:
            nb.aspects.append(ExtraLatencyAspect(tor))
        return nb


registry_node_builders = RegistryNodeBuilders()

# ---------------------------------------------------------------------------
# Batched-protocol registry (enumeration hook for tooling)
# ---------------------------------------------------------------------------
# Every `protocols/*_batched.py` implementation registers here with a
# SMALL-SCALE factory returning the usual `(net, state)` pair.  The point is
# enumeration, not construction convenience: the static checker
# (wittgenstein_tpu.analysis) iterates these entries to run its
# abstract-eval contract passes over EVERY protocol, and its SL301
# meta-rule fails CI when a new `*_batched.py` lands without an entry.
# Factories import lazily (inside the call) so this module stays cheap to
# import and free of protocol->core->protocol cycles.


@dataclasses.dataclass(frozen=True)
class BatchedProtocolEntry:
    """One registered batched protocol.

    name            registry key (stable id used in reports);
    module          module path under wittgenstein_tpu.protocols;
    factory         () -> (net, state) at a small analysis-friendly scale
                    (mirrors each protocol's standard-scenario test config);
    contract_checks False for implementations that are not BatchedProtocol
                    kernels on the generic engine (their `note` says why) —
                    SL301 still counts them as covered, the abstract-eval
                    pass skips them loudly rather than silently.
    """

    name: str
    module: str
    factory: Callable[[], Tuple[Any, Any]]
    contract_checks: bool = True
    note: str = ""


class RegistryBatchedProtocols:
    def __init__(self):
        self._entries: Dict[str, BatchedProtocolEntry] = {}

    def register(self, entry: BatchedProtocolEntry) -> None:
        if entry.name in self._entries:
            raise ValueError(f"duplicate batched protocol {entry.name!r}")
        self._entries[entry.name] = entry

    def names(self) -> List[str]:
        return sorted(self._entries)

    def get(self, name: str) -> BatchedProtocolEntry:
        return self._entries[name]

    def entries(self) -> List[BatchedProtocolEntry]:
        return [self._entries[n] for n in self.names()]

    def modules(self) -> List[str]:
        return sorted({e.module for e in self._entries.values()})


registry_batched_protocols = RegistryBatchedProtocols()


def _reg(name, module, factory, **kw):
    registry_batched_protocols.register(
        BatchedProtocolEntry(name, module, factory, **kw)
    )


def _make_pingpong_small():
    from ..protocols.pingpong_batched import make_pingpong

    return make_pingpong(64)


def _make_p2pflood_small():
    from ..protocols.p2pflood import P2PFloodParameters
    from ..protocols.p2pflood_batched import make_p2pflood

    return make_p2pflood(P2PFloodParameters(), capacity=2048)


def _make_p2pflood_faults_small():
    # the fault-LANE contract entry: same protocol/scale as "p2pflood"
    # but with the fault engine armed and a non-neutral schedule, so
    # simlint traces deliver/step against a state that actually carries
    # fault leaves (SL402/SL407 on the plain entry would be vacuous —
    # zero fault leaves to check ownership of)
    from ..faults import FaultConfig, FaultPlan
    from ..protocols.p2pflood import P2PFloodParameters
    from ..protocols.p2pflood_batched import make_p2pflood

    net, state = make_p2pflood(P2PFloodParameters(), capacity=2048)
    plan = (
        FaultPlan("contract")
        .crash(range(20, 30), at=200, recover=900)
        .drop(100, start=100)
        .inflate(1500, add_ms=5, start=100, end=800)
    )
    return net.with_faults(state, FaultConfig(), plan)


def _make_paxos_small():
    from ..protocols.paxos import PaxosParameters
    from ..protocols.paxos_batched import make_paxos

    return make_paxos(PaxosParameters())


def _make_slush_small():
    from ..protocols.avalanche_batched import make_slush

    return make_slush()


def _make_snowflake_small():
    from ..protocols.avalanche_batched import make_snowflake

    return make_snowflake()


def _make_handel_small():
    from ..protocols.handel import HandelParameters
    from ..protocols.handel_batched import make_handel

    return make_handel(
        HandelParameters(
            node_count=64,
            threshold=int(64 * 0.99),
            pairing_time=3,
            level_wait_time=50,
            extra_cycle=10,
            dissemination_period_ms=10,
            fast_path=10,
            nodes_down=0,
        ),
        # pinned ON (the default is backend-auto): the registry entry is
        # what simlint's SL701 derived-cache audit steps, so the cache
        # path must be exercised on the CPU CI backend too
        score_cache=True,
    )


def _make_gsf_small():
    from ..protocols.gsf import GSFSignatureParameters
    from ..protocols.gsf_batched import make_gsf

    return make_gsf(
        GSFSignatureParameters(
            node_count=64,
            threshold=int(64 * 0.99),
            pairing_time=3,
            timeout_per_level_ms=50,
            period_duration_ms=10,
            accelerated_calls_count=10,
            nodes_down=0,
        )
    )


def _make_handeleth2_small():
    from ..protocols.handeleth2 import HandelEth2Parameters
    from ..protocols.handeleth2_batched import make_handeleth2

    return make_handeleth2(
        HandelEth2Parameters(
            node_count=32,
            pairing_time=3,
            level_wait_time=100,
            period_duration_ms=50,
            nodes_down=0,
        )
    )


def _make_optimistic_small():
    from ..protocols.optimistic_p2p_signature import (
        OptimisticP2PSignatureParameters,
    )
    from ..protocols.optimistic_p2p_signature_batched import make_optimistic

    return make_optimistic(
        OptimisticP2PSignatureParameters(
            node_count=64, threshold=56, connection_count=10, pairing_time=3
        )
    )


def _make_p2phandel_small():
    from ..protocols.p2phandel import P2PHandelParameters
    from ..protocols.p2phandel_batched import make_p2phandel

    # score_cache pinned for the same reason as the handel entry: SL701
    # steps this factory's output
    return make_p2phandel(P2PHandelParameters(), score_cache=True)


def _make_sanfermin_small():
    from ..protocols.sanfermin import SanFerminSignatureParameters
    from ..protocols.sanfermin_batched import make_sanfermin

    return make_sanfermin(
        SanFerminSignatureParameters(
            node_count=64,
            threshold=64,
            pairing_time=2,
            signature_size=48,
            reply_timeout=300,
            candidate_count=1,
            shuffled_lists=False,
        )
    )


def _make_sanfermin_cappos_small():
    from ..protocols.sanfermin_cappos import SanFerminParameters
    from ..protocols.sanfermin_cappos_batched import make_sanfermin_cappos

    return make_sanfermin_cappos(
        SanFerminParameters(
            node_count=64,
            threshold=32,
            pairing_time=2,
            signature_size=48,
            timeout=150,
            candidate_count=4,
        )
    )


def _make_dfinity_small():
    from ..protocols.dfinity import DfinityParameters
    from ..protocols.dfinity_batched import make_dfinity

    return make_dfinity(DfinityParameters(), max_heights=64)


def _make_casper_small():
    from ..protocols.casper import CasperParameters
    from ..protocols.casper_batched import make_casper

    return make_casper(CasperParameters(), max_heights=16)


def _make_enr_small():
    from ..protocols.enr_gossiping import ENRParameters
    from ..protocols.enr_batched import make_enr

    return make_enr(
        ENRParameters(
            nodes=24,
            total_peers=4,
            max_peers=10,
            number_of_different_capabilities=5,
            cap_per_node=2,
            cap_gossip_time=5_000,
            time_to_leave=50_000,
            time_to_change=10_000_000,
            changing_nodes=1,
            discard_time=100,
        ),
        horizon_ms=30_000,
        capacity=1024,
    )


def _make_ethpow_small():
    raise NotImplementedError(
        "ethpow_batched is a standalone mining engine (EthPowState), not a "
        "BatchedProtocol on the generic message store"
    )


_reg("pingpong", "pingpong_batched", _make_pingpong_small)
_reg("p2pflood", "p2pflood_batched", _make_p2pflood_small)
_reg(
    "p2pflood_faults",
    "p2pflood_batched",
    _make_p2pflood_faults_small,
    note="fault-injection lane (wittgenstein_tpu.faults) traced on the "
    "p2pflood kernels; exercises SL406/SL407 on a non-neutral schedule",
)
_reg("paxos", "paxos_batched", _make_paxos_small)
_reg("slush", "avalanche_batched", _make_slush_small)
_reg("snowflake", "avalanche_batched", _make_snowflake_small)
_reg("handel", "handel_batched", _make_handel_small)
_reg("gsf", "gsf_batched", _make_gsf_small)
_reg("handeleth2", "handeleth2_batched", _make_handeleth2_small)
_reg("optimistic", "optimistic_p2p_signature_batched", _make_optimistic_small)
_reg("p2phandel", "p2phandel_batched", _make_p2phandel_small)
_reg("sanfermin", "sanfermin_batched", _make_sanfermin_small)
_reg("sanfermin_cappos", "sanfermin_cappos_batched", _make_sanfermin_cappos_small)
_reg("dfinity", "dfinity_batched", _make_dfinity_small)
_reg("casper", "casper_batched", _make_casper_small)
_reg("enr", "enr_batched", _make_enr_small)
_reg(
    "ethpow",
    "ethpow_batched",
    _make_ethpow_small,
    contract_checks=False,
    note="standalone chain-mining engine (EthPowState pytree, no generic "
    "message store); covered by tests/test_ethpow_batched.py instead",
)

"""TCP throughput-aware delay (Mathis equation).

Reference semantics: core NetworkThroughput.java:17-57.  Closed-form, so the
vectorized twin is trivial.
"""

from __future__ import annotations

import math

from ..utils.javaops import jint
from .latency import NetworkLatency
from .node import Node


class NetworkThroughput:
    def delay(self, from_node: Node, to_node: Node, delta: int, msg_size: int, nl=None) -> int:
        """`nl` is the owning Network's latency model (Network.transit_ms
        always passes it); implementations should price off it when given."""
        raise NotImplementedError


class MathisNetworkThroughput(NetworkThroughput):
    MSS = 1460
    LOSS = 0.004

    def __init__(self, nl: NetworkLatency, window_size_bytes: int = 87380 * 1024):
        self.nl = nl
        self.window_size = 8 * window_size_bytes
        self._div = math.sqrt(self.LOSS)

    def delay(self, from_node: Node, to_node: Node, delta: int, msg_size: int, nl=None) -> int:
        """Size-dependent delay; `nl` (default: the constructor's model)
        lets the owning Network price off ITS latency model, so
        set_network_latency keeps working with a throughput installed."""
        st = (nl or self.nl).get_latency(from_node, to_node, delta)
        if msg_size < self.MSS:
            return st
        rtt = st * 2.0
        bandwidth = (self.MSS * 8) / (rtt * self._div)
        w_max = self.window_size / rtt
        av_bandwidth = min(bandwidth, w_max)
        return jint((8 * msg_size) / av_bandwidth + st)

    def vec_delay(self, static, from_idx, to_idx, delta, msg_size, nl=None):
        """Vectorized twin of delay() for the batched engine: closed-form
        Mathis throughput on top of the vectorized latency models.

        Precision: computed in float32 (jax x64 stays off), so results can
        differ from the float64 scalar path by at most 1 ms on large
        bandwidth-bound messages — covered by the parity test's +-1 bound.
        Distribution-level parity is unaffected."""
        import jax.numpy as jnp

        from .latency import vec_latency

        st = vec_latency(nl or self.nl, static, from_idx, to_idx, delta)
        stf = st.astype(jnp.float32)
        rtt = stf * 2.0
        bandwidth = (self.MSS * 8.0) / (rtt * self._div)
        w_max = self.window_size / rtt
        av_bandwidth = jnp.minimum(bandwidth, w_max)
        size = jnp.asarray(msg_size, jnp.float32)
        big = ((8.0 * size) / av_bandwidth + stf).astype(jnp.int32)
        return jnp.where(jnp.asarray(msg_size) < self.MSS, st, big)

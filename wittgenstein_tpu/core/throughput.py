"""TCP throughput-aware delay (Mathis equation).

Reference semantics: core NetworkThroughput.java:17-57.  Closed-form, so the
vectorized twin is trivial.
"""

from __future__ import annotations

import math

from ..utils.javaops import jint
from .latency import NetworkLatency
from .node import Node


class NetworkThroughput:
    def delay(self, from_node: Node, to_node: Node, delta: int, msg_size: int) -> int:
        raise NotImplementedError


class MathisNetworkThroughput(NetworkThroughput):
    MSS = 1460
    LOSS = 0.004

    def __init__(self, nl: NetworkLatency, window_size_bytes: int = 87380 * 1024):
        self.nl = nl
        self.window_size = 8 * window_size_bytes
        self._div = math.sqrt(self.LOSS)

    def delay(self, from_node: Node, to_node: Node, delta: int, msg_size: int) -> int:
        st = self.nl.get_latency(from_node, to_node, delta)
        if msg_size < self.MSS:
            return st
        rtt = st * 2.0
        bandwidth = (self.MSS * 8) / (rtt * self._div)
        w_max = self.window_size / rtt
        av_bandwidth = min(bandwidth, w_max)
        return jint((8 * msg_size) / av_bandwidth + st)

"""Stats framework.

Reference semantics: core utils/StatsHelper.java — Stat/SimpleStats
value objects, getStatsOn over node getters, StatsGetter plugin interface,
and field-by-field integer-average across runs (StatsHelper.avg uses Java
long division, kept exact here).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Sequence


class Stat:
    def fields(self) -> List[str]:
        raise NotImplementedError

    def get(self, field_name: str) -> int:
        raise NotImplementedError

    def create_from_value(self, vals: Dict[str, int]) -> "Stat":
        raise NotImplementedError


def avg(stats: Sequence[Stat]) -> Stat:
    """Field-by-field average, Java integer division (StatsHelper.java:31-54)."""
    if not stats:
        raise ValueError("no stats")
    if len(stats) == 1:
        return stats[0]
    vals: Dict[str, int] = {}
    for f in stats[0].fields():
        for s in stats:
            vals[f] = vals.get(f, 0) + s.get(f)
    n = len(stats)
    for f in vals:
        v = vals[f]
        # Java long division truncates toward zero
        vals[f] = -((-v) // n) if v < 0 else v // n
    return stats[0].create_from_value(vals)


class Counter(Stat):
    def __init__(self, val: int):
        self.count = int(val)

    def fields(self) -> List[str]:
        return ["count"]

    def get(self, field_name: str) -> int:
        return self.count

    def create_from_value(self, vals: Dict[str, int]) -> "Counter":
        return Counter(vals["count"])

    def __repr__(self) -> str:
        return f"Counter{{count={self.count}}}"


class SimpleStats(Stat):
    def __init__(self, min_: int, max_: int, avg_: int):
        self.min = int(min_)
        self.max = int(max_)
        self.avg = int(avg_)

    def fields(self) -> List[str]:
        return ["min", "max", "avg"]

    def get(self, field_name: str) -> int:
        return {"min": self.min, "max": self.max, "avg": self.avg}[field_name]

    def create_from_value(self, vals: Dict[str, int]) -> "SimpleStats":
        return SimpleStats(vals["min"], vals["max"], vals["avg"])

    def __repr__(self) -> str:
        return f"min: {self.min}, max:{self.max}, avg:{self.avg}"


def get_stats_on(nodes: Sequence, get: Callable) -> SimpleStats:
    """min/max/avg of a node getter (StatsHelper.java:127-140); avg is Java
    long division by node count."""
    mn = 2**63 - 1
    mx = -(2**63)
    tot = 0
    for n in nodes:
        v = get(n)
        tot += v
        mn = min(mn, v)
        mx = max(mx, v)
    a = tot // len(nodes) if tot >= 0 else -((-tot) // len(nodes))
    return SimpleStats(mn, mx, a)


def get_done_at(nodes) -> SimpleStats:
    return get_stats_on(nodes, lambda n: n.done_at)


def get_msg_received(nodes) -> SimpleStats:
    return get_stats_on(nodes, lambda n: n.msg_received)


class StatsGetter:
    def fields(self) -> List[str]:
        raise NotImplementedError

    def get(self, live_nodes) -> Stat:
        raise NotImplementedError


class SimpleStatsGetter(StatsGetter):
    def fields(self) -> List[str]:
        return ["min", "max", "avg"]


class DoneAtStatGetter(SimpleStatsGetter):
    def get(self, live_nodes) -> Stat:
        return get_done_at(live_nodes)


class MsgReceivedStatGetter(SimpleStatsGetter):
    def get(self, live_nodes) -> Stat:
        return get_msg_received(live_nodes)


class CounterStatsGetter(StatsGetter):
    """Counts live nodes matching a predicate (the anonymous StatsGetter
    pattern used in e.g. P2PFlood.floodTime)."""

    def __init__(self, pred: Callable):
        self._pred = pred

    def fields(self) -> List[str]:
        return ["count"]

    def get(self, live_nodes) -> Stat:
        return Counter(sum(1 for n in live_nodes if self._pred(n)))


# -- batched-engine adapters -------------------------------------------------
# The same Stat/StatsGetter shape over SoA columns and telemetry counters:
# sweep drivers and the /w/sweep endpoint reduce batched outputs with the
# identical field contract (min/max/avg, Java long division) the host-side
# getters expose, so downstream consumers never see two schemas.


def get_stats_on_array(values) -> SimpleStats:
    """min/max/avg of a value array (any shape), Java long division —
    the vectorized twin of get_stats_on."""
    import numpy as np

    v = np.asarray(values, dtype=np.int64).reshape(-1)
    if v.size == 0:
        raise ValueError("no values")
    tot = int(v.sum())
    a = tot // v.size if tot >= 0 else -((-tot) // v.size)
    return SimpleStats(int(v.min()), int(v.max()), a)


class BatchedStatsGetter(StatsGetter):
    """SimpleStats over a SimState node column, reduced across every
    (replica, node) pair with the node live.  `get` accepts either a
    batched SimState (leading replica axes collapse) or a plain array."""

    def __init__(self, column: str):
        self.column = column

    def fields(self) -> List[str]:
        return ["min", "max", "avg"]

    def get(self, state_or_values) -> Stat:
        import numpy as np

        if hasattr(state_or_values, self.column):
            state = state_or_values
            vals = np.asarray(getattr(state, self.column))
            live = ~np.asarray(state.down)
            return get_stats_on_array(vals[live])
        return get_stats_on_array(state_or_values)


class DoneAtBatchedStatGetter(BatchedStatsGetter):
    def __init__(self):
        super().__init__("done_at")


class MsgReceivedBatchedStatGetter(BatchedStatsGetter):
    def __init__(self):
        super().__init__("msg_received")


class TelemetryCounterStatGetter(StatsGetter):
    """Counter over an in-graph telemetry field (telemetry.TelemetryState
    on a state's `tele` side-car), summed over replicas and — unless a
    specific mtype index is given — over message types."""

    def __init__(self, field: str, mtype: "int | None" = None):
        self.field = field
        self.mtype = mtype

    def fields(self) -> List[str]:
        return ["count"]

    def get(self, state) -> Stat:
        import numpy as np

        tele = state.tele if hasattr(state, "tele") else state
        if tele == ():
            raise ValueError(
                "state has no telemetry side-car — build the engine with "
                "telemetry=TelemetryConfig(...)"
            )
        a = np.asarray(getattr(tele, self.field))
        if self.mtype is not None:
            a = a[..., self.mtype]
        return Counter(int(a.sum()))

from .params import WParameters, protocol_registry, register_protocol

__all__ = ["WParameters", "protocol_registry", "register_protocol"]

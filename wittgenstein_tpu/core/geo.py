"""Geographic data: city positions (Mercator-projected), population-weighted
city sampling.

Reference semantics: core geoinfo/Geo.java, GeoAWS.java, GeoAllCities.java,
CityInfo.java.  Data comes from the baked arrays in wittgenstein_tpu/data
(produced by tools/bake_data.py from the public wondernetwork/city CSVs) or,
if absent, parsed directly from a cities.csv file.
"""

from __future__ import annotations

import csv
import dataclasses
import math
import os
from typing import Dict, Tuple

import numpy as np

MAX_X = 2000
MAX_Y = 1112
MAX_DIST = int(math.sqrt((MAX_X / 2.0) ** 2 + (MAX_Y / 2.0) ** 2))
DEFAULT_CITY = "world"

_DATA_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "data")
_REFERENCE_RESOURCES = "/root/reference/core/src/main/resources"


@dataclasses.dataclass(frozen=True)
class CityInfo:
    merc_x: int
    merc_y: int
    cumulative_probability: float


class Geo:
    def cities_position(self) -> Dict[str, CityInfo]:
        raise NotImplementedError

    @staticmethod
    def city_info_map(
        cities: Dict[str, Tuple[int, int, int]], total_population: int
    ) -> Dict[str, CityInfo]:
        """cities: name -> (mercX, mercY, population).  Cumulative probability
        accumulates in iteration order (reference Geo.java:11-19; there the
        order is HashMap order — here it is the dict insertion order, which is
        deterministic; city sampling parity is distributional, not bitwise)."""
        cum = 0.0
        out: Dict[str, CityInfo] = {}
        for name, (x, y, pop) in cities.items():
            cum += pop * 1.0 / total_population
            out[name] = CityInfo(x, y, cum)
        return out


class GeoAWS(Geo):
    """Positions of the 11 AWS-region cities (reference GeoAWS.java:10-23)."""

    CITY_POS: Dict[str, Tuple[int, int, int]] = {
        "Oregon": (271, 261, 1),
        "Virginia": (513, 316, 1),
        "Mumbai": (1344, 426, 1),
        "Seoul": (1641, 312, 1),
        "Singapore": (1507, 532, 1),
        "Sydney": (1773, 777, 1),
        "Tokyo": (1708, 316, 1),
        "Canada central": (422, 256, 1),
        "Frankfurt": (985, 226, 1),
        "Ireland": (891, 200, 1),
        "London": (937, 205, 1),
    }

    def cities_position(self) -> Dict[str, CityInfo]:
        return self.city_info_map(self.CITY_POS, len(self.CITY_POS))


def mercator_x(longitude: float) -> int:
    """Reference GeoAllCities.convertToMercatorX (GeoAllCities.java:60-68)."""
    pos_x = int((longitude + 180) * (MAX_X / 360))
    if pos_x < MAX_X / 2:
        pos_x -= 45
    else:
        pos_x -= 70
    return pos_x


def mercator_y(latitude: float) -> int:
    """Reference GeoAllCities.convertToMercatorY (GeoAllCities.java:70-77)."""
    pos_y = int(math.floor((MAX_Y / 2) - (latitude * MAX_Y / 180) + 0.5))
    if pos_y < 0.2 * MAX_Y:
        pos_y -= 35
    return pos_y


class GeoAllCities(Geo):
    """All ~240 cities from cities.csv with population-weighted probability.

    Loads the baked npz when present, falling back to parsing a cities.csv
    (reference resource format: city,Lat,Long,Population; spaces in names
    become '+'; population gets +200000 — GeoAllCities.java:41-55)."""

    def __init__(self, csv_path: str | None = None):
        baked = os.path.join(_DATA_DIR, "geo_cities.npz")
        if csv_path is None and os.path.exists(baked):
            z = np.load(baked, allow_pickle=False)
            names = [str(s) for s in z["names"]]
            xs, ys, pops = z["merc_x"], z["merc_y"], z["population"]
            cities = {
                n: (int(x), int(y), int(p)) for n, x, y, p in zip(names, xs, ys, pops)
            }
        else:
            if csv_path is None:
                csv_path = os.path.join(_REFERENCE_RESOURCES, "cities.csv")
            cities = parse_cities_csv(csv_path)
        total = sum(v[2] for v in cities.values())
        self._positions = self.city_info_map(cities, total)

    def cities_position(self) -> Dict[str, CityInfo]:
        return dict(self._positions)


def parse_cities_csv(path: str) -> Dict[str, Tuple[int, int, int]]:
    cities: Dict[str, Tuple[int, int, int]] = {}
    with open(path, newline="", encoding="utf-8") as f:
        reader = csv.reader(f)
        next(reader)  # header
        for row in reader:
            if not row:
                continue
            name = row[0].replace(" ", "+")
            lat, lon = float(row[1]), float(row[2])
            population = int(row[3]) + 200000
            cities[name] = (mercator_x(lon), mercator_y(lat), population)
    return cities

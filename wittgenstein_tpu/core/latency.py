"""Network latency models.

Reference semantics: core NetworkLatency.java (9 models + measurement
helpers).  Every model exists in two forms:

  * scalar `get_latency(from_node, to_node, delta)` — bit-exact with the
    reference (Java int truncation / Math.round semantics), used by the
    oracle DES;
  * vectorized `ext_vec(static, from_idx, to_idx, delta)` — pure jnp,
    jittable, used inside the batched tick kernel.  `delta` is an int array
    in [0, 99]; the shared wrapper `vec_latency` adds extra-latency columns,
    the from==to short-circuit, and the max(1, ·) clamp
    (NetworkLatency.getLatency, NetworkLatency.java:27-34).

All randomness is externalized into `delta` (reference design: a 0..99
uniform), which maps directly onto counter-based RNG in the batched engine.
"""

from __future__ import annotations

import math
from typing import Dict, Optional

import numpy as np

from ..utils.gpd import GeneralizedParetoDistribution
from ..utils.javaops import java_int_div, jint, jround
from .geo import MAX_X, MAX_Y
from .node import MAX_DIST, Node

_WAN_GPD = GeneralizedParetoDistribution(1.4, -0.3, 0.35)
# delta only ever takes 100 values: precompute the jitter table once.
JITTER_TABLE = np.array([_WAN_GPD.inverse_f(d / 100.0) for d in range(100)])


class NetworkLatency:
    def get_extended_latency(self, from_node: Node, to_node: Node, delta: int) -> int:
        raise NotImplementedError

    def _check_delta(self, delta: int) -> None:
        if delta < 0 or delta > 99:
            raise ValueError(f"delta={delta}")

    def get_latency(self, from_node: Node, to_node: Node, delta: int) -> int:
        if from_node is to_node:
            return 1
        base = from_node.extra_latency + to_node.extra_latency
        base += self.get_extended_latency(from_node, to_node, delta)
        return max(1, base)

    # -- vectorized twin ---------------------------------------------------
    def ext_vec(self, static: "LatencyStatic", from_idx, to_idx, delta):
        """jnp latencies for index arrays; override per model."""
        raise NotImplementedError

    def __str__(self) -> str:
        return type(self).__name__


class LatencyStatic:
    """Static per-node columns the vectorized models read: positions,
    extra latency, city/region indices, plus any model tables."""

    def __init__(self, x, y, extra_latency, city_idx=None):
        import jax.numpy as jnp

        self.x = jnp.asarray(x, dtype=jnp.int32)
        self.y = jnp.asarray(y, dtype=jnp.int32)
        self.extra_latency = jnp.asarray(extra_latency, dtype=jnp.int32)
        self.city_idx = (
            None if city_idx is None else jnp.asarray(city_idx, dtype=jnp.int32)
        )

    @classmethod
    def from_columns(cls, cols: dict) -> "LatencyStatic":
        return cls(cols["x"], cols["y"], cols["extra_latency"], cols.get("city_idx"))


def vec_latency(model: NetworkLatency, static: LatencyStatic, from_idx, to_idx, delta):
    """Shared wrapper (getLatency semantics) around a model's ext_vec."""
    import jax.numpy as jnp

    ext = model.ext_vec(static, from_idx, to_idx, delta)
    extras = static.extra_latency[from_idx] + static.extra_latency[to_idx]
    lat = jnp.maximum(1, extras + ext)
    return jnp.where(from_idx == to_idx, 1, lat).astype(jnp.int32)


def _dist_vec(static: LatencyStatic, from_idx, to_idx):
    """Toroidal distance, int-truncated like Node.dist."""
    import jax.numpy as jnp

    dx = jnp.abs(static.x[from_idx] - static.x[to_idx])
    dx = jnp.minimum(dx, MAX_X - dx)
    dy = jnp.abs(static.y[from_idx] - static.y[to_idx])
    dy = jnp.minimum(dy, MAX_Y - dy)
    d2 = dx * dx + dy * dy
    # XLA's f32 sqrt can be 1 ulp off; snap to the exact integer sqrt so the
    # table lookups stay bit-exact with the scalar path.
    s = jnp.sqrt(d2.astype(jnp.float32)).astype(jnp.int32)
    s = jnp.where((s + 1) * (s + 1) <= d2, s + 1, s)
    s = jnp.where(s * s > d2, s - 1, s)
    return s


# ---------------------------------------------------------------------------
# 1. Distance + Generalized-Pareto jitter (the WAN default)
# ---------------------------------------------------------------------------


class NetworkLatencyByDistanceWJitter(NetworkLatency):
    """RTT = 0.022 * miles + 4.862 plus GPD(ξ=1.4, μ=-0.3, σ=0.35) jitter,
    halved for one-way (NetworkLatency.java:49-73)."""

    EARTH_PERIMETER = 24_860
    POINT_VALUE = (EARTH_PERIMETER / 2) / MAX_DIST

    def dist_to_mile(self, dist: int) -> float:
        return self.POINT_VALUE * dist

    def get_jitter(self, delta: int) -> float:
        return float(JITTER_TABLE[delta])

    def get_fixed_latency(self, dist: int) -> float:
        return self.dist_to_mile(dist) * 0.022 + 4.862

    def get_extended_latency(self, from_node: Node, to_node: Node, delta: int) -> int:
        self._check_delta(delta)
        raw = self.get_fixed_latency(from_node.dist(to_node)) + self.get_jitter(delta)
        return jint(raw / 2)

    # Exact-table trick: dist is an int <= MAX_DIST and delta < 100, so the
    # whole model is a [MAX_DIST+1, 100] int32 table computed in float64 on
    # the host.  The kernel is then a single gather — bit-exact with the
    # scalar path AND cheaper on TPU than transcendentals.
    _TABLE = None

    @classmethod
    def _table(cls) -> np.ndarray:
        if cls._TABLE is None:
            dists = np.arange(MAX_DIST + 1, dtype=np.float64)
            fixed = dists * (cls.POINT_VALUE * 0.022) + 4.862
            raw = fixed[:, None] + JITTER_TABLE[None, :]
            cls._TABLE = (raw / 2).astype(np.int32)  # trunc toward zero (>0)
        return cls._TABLE

    def ext_vec(self, static, from_idx, to_idx, delta):
        import jax.numpy as jnp

        table = jnp.asarray(self._table())
        dist = _dist_vec(static, from_idx, to_idx)
        return table[dist, delta]


# ---------------------------------------------------------------------------
# 2. AWS region ping matrix
# ---------------------------------------------------------------------------

AWS_REGION_PER_CITY: Dict[str, int] = {
    "Oregon": 0,
    "Virginia": 1,
    "Mumbai": 2,
    "Seoul": 3,
    "Singapore": 4,
    "Sydney": 5,
    "Tokyo": 6,
    "Canada central": 7,
    "Frankfurt": 8,
    "Ireland": 9,
    "London": 10,
}

# Upper-triangular ping matrix, ms RTT (NetworkLatency.java:112-128)
_AWS_PINGS = np.array(
    [
        [0, 81, 216, 126, 165, 138, 97, 64, 164, 131, 141],
        [0, 0, 182, 181, 232, 195, 167, 13, 88, 80, 75],
        [0, 0, 0, 152, 62, 223, 123, 194, 111, 122, 113],
        [0, 0, 0, 0, 97, 133, 35, 184, 259, 254, 264],
        [0, 0, 0, 0, 0, 169, 69, 218, 162, 174, 171],
        [0, 0, 0, 0, 0, 0, 105, 210, 282, 269, 271],
        [0, 0, 0, 0, 0, 0, 0, 156, 235, 222, 234],
        [0, 0, 0, 0, 0, 0, 0, 0, 101, 78, 87],
        [0, 0, 0, 0, 0, 0, 0, 0, 0, 24, 13],
        [0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 12],
        [0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0],
    ],
    dtype=np.int32,
)


def _aws_oneway_matrix() -> np.ndarray:
    """Symmetric one-way base matrix: ping/2, diagonal 0 (same-region handled
    separately)."""
    full = np.maximum(_AWS_PINGS, _AWS_PINGS.T)
    return full // 2


class AwsRegionNetworkLatency(NetworkLatency):
    ONEWAY = _aws_oneway_matrix()

    @staticmethod
    def cities():
        return sorted(AWS_REGION_PER_CITY.keys())

    def get_extended_latency(self, from_node: Node, to_node: Node, delta: int) -> int:
        reg1 = AWS_REGION_PER_CITY.get(from_node.city_name)
        reg2 = AWS_REGION_PER_CITY.get(to_node.city_name)
        if reg1 is None or reg2 is None:
            raise ValueError(
                f"{from_node} or {to_node} not in our aws cities list"
            )
        if reg1 == reg2:
            return 1
        base = int(self.ONEWAY[reg1, reg2])
        return max(1, base + jint(float(JITTER_TABLE[delta])))

    def ext_vec(self, static, from_idx, to_idx, delta):
        import jax.numpy as jnp

        m = jnp.asarray(self.ONEWAY, dtype=jnp.int32)
        jt = jnp.asarray(JITTER_TABLE, dtype=jnp.float32)
        r1 = static.city_idx[from_idx]
        r2 = static.city_idx[to_idx]
        lat = jnp.maximum(1, m[r1, r2] + jt[delta].astype(jnp.int32))
        return jnp.where(r1 == r2, 1, lat)


# ---------------------------------------------------------------------------
# 3/4. Wondernetwork city matrix, without and with jitter
# ---------------------------------------------------------------------------


class NetworkLatencyByCity(NetworkLatency):
    def __init__(self, reader=None):
        if reader is None:
            from ..tools.latency_csv import CSVLatencyReader

            reader = CSVLatencyReader()
        self._reader = reader
        self._index = reader.city_index()
        self._matrix = reader.matrix()

    @property
    def city_index(self):
        return self._index

    def _city_lat(self, city_from: str, city_to: str) -> float:
        return float(self._matrix[self._index[city_from], self._index[city_to]])

    def get_extended_latency(self, from_node: Node, to_node: Node, delta: int) -> int:
        if from_node.node_id == to_node.node_id:
            return 1
        if (
            from_node.city_name == Node.DEFAULT_CITY
            or to_node.city_name == Node.DEFAULT_CITY
        ):
            raise ValueError(
                "Can't use NetworkLatencyByCity model with default city location"
            )
        raw = np.float32(0.5) * np.float32(
            self._city_lat(from_node.city_name, to_node.city_name)
        )
        return max(1, jround(float(raw)))

    def ext_vec(self, static, from_idx, to_idx, delta):
        import jax.numpy as jnp

        m = jnp.asarray(self._matrix, dtype=jnp.float32)
        c1 = static.city_idx[from_idx]
        c2 = static.city_idx[to_idx]
        lat = jnp.maximum(1, jnp.floor(0.5 * m[c1, c2] + 0.5).astype(jnp.int32))
        return jnp.where(from_idx == to_idx, 1, lat)


class NetworkLatencyByCityWJitter(NetworkLatencyByCity):
    """City matrix + GPD jitter; same-city RTT approximated as 10 ms
    (NetworkLatency.java:200-233)."""

    SAME_CITY_RTT = 10.0

    def get_extended_latency(self, from_node: Node, to_node: Node, delta: int) -> int:
        if from_node.node_id == to_node.node_id:
            return 1
        if (
            from_node.city_name == Node.DEFAULT_CITY
            or to_node.city_name == Node.DEFAULT_CITY
        ):
            raise ValueError(
                "Can't use NetworkLatencyByCity model with default city location"
            )
        raw = float(JITTER_TABLE[delta])
        if from_node.city_name == to_node.city_name:
            raw += self.SAME_CITY_RTT
        else:
            raw += self._city_lat(from_node.city_name, to_node.city_name)
        return max(1, jint(jround(0.5 * raw)))

    def ext_vec(self, static, from_idx, to_idx, delta):
        import jax.numpy as jnp

        m = jnp.asarray(self._matrix, dtype=jnp.float32)
        jt = jnp.asarray(JITTER_TABLE, dtype=jnp.float32)
        c1 = static.city_idx[from_idx]
        c2 = static.city_idx[to_idx]
        base = jnp.where(c1 == c2, jnp.float32(self.SAME_CITY_RTT), m[c1, c2])
        raw = base + jt[delta]
        lat = jnp.maximum(1, jnp.floor(0.5 * raw + 0.5).astype(jnp.int32))
        return jnp.where(from_idx == to_idx, 1, lat)


# ---------------------------------------------------------------------------
# 5/6/7. Fixed / uniform / none
# ---------------------------------------------------------------------------


class NetworkFixedLatency(NetworkLatency):
    def __init__(self, fixed_latency: int):
        self.fixed_latency = max(1, fixed_latency)

    def get_extended_latency(self, from_node, to_node, delta) -> int:
        return self.fixed_latency

    def ext_vec(self, static, from_idx, to_idx, delta):
        import jax.numpy as jnp

        return jnp.full(jnp.shape(from_idx), self.fixed_latency, dtype=jnp.int32)

    def __str__(self):
        return f"fixedLatency:{self.fixed_latency}"


class NetworkUniformLatency(NetworkLatency):
    def __init__(self, max_latency: int):
        self.max_latency = max(1, max_latency)

    def get_extended_latency(self, from_node, to_node, delta) -> int:
        return jint((delta / 99.0) * self.max_latency)

    def ext_vec(self, static, from_idx, to_idx, delta):
        import jax.numpy as jnp

        return (
            (delta.astype(jnp.float32) / 99.0) * self.max_latency
        ).astype(jnp.int32)

    def __str__(self):
        return f"NetworkUniformLatency:{self.max_latency}"


class NetworkNoLatency(NetworkLatency):
    def get_extended_latency(self, from_node, to_node, delta) -> int:
        return 1

    def ext_vec(self, static, from_idx, to_idx, delta):
        import jax.numpy as jnp

        return jnp.ones(jnp.shape(from_idx), dtype=jnp.int32)


# ---------------------------------------------------------------------------
# 8. Measured distribution (100-bucket inverse CDF)
# ---------------------------------------------------------------------------


class MeasuredNetworkLatency(NetworkLatency):
    def __init__(self, distrib_prop, distrib_val):
        self.long_distrib = self._set_latency(distrib_prop, distrib_val)

    @staticmethod
    def _set_latency(proportions, values) -> np.ndarray:
        """Integer-step interpolation, exact reference arithmetic
        (NetworkLatency.java:284-303)."""
        out = np.zeros(100, dtype=np.int64)
        li = 0
        cur = 0
        total = 0
        for prop, val in zip(proportions, values):
            if prop == 0:
                cur = val
                continue
            total += prop
            step = java_int_div(val - cur, prop)  # Java int division
            for _ in range(prop):
                cur += step
                out[li] = cur
                li += 1
        if total != 100 or li != 100:
            raise ValueError("proportions must sum to 100")
        return out

    def get_extended_latency(self, from_node, to_node, delta) -> int:
        self._check_delta(delta)
        return int(self.long_distrib[delta])

    def ext_vec(self, static, from_idx, to_idx, delta):
        import jax.numpy as jnp

        table = jnp.asarray(self.long_distrib, dtype=jnp.int32)
        return table[delta]


# ---------------------------------------------------------------------------
# 9. EthStats block-propagation distribution
# ---------------------------------------------------------------------------


class EthScanNetworkLatency(NetworkLatency):
    DISTRIB_PROP = [16, 18, 17, 12, 8, 5, 4, 3, 3, 1, 1, 2, 1, 1, 8]
    DISTRIB_VAL = [
        250, 500, 1000, 1250, 1500, 1750, 2000, 2250, 2500, 2750,
        4500, 6000, 8500, 9750, 10000,
    ]

    def __init__(self):
        self._m = MeasuredNetworkLatency(self.DISTRIB_PROP, self.DISTRIB_VAL)

    def get_extended_latency(self, from_node, to_node, delta) -> int:
        # The reference delegates to MeasuredNetworkLatency.getLatency (adds
        # extras + clamps inside); kept exact (NetworkLatency.java:374-377).
        return self._m.get_latency(from_node, to_node, delta)

    def ext_vec(self, static, from_idx, to_idx, delta):
        import jax.numpy as jnp

        inner = vec_latency(self._m, static, from_idx, to_idx, delta)
        return inner


# ---------------------------------------------------------------------------
# 10. IC3 area-quantile latency
# ---------------------------------------------------------------------------


class IC3NetworkLatency(NetworkLatency):
    S10 = 92
    SW = 350

    def get_extended_latency(self, from_node: Node, to_node: Node, delta: int) -> int:
        dist = from_node.dist(to_node)
        surface = dist * dist * math.pi
        total_surface = MAX_X * MAX_Y
        position = jint((surface * 100) / total_surface)
        if position <= 10:
            return self.S10 // 2
        if position <= 33:
            return 125 // 2
        if position <= 50:
            return 152 // 2
        if position <= 67:
            return 200 // 2
        if position <= 90:
            return 276 // 2
        return self.SW // 2

    _TABLE = None

    @classmethod
    def _table(cls) -> np.ndarray:
        """Exact per-distance table (float64 host precompute, see
        NetworkLatencyByDistanceWJitter._table for the rationale)."""
        if cls._TABLE is None:
            out = np.empty(MAX_DIST + 1, dtype=np.int32)
            for dist in range(MAX_DIST + 1):
                surface = float(dist) * dist * math.pi
                position = jint((surface * 100) / (MAX_X * MAX_Y))
                if position <= 10:
                    out[dist] = cls.S10 // 2
                elif position <= 33:
                    out[dist] = 125 // 2
                elif position <= 50:
                    out[dist] = 152 // 2
                elif position <= 67:
                    out[dist] = 200 // 2
                elif position <= 90:
                    out[dist] = 276 // 2
                else:
                    out[dist] = cls.SW // 2
            cls._TABLE = out
        return cls._TABLE

    def ext_vec(self, static, from_idx, to_idx, delta):
        import jax.numpy as jnp

        table = jnp.asarray(self._table())
        dist = _dist_vec(static, from_idx, to_idx)
        return table[dist]


# ---------------------------------------------------------------------------
# Empirical re-measurement (estimateLatency family, NetworkLatency.java:432-509)
# ---------------------------------------------------------------------------


def _add_to_stats(lat: int, props, vals) -> None:
    p = 0
    while p < len(props) - 1 and vals[p] < lat:
        p += 1
    props[p] += 1


def estimate_latency(net, rounds: int, peer_getter=None) -> MeasuredNetworkLatency:
    """Sample the live latency model into a measured distribution, using the
    network's RNG stream exactly like the reference."""
    from ..utils.javarand import JavaRandom

    props = [0] * 50
    vals = [0] * 50
    pos = 0
    for i in range(10, 201, 10):
        vals[pos] = i
        pos += 1
    for i in range(300, 2001, 100):
        vals[pos] = i
        pos += 1
    while pos < len(vals):
        vals[pos] = vals[pos - 1] + 1000
        pos += 1

    if peer_getter is None:

        def peer_getter(n):
            prd = JavaRandom(0)
            res = n
            while res is n:
                res = net.all_nodes[prd.next_int(len(net.all_nodes))]
            return res

    node_ct = len(net.all_nodes)
    rounds_ct = rounds
    while rounds_ct > 0:
        n1 = net.all_nodes[net.rd.next_int(node_ct)]
        n2 = peer_getter(n1)
        if n1 is not n2:
            rounds_ct -= 1
            delay = net.network_latency.get_latency(n1, n2, net.rd.next_int(100))
            _add_to_stats(delay, props, vals)

    props = [jround((100.0 * p) / rounds) for p in props]
    tot = sum(props)
    while tot != 100:
        gap = 100 - tot
        tot = 0
        for i in range(len(props)):
            if gap > 0 and props[i] > 0:
                props[i] += 1
                gap -= 1
            elif gap < 0 and props[i] > 1:
                props[i] -= 1
                gap += 1
            tot += props[i]
    return MeasuredNetworkLatency(props, vals)


def estimate_p2p_latency(net, rounds: int) -> MeasuredNetworkLatency:
    from ..utils.javarand import JavaRandom

    def peer_getter(n):
        prd = JavaRandom(0)
        res = n
        while res is n:
            res = n.peers[prd.next_int(len(n.peers))]
        return res

    return estimate_latency(net, rounds, peer_getter)

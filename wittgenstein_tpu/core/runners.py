"""Scenario runners.

Reference semantics: core RunMultipleTimes.java (N reseeded runs, stats
averaged across runs) and ProgressPerTime.java (per-interval stat series,
traffic summary, graph.png).  On the batched engine these are superseded by
vmap sweeps (engine.sweep), but the host-side runners stay as the oracle
scenario drivers and the conformance baseline.
"""

from __future__ import annotations

from typing import Callable, List, Optional

from . import stats as SH


class RunMultipleTimes:
    """N runs of protocol.copy() with rd.setSeed(i); returns per-getter
    averages (RunMultipleTimes.java:14-88)."""

    def __init__(
        self,
        p,
        run_count: int,
        max_time: int,
        stats_getters: List[SH.StatsGetter],
        final_check: Optional[Callable] = None,
    ):
        self.p = p
        self.run_count = run_count
        self.max_time = max_time
        self.stats_getters = stats_getters
        self.final_check = final_check

    def run(self, cont_if: Optional[Callable]) -> List[SH.Stat]:
        all_stats = {id(sg): [] for sg in self.stats_getters}
        for i in range(self.run_count):
            c = self.p.copy()
            c.network().rd.set_seed(i)
            c.init()
            while True:
                did_something = c.network().run_ms(10)
                if self.max_time != 0 and c.network().time >= self.max_time:
                    break
                if did_something and (cont_if is None or not cont_if(c)):
                    break
            if self.final_check is not None and not self.final_check(c):
                raise RuntimeError(f"Failed execution of {c} for random seed of {i}")
            for sg in self.stats_getters:
                all_stats[id(sg)].append(sg.get(c.network().live_nodes()))
        return [SH.avg(all_stats[id(sg)]) for sg in self.stats_getters]

    @staticmethod
    def cont_until_done() -> Callable:
        """Continue while any live node has doneAt == 0
        (RunMultipleTimes.java:90-98)."""

        def cont(p) -> bool:
            return any(n.done_at == 0 for n in p.network().live_nodes())

        return cont


class ProgressPerTime:
    """Per-interval stat series over repeated runs + graph.png
    (ProgressPerTime.java:16-141)."""

    def __init__(
        self,
        template,
        config_desc: str,
        y_axis_desc: str,
        stats_getter: SH.StatsGetter,
        round_count: int,
        end_callback: Optional[Callable],
        stat_each_x_ms: int,
        verbose: bool = True,
    ):
        if round_count <= 0:
            raise ValueError(f"roundCount must be greater than 0. roundCount={round_count}")
        self.protocol = template.copy()
        self.config_desc = config_desc
        self.y_axis_desc = y_axis_desc
        self.stats_getter = stats_getter
        self.round_count = round_count
        self.end_callback = end_callback
        self.stat_each_x_ms = stat_each_x_ms
        self.verbose = verbose

    def run(self, cont_if: Callable, graph_path: Optional[str] = "graph.png"):
        from ..tools.graph import Graph, ReportLine, Series, stat_series

        raw_results = {f: [] for f in self.stats_getter.fields()}
        sums = {"bytesSent": 0, "bytesRcv": 0, "msgSent": 0, "msgRcv": 0, "doneAt": 0}

        for r in range(self.round_count):
            p = self.protocol.copy()
            p.network().rd.set_seed(r)
            p.init()
            if self.verbose:
                print(f"round={r}, {p} {self.config_desc}")
            raw_result = {}
            for f in self.stats_getter.fields():
                gs = Series()
                raw_result[f] = gs
                raw_results[f].append(gs)
            while True:
                p.network().run_ms(self.stat_each_x_ms)
                live_nodes = [n for n in p.network().all_nodes if not n.is_down()]
                s = self.stats_getter.get(live_nodes)
                for f in self.stats_getter.fields():
                    raw_result[f].add_line(ReportLine(p.network().time, s.get(f)))
                if self.verbose and p.network().time % 10000 == 0:
                    print(f"time goes by... time={p.network().time // 1000}, stats={s}")
                if not cont_if(p):
                    break
            if self.end_callback is not None:
                self.end_callback(p)
            for key, getter in (
                ("bytesSent", lambda n: n.bytes_sent),
                ("bytesRcv", lambda n: n.bytes_received),
                ("msgSent", lambda n: n.msg_sent),
                ("msgRcv", lambda n: n.msg_received),
                ("doneAt", lambda n: n.done_at),
            ):
                st = SH.get_stats_on(live_nodes, getter)
                if self.verbose:
                    print(f"{key}: {st}")
                sums[key] += st.avg

        if self.verbose and self.round_count > 1:
            print(f"\nAverage on the {self.round_count} rounds")
            for key, v in sums.items():
                print(f"{key}: {v // self.round_count}")

        if graph_path:
            self.protocol.init()
            graph = Graph(
                f"{self.protocol} {self.config_desc}",
                "time in milliseconds",
                self.y_axis_desc,
            )
            for f in self.stats_getter.fields():
                ss = stat_series(f, raw_results[f])
                graph.add_serie(ss.min)
                graph.add_serie(ss.max)
                graph.add_serie(ss.avg)
            graph.clean_series()
            graph.save(graph_path)
        return raw_results

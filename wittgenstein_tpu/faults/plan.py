"""Host-side fault-plan builder: the declarative layer over FaultState.

A `FaultPlan` is what scenarios and scripts write — named, validated,
composable method calls — and `plan.lower(n_nodes, n_msg_types)`
compiles it into the struct-of-arrays `FaultState` the engine consumes.
`lower_plans` stacks a list of plans (None = fault-free control) along a
new leading replica axis, so one `run_ms_batched` call runs a different
schedule per replica row:

    plans = [
        None,                                        # control
        FaultPlan("crash").crash(range(10), at=200),
        FaultPlan("split").partition(groups, start=100, end=800),
        FaultPlan("lossy").drop(300, start=0),
    ]
    fs = lower_plans(plans, net.n_nodes, net.protocol.n_msg_types())
    fnet, fstate = net.with_faults(state, FaultConfig(), fs)  # singleton
    batched = replicate_state(fstate, len(plans))._replace(faults=fs)

All times are sim-time ms with the engine-wide window convention
`start <= t < end` (end=None = forever).  Validation happens at lower()
time, where n_nodes / n_msg_types are known.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .state import INT_MAX, FaultState, neutral_fault_state, stack_fault_states


class FaultPlanError(ValueError):
    """A fault plan that cannot mean what it says: reversed windows
    (crash at or after recovery, partition end before start), rates
    outside their domain, or nodes/mtypes outside the population.
    Raised at BUILD time wherever possible (window ordering does not
    need n_nodes), and at lower() time for the population-sized checks
    — never silently lowered to a no-op lane.  Subclasses ValueError so
    pre-typed callers keep catching it."""


def _window(start, end, what: str) -> Tuple[int, int]:
    start = int(start)
    end = int(INT_MAX) if end is None else int(end)
    if start < 0:
        raise FaultPlanError(f"{what}: start={start} must be >= 0")
    if end <= start:
        raise FaultPlanError(f"{what}: end={end} must be > start={start}")
    return start, end


@dataclasses.dataclass
class FaultPlan:
    """One replica's fault schedule.  Builder methods return self so
    plans chain; each lane may be configured at most once per plan
    (sweep over plans, not over calls, for multi-phase scenarios)."""

    label: str = "faults"
    _crashes: List[Tuple[tuple, int, int]] = dataclasses.field(
        default_factory=list
    )
    _partition: Optional[Tuple[Sequence[int], int, int]] = None
    _drop: Optional[Tuple[Optional[Sequence[int]], int, int, int]] = None
    _inflate: Optional[
        Tuple[Optional[Sequence[int]], int, int, int, int]
    ] = None
    _silence: Optional[Tuple[tuple, int, int]] = None
    _delay: Optional[Tuple[tuple, int, int, int]] = None

    # -- builder methods -----------------------------------------------------
    def crash(self, nodes, at: int, recover: Optional[int] = None):
        """Crash `nodes` for ticks `at <= t < recover` (recover=None =
        forever).  crashed nodes neither send nor receive; sender
        counters still tick, mirroring the oracle's send-time check.
        For nodes dead from t=0 prefer init_state(down=...), which also
        skips their initial emissions like the oracle's never-started
        nodes."""
        at, recover = _window(at, recover, f"crash({self.label})")
        self._crashes.append((tuple(int(i) for i in nodes), at, recover))
        return self

    def partition(self, groups, start: int, end: Optional[int] = None):
        """Split the network into link groups for the window: `groups`
        maps node id -> group id (any int labels); cross-group messages
        are dropped at send and on arrival while active."""
        if self._partition is not None:
            raise FaultPlanError(f"{self.label}: partition() already set")
        start, end = _window(start, end, f"partition({self.label})")
        self._partition = (np.asarray(groups), start, end)
        return self

    def drop(self, per_mille: int, mtypes=None, start: int = 0,
             end: Optional[int] = None):
        """Drop each in-window send with probability per_mille/1000,
        from a dedicated RNG stream (base latency draws untouched).
        mtypes=None applies to every message type."""
        if self._drop is not None:
            raise FaultPlanError(f"{self.label}: drop() already set")
        per_mille = int(per_mille)
        if not 0 <= per_mille <= 1000:
            raise FaultPlanError(
                f"drop({self.label}): per_mille={per_mille} outside [0,1000]"
            )
        start, end = _window(start, end, f"drop({self.label})")
        self._drop = (mtypes, per_mille, start, end)
        return self

    def inflate(self, multiplier_pm: int = 1000, add_ms: int = 0,
                mtypes=None, start: int = 0, end: Optional[int] = None):
        """Inflate in-window sampled latencies: lat' = lat *
        multiplier_pm // 1000 + add_ms (per-mille multiplier; 2000 =
        2x).  mtypes=None applies to every message type."""
        if self._inflate is not None:
            raise FaultPlanError(f"{self.label}: inflate() already set")
        multiplier_pm, add_ms = int(multiplier_pm), int(add_ms)
        if multiplier_pm < 0 or add_ms < 0:
            raise FaultPlanError(
                f"inflate({self.label}): multiplier_pm/add_ms must be >= 0"
            )
        start, end = _window(start, end, f"inflate({self.label})")
        self._inflate = (mtypes, multiplier_pm, add_ms, start, end)
        return self

    def silence(self, nodes, start: int = 0, end: Optional[int] = None):
        """Byzantine silence: `nodes` emit nothing while active (their
        counters still tick — observers cannot tell a silent node from
        a lossy link, which is the point)."""
        if self._silence is not None:
            raise FaultPlanError(f"{self.label}: silence() already set")
        start, end = _window(start, end, f"silence({self.label})")
        self._silence = (tuple(int(i) for i in nodes), start, end)
        return self

    def delay(self, nodes, delay_ms: int, start: int = 0,
              end: Optional[int] = None):
        """Byzantine delay: every message `nodes` send while active
        arrives delay_ms later than the latency model sampled."""
        if self._delay is not None:
            raise FaultPlanError(f"{self.label}: delay() already set")
        delay_ms = int(delay_ms)
        if delay_ms < 0:
            raise FaultPlanError(f"delay({self.label}): delay_ms must be >= 0")
        start, end = _window(start, end, f"delay({self.label})")
        self._delay = (tuple(int(i) for i in nodes), delay_ms, start, end)
        return self

    # -- lowering ------------------------------------------------------------
    def _check_nodes(self, nodes, n_nodes, what):
        for i in nodes:
            if not 0 <= i < n_nodes:
                raise FaultPlanError(
                    f"{what}({self.label}): node {i} outside [0,{n_nodes})"
                )

    def _mtype_rows(self, mtypes, n_msg_types, what):
        if mtypes is None:
            return list(range(n_msg_types))
        rows = [int(m) for m in mtypes]
        for m in rows:
            if not 0 <= m < n_msg_types:
                raise FaultPlanError(
                    f"{what}({self.label}): mtype {m} outside "
                    f"[0,{n_msg_types})"
                )
        return rows

    def lower(self, n_nodes: int, n_msg_types: int) -> FaultState:
        """Compile to the engine's struct-of-arrays FaultState (jnp
        leaves; stack with lower_plans / stack_fault_states for a
        per-replica heterogeneous sweep)."""
        # writable numpy twins of neutral_fault_state (jnp buffers are
        # read-only; the scatter-y mutation below wants plain numpy)
        fs = FaultState(
            crash_at=np.full(n_nodes, INT_MAX, np.int32),
            recover_at=np.full(n_nodes, INT_MAX, np.int32),
            group=np.zeros(n_nodes, np.int32),
            part_start=np.asarray(INT_MAX, np.int32),
            part_end=np.asarray(INT_MAX, np.int32),
            drop_pm=np.zeros(n_msg_types, np.int32),
            drop_start=np.asarray(INT_MAX, np.int32),
            drop_end=np.asarray(INT_MAX, np.int32),
            infl_pm=np.full(n_msg_types, 1000, np.int32),
            infl_add=np.zeros(n_msg_types, np.int32),
            infl_start=np.asarray(INT_MAX, np.int32),
            infl_end=np.asarray(INT_MAX, np.int32),
            byz_silent=np.zeros(n_nodes, bool),
            byz_delay=np.zeros(n_nodes, np.int32),
            byz_start=np.asarray(INT_MAX, np.int32),
            byz_end=np.asarray(INT_MAX, np.int32),
            dropped_by_fault=np.zeros(n_msg_types, np.int32),
            delayed_by_fault=np.zeros(n_msg_types, np.int32),
        )
        for nodes, at, recover in self._crashes:
            self._check_nodes(nodes, n_nodes, "crash")
            idx = list(nodes)
            fs.crash_at[idx] = at
            fs.recover_at[idx] = recover
        if self._partition is not None:
            groups, start, end = self._partition
            if groups.shape != (n_nodes,):
                raise FaultPlanError(
                    f"partition({self.label}): groups shape {groups.shape} "
                    f"!= ({n_nodes},)"
                )
            fs.group[:] = groups.astype(np.int32)
            fs.part_start[...] = start
            fs.part_end[...] = end
        if self._drop is not None:
            mtypes, pm, start, end = self._drop
            rows = self._mtype_rows(mtypes, n_msg_types, "drop")
            fs.drop_pm[rows] = pm
            fs.drop_start[...] = start
            fs.drop_end[...] = end
        if self._inflate is not None:
            mtypes, mult, add, start, end = self._inflate
            rows = self._mtype_rows(mtypes, n_msg_types, "inflate")
            fs.infl_pm[rows] = mult
            fs.infl_add[rows] = add
            fs.infl_start[...] = start
            fs.infl_end[...] = end
        byz_windows = []
        if self._silence is not None:
            nodes, start, end = self._silence
            self._check_nodes(nodes, n_nodes, "silence")
            fs.byz_silent[list(nodes)] = True
            byz_windows.append((start, end))
        if self._delay is not None:
            nodes, delay_ms, start, end = self._delay
            self._check_nodes(nodes, n_nodes, "delay")
            fs.byz_delay[list(nodes)] = delay_ms
            byz_windows.append((start, end))
        if byz_windows:
            if len(set(byz_windows)) > 1:
                raise FaultPlanError(
                    f"{self.label}: silence() and delay() share one "
                    f"Byzantine window; got {byz_windows}"
                )
            fs.byz_start[...] = byz_windows[0][0]
            fs.byz_end[...] = byz_windows[0][1]
        import jax
        import jax.numpy as jnp

        return jax.tree_util.tree_map(jnp.asarray, fs)

    def describe(self) -> dict:
        """JSON-friendly summary for reports/run records."""
        out = {"label": self.label}
        if self._crashes:
            out["crashes"] = [
                {"nodes": len(n), "at": a,
                 "recover": None if r == int(INT_MAX) else r}
                for n, a, r in self._crashes
            ]
        if self._partition is not None:
            g, s, e = self._partition
            out["partition"] = {
                "groups": int(len(np.unique(g))), "start": s,
                "end": None if e == int(INT_MAX) else e,
            }
        if self._drop is not None:
            m, pm, s, e = self._drop
            out["drop"] = {"per_mille": pm, "start": s,
                           "end": None if e == int(INT_MAX) else e}
        if self._inflate is not None:
            m, mult, add, s, e = self._inflate
            out["inflate"] = {"multiplier_pm": mult, "add_ms": add,
                              "start": s,
                              "end": None if e == int(INT_MAX) else e}
        if self._silence is not None:
            n, s, e = self._silence
            out["silence"] = {"nodes": len(n), "start": s,
                              "end": None if e == int(INT_MAX) else e}
        if self._delay is not None:
            n, d, s, e = self._delay
            out["delay"] = {"nodes": len(n), "delay_ms": d, "start": s,
                            "end": None if e == int(INT_MAX) else e}
        return out


def lower_plans(plans, n_nodes: int, n_msg_types: int) -> FaultState:
    """Lower a list of plans (None = fault-free control row) and stack
    them along a new leading replica axis — the fault side-car for a
    heterogeneous run_ms_batched sweep."""
    lowered = [
        neutral_fault_state(n_nodes, n_msg_types)
        if p is None
        else p.lower(n_nodes, n_msg_types)
        for p in plans
    ]
    return stack_fault_states(lowered)


def fault_state_digest(fs: FaultState) -> str:
    """Stable content digest of one lowered schedule: field names, leaf
    dtypes/shapes, and bytes, hashed in field order.  Two plans with the
    same digest produce bit-identical FaultState rows, so the digest is
    the dedupe/pin identity for sweeps and regression scenarios (the
    label is narrative, the digest is the plan)."""
    h = hashlib.blake2b(digest_size=16)
    for name, leaf in zip(fs._fields, fs):
        a = np.asarray(leaf)
        h.update(name.encode())
        h.update(str(a.dtype).encode())
        h.update(repr(a.shape).encode())
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()


def plan_digest(plan: Optional["FaultPlan"], n_nodes: int,
                n_msg_types: int) -> str:
    """fault_state_digest of `plan` lowered at this population size
    (None = the neutral control schedule)."""
    fs = (
        neutral_fault_state(n_nodes, n_msg_types)
        if plan is None
        else plan.lower(n_nodes, n_msg_types)
    )
    return fault_state_digest(fs)

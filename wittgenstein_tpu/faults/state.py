"""In-graph fault state: the device-side fault-injection tier.

The reference simulator treats adversity as first-class — down nodes
(NodeBuilder), Byzantine senders (Casper's ByzBlockProducer, Handel's
suppression scenarios), degraded WANs — but only through host-side Java
objects mutated between `run_ms` calls.  On the batched engine a fault
schedule must live INSIDE the compiled program so `run_ms_batched` can
sweep fault scenarios the way it already sweeps seeds: the schedule is a
`FaultState` pytree side-car on `SimState`, per-replica heterogeneous
(every leaf grows the leading replica axis under vmap like any other
state column).

Lanes, all windowed on sim time `t` with the convention
`active(t) = start <= t < end` (end exclusive; INT_MAX start = never):

  * crash/recovery per node: `crashed(i, t) = crash_at[i] <= t <
    recover_at[i]`.  A crashed node's sends are suppressed at the
    latency kernel (the oracle's send-time `is_down()` check,
    Network.java:476-487) and deliveries TO it are suppressed at the
    delivery view (Network.java:606); messages already in flight from
    it still arrive, exactly like the oracle.  Sender counters still
    tick for suppressed sends (the oracle ticks msg_sent before its
    down check).  Recovery is just the window end: from `recover_at`
    the node sends and receives again.
  * group partition: a node->group map plus one window; cross-group
    messages are suppressed at send AND at delivery (a message sent
    before the window but arriving inside it is dropped on arrival,
    mirroring the oracle's delivery-time partition re-check).
  * per-mtype probabilistic drop: drop_pm[T] per-mille, drawn from a
    dedicated `hash32` stream salted with FAULT_STREAM — the engine's
    send_ctr is NOT advanced, so the base RNG sequence (and therefore
    every fault-free latency draw) is untouched.
  * per-mtype latency inflation: arrival' = send_time +
    (lat * infl_pm[T]) // 1000 + infl_add[T] inside the window.
  * Byzantine masks: byz_silent[N] senders emit nothing inside the
    window (counters still tick); byz_delay[N] adds a per-sender
    constant to every outgoing latency.

Neutrality is the contract (simlint SL406, tests/test_faults.py): with
the neutral `FaultState` every predicate above is constant-false and
every latency passes through `jnp.where` unchanged, so a fault-enabled
run is bit-identical in all non-fault fields to a disabled one.  The
enable switch is STATIC (`FaultConfig` on the engine, part of its jit
cache key): disabled engines carry `faults=()` — an empty pytree, zero
leaves, zero traced ops, the exact pattern of the telemetry side-car.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

INT_MAX = np.int32(2**31 - 1)

# salt for the drop-draw hash32 stream: decorrelates fault draws from the
# latency draws that share (seed, send_time, from, mtype, send_ctr, to)
FAULT_STREAM = np.int32(0x5AFE)


@dataclasses.dataclass(frozen=True)
class FaultConfig:
    """Static fault-lane switches; hashable, stamped into the engine's
    cache_key (a different config is a different traced program).  Each
    flag prunes its lane's ops from the trace entirely — an engine with
    only `crashes=True` pays nothing for the drop/inflation RNG."""

    crashes: bool = True
    partitions: bool = True
    drops: bool = True
    delays: bool = True  # latency inflation lane
    byzantine: bool = True  # silence + per-sender delay masks

    def __post_init__(self):
        if not any(
            (self.crashes, self.partitions, self.drops, self.delays,
             self.byzantine)
        ):
            raise ValueError(
                "FaultConfig with every lane disabled traces zero fault "
                "ops; pass faults=None to the engine instead"
            )

    def key(self) -> tuple:
        return (self.crashes, self.partitions, self.drops, self.delays,
                self.byzantine)


class FaultState(NamedTuple):
    """The fault-schedule side-car (int32/bool; leading replica axis
    appears under vmap exactly like every other SimState leaf).
    [N] = one row per node, [T] = one row per protocol message type;
    window scalars are int32 with INT_MAX = never active."""

    # crash lane [N]: crashed(i, t) = crash_at[i] <= t < recover_at[i]
    crash_at: jnp.ndarray
    recover_at: jnp.ndarray
    # partition lane: group map [N] + one active window
    group: jnp.ndarray
    part_start: jnp.ndarray
    part_end: jnp.ndarray
    # probabilistic drop lane [T] (per-mille) + window
    drop_pm: jnp.ndarray
    drop_start: jnp.ndarray
    drop_end: jnp.ndarray
    # latency-inflation lane [T]: lat' = lat * infl_pm // 1000 + infl_add
    infl_pm: jnp.ndarray
    infl_add: jnp.ndarray
    infl_start: jnp.ndarray
    infl_end: jnp.ndarray
    # Byzantine lane [N] + window
    byz_silent: jnp.ndarray  # bool[N]: sender emits nothing in-window
    byz_delay: jnp.ndarray  # int32[N]: flat ms added to outgoing latency
    byz_start: jnp.ndarray
    byz_end: jnp.ndarray
    # fault counters [T] (pure accounting, like the telemetry tier)
    dropped_by_fault: jnp.ndarray  # sends/deliveries a fault suppressed
    delayed_by_fault: jnp.ndarray  # sends whose latency a fault changed


def neutral_fault_state(n_nodes: int, n_msg_types: int) -> FaultState:
    """The do-nothing schedule: every window starts at INT_MAX, drop
    probability 0, inflation multiplier 1000 (identity).  A fault-enabled
    engine running this state is bit-identical to a disabled one (pinned
    by tests/test_faults.py and simlint SL406)."""
    n, t = n_nodes, n_msg_types
    never = lambda: jnp.asarray(INT_MAX, jnp.int32)
    return FaultState(
        crash_at=jnp.full(n, INT_MAX, dtype=jnp.int32),
        recover_at=jnp.full(n, INT_MAX, dtype=jnp.int32),
        group=jnp.zeros(n, dtype=jnp.int32),
        part_start=never(),
        part_end=never(),
        drop_pm=jnp.zeros(t, dtype=jnp.int32),
        drop_start=never(),
        drop_end=never(),
        infl_pm=jnp.full(t, 1000, dtype=jnp.int32),
        infl_add=jnp.zeros(t, dtype=jnp.int32),
        infl_start=never(),
        infl_end=never(),
        byz_silent=jnp.zeros(n, dtype=bool),
        byz_delay=jnp.zeros(n, dtype=jnp.int32),
        byz_start=never(),
        byz_end=never(),
        dropped_by_fault=jnp.zeros(t, dtype=jnp.int32),
        delayed_by_fault=jnp.zeros(t, dtype=jnp.int32),
    )


def stack_fault_states(states) -> FaultState:
    """Stack per-replica schedules along a new leading axis — the fault
    analog of engine.core.stack_states, for heterogeneous sweeps."""
    import jax

    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *states)


# -- in-graph predicates (called from the engine's two choke points) ---------
def window_active(start, end, t):
    return (start <= t) & (t < end)


def node_crashed(fs: FaultState, idx, t):
    return (fs.crash_at[idx] <= t) & (t < fs.recover_at[idx])


def send_suppress(
    cfg: FaultConfig, fs: FaultState, t, from_idx, to_idx, mtype_rows,
    seed, send_ctr, send_time,
):
    """bool[K]: rows the fault lanes kill at the latency kernel.  The
    crash predicate is evaluated at the CURRENT tick `t` (not at
    send_time): the oracle executes a send during the tick that emits
    it, so forwards emitted while processing tick t carry send_time t+1
    but are accepted as long as the sender is alive AT t."""
    supp = jnp.zeros(jnp.shape(from_idx), dtype=bool)
    if cfg.crashes:
        # both endpoints, like the oracle's send-time is_down() pair
        supp = supp | node_crashed(fs, from_idx, t) | node_crashed(fs, to_idx, t)
    if cfg.partitions:
        cross = fs.group[from_idx] != fs.group[to_idx]
        supp = supp | (window_active(fs.part_start, fs.part_end, t) & cross)
    if cfg.byzantine:
        supp = supp | (
            window_active(fs.byz_start, fs.byz_end, t) & fs.byz_silent[from_idx]
        )
    if cfg.drops:
        from ..engine.rng import hash32

        # dedicated stream: salting with FAULT_STREAM (and NOT advancing
        # send_ctr) leaves every base latency draw untouched, so drop_pm=0
        # rows are bit-identical to a fault-free run
        u = hash32(
            seed, jnp.asarray(FAULT_STREAM, jnp.int32), send_time, from_idx,
            mtype_rows, send_ctr, to_idx,
        ).astype(jnp.uint32)
        draw = (u % jnp.uint32(1000)).astype(jnp.int32)
        supp = supp | (
            window_active(fs.drop_start, fs.drop_end, t)
            & (draw < fs.drop_pm[mtype_rows])
        )
    return supp


def inflate_latency(
    cfg: FaultConfig, fs: FaultState, t, from_idx, mtype_rows, lat
):
    """int32[K]: the sampled latency after the inflation and Byzantine
    delay lanes.  Outside their windows both are exact passthroughs
    (jnp.where picks the untouched value), preserving bit-identity."""
    new = lat
    if cfg.delays:
        act = window_active(fs.infl_start, fs.infl_end, t)
        inflated = (lat * fs.infl_pm[mtype_rows]) // jnp.int32(1000) + (
            fs.infl_add[mtype_rows]
        )
        new = jnp.where(act, inflated, new)
    if cfg.byzantine:
        bact = window_active(fs.byz_start, fs.byz_end, t)
        new = new + jnp.where(bact, fs.byz_delay[from_idx], jnp.int32(0))
    return new


def deliver_suppress(cfg: FaultConfig, fs: FaultState, t, view_from, view_to):
    """bool[D]: due rows the fault lanes discard on arrival.  Only the
    destination's crash state matters here (a message in flight from a
    node that crashed after sending still arrives, like the oracle);
    the partition lane re-checks on arrival like the oracle's
    delivery-time partition test (Network.java:606)."""
    supp = jnp.zeros(jnp.shape(view_to), dtype=bool)
    if cfg.crashes:
        supp = supp | node_crashed(fs, view_to, t)
    if cfg.partitions:
        cross = fs.group[view_from] != fs.group[view_to]
        supp = supp | (window_active(fs.part_start, fs.part_end, t) & cross)
    return supp

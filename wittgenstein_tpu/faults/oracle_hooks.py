"""Oracle (Java-faithful DES) twins of the batched crash lane.

The oracle's `Node.start()/stop()` just flip `_down`, so a crash/recover
schedule is a sequence of run segments with stop/start calls between
them.  These helpers chop `Network.run_ms` so a batched `FaultPlan`'s
crash windows replay exactly on the oracle, which is how parity tests
pin the fault lane's done-at CDF (tests/test_faults.py).

Alignment with the batched predicate `crashed(t) = crash_at <= t <
recover_at` (see faults/state.py):

  * the oracle is run through tick `crash_at - 1` BEFORE stop() — sends
    executed while processing tick crash_at-1 (send_time crash_at) are
    accepted in both implementations, because the batched send check
    evaluates the crash at the CURRENT tick, not at send_time;
  * deliveries at tick crash_at and later are dropped by the oracle's
    delivery-time `is_down()` check and by the batched delivery view;
  * start() lands the same way at recover_at.

Only the crash lane has an oracle twin: partitions on the oracle are
x-threshold based (`Network.partition`) and already parity-tested, and
the probabilistic drop / inflation / Byzantine lanes are batched-RNG
constructs with no Java counterpart.  `run_ms_with_plan` raises on
plans using those lanes rather than silently ignoring them.

Caveat: a `crash(at=0)` plan is NOT the oracle's never-started node —
the oracle skips start() (so no initial sends attempt, msg_sent==0)
while the batched engine suppresses the initial emissions but still
ticks sender counters.  Nodes dead from t=0 belong in
`init_state(down=...)` / the node builder's down set, which both sides
treat identically.
"""

from __future__ import annotations

from typing import Iterable, List, Tuple

from .state import INT_MAX


def stop_nodes(network, ids: Iterable[int]) -> None:
    for i in ids:
        network.all_nodes[i].stop()


def start_nodes(network, ids: Iterable[int]) -> None:
    for i in ids:
        network.all_nodes[i].start()


def crash_edges(plan) -> List[Tuple[int, str, tuple]]:
    """[(tick, 'stop'|'start', node ids)] from a FaultPlan's crash lane;
    raises if the plan uses lanes the oracle cannot replay."""
    for lane in ("_partition", "_drop", "_inflate", "_silence", "_delay"):
        if getattr(plan, lane) is not None:
            raise ValueError(
                f"plan '{plan.label}' uses {lane.lstrip('_')}(): only the "
                "crash lane has an oracle twin (x-partitions go through "
                "Network.partition directly)"
            )
    edges: List[Tuple[int, str, tuple]] = []
    for nodes, at, recover in plan._crashes:
        edges.append((at, "stop", nodes))
        if recover < int(INT_MAX):
            edges.append((recover, "start", nodes))
    edges.sort(key=lambda e: e[0])
    return edges


def run_ms_with_plan(network, plan, sim_ms: int):
    """Run the oracle to `sim_ms` replaying the plan's crash windows at
    the batched engine's tick alignment (see module docstring).  The
    network must be freshly initialised (time 0)."""
    for tick, kind, nodes in crash_edges(plan):
        if tick > sim_ms:
            break
        pre = tick - 1  # last tick the old up/down state applies to
        if pre > network.time:
            network.run_ms(pre - network.time)
        (stop_nodes if kind == "stop" else start_nodes)(network, nodes)
    if sim_ms > network.time:
        network.run_ms(sim_ms - network.time)
    return network

"""Vectorized fault injection for the batched engine.

A host-side `FaultPlan` (crash/recover windows, group partitions,
per-mtype drop probability, latency inflation, Byzantine silence/delay)
lowers into a struct-of-arrays `FaultState` side-car on `SimState`,
per-replica heterogeneous, injected in-graph at the engine's two choke
points (`latency_arrivals` and the delivery view) behind a static
`FaultConfig` flag — bit-identical to a fault-free engine when off.
See docs/faults.md.
"""

from .oracle_hooks import crash_edges, run_ms_with_plan, start_nodes, stop_nodes
from .plan import (
    FaultPlan,
    FaultPlanError,
    fault_state_digest,
    lower_plans,
    plan_digest,
)
from .state import (
    FAULT_STREAM,
    FaultConfig,
    FaultState,
    deliver_suppress,
    inflate_latency,
    neutral_fault_state,
    node_crashed,
    send_suppress,
    stack_fault_states,
)

__all__ = [
    "FAULT_STREAM",
    "FaultConfig",
    "FaultPlan",
    "FaultPlanError",
    "FaultState",
    "crash_edges",
    "deliver_suppress",
    "fault_state_digest",
    "inflate_latency",
    "lower_plans",
    "plan_digest",
    "neutral_fault_state",
    "node_crashed",
    "run_ms_with_plan",
    "send_suppress",
    "stack_fault_states",
    "start_nodes",
    "stop_nodes",
]

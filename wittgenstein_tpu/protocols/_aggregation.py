"""Shared pieces of the San Fermin-style aggregation protocols
(GSFSignature, Handel, handeleth2): the binary-tree membership trick and
the common parameter normalization/validation."""

from __future__ import annotations


def all_sigs_at_level(node_id: int, round_: int, node_count: int) -> int:
    """All the signatures a node should have when `round_` is finished —
    the sibling-subtree bitmask trick (Handel.java:634-647,
    GSFSignature.java:361-374)."""
    if round_ < 1:
        raise ValueError(f"round={round_}")
    c_mask = (1 << round_) - 1
    start = (c_mask | node_id) ^ c_mask
    end = min(node_id | c_mask, node_count - 1)
    res = ((1 << (end + 1)) - 1) ^ ((1 << start) - 1)
    res &= ~(1 << node_id)
    return res


def normalize_agg_params(p) -> None:
    """Threshold/nodes_down normalization + validation shared by the
    aggregation parameter classes: -1 -> 99% default, float -> ratio of
    node_count (mirroring the reference's int vs ratio constructor
    overloads)."""
    if p.threshold == -1:
        p.threshold = int(p.node_count * 0.99)
    elif isinstance(p.threshold, float):
        p.threshold = int(p.threshold * p.node_count)
    if isinstance(p.nodes_down, float):
        p.nodes_down = int(p.nodes_down * p.node_count)
    if (
        p.nodes_down >= p.node_count
        or p.nodes_down < 0
        or p.threshold > p.node_count
        or (p.nodes_down + p.threshold > p.node_count)
    ):
        raise ValueError(f"nodeCount={p.node_count}, threshold={p.threshold}")

"""Classic Paxos with a handful of acceptors and proposers; proposer
timeouts via registerTask, seq numbers partitioned by proposer rank.

Reference semantics: protocols/Paxos.java (messages :43-145, AcceptorNode
:153-207, ProposerNode :209-339, seq-number scheme :313-338, RunMultipleTimes
driver `play` :394-519).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

from ..core import stats as SH
from ..core.node import Node
from ..core.params import WParameters, register_protocol
from ..core.registries import registry_network_latencies, registry_node_builders
from ..core.runners import RunMultipleTimes
from ..oracle.messages import Message
from ..oracle.network import Network, Protocol

MAX_VAL = 1000


@dataclasses.dataclass
class PaxosParameters(WParameters):
    acceptor_count: int = 3
    proposer_count: int = 3
    timeout: int = 1000
    node_builder: Optional[str] = None
    latency: Optional[str] = None


class Propose(Message):
    def __init__(self, seq: int):
        self.seq = seq

    def action(self, network, from_node, to_node):
        to_node.on_propose(from_node, self)


class Reject(Message):
    def __init__(self, seq_rejected: int, seq_accepted: int):
        self.seq_rejected = seq_rejected
        self.seq_accepted = seq_accepted

    def action(self, network, from_node, to_node):
        to_node.on_reject(self.seq_rejected, self.seq_accepted)


class Agree(Message):
    def __init__(self, your_seq: int, accepted_seq: Optional[int], accepted_val: Optional[int]):
        self.your_seq = your_seq
        self.accepted_seq = accepted_seq
        self.accepted_val = accepted_val

    def action(self, network, from_node, to_node):
        to_node.on_agree(self.your_seq, self.accepted_seq, self.accepted_val)


class Commit(Message):
    def __init__(self, seq: int, val: int):
        self.seq = seq
        self.val = val

    def action(self, network, from_node, to_node):
        to_node.on_commit(from_node, self.seq, self.val)


class Accept(Message):
    def __init__(self, your_seq: int):
        self.your_seq = your_seq

    def action(self, network, from_node, to_node):
        to_node.on_accept(self.your_seq)


class RejectOnCommit(Message):
    def __init__(self, seq_rejected: int, seq_accepted: int):
        self.seq_rejected = seq_rejected
        self.seq_accepted = seq_accepted

    def action(self, network, from_node, to_node):
        to_node.on_reject_on_commit(self.seq_rejected, self.seq_accepted)


class PaxosNode(Node):
    __slots__ = ()


class AcceptorNode(PaxosNode):
    __slots__ = ("max_agreed", "accepted_seq", "accepted_val", "agreed_to", "_p")

    def __init__(self, p: "Paxos"):
        super().__init__(p.network().rd, p.nb)
        self.max_agreed = -1
        self.accepted_seq: Optional[int] = None
        self.accepted_val: Optional[int] = None
        self.agreed_to: Optional["ProposerNode"] = None
        self._p = p

    def on_propose(self, from_node, p_msg: Propose) -> None:
        """First round (Paxos.java:163-177)."""
        net = self._p.network()
        if p_msg.seq < self.max_agreed:
            net.send(Reject(p_msg.seq, self.max_agreed), self, from_node)
        elif p_msg.seq == self.max_agreed:
            # can't happen: no message duplication, no byzantine nodes
            raise RuntimeError(f"{self} {p_msg}")
        else:
            a = Agree(p_msg.seq, self.accepted_seq, self.accepted_val)
            self.max_agreed = p_msg.seq
            self.agreed_to = from_node
            net.send(a, self, from_node)

    def on_commit(self, from_node, seq: int, val: int) -> None:
        """Second round (Paxos.java:179-192)."""
        net = self._p.network()
        if seq != self.max_agreed or (self.accepted_val is not None and self.accepted_val != val):
            net.send(RejectOnCommit(seq, self.max_agreed), self, from_node)
        else:
            self.accepted_val = val
            self.accepted_seq = seq if self.accepted_seq is None else max(self.accepted_seq, seq)
            net.send(Accept(seq), self, from_node)

    def __repr__(self) -> str:
        return (
            f"AcceptorNode{{maxAgreed={self.max_agreed}, acceptedSeq={self.accepted_seq}, "
            f"acceptedVal={self.accepted_val}, agreedTo={self.agreed_to}}}"
        )


class ProposerNode(PaxosNode):
    __slots__ = (
        "rank",
        "value_proposed",
        "value_accepted",
        "accepted_seq_ip",
        "accepted_val_ip",
        "seq_ip",
        "agree_count_ip",
        "reject1_count_ip",
        "accept_count_ip",
        "reject2_count_ip",
        "proposal_ip",
        "seq_accepted",
        "agree_count",
        "reject1_count",
        "reject2_count",
        "timeout_count",
        "_p",
    )

    def __init__(self, rank: int, p: "Paxos"):
        super().__init__(p.network().rd, p.nb)
        self.rank = rank
        self.value_proposed = p.network().rd.next_int(MAX_VAL)
        self.value_accepted: Optional[int] = None
        self.accepted_seq_ip: Optional[int] = None
        self.accepted_val_ip: Optional[int] = None
        self.seq_ip = 0
        self.agree_count_ip = 0
        self.reject1_count_ip = 0
        self.accept_count_ip = 0
        self.reject2_count_ip = 0
        self.proposal_ip = False
        self.seq_accepted = 0
        self.agree_count = 0
        self.reject1_count = 0
        self.reject2_count = 0
        self.timeout_count = 0
        self._p = p

    def on_reject(self, seq: int, server_cur_seq: int) -> None:
        if seq == self.seq_ip:
            self.reject1_count_ip += 1
            if self.reject1_count_ip == self._p.majority:
                self.proposal_ip = False
                self.seq_accepted = max(self.seq_accepted, server_cur_seq)
                self.reject1_count += 1
                self.start_next_proposal()

    def on_agree(self, seq: int, accepted_seq: Optional[int], accepted_val: Optional[int]) -> None:
        """Track the highest previously-accepted (seq, val) among agreeing
        acceptors; on majority, commit that value or our own
        (Paxos.java:250-268)."""
        if seq == self.seq_ip and self.agree_count_ip < self._p.majority:
            self.agree_count_ip += 1
            if accepted_seq is not None:
                if self.accepted_seq_ip is None or self.accepted_seq_ip < accepted_seq:
                    self.accepted_seq_ip = accepted_seq
                    self.accepted_val_ip = accepted_val
            if self.agree_count_ip >= self._p.majority:
                self.agree_count += 1
                if self.accepted_val_ip is None:
                    self.accepted_val_ip = self.value_proposed
                c = Commit(self.seq_ip, self.accepted_val_ip)
                self._send_to_acceptors(c, self._p.network().time + 1)

    def on_accept(self, seq: int) -> None:
        if seq == self.seq_ip and self.accept_count_ip < self._p.majority:
            self.accept_count_ip += 1
            if self.accept_count_ip >= self._p.majority:
                self.proposal_ip = False
                if self.accepted_val_ip is None:
                    raise RuntimeError("accept without a value in progress")
                if self.value_accepted is not None:
                    raise RuntimeError("Already accepted a value")
                self.value_accepted = self.accepted_val_ip
                self.done_at = self._p.network().time

    def on_reject_on_commit(self, seq: int, server_cur_seq: int) -> None:
        if seq == self.seq_ip:
            self.reject2_count_ip += 1
            if self.reject2_count_ip == self._p.majority:
                self.proposal_ip = False
                self.seq_accepted = max(self.seq_accepted, server_cur_seq)
                self.reject2_count += 1
                self.start_next_proposal()

    def _send_to_acceptors(self, m: Message, sent_time: int) -> None:
        net = self._p.network()
        dest = list(self._p.acceptors)
        net.rd.shuffle(dest)
        net.send(m, sent_time, self, dest)

    def on_timeout(self, seq: int) -> None:
        if seq == self.seq_ip and self.proposal_ip:
            self.proposal_ip = False
            self.timeout_count += 1
            self.start_next_proposal()

    def start_next_proposal(self) -> None:
        """Seq scheme guaranteeing distinct, incremental seqs per proposer
        (Paxos.java:313-338)."""
        if self.proposal_ip:
            raise RuntimeError("proposal already in progress")
        self.accepted_seq_ip = None
        self.accepted_val_ip = None
        self.proposal_ip = True
        self.agree_count_ip = 0
        self.reject1_count_ip = 0
        self.accept_count_ip = 0
        self.reject2_count_ip = 0

        pc = self._p.params.proposer_count
        gap = self.seq_accepted % pc
        new_seq_ip = self.seq_accepted + pc - gap + self.rank
        self.seq_ip = new_seq_ip if new_seq_ip > self.seq_ip else self.seq_ip + pc

        p_msg = Propose(self.seq_ip)
        net = self._p.network()
        sent_time = net.time + 1
        self._send_to_acceptors(p_msg, sent_time)
        seq = p_msg.seq
        net.register_task(lambda: self.on_timeout(seq), sent_time + self._p.params.timeout, self)


@register_protocol("Paxos", PaxosParameters)
class Paxos(Protocol):
    def __init__(self, params: PaxosParameters):
        self.params = params
        self._network: Network[PaxosNode] = Network()
        self.acceptors: List[AcceptorNode] = []
        self.proposers: List[ProposerNode] = []
        self.majority = params.acceptor_count // 2 + 1
        self.nb = registry_node_builders.get_by_name(params.node_builder)
        self._network.set_network_latency(
            registry_network_latencies.get_by_name(params.latency)
        )

    def network(self) -> Network:
        return self._network

    def copy(self) -> "Paxos":
        return Paxos(self.params)

    def init(self) -> None:
        for _ in range(self.params.acceptor_count):
            an = AcceptorNode(self)
            self._network.add_node(an)
            self.acceptors.append(an)
        for i in range(self.params.proposer_count):
            pn = ProposerNode(i, self)
            self._network.add_node(pn)
            self.proposers.append(pn)
            pn.start_next_proposal()

    def __str__(self) -> str:
        return f"Paxos{{params={self.params}}}"

    def play(self, verbose: bool = False):
        """RunMultipleTimes driver: 10 reseeded runs, 5 s cap, final check
        that all proposers accepted the same value (Paxos.java:394-519)."""

        def proposer_stats(getter):
            class _G(SH.SimpleStatsGetter):
                def get(self, live_nodes):
                    props = [n for n in live_nodes if isinstance(n, ProposerNode)]
                    return SH.get_stats_on(props, getter)

            return _G()

        class _MsgR(SH.SimpleStatsGetter):
            def get(self, live_nodes):
                return SH.get_stats_on(live_nodes, lambda n: n.msg_received)

        stats_to_get = [
            proposer_stats(lambda p: p.done_at),
            proposer_stats(lambda p: p.timeout_count),
            proposer_stats(lambda p: p.reject1_count),
            proposer_stats(lambda p: p.reject2_count),
            _MsgR(),
        ]

        def final_check(paxos) -> bool:
            val = None
            for pn in paxos.proposers:
                if val is None:
                    val = pn.value_accepted
                elif val != pn.value_accepted:
                    return False
            return True

        rmt = RunMultipleTimes(self, 10, 5000, stats_to_get, final_check)

        def cont(protocol) -> bool:
            return any(
                isinstance(n, ProposerNode) and n.done_at == 0
                for n in protocol.network().all_nodes
            )

        res = rmt.run(cont)
        if verbose:
            da, to, r1, r2, mr = res
            print(
                f"{self}, doneAt=({da}), timeout=({to}), rejectRound1=({r1}), "
                f"rejectRound2=({r2}), msg received=({mr})"
            )
        return res


def main():
    Paxos(PaxosParameters()).play(verbose=True)


if __name__ == "__main__":
    main()

"""Binary-id interval machinery shared by the San Fermin protocols.

Reference semantics: protocols/SanFerminHelper.java — own-set / candidate-set
interval halving over the binary node id (:46-96), used-node tracking with
the quirky post-removal index filter of pickNextNodes (:123-157), and the
left-padded binary id (:159-172).
"""

from __future__ import annotations

from typing import Dict, List, Set, TypeVar

from ..utils.javarand import JavaRandom
from ..utils.more_math import log2

T = TypeVar("T")


def to_binary_id(node, set_size: int) -> str:
    """Node id as a log2(setSize)-wide binary string
    (SanFerminHelper.toBinaryID)."""
    width = log2(set_size)
    s = format(node.node_id, "b")
    if len(s) > width:
        raise ValueError(f"id {node.node_id} does not fit in {width} bits")
    return s.rjust(width, "0")


class SanFerminHelper:
    """Tracks contacted nodes per level and computes own/candidate sets."""

    def __init__(self, n, all_nodes: List, rd: JavaRandom):
        self.n = n
        self.binary_id = to_binary_id(n, len(all_nodes))
        self.all_nodes = all_nodes
        self.used_nodes: Dict[int, Set[int]] = {}
        self.rd = rd
        self.current_level = log2(len(all_nodes))

    def _interval(self, level: int, swap_at_level: bool) -> tuple:
        """The shared halving loop of getOwnSet/getCandidateSet
        (SanFerminHelper.java:46-96); swap_at_level flips the branch when
        currLevel == level (candidate set)."""
        min_ = 0
        max_ = len(self.all_nodes)
        curr_level = 0
        while curr_level <= level and min_ <= max_:
            m = (max_ + min_) // 2
            c = self.binary_id[curr_level]
            if c == "0":
                if swap_at_level and curr_level == level:
                    min_ = m
                else:
                    max_ = m
            elif c == "1":
                if swap_at_level and curr_level == level:
                    max_ = m
                else:
                    min_ = m
            if max_ == min_:
                break
            if max_ - 1 == 0 or min_ == len(self.all_nodes):
                break
            curr_level += 1
        return min_, max_

    def get_own_set(self, level: int) -> List:
        min_, max_ = self._interval(level, swap_at_level=False)
        return self.all_nodes[min_:max_]

    def get_candidate_set(self, level: int) -> List:
        min_, max_ = self._interval(level, swap_at_level=True)
        return self.all_nodes[min_:max_]

    def is_candidate(self, node, level: int) -> bool:
        return node in self.get_candidate_set(level)

    def get_exact_candidate_node(self, level: int):
        own = self.get_own_set(level)
        idx = own.index(self.n)
        candidates = self.get_candidate_set(level)
        if idx >= len(candidates):
            raise RuntimeError("no exact candidate")
        return candidates[idx]

    def pick_next_nodes(self, level: int, how_many: int) -> List:
        """Return not-yet-contacted candidates at `level`, own-index node
        first, then up to how_many more by (post-removal) index — including
        the reference's index-shift quirk after the first removal
        (SanFerminHelper.java:123-157) — shuffled."""
        candidate_set = list(self.get_candidate_set(level))
        own_set = self.get_own_set(level)
        try:
            idx = own_set.index(self.n)
        except ValueError:
            raise RuntimeError("node not in its own set")
        if len(own_set) < idx:
            raise RuntimeError("bad own-set index")

        new_list = []
        used = self.used_nodes.get(level, set())
        if idx not in used:
            new_list.append(candidate_set[idx])
            del candidate_set[idx]
            used.add(idx)

        count = 0
        for i in range(len(candidate_set)):
            if i in used:
                continue
            if count >= how_many:
                break
            used.add(i)
            new_list.append(candidate_set[i])
            count += 1

        self.used_nodes[level] = used
        self.rd.shuffle(new_list)
        return new_list

"""P2PHandel: Handel-style aggregation on a generic P2P graph — nodes
periodically push missing-signature sets to the neighbour with the largest
diff, with four wire-compression strategies.

Reference semantics: protocols/P2PHandel.java (State/SendSigs messages
:119-253, range-compression size model :160-229, node logic :255-480, init
tasks :482-509).  BitSet aliasing quirks (checkSigs2 mutating a message's
shared bitset) are mirrored via utils.bitset.JavaBitSet.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, List, Optional, Set

from ..core.params import WParameters, register_protocol
from ..core.registries import registry_network_latencies, registry_node_builders
from ..oracle.messages import Message
from ..oracle.network import Network, Protocol
from ..oracle.p2p import P2PNetwork, P2PNode
from ..utils.bitset import JavaBitSet
from ..utils.more_math import log2


class SendSigsStrategy(enum.Enum):
    all = "all"  # send all signatures, ignore peer state
    dif = "dif"  # send just the diff
    cmp_all = "cmp_all"  # send all, compressed
    cmp_diff = "cmp_diff"  # compressed; diff if it compresses smaller


@dataclasses.dataclass
class P2PHandelParameters(WParameters):
    signing_node_count: int = 100
    relaying_node_count: int = 20
    threshold: int = 99
    connection_count: int = 40
    pairing_time: int = 100
    sigs_send_period: int = 1000
    double_aggregate_strategy: bool = True
    send_sigs_strategy: str = "dif"
    send_state: bool = False
    node_builder_name: Optional[str] = None
    network_latency_name: Optional[str] = None

    @property
    def strategy(self) -> SendSigsStrategy:
        s = self.send_sigs_strategy
        return s if isinstance(s, SendSigsStrategy) else SendSigsStrategy(s)


class State(Message):
    """Peer-state broadcast; trailing zero bits are implicit for sizing
    (P2PHandel.java:119-141)."""

    def __init__(self, who: "P2PHandelNode"):
        self.desc = who.verified_signatures.clone()
        self.who = who

    def size(self) -> int:
        return max(1, self.desc.length() // 8)

    def action(self, network, from_node, to_node):
        to_node.on_peer_state(self)


class SendSigs(Message):
    def __init__(self, sigs: JavaBitSet, sig_count: Optional[int] = None):
        if sig_count is None:
            sig_count = sigs.cardinality()
        self.sigs = sigs.clone()
        self._size = max(1, sig_count)

    def size(self) -> int:
        return self._size

    def action(self, network, from_node, to_node):
        to_node.on_new_sig(from_node, self.sigs)


class P2PHandelNode(P2PNode):
    __slots__ = ("verified_signatures", "to_verify", "peers_state", "just_relay", "_p")

    def __init__(self, p: "P2PHandel", just_relay: bool):
        super().__init__(p.network().rd, p.nb)
        self._p = p
        self.verified_signatures = JavaBitSet()
        self.to_verify: Set[JavaBitSet] = set()
        self.peers_state: Dict[int, JavaBitSet] = {}
        self.just_relay = just_relay
        if not just_relay:
            self.verified_signatures.set(self.node_id, True)

    def start(self) -> None:
        super().start()
        # peer states start empty: we don't know who is a validator
        for pr in self.peers:
            self.peers_state[pr.node_id] = JavaBitSet()

    def on_peer_state(self, state: State) -> None:
        """Asynchronous, so the state can be an old one (P2PHandel.java:281-283)."""
        self.peers_state[state.who.node_id].or_(state.desc)

    def update_verified_signatures(self, sigs: JavaBitSet) -> None:
        """(P2PHandel.java:290-303)."""
        p, net = self._p.params, self._p.network()
        old_card = self.verified_signatures.cardinality()
        self.verified_signatures.or_(sigs)
        new_card = self.verified_signatures.cardinality()
        if new_card > old_card:
            if self.done_at == 0 and self.verified_signatures.cardinality() >= p.threshold:
                self.done_at = net.time
                self.send_final_sig_to_peers()
            elif self.done_at == 0 and p.send_state:
                self.send_state_to_peers()

    def send_final_sig_to_peers(self) -> None:
        """Final aggregation to every peer still short of threshold; size 1
        (P2PHandel.java:305-317)."""
        p, net = self._p.params, self._p.network()
        dest = []
        for pr in self.peers:
            if self.peers_state[pr.node_id].cardinality() < p.threshold:
                dest.append(pr)
                self.peers_state[pr.node_id].or_(self.verified_signatures)
        net.send(SendSigs(self.verified_signatures, 1), self, dest)

    def send_state_to_peers(self) -> None:
        net = self._p.network()
        net.send(State(self), self, self.peers)

    def on_new_sig(self, from_node, sigs: JavaBitSet) -> None:
        self.peers_state[from_node.node_id].or_(sigs)
        self.to_verify.add(sigs)

    def send_sigs(self) -> None:
        """Periodic push to the peer with the largest diff
        (P2PHandel.java:336-354)."""
        net = self._p.network()
        if self.done_at > 0:
            return
        dest = self._best_dest()
        if dest is None:
            return
        to_send = self._diff(dest)
        self.peers_state[dest.node_id].or_(self.verified_signatures)
        ss = self._create_send_sigs(to_send)
        net.send(ss, self, dest)

    def _diff(self, peer: "P2PHandelNode") -> JavaBitSet:
        needed = self.verified_signatures.clone()
        needed.and_not(self.peers_state[peer.node_id])
        return needed

    def _best_dest(self) -> Optional["P2PHandelNode"]:
        dest = None
        dest_size = 0
        for pr in self.peers:
            size = self._diff(pr).cardinality()
            if size > dest_size:
                dest = pr
                dest_size = size
        return dest

    def _create_send_sigs(self, to_send: JavaBitSet) -> SendSigs:
        """(P2PHandel.java:389-404)."""
        p = self._p
        strat = p.params.strategy
        if strat is SendSigsStrategy.dif:
            return SendSigs(to_send)
        elif strat is SendSigsStrategy.cmp_all:
            return SendSigs(
                self.verified_signatures.clone(), p.compressed_size(self.verified_signatures)
            )
        elif strat is SendSigsStrategy.cmp_diff:
            s1 = p.compressed_size(self.verified_signatures)
            s2 = p.compressed_size(to_send)
            return SendSigs(self.verified_signatures.clone(), min(s1, s2))
        else:
            return SendSigs(self.verified_signatures.clone())

    def check_sigs(self) -> None:
        if self._p.params.double_aggregate_strategy:
            self.check_sigs2()
        else:
            self.check_sigs1()

    def check_sigs1(self) -> None:
        """Strategy 1: verify the set with the most new signatures
        (P2PHandel.java:419-447)."""
        net = self._p.network()
        best = None
        best_v = 0
        for o1 in list(self.to_verify):
            oo1 = o1.clone()
            oo1.and_not(self.verified_signatures)
            v1 = oo1.cardinality()
            if v1 == 0:
                self.to_verify.discard(o1)
            elif v1 > best_v:
                best_v = v1
                best = o1
        if best is not None:
            self.to_verify.discard(best)
            t_best = best
            net.register_task(
                lambda: self.update_verified_signatures(t_best),
                net.time + self._p.params.pairing_time * 2,
                self,
            )

    def check_sigs2(self) -> None:
        """Strategy 2: aggregate everything and verify once.  NOTE: or-ing
        into the first element mutates a bitset possibly shared with other
        nodes' toVerify sets — reference aliasing kept
        (P2PHandel.java:455-479)."""
        net = self._p.network()
        agg = None
        for o1 in self.to_verify:
            if agg is None:
                agg = o1
            else:
                agg.or_(o1)
        self.to_verify.clear()
        if agg is not None:
            oo1 = agg.clone()
            oo1.and_not(self.verified_signatures)
            if oo1.cardinality() > 0:
                t_best = agg
                net.register_task(
                    lambda: self.update_verified_signatures(t_best),
                    net.time + self._p.params.pairing_time * 2,
                    self,
                )


@register_protocol("P2PHandel", P2PHandelParameters)
class P2PHandel(Protocol):
    def __init__(self, params: P2PHandelParameters):
        self.params = params
        self._network: P2PNetwork[P2PHandelNode] = P2PNetwork(params.connection_count, False)
        self.nb = registry_node_builders.get_by_name(params.node_builder_name)
        self._network.set_network_latency(
            registry_network_latencies.get_by_name(params.network_latency_name)
        )

    def compressed_size(self, sigs: JavaBitSet) -> int:
        """Ranged-aggregation size model (P2PHandel.java:160-197)."""
        if sigs.length() == self.params.signing_node_count:
            return 1
        first_one_at = -1
        sig_ct = 0
        pos = -1
        compressing = False
        was_compressing = False
        while True:
            pos += 1
            if pos > sigs.length() + 1:
                break
            if not sigs.get(pos):
                compressing = False
                sig_ct -= self._merge_ranges(first_one_at, pos)
                first_one_at = -1
            elif compressing:
                if (pos + 1) % 2 == 0:
                    compressing = False
                    was_compressing = True
            else:
                sig_ct += 1
                if pos % 2 == 0:
                    compressing = True
                    if not was_compressing:
                        first_one_at = pos
                    else:
                        was_compressing = False
        return sig_ct

    def _merge_ranges(self, first_one_at: int, pos: int) -> int:
        """(P2PHandel.java:204-229)."""
        if first_one_at < 0:
            return 0
        if first_one_at % 4 != 0:
            first_one_at += 4 - (first_one_at % 4)
        range_ct = (pos - first_one_at) // 2
        if range_ct < 2:
            return 0
        max_ = log2(range_ct)
        while max_ > 0:
            size_in_blocks = 2 ** max_
            size = size_in_blocks * 2
            if first_one_at % size == 0:
                return (size_in_blocks - 1) + self._merge_ranges(first_one_at + size, pos)
            max_ -= 1
        return 0

    def init(self) -> None:
        """(P2PHandel.java:482-509)."""
        p, net = self.params, self._network
        just_relay: Set[int] = set()
        while len(just_relay) < p.relaying_node_count:
            just_relay.add(net.rd.next_int(p.signing_node_count + p.relaying_node_count))

        for i in range(p.signing_node_count + p.relaying_node_count):
            n = P2PHandelNode(self, i in just_relay)
            net.add_node(n)
            if p.send_state:
                net.register_task(n.send_state_to_peers, 1, n)
            net.register_periodic_task(n.send_sigs, 1, p.sigs_send_period, n)
            net.register_conditional_task(
                n.check_sigs, 1, p.pairing_time, n,
                (lambda nn: lambda: len(nn.to_verify) > 0)(n),
                (lambda nn: lambda: nn.done_at == 0)(n),
            )
        net.set_peers()

    def network(self) -> Network:
        return self._network

    def copy(self) -> "P2PHandel":
        return P2PHandel(self.params)


def default_params(
    nodes: int,
    dead_ratio: float = 0.0,
    connection_count: Optional[int] = None,
    tor=None,
    loc=None,
) -> P2PHandelParameters:
    """P2PHandelScenarios.defaultParams (P2PHandelScenarios.java:261-277).
    dead_ratio / tor / loc are accepted and ignored, exactly like the
    reference (its own defaultParams never reads them)."""
    ts = int(nodes * 0.99)
    from ..core.registries import CITIES, builder_name

    nb = builder_name(CITIES, True, 0)
    nl = "NetworkLatencyByCityWJitter"
    cc = 10 if connection_count is None else connection_count
    return P2PHandelParameters(nodes, 0, ts, cc, 4, 20, True, "dif", False, nb, nl)

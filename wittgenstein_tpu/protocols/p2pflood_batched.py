"""Batched P2PFlood: flood routing as masked frontier propagation.

Same behavior as protocols/P2PFlood.java on the batched engine:

  * the random graph is built host-side by the oracle P2PNetwork (same
    JavaRandom stream → identical topology) and baked into a padded
    `[N, max_peers]` adjacency array;
  * dedup-and-forward (messages/FloodMessage.java:47-56) becomes a
    per-tick "winner" reduction: of all ring slots delivering the same
    (node, flood) pair this millisecond, the lowest slot wins, marks the
    pair received, and forwards to every peer except the winning sender —
    per-tick work scales with ring capacity × max_peers, not N × M;
  * doneAt is set when a node holds msg_count distinct floods
    (P2PFlood.java:39-43).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..core.node import build_node_columns
from ..core.registries import registry_network_latencies, registry_node_builders
from ..engine import BatchedNetwork, BatchedProtocol, Emission
from .p2pflood import P2PFlood, P2PFloodParameters


def build_adjacency(net) -> np.ndarray:
    """Pad the oracle P2P graph into [N, max_degree] int32, -1 = no peer."""
    degrees = [len(n.peers) for n in net.all_nodes]
    max_deg = max(degrees) if degrees else 0
    adj = np.full((len(net.all_nodes), max_deg), -1, dtype=np.int32)
    for i, n in enumerate(net.all_nodes):
        for j, p in enumerate(n.peers):
            adj[i, j] = p.node_id
    return adj


class BatchedP2PFlood(BatchedProtocol):
    MSG_TYPES = ["FLOOD"]
    PAYLOAD_WIDTH = 1  # flood id
    TICK_INTERVAL = None  # pure message protocol: engine may skip empty ms

    def __init__(self, params: P2PFloodParameters, adjacency: np.ndarray, senders):
        self.params = params
        self.adj = jnp.asarray(adjacency, jnp.int32)
        self.senders = list(senders)  # flood id -> origin node id
        self.n_nodes = params.node_count
        self.n_floods = len(self.senders)

    def msg_size(self, mtype: int) -> int:
        return 1  # FloodMessage(1, ...) in P2PFlood.init

    def proto_init(self, n_nodes: int):
        received = jnp.zeros((n_nodes, self.n_floods), dtype=bool)
        # senders pre-mark their own message (sendPeers -> addToReceived)
        received = received.at[
            jnp.asarray(self.senders, jnp.int32), jnp.arange(self.n_floods)
        ].set(True)
        return {"received": received}

    # -- helpers -------------------------------------------------------------
    def _forward(self, state, src, fid, mask, exclude):
        """Emission: src[K] forwards flood fid[K] to all its peers except
        `exclude[K]`, with FloodMessage local/per-peer delays."""
        p = self.params
        k = src.shape[0]
        n_peers = self.adj.shape[1]
        src_r = jnp.repeat(src, n_peers)
        fid_r = jnp.repeat(fid, n_peers)
        mask_r = jnp.repeat(mask, n_peers)
        dest = self.adj[src].reshape(-1)
        excl_r = jnp.repeat(exclude, n_peers)
        ok = mask_r & (dest >= 0) & (dest != excl_r)
        # sendPeers/_send_multi spacing: k-th *actual* destination leaves at
        # base + k*(delay+1) when delay_between_sends > 0 (Network.java:
        # 449-467) — rank over the compacted send list, so an excluded
        # sender mid-list leaves no spacing gap
        base = state.time + 1 + p.delay_before_resent
        rank = (jnp.cumsum(ok.reshape(k, n_peers), axis=1) - 1).reshape(-1)
        spacing = (p.delay_between_sends + 1) if p.delay_between_sends > 0 else 0
        send_time = jnp.broadcast_to(base, rank.shape) + rank.astype(jnp.int32) * spacing
        return Emission(
            mask=ok,
            from_idx=src_r,
            to_idx=jnp.maximum(dest, 0),
            mtype=self.mtype("FLOOD"),
            payload=fid_r[:, None],
            send_time=send_time,
        )

    def initial_emissions(self, net, state):
        src = jnp.asarray(self.senders, jnp.int32)
        fid = jnp.arange(self.n_floods, dtype=jnp.int32)
        mask = jnp.ones(self.n_floods, dtype=bool)
        exclude = jnp.full(self.n_floods, -1, jnp.int32)  # senders flood all peers
        # sendPeers base time is time+1+localDelay with time=0 (P2PNetwork.java:127-133)
        return [self._forward(state, src, fid, mask, exclude)]

    def deliver(self, net, state, deliver_mask):
        c = deliver_mask.shape[0]
        to = state.msg_to
        fid = state.msg_payload[:, 0]
        received = state.proto["received"]
        fresh = deliver_mask & ~received[to, fid]

        # winner per (node, flood): lowest delivering slot this tick
        slot = jnp.arange(c, dtype=jnp.int32)
        winner = jnp.full((self.n_nodes, self.n_floods), c, jnp.int32)
        winner = winner.at[to, fid].min(jnp.where(fresh, slot, c), mode="drop")
        is_winner = fresh & (winner[to, fid] == slot)

        received = received.at[to, fid].max(fresh, mode="drop")
        count = jnp.sum(received, axis=1).astype(jnp.int32)
        # onFlood: done when msg_count distinct messages held (P2PFlood.java:39-43)
        done = (count >= self.params.msg_count) & (state.done_at == 0) & ~state.down
        done_at = jnp.where(done, state.time, state.done_at)

        em = self._forward(state, to, fid, is_winner, state.msg_from)
        state = state._replace(proto={"received": received}, done_at=done_at)
        return state, [em]

    def all_done(self, state):
        live = ~state.down
        return jnp.all(jnp.where(live, state.done_at > 0, True))


def make_p2pflood(
    params: Optional[P2PFloodParameters] = None,
    capacity: int = 1 << 13,
    seed: int = 0,
    telemetry=None,
):
    """Host-side construction: run the oracle init() for the graph + sender
    choice (same RNG stream), then bake into the batched engine."""
    params = params or P2PFloodParameters()
    oracle = P2PFlood(params)
    oracle.init()
    net_o = oracle.network()
    adj = build_adjacency(net_o)
    # oracle sender order: nodes whose own message is pre-marked received
    senders = [
        n.node_id for n in net_o.all_nodes if len(n.get_msg_received(-1)) > 0
    ]
    latency = registry_network_latencies.get_by_name(params.network_latency_name)
    city_index = getattr(latency, "city_index", None)
    cols = build_node_columns(net_o.all_nodes, city_index)
    proto = BatchedP2PFlood(params, adj, senders)
    # flat mode: flood waves are send-synchronized (delay_between_sends can
    # be 0 and latencies fixed), so a whole wave can land on ONE tick —
    # per-arrival-tick bucketing would need wheel rows as wide as the ring
    net = BatchedNetwork(
        proto, latency, params.node_count, capacity=capacity, wheel_rows=0,
        telemetry=telemetry,
    )
    # dead nodes are down from t=0 (P2PFloodNode ctor stop()), before the
    # initial floods go out
    down = np.array([n.is_down() for n in net_o.all_nodes])
    state = net.init_state(
        cols, seed=seed, proto=proto.proto_init(params.node_count), down=down
    )
    if params.msg_count == 1:
        # the single sender is done at t=1 (P2PFlood.init)
        done0 = np.zeros(params.node_count, dtype=np.int32)
        done0[senders[0]] = 1
        state = state._replace(done_at=jnp.asarray(done0))
    return net, state

"""Batched Handel: the north-star protocol on the TPU engine.

Re-expression of protocols/Handel.java for the batched time-stepped core.
State is packed uint32 bitsets in the XOR-relative layout (ops.bitops):
bit j of node i's vector is node i^j, so every node shares the same level
geometry — level l = bit block [2^(l-1), 2^l) (Handel.allSigsAtLevel,
Handel.java:634-647, becomes a static mask), and re-addressing a level-l
contribution from sender s into receiver i's space is the bit permutation
j -> j ^ r0 with r0 = (i^s) & (2^(l-1)-1).

Memory layout (what makes 4096 nodes x 32 replicas fit in HBM): level l's
outgoing content is only bits [0, 2^(l-1)) — w_l = max(1, 2^(l-1)/32)
words — so all per-level buffers are packed into ONE flat word axis of
W_total = sum_l w_l words (132 for n=4096) instead of a uniform
[L, n_words/2] block (6.3x smaller, and it avoids XLA's (8,128) tile
padding on small minor dimensions).

Three buffer stages per (receiver, level), mirroring the reference's
message + toVerifyAgg + pairing pipeline:

  1. in-flight channel: D slots keyed by ((arrival-now)<<rel_bits | rel),
     slot = arrival mod D, earliest arrival wins; displaced sends are
     lost — Handel's periodic dissemination re-offers content every
     period, exactly the redundancy the reference relies on for its own
     dropped/filtered messages.  Content is stored in SENDER bit space.
  2. candidate buffer (toVerifyAgg, Handel.java:447): K slots of arrived,
     not-yet-verified aggregate sigs in receiver block-local space,
     curated exactly like bestToVerify's pruning — a candidate survives
     only while sizeIfIncluded > |totalIncoming| and its sender is not
     blacklisted (Handel.java:592-612); arrivals beyond K displace the
     lowest-(sizeIfIncluded, -rank) entry.
  3. verification register: one in-progress verification per node;
     selection at time t commits its merge at t + pairingTime
     (checkSigs -> registerTask(updateVerifiedSignatures, now +
     nodePairingTime), Handel.java:833-836) — the node is busy meanwhile,
     preserving the 1-verification-per-pairingTime capacity model.

Semantics carried exactly (Handel.java refs):
  * windowed scoring: windowIndex = min rank in the queue, rank-based
    choice outside the window, score-based inside (bestToVerify,
    :566-630); score() = added-signature count with the
    non-intersecting/with-individuals cases (:650-664); exponential
    window adaptation ceil(*2)/floor(/4) clamped to [min, max] and the
    chosen level's size (WindowParameters/ScoringExp :150-210, applied at
    :823-825).
  * updateVerifiedSignatures (:686-750): blacklist on bad sigs;
    verifiedInd bit; the **improved guard** — lastAggVerified is only
    replaced/extended when |sig ∪ ind| > |ind|, so a verified aggregate
    can never shrink; totalIncoming = lastAgg | ind; fastPath burst to
    fast_path peers of the first higher level whose outgoing just
    completed (:738-742); doneAt when the cross-level union reaches the
    threshold (:747-749).
  * byzantineSuicide (:538-559): while un-blacklisted down Byzantine
    peers with rank inside windowIndex+window exist at a level, a forged
    full-block sig from one of them is returned as that level's
    bestToVerify result directly; verifying it wastes pairingTime and
    blacklists the sender (:687-694).
  * hiddenByzantine (:840-917): when the chosen best is at the top level,
    a valid single-bit sig from the lowest-rank down Byzantine peer not
    yet in totalIncoming competes by score; if it wins the node wastes a
    verification on a nearly-useless contribution.
  * uniform-random choice among per-level bests (chooseBestFromLevels,
    :788-790), extraCycle post-done dissemination (:331-338), done-node
    message filtering (msgFiltered, :752-756), desynchronizedStart,
    per-node pairing time scaled by speedRatio.

Distribution-parity approximations (deliberate, each noted inline):
  * reception ranks: the reference shuffles one global [N] permutation
    per receiver (setReceivingRanks :940-948); here rank(i, l, rel) is a
    counter-hash bijection over the level block scaled to the same [0, N)
    range.  The post-verification demotion (receptionRanks[from] +=
    nodeCount, :826-830) becomes a +N penalty whenever the sender's
    individual sig is already verified.
  * emission order (:991-1013) is a counter-hash offset + cycling cursor
    per level rather than the rank-derived emission lists; finished-peer
    bookkeeping (levelFinished/finishedPeers) is not tracked.
  * suicide-byz picks the lowest-block-index eligible peer, not the
    suicideBizAfter cursor order; hidden-byz re-attempts injection each
    selection instead of tracking the `last` candidate.
  * same-ms deliveries are simultaneous; per-ms LIFO order inside the
    oracle's buckets has no analog.

int32 packing guards: channel keys pack (arrival - now) << rel_bits | rel
and candidate sort keys pack sizeIfIncluded * 4N + rank, so node_count is
capped at 2^14 (16384) — far above the 4096-node north star — and
construction fails loudly beyond it rather than overflowing.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.node import Node, build_node_columns
from ..core.registries import registry_network_latencies, registry_node_builders
from ..engine import BatchedNetwork
from ..engine.rng import hash32
from ..ops.bitops import popcount_words, xor_shuffle
from ..utils.javarand import JavaRandom
from ._agg_batched import INT32_MAX, BitsetAggBase
from .handel import HandelParameters


class BatchedHandel(BitsetAggBase):
    CAND_SLOTS = 8  # K: arrived verification candidates per (receiver, level)

    def __init__(self, params: HandelParameters):
        self.params = params
        self._init_geometry(params.node_count)

    def msg_size(self, mtype: int) -> int:
        # Size = level + bit field + the signatures included + our own sig
        # (SendSigs, Handel.java:253-258)
        expected = 1 if mtype == 0 else 1 << (mtype - 1)
        return 1 + expected // 8 + 96 * 2

    # -- ranks ---------------------------------------------------------------
    def _base_rank(self, seed, ids, l: int, rel):
        """Counter-hash stand-in for the reference's global reception-rank
        permutation (setReceivingRanks, Handel.java:940-948): a bijection
        over the level block scaled to the [0, N) range so windowIndex +
        currWindowSize comparisons see reference-like rank spacing."""
        bs = 1 << (l - 1)
        r0 = rel & (bs - 1)
        mul = hash32(seed, ids, jnp.int32(l), jnp.int32(0xA11CE)) | jnp.int32(1)
        add = hash32(seed, ids, jnp.int32(l), jnp.int32(0xBEEF))
        perm = (r0 * mul + add) & (bs - 1)
        gap = self.n_nodes // bs
        if gap > 1:
            jit = hash32(seed, ids, rel, jnp.int32(l)) & jnp.int32(gap - 1)
            return perm * gap + jit
        return perm

    # -- state ---------------------------------------------------------------
    def proto_init(
        self,
        n_nodes: int,
        pairing: np.ndarray,
        start_at: np.ndarray,
        byz_rel: Optional[np.ndarray] = None,
    ):
        n, L, D, K = self.n_nodes, self.n_levels, self.CHANNEL_DEPTH, self.CAND_SLOTS
        own = np.zeros((n, self.n_words), dtype=np.uint32)
        own[:, 0] = 1  # bit 0 = own signature (level 0)
        if byz_rel is None:
            byz_rel = np.zeros((n, self.n_words), dtype=np.uint32)
        in_key, in_sig = self._channel_init(n)
        return {
            "agg": jnp.asarray(own),  # lastAggVerified per level block
            "ind": jnp.asarray(own),  # verifiedIndSignatures
            "inc": jnp.asarray(own),  # totalIncoming = agg | ind
            "bl": jnp.zeros((n, self.n_words), jnp.uint32),  # blacklist (rel)
            "byz": jnp.asarray(byz_rel),  # down Byzantine peers (rel space)
            # stage 1: in-flight channel (D arrival slots + 1 fresh backstop
            # per level; see BitsetAggBase)
            "in_key": in_key,
            "in_sig": in_sig,
            # stage 2: candidate buffer (toVerifyAgg)
            "cand_rank": jnp.full((n, (L - 1) * K), INT32_MAX, jnp.int32),
            "cand_rel": jnp.zeros((n, (L - 1) * K), jnp.int32),
            "cand_sig": jnp.zeros((n, K * self.w_total), jnp.uint32),
            # stage 3: verification register
            "ver_active": jnp.zeros(n, bool),
            "ver_done_t": jnp.zeros(n, jnp.int32),
            "ver_level": jnp.zeros(n, jnp.int32),
            "ver_rel": jnp.zeros(n, jnp.int32),
            "ver_bad": jnp.zeros(n, bool),
            "ver_sig": jnp.zeros((n, self.w_max), jnp.uint32),
            "window": jnp.full(n, self.params.window_initial, jnp.int32),
            "pos": jnp.zeros((n, L), jnp.int32),
            "added_cycle": jnp.full(n, self.params.extra_cycle, jnp.int32),
            "sigs_checked": jnp.zeros(n, jnp.int32),
            "msg_filtered": jnp.zeros(n, jnp.int32),
            "pairing": jnp.asarray(pairing, jnp.int32),
            "start_at": jnp.asarray(start_at, jnp.int32),
        }

    # -- tick phase 1: commit due verifications ------------------------------
    def _commit(self, net, state):
        """updateVerifiedSignatures at t = selection + pairingTime
        (Handel.java:686-750)."""
        p = self.params
        proto = state.proto
        t = state.time
        n, L = self.n_nodes, self.n_levels
        ids = jnp.arange(n, dtype=jnp.int32)

        due = proto["ver_active"] & (t >= proto["ver_done_t"])
        bad = due & proto["ver_bad"]
        good = due & ~proto["ver_bad"]

        # bad sig: blacklist the sender, nothing else (:687-694)
        rel = proto["ver_rel"]
        oh_full = self._onehot(rel, self.n_words)
        new_bl = jnp.where(bad[:, None], proto["bl"] | oh_full, proto["bl"])

        agg, ind, inc = proto["agg"], proto["ind"], proto["inc"]
        improved_any = jnp.zeros(n, bool)
        just_completed = jnp.zeros(n, bool)
        for l in range(1, L):
            m = good & (proto["ver_level"] == l)
            bs = 1 << (l - 1)
            r0 = rel & (bs - 1)
            sig_b = proto["ver_sig"][:, : self.w[l]]
            ind_b = self._blk(ind, l)
            agg_b = self._blk(agg, l)
            inc_b = self._blk(inc, l)
            sender = self._onehot(r0, self.w[l])

            new_ind_b = ind_b | sender
            # the improved guard: extend/replace lastAgg ONLY when the
            # candidate plus individuals is strictly larger (:716-722)
            improved2 = popcount_words(sig_b | new_ind_b) > popcount_words(new_ind_b)
            inter = popcount_words(agg_b & sig_b) > 0
            new_agg_b = jnp.where(
                (improved2 & inter)[:, None], sig_b, agg_b | jnp.where(
                    improved2[:, None], sig_b, jnp.uint32(0)
                )
            )
            new_inc_b = jnp.where(
                improved2[:, None], new_agg_b | new_ind_b, inc_b | sender
            )
            improved1 = popcount_words(inc_b & sender) == 0
            improved = m & (improved1 | improved2)

            before_full = popcount_words(inc_b) == bs
            after_full = popcount_words(new_inc_b) == bs
            just_completed = just_completed | (improved & after_full & ~before_full)
            improved_any = improved_any | improved

            ind = self._blk_write(ind, l, new_ind_b, m)
            agg = self._blk_write(agg, l, new_agg_b, m & improved2)
            inc = self._blk_write(inc, l, new_inc_b, m)

        total = popcount_words(inc)
        done_now = (
            improved_any & (state.done_at == 0) & ~state.down & (total >= p.threshold)
        )
        state = state._replace(
            done_at=jnp.where(done_now, t, state.done_at),
            proto=dict(
                proto,
                agg=agg,
                ind=ind,
                inc=inc,
                bl=new_bl,
                ver_active=proto["ver_active"] & ~due,
            ),
        )

        # fastPath burst (:738-742): on completing a level's incoming set,
        # contact fast_path peers of the first higher level whose outgoing
        # is now complete but whose incoming is not
        if p.fast_path > 0 and L > 1:
            out_done = jnp.stack(
                [
                    popcount_words(self._low(inc, l)) == (1 if l == 1 else 1 << (l - 1))
                    for l in range(1, L)
                ],
                axis=1,
            )
            inc_done = jnp.stack(
                [
                    popcount_words(self._blk(inc, l)) == (1 << (l - 1))
                    for l in range(1, L)
                ],
                axis=1,
            )
            target_ok = out_done & ~inc_done
            has_target = jnp.any(target_ok, axis=1)
            lsel = (jnp.argmax(target_ok, axis=1) + 1).astype(jnp.int32)
            fp_mask_base = just_completed & has_target
            fp = min(p.fast_path, max(1, self.n_nodes // 2))
            ks = jnp.arange(fp, dtype=jnp.int32)
            offset = hash32(state.seed, ids, lsel, t)
            for l in range(1, L):
                bs = 1 << (l - 1)
                fpl = min(fp, bs)
                m = fp_mask_base & (lsel == l)
                rel_fp = bs + ((offset[:, None] + ks[None, :fpl]) & (bs - 1))
                content = self._low(inc, l)
                state = self._send_level(
                    net,
                    state,
                    l,
                    jnp.repeat(m, fpl),
                    jnp.repeat(ids, fpl),
                    (ids[:, None] ^ rel_fp).reshape(-1),
                    jnp.repeat(content, fpl, axis=0),
                )
        return state

    # -- tick phase 2: deliver due channel slots into the candidate buffer ---
    def _channel_deliver(self, net, state):
        """onNewSig (Handel.java:752-786): due in-flight slots become
        verification candidates; the buffer keeps the top-K by
        (sizeIfIncluded, rank) among survivors of the curation rule."""
        proto = state.proto
        t = state.time
        n, L, D, K = self.n_nodes, self.n_levels, self.CHANNEL_DEPTH, self.CAND_SLOTS
        ids = jnp.arange(n, dtype=jnp.int32)
        rel_mask = (1 << self.rel_bits) - 1

        ss = D + 1
        in_key, due_all, empty_tpl = self._advance_channel(proto["in_key"])

        # (receiver traffic counters tick at send time in _send_level)
        d_by_level = due_all.reshape(n, L - 1, ss)
        started = t >= proto["start_at"]
        not_done = state.done_at == 0
        filtered = jnp.sum((d_by_level & ~not_done[:, None, None]).astype(jnp.int32), axis=(1, 2))

        new_cand_rank = proto["cand_rank"]
        new_cand_rel = proto["cand_rel"]
        new_cand_sig = proto["cand_sig"]
        inc, ind, bl = proto["inc"], proto["ind"], proto["bl"]

        for l in range(1, L):
            bs = 1 << (l - 1)
            w = self.w[l]
            keys = self._key_seg(in_key, l)  # [N, D]
            due = self._key_seg(due_all, l)
            rel = keys & rel_mask
            r0 = rel & (bs - 1)

            # onNewSig drop filters: not started, done, blacklisted sender
            bl_bit = self._getbit(bl, rel)
            accept = due & started[:, None] & not_done[:, None] & (bl_bit == 0)

            # shuffle sender-space content into receiver block-local space
            sig_new = xor_shuffle(self._sig_seg(proto["in_sig"], l, ss), r0)

            # rank + verified-sender demotion (receptionRanks += nodeCount)
            ind_bit = self._getbit(ind, rel)
            rank_new = self._base_rank(
                state.seed, ids[:, None], l, rel
            ) + self.n_nodes * ind_bit.astype(jnp.int32)
            rank_new = jnp.where(accept, rank_new, INT32_MAX)

            # merge [K existing + D new], keep top-K by (sizeIfIncluded, -rank)
            c_rank = proto["cand_rank"][:, (l - 1) * K : l * K]
            c_rel = proto["cand_rel"][:, (l - 1) * K : l * K]
            c_sig = self._sig_seg(proto["cand_sig"], l, K)

            all_rank = jnp.concatenate([c_rank, rank_new], axis=1)  # [N, K+D]
            all_rel = jnp.concatenate([c_rel, rel], axis=1)
            all_sig = jnp.concatenate([c_sig, sig_new], axis=1)  # [N, K+D, w]
            valid = all_rank != INT32_MAX

            inc_b = self._blk(inc, l)
            ind_b = self._blk(ind, l)
            inter = popcount_words(all_sig & inc_b[:, None, :]) > 0
            c = jnp.where(inter[..., None], all_sig, all_sig | inc_b[:, None, :])
            s = popcount_words(c | ind_b[:, None, :])  # sizeIfIncluded
            cur = popcount_words(inc_b)
            bl_all = self._getbit(bl, all_rel)
            keep = valid & (s > cur[:, None]) & (bl_all == 0)

            # sort key: higher sizeIfIncluded first, then lower rank;
            # bounded (s <= bs <= N/2, rank < 3N) so s*4N + rank fits int32
            r4 = 4 * self.n_nodes
            skey = jnp.where(
                keep, s * r4 + (r4 - 1 - jnp.minimum(all_rank, r4 - 1)), -1
            )
            order = jnp.argsort(-skey, axis=1)[:, :K]  # top-K
            top_keep = jnp.take_along_axis(skey, order, axis=1) >= 0
            sel_rank = jnp.where(
                top_keep, jnp.take_along_axis(all_rank, order, axis=1), INT32_MAX
            )
            sel_rel = jnp.take_along_axis(all_rel, order, axis=1)
            sel_sig = jnp.take_along_axis(all_sig, order[..., None], axis=1)

            new_cand_rank = new_cand_rank.at[:, (l - 1) * K : l * K].set(sel_rank)
            new_cand_rel = new_cand_rel.at[:, (l - 1) * K : l * K].set(sel_rel)
            o, wk = self.off[l] * K, self.w[l] * K
            new_cand_sig = new_cand_sig.at[:, o : o + wk].set(
                sel_sig.reshape(n, wk)
            )

        state = state._replace(
            proto=dict(
                proto,
                in_key=jnp.where(due_all, empty_tpl[None, :], in_key),
                cand_rank=new_cand_rank,
                cand_rel=new_cand_rel,
                cand_sig=new_cand_sig,
                msg_filtered=proto["msg_filtered"] + filtered,
            )
        )
        return state

    # -- tick phase 3: periodic dissemination --------------------------------
    def _dissemination(self, net, state):
        """Periodic doCycle over open levels (Handel.java:331-343, 452-480)."""
        p = self.params
        proto = state.proto
        t = state.time
        ids = jnp.arange(self.n_nodes, dtype=jnp.int32)

        start = proto["start_at"] + 1
        on_beat = (t >= start) & (
            lax.rem(t - start, jnp.int32(p.dissemination_period_ms)) == 0
        )
        is_done = state.done_at > 0
        may_send = on_beat & ~state.down & (~is_done | (proto["added_cycle"] > 0))
        new_added = jnp.where(
            on_beat & is_done & (proto["added_cycle"] > 0),
            proto["added_cycle"] - 1,
            proto["added_cycle"],
        )
        new_pos = proto["pos"]
        state = state._replace(proto=dict(proto, added_cycle=new_added))

        for l in range(1, self.n_levels):
            bs = 1 << (l - 1)
            opened = t >= (l - 1) * p.level_wait_time
            out_b = self._low(state.proto["inc"], l)
            complete = popcount_words(out_b) == (1 if l == 1 else bs)
            mask = may_send & (opened | complete)
            offset = hash32(state.seed, ids, jnp.int32(l)) & (bs - 1)
            rel = (bs + ((new_pos[:, l] + offset) & (bs - 1))).astype(jnp.int32)
            new_pos = new_pos.at[:, l].set(
                jnp.where(mask, new_pos[:, l] + 1, new_pos[:, l])
            )
            state = self._send_level(net, state, l, mask, ids, ids ^ rel, out_b)
        state = state._replace(proto=dict(state.proto, pos=new_pos))
        return state

    # -- tick phase 4: start new verifications (checkSigs) -------------------
    def _select(self, net, state):
        """bestToVerify per level + uniform cross-level choice + attacks +
        window adaptation (Handel.java:566-630, 788-837)."""
        p = self.params
        proto = state.proto
        t = state.time
        n, L, K = self.n_nodes, self.n_levels, self.CAND_SLOTS
        ids = jnp.arange(n, dtype=jnp.int32)

        free = (
            ~proto["ver_active"]
            & ~state.down
            & (t >= proto["start_at"] + 1)
        )
        window = proto["window"]
        inc, ind, agg, bl, byz = (
            proto["inc"],
            proto["ind"],
            proto["agg"],
            proto["bl"],
            proto["byz"],
        )

        # per-level bests
        has = []  # level has a candidate to verify
        b_rank = []  # chosen candidate's rank (for hidden-byz comparison)
        b_rel = []
        b_bad = []
        b_kidx = []  # candidate-buffer slot, -1 = injected
        b_widx = []  # windowIndex per level (hidden-byz re-run needs it)
        b_insc = []  # inside-window score of the choice, -1 = outside pick
        new_cand_rank = proto["cand_rank"]
        for l in range(1, L):
            bs = 1 << (l - 1)
            c_rank = proto["cand_rank"][:, (l - 1) * K : l * K]
            c_rel = proto["cand_rel"][:, (l - 1) * K : l * K]
            c_sig = self._sig_seg(proto["cand_sig"], l, K)
            valid = c_rank != INT32_MAX

            inc_b = self._blk(inc, l)
            ind_b = self._blk(ind, l)
            agg_b = self._blk(agg, l)

            # curation (bestToVerify :592-612): drop blacklisted senders and
            # candidates that can no longer grow the aggregate
            inter = popcount_words(c_sig & inc_b[:, None, :]) > 0
            cc = jnp.where(inter[..., None], c_sig, c_sig | inc_b[:, None, :])
            s = popcount_words(cc | ind_b[:, None, :])
            bl_bit = self._getbit(bl, c_rel)
            curated = valid & (s > popcount_words(inc_b)[:, None]) & (bl_bit == 0)
            # permanent removal, like replaceToVerifyAgg (:612-618)
            pruned_rank = jnp.where(curated, c_rank, INT32_MAX)
            new_cand_rank = new_cand_rank.at[:, (l - 1) * K : l * K].set(pruned_rank)

            # windowIndex = min rank over the (pre-curation valid) queue
            window_index = jnp.min(
                jnp.where(valid, c_rank, INT32_MAX), axis=1
            )
            win_hi = jnp.where(
                window_index < INT32_MAX - window, window_index + window, INT32_MAX
            )
            inside = curated & (c_rank <= win_hi[:, None])

            # score (:650-664)
            agg_card = popcount_words(agg_b)
            sig_card = popcount_words(c_sig)
            agg_inter = popcount_words(c_sig & agg_b[:, None, :]) > 0
            with_ind = popcount_words(c_sig | ind_b[:, None, :])
            score = jnp.where(
                agg_card[:, None] >= bs,
                0,
                jnp.where(
                    ~agg_inter,
                    agg_card[:, None] + sig_card,
                    jnp.maximum(0, with_ind - agg_card[:, None]),
                ),
            )
            in_score = jnp.where(inside & (score > 0), score, -1)
            k_in = jnp.argmax(in_score, axis=1)
            sc_in = jnp.take_along_axis(in_score, k_in[:, None], axis=1)[:, 0]
            exists_in = sc_in > 0

            out_rank = jnp.where(curated & ~inside, c_rank, INT32_MAX)
            k_out = jnp.argmin(out_rank, axis=1)
            rk_out = jnp.take_along_axis(out_rank, k_out[:, None], axis=1)[:, 0]
            exists_out = rk_out < INT32_MAX

            kidx = jnp.where(exists_in, k_in, k_out)
            lrank = jnp.where(
                exists_in,
                jnp.take_along_axis(c_rank, k_in[:, None], axis=1)[:, 0],
                rk_out,
            )
            lrel = jnp.take_along_axis(c_rel, kidx[:, None], axis=1)[:, 0]
            lhas = exists_in | exists_out
            lbad = jnp.zeros(n, bool)

            if p.byzantine_suicide:
                # createSuicideByzantineSig (:538-559): a forged full-block
                # sig from an eligible Byzantine peer short-circuits the
                # level's choice.  Eligible = down+byz, not blacklisted,
                # rank inside windowIndex + currWindowSize, queue non-empty.
                eligible = self._blk(byz, l) & ~self._blk(bl, l)
                any_valid = jnp.any(valid, axis=1)
                has_byz = popcount_words(eligible) > 0
                # lowest block-local index (stand-in for cursor order)
                m_byz = self._lowest_bit(eligible)
                rel_byz = bs + (m_byz & (bs - 1))
                rank_byz = self._base_rank(state.seed, ids, l, rel_byz)
                inject = (
                    has_byz
                    & any_valid
                    & (rank_byz < win_hi)
                )
                lhas = lhas | inject
                lbad = jnp.where(inject, True, lbad)
                lrel = jnp.where(inject, rel_byz, lrel)
                lrank = jnp.where(inject, rank_byz, lrank)
                kidx = jnp.where(inject, -1, kidx)

            has.append(lhas)
            b_rank.append(lrank)
            b_rel.append(lrel)
            b_bad.append(lbad)
            b_kidx.append(kidx)
            b_widx.append(window_index)
            b_insc.append(jnp.where(exists_in, sc_in, -1))

        has = jnp.stack(has, axis=1)  # [N, L-1]
        b_rank = jnp.stack(b_rank, axis=1)
        b_rel = jnp.stack(b_rel, axis=1)
        b_bad = jnp.stack(b_bad, axis=1)
        b_kidx = jnp.stack(b_kidx, axis=1)

        # chooseBestFromLevels: uniform among levels with a candidate (:788)
        vcount = jnp.sum(has, axis=1).astype(jnp.int32)
        can = free & (vcount > 0)
        rnd = (
            hash32(state.seed, t, ids, jnp.int32(0x5EED)).astype(jnp.uint32)
            >> jnp.uint32(8)
        ).astype(jnp.int32)
        pick = jnp.where(vcount > 0, lax.rem(rnd, jnp.maximum(vcount, 1)), 0)
        cum = jnp.cumsum(has, axis=1)
        lidx = jnp.argmax((cum == (pick + 1)[:, None]) & has, axis=1)  # 0-based
        level_sel = (lidx + 1).astype(jnp.int32)

        sel_rank = jnp.take_along_axis(b_rank, lidx[:, None], axis=1)[:, 0]
        sel_rel = jnp.take_along_axis(b_rel, lidx[:, None], axis=1)[:, 0]
        sel_bad = jnp.take_along_axis(b_bad, lidx[:, None], axis=1)[:, 0]
        sel_kidx = jnp.take_along_axis(b_kidx, lidx[:, None], axis=1)[:, 0]
        sel_single = jnp.zeros(n, bool)  # hidden-byz single-bit sig marker

        if p.hidden_byzantine and L > 1:
            # HiddenByzantine.attack (:840-917), modeled at selection time:
            # when the chosen best is at the top level, a valid single-bit
            # sig from the lowest-index down-byz peer not yet in
            # totalIncoming is appended and bestToVerify re-runs — the
            # injected sig wins when it lands inside the (possibly lowered)
            # window with a strictly higher score than any inside candidate
            # (appended last, so ties keep the incumbent, :578-584).
            l = L - 1
            bs = 1 << (l - 1)
            inc_b = self._blk(inc, l)
            ind_b = self._blk(ind, l)
            agg_b = self._blk(agg, l)
            eligible = self._blk(byz, l) & ~inc_b
            has_byz = popcount_words(eligible) > 0
            m_byz = self._lowest_bit(eligible)
            rel_byz = bs + (m_byz & (bs - 1))
            rank_byz = self._base_rank(state.seed, ids, l, rel_byz)

            # its score: single new bit (:650-664)
            agg_card = popcount_words(agg_b)
            oh = self._onehot(m_byz & (bs - 1), self.w[l])
            byz_inter = popcount_words(oh & agg_b) > 0
            byz_score = jnp.where(
                agg_card >= bs,
                0,
                jnp.where(
                    ~byz_inter,
                    agg_card + 1,
                    jnp.maximum(0, popcount_words(oh | ind_b) - agg_card),
                ),
            )
            widx_top = b_widx[-1]
            insc_top = b_insc[-1]
            new_widx = jnp.minimum(widx_top, rank_byz)
            win_hi = jnp.where(
                new_widx < INT32_MAX - window, new_widx + window, INT32_MAX
            )
            was_outside = insc_top < 0
            wins = (
                can
                & (level_sel == l)
                & (sel_kidx >= 0)
                & has_byz
                & (rank_byz < sel_rank)
                & (rank_byz <= win_hi)
                & (byz_score > 0)
                & (was_outside | (byz_score > insc_top))
            )
            sel_rel = jnp.where(wins, rel_byz, sel_rel)
            sel_rank = jnp.where(wins, rank_byz, sel_rank)
            sel_kidx = jnp.where(wins, -1, sel_kidx)
            sel_single = wins

        # window adaptation (:823-825): exp increase on correct, exp
        # decrease on bad, clamped to [min, max] and the level size
        grown = jnp.ceil(window.astype(jnp.float32) * p.window_increase_factor)
        shrunk = jnp.floor(window.astype(jnp.float32) / p.window_decrease_factor)
        adapted = jnp.where(sel_bad, shrunk, grown).astype(jnp.int32)
        adapted = jnp.clip(adapted, p.window_minimum, p.window_maximum)
        lsize = (jnp.uint32(1) << jnp.maximum(level_sel - 1, 0).astype(jnp.uint32)).astype(
            jnp.int32
        )
        new_window = jnp.where(can, jnp.minimum(adapted, lsize), window)

        # load the chosen sig into the verification register
        ver_sig = proto["ver_sig"]
        for l in range(1, L):
            bs = 1 << (l - 1)
            m = can & (level_sel == l)
            c_sig = self._sig_seg(proto["cand_sig"], l, K)
            safe_k = jnp.maximum(sel_kidx, 0)
            from_buf = jnp.take_along_axis(c_sig, safe_k[:, None, None], axis=1)[:, 0]
            full_block = jnp.full((n, self.w[l]), 0xFFFFFFFF, jnp.uint32)
            if bs < 32:
                full_block = jnp.full((n, 1), (1 << bs) - 1, jnp.uint32)
            single = self._onehot((sel_rel & (bs - 1)), self.w[l])
            sig_l = jnp.where(
                (sel_kidx >= 0)[:, None],
                from_buf,
                jnp.where(sel_single[:, None], single, full_block),
            )
            pad = jnp.zeros((n, self.w_max - self.w[l]), jnp.uint32)
            sig_l = jnp.concatenate([sig_l, pad], axis=1)
            ver_sig = jnp.where(m[:, None], sig_l, ver_sig)

        # remove the chosen buffer candidate (commit-time removal in the
        # reference; removal at selection avoids double-verification)
        flat_idx = (level_sel - 1) * K + jnp.maximum(sel_kidx, 0)
        remove = can & (sel_kidx >= 0)
        safe_row = jnp.where(remove, ids, n)
        new_cand_rank = new_cand_rank.at[safe_row, flat_idx].set(
            INT32_MAX, mode="drop"
        )

        state = state._replace(
            proto=dict(
                proto,
                cand_rank=new_cand_rank,
                ver_active=jnp.where(can, True, proto["ver_active"]),
                ver_done_t=jnp.where(
                    can, t + proto["pairing"], proto["ver_done_t"]
                ),
                ver_level=jnp.where(can, level_sel, proto["ver_level"]),
                ver_rel=jnp.where(can, sel_rel, proto["ver_rel"]),
                ver_bad=jnp.where(can, sel_bad, proto["ver_bad"]),
                ver_sig=ver_sig,
                window=new_window,
                sigs_checked=proto["sigs_checked"] + can.astype(jnp.int32),
            )
        )
        return state

    # -- engine hooks --------------------------------------------------------
    def tick(self, net, state):
        # deliver FIRST: it decrements every occupied channel key by one
        # tick, so anything sent later in this tick (fastPath bursts in
        # _commit, dissemination) is first decremented next tick and lands
        # exactly at its sampled arrival
        state = self._channel_deliver(net, state)
        state = self._commit(net, state)
        state = self._dissemination(net, state)
        state = self._select(net, state)
        return state

    def all_done(self, state):
        live = ~state.down
        return jnp.all(jnp.where(live, state.done_at > 0, True))


def make_handel(
    params: Optional[HandelParameters] = None,
    capacity: int = 8,  # generic ring unused by this protocol
    seed: int = 0,
):
    """Host-side construction: build the node population with the oracle's
    RNG stream (positions, speed ratios, down set), bake into the engine."""
    params = params or HandelParameters()
    n = params.node_count
    nb = registry_node_builders.get_by_name(params.node_builder_name)
    latency = registry_network_latencies.get_by_name(params.network_latency_name)
    rd = JavaRandom(0)

    from ..oracle.network import Network as ONetwork

    if params.bad_nodes is not None:
        bad_bits = params.bad_nodes
        bad = {i for i in range(n) if (bad_bits >> i) & 1}
    else:
        bad = ONetwork.choose_bad_nodes(rd, n, params.nodes_down)

    nodes = []
    start_at = np.zeros(n, dtype=np.int32)
    for i in range(n):
        if params.desynchronized_start != 0:
            start_at[i] = rd.next_int(params.desynchronized_start)
        nodes.append(Node(rd, nb))
    down = np.array([i in bad for i in range(n)])

    pairing = np.maximum(
        1, (params.pairing_time * np.array([nd.speed_ratio for nd in nodes]))
    ).astype(np.int32)

    proto = BatchedHandel(params)

    # Byzantine peers, as each receiver's rel-space bitset (nodes that are
    # both down and flagged byzantine — Handel.java:957-976 stops them and
    # the attacks impersonate them)
    byz_rel = None
    if params.byzantine_suicide or params.hidden_byzantine:
        byz_abs = np.zeros(proto.n_words, dtype=np.uint32)
        for i in sorted(bad):
            byz_abs[i // 32] |= np.uint32(1 << (i % 32))
        ids = np.arange(n, dtype=np.int32)
        byz_rel = np.asarray(
            xor_shuffle(jnp.broadcast_to(jnp.asarray(byz_abs), (n, proto.n_words)), ids)
        )

    city_index = getattr(latency, "city_index", None)
    cols = build_node_columns(nodes, city_index)
    net = BatchedNetwork(proto, latency, n, capacity=capacity)
    state = net.init_state(
        cols,
        seed=seed,
        proto=proto.proto_init(n, pairing, start_at, byz_rel),
        down=down,
    )
    return net, state

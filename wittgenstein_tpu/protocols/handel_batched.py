"""Batched Handel: the north-star protocol on the TPU engine.

Re-expression of protocols/Handel.java for the batched time-stepped core.
State is packed uint32 bitsets in the XOR-relative layout (ops.bitops):
bit j of node i's vector is node i^j, so every node shares the same level
geometry — level l = bit block [2^(l-1), 2^l) (Handel.allSigsAtLevel,
Handel.java:634-647, becomes a static mask), and re-addressing a level-l
contribution from sender s into receiver i's space is the bit permutation
j -> j ^ r0 with r0 = (i^s) & (2^(l-1)-1).

Program-size layout (what makes the 4096-node program compile): levels
are grouped into WIDTH BUCKETS (BitsetAggBase) and every phase runs once
per bucket on a stacked [N, nl, ...] level axis instead of once per
level; the per-level dissemination/fastPath send calls collapse into one
stacked send over [N * levels] rows.  Channel and candidate content are
flat per-bucket 2D arrays, so nothing pays XLA's (8,128) tile padding.

Three buffer stages per (receiver, level), mirroring the reference's
message + toVerifyAgg + pairing pipeline:

  1. in-flight channel: D slots keyed by (arrival<<rel_bits | rel),
     slot = arrival mod D, earliest arrival wins; displaced sends are
     counted in proto["displaced"] and lost — Handel's periodic
     dissemination re-offers content every period, exactly the redundancy
     the reference relies on for its own dropped/filtered messages.
     Content is stored in the RECEIVER's block-local bit space,
     re-addressed at send time (see BitsetAggBase._send_stacked).
  2. candidate buffer (toVerifyAgg, Handel.java:447): K slots of arrived,
     not-yet-verified aggregate sigs in receiver block-local space,
     curated exactly like bestToVerify's pruning — a candidate survives
     only while sizeIfIncluded > |totalIncoming| and its sender is not
     blacklisted (Handel.java:592-612); arrivals beyond K displace the
     lowest-(sizeIfIncluded, -rank) entry.
  3. verification register: one in-progress verification per node;
     selection at time t commits its merge at t + pairingTime
     (checkSigs -> registerTask(updateVerifiedSignatures, now +
     nodePairingTime), Handel.java:833-836) — the node is busy meanwhile,
     preserving the 1-verification-per-pairingTime capacity model.

Semantics carried exactly (Handel.java refs):
  * windowed scoring: windowIndex = min rank in the queue, rank-based
    choice outside the window, score-based inside (bestToVerify,
    :566-630); score() = added-signature count with the
    non-intersecting/with-individuals cases (:650-664); exponential
    window adaptation ceil(*2)/floor(/4) clamped to [min, max] and the
    chosen level's size (WindowParameters/ScoringExp :150-210, applied at
    :823-825).
  * updateVerifiedSignatures (:686-750): blacklist on bad sigs;
    verifiedInd bit; the **improved guard** — lastAggVerified is only
    replaced/extended when |sig ∪ ind| > |ind|, so a verified aggregate
    can never shrink; totalIncoming = lastAgg | ind; fastPath burst to
    fast_path peers of the first higher level whose outgoing just
    completed (:738-742); doneAt when the cross-level union reaches the
    threshold (:747-749).
  * byzantineSuicide (:538-559): while un-blacklisted down Byzantine
    peers with rank inside windowIndex+window exist at a level, a forged
    full-block sig from one of them is returned as that level's
    bestToVerify result directly; verifying it wastes pairingTime and
    blacklists the sender (:687-694).
  * hiddenByzantine (:840-917): when the chosen best is at the top level,
    a valid single-bit sig from the lowest-rank down Byzantine peer not
    yet in totalIncoming competes by score; if it wins the node wastes a
    verification on a nearly-useless contribution.
  * uniform-random choice among per-level bests (chooseBestFromLevels,
    :788-790), extraCycle post-done dissemination (:331-338), done-node
    message filtering (msgFiltered, :752-756), desynchronizedStart,
    per-node pairing time scaled by speedRatio.

Distribution-parity approximations (deliberate, each noted inline):
  * reception ranks: the reference shuffles one global [N] permutation
    per receiver (setReceivingRanks :940-948); here rank(i, l, rel) is a
    keyed pseudorandom PERMUTATION of [0, N) per receiver evaluated at
    the sender's absolute id (see _rank) — globally distinct ranks whose
    level-block order statistics match the reference's shuffle.  The
    post-verification demotion (receptionRanks[from] += nodeCount,
    :826-830) becomes a +N penalty whenever the sender's individual sig
    is already verified.
  * emission order (:991-1013) is a counter-hash offset + cycling cursor
    per level rather than the rank-derived emission lists; finished-peer
    bookkeeping (levelFinished/finishedPeers) is not tracked.
  * suicide-byz picks the lowest-block-index eligible peer, not the
    suicideBizAfter cursor order; hidden-byz re-attempts injection each
    selection instead of tracking the `last` candidate.
  * same-ms deliveries are simultaneous; per-ms LIFO order inside the
    oracle's buckets has no analog.

int32 packing guards: channel keys pack arrival << rel_bits | rel (sim
horizon 2^(31-rel_bits) ms — 524 s at 4096 nodes; later sends drop into
the displaced counter) and candidate sort keys pack sizeIfIncluded * 4N
+ rank, so node_count is capped at 2^14 (16384) — far above the
4096-node north star — and construction fails loudly beyond it.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.node import Node, build_node_columns
from ..core.registries import registry_network_latencies, registry_node_builders
from ..engine import BatchedNetwork
from ..engine.rng import hash32
from ..ops.bitops import popcount_words, xor_shuffle
from ..utils.javarand import JavaRandom
from ._agg_batched import INT32_MAX, BitsetAggBase
from .handel import HandelParameters


class BatchedHandel(BitsetAggBase):
    CAND_SLOTS = 8  # K: arrived verification candidates per (receiver, level)
    # D=32 arrival slots (vs the base class's 8): the r5 residual
    # decomposition (scripts/parity_residual.py + parity_ablate.py)
    # measured displacement as the dominant CDF bias — 25% of received
    # traffic displaced at D=8 costs +3.8%/+7.7% on P50/P90 done_at;
    # D=32 cuts displacement to ~10% and the residual to |2.7|% worst-
    # case.  Delivery cost is O(1) in D (only 2 slots can be due per
    # tick); the price is channel memory, ~3.7x on in_sig — ~106 MiB per
    # 4096-node replica, still 32+ replicas inside a v5e chip's HBM.
    CHANNEL_DEPTH = 32
    # r5 parity fix: _select reads the END-of-previous-tick candidate and
    # merge state (see tick() below).  Instance-overridable so the
    # profiling ablation (profiling/ablation.py) can price the snapshot
    # dicts the view costs per tick; False reproduces the pre-r5
    # one-tick-lead selection and is NOT parity-correct.
    BOUNDARY_VIEW = True
    # Candidate-score caching (the PR-8 lever): carry the per-slot derived
    # quantities _select needs — sizeIfIncluded, cardinality, |sig ∪ ind|
    # and the agg-intersection flag — as int32 leaves in state.proto,
    # refreshed only where delivery merges new content and where _commit
    # moves the aggregates.  The selection and the channel merge then read
    # cached int32 columns instead of re-popcounting every candidate's
    # signature words each tick (the top bytes-accessed term in
    # BUDGET.json).  End-of-tick invariant, pinned by simlint SL701 and
    # tests/test_score_cache.py: each cache leaf equals its from-scratch
    # recompute (_recompute_cache_dict) from (cand_sig*, inc, ind, agg).
    # False restores the uncached program, leaf-for-leaf identical to the
    # pre-cache tree (the ablation's score_cache_off lever).
    SCORE_CACHE = True
    CACHE_LEAF_NAMES = ("cand_s", "cand_card", "cand_wind", "cand_aggi")

    def __init__(self, params: HandelParameters):
        self.params = params
        if params.channel_depth is not None:
            if params.channel_depth <= 0:
                raise ValueError(
                    f"channel_depth={params.channel_depth} must be positive"
                )
            self.CHANNEL_DEPTH = params.channel_depth  # instance override
        if params.cand_slots is not None:
            if params.cand_slots <= 0:
                raise ValueError(
                    f"cand_slots={params.cand_slots} must be positive"
                )
            self.CAND_SLOTS = params.cand_slots  # instance override
        self._init_geometry(params.node_count)
        self.DERIVED_CACHE_LEAVES = (
            self.CACHE_LEAF_NAMES if self.SCORE_CACHE else ()
        )
        # blacklist + byzantine bitsets are carried only when an attack can
        # ever set a bit in them (byzantineSuicide writes bl, both attacks
        # read byz); attack-free replicas — the flagship density config —
        # drop both [N, n_words] planes from the carried state entirely.
        # Every read site is gated on this flag, so the attack-free program
        # is the all-zero-bl program with the (no-op) bl terms elided.
        self.track_bad = bool(
            params.byzantine_suicide or params.hidden_byzantine
        )
        self.NARROW_LEAVES = self._narrow_plan()

    def _narrow_plan(self) -> tuple:
        """NARROW_LEAVES for this instance's geometry (engine.density,
        docs/density.md).  Every bound is provable from static parameters:

          cand_rank  rank = per-receiver permutation of [0, N) plus the
                     +N verified-sender demotion -> < 2N; INT32_MAX empty
                     sentinel (stored as the narrow dtype max)
          cand_rel / ver_rel  relative peer ids, < N
          ver_level / fp_level  level numbers, <= L-1
          fp_left    fastPath burst countdown, <= min(fast_path, N/2)
          window     clamped to [window_minimum, window_maximum] and the
                     selected level's size
          cand_s / cand_card / cand_wind  popcounts over one level block
                     (block size <= N/2; N is a safe static bound)
          cand_aggi  boolean flag carried as an integer

        Leaves whose bound already needs int32 are omitted (narrowing
        would be a no-op); widen_proto/narrow_proto skip absent leaves, so
        the cache entries are inert when SCORE_CACHE is off."""
        from ..engine.density import NarrowLeaf, narrowest_int

        p, n, L = self.params, self.n_nodes, self.n_levels
        fp_max = max(1, min(p.fast_path, max(1, n // 2)))
        bounds = (
            ("cand_rank", 2 * n - 1, True),
            ("cand_rel", max(1, n - 1), False),
            ("ver_level", max(1, L - 1), False),
            ("ver_rel", max(1, n - 1), False),
            ("fp_level", max(1, L - 1), False),
            ("fp_left", fp_max, False),
            ("window", max(p.window_initial, p.window_maximum), False),
            ("cand_s", n, False),
            ("cand_card", n, False),
            ("cand_wind", n, False),
            ("cand_aggi", 1, False),
        )
        leaves = []
        for name, bound, sentinel in bounds:
            dt = narrowest_int(bound, reserve_sentinel=sentinel)
            if dt.itemsize < 4:
                leaves.append(NarrowLeaf(name, dt.name, bound, sentinel))
        return tuple(leaves)

    def msg_size(self, mtype: int) -> int:
        # Size = level + bit field + the signatures included + our own sig
        # (SendSigs, Handel.java:253-258)
        expected = 1 if mtype == 0 else 1 << (mtype - 1)
        return 1 + expected // 8 + 96 * 2

    # -- ranks ---------------------------------------------------------------
    def _rank(self, seed, ids, level, rel):
        """Stand-in for the reference's global reception-rank permutation
        (setReceivingRanks, Handel.java:940-948): one pseudorandom
        PERMUTATION of [0, N) per receiver, evaluated at the sender's
        absolute id.  Three keyed multiply/xorshift/add rounds over the
        n-bit domain — each round is bijective mod 2^n (odd multiplier,
        xorshift, add), so ranks are globally distinct per receiver and a
        level block's ranks have the order statistics of a uniform draw
        WITHOUT replacement from [0, N), matching the reference's shuffle.
        (The r4 stratified construction halved E[min rank] = windowIndex —
        measured -2% doneAt bias; see scripts/parity_residual.py.)

        ids/level/rel broadcast together; level may be a static int or a
        stacked [.., L-1, ..] axis."""
        level = jnp.asarray(level, jnp.int32)
        bs = jnp.asarray(self.lv_bs)[level - 1]
        r0 = rel & (bs - 1)
        # sender's absolute id: level-l peers of receiver i are i ^ j for
        # bit index j in [bs, 2*bs)
        x = (jnp.asarray(ids, jnp.int32) ^ (bs + r0)).astype(jnp.uint32)
        mask = jnp.uint32(self.n_nodes - 1)
        nbits = self.n_nodes.bit_length() - 1
        s1 = max(1, nbits // 2)
        x &= mask
        for rnd in range(3):
            mul = hash32(seed, ids, jnp.int32(0xA11CE + rnd)).astype(jnp.uint32) | jnp.uint32(1)
            add = hash32(seed, ids, jnp.int32(0xBEEF + rnd)).astype(jnp.uint32)
            x = (x * mul) & mask
            x = x ^ (x >> jnp.uint32(s1 + (rnd & 1)))
            x = (x + add) & mask
        return x.astype(jnp.int32)

    def _dyn_full_block(self, bs, w_pad: int):
        """[..,] dynamic block sizes -> [.., w_pad] all-ones-below-bs words."""
        bits = jnp.clip(
            bs[..., None] - 32 * jnp.arange(w_pad, dtype=jnp.int32), 0, 32
        )
        m = (jnp.uint32(1) << (bits & 31).astype(jnp.uint32)) - 1
        return jnp.where(bits >= 32, jnp.uint32(0xFFFFFFFF), m)

    # -- state ---------------------------------------------------------------
    def proto_init(
        self,
        n_nodes: int,
        pairing: np.ndarray,
        start_at: np.ndarray,
        byz_rel: Optional[np.ndarray] = None,
    ):
        n, L, K = self.n_nodes, self.n_levels, self.CAND_SLOTS
        own = np.zeros((n, self.n_words), dtype=np.uint32)
        own[:, 0] = 1  # bit 0 = own signature (level 0)
        in_key, in_sigs = self._channel_init(n)
        cand_sigs = {
            f"cand_sig{i}": jnp.zeros((n, b.nl * K * b.w_pad), jnp.uint32)
            for i, b in enumerate(self.buckets)
        }
        proto = {
            "agg": jnp.asarray(own),  # lastAggVerified per level block
            "ind": jnp.asarray(own),  # verifiedIndSignatures
            "inc": jnp.asarray(own),  # totalIncoming = agg | ind
            # stage 1: in-flight channel (D arrival slots + 1 fresh backstop
            # per level; see BitsetAggBase)
            "in_key": in_key,
            **in_sigs,
            "displaced": jnp.int32(0),
            # stage 2: candidate buffer (toVerifyAgg)
            "cand_rank": jnp.full((n, (L - 1) * K), INT32_MAX, jnp.int32),
            "cand_rel": jnp.zeros((n, (L - 1) * K), jnp.int32),
            **cand_sigs,
            # stage 3: verification register
            "ver_active": jnp.zeros(n, bool),
            "ver_done_t": jnp.zeros(n, jnp.int32),
            "ver_level": jnp.zeros(n, jnp.int32),
            "ver_rel": jnp.zeros(n, jnp.int32),
            "ver_bad": jnp.zeros(n, bool),
            "ver_sig": jnp.zeros((n, self.w_max), jnp.uint32),
            # fastPath burst register: peers left to contact, level, offset
            "fp_left": jnp.zeros(n, jnp.int32),
            "fp_level": jnp.zeros(n, jnp.int32),
            "fp_off": jnp.zeros(n, jnp.int32),
            "window": jnp.full(n, self.params.window_initial, jnp.int32),
            "pos": jnp.zeros((n, L), jnp.int32),
            "added_cycle": jnp.full(n, self.params.extra_cycle, jnp.int32),
            "sigs_checked": jnp.zeros(n, jnp.int32),
            "msg_filtered": jnp.zeros(n, jnp.int32),
            "pairing": jnp.asarray(pairing, jnp.int32),
            "start_at": jnp.asarray(start_at, jnp.int32),
        }
        if self.track_bad:
            # blacklist (rel space) + down Byzantine peers (rel space) —
            # carried only when an attack can set them (see __init__)
            proto["bl"] = jnp.zeros((n, self.n_words), jnp.uint32)
            if byz_rel is None:
                byz_rel = np.zeros((n, self.n_words), dtype=np.uint32)
            proto["byz"] = jnp.asarray(byz_rel)
        if self.SCORE_CACHE:
            proto.update(self._recompute_cache_dict(proto))
        return self.narrow_proto(proto)

    # -- candidate-score caches (SCORE_CACHE) --------------------------------
    def _recompute_cache_dict(self, proto) -> dict:
        """From-scratch values of the four candidate-score cache leaves,
        computed only from (cand_sig*, inc, ind, agg) — the oracle the
        end-of-tick invariant is checked against (simlint SL701) and the
        initializer for proto_init.  Per slot k of (receiver, level):
          cand_s    = sizeIfIncluded (bestToVerify's curation quantity,
                      Handel.java:592-612): |merge(sig, inc) ∪ ind|
          cand_card = |sig|
          cand_wind = |sig ∪ ind|   (the score's with-individuals term)
          cand_aggi = 1 iff sig ∩ lastAgg ≠ ∅  (the score's branch flag)
        All int32 [N, (L-1)*K], addressed exactly like cand_rank."""
        n, L, K = self.n_nodes, self.n_levels, self.CAND_SLOTS
        inc, ind, agg = proto["inc"], proto["ind"], proto["agg"]
        s_p, card_p, wind_p, aggi_p = [], [], [], []
        for i, b in enumerate(self.buckets):
            c_sig = self._sig_view(proto, i, K, prefix="cand_sig")
            inc_b = self._blocks(inc, b)[:, :, None, :]
            ind_b = self._blocks(ind, b)[:, :, None, :]
            agg_b = self._blocks(agg, b)[:, :, None, :]
            inter = popcount_words(c_sig & inc_b) > 0
            cc = jnp.where(inter[..., None], c_sig, c_sig | inc_b)
            s_p.append(popcount_words(cc | ind_b))
            card_p.append(popcount_words(c_sig))
            wind_p.append(popcount_words(c_sig | ind_b))
            aggi_p.append(
                (popcount_words(c_sig & agg_b) > 0).astype(jnp.int32)
            )
        flat = lambda ps: jnp.concatenate(ps, axis=1).reshape(n, (L - 1) * K)
        return {
            "cand_s": flat(s_p),
            "cand_card": flat(card_p),
            "cand_wind": flat(wind_p),
            "cand_aggi": flat(aggi_p),
        }

    def recompute_caches(self, state) -> dict:
        if not self.SCORE_CACHE:
            return {}
        # oracle recompute on the int32 view, re-narrowed so the returned
        # leaves match the carried storage dtypes exactly (the SL701 and
        # checkpoint-template comparisons are dtype-strict)
        caches = self._recompute_cache_dict(self.widen_proto(state.proto))
        return self.narrow_proto(caches)

    # -- tick phase 1: commit due verifications ------------------------------
    def _commit(self, net, state):
        """updateVerifiedSignatures at t = selection + pairingTime
        (Handel.java:686-750), one stacked body per width bucket."""
        p = self.params
        proto = state.proto
        t = state.time
        n, L = self.n_nodes, self.n_levels
        ids = jnp.arange(n, dtype=jnp.int32)

        due = proto["ver_active"] & (t >= proto["ver_done_t"])
        good = due & ~proto["ver_bad"]

        rel = proto["ver_rel"]
        new_bl = None
        if self.track_bad:
            # bad sig: blacklist the sender, nothing else (:687-694)
            bad = due & proto["ver_bad"]
            oh_full = self._onehot(rel, self.n_words)
            new_bl = jnp.where(
                bad[:, None], proto["bl"] | oh_full, proto["bl"]
            )

        agg, ind, inc = proto["agg"], proto["ind"], proto["inc"]
        lvl = proto["ver_level"]
        improved_any = jnp.zeros(n, bool)
        just_completed = jnp.zeros(n, bool)
        ind_pieces, agg_pieces, inc_pieces = [], [], []
        for i, b in enumerate(self.buckets):
            lv = jnp.asarray(b.levels, jnp.int32)
            bs = jnp.asarray([self.bs[l] for l in b.levels], jnp.int32)
            m = good[:, None] & (lvl[:, None] == lv[None, :])  # [N, nl]
            r0 = rel[:, None] & (bs[None, :] - 1)
            sig_b = proto["ver_sig"][:, None, : b.w_pad]  # zero above w[lvl]
            ind_b = self._blocks(ind, b)  # [N, nl, w_pad]
            agg_b = self._blocks(agg, b)
            inc_b = self._blocks(inc, b)
            sender = self._onehot(r0, b.w_pad)

            new_ind_b = ind_b | sender
            # the improved guard: extend/replace lastAgg ONLY when the
            # candidate plus individuals is strictly larger (:716-722)
            improved2 = popcount_words(sig_b | new_ind_b) > popcount_words(new_ind_b)
            inter = popcount_words(agg_b & sig_b) > 0
            new_agg_b = jnp.where(
                (improved2 & inter)[..., None],
                jnp.broadcast_to(sig_b, agg_b.shape),
                agg_b | jnp.where(improved2[..., None], sig_b, jnp.uint32(0)),
            )
            new_inc_b = jnp.where(
                improved2[..., None], new_agg_b | new_ind_b, inc_b | sender
            )
            improved1 = popcount_words(inc_b & sender) == 0
            improved = m & (improved1 | improved2)

            before_full = popcount_words(inc_b) == bs[None, :]
            after_full = popcount_words(new_inc_b) == bs[None, :]
            just_completed = just_completed | jnp.any(
                improved & after_full & ~before_full, axis=1
            )
            improved_any = improved_any | jnp.any(improved, axis=1)

            ind_pieces.append(jnp.where(m[..., None], new_ind_b, ind_b))
            agg_pieces.append(
                jnp.where((m & improved2)[..., None], new_agg_b, agg_b)
            )
            inc_pieces.append(jnp.where(m[..., None], new_inc_b, inc_b))

        ind = self._assemble(ind, ind_pieces)
        agg = self._assemble(agg, agg_pieces)
        inc = self._assemble(inc, inc_pieces)

        total = popcount_words(inc)
        done_now = (
            improved_any & (state.done_at == 0) & ~state.down & (total >= p.threshold)
        )
        cache_fix = {}
        if self.SCORE_CACHE:
            # a good commit moves (inc, ind, agg) at exactly ver_level, so
            # the score caches of that one level's K slots are re-derived
            # against the NEW aggregates; every other level's caches stay
            # valid (cand_card depends on sig content only — untouched)
            K = self.CAND_SLOTS
            cs3 = proto["cand_s"].reshape(n, L - 1, K)
            cw3 = proto["cand_wind"].reshape(n, L - 1, K)
            ca3 = proto["cand_aggi"].reshape(n, L - 1, K)
            lv_rows = jnp.arange(L - 1, dtype=jnp.int32)
            for i, b in enumerate(self.buckets):
                mlev = good & (lvl >= b.lo) & (lvl <= b.hi)
                li = jnp.clip(lvl - b.lo, 0, b.nl - 1)
                c_sig = self._sig_view(proto, i, K, prefix="cand_sig")
                sig_lv = jnp.take_along_axis(
                    c_sig, li[:, None, None, None], axis=1
                )[:, 0]  # [N, K, w_pad]
                inc_lv = jnp.take_along_axis(
                    self._blocks(inc, b), li[:, None, None], axis=1
                )[:, 0]
                ind_lv = jnp.take_along_axis(
                    self._blocks(ind, b), li[:, None, None], axis=1
                )[:, 0]
                agg_lv = jnp.take_along_axis(
                    self._blocks(agg, b), li[:, None, None], axis=1
                )[:, 0]
                inter = popcount_words(sig_lv & inc_lv[:, None, :]) > 0
                cc = jnp.where(
                    inter[..., None], sig_lv, sig_lv | inc_lv[:, None, :]
                )
                s_lv = popcount_words(cc | ind_lv[:, None, :])
                wind_lv = popcount_words(sig_lv | ind_lv[:, None, :])
                aggi_lv = (
                    popcount_words(sig_lv & agg_lv[:, None, :]) > 0
                ).astype(jnp.int32)
                lm = mlev[:, None] & (lv_rows[None, :] == (lvl - 1)[:, None])
                cs3 = jnp.where(lm[..., None], s_lv[:, None, :], cs3)
                cw3 = jnp.where(lm[..., None], wind_lv[:, None, :], cw3)
                ca3 = jnp.where(lm[..., None], aggi_lv[:, None, :], ca3)
            cache_fix = {
                "cand_s": cs3.reshape(n, (L - 1) * K),
                "cand_wind": cw3.reshape(n, (L - 1) * K),
                "cand_aggi": ca3.reshape(n, (L - 1) * K),
            }
        upd = dict(
            agg=agg,
            ind=ind,
            inc=inc,
            ver_active=proto["ver_active"] & ~due,
            **cache_fix,
        )
        if self.track_bad:
            upd["bl"] = new_bl
        state = state._replace(
            done_at=jnp.where(done_now, t, state.done_at),
            proto=dict(proto, **upd),
        )

        # fastPath burst (:738-742): on completing a level's incoming set,
        # contact fast_path peers of the first higher level whose outgoing
        # is now complete but whose incoming is not.  The burst drains
        # through a register over two ticks (ceil(fp/2) peers per tick)
        # instead of fp simultaneous rows: the send's scatter costs
        # N*fp/2 rows/tick, and the <= 1 ms arrival spread stays inside
        # the parity suite's tolerance (1-peer-per-tick draining pushed
        # P90 to 9.6% vs the 8% bar; two-tick draining passes).  A new
        # completion overwrites a still-draining burst.
        if p.fast_path > 0 and L > 1:
            out_done = self._level_stats(
                [
                    popcount_words(self._lows(inc, b))
                    == jnp.asarray([self.bs[l] for l in b.levels], jnp.int32)[None, :]
                    for b in self.buckets
                ]
            )
            inc_done = self._level_stats(
                [
                    popcount_words(self._blocks(inc, b))
                    == jnp.asarray([self.bs[l] for l in b.levels], jnp.int32)[None, :]
                    for b in self.buckets
                ]
            )
            target_ok = out_done & ~inc_done  # [N, L-1]
            has_target = jnp.any(target_ok, axis=1)
            lsel = (jnp.argmax(target_ok, axis=1) + 1).astype(jnp.int32)
            fp_mask_base = just_completed & has_target
            fp = min(p.fast_path, max(1, self.n_nodes // 2))

            fp_left = jnp.where(fp_mask_base, fp, proto["fp_left"])
            fp_level = jnp.where(fp_mask_base, lsel, proto["fp_level"])
            fp_off = jnp.where(
                fp_mask_base, hash32(state.seed, ids, lsel, t), proto["fp_off"]
            )
            r = (fp + 1) // 2  # peers contacted per tick; burst drains in 2
            firing = fp_left > 0
            bs_sel = jnp.asarray(self.lv_bs)[jnp.maximum(fp_level - 1, 0)]
            ks = (fp - fp_left)[:, None] + jnp.arange(r, dtype=jnp.int32)[None, :]
            m_rows = (
                firing[:, None]
                & (jnp.arange(r, dtype=jnp.int32)[None, :] < fp_left[:, None])
                & (ks < bs_sel[:, None])
            )
            rel_fp = bs_sel[:, None] + ((fp_off[:, None] + ks) & (bs_sel[:, None] - 1))
            content = [
                jnp.repeat(self._dyn_low(inc, fp_level, b), r, axis=0)
                for b in self.buckets
            ]
            state = state._replace(
                proto=dict(
                    state.proto,
                    fp_left=jnp.maximum(fp_left - r, 0),
                    fp_level=fp_level,
                    fp_off=fp_off,
                )
            )
            state = self._send_stacked(
                net,
                state,
                m_rows.reshape(-1),
                jnp.repeat(ids, r),
                (ids[:, None] ^ rel_fp).reshape(-1),
                jnp.repeat(fp_level, r),
                content,
            )
        return state

    # -- tick phase 2: deliver due channel slots into the candidate buffer ---
    def _channel_deliver(self, net, state):
        """onNewSig (Handel.java:752-786): due in-flight slots become
        verification candidates; the buffer keeps the top-K by
        (sizeIfIncluded, rank) among survivors of the curation rule."""
        proto = state.proto
        t = state.time
        n, L, D, K = self.n_nodes, self.n_levels, self.CHANNEL_DEPTH, self.CAND_SLOTS
        ids = jnp.arange(n, dtype=jnp.int32)
        rel_mask = (1 << self.rel_bits) - 1
        ss = D + 1
        lv_all = jnp.arange(1, L, dtype=jnp.int32)  # [L-1]

        in_key, due_all, empty_tpl = self._advance_channel(proto["in_key"], t)

        keys3 = self._keys_stacked(in_key)  # [N, L-1, ss]
        due3 = due_all.reshape(n, L - 1, ss)
        # only arrival slot (t mod D) and the fresh slot can be due at t
        keys2, due2 = self._due_pair_keys(keys3, due3, t)  # [N, L-1, 2]
        rel2 = keys2 & rel_mask

        # (receiver traffic counters tick at send time in _send_stacked)
        started = t >= proto["start_at"]
        not_done = state.done_at == 0
        filtered = jnp.sum(
            (due2 & ~not_done[:, None, None]).astype(jnp.int32), axis=(1, 2)
        )

        # onNewSig drop filters: not started, done, blacklisted sender
        accept = due2 & started[:, None, None] & not_done[:, None, None]
        if self.track_bad:
            accept = accept & (self._getbit(proto["bl"], rel2) == 0)

        # rank + verified-sender demotion (receptionRanks += nodeCount)
        ind_bit = self._getbit(proto["ind"], rel2)
        rank2 = self._rank(
            state.seed, ids[:, None, None], lv_all[None, :, None], rel2
        ) + self.n_nodes * ind_bit.astype(jnp.int32)
        rank2 = jnp.where(accept, rank2, INT32_MAX)

        inc, ind = proto["inc"], proto["ind"]
        bl = proto["bl"] if self.track_bad else None
        agg = proto["agg"]
        rank_pieces, rel_pieces = [], []
        s_pieces, card_pieces, wind_pieces, aggi_pieces = [], [], [], []
        cand_sig_updates = {}
        for i, b in enumerate(self.buckets):
            sl = slice(b.lo - 1, b.hi)  # level rows of this bucket
            sig_new = self._due_pair_sig(proto, i, t)  # [N, nl, 2, w_pad]
            rank_new = rank2[:, sl, :]
            rel_new = rel2[:, sl, :]

            # merge [K existing + 2 new], keep top-K by (sizeIfIncluded, -rank)
            c_rank = proto["cand_rank"].reshape(n, L - 1, K)[:, sl, :]
            c_rel = proto["cand_rel"].reshape(n, L - 1, K)[:, sl, :]
            c_sig = self._sig_view(proto, i, K, prefix="cand_sig")

            all_rank = jnp.concatenate([c_rank, rank_new], axis=2)  # [N, nl, K+2]
            all_rel = jnp.concatenate([c_rel, rel_new], axis=2)
            all_sig = jnp.concatenate([c_sig, sig_new], axis=2)
            valid = all_rank != INT32_MAX

            inc_b = self._blocks(inc, b)  # [N, nl, w_pad]
            ind_b = self._blocks(ind, b)
            if self.SCORE_CACHE:
                # only the two due slots pay popcounts: the K resident
                # slots' quantities ride in the caches, valid against the
                # pre-commit aggregates by the end-of-tick invariant
                # (deliver runs first; _commit re-fixes what it moves)
                agg_b = self._blocks(agg, b)
                inter2 = popcount_words(sig_new & inc_b[:, :, None, :]) > 0
                c2 = jnp.where(
                    inter2[..., None], sig_new, sig_new | inc_b[:, :, None, :]
                )
                s_new = popcount_words(c2 | ind_b[:, :, None, :])
                all_s = jnp.concatenate(
                    [proto["cand_s"].reshape(n, L - 1, K)[:, sl, :], s_new],
                    axis=2,
                )
                all_card = jnp.concatenate(
                    [
                        proto["cand_card"].reshape(n, L - 1, K)[:, sl, :],
                        popcount_words(sig_new),
                    ],
                    axis=2,
                )
                all_wind = jnp.concatenate(
                    [
                        proto["cand_wind"].reshape(n, L - 1, K)[:, sl, :],
                        popcount_words(sig_new | ind_b[:, :, None, :]),
                    ],
                    axis=2,
                )
                all_aggi = jnp.concatenate(
                    [
                        proto["cand_aggi"].reshape(n, L - 1, K)[:, sl, :],
                        (
                            popcount_words(sig_new & agg_b[:, :, None, :]) > 0
                        ).astype(jnp.int32),
                    ],
                    axis=2,
                )
                s = all_s
            else:
                inter = popcount_words(all_sig & inc_b[:, :, None, :]) > 0
                c = jnp.where(
                    inter[..., None], all_sig, all_sig | inc_b[:, :, None, :]
                )
                s = popcount_words(c | ind_b[:, :, None, :])  # sizeIfIncluded
            cur = popcount_words(inc_b)
            keep = valid & (s > cur[:, :, None])
            if self.track_bad:
                keep = keep & (self._getbit(bl, all_rel) == 0)

            # sort key: higher sizeIfIncluded first, then lower rank;
            # bounded (s <= bs <= N/2, rank < 3N) so s*4N + rank fits int32
            r4 = 4 * self.n_nodes
            skey = jnp.where(
                keep, s * r4 + (r4 - 1 - jnp.minimum(all_rank, r4 - 1)), -1
            )
            order = jnp.argsort(-skey, axis=2)[:, :, :K]  # top-K
            top_keep = jnp.take_along_axis(skey, order, axis=2) >= 0
            sel_rank = jnp.where(
                top_keep, jnp.take_along_axis(all_rank, order, axis=2), INT32_MAX
            )
            sel_rel = jnp.take_along_axis(all_rel, order, axis=2)
            sel_sig = jnp.take_along_axis(all_sig, order[..., None], axis=2)

            rank_pieces.append(sel_rank)
            rel_pieces.append(sel_rel)
            cand_sig_updates[f"cand_sig{i}"] = sel_sig.reshape(
                n, b.nl * K * b.w_pad
            )
            if self.SCORE_CACHE:
                s_pieces.append(jnp.take_along_axis(all_s, order, axis=2))
                card_pieces.append(
                    jnp.take_along_axis(all_card, order, axis=2)
                )
                wind_pieces.append(
                    jnp.take_along_axis(all_wind, order, axis=2)
                )
                aggi_pieces.append(
                    jnp.take_along_axis(all_aggi, order, axis=2)
                )

        cache_updates = {}
        if self.SCORE_CACHE:
            flat = lambda ps: jnp.concatenate(ps, axis=1).reshape(
                n, (L - 1) * K
            )
            cache_updates = {
                "cand_s": flat(s_pieces),
                "cand_card": flat(card_pieces),
                "cand_wind": flat(wind_pieces),
                "cand_aggi": flat(aggi_pieces),
            }
        state = state._replace(
            proto=dict(
                proto,
                in_key=jnp.where(due_all, empty_tpl[None, :], in_key),
                cand_rank=jnp.concatenate(rank_pieces, axis=1).reshape(n, (L - 1) * K),
                cand_rel=jnp.concatenate(rel_pieces, axis=1).reshape(n, (L - 1) * K),
                msg_filtered=proto["msg_filtered"] + filtered,
                **cand_sig_updates,
                **cache_updates,
            )
        )
        return state

    # -- tick phase 3: periodic dissemination --------------------------------
    def _dissemination(self, net, state):
        """Periodic doCycle over open levels (Handel.java:331-343, 452-480),
        all levels in ONE stacked send."""
        p = self.params
        proto = state.proto
        t = state.time
        n, L = self.n_nodes, self.n_levels
        ids = jnp.arange(n, dtype=jnp.int32)
        lv_all = jnp.arange(1, L, dtype=jnp.int32)
        bs_all = jnp.asarray(self.lv_bs)

        start = proto["start_at"] + 1
        on_beat = (t >= start) & (
            lax.rem(t - start, jnp.int32(p.dissemination_period_ms)) == 0
        )
        is_done = state.done_at > 0
        may_send = on_beat & ~state.down & (~is_done | (proto["added_cycle"] > 0))
        new_added = jnp.where(
            on_beat & is_done & (proto["added_cycle"] > 0),
            proto["added_cycle"] - 1,
            proto["added_cycle"],
        )

        inc = proto["inc"]
        opened = t >= (lv_all - 1) * jnp.int32(p.level_wait_time)  # [L-1]
        complete = self._level_stats(
            [
                popcount_words(self._lows(inc, b))
                == jnp.asarray([self.bs[l] for l in b.levels], jnp.int32)[None, :]
                for b in self.buckets
            ]
        )
        mask = may_send[:, None] & (opened[None, :] | complete)  # [N, L-1]

        offset = hash32(state.seed, ids[:, None], lv_all[None, :]) & (bs_all[None, :] - 1)
        pos = proto["pos"][:, 1:]
        rel = (bs_all[None, :] + ((pos + offset) & (bs_all[None, :] - 1))).astype(
            jnp.int32
        )
        new_pos = proto["pos"].at[:, 1:].set(jnp.where(mask, pos + 1, pos))
        state = state._replace(
            proto=dict(proto, added_cycle=new_added, pos=new_pos)
        )

        # content: each level sends its outgoing prefix (zeros for levels
        # outside a bucket — those rows are masked in the scatter)
        content = []
        for b in self.buckets:
            lows = self._lows(inc, b)  # [N, nl, w_pad]
            full = jnp.zeros((n, L - 1, b.w_pad), jnp.uint32)
            full = full.at[:, b.lo - 1 : b.hi, :].set(lows)
            content.append(full.reshape(n * (L - 1), b.w_pad))

        state = self._send_stacked(
            net,
            state,
            mask.reshape(-1),
            jnp.repeat(ids, L - 1),
            (ids[:, None] ^ rel).reshape(-1),
            jnp.broadcast_to(lv_all[None, :], (n, L - 1)).reshape(-1),
            content,
        )
        return state

    # -- tick phase 4: start new verifications (checkSigs) -------------------
    def _select(self, net, state, view=None):
        """bestToVerify per level + uniform cross-level choice + attacks +
        window adaptation (Handel.java:566-630, 788-837).

        `view` (tick() passes it) holds the BOUNDARY state — candidates
        and aggregates as of the end of the previous tick — which is what
        the reference's boundary-fired checkSigs sees.  Candidate
        write-backs (curation removal, chosen-slot consumption) target
        the viewed ENTRY by (rank, cardinality) identity matched against
        any current slot of the level: delivery re-sorts the K slots on
        arrival ticks, so slot-index matching would both miss moved
        entries and clobber same-rank refreshes.  Rank is unique per
        (receiver, level, sender) and a refreshed aggregate differs in
        cardinality, so the only ambiguity is content-equal duplicates —
        clearing those loses nothing."""
        p = self.params
        proto = state.proto
        v = proto if view is None else {**proto, **view}
        t = state.time
        n, L, K = self.n_nodes, self.n_levels, self.CAND_SLOTS
        ids = jnp.arange(n, dtype=jnp.int32)

        # busy gate from CURRENT state (a commit this tick frees the node,
        # preserving the reference's pairing-time cadence); everything the
        # selection SCORES on comes from the boundary view
        free = ~proto["ver_active"] & ~state.down & (t >= proto["start_at"] + 1)
        window = proto["window"]
        inc, ind, agg = v["inc"], v["ind"], v["agg"]
        bl = v["bl"] if self.track_bad else None
        byz = proto["byz"] if self.track_bad else None

        # per-level bests, one stacked body per bucket
        has_p, b_rank_p, b_rel_p, b_bad_p, b_kidx_p = [], [], [], [], []
        widx_p, insc_p = [], []
        condemn_pieces, vcard_pieces, ccard_pieces = [], [], []
        for i, b in enumerate(self.buckets):
            sl = slice(b.lo - 1, b.hi)
            lv = jnp.asarray(b.levels, jnp.int32)
            bs = jnp.asarray([self.bs[l] for l in b.levels], jnp.int32)
            c_rank = v["cand_rank"].reshape(n, L - 1, K)[:, sl, :]
            c_rel = v["cand_rel"].reshape(n, L - 1, K)[:, sl, :]
            c_sig = self._sig_view(v, i, K, prefix="cand_sig")
            valid = c_rank != INT32_MAX

            inc_b = self._blocks(inc, b)
            ind_b = self._blocks(ind, b)
            agg_b = self._blocks(agg, b)

            # curation (bestToVerify :592-612): drop blacklisted senders and
            # candidates that can no longer grow the aggregate
            if self.SCORE_CACHE:
                # sizeIfIncluded / cardinalities come from the carried
                # int32 caches (the viewed snapshot for scoring, the
                # current leaf for entry identity) — no signature-word
                # popcounts on this path
                s = v["cand_s"].reshape(n, L - 1, K)[:, sl, :]
                ccard_pieces.append(
                    proto["cand_card"].reshape(n, L - 1, K)[:, sl, :]
                )
            else:
                inter = popcount_words(c_sig & inc_b[:, :, None, :]) > 0
                cc = jnp.where(inter[..., None], c_sig, c_sig | inc_b[:, :, None, :])
                s = popcount_words(cc | ind_b[:, :, None, :])
                cur_sig = self._sig_view(proto, i, K, prefix="cand_sig")
                ccard_pieces.append(popcount_words(cur_sig))
            curated = valid & (s > popcount_words(inc_b)[:, :, None])
            if self.track_bad:
                curated = curated & (self._getbit(bl, c_rel) == 0)
            # permanent removal, like replaceToVerifyAgg (:612-618) —
            # recorded as a condemn mask, applied by ENTRY IDENTITY below
            condemn_pieces.append(valid & ~curated)

            # windowIndex = min rank over the (pre-curation valid) queue
            window_index = jnp.min(
                jnp.where(valid, c_rank, INT32_MAX), axis=2
            )  # [N, nl]
            win_hi = jnp.where(
                window_index < INT32_MAX - window[:, None],
                window_index + window[:, None],
                INT32_MAX,
            )
            inside = curated & (c_rank <= win_hi[:, :, None])

            # score (:650-664)
            agg_card = popcount_words(agg_b)  # [N, nl]
            if self.SCORE_CACHE:
                sig_card = v["cand_card"].reshape(n, L - 1, K)[:, sl, :]
                agg_inter = v["cand_aggi"].reshape(n, L - 1, K)[:, sl, :] > 0
                with_ind = v["cand_wind"].reshape(n, L - 1, K)[:, sl, :]
            else:
                sig_card = popcount_words(c_sig)
                agg_inter = popcount_words(c_sig & agg_b[:, :, None, :]) > 0
                with_ind = popcount_words(c_sig | ind_b[:, :, None, :])
            vcard_pieces.append(sig_card)
            score = jnp.where(
                agg_card[:, :, None] >= bs[None, :, None],
                0,
                jnp.where(
                    ~agg_inter,
                    agg_card[:, :, None] + sig_card,
                    jnp.maximum(0, with_ind - agg_card[:, :, None]),
                ),
            )
            in_score = jnp.where(inside & (score > 0), score, -1)
            k_in = jnp.argmax(in_score, axis=2)
            sc_in = jnp.take_along_axis(in_score, k_in[..., None], axis=2)[..., 0]
            exists_in = sc_in > 0

            out_rank = jnp.where(curated & ~inside, c_rank, INT32_MAX)
            k_out = jnp.argmin(out_rank, axis=2)
            rk_out = jnp.take_along_axis(out_rank, k_out[..., None], axis=2)[..., 0]
            exists_out = rk_out < INT32_MAX

            kidx = jnp.where(exists_in, k_in, k_out)
            lrank = jnp.where(
                exists_in,
                jnp.take_along_axis(c_rank, k_in[..., None], axis=2)[..., 0],
                rk_out,
            )
            lrel = jnp.take_along_axis(c_rel, kidx[..., None], axis=2)[..., 0]
            lhas = exists_in | exists_out
            lbad = jnp.zeros((n, b.nl), bool)

            if p.byzantine_suicide:
                # createSuicideByzantineSig (:538-559): a forged full-block
                # sig from an eligible Byzantine peer short-circuits the
                # level's choice.  Eligible = down+byz, not blacklisted,
                # rank inside windowIndex + currWindowSize, queue non-empty.
                eligible = self._blocks(byz, b) & ~self._blocks(bl, b)
                any_valid = jnp.any(valid, axis=2)
                has_byz = popcount_words(eligible) > 0
                # lowest block-local index (stand-in for cursor order)
                m_byz = self._lowest_bit(eligible)
                rel_byz = bs[None, :] + (m_byz & (bs[None, :] - 1))
                rank_byz = self._rank(
                    state.seed, ids[:, None], lv[None, :], rel_byz
                )
                inject = has_byz & any_valid & (rank_byz < win_hi)
                lhas = lhas | inject
                lbad = jnp.where(inject, True, lbad)
                lrel = jnp.where(inject, rel_byz, lrel)
                lrank = jnp.where(inject, rank_byz, lrank)
                kidx = jnp.where(inject, -1, kidx)

            has_p.append(lhas)
            b_rank_p.append(lrank)
            b_rel_p.append(lrel)
            b_bad_p.append(lbad)
            b_kidx_p.append(kidx)
            widx_p.append(window_index)
            insc_p.append(jnp.where(exists_in, sc_in, -1))

        has = self._level_stats(has_p)  # [N, L-1]
        b_rank = self._level_stats(b_rank_p)
        b_rel = self._level_stats(b_rel_p)
        b_bad = self._level_stats(b_bad_p)
        b_kidx = self._level_stats(b_kidx_p)
        # curation removal by ENTRY IDENTITY (rank, cardinality) matched
        # against ANY current slot of the level: delivery re-sorts the K
        # slots on arrival ticks, so slot-index matching would miss moved
        # entries (surviving for a duplicate verification) and clobber
        # same-rank refreshes; rank is unique per (receiver, level,
        # sender) and a refreshed aggregate has a different cardinality,
        # so the pair identifies the viewed entry up to content-equal
        # duplicates (clearing those loses nothing)
        condemn3 = jnp.concatenate(condemn_pieces, axis=1)  # [N, L-1, K]
        vrank3 = v["cand_rank"].reshape(n, L - 1, K)
        vcard3 = jnp.concatenate(vcard_pieces, axis=1)
        crank3 = proto["cand_rank"].reshape(n, L - 1, K)
        ccard3 = jnp.concatenate(ccard_pieces, axis=1)

        cleared = self._entry_clear(crank3, ccard3, vrank3, vcard3, condemn3)
        new_rank3 = jnp.where(cleared, INT32_MAX, crank3)

        # chooseBestFromLevels: uniform among levels with a candidate (:788)
        vcount = jnp.sum(has, axis=1).astype(jnp.int32)
        can = free & (vcount > 0)
        rnd = (
            hash32(state.seed, t, ids, jnp.int32(0x5EED)).astype(jnp.uint32)
            >> jnp.uint32(8)
        ).astype(jnp.int32)
        pick = jnp.where(vcount > 0, lax.rem(rnd, jnp.maximum(vcount, 1)), 0)
        cum = jnp.cumsum(has, axis=1)
        lidx = jnp.argmax((cum == (pick + 1)[:, None]) & has, axis=1)  # 0-based
        level_sel = (lidx + 1).astype(jnp.int32)

        sel_rank = jnp.take_along_axis(b_rank, lidx[:, None], axis=1)[:, 0]
        sel_rel = jnp.take_along_axis(b_rel, lidx[:, None], axis=1)[:, 0]
        sel_bad = jnp.take_along_axis(b_bad, lidx[:, None], axis=1)[:, 0]
        sel_kidx = jnp.take_along_axis(b_kidx, lidx[:, None], axis=1)[:, 0]
        sel_single = jnp.zeros(n, bool)  # hidden-byz single-bit sig marker

        if p.hidden_byzantine and L > 1:
            # HiddenByzantine.attack (:840-917), modeled at selection time:
            # when the chosen best is at the top level, a valid single-bit
            # sig from the lowest-index down-byz peer not yet in
            # totalIncoming is appended and bestToVerify re-runs — the
            # injected sig wins when it lands inside the (possibly lowered)
            # window with a strictly higher score than any inside candidate
            # (appended last, so ties keep the incumbent, :578-584).
            l = L - 1
            bt = self.buckets[-1]
            bs = self.bs[l]
            inc_b = self._blocks(inc, bt)[:, -1]
            ind_b = self._blocks(ind, bt)[:, -1]
            agg_b = self._blocks(agg, bt)[:, -1]
            eligible = self._blocks(byz, bt)[:, -1] & ~inc_b
            has_byz = popcount_words(eligible) > 0
            m_byz = self._lowest_bit(eligible)
            rel_byz = bs + (m_byz & (bs - 1))
            rank_byz = self._rank(state.seed, ids, jnp.int32(l), rel_byz)

            # its score: single new bit (:650-664)
            agg_card = popcount_words(agg_b)
            oh = self._onehot(m_byz & (bs - 1), bt.w_pad)
            byz_inter = popcount_words(oh & agg_b) > 0
            byz_score = jnp.where(
                agg_card >= bs,
                0,
                jnp.where(
                    ~byz_inter,
                    agg_card + 1,
                    jnp.maximum(0, popcount_words(oh | ind_b) - agg_card),
                ),
            )
            widx_top = self._level_stats(widx_p)[:, -1]
            insc_top = self._level_stats(insc_p)[:, -1]
            new_widx = jnp.minimum(widx_top, rank_byz)
            win_hi = jnp.where(
                new_widx < INT32_MAX - window, new_widx + window, INT32_MAX
            )
            was_outside = insc_top < 0
            wins = (
                can
                & (level_sel == l)
                & (sel_kidx >= 0)
                & has_byz
                & (rank_byz < sel_rank)
                & (rank_byz <= win_hi)
                & (byz_score > 0)
                & (was_outside | (byz_score > insc_top))
            )
            sel_rel = jnp.where(wins, rel_byz, sel_rel)
            sel_rank = jnp.where(wins, rank_byz, sel_rank)
            sel_kidx = jnp.where(wins, -1, sel_kidx)
            sel_single = wins

        # window adaptation (:823-825): exp increase on correct, exp
        # decrease on bad, clamped to [min, max] and the level size
        grown = jnp.ceil(window.astype(jnp.float32) * p.window_increase_factor)
        shrunk = jnp.floor(window.astype(jnp.float32) / p.window_decrease_factor)
        adapted = jnp.where(sel_bad, shrunk, grown).astype(jnp.int32)
        adapted = jnp.clip(adapted, p.window_minimum, p.window_maximum)
        lsize = (
            jnp.uint32(1) << jnp.maximum(level_sel - 1, 0).astype(jnp.uint32)
        ).astype(jnp.int32)
        new_window = jnp.where(can, jnp.minimum(adapted, lsize), window)

        # load the chosen sig into the verification register
        bs_sel = jnp.asarray(self.lv_bs)[jnp.maximum(level_sel - 1, 0)]
        ver_sig = proto["ver_sig"]
        for i, b in enumerate(self.buckets):
            m = can & (level_sel >= b.lo) & (level_sel <= b.hi)
            c_sig = self._sig_view(v, i, K, prefix="cand_sig")
            li = jnp.clip(level_sel - b.lo, 0, b.nl - 1)
            c_lv = jnp.take_along_axis(
                c_sig, li[:, None, None, None], axis=1
            )[:, 0]  # [N, K, w_pad]
            safe_k = jnp.maximum(sel_kidx, 0)
            from_buf = jnp.take_along_axis(c_lv, safe_k[:, None, None], axis=1)[:, 0]
            full_block = self._dyn_full_block(bs_sel, b.w_pad)
            single = self._onehot(sel_rel & (bs_sel - 1), b.w_pad)
            sig_l = jnp.where(
                (sel_kidx >= 0)[:, None],
                from_buf,
                jnp.where(sel_single[:, None], single, full_block),
            )
            pad = jnp.zeros((n, self.w_max - b.w_pad), jnp.uint32)
            sig_l = jnp.concatenate([sig_l, pad], axis=1)
            ver_sig = jnp.where(m[:, None], sig_l, ver_sig)

        # remove the chosen buffer candidate (commit-time removal in the
        # reference; removal at selection avoids double-verification) —
        # matched by (rank, cardinality) entry identity against the
        # chosen level's CURRENT slots, like the curation clear above
        lvl_idx = jnp.maximum(level_sel - 1, 0)
        sel_card = jnp.take_along_axis(
            jnp.take_along_axis(vcard3, lvl_idx[:, None, None], axis=1)[:, 0],
            jnp.maximum(sel_kidx, 0)[:, None],
            axis=1,
        )[:, 0]
        remove = can & (sel_kidx >= 0)
        new_rank3 = self._remove_chosen(
            ids, new_rank3, ccard3, lvl_idx, sel_rank, sel_card, remove
        )
        new_cand_rank = new_rank3.reshape(n, (L - 1) * K)

        state = state._replace(
            proto=dict(
                proto,
                cand_rank=new_cand_rank,
                ver_active=jnp.where(can, True, proto["ver_active"]),
                ver_done_t=jnp.where(can, t + proto["pairing"], proto["ver_done_t"]),
                ver_level=jnp.where(can, level_sel, proto["ver_level"]),
                ver_rel=jnp.where(can, sel_rel, proto["ver_rel"]),
                ver_bad=jnp.where(can, sel_bad, proto["ver_bad"]),
                ver_sig=ver_sig,
                window=new_window,
                sigs_checked=proto["sigs_checked"] + can.astype(jnp.int32),
            )
        )
        return state

    # -- engine hooks --------------------------------------------------------
    def tick(self, net, state):
        # NARROW_LEAVES boundary (engine.density): the tick body — and the
        # boundary-view snapshots it takes — compute on the int32 view;
        # the carried state between ticks stores the declared narrow
        # dtypes.  Bit-identical by construction: widen/narrow is a
        # lossless sentinel-mapped cast both ways.
        state = state._replace(proto=self.widen_proto(state.proto))
        state = self._tick_impl(net, state)
        return state._replace(proto=self.narrow_proto(state.proto))

    def _tick_impl(self, net, state):
        # deliver FIRST: it decrements every occupied channel key by one
        # tick, so anything sent later in this tick (fastPath bursts in
        # _commit, dissemination in tick_beat) is first decremented next
        # tick and lands exactly at its sampled arrival.  Dissemination
        # runs as the beat hook (same-tick order vs _select is immaterial:
        # _select reads none of the channel/pos state dissemination
        # writes, and channel slot resolution is order-independent
        # min/max competition).
        #
        # _select runs on the BOUNDARY VIEW (r5): the reference's checkSigs
        # is a conditional task that fires at the ms boundary — after
        # time++ but BEFORE the new ms's arrivals and before that ms's
        # updateVerifiedSignatures task (Network.java:533-565) — so the
        # selection must see candidates and aggregates as of the END of
        # the previous tick.  Selecting on same-tick state gave the
        # batched engine a 1-tick information lead per verification hop,
        # measured as a -4..-9 ms CDF lead (docs/TPU_NOTES.md r5).  The
        # busy gate stays post-commit (a commit at t frees the node for a
        # same-tick re-select, like the reference's minStartTime spacing).
        if not self.BOUNDARY_VIEW:  # pre-r5 ablation lever: same-tick view
            state = self._channel_deliver(net, state)
            state = self._commit(net, state)
            return self._select(net, state)
        pre_cand = {k: state.proto[k] for k in self._cand_keys()}
        state = self._channel_deliver(net, state)
        merge_keys = ("inc", "ind", "agg") + (
            ("bl",) if self.track_bad else ()
        )
        pre_merge = {k: state.proto[k] for k in merge_keys}
        state = self._commit(net, state)
        state = self._select(net, state, view={**pre_cand, **pre_merge})
        return state

    def _cand_keys(self):
        keys = ("cand_rank", "cand_rel") + tuple(
            f"cand_sig{i}" for i in range(len(self.buckets))
        )
        if self.SCORE_CACHE:
            # the boundary view scores on end-of-previous-tick caches,
            # which by the invariant equal a recompute from the viewed
            # (cand_sig, inc, ind, agg) exactly
            keys = keys + self.CACHE_LEAF_NAMES
        return keys

    def all_done(self, state):
        live = ~state.down
        return jnp.all(jnp.where(live, state.done_at > 0, True))


def make_handel(
    params: Optional[HandelParameters] = None,
    capacity: int = 8,  # generic ring unused by this protocol
    seed: int = 0,
    wheel_rows: int = 0,  # flat by default; >0 = time wheel (parity tests)
    telemetry=None,  # telemetry.TelemetryConfig (None = uninstrumented)
    boundary_view: bool = True,  # False = pre-r5 selection (ablation only)
    annotate: bool = True,  # False = strip named-scope phase markers
    score_cache: Optional[bool] = None,  # None = auto: on for TPU only
    fuse_step: bool = False,  # True = engine's fused delivery+tick path
):
    """Host-side construction: build the node population with the oracle's
    RNG stream (positions, speed ratios, down set), bake into the engine."""
    params = params or HandelParameters()
    if score_cache is None:
        # The score cache trades bytes-accessed for carried int32 leaves —
        # an HBM-bandwidth economy.  On TPU that is the budget's dominant
        # cost (BUDGET.json: 1.93 GB/tick), so the cache defaults ON.  On
        # CPU the masked delta-update scatters pay full width regardless
        # of the due mask, and the 256x4 ablation prices the cache at a
        # 5-10% LOSS — so it defaults OFF off-TPU.  Pass True/False to
        # pin either way (bit-identical: tests/test_score_cache.py).
        import jax

        score_cache = jax.default_backend() == "tpu"
    n = params.node_count
    nb = registry_node_builders.get_by_name(params.node_builder_name)
    latency = registry_network_latencies.get_by_name(params.network_latency_name)
    rd = JavaRandom(0)

    from ..oracle.network import Network as ONetwork

    if params.bad_nodes is not None:
        bad_bits = params.bad_nodes
        bad = {i for i in range(n) if (bad_bits >> i) & 1}
    else:
        bad = ONetwork.choose_bad_nodes(rd, n, params.nodes_down)

    nodes = []
    start_at = np.zeros(n, dtype=np.int32)
    for i in range(n):
        if params.desynchronized_start != 0:
            start_at[i] = rd.next_int(params.desynchronized_start)
        nodes.append(Node(rd, nb))
    down = np.array([i in bad for i in range(n)])

    pairing = np.maximum(
        1, (params.pairing_time * np.array([nd.speed_ratio for nd in nodes]))
    ).astype(np.int32)

    proto = BatchedHandel(params)
    proto.BOUNDARY_VIEW = bool(boundary_view)
    proto.SCORE_CACHE = bool(score_cache)
    proto.DERIVED_CACHE_LEAVES = (
        proto.CACHE_LEAF_NAMES if score_cache else ()
    )
    # beat structure for the engine's real-branch gating: dissemination
    # fires at t with (t - (start_at + 1)) % period == 0
    proto.BEAT_PERIOD = params.dissemination_period_ms
    proto.BEAT_RESIDUES = tuple(
        sorted({int((s + 1) % params.dissemination_period_ms) for s in start_at})
    )

    # Byzantine peers, as each receiver's rel-space bitset (nodes that are
    # both down and flagged byzantine — Handel.java:957-976 stops them and
    # the attacks impersonate them)
    byz_rel = None
    if params.byzantine_suicide or params.hidden_byzantine:
        byz_abs = np.zeros(proto.n_words, dtype=np.uint32)
        for i in sorted(bad):
            byz_abs[i // 32] |= np.uint32(1 << (i % 32))
        ids = np.arange(n, dtype=np.int32)
        byz_rel = np.asarray(
            xor_shuffle(jnp.broadcast_to(jnp.asarray(byz_abs), (n, proto.n_words)), ids)
        )

    city_index = getattr(latency, "city_index", None)
    cols = build_node_columns(nodes, city_index)
    # flat mode by default: aggregation messaging bypasses the generic
    # store entirely (the channel in _agg_batched), so keep the per-tick
    # scan minimal
    net = BatchedNetwork(
        proto, latency, n, capacity=capacity, wheel_rows=wheel_rows,
        telemetry=telemetry, annotate=annotate, fuse_step=fuse_step,
    )
    state = net.init_state(
        cols,
        seed=seed,
        proto=proto.proto_init(n, pairing, start_at, byz_rel),
        down=down,
    )
    return net, state

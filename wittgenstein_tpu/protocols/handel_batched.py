"""Batched Handel: the north-star protocol on the TPU engine.

Re-expression of protocols/Handel.java for the batched time-stepped core.
State is packed uint32 bitsets in the XOR-relative layout (ops.bitops):
bit j of node i's vector is node i^j, so every node shares the same level
geometry — level l = bit block [2^(l-1), 2^l) (Handel.allSigsAtLevel,
Handel.java:634-647, becomes a static mask).

Messaging uses a protocol-specific channel instead of the generic ring
(SURVEY §7 "per-protocol message representations"): D in-flight slots per
(receiver, level), slot = arrival mod D, each holding
((arrival - now)<<REL_BITS | sender_rel, content) — time-RELATIVE keys,
decremented once per tick, so the packing never overflows int32 no matter
the simulation horizon.  Earliest arrival wins a slot;
displaced sends are simply lost — Handel is a gossip protocol whose
periodic dissemination re-offers content every period, which is exactly
the redundancy the reference relies on for dropped/filtered messages.
Delivery is then pure elementwise work on [N, L, D] arrays — no scatters
on the delivery path, and memory is O(N·L·D·W) regardless of traffic.

Mapping from the reference (semantics deltas are deliberate,
distribution-parity approximations — each is noted):

  * SendSigs content (totalOutgoing at the level = bits [0, 2^(l-1)) of
    the sender's vector) is captured exactly at send time in the slot;
  * the per-level toVerifyAgg queue becomes a one-candidate register
    pend_key[N, L] + cand_sig[N, L, W/2], preferring fuller content (the
    stand-in for bestToVerify's added-sigs scoring, Handel.java:566-630);
  * checkSigs' uniformly-random choice among per-level bests
    (Handel.java:788-790) is kept, via a counter-hash draw;
  * verification completion follows updateVerifiedSignatures exactly:
    verified individual bit, replace-on-intersect lastAgg, totalIncoming =
    agg | ind, threshold -> doneAt (Handel.java:686-750);
  * fastPath: on completing a level's incoming set, burst-send to
    fast_path peers of the first higher level whose outgoing just
    completed (Handel.java:738-742);
  * extraCycle dissemination continuation after done; incoming is
    filtered (msg_filtered) once done (Handel.java:752-756);
  * emission order is a counter-hash offset + cycling cursor (stands in
    for the reception-rank emission lists, Handel.java:991-1013).

Byzantine attack modes are not yet ported to the batched path.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.node import Node, build_node_columns
from ..core.registries import registry_network_latencies, registry_node_builders
from ..engine import BatchedNetwork, BatchedProtocol
from ..engine.rng import hash32
from ..ops.bitops import level_block_mask, popcount_words, xor_shuffle
from ..utils.javarand import JavaRandom
from .handel import HandelParameters

INT32_MAX = np.int32(2**31 - 1)


class BatchedHandel(BatchedProtocol):
    TICK_INTERVAL = 1  # verification capacity is modeled per-ms
    PAYLOAD_WIDTH = 0  # messaging bypasses the generic ring entirely
    CHANNEL_DEPTH = 8  # in-flight slots per (receiver, level)

    def __init__(self, params: HandelParameters):
        self.params = params
        n = params.node_count
        if n & (n - 1):
            raise ValueError("power-of-two node counts only")
        self.n_nodes = n
        self.n_words = max(1, n // 32)
        self.n_levels = n.bit_length()  # levels 0..log2(n)
        # outgoing content at any level fits in the low half of the vector
        self.out_words = max(1, self.n_words // 2)
        self.MSG_TYPES = [f"SIGS_L{l}" for l in range(self.n_levels)]
        self.rel_bits = max(1, (n - 1).bit_length())
        # static level masks
        self.level_masks = np.stack(
            [level_block_mask(l, self.n_words) for l in range(self.n_levels)]
        )
        low = np.zeros_like(self.level_masks)
        acc = np.zeros(self.n_words, dtype=np.uint32)
        for l in range(self.n_levels):
            low[l] = acc  # bits below level l's block == outgoing content
            acc = acc | self.level_masks[l]
        self.low_masks = low

    def msg_size(self, mtype: int) -> int:
        # Size = level + bit field + the signatures included + our own sig
        # (SendSigs, Handel.java:253-258)
        expected = 1 if mtype == 0 else 1 << (mtype - 1)
        return 1 + expected // 8 + 96 * 2

    # -- state ---------------------------------------------------------------
    def proto_init(self, n_nodes: int, pairing: np.ndarray, start_at: np.ndarray):
        n, L = self.n_nodes, self.n_levels
        own = np.zeros((n, self.n_words), dtype=np.uint32)
        own[:, 0] = 1  # bit 0 = own signature (level 0)
        return {
            "agg": jnp.asarray(own),  # lastAggVerified per level block
            "ind": jnp.asarray(own),  # verifiedIndSignatures
            "inc": jnp.asarray(own),  # totalIncoming = agg | ind
            # in-flight channel: D slots per (receiver, level)
            "in_key": jnp.full((n, L, self.CHANNEL_DEPTH), INT32_MAX, jnp.int32),
            "in_sig": jnp.zeros(
                (n, L, self.CHANNEL_DEPTH, self.out_words), jnp.uint32
            ),
            # verification candidate per (receiver, level)
            "pend_key": jnp.full((n, L), INT32_MAX, jnp.int32),
            "cand_sig": jnp.zeros((n, L, self.out_words), jnp.uint32),
            "busy_until": jnp.zeros(n, jnp.int32),
            "pos": jnp.zeros((n, L), jnp.int32),
            "added_cycle": jnp.full(n, self.params.extra_cycle, jnp.int32),
            "sigs_checked": jnp.zeros(n, jnp.int32),
            "msg_filtered": jnp.zeros(n, jnp.int32),
            "pairing": jnp.asarray(pairing, jnp.int32),
            "start_at": jnp.asarray(start_at, jnp.int32),
        }

    # -- helpers -------------------------------------------------------------
    def _outgoing_complete(self, inc, level: int) -> jnp.ndarray:
        want = 1 if level == 1 else 1 << (level - 1)
        low = jnp.asarray(self.low_masks[level])
        return popcount_words(inc & low) == want

    def _incoming_complete(self, inc, level: int) -> jnp.ndarray:
        want = 1 << (level - 1)
        m = jnp.asarray(self.level_masks[level])
        return popcount_words(inc & m) == want

    def _send(self, net, state, mask, from_idx, to_idx, lv, content):
        """Send K messages into the per-(receiver, level, arrival%D) slot;
        earliest arrival wins a slot, ties broken by sender rel index."""
        proto = state.proto
        state, ok, arrival = net.latency_arrivals(
            state, mask, from_idx, to_idx, state.time + 1, lv
        )
        rel = (to_idx ^ from_idx).astype(jnp.int32)
        slot = lax.rem(arrival, jnp.int32(self.CHANNEL_DEPTH))
        # time-relative arrival (>= 2): decremented per tick in
        # _channel_deliver, so the key packing never overflows
        rel_arr = arrival - state.time
        key = jnp.where(ok, (rel_arr << self.rel_bits) | rel, INT32_MAX)
        safe_to = jnp.where(ok, to_idx, self.n_nodes)
        new_key = proto["in_key"].at[safe_to, lv, slot].min(key, mode="drop")
        winner = ok & (new_key[to_idx, lv, slot] == key)
        win_to = jnp.where(winner, to_idx, self.n_nodes)
        new_sig = proto["in_sig"].at[win_to, lv, slot].set(
            content.astype(jnp.uint32), mode="drop"
        )
        return state._replace(
            proto=dict(proto, in_key=new_key, in_sig=new_sig)
        )

    # -- tick phases ---------------------------------------------------------
    def _channel_deliver(self, net, state):
        """Promote due in-flight slots into the verification candidate
        register (onNewSig, Handel.java:752-786) — pure elementwise."""
        proto = state.proto
        t = state.time
        # advance relative arrivals by one tick, then deliver the due ones
        occupied = proto["in_key"] != INT32_MAX
        in_key = jnp.where(
            occupied, proto["in_key"] - (1 << self.rel_bits), proto["in_key"]
        )  # [N, L, D]
        due = occupied & ((in_key >> self.rel_bits) <= 0)
        rel = in_key & ((1 << self.rel_bits) - 1)

        # receiver traffic counters tick for every delivered message
        # (Network.java:611-612, before onNewSig's own filters)
        sizes = jnp.asarray(
            [self.msg_size(l) for l in range(self.n_levels)], jnp.int32
        )
        dm = due.astype(jnp.int32)
        state = state._replace(
            msg_received=state.msg_received + jnp.sum(dm, axis=(1, 2)),
            bytes_received=state.bytes_received
            + jnp.sum(dm * sizes[None, :, None], axis=(1, 2)),
        )

        started = t >= proto["start_at"][:, None, None]
        not_done = (state.done_at == 0)[:, None, None]
        accept = due & started & not_done
        filtered = jnp.sum((due & ~not_done).astype(jnp.int32), axis=(1, 2))

        # candidate priority: fuller content first (the stand-in for the
        # reference's added-sigs scoring), sender rel as tie-break
        content_bits = popcount_words(proto["in_sig"]).astype(jnp.int32)  # [N, L, D]
        half = self.n_nodes // 2
        prio = half + 1 - jnp.minimum(content_bits, half)
        key2 = jnp.where(accept, (prio << self.rel_bits) | rel, INT32_MAX)
        # best due slot per (receiver, level), then fold into the register
        best_d = jnp.argmin(key2, axis=2)  # [N, L]
        best_key = jnp.take_along_axis(key2, best_d[:, :, None], axis=2)[:, :, 0]
        best_sig = jnp.take_along_axis(
            proto["in_sig"], best_d[:, :, None, None], axis=2
        )[:, :, 0, :]
        better = best_key < proto["pend_key"]

        state = state._replace(
            proto=dict(
                proto,
                in_key=jnp.where(due, INT32_MAX, in_key),
                pend_key=jnp.where(better, best_key, proto["pend_key"]),
                cand_sig=jnp.where(better[..., None], best_sig, proto["cand_sig"]),
                msg_filtered=proto["msg_filtered"] + filtered,
            )
        )
        return state

    def _dissemination(self, net, state):
        """Periodic doCycle over open levels (Handel.java:331-343, 452-480)."""
        p = self.params
        proto = state.proto
        t = state.time
        ids = jnp.arange(self.n_nodes, dtype=jnp.int32)

        start = proto["start_at"] + 1
        on_beat = (t >= start) & (
            lax.rem(t - start, jnp.int32(p.dissemination_period_ms)) == 0
        )
        is_done = state.done_at > 0
        may_send = on_beat & ~state.down & (~is_done | (proto["added_cycle"] > 0))
        new_added = jnp.where(
            on_beat & is_done & (proto["added_cycle"] > 0),
            proto["added_cycle"] - 1,
            proto["added_cycle"],
        )

        masks, dests, types, contents = [], [], [], []
        new_pos = proto["pos"]
        for l in range(1, self.n_levels):
            bs = 1 << (l - 1)
            opened = t >= (l - 1) * p.level_wait_time
            complete = self._outgoing_complete(proto["inc"], l)
            mask = may_send & (opened | complete)
            offset = hash32(state.seed, ids, jnp.int32(l)) & (bs - 1)
            rel = (bs + ((new_pos[:, l] + offset) & (bs - 1))).astype(jnp.int32)
            new_pos = new_pos.at[:, l].set(
                jnp.where(mask, new_pos[:, l] + 1, new_pos[:, l])
            )
            masks.append(mask)
            dests.append(ids ^ rel)
            types.append(jnp.full(self.n_nodes, l, jnp.int32))
            contents.append(
                (proto["inc"] & jnp.asarray(self.low_masks[l]))[:, : self.out_words]
            )
        state = state._replace(proto=dict(proto, pos=new_pos, added_cycle=new_added))
        state = self._send(
            net,
            state,
            jnp.concatenate(masks),
            jnp.tile(ids, self.n_levels - 1),
            jnp.concatenate(dests),
            jnp.concatenate(types),
            jnp.concatenate(contents, axis=0),
        )
        return state

    def _verify(self, net, state):
        """checkSigs + updateVerifiedSignatures, one verification per free
        node per tick (capacity = pairingTime serialization)."""
        p = self.params
        proto = state.proto
        t = state.time
        n, L = self.n_nodes, self.n_levels
        ids = jnp.arange(n, dtype=jnp.int32)

        keys = proto["pend_key"]  # [N, L]
        valid = keys < INT32_MAX
        can = (
            (proto["busy_until"] <= t)
            & ~state.down
            & (t >= proto["start_at"] + 1)
            & jnp.any(valid, axis=1)
        )

        # chooseBestFromLevels: uniform random among levels with candidates
        rnd = (hash32(state.seed, t, ids, jnp.int32(0x5EED)).astype(jnp.uint32)
               >> jnp.uint32(8)).astype(jnp.int32)
        vcount = jnp.sum(valid, axis=1).astype(jnp.int32)
        pick = jnp.where(vcount > 0, lax.rem(rnd, jnp.maximum(vcount, 1)), 0)
        cum = jnp.cumsum(valid, axis=1)
        level_sel = jnp.argmax((cum == (pick + 1)[:, None]) & valid, axis=1)

        key_sel = jnp.take_along_axis(keys, level_sel[:, None], axis=1)[:, 0]
        rel = jnp.where(can, key_sel & ((1 << self.rel_bits) - 1), 0)

        # the candidate's exact send-time content, re-addressed into our
        # space by the xor permutation
        cand = jnp.take_along_axis(
            proto["cand_sig"], level_sel[:, None, None], axis=1
        )[:, 0, :]
        pad = jnp.zeros((n, self.n_words - self.out_words), jnp.uint32)
        sig = xor_shuffle(jnp.concatenate([cand, pad], axis=1), rel)
        lmask = jnp.asarray(self.level_masks)[level_sel]
        sig = sig & lmask  # safety: stay within the level block

        canw = can[:, None]
        agg, ind, inc = proto["agg"], proto["ind"], proto["inc"]

        # verifiedIndSignatures.set(from) — the sender bit
        one = np.zeros(self.n_words, dtype=np.uint32)
        one[0] = 1
        ind_bit = xor_shuffle(jnp.broadcast_to(jnp.asarray(one), (n, self.n_words)), rel)
        new_ind = jnp.where(canw, ind | ind_bit, ind)

        # lastAgg replace-on-intersect (Handel.java:714-722)
        agg_l = agg & lmask
        intersects = popcount_words(agg_l & sig) > 0
        new_agg_l = jnp.where(intersects[:, None], sig, agg_l | sig)
        new_agg = jnp.where(canw, (agg & ~lmask) | new_agg_l, agg)
        new_inc = jnp.where(canw, (new_agg | new_ind), inc)

        was_complete = jnp.stack(
            [self._incoming_complete(inc, l) for l in range(1, L)], axis=1
        )
        now_complete = jnp.stack(
            [self._incoming_complete(new_inc, l) for l in range(1, L)], axis=1
        )

        new_keys = jnp.where(
            can[:, None] & (jnp.arange(L)[None, :] == level_sel[:, None]),
            INT32_MAX,
            keys,
        )
        new_busy = jnp.where(can, t + proto["pairing"], proto["busy_until"])
        checked = proto["sigs_checked"] + can.astype(jnp.int32)

        total = popcount_words(new_inc)
        done_now = (state.done_at == 0) & ~state.down & (total >= p.threshold)
        new_done_at = jnp.where(done_now, t, state.done_at)

        state = state._replace(
            done_at=new_done_at,
            proto=dict(
                proto,
                agg=new_agg,
                ind=new_ind,
                inc=new_inc,
                pend_key=new_keys,
                busy_until=new_busy,
                sigs_checked=checked,
            ),
        )

        # fastPath burst: a just-completed incoming level completes the
        # outgoing of the next level -> contact fast_path peers of the first
        # higher level that is still incomplete (Handel.java:738-742)
        just = can & jnp.any(now_complete & ~was_complete, axis=1)
        if p.fast_path > 0:
            out_done = jnp.stack(
                [self._outgoing_complete(new_inc, l) for l in range(1, L)], axis=1
            )
            target_ok = out_done & ~now_complete
            has_target = jnp.any(target_ok, axis=1)
            lsel = (jnp.argmax(target_ok, axis=1) + 1).astype(jnp.int32)
            bs = (1 << (lsel - 1)).astype(jnp.int32)
            fp_mask = just & has_target
            fp = min(p.fast_path, max(1, self.n_nodes // 2))
            offset = hash32(state.seed, ids, lsel, t)
            ks = jnp.arange(fp, dtype=jnp.int32)
            rel_fp = (
                bs[:, None] + ((offset[:, None] + ks[None, :]) & (bs[:, None] - 1))
            ).astype(jnp.int32)
            mask_fp = fp_mask[:, None] & (ks[None, :] < bs[:, None])
            low_sel = jnp.asarray(self.low_masks)[lsel]
            content = (new_inc & low_sel)[:, : self.out_words]
            state = self._send(
                net,
                state,
                mask_fp.reshape(-1),
                jnp.repeat(ids, fp),
                (ids[:, None] ^ rel_fp).reshape(-1),
                jnp.repeat(lsel, fp),
                jnp.repeat(content, fp, axis=0),
            )
        return state

    # -- engine hooks --------------------------------------------------------
    def tick(self, net, state):
        state = self._channel_deliver(net, state)
        state = self._dissemination(net, state)
        state = self._verify(net, state)
        return state

    def all_done(self, state):
        live = ~state.down
        return jnp.all(jnp.where(live, state.done_at > 0, True))


def make_handel(
    params: Optional[HandelParameters] = None,
    capacity: int = 8,  # generic ring unused by this protocol
    seed: int = 0,
):
    """Host-side construction: build the node population with the oracle's
    RNG stream (positions, speed ratios, down set), bake into the engine."""
    params = params or HandelParameters()
    n = params.node_count
    nb = registry_node_builders.get_by_name(params.node_builder_name)
    latency = registry_network_latencies.get_by_name(params.network_latency_name)
    rd = JavaRandom(0)

    from ..oracle.network import Network as ONetwork

    if params.bad_nodes is not None:
        bad_bits = params.bad_nodes
        bad = {i for i in range(n) if (bad_bits >> i) & 1}
    else:
        bad = ONetwork.choose_bad_nodes(rd, n, params.nodes_down)

    nodes = []
    start_at = np.zeros(n, dtype=np.int32)
    for i in range(n):
        if params.desynchronized_start != 0:
            start_at[i] = rd.next_int(params.desynchronized_start)
        nodes.append(Node(rd, nb))
    down = np.array([i in bad for i in range(n)])

    pairing = np.maximum(
        1, (params.pairing_time * np.array([nd.speed_ratio for nd in nodes]))
    ).astype(np.int32)

    city_index = getattr(latency, "city_index", None)
    cols = build_node_columns(nodes, city_index)
    proto = BatchedHandel(params)
    net = BatchedNetwork(proto, latency, n, capacity=capacity)
    state = net.init_state(
        cols,
        seed=seed,
        proto=proto.proto_init(n, pairing, start_at),
        down=down,
    )
    return net, state

"""Batched SanFerminSignature: binomial-tree pairwise aggregation as
vectorized per-tick kernels.

Reference semantics: protocols/SanFerminSignature.java — the swap
request/reply state machine (:229-323), timeout re-picks (:329-369),
goNextLevel descent (:379-419), pairingTime aggregation commit (:434-455) —
via the oracle port `protocols/sanfermin.py`.

TPU-first design:

  * binary-id interval sets (SanFerminHelper.java:46-96) are XOR blocks:
    with W = log2(N), the candidate set at prefix length `cpl` is
    { me ^ (bs + r) : r in [0, bs) } with bs = 2^(W-cpl-1), and the "exact"
    candidate (own-set index pick, SanFerminHelper.java:129-136) is r = 0
    (partner = me ^ bs).  No interval arithmetic at runtime — just XOR.
  * pickNextNodes' used-candidate tracking collapses to ONE cursor per
    node (levels never revisit): position 0 is the exact candidate,
    positions >= 1 enumerate the rest of the block through a per-(node,
    level) XOR bijection — a uniform-random untried pick, standing in for
    the reference's index-order-with-shuffle (and its post-removal index
    shift quirk, SanFerminHelper.java:123-157), which is not worth
    reproducing bit-for-bit.
  * pending_nodes is a packed absolute-id bitset [N, N/32]; reset on level
    entry, bit-tested on replies.
  * one live timeout per node (re-armed on every send).  The oracle stacks
    a timeout per send and fires ALL of them while the level is unchanged
    (SanFerminSignature.java:356-366), so it can re-pick slightly faster
    under repeated NO replies; documented approximation.
  * same-tick transition races (multiple valid REQ/REP arrivals) resolve
    by lowest ring slot; the losers' content is simply not aggregated —
    the oracle's LIFO-in-ms processing picks an equally arbitrary winner
    (every reply is still answered).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..core.node import build_node_columns
from ..core.registries import registry_network_latencies
from ..engine import BatchedNetwork, BatchedProtocol, Emission
from ..engine.rng import hash32
from ..utils.more_math import log2
from .sanfermin import SanFerminSignature, SanFerminSignatureParameters

INT32_MAX = jnp.int32(2**31 - 1)


class BatchedSanFermin(BatchedProtocol):
    MSG_TYPES = ["SWAP_REQ", "SWAP_REP_OK", "SWAP_REP_NO"]
    PAYLOAD_WIDTH = 2  # (level, agg_value)
    TICK_INTERVAL = 1  # timeouts + pairing commits need per-ms ticks

    def __init__(self, params: SanFerminSignatureParameters):
        self.params = params
        self.n_nodes = params.node_count
        self.w = log2(self.n_nodes)
        assert 1 << self.w == self.n_nodes, "node_count must be a power of two"
        self.n_words = max(1, self.n_nodes // 32)

    def msg_size(self, mtype: int) -> int:
        return 4 + self.params.signature_size  # uint32 + sig (both types)

    def proto_init(self, n_nodes: int, seed: int = 0):
        w = self.w
        cache_val = jnp.zeros((n_nodes, w + 1), jnp.int32)
        cache_ok = jnp.zeros((n_nodes, w + 1), bool)
        # the t=1 goNextLevel is pre-applied: cpl = W-1, cache[W-1] = 1
        cache_val = cache_val.at[:, w - 1].set(1)
        cache_ok = cache_ok.at[:, w - 1].set(True)
        # ... including its send bookkeeping (cursor/pending for the
        # exact-candidate + candidate_count initial contacts); the matching
        # emission rows are built by initial_emissions from the same seed
        cc = max(1, self.params.candidate_count)
        eng_seed = jnp.int32(np.int64(seed) & 0x7FFFFFFF)  # matches init_state
        ids = jnp.arange(n_nodes, dtype=jnp.int32)
        cpl0 = jnp.full(n_nodes, w - 1, jnp.int32)
        pending = jnp.zeros((n_nodes, self.n_words), jnp.uint32)
        for j in range(1 + cc):
            partner, ok = self._partner(
                eng_seed, ids, cpl0, jnp.full(n_nodes, j, jnp.int32)
            )
            pending = jnp.where(
                ok[:, None], pending | self._onehot_words(partner), pending
            )
        return {
            "cpl": jnp.full(n_nodes, w - 1, jnp.int32),
            "agg": jnp.ones(n_nodes, jnp.int32),
            "done": jnp.zeros(n_nodes, bool),
            "thr_done": jnp.zeros(n_nodes, bool),
            "thr_at": jnp.zeros(n_nodes, jnp.int32),
            "swapping": jnp.zeros(n_nodes, bool),
            "swap_add": jnp.zeros(n_nodes, jnp.int32),
            "swap_t": jnp.zeros(n_nodes, jnp.int32),
            "cache_val": cache_val,
            "cache_ok": cache_ok,
            "pending": pending,
            "cursor": jnp.full(n_nodes, 1 + cc, jnp.int32),
            "resend": jnp.zeros(n_nodes, bool),  # NO-reply re-pick flag
            "tmo_t": jnp.full(n_nodes, 1 + self.params.reply_timeout, jnp.int32),
            "tmo_lvl": jnp.full(n_nodes, w - 1, jnp.int32),
            "sent_req": jnp.zeros(n_nodes, jnp.int32),
            "recv_req": jnp.zeros(n_nodes, jnp.int32),
        }

    # -- candidate enumeration ----------------------------------------------
    def _bs(self, cpl):
        """Candidate-block size at prefix length cpl: 2^(W-cpl-1)."""
        return (jnp.int32(1) << (self.w - 1 - cpl)).astype(jnp.int32)

    def _partner(self, seed, ids, cpl, position):
        """The `position`-th candidate of node `ids` at level `cpl`:
        position 0 = exact candidate (r=0), then an XOR-bijection walk of
        the rest of the block.  Returns (partner, valid)."""
        bs = self._bs(cpl)
        x = hash32(seed, ids, cpl, jnp.int32(0x5AFE)) & (bs - 1)
        q = position - 1
        p = q + (q >= x).astype(jnp.int32)  # skip the slot that maps to 0
        r = jnp.where(position == 0, 0, p ^ x)
        partner = ids ^ (bs + r)
        return partner, position < bs

    def _onehot_words(self, idx):
        """Absolute-id onehot over the packed [n_words] axis."""
        word = idx // 32
        bit = (jnp.uint32(1) << (idx % 32).astype(jnp.uint32)).astype(jnp.uint32)
        cols = jnp.arange(self.n_words, dtype=jnp.int32)
        return jnp.where(
            cols[None, :] == word[:, None], bit[:, None], jnp.uint32(0)
        )

    def _getbit(self, words, rows, idx):
        """Bit `idx[K]` of the packed row `words[rows[K]]`."""
        w = words[rows, idx // 32]
        return (w >> (idx % 32).astype(jnp.uint32)) & jnp.uint32(1)

    def _send_requests(self, state, mask, entering, proto):
        """_send_to_nodes (SanFerminSignature.java:329-369): contact the
        next candidates — exact-first on level entry, candidate_count per
        re-pick — update pending/cursor, arm the timeout."""
        cc = max(1, self.params.candidate_count)
        k = 1 + cc
        n = self.n_nodes
        ids = jnp.arange(n, dtype=jnp.int32)
        cpl, cursor, agg = proto["cpl"], proto["cursor"], proto["agg"]
        npick = jnp.where(entering, 1 + cc, cc)

        rows_mask, rows_from, rows_to = [], [], []
        pending = proto["pending"]
        for j in range(k):
            pos = cursor + j
            partner, in_block = self._partner(state.seed, ids, cpl, pos)
            m = mask & (j < npick) & in_block
            rows_mask.append(m)
            rows_from.append(ids)
            rows_to.append(partner)
            pending = jnp.where(
                m[:, None], pending | self._onehot_words(partner), pending
            )
        mask_k = jnp.stack(rows_mask, 1).reshape(-1)
        from_k = jnp.stack(rows_from, 1).reshape(-1)
        to_k = jnp.stack(rows_to, 1).reshape(-1)
        em = Emission(
            mask=mask_k,
            from_idx=from_k,
            to_idx=jnp.clip(to_k, 0, n - 1),
            mtype=self.mtype("SWAP_REQ"),
            payload=jnp.stack(
                [
                    jnp.repeat(cpl[:, None], k, 1).reshape(-1),
                    jnp.repeat(agg[:, None], k, 1).reshape(-1),
                ],
                axis=1,
            ),
        )
        proto = dict(
            proto,
            pending=pending,
            cursor=jnp.where(mask, cursor + npick, cursor),
            sent_req=proto["sent_req"]
            + jnp.sum(
                jnp.stack(rows_mask, 1).astype(jnp.int32), axis=1
            ),
            # re-arm the reply timeout (one live timeout per node)
            tmo_t=jnp.where(mask, state.time + 1 + self.params.reply_timeout, proto["tmo_t"]),
            tmo_lvl=jnp.where(mask, cpl, proto["tmo_lvl"]),
        )
        return proto, em

    # -- message handling ----------------------------------------------------
    def deliver(self, net, state, deliver_mask):
        p = self.params
        proto = dict(state.proto)
        n = self.n_nodes
        c = deliver_mask.shape[0]
        t = state.time
        ids = jnp.arange(n, dtype=jnp.int32)
        to, frm = state.msg_to, state.msg_from
        lvl_p = jnp.clip(state.msg_payload[:, 0], 0, self.w)
        val_p = state.msg_payload[:, 1]
        slot = jnp.arange(c, dtype=jnp.int32)

        is_req = deliver_mask & (state.msg_type == self.mtype("SWAP_REQ"))
        is_ok = deliver_mask & (state.msg_type == self.mtype("SWAP_REP_OK"))
        is_no = deliver_mask & (state.msg_type == self.mtype("SWAP_REP_NO"))

        cpl, done, swapping = proto["cpl"], proto["done"], proto["swapping"]
        cache_ok, cache_val = proto["cache_ok"], proto["cache_val"]
        # sender in receiver's candidate set at level L:
        # (me ^ from) in [bs(L), 2*bs(L))  (SanFerminHelper.java:46-96)
        xorv = to ^ frm
        bs_p = (jnp.int32(1) << jnp.clip(self.w - 1 - lvl_p, 0, self.w)).astype(jnp.int32)
        is_cand_at_lvl = (xorv >= bs_p) & (xorv < 2 * bs_p)

        proto["recv_req"] = proto["recv_req"] + jnp.zeros(n, jnp.int32).at[to].add(
            is_req.astype(jnp.int32), mode="drop"
        )

        # ---- on_swap_request (:229-270) -----------------------------------
        lvl_mismatch = done[to] | (lvl_p != cpl[to])
        cached = cache_ok[to, lvl_p]
        # case A1: stale/done receiver with a cached value -> OK(cached)
        a1 = is_req & lvl_mismatch & cached
        # case A2: stale/done receiver, no cache -> NO(0) at receiver's cpl,
        # remembering the offered value when the sender is a candidate
        a2 = is_req & lvl_mismatch & ~cached
        # case B: level match while swapping -> optimistic OK(agg)
        b = is_req & ~lvl_mismatch & swapping[to]
        # case C: level match, idle -> valid swap request (transition)
        c_req = is_req & ~lvl_mismatch & ~swapping[to] & is_cand_at_lvl

        # replies: cases A1/A2/B only — a valid swap REQUEST (case C) is
        # absorbed into the receiver's transition and NEVER answered; the
        # requester is rescued by its reply timeout (the reference's
        # requester-loses asymmetry, SanFerminSignature.java:251-262)
        rep_ok = a1 | b
        rep_val = jnp.where(a1, cache_val[to, lvl_p], proto["agg"][to])
        rep_lvl = jnp.where(a2, cpl[to], lvl_p)
        reply_em = Emission(
            mask=a1 | a2 | b,
            from_idx=to,
            to_idx=frm,
            mtype=jnp.where(
                rep_ok, self.mtype("SWAP_REP_OK"), self.mtype("SWAP_REP_NO")
            ),
            payload=jnp.stack([rep_lvl, jnp.where(rep_ok, rep_val, 0)], axis=1),
        )

        # A2 cache store (winner = lowest slot per (node, level))
        store = a2 & is_cand_at_lvl
        winner = jnp.full((n, self.w + 1), c, jnp.int32)
        winner = winner.at[to, lvl_p].min(jnp.where(store, slot, c), mode="drop")
        is_wstore = store & (winner[to, lvl_p] == slot)
        # scatter ONLY the winner rows (losers routed out of bounds):
        # writing `where(win, new, current)` for every row would race —
        # XLA's duplicate-index .set order is unspecified, so a stale row's
        # "current" write can clobber the winner's value
        w_to = jnp.where(is_wstore, to, n)
        cache_val = cache_val.at[w_to, lvl_p].set(val_p, mode="drop")
        cache_ok = cache_ok.at[w_to, lvl_p].set(True, mode="drop")
        proto["cache_val"], proto["cache_ok"] = cache_val, cache_ok

        # ---- on_swap_reply (:272-323) -------------------------------------
        live = ~done[to] & (lvl_p == cpl[to]) & ~swapping[to]
        in_pending = self._getbit(proto["pending"], to, frm) == 1
        ok_trigger = is_ok & live & (in_pending | is_cand_at_lvl)
        no_trigger = is_no & live & in_pending

        # ---- transitions: winner per node among C + OK triggers -----------
        trig = c_req | ok_trigger
        twin = jnp.full(n, c, jnp.int32)
        twin = twin.at[to].min(jnp.where(trig, slot, c), mode="drop")
        has_t = twin < c
        tslot = jnp.clip(twin, 0, c - 1)
        add_val = val_p[tslot]
        proto["swapping"] = swapping | has_t
        proto["swap_add"] = jnp.where(has_t, add_val, proto["swap_add"])
        proto["swap_t"] = jnp.where(has_t, t + p.pairing_time, proto["swap_t"])

        # NO replies from pending partners re-pick next candidates in the
        # tick phase (flag survives until consumed)
        got_no = jnp.zeros(n, bool).at[to].max(no_trigger, mode="drop")
        proto["resend"] = proto["resend"] | got_no

        return state._replace(proto=proto), [reply_em]

    # -- per-tick: commits, level descent, timeouts, sends -------------------
    def tick(self, net, state):
        p = self.params
        proto = dict(state.proto)
        t = state.time
        n = self.n_nodes
        w = self.w

        # 1. aggregation commit at swap_t (do_aggregate + goNextLevel,
        # :434-455, :379-419)
        commit = proto["swapping"] & (t >= proto["swap_t"]) & (proto["swap_t"] > 0)
        agg = jnp.where(commit, proto["agg"] + proto["swap_add"], proto["agg"])

        thr_now = commit & ~proto["thr_done"] & (agg >= p.threshold)
        proto["thr_done"] = proto["thr_done"] | thr_now
        proto["thr_at"] = jnp.where(thr_now, t + 2 * p.pairing_time, proto["thr_at"])

        finish = commit & (proto["cpl"] == 0)
        descend = commit & ~finish
        proto["done"] = proto["done"] | finish
        state = state._replace(
            done_at=jnp.where(finish, t + 2 * p.pairing_time, state.done_at)
        )

        new_cpl = jnp.where(descend, proto["cpl"] - 1, proto["cpl"])
        lvl_row = jnp.arange(w + 1, dtype=jnp.int32)[None, :]
        proto["cache_val"] = jnp.where(
            descend[:, None] & (lvl_row == new_cpl[:, None]),
            agg[:, None],
            proto["cache_val"],
        )
        proto["cache_ok"] = proto["cache_ok"] | (
            descend[:, None] & (lvl_row == new_cpl[:, None])
        )
        proto["agg"] = agg
        proto["cpl"] = new_cpl
        proto["swapping"] = proto["swapping"] & ~commit
        proto["pending"] = jnp.where(
            descend[:, None], jnp.uint32(0), proto["pending"]
        )
        proto["cursor"] = jnp.where(descend, 0, proto["cursor"])
        proto["resend"] = proto["resend"] & ~commit

        # 2. reply timeout (fires while the level is unchanged, :356-366)
        tmo = (
            ~proto["done"]
            & (proto["tmo_t"] > 0)
            & (t >= proto["tmo_t"])
            & (proto["tmo_lvl"] == proto["cpl"])
        )
        # disarm on fire (or when the level moved on); _send_requests
        # re-arms for the nodes that actually send
        stale = (proto["tmo_t"] > 0) & (t >= proto["tmo_t"])
        proto["tmo_t"] = jnp.where(stale, 0, proto["tmo_t"])

        # 3. sends: level entry (exact-first) or re-pick (timeout / NO)
        send = (descend | tmo | proto["resend"]) & ~proto["done"]
        send = send & (proto["cursor"] < self._bs(proto["cpl"]))
        proto["resend"] = proto["resend"] & ~send
        proto, em = self._send_requests(state, send, descend, proto)
        state = state._replace(proto=proto)
        return net.apply_emission(state, em)

    def initial_emissions(self, net, state):
        """The pre-applied t=1 goNextLevel's sends: every node contacts its
        exact candidate (+ candidate_count more).  The matching cursor /
        pending / timeout bookkeeping is already baked into proto_init
        (same seed, same _partner walk), so this only builds the rows."""
        cc = max(1, self.params.candidate_count)
        k = 1 + cc
        n = self.n_nodes
        ids = jnp.arange(n, dtype=jnp.int32)
        cpl = state.proto["cpl"]
        rows_mask, rows_to = [], []
        for j in range(k):
            partner, in_block = self._partner(
                state.seed, ids, cpl, jnp.full(n, j, jnp.int32)
            )
            rows_mask.append(in_block)
            rows_to.append(partner)
        return [
            Emission(
                mask=jnp.stack(rows_mask, 1).reshape(-1),
                from_idx=jnp.repeat(ids, k),
                to_idx=jnp.clip(jnp.stack(rows_to, 1).reshape(-1), 0, n - 1),
                mtype=self.mtype("SWAP_REQ"),
                payload=jnp.stack(
                    [
                        jnp.repeat(cpl[:, None], k, 1).reshape(-1),
                        jnp.repeat(state.proto["agg"][:, None], k, 1).reshape(-1),
                    ],
                    axis=1,
                ),
            )
        ]

    def all_done(self, state):
        return jnp.all(state.proto["done"])


def make_sanfermin(
    params: Optional[SanFerminSignatureParameters] = None,
    capacity: int = 1 << 14,
    seed: int = 0,
):
    """Host-side construction: the oracle builds the node population (same
    JavaRandom stream → same layout), baked into the engine."""
    params = params or SanFerminSignatureParameters()
    oracle = SanFerminSignature(params)
    net_o = oracle.network()
    latency = registry_network_latencies.get_by_name(params.network_latency_name)
    city_index = getattr(latency, "city_index", None)
    cols = build_node_columns(net_o.all_nodes, city_index)
    proto = BatchedSanFermin(params)
    net = BatchedNetwork(proto, latency, params.node_count, capacity=capacity)
    state = net.init_state(
        cols, seed=seed, proto=proto.proto_init(params.node_count, seed=seed)
    )
    return net, state

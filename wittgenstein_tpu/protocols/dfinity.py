"""Dfinity consensus: three node roles — block producers, attester
committees, and a random-beacon committee — driving a notarized chain with
3-second rounds.

Reference semantics: protocols/Dfinity.java (block comparator :107-130,
messages :132-186, BlockProducerNode :215-263, AttesterNode :265-351,
RandomBeaconNode :353-424, init :426-450).  Quirks kept: the parameters
object owns the genesis/node lists (so copy() shares them — the reason the
reference's own copy test is disabled), the networkLatencyName parameter is
never read (callers set latency on the network directly, as DfinityTest
does), and RandomBeaconNode.onBlock's inverted return values.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Set

from ..core.params import WParameters, register_protocol
from ..core.registries import registry_node_builders
from ..oracle.blockchain import Block, BlockChainNetwork, BlockChainNode, SendBlock
from ..oracle.messages import Message
from ..oracle.network import Protocol


class DfinityBlock(Block):
    @staticmethod
    def create_genesis() -> "DfinityBlock":
        return DfinityBlock(genesis=True)

    def __init__(self, producer=None, height=0, head=None, valid=True, time=0, genesis=False):
        if genesis:
            super().__init__(height=0, genesis=True)
        else:
            super().__init__(producer, height, head, valid, time)


def dfinity_block_cmp(o1: DfinityBlock, o2: DfinityBlock) -> int:
    """(Dfinity.java:107-130) — note the reference's self-comparison quirk
    on the last line (compares o1's producer id with itself, i.e. ties
    resolve to 0)."""
    if o1 is o2:
        return 0
    if not o2.valid:
        return 1
    if not o1.valid:
        return -1
    if o1.has_direct_link(o2):
        return -1 if o1.height < o2.height else 1
    if o1.height != o2.height:
        return -1 if o1.height < o2.height else 1
    assert o1.producer is not None
    return 0  # Long.compare(o1.producer.nodeId, o1.producer.nodeId)


@dataclasses.dataclass
class DfinityParameters(WParameters):
    block_producers_count: int = 10
    attesters_count: int = 10
    attesters_per_round: int = 10
    block_construction_time: int = 1
    attestation_construction_time: int = 1
    percentage_dead_attester: int = 0
    node_builder_name: Optional[str] = None
    network_latency_name: Optional[str] = None  # never read — reference quirk

    round_time: int = dataclasses.field(default=3000, init=False, repr=False)
    block_producers_per_round: int = dataclasses.field(default=5, init=False, repr=False)

    def __post_init__(self):
        self.block_producers_round = self.block_producers_count // self.block_producers_per_round
        self.attesters_round = self.attesters_count // self.attesters_per_round
        # simplification: the beacon committee has the attesters' size
        self.random_beacon_count = self.attesters_per_round
        self.majority = (self.attesters_per_round // 2) + 1
        # mutable protocol state living on the params object, like the
        # reference (Dfinity.java:35-40)
        self.genesis = DfinityBlock.create_genesis()
        self.attesters: List[AttesterNode] = []
        self.bps: List[BlockProducerNode] = []
        self.rds: List[RandomBeaconNode] = []


class BlockProposal(Message):
    def __init__(self, block: DfinityBlock):
        self.block = block

    def action(self, network, from_node, to_node):
        to_node.on_proposal(self.block)


class Vote(Message):
    def __init__(self, vote_for: DfinityBlock):
        self.vote_for = vote_for

    def action(self, network, from_node, to_node):
        to_node.on_vote(from_node, self.vote_for)


class RandomBeaconExchange(Message):
    def __init__(self, height: int):
        self.height = height

    def action(self, network, from_node, to_node):
        to_node.on_random_beacon_exchange(from_node, self.height)


class RandomBeaconResult(Message):
    def __init__(self, height: int, rd: int):
        self.height = height
        self.rd = rd

    def action(self, network, from_node, to_node):
        to_node.on_random_beacon(self.height, self.rd)


class DfinityNode(BlockChainNode):
    __slots__ = ("committee_majority_blocks", "committee_majority_height", "last_random_beacon", "_p")

    def __init__(self, p: "Dfinity", genesis: DfinityBlock):
        super().__init__(p.network().rd, p.nb, False, genesis)
        self._p = p
        self.committee_majority_blocks: Set[int] = set()
        self.committee_majority_height: Set[int] = set()
        self.last_random_beacon = 0

    def best(self, o1: DfinityBlock, o2: DfinityBlock) -> DfinityBlock:
        return o1 if dfinity_block_cmp(o1, o2) >= 0 else o2

    def on_vote(self, voter, vote_for: DfinityBlock) -> None:
        pass

    def on_random_beacon(self, height: int, rd: int) -> None:
        """Can be called multiple times for a single node."""
        if self.last_random_beacon < height:
            self.last_random_beacon = height
            self.on_random_beacon_once(height, rd)

    def on_random_beacon_once(self, height: int, rd: int) -> None:
        pass

    def on_proposal(self, b: DfinityBlock) -> None:  # only attesters receive these
        raise NotImplementedError


class BlockProducerNode(DfinityNode):
    __slots__ = ("my_round", "wait_for_block_height")

    def __init__(self, p: "Dfinity", my_round: int, genesis: DfinityBlock):
        super().__init__(p, genesis)
        self.my_round = my_round
        self.wait_for_block_height = -1

    def create_proposal(self, height: int) -> None:
        """(Dfinity.java:225-240)."""
        net, params = self._p.network(), self._p.params
        if self.head.height != height - 1:
            raise ValueError(f"head={self.head.height}, height={height}")
        new_block = DfinityBlock(self, height, self.head, True, net.time)
        attesters_s = list(params.attesters)
        net.rd.shuffle(attesters_s)
        net.send(
            BlockProposal(new_block),
            net.time + params.block_construction_time,
            self,
            attesters_s,
        )
        self.wait_for_block_height = -1

    def on_block(self, b: DfinityBlock) -> bool:
        if not super().on_block(b):
            return False
        if self.head.height == self.wait_for_block_height:
            self.create_proposal(self.wait_for_block_height + 1)
        return True

    def on_random_beacon_once(self, h: int, rd: int) -> None:
        """If randomly selected, propose (or wait for the parent block)."""
        if rd % self._p.params.block_producers_round == self.my_round:
            if self.head.height == h - 1:
                self.create_proposal(h)


class AttesterNode(DfinityNode):
    __slots__ = ("votes", "proposals", "my_round", "vote_for_height")

    def __init__(self, p: "Dfinity", my_round: int, genesis: DfinityBlock):
        super().__init__(p, genesis)
        self.votes: Dict[int, Set[int]] = {}
        self.proposals: List[DfinityBlock] = []
        self.my_round = my_round
        self.vote_for_height = -1

    def on_vote(self, voter, vote_for: DfinityBlock) -> None:
        voters = self.votes.setdefault(vote_for.id, set())
        if self.vote_for_height == vote_for.height:
            if voter.node_id not in voters:
                voters.add(voter.node_id)
                if len(voters) >= self._p.params.majority:
                    self._send_block(vote_for)

    def _send_block(self, vote_for: DfinityBlock) -> None:
        self.committee_majority_blocks.add(vote_for.id)
        self.committee_majority_height.add(vote_for.height)
        self.vote_for_height = -1
        self._p.network().send_all(SendBlock(vote_for), self)

    def on_proposal(self, b: DfinityBlock) -> None:
        """Vote for proposals at our height; at majority, notarize and
        broadcast (Dfinity.java:298-318)."""
        net, params = self._p.network(), self._p.params
        if self.vote_for_height == b.height:
            voters = self.votes.setdefault(b.id, set())
            if self.node_id not in voters:
                voters.add(self.node_id)
                if len(voters) >= params.majority:
                    self._send_block(b)
                else:
                    v = Vote(b)
                    attesters_s = list(params.attesters)
                    net.rd.shuffle(attesters_s)
                    net.send(
                        v, net.time + params.attestation_construction_time, self, attesters_s
                    )
        elif b.height > self.head.height:
            # buffer proposals received in advance
            self.proposals.append(b)

    def on_block(self, b: DfinityBlock) -> bool:
        if not super().on_block(b):
            return False
        self.committee_majority_blocks.add(b.id)
        self.committee_majority_height.add(b.height)
        if self.vote_for_height == b.height:
            self.vote_for_height = -1
        return True

    def on_random_beacon_once(self, h: int, rd: int) -> None:
        """(Dfinity.java:335-350)."""
        net, params = self._p.network(), self._p.params
        if rd % params.attesters_round == self.my_round and h not in self.committee_majority_height:
            self.vote_for_height = h
            sent: Set[DfinityBlock] = set()
            for b in self.proposals:
                if b.height == h and b not in sent:
                    sent.add(b)
                    v = Vote(b)
                    attesters_s = list(params.attesters)
                    net.rd.shuffle(attesters_s)
                    net.send(
                        v, net.time + params.attestation_construction_time, self, attesters_s
                    )
            self.proposals.clear()


class RandomBeaconNode(DfinityNode):
    __slots__ = ("rd_value", "height", "last_rd_sent", "exchanged")

    def __init__(self, p: "Dfinity", genesis: DfinityBlock):
        super().__init__(p, genesis)
        self.rd_value = 0
        self.height = 1
        self.last_rd_sent = 0
        self.exchanged: Dict[int, Set[int]] = {}

    def on_random_beacon_exchange(self, from_node: "RandomBeaconNode", height: int) -> None:
        if height >= self.height and height > self.last_rd_sent:
            voters = self.exchanged.setdefault(height, set())
            if from_node.node_id not in voters:
                voters.add(from_node.node_id)
                if height == self.height and len(voters) >= self._p.params.majority:
                    self.send_rb()

    def send_rb(self) -> None:
        net, params = self._p.network(), self._p.params
        self.rd_value = self.height  # height as a stand-in for threshold sigs
        self.last_rd_sent = self.height
        rb = RandomBeaconResult(self.height, self.rd_value)
        net.send_all(rb, self, net.time + params.attestation_construction_time)

    def on_block(self, b: DfinityBlock) -> bool:
        """A block at our height starts the next beacon round.  Note the
        reference's inverted returns (true on reject, false on success —
        Dfinity.java:387-410), kept verbatim."""
        net, params = self._p.network(), self._p.params
        if not super().on_block(b):
            return True
        if self.head.height == self.height:
            self.height += 1
            voters = self.exchanged.setdefault(self.height, set())
            if self.node_id not in voters:
                voters.add(self.node_id)
                if len(voters) >= params.majority:
                    self.send_rb()
                    return False
            # the len-check replays the reference's `voters.add(id) &&
            # size >= majority` short-circuit: add failed or not enough
            assert self.head.parent is not None
            wt = self.head.parent.proposal_time + params.round_time * 2
            if wt <= net.time:
                wt = net.time + params.attestation_construction_time
            rbe = RandomBeaconExchange(self.height)
            rds_sends = list(params.rds)
            net.rd.shuffle(rds_sends)
            net.send(rbe, wt, self, rds_sends)
        return False

    def on_random_beacon_once(self, h: int, rd: int) -> None:
        """Accept a beacon generated by others before we finished."""
        if h > self.height:
            self.last_rd_sent = self.height
            self.height = h
            self.rd_value = rd


class _ObserverNode(DfinityNode):
    """The anonymous DfinityNode subclass used as observer (Dfinity.java:89)."""
    __slots__ = ()


@register_protocol("Dfinity", DfinityParameters)
class Dfinity(Protocol):
    def __init__(self, params: DfinityParameters):
        self.params = params
        self._network: BlockChainNetwork = BlockChainNetwork()
        self.nb = registry_node_builders.get_by_name(params.node_builder_name)
        # NOTE: network_latency_name is not applied — the reference never
        # reads it (Dfinity.java:86-90); callers override network latency
        # directly (DfinityTest.java:18)
        self._network.add_observer(_ObserverNode(self, params.genesis))

    def network(self) -> BlockChainNetwork:
        return self._network

    def copy(self) -> "Dfinity":
        return Dfinity(self.params)

    def init(self) -> None:
        """(Dfinity.java:426-450)."""
        p, net = self.params, self._network
        for i in range(p.attesters_count):
            n = AttesterNode(self, i % p.attesters_round, p.genesis)
            p.attesters.append(n)
            net.add_node(n)
        for i in range(p.block_producers_count):
            n = BlockProducerNode(self, i % p.block_producers_round, p.genesis)
            p.bps.append(n)
            net.add_node(n)
        for _ in range(p.random_beacon_count):
            n = RandomBeaconNode(self, p.genesis)
            p.rds.append(n)
            net.add_node(n)
        net.rd.shuffle(p.bps)
        for n in p.rds:
            n.send_rb()


def main():
    from ..oracle.blockchain import Block

    Block.reset_block_ids()
    bc = Dfinity(DfinityParameters())
    bc.init()
    bc.network().run(50)
    bc.network().partition(0.20)
    bc.network().run(2_000)
    bc.network().end_partition()
    bc.network().run(50)
    bc.network().print_stat(False)


if __name__ == "__main__":
    main()

"""Batched ENRGossiping: node-record gossip with churn on the TPU engine.

Re-expression of protocols/ENRGossiping.java (via the oracle port
protocols/enr_gossiping.py) — the last protocol family to get a batched
twin, because BOTH static axes of the engine mutate at runtime: the node
set grows (a joiner every timeToLeave/8 ms, ENRGossiping.java:284-293)
and the peer graph is surgical (addedValue / removeWorseIfPossible,
:296-322, :417-438).  The batched design follows
docs/enr_batched_design.md:

  * **Preallocated slots**: M = nodes + horizon/(timeToLeave/8) + 1;
    unborn slots are protocol-dead (`alive` mask — NOT the engine's
    `down` column, which would drop their birth wake-ups) with a
    host-sampled `born_at`/`exit_at`/first-broadcast schedule; the birth
    event wires total_peers links to hash-ranked alive slots.
  * **Dense adjacency** [M, M] bool replaces the peer lists; link
    create/remove are symmetric writes; scores read LIVE capabilities —
    the record is only a discovery ping (design note).
  * **Scores in closed form**: k_c = matching-cap neighbor counts (one
    [M, M] @ [M, C] product); score = sum_c k_c * min(k_c, 3)
    (score_of, ENRGossiping.java:395-409); addedValue and the
    remove-worst scan are the same expression with one row toggled.
  * **Per-cap reachability**: isFullyConnected's BFS (:330-360) becomes
    a boolean-matmul transitive closure per capability, evaluated only
    for nodes touched by an event this ms (birth or either side of a
    connect) — the oracle, too, only re-checks on those events.
  * **Event-driven time**: TICK_INTERVAL=None; births, exits, capability
    changes and gossip beats are size-0 self-messages with explicit
    arrivals, so the engine's empty-ms jump skips the (huge: beats are
    minutes apart) gaps — the batched analog of the oracle's DES queue.

Distribution-level approximations (each deliberate):
  * joiner peer choice / changed capability sets come from counter-hash
    top-k draws instead of the oracle's retry loops over its live rd
    stream (the oracle interleaves those draws with traffic, so stream-
    exact replay is impossible by construction);
  * one on_flood peer-evaluation per receiver per ms (the lowest-slot
    winner); same-ms duplicates still dedup + forward;
  * same-ms connect races: removals apply before additions, and a
    same-ms degree check may transiently exceed max_peers by the number
    of simultaneous connectors (the oracle serializes within the ms).

The oracle's done_at quirk is carried exactly: done_at stores the
RELATIVE time max(1, t - start_time) (set_done_at, enr_gossiping.py),
not the absolute time every other protocol stores.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..core.node import build_node_columns
from ..core.registries import registry_network_latencies, registry_node_builders
from ..engine import BatchedNetwork, BatchedProtocol, Emission
from ..engine.rng import hash32
from .enr_gossiping import PEERS_PER_CAP, ENRGossiping, ENRParameters

INT32_MAX = np.int32(2**31 - 1)


class BatchedENR(BatchedProtocol):
    MSG_TYPES = ["RECORD", "WAKE"]
    PAYLOAD_WIDTH = 2  # (source, seq)
    TICK_INTERVAL = None  # event-driven: wakes carry the schedule
    # deliver arrivals on an 8 ms grid (each delayed < 8 ms): ENR's
    # observables (record propagation, join/leave dynamics) live at the
    # seconds scale, and gossip traffic lands nearly every ms, so exact
    # arrival times buy nothing but ~8x more loop iterations
    TIME_QUANTUM = 8

    def __init__(self, params: ENRParameters, m_slots: int, schedule: dict):
        self.params = params
        self.m = m_slots
        self.n_caps = params.number_of_different_capabilities
        self.schedule = schedule  # host-side columns, see make_enr

    def msg_size(self, mtype: int) -> int:
        return [1, 0][mtype]  # Record size 1; wakes are task-style

    # -- capability scoring (closed form) ------------------------------------
    def _kc(self, adj, caps, own):
        """k_c[i, c] = matching-cap neighbor counts: adjacent holders of c,
        counted only for c in i's own set."""
        k = adj.astype(jnp.int32) @ caps.astype(jnp.int32)
        return k * own.astype(jnp.int32)

    @staticmethod
    def _score_from_counts(k):
        """score_of: each cap contributes k_c * min(k_c, PEERS_PER_CAP)."""
        return jnp.sum(k * jnp.minimum(k, PEERS_PER_CAP), axis=-1)

    def _gen_caps(self, seed, ids, salt):
        """cap_per_node distinct capabilities per node: top-k of hashed
        per-cap scores (the oracle's retry loop, distribution-level)."""
        c = self.n_caps
        scores = hash32(seed, ids[:, None], jnp.arange(c, dtype=jnp.int32)[None, :], salt)
        kth = jnp.sort(scores, axis=1)[:, c - self.params.cap_per_node]
        return scores >= kth[:, None]

    # -- flood forwarding ----------------------------------------------------
    def _forward(self, state, src, src_of_record, seq, mask, exclude):
        """Winners forward record (src_of_record, seq) to all their live
        peers except `exclude`, with Record(local_delay=10,
        delay_between_peers=10) spacing (enr_gossiping Record ctor)."""
        adjm = state.proto["adj"]
        k = src.shape[0]
        m = self.m
        src_r = jnp.repeat(src, m)
        dest = jnp.broadcast_to(jnp.arange(m, dtype=jnp.int32)[None, :], (k, m)).reshape(-1)
        ok = (
            jnp.repeat(mask, m)
            & adjm[src].reshape(-1)
            & (dest != jnp.repeat(exclude, m))
            & state.proto["alive"][dest]
        )
        base = state.time + 1 + 10  # local_delay = 10
        rank = (jnp.cumsum(ok.reshape(k, m), axis=1) - 1).reshape(-1)
        send_time = jnp.broadcast_to(base, rank.shape) + rank.astype(jnp.int32) * 11
        payload = jnp.stack(
            [jnp.repeat(src_of_record, m), jnp.repeat(seq, m)], axis=1
        )
        return Emission(
            mask=ok,
            from_idx=src_r,
            to_idx=dest,
            mtype=self.mtype("RECORD"),
            payload=payload,
            send_time=send_time,
        )

    def _wake(self, state, ids, mask, arrival):
        return Emission(
            mask=mask,
            from_idx=ids,
            to_idx=ids,
            mtype=self.mtype("WAKE"),
            payload=jnp.zeros((ids.shape[0], 2), jnp.int32),
            arrival=arrival,
        )

    # -- state ---------------------------------------------------------------
    def proto_init(self, n_nodes: int):
        s = self.schedule
        return {
            "alive": jnp.asarray(s["alive0"]),
            "caps": jnp.asarray(s["caps0"]),
            "adj": jnp.asarray(s["adj0"]),
            "seen": jnp.full((self.m, self.m), -1, jnp.int32),
            "records": jnp.zeros(self.m, jnp.int32),
            "start_time": jnp.zeros(self.m, jnp.int32),
            "born_at": jnp.asarray(s["born_at"]),
            "exit_at": jnp.asarray(s["exit_at"]),
            "bcast_next": jnp.asarray(s["bcast0"]),
            "change_next": jnp.asarray(s["change0"]),
            # time of the previous engine step: schedule checks fire on
            # WINDOW CROSSING (last_t < sched <= t), not equality, so the
            # TIME_QUANTUM-coarsened jump cannot step over an event
            "last_t": jnp.int32(-1),
        }

    def initial_emissions(self, net, state):
        p = self.proto_initial_wakes(state)
        return p

    def proto_initial_wakes(self, state):
        proto = state.proto
        ids = jnp.arange(self.m, dtype=jnp.int32)
        ems = []
        for col, guard in (
            ("born_at", proto["born_at"] > 0),
            ("exit_at", proto["exit_at"] < INT32_MAX),
            ("bcast_next", proto["bcast_next"] < INT32_MAX),
            ("change_next", proto["change_next"] < INT32_MAX),
        ):
            ems.append(self._wake(state, ids, guard, proto[col]))
        return ems

    # -- the event handler ---------------------------------------------------
    def deliver(self, net, state, deliver_mask):
        p = self.params
        proto = state.proto
        t = state.time
        m = self.m
        ids = jnp.arange(m, dtype=jnp.int32)
        alive, caps, adj = proto["alive"], proto["caps"], proto["adj"]
        emissions = []
        touched = jnp.zeros(m, bool)  # nodes needing a done re-check

        # schedules fire when crossed by this step's window (last_t, t] —
        # robust to TIME_QUANTUM-coarsened jumps that skip the exact ms
        last_t = proto["last_t"]
        crossed = lambda sched: (sched > last_t) & (sched <= t)

        # ---- births (the _add_new_node beat, ENRGossiping.java:284-293;
        # the t=0 joiner is wired host-side in make_enr like the oracle's)
        born = ~alive & crossed(proto["born_at"]) & (proto["born_at"] > 0)
        # total_peers hash-ranked alive targets per newborn
        rank = hash32(state.seed, t, ids[:, None], ids[None, :])
        eligible = alive[None, :] & (ids[None, :] != ids[:, None])
        rank = jnp.where(eligible, rank & 0x7FFFFFFF, INT32_MAX)
        order = jnp.argsort(rank, axis=1)[:, : p.total_peers]  # [M, tp]
        sel_ok = (
            jnp.take_along_axis(rank, order, axis=1) != INT32_MAX
        ) & born[:, None]
        row_new = jnp.zeros((m, m), bool)
        row_new = row_new.at[
            jnp.where(sel_ok, ids[:, None], m), jnp.where(sel_ok, order, m)
        ].set(True, mode="drop")
        adj = adj | row_new | row_new.T
        alive = alive | born
        start_time = jnp.where(born, t, proto["start_time"])
        touched = touched | born

        # ---- exits (exit_network: disconnect + stop, :198-207)
        exiting = alive & crossed(proto["exit_at"])
        keep = ~exiting
        adj = adj & keep[:, None] & keep[None, :]
        alive = alive & ~exiting

        # ---- capability changes (change_cap + periodic re-arm)
        changing = alive & crossed(proto["change_next"])
        new_caps = self._gen_caps(state.seed, ids, t)
        caps = jnp.where(changing[:, None], new_caps, caps)
        change_next = jnp.where(
            changing, proto["change_next"] + jnp.int32(p.time_to_change), proto["change_next"]
        )
        emissions.append(self._wake(state, ids, changing, change_next))

        # ---- gossip beats (broadcast_capabilities + periodic re-arm)
        bcast = alive & crossed(proto["bcast_next"])
        announce = bcast | changing  # change_cap also floods a fresh record
        records = proto["records"]
        seq_out = records
        records = records + announce.astype(jnp.int32)
        # originators never reprocess their own record
        seen = proto["seen"].at[ids, ids].max(jnp.where(announce, seq_out, -1))
        bcast_next = jnp.where(
            bcast, proto["bcast_next"] + jnp.int32(p.cap_gossip_time), proto["bcast_next"]
        )
        emissions.append(self._wake(state, ids, bcast, bcast_next))

        state = state._replace(
            proto=dict(
                proto,
                alive=alive,
                caps=caps,
                adj=adj,
                records=records,
                start_time=start_time,
                change_next=change_next,
                bcast_next=bcast_next,
                last_t=t,
            )
        )
        emissions.append(
            self._forward(state, ids, ids, seq_out, announce, jnp.full(m, -1, jnp.int32))
        )

        # ---- record deliveries: dedup, forward, evaluate source as peer
        is_rec = deliver_mask & (state.msg_type == self.mtype("RECORD"))
        to = state.msg_to
        src = state.msg_payload[:, 0]
        seq = state.msg_payload[:, 1]
        fresh = is_rec & alive[to] & (seq > seen[to, src])
        c = deliver_mask.shape[0]
        slot = jnp.arange(c, dtype=jnp.int32)
        # highest seq per (to, src) wins the dedup table
        seen = seen.at[to, src].max(jnp.where(fresh, seq, -1), mode="drop")
        win = fresh & (seen[to, src] == seq)
        # winner slot per (to, src) forwards (FloodMessage dedup-and-forward)
        wslot = jnp.full((m, m), c, jnp.int32)
        wslot = wslot.at[to, src].min(jnp.where(win, slot, c), mode="drop")
        fwd = win & (wslot[to, src] == slot)
        emissions.append(
            self._forward(state, to, src, seq, fwd, state.msg_from)
        )

        # one peer-evaluation per receiver per ms: its lowest winning slot
        rslot = jnp.full(m, c, jnp.int32)
        rslot = rslot.at[to].min(jnp.where(fwd, slot, c), mode="drop")
        ev = fwd & (rslot[to] == slot)
        # gather the (i, s) pairs as per-node columns
        eval_src = jnp.full(m, -1, jnp.int32)
        eval_src = eval_src.at[jnp.where(ev, to, m)].set(src, mode="drop")
        has_eval = eval_src >= 0
        s_idx = jnp.maximum(eval_src, 0)

        # on_flood (:296-322): canConnect + addedValue + removeWorse
        adj = state.proto["adj"]
        caps = state.proto["caps"]
        deg = jnp.sum(adj, axis=1).astype(jnp.int32)
        k0 = self._kc(adj, caps, caps)  # [M, C]
        s0 = self._score_from_counts(k0)  # current score_of(peers)
        cap_s = caps[s_idx]  # source capabilities [M, C]
        match_s = (cap_s & caps).astype(jnp.int32)
        s_add = self._score_from_counts(k0 + match_s)
        added_value = s_add - s0
        can = (
            has_eval
            & alive
            & alive[s_idx]
            & (deg[s_idx] < p.max_peers)
            & ~adj[ids, s_idx]
            & (added_value != 0)
        )

        # removeWorseIfPossible (:417-438): best single-peer swap
        match_j = (caps[None, :, :] & caps[:, None, :]).astype(jnp.int32)  # [i, j, C]
        k_swap = k0[:, None, :] - match_j + match_s[:, None, :]
        s_swap = jnp.where(
            adj, self._score_from_counts(k_swap), jnp.int32(-(2**30))
        )  # [i, j]
        j_best = jnp.argmax(s_swap, axis=1)
        s_best = jnp.take_along_axis(s_swap, j_best[:, None], axis=1)[:, 0]
        at_cap = deg >= p.max_peers
        swap_ok = s_best > s0
        connect = can & (~at_cap | swap_ok)
        drop_j = can & at_cap & swap_ok

        # removals first, then additions (same-ms race policy, see header)
        r_i = jnp.where(drop_j, ids, m)
        r_j = jnp.where(drop_j, j_best, m)
        adj = adj.at[r_i, r_j].set(False, mode="drop")
        adj = adj.at[r_j, r_i].set(False, mode="drop")
        a_i = jnp.where(connect, ids, m)
        a_j = jnp.where(connect, s_idx, m)
        adj = adj.at[a_i, a_j].set(True, mode="drop")
        adj = adj.at[a_j, a_i].set(True, mode="drop")
        touched = touched | connect
        touched = touched | jnp.zeros(m, bool).at[a_j].set(connect, mode="drop")

        state = state._replace(proto=dict(state.proto, adj=adj, seen=seen))

        # ---- done checks for touched nodes (isFullyConnected, :226-248)
        done_now = touched & alive & (state.done_at == 0) & self._fully_connected(
            state.proto
        )
        rel = jnp.maximum(1, t - state.proto["start_time"])
        state = state._replace(
            done_at=jnp.where(done_now, rel, state.done_at)
        )
        return state, emissions

    def _fully_connected(self, proto):
        """score >= 3*|caps| and every own capability's subgraph reaches at
        least half that capability's alive holders (BFS -> boolean-matmul
        closure)."""
        alive, caps, adj = proto["alive"], proto["caps"], proto["adj"]
        m = self.m
        k = self._kc(adj, caps, caps)
        score_ok = self._score_from_counts(k) >= self.params.cap_per_node * PEERS_PER_CAP

        holders = caps & alive[:, None]  # [M, C]
        # cap-confined adjacency, reflexive closure by squaring (boolean
        # matmuls as int32 contractions)
        a_c = (
            adj[None, :, :]
            & holders.T[:, :, None]
            & holders.T[:, None, :]
        )  # [C, M, M]
        reach = (a_c | jnp.eye(m, dtype=bool)[None, :, :]).astype(jnp.int32)
        for _ in range(max(1, int(np.ceil(np.log2(max(2, m)))))):
            reach = jnp.minimum(reach + reach @ reach, 1)
        starts = (adj[None, :, :] & holders.T[:, None, :]).astype(jnp.int32)
        explored = (starts @ reach) > 0  # [C, i, k]: reachable holders
        explored = explored | jnp.eye(m, dtype=bool)[None, :, :]  # self counts
        count = jnp.sum(explored, axis=2).T  # [M, C]
        threshold = (jnp.sum(holders, axis=0) // 2)[None, :]  # [1, C]
        ok_c = jnp.where(caps, count >= threshold, True)
        return score_ok & jnp.all(ok_c, axis=1)

    def all_done(self, state):
        return jnp.all(
            jnp.where(state.proto["alive"], state.done_at > 0, True)
        )


def make_enr(
    params: Optional[ENRParameters] = None,
    horizon_ms: int = 4_000_000,
    capacity: int = 1 << 12,
    seed: int = 0,
):
    """Host-side construction: run the oracle's init() for the initial
    population (same caps/graph/changing-node draws), pre-sample the join/
    exit/beat schedule with the continuing rd stream, bake into the engine.

    `horizon_ms` bounds the join schedule: one slot per timeToLeave/8 beat
    up to the horizon (ENRGossiping.java:284-293); running past it simply
    stops producing joiners."""
    params = params or ENRParameters()
    oracle = ENRGossiping(params)
    oracle.init()
    onet = oracle.network()
    rd = onet.rd

    n0 = params.nodes
    period = params.time_to_leave // 8
    n_join = min(horizon_ms // period + 1, 4096)
    m = n0 + int(n_join)

    caps0 = np.zeros((m, params.number_of_different_capabilities), bool)
    adj0 = np.zeros((m, m), bool)
    alive0 = np.zeros(m, bool)
    for i, nd in enumerate(onet.all_nodes):
        alive0[i] = not nd.is_down()
        for cap in nd.capabilities:
            caps0[i, int(cap.split("_")[1])] = True
        for pr in nd.peers:
            adj0[i, pr.node_id] = True
    # future joiners: caps + schedule from the continuing rd stream
    born_at = np.zeros(m, np.int32)
    exit_at = np.full(m, INT32_MAX, np.int32)
    bcast0 = np.full(m, INT32_MAX, np.int32)
    change0 = np.full(m, INT32_MAX, np.int32)
    for j in range(n_join):
        i = n0 + j
        born_at[i] = j * period
        caps_set = set()
        while len(caps_set) < params.cap_per_node:
            caps_set.add(rd.next_int(params.number_of_different_capabilities))
        for cap_i in caps_set:
            caps0[i, cap_i] = True
        if j == 0:
            # the oracle's first joiner arrives at t=0, inside init: wire
            # it host-side (the jit birth mask only fires for t > 0)
            alive0[i] = True
            wired = 0
            while wired < params.total_peers:
                tgt = rd.next_int(n0 + 1)
                if tgt != i and alive0[tgt] and not adj0[i, tgt]:
                    adj0[i, tgt] = adj0[tgt, i] = True
                    wired += 1
        if born_at[i] > 1:
            exit_at[i] = born_at[i] + rd.next_int(params.time_to_leave)
        b = born_at[i] + rd.next_int(params.cap_gossip_time) + 1
        if b < exit_at[i]:
            bcast0[i] = b
    # initial nodes: broadcast beats (start() for t=0 nodes: no exit)
    for i in range(n0):
        bcast0[i] = rd.next_int(params.cap_gossip_time) + 1
    # capability-change schedule: the oracle drew these inside init(); the
    # draws here are fresh from the continuing stream (distribution-level)
    for nd in oracle.changed_nodes:
        change0[nd.node_id] = rd.next_int(params.time_to_change) + 1

    schedule = {
        "alive0": alive0,
        "caps0": caps0,
        "adj0": adj0,
        "born_at": born_at,
        "exit_at": exit_at,
        "bcast0": bcast0,
        "change0": change0,
    }
    proto = BatchedENR(params, m, schedule)

    latency = registry_network_latencies.get_by_name(params.network_latency_name)
    city_index = getattr(latency, "city_index", None)
    # node columns: oracle nodes + future joiners drawn with the same builder
    from ..core.node import Node

    nodes = list(onet.all_nodes)
    nb = registry_node_builders.get_by_name(params.node_builder_name)
    while len(nodes) < m:
        nodes.append(Node(rd, nb))
    cols = build_node_columns(nodes, city_index)
    # flat mode: the wake calendar schedules explicit arrivals up to the
    # whole sim horizon ahead (births/exits), far beyond the wheel window
    net = BatchedNetwork(proto, latency, m, capacity=capacity, wheel_rows=0)
    state = net.init_state(cols, seed=seed, proto=proto.proto_init(m))

    # t=0 fully-connected marks (start() -> set_done_at at birth): host-side
    import jax

    done0 = np.asarray(
        jax.jit(proto._fully_connected)(state.proto)
    ) & alive0
    state = state._replace(
        done_at=jnp.where(jnp.asarray(done0), jnp.int32(1), state.done_at)
    )
    return net, state

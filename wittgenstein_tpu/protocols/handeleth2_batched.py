"""Batched HandelEth2: multi-height Handel aggregation — three concurrent
aggregation processes per node sharing one verification core, multi-value
attestations (one bitset per head hash), exponential dissemination backoff.

Reference semantics: protocols/handeleth2/ (HandelEth2.java, HNode.java,
HLevel.java) via the oracle port `protocols/handeleth2.py`.

TPU-first design:

  * the three live processes (a new one every PERIOD_TIME=6000 ms, each
    living 3 periods) occupy a rotating slot axis P=3, slot = height % 3;
    starting height h and stopping height h-3 share a tick, exactly like
    the oracle's same-ms start/stop tasks;
  * multi-value contributions are a dense hash axis H=8: `create()`'s
    geometric hash draw (80% hash 0, HNode.java:62-73) exceeds 7 with
    probability 0.2^8 — clipped;
  * per-(process, level) state is `[N, P, L, H, W]` packed who-bitsets
    (incoming / individual / outgoing); cardinalities are derived by
    popcount instead of incrementally maintained (level blocks hold
    disjoint who-sets, so sums equal union sizes);
  * updateAllOutgoing's running merge is a prefix scan over the level
    axis; isOpen gates writes per level (HNode.java:208-231);
  * the verification core is one register per node: the verify beat
    (every nodePairingTime) selects by sizeIfMerged score — the window
    is computed but unused in the reference ("bestInside" dead code,
    HLevel.java:300-330) — and commits at t + pairingTime - 1;
  * the to-verify pool is a K-slot buffer per (process, level); arrivals
    land in the empty-or-worst slot by reception rank (the oracle's
    unbounded list minus entries its curation would drop anyway);
  * emission order: each node's per-level peer list (emission ranks,
    HandelEth2.java:103-147) is baked from the oracle's init; the
    get_remaining_peers cursor walk keeps the loop-detection
    (lastCardinalitySent / firstNodeWithBestCard, HLevel.java:123-157)
    for the single-destination cycle sends; fastPath bursts contact the
    next levelCount eligible peers from the cursor.

Scale note: state is O(N * P * L * H * N/32) words — right for the
reference's 64-256 node eth2 committee sims, not for 4096 (plain Handel's
packed single-value layout covers that regime)."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.node import build_node_columns
from ..core.registries import registry_network_latencies
from ..engine import BatchedNetwork, BatchedProtocol, Emission
from ..engine.rng import hash32, uniform_u01
from ..ops.bitops import popcount_words
from ..utils.more_math import log2
from .handeleth2 import (
    PERIOD_AGG_TIME,
    PERIOD_TIME,
    HandelEth2,
    HandelEth2Parameters,
)

P = 3  # concurrent processes
H = 8  # hash axis


class BatchedHandelEth2(BatchedProtocol):
    MSG_TYPES = ["AGG"]
    TICK_INTERVAL = 1
    CAND_SLOTS = 8

    def __init__(self, params: HandelEth2Parameters, roles: dict):
        self.params = params
        self.n_nodes = params.node_count
        self.lc = log2(self.n_nodes)  # levelCount
        self.nl = self.lc + 1  # levels 0..levelCount
        self.nw = max(1, self.n_nodes // 32)
        # payload: height, level, own_hash, level_finished, atts[H*W]
        self.PAYLOAD_WIDTH = 4 + H * self.nw
        self.rr = jnp.asarray(roles["reception_ranks"], jnp.int32)  # [N, N]
        # emission peer lists per level, -1 padded: [N, L, N/2]
        self.peers = jnp.asarray(roles["peers"], jnp.int32)
        self.pairing = jnp.asarray(roles["pairing"], jnp.int32)  # [N]
        # per-node start offset (HandelEth2.init: periodic tasks begin at
        # delta_start + 1); all beat tests run on the shifted clock t - delta
        self.delta = jnp.asarray(
            roles.get("delta", np.zeros(self.n_nodes, np.int32)), jnp.int32
        )

    def msg_size(self, mtype: int) -> int:
        return 1

    def proto_init(self, n_nodes: int):
        n, nl, nw, k = self.n_nodes, self.nl, self.nw, self.CAND_SLOTS
        zi = lambda s: jnp.zeros(s, jnp.int32)
        return {
            "height": jnp.zeros((n, P), jnp.int32),  # 0 = inactive slot
            "own_hash": zi((n, P)),
            "start_at": zi((n, P)),
            "fin_peers": jnp.zeros((n, P, nw), jnp.uint32),
            "rr_bump": zi((n, P, n)),
            "inc": jnp.zeros((n, P, nl, H, nw), jnp.uint32),
            "ind": jnp.zeros((n, P, nl, H, nw), jnp.uint32),
            "out": jnp.zeros((n, P, nl, H, nw), jnp.uint32),
            "out_fin": jnp.zeros((n, P, nl), bool),
            "last_sent": jnp.full((n, P, nl), -1, jnp.int32),
            "first_best": jnp.full((n, P, nl), -1, jnp.int32),
            "contacted": zi((n, P, nl)),
            "cycle_ct": zi((n, P, nl)),
            "pos": zi((n, P, nl)),
            # to-verify buffer
            "c_rank": jnp.full((n, P, nl, k), 2**31 - 1, jnp.int32),
            "c_from": zi((n, P, nl, k)),
            "c_hash": zi((n, P, nl, k)),
            "c_atts": jnp.zeros((n, P, nl, k, H, nw), jnp.uint32),
            # shared verification core
            "v_active": jnp.zeros(n, bool),
            "v_done_t": zi(n),
            "v_proc": zi(n),
            "v_level": zi(n),
            "v_from": zi(n),
            "v_hash": zi(n),
            "v_height": zi(n),
            "v_atts": jnp.zeros((n, H, nw), jnp.uint32),
            "last_vproc_h": zi(n),  # lastVerified process height
            "last_lvl": jnp.full((n, P), 2, jnp.int32),
            "window": jnp.full(n, 16, jnp.int32),
            "agg_done": zi(n),
            "contrib_total": zi(n),
            "next_height": jnp.full(n, 1001, jnp.int32),
        }

    # -- helpers -------------------------------------------------------------
    def _onehot_w(self, idx):
        cols = jnp.arange(self.nw, dtype=jnp.int32)
        bit = (jnp.uint32(1) << (idx % 32).astype(jnp.uint32)).astype(jnp.uint32)
        return jnp.where(cols == (idx // 32)[..., None], bit[..., None], jnp.uint32(0))

    def _card(self, who):  # popcount over (H, W) trailing axes
        return popcount_words(who.reshape(who.shape[:-2] + (-1,)))

    def _size_if_merged(self, inc_l, ind_l, cand):
        """sizeIfMerged (HLevel.java:160-196), vectorized over any leading
        axes: inc_l/ind_l [..., H, W], cand [..., H, W]."""
        our_c = popcount_words(inc_l)  # [..., H]
        av_c = popcount_words(cand)
        inter = popcount_words(inc_l & cand) > 0
        merged = popcount_words(ind_l | cand)
        per_hash = jnp.where(
            our_c == 0,
            av_c,
            jnp.where(~inter, our_c + av_c, jnp.maximum(merged, our_c)),
        )
        # hashes where the candidate has nothing keep our contribution
        per_hash = jnp.where(av_c == 0, our_c, per_hash)
        return jnp.sum(per_hash, axis=-1)

    def _next_peer(self, proto, sel_p, sel_l, count):
        """get_remaining_peers for `count` destinations from the cursor,
        skipping finished peers (blacklist is empty: nothing ever fails
        verification).  Returns (dests [N, count], ok [N, count])."""
        n = self.n_nodes
        ids = jnp.arange(n, dtype=jnp.int32)
        mp = self.peers.shape[2]
        plist = self.peers[ids, jnp.clip(sel_l, 0, self.nl - 1)]  # [N, mp]
        pos = proto["pos"][ids, sel_p, sel_l]
        fin = proto["fin_peers"][ids, sel_p]  # [N, nw]
        pv = jnp.clip(plist, 0, n - 1)
        fbit = (fin[ids[:, None], pv // 32] >> (pv % 32).astype(jnp.uint32)) & 1
        eligible = (plist >= 0) & (fbit == 0)
        # rotate eligibility by pos and take the first `count`
        idxs = (pos[:, None] + jnp.arange(mp, dtype=jnp.int32)[None, :]) % jnp.maximum(
            1, jnp.sum(plist >= 0, axis=1)
        )[:, None]
        rot_ok = jnp.take_along_axis(eligible, idxs, axis=1)
        rot_peer = jnp.take_along_axis(plist, idxs, axis=1)
        # rank eligible entries in rotated order
        order = jnp.cumsum(rot_ok.astype(jnp.int32), axis=1)
        dests, oks, steps = [], [], []
        for j in range(count):
            hit = rot_ok & (order == j + 1)
            any_hit = jnp.any(hit, axis=1)
            first = jnp.argmax(hit, axis=1)
            dests.append(
                jnp.where(
                    any_hit,
                    rot_peer[jnp.arange(n, dtype=jnp.int32), first],
                    0,
                )
            )
            oks.append(any_hit)
            steps.append(jnp.where(any_hit, first + 1, 0))
        return (
            jnp.stack(dests, 1),
            jnp.stack(oks, 1),
            jnp.max(jnp.stack(steps, 1), axis=1),
        )

    # -- per-tick ------------------------------------------------------------
    def tick(self, net, state):
        # ---- 1. verification commits (update at t = beat + pairing - 1) ---
        proto = dict(state.proto)
        proto, ems_fp = self._commit(net, state, proto)
        state = state._replace(proto=proto)
        for em in ems_fp:
            state = net.apply_emission(state, em)
        return state

    def tick_beat(self, net, state):
        """Sparse periodic phases, gated by the engine's real beat branch
        (BEAT_PERIOD; the start/stop beat at PERIOD_TIME lands on the same
        grid because PERIOD_TIME % period_duration_ms == 0 — enforced in
        make_handeleth2 before the attrs are set)."""
        p = self.params
        t = state.time
        live = ~state.down
        proto = dict(state.proto)

        # ---- 2. process start/stop beat (every PERIOD_TIME) ----------------
        tb = t - self.delta  # per-node shifted clock (desynchronized start)
        beat_start = live & (tb >= 1) & ((tb - 1) % PERIOD_TIME == 0)
        proto = self._start_stop(state, proto, beat_start)

        # ---- 3. dissemination beat (every period_duration_ms) --------------
        beat_diss = live & (tb >= 1) & ((tb - 1) % p.period_duration_ms == 0)
        proto, ems = self._dissemination(state, proto, beat_diss)

        state = state._replace(proto=proto)
        for em in ems:
            state = net.apply_emission(state, em)
        return state

    def tick_post(self, net, state):
        # ---- 4. verify beat (every nodePairingTime, per node) --------------
        t = state.time
        live = ~state.down
        proto = dict(state.proto)
        tb = t - self.delta
        beat_ver = live & (tb >= 1) & ((tb - 1) % self.pairing == 0)
        proto = self._select(state, proto, beat_ver)
        return state._replace(proto=proto)

    def _start_stop(self, state, proto, beat):
        """startNewAggregation + the expiring slot's stopAggregation
        (HNode.java:111-145, 468-486)."""
        n, nl, nw = self.n_nodes, self.nl, self.nw
        ids = jnp.arange(n, dtype=jnp.int32)
        h_new = proto["next_height"]
        slot = h_new % P
        old_h = proto["height"][ids, slot]
        stopping = beat & (old_h > 0)
        # contributionsTotal += last level's incoming+outgoing cardinality
        last_inc = proto["inc"][ids, slot, nl - 1]
        last_out = proto["out"][ids, slot, nl - 1]
        best = self._card(last_inc) + self._card(last_out)
        proto["contrib_total"] = proto["contrib_total"] + jnp.where(
            stopping, best, 0
        )
        proto["agg_done"] = proto["agg_done"] + stopping.astype(jnp.int32)

        # own hash: geometric (80% h=0) from the counter RNG
        hsh = jnp.zeros(n, jnp.int32)
        cont = jnp.ones(n, bool)
        for j in range(H - 1):
            u = uniform_u01(state.seed, jnp.int32(0xE717), ids, h_new, jnp.int32(j))
            cont = cont & (u < 0.2)
            hsh = hsh + cont.astype(jnp.int32)

        # reset the slot
        def slot_set(name, new_val):
            proto[name] = proto[name].at[ids, slot].set(
                jnp.where(
                    beat.reshape((n,) + (1,) * (proto[name].ndim - 2)),
                    new_val,
                    proto[name][ids, slot],
                ),
                mode="drop",
            )

        slot_set("height", jnp.where(beat, h_new, old_h))
        slot_set("own_hash", hsh)
        slot_set("start_at", jnp.broadcast_to(state.time, (n,)))
        slot_set("fin_peers", jnp.zeros((n, nw), jnp.uint32))
        slot_set("rr_bump", jnp.zeros((n, n), jnp.int32))
        inc0 = jnp.zeros((n, nl, H, nw), jnp.uint32)
        own_bit = self._onehot_w(ids)  # [N, nw]
        inc0 = inc0.at[ids, 0, hsh].set(own_bit)
        slot_set("inc", inc0)
        slot_set("ind", inc0)
        slot_set("out", jnp.zeros((n, nl, H, nw), jnp.uint32))
        of0 = jnp.zeros((n, nl), bool).at[:, 0].set(True)
        slot_set("out_fin", of0)
        slot_set("last_sent", jnp.full((n, nl), -1, jnp.int32))
        slot_set("first_best", jnp.full((n, nl), -1, jnp.int32))
        slot_set("contacted", jnp.zeros((n, nl), jnp.int32))
        slot_set("cycle_ct", jnp.zeros((n, nl), jnp.int32))
        slot_set("pos", jnp.zeros((n, nl), jnp.int32))
        slot_set("c_rank", jnp.full((n, nl, self.CAND_SLOTS), 2**31 - 1, jnp.int32))
        slot_set("last_lvl", jnp.full((n,), 2, jnp.int32))
        proto["next_height"] = jnp.where(beat, h_new + 1, h_new)
        return proto

    def _update_all_outgoing(self, proto, mask, now):
        """Prefix merge over levels for OPEN levels (HNode.java:208-231);
        mask [N, P] selects the processes to refresh."""
        # prefix[l] = union of incoming[0..l-1]
        inc = proto["inc"]  # [N, P, L, H, W]
        # OR-prefix over the level axis
        pre = lax.associative_scan(jnp.bitwise_or, inc, axis=2)
        shifted = jnp.concatenate(
            [jnp.zeros_like(pre[:, :, :1]), pre[:, :, :-1]], axis=2
        )
        is_open = self._is_open(proto, now)
        upd = mask[:, :, None] & is_open
        proto["out"] = jnp.where(upd[..., None, None], shifted, proto["out"])
        return proto

    def _is_open(self, proto, now):
        """isOpen per (N, P, L) (HLevel.java:106-117)."""
        nl = self.nl
        lr = jnp.arange(nl, dtype=jnp.int32)
        elapsed = proto["start_at"][:, :, None]
        return ~proto["out_fin"] & (
            (now - elapsed >= (lr[None, None, :] - 1) * self.params.level_wait_time)
            | (self._out_complete(proto))
        ) & (proto["height"][:, :, None] > 0) & (lr[None, None, :] > 0)

    def _out_complete(self, proto):
        lr = jnp.arange(self.nl, dtype=jnp.int32)
        peers_ct = jnp.where(lr == 0, 1, 1 << jnp.maximum(lr - 1, 0))
        return self._card(proto["out"]) == peers_ct[None, None, :]

    def _inc_complete(self, proto):
        lr = jnp.arange(self.nl, dtype=jnp.int32)
        peers_ct = jnp.where(lr == 0, 1, 1 << jnp.maximum(lr - 1, 0))
        return self._card(proto["inc"]) == peers_ct[None, None, :]

    def _dissemination(self, state, proto, beat):
        """doCycle over open levels of every live process
        (HNode.java:440-445, HLevel.java:80-93)."""
        p = self.params
        n, nl = self.n_nodes, self.nl
        ids = jnp.arange(n, dtype=jnp.int32)
        proto = self._update_all_outgoing(
            proto, beat[:, None] & (proto["height"] > 0), state.time
        )
        is_open = self._is_open(proto, state.time)
        proto["cycle_ct"] = proto["cycle_ct"] + (
            beat[:, None, None] & is_open
        ).astype(jnp.int32)
        m = proto["contacted"] // self.lc
        period = jnp.power(jnp.int32(3), jnp.clip(m, 0, 9))
        fire = beat[:, None, None] & is_open & (
            lax.rem(proto["cycle_ct"], period) == 0
        )

        ems = []
        for pi in range(P):
            pia = jnp.full(n, pi, jnp.int32)
            for l in range(1, nl):
                la = jnp.full(n, l, jnp.int32)
                f = fire[:, pi, l]
                dest, ok, step = self._next_peer(proto, pia, la, 1)
                d0, ok0 = dest[:, 0], ok[:, 0] & f
                # loop detection: same content to the same first peer
                card = self._card(proto["out"][ids, pi, l])
                looped = (card == proto["last_sent"][ids, pi, l]) & (
                    d0 == proto["first_best"][ids, pi, l]
                )
                send = ok0 & ~looped
                proto["pos"] = proto["pos"].at[ids, pi, l].add(
                    jnp.where(send, step, 0)
                )
                proto["contacted"] = proto["contacted"].at[ids, pi, l].add(
                    send.astype(jnp.int32)
                )
                newbest = send & (card > proto["last_sent"][ids, pi, l])
                proto["first_best"] = proto["first_best"].at[ids, pi, l].set(
                    jnp.where(newbest, d0, proto["first_best"][ids, pi, l])
                )
                proto["last_sent"] = proto["last_sent"].at[ids, pi, l].set(
                    jnp.where(newbest, card, proto["last_sent"][ids, pi, l])
                )
                ems.append(
                    self._agg_emission(proto, send[:, None], d0[:, None], pia, la)
                )
        return proto, ems

    def _agg_emission(self, proto, masks, dests, proc_idx, lvl_idx):
        """SendAggregation(level, ownHash, levelFinished, outgoing) to D
        destinations per node; proc_idx/lvl_idx are [N] dynamic indices."""
        n = self.n_nodes
        d = dests.shape[1]
        ids = jnp.arange(n, dtype=jnp.int32)
        out_l = proto["out"][ids, proc_idx, lvl_idx]  # [N, H, nw]
        inc_c = self._inc_complete(proto)[ids, proc_idx, lvl_idx]
        payload = jnp.concatenate(
            [
                proto["height"][ids, proc_idx][:, None],
                lvl_idx[:, None],
                proto["own_hash"][ids, proc_idx][:, None],
                inc_c[:, None].astype(jnp.int32),
                out_l.reshape(n, H * self.nw).astype(jnp.int32),
            ],
            axis=1,
        )
        nonempty = self._card(out_l) > 0
        return Emission(
            mask=(masks & nonempty[:, None]).reshape(-1),
            from_idx=jnp.repeat(ids, d),
            to_idx=jnp.clip(dests, 0, n - 1).reshape(-1),
            mtype=self.mtype("AGG"),
            payload=jnp.repeat(payload, d, axis=0),
        )

    # -- arrivals (onNewAgg, HNode.java:317-349) ----------------------------
    def deliver(self, net, state, deliver_mask):
        proto = dict(state.proto)
        n, nl, nw, k = self.n_nodes, self.nl, self.nw, self.CAND_SLOTS
        c = deliver_mask.shape[0]
        to, frm = state.msg_to, state.msg_from
        pay = state.msg_payload
        mh, ml = pay[:, 0], jnp.clip(pay[:, 1], 0, nl - 1)
        mhash, mfin = jnp.clip(pay[:, 2], 0, H - 1), pay[:, 3] == 1
        slot = (mh % P).astype(jnp.int32)
        ok = deliver_mask & (proto["height"][to, slot] == mh) & (mh > 0)

        # levelFinished -> finished_peers bit
        fin_bit = self._onehot_w(frm)  # [C, nw]
        w_to = jnp.where(ok & mfin, to, n)
        proto["fin_peers"] = proto["fin_peers"].at[w_to, slot].max(
            fin_bit, mode="drop"
        )

        # reception rank, then bump (HNode.java:338-341)
        rank = self.rr[to, frm] + proto["rr_bump"][to, slot, frm] * n
        proto["rr_bump"] = proto["rr_bump"].at[
            jnp.where(ok, to, n), slot, frm
        ].add(1, mode="drop")

        # insert into the to-verify buffer unless the level is complete;
        # winner-per-slot: lowest ring index fills the worst buffer slot
        inc_c = self._inc_complete(proto)[to, slot, ml]
        want = ok & ~inc_c
        atts = self._unpack_atts(pay)  # [C, H, nw]
        # one insertion per (node, proc, level) per tick: lowest ring slot
        ringslot = jnp.arange(c, dtype=jnp.int32)
        win = jnp.full((n, P, nl), c, jnp.int32)
        win = win.at[to, slot, ml].min(
            jnp.where(want, ringslot, c), mode="drop"
        )
        is_win = want & (win[to, slot, ml] == ringslot)
        # worst existing buffer slot by rank (max); replace if empty/worse
        worst = jnp.argmax(proto["c_rank"][to, slot, ml], axis=1)
        worst_rank = jnp.take_along_axis(
            proto["c_rank"][to, slot, ml], worst[:, None], axis=1
        )[:, 0]
        do_ins = is_win & (rank < worst_rank)
        wi_to = jnp.where(do_ins, to, n)
        proto["c_rank"] = proto["c_rank"].at[wi_to, slot, ml, worst].set(
            rank, mode="drop"
        )
        proto["c_from"] = proto["c_from"].at[wi_to, slot, ml, worst].set(
            frm, mode="drop"
        )
        proto["c_hash"] = proto["c_hash"].at[wi_to, slot, ml, worst].set(
            mhash, mode="drop"
        )
        proto["c_atts"] = proto["c_atts"].at[wi_to, slot, ml, worst].set(
            atts, mode="drop"
        )
        return state._replace(proto=proto), []

    def _unpack_atts(self, pay):
        c = pay.shape[0]
        return pay[:, 4 : 4 + H * self.nw].astype(jnp.uint32).reshape(c, H, self.nw)

    # -- verification core ---------------------------------------------------
    def _select(self, state, proto, beat):
        """verify (HNode.java:262-287) + AggregationProcess.bestToVerify
        (:148-175): next-height process first, else min height; level 1
        first, then the cycling level cursor."""
        n, nl, k = self.n_nodes, self.nl, self.CAND_SLOTS
        ids = jnp.arange(n, dtype=jnp.int32)
        free = beat & ~proto["v_active"] & jnp.any(proto["height"] > 0, axis=1)

        # candidate scores per (proc, level, slot), curated
        inc_c = self._inc_complete(proto)  # [N, P, L]
        valid = proto["c_rank"] < 2**31 - 1
        scores = self._size_if_merged(
            proto["inc"][:, :, :, None], proto["ind"][:, :, :, None], proto["c_atts"]
        )  # [N, P, L, K]
        cur_card = self._card(proto["inc"])  # [N, P, L]
        keep = valid & (scores > cur_card[..., None]) & ~inc_c[..., None]
        # purge: completed levels clear their buffers; non-improving drop
        proto["c_rank"] = jnp.where(keep, proto["c_rank"], 2**31 - 1)

        # best slot per (proc, level) by score
        sl_best = jnp.argmax(jnp.where(keep, scores, -1), axis=3)
        sl_score = jnp.take_along_axis(
            jnp.where(keep, scores, -1), sl_best[..., None], axis=3
        )[..., 0]
        has = sl_score > 0  # [N, P, L]

        # choose the process: lastVerified.height+1 if it has work, else
        # the minimum active height (approximation of the cursor: the
        # reference retries the same process until success)
        hts = proto["height"]  # [N, P]
        has_proc = jnp.any(has, axis=2)
        next_h = proto["last_vproc_h"] + 1
        is_next = (hts == next_h[:, None]) & (hts > 0) & has_proc
        minh = jnp.min(jnp.where((hts > 0) & has_proc, hts, 2**30), axis=1)
        is_min = (hts == minh[:, None]) & has_proc
        pick = jnp.where(jnp.any(is_next, axis=1)[:, None], is_next, is_min)
        proc_sel = jnp.argmax(pick, axis=1)
        proc_ok = jnp.any(pick, axis=1) & free

        # level: 1 first, else cycle from last_lvl (:148-175)
        has_p = has[ids, proc_sel]  # [N, L]
        lvl1 = has_p[:, 1] if nl > 1 else jnp.zeros(n, bool)
        start = jnp.clip(proto["last_lvl"][ids, proc_sel], 2, nl - 1)
        offs = jnp.arange(nl, dtype=jnp.int32)
        rot = 2 + lax.rem(start[:, None] - 2 + offs[None, :], jnp.maximum(1, nl - 2))
        rot_has = jnp.take_along_axis(
            has_p, jnp.clip(rot, 0, nl - 1), axis=1
        )
        first = jnp.argmax(rot_has, axis=1)
        lvl_cyc = jnp.take_along_axis(
            jnp.clip(rot, 0, nl - 1), first[:, None], axis=1
        )[:, 0]
        lvl_sel = jnp.where(lvl1, 1, lvl_cyc)
        lvl_ok = lvl1 | jnp.any(rot_has, axis=1)
        go = proc_ok & lvl_ok

        ks = sl_best[ids, proc_sel, lvl_sel]
        proto["last_vproc_h"] = jnp.where(
            go, proto["height"][ids, proc_sel], proto["last_vproc_h"]
        )
        proto["last_lvl"] = proto["last_lvl"].at[ids, proc_sel].set(
            jnp.where(go & ~lvl1, lvl_sel, proto["last_lvl"][ids, proc_sel]),
            mode="drop",
        )
        proto["v_active"] = proto["v_active"] | go
        proto["v_done_t"] = jnp.where(
            go, state.time + self.pairing - 1, proto["v_done_t"]
        )
        proto["v_proc"] = jnp.where(go, proc_sel, proto["v_proc"])
        proto["v_level"] = jnp.where(go, lvl_sel, proto["v_level"])
        proto["v_from"] = jnp.where(
            go, proto["c_from"][ids, proc_sel, lvl_sel, ks], proto["v_from"]
        )
        proto["v_hash"] = jnp.where(
            go, proto["c_hash"][ids, proc_sel, lvl_sel, ks], proto["v_hash"]
        )
        proto["v_height"] = jnp.where(
            go, proto["height"][ids, proc_sel], proto["v_height"]
        )
        proto["v_atts"] = jnp.where(
            go[:, None, None],
            proto["c_atts"][ids, proc_sel, lvl_sel, ks],
            proto["v_atts"],
        )
        # consume the buffer slot
        proto["c_rank"] = proto["c_rank"].at[
            jnp.where(go, ids, n), proc_sel, lvl_sel, ks
        ].set(2**31 - 1, mode="drop")
        return proto

    def _commit(self, net, state, proto):
        """updateVerifiedSignatures (HNode.java:181-205): merge, window
        growth, fastPath on level completion."""
        p = self.params
        n, nl, nw = self.n_nodes, self.nl, self.nw
        ids = jnp.arange(n, dtype=jnp.int32)
        due = proto["v_active"] & (state.time >= proto["v_done_t"])
        pi, l = proto["v_proc"], proto["v_level"]
        # the slot may have rotated to the NEXT height since selection —
        # match the height captured at selection, not just slot liveness
        still = due & (proto["height"][ids, pi] == proto["v_height"]) & (
            proto["v_height"] > 0
        )
        proto["v_active"] = proto["v_active"] & ~due

        inc_l = proto["inc"][ids, pi, l]  # [N, H, nw]
        ind_l = proto["ind"][ids, pi, l]
        cand = proto["v_atts"]
        # merge_incoming (HLevel.java:228-262) per hash
        our_c = popcount_words(inc_l)
        av_c = popcount_words(cand)
        inter = popcount_words(inc_l & cand) > 0
        merged_ind = ind_l | cand
        use_cand = (our_c == 0) | (~inter)
        grow = popcount_words(merged_ind) > our_c
        new_inc = jnp.where(
            (av_c > 0)[..., None],
            jnp.where(
                use_cand[..., None],
                inc_l | cand,
                jnp.where(grow[..., None], merged_ind, inc_l),
            ),
            inc_l,
        )
        new_ind = ind_l.at[jnp.arange(n, dtype=jnp.int32), proto["v_hash"]].max(
            self._onehot_w(proto["v_from"])
        )
        proto["inc"] = proto["inc"].at[jnp.where(still, ids, n), pi, l].set(
            new_inc, mode="drop"
        )
        proto["ind"] = proto["ind"].at[jnp.where(still, ids, n), pi, l].set(
            new_ind, mode="drop"
        )
        proto["window"] = jnp.where(
            still, jnp.minimum(128, proto["window"] * 2), proto["window"]
        )

        # fastPath: completing a level bursts the now-complete outgoing of
        # HIGHER levels to levelCount peers each (HNode.java:195-203; the
        # top level is excluded by the reference's bound, kept bug-for-bug)
        proto = self._update_all_outgoing(
            proto,
            jnp.zeros((n, P), bool).at[ids, pi].max(still, mode="drop"),
            state.time,
        )
        inc_done = self._inc_complete(proto)[ids, pi, l] & still & (l < self.lc)
        ems = []
        out_c = self._out_complete(proto)
        for lu in range(2, nl - 1):
            la = jnp.full(n, lu, jnp.int32)
            m = inc_done & (lu > l) & out_c[ids, pi, lu]
            dests, oks, step = self._next_peer(proto, pi, la, self.lc)
            rows = m[:, None] & oks
            proto["pos"] = proto["pos"].at[ids, pi, lu].add(jnp.where(m, step, 0))
            proto["contacted"] = proto["contacted"].at[ids, pi, lu].add(
                jnp.sum(rows, axis=1).astype(jnp.int32)
            )
            ems.append(self._agg_emission(proto, rows, dests, pi, la))
        return proto, ems

    def all_done(self, state):
        return jnp.asarray(False)


def make_handeleth2(
    params: Optional[HandelEth2Parameters] = None,
    capacity: int = 1 << 14,
    seed: int = 0,
):
    """Host-side construction from the oracle init (reception + emission
    ranks use the same JavaRandom stream)."""
    params = params or HandelEth2Parameters()
    oracle = HandelEth2(params)
    oracle.init()
    nodes = oracle.network().all_nodes
    n = len(nodes)
    lc = log2(n)
    rr = np.zeros((n, n), np.int32)
    for nd in nodes:
        rr[nd.node_id] = nd.reception_ranks
    mp = max(1, n // 2)
    peers = np.full((n, lc + 1, mp), -1, np.int32)
    for nd in nodes:
        if nd.is_down():
            continue
        for l in range(1, lc + 1):
            for j, pr in enumerate(nd.peers_per_level[l]):
                peers[nd.node_id, l, j] = pr.node_id
    pairing = np.array(
        [max(1, getattr(nd, "node_pairing_time", params.pairing_time)) for nd in nodes],
        np.int32,
    )
    delta = np.array([nd.delta_start for nd in nodes], np.int32)
    roles = {
        "reception_ranks": rr,
        "peers": peers,
        "pairing": pairing,
        "delta": delta,
    }
    latency = registry_network_latencies.get_by_name(params.network_latency_name)
    city_index = getattr(latency, "city_index", None)
    cols = build_node_columns(nodes, city_index)
    proto = BatchedHandelEth2(params, roles)
    # beat gating: node i's tick_beat fires at t ≡ 1 + delta_i
    # (mod period_duration_ms); the PERIOD_TIME start/stop beat lands on the
    # same grid.  With desynchronized starts the residue set is the distinct
    # (1 + delta_i) values — if that covers the whole period, run_ms_batched
    # falls back to the ungated vmap path on its own.
    if PERIOD_TIME % params.period_duration_ms == 0:
        pd = params.period_duration_ms
        proto.BEAT_PERIOD = pd
        proto.BEAT_RESIDUES = tuple(sorted({(1 + int(d)) % pd for d in delta}))
        # send_ctr compensation: _dissemination emits P*(nl-1) ring
        # emissions per call (one per (process, level))
        proto.BEAT_SEND_CALLS = P * (proto.nl - 1)
    net = BatchedNetwork(proto, latency, n, capacity=capacity)
    down = np.array([nd.is_down() for nd in nodes])
    state = net.init_state(
        cols, seed=seed, proto=proto.proto_init(n), down=down
    )
    return net, state

"""SanFerminCappos: San Fermin variant with multi-candidate swaps, per-level
signature caches and a per-level timeout instead of per-request replies.

Reference semantics: protocols/SanFerminCappos.java (onSwap state machine
:201-241, tryNextNodes + timeout :248-296, goNextLevel :306-344,
totalNumberOfSigs cache reduction :351-358, putCachedSig threshold check
:382-393).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from ..core import stats as SH
from ..core.params import WParameters, register_protocol
from ..core.registries import registry_network_latencies, registry_node_builders
from ..core.node import Node
from ..oracle.messages import Message
from ..oracle.network import Network, Protocol
from ..utils.more_math import log2
from .sanfermin_helper import SanFerminHelper, to_binary_id


@dataclasses.dataclass
class SanFerminParameters(WParameters):
    node_count: int = 32768 // 16
    threshold: int = 32768 // 32
    pairing_time: int = 2
    signature_size: int = 48
    timeout: int = 150
    candidate_count: int = 50
    node_builder_name: Optional[str] = None
    network_latency_name: Optional[str] = None
    verbose: bool = False


class Swap(Message):
    def __init__(self, p: "SanFerminCappos", level: int, agg_value: int, want_reply: bool):
        self._p = p
        self.level = level
        self.agg_value = agg_value
        self.want_reply = want_reply

    def action(self, network, from_node, to_node):
        to_node.on_swap(from_node, self)

    def size(self) -> int:
        return 4 + self._p.params.signature_size


class SanFerminNode(Node):
    __slots__ = (
        "binary_id",
        "helper",
        "current_prefix_length",
        "signature_cache",
        "is_swapping",
        "agg_value",
        "threshold_at",
        "threshold_done",
        "done",
        "_p",
    )

    def __init__(self, p: "SanFerminCappos", nb):
        super().__init__(p.network().rd, nb)
        self._p = p
        self.binary_id = to_binary_id(self, p.params.node_count)
        self.helper: Optional[SanFerminHelper] = None
        self.done = False
        self.threshold_done = False
        self.threshold_at = 0
        self.agg_value = 1
        self.is_swapping = False
        self.current_prefix_length = log2(p.params.node_count)
        self.signature_cache: Dict[int, List[int]] = {}

    def on_swap(self, from_node: "SanFerminNode", swap: Swap) -> None:
        """(SanFerminCappos.java:201-241)."""
        want_reply = swap.want_reply
        if self.done or swap.level != self.current_prefix_length:
            is_value_cached = swap.level in self.signature_cache
            if want_reply and is_value_cached:
                self._print(
                    f"sending back CACHED signature at level {swap.level} "
                    f"to node {from_node.binary_id}"
                )
                self._send_swap(
                    [from_node], swap.level, self._get_best_cached_sig(swap.level), False
                )
            else:
                is_candidate = self.helper.is_candidate(from_node, swap.level)
                is_valid_sig = True  # as always :)
                if is_candidate and is_valid_sig:
                    self._put_cached_sig(swap.level, swap.agg_value)
            return

        if want_reply:
            self._send_swap(
                [from_node], swap.level, self.total_number_of_sigs(swap.level), False
            )

        good_level = swap.level == self.current_prefix_length
        is_candidate = self.helper.is_candidate(from_node, self.current_prefix_length)
        is_valid_sig = True
        if is_candidate and good_level and is_valid_sig:
            if not self.is_swapping:
                self._transition(
                    " received valid SWAP ", from_node.binary_id, swap.level, swap.agg_value
                )
        else:
            self._print(
                f" received  INVALID Swapfrom {from_node.binary_id} at level {swap.level}"
            )
            self._print(f"   ---> {is_valid_sig} - {good_level} - {is_candidate}")

    def _try_next_nodes(self, candidates: List["SanFerminNode"]) -> None:
        """(SanFerminCappos.java:248-296)."""
        p, net = self._p, self._p.network()
        if not candidates:
            self._print(" is OUT (no more nodes to pick)")
            return
        for n in candidates:
            if not self.helper.is_candidate(n, self.current_prefix_length):
                raise RuntimeError(
                    f"currentPrefixlength={self.current_prefix_length} "
                    f"vs helper.currentLevel={self.helper.current_level}"
                )
        self._print(
            " send Swaps to " + " - ".join(n.binary_id for n in candidates)
        )
        self._send_swap(
            candidates,
            self.current_prefix_length,
            self.total_number_of_sigs(self.current_prefix_length + 1),
            True,
        )

        curr_level = self.current_prefix_length

        def on_timeout():
            if not self.done and self.current_prefix_length == curr_level:
                self._print(f"TIMEOUT of SwapRequest at level {curr_level}")
                next_nodes = self.helper.pick_next_nodes(
                    self.current_prefix_length, p.params.candidate_count
                )
                self._try_next_nodes(next_nodes)

        net.register_task(on_timeout, net.time + p.params.timeout, self)

    def go_next_level(self) -> None:
        """(SanFerminCappos.java:306-344)."""
        p, net = self._p, self._p.network()
        if self.done:
            return

        enough_sigs = self.total_number_of_sigs(self.current_prefix_length) >= p.params.threshold
        no_more_swap = self.current_prefix_length == 0

        if enough_sigs and not self.threshold_done:
            self._print(" --- THRESHOLD REACHED --- ")
            self.threshold_done = True
            self.threshold_at = net.time + p.params.pairing_time * 2

        if no_more_swap and not self.done:
            self._print(" --- FINISHED ---- protocol")
            self.done_at = net.time + p.params.pairing_time * 2
            p.finished_nodes.append(self)
            self.done = True
            return
        self.current_prefix_length -= 1
        self.is_swapping = False

        if self.current_prefix_length in self.signature_cache:
            self._print(
                f" FUTURe value at new level{self.current_prefix_length} saved. "
                "Moving on directly !"
            )
            self.go_next_level()
            return
        new_nodes = self.helper.pick_next_nodes(
            self.current_prefix_length, p.params.candidate_count
        )
        self._try_next_nodes(new_nodes)

    def _send_swap(self, nodes: List["SanFerminNode"], level: int, value: int, want_reply: bool):
        r = Swap(self._p, level, value, want_reply)
        self._p.network().send(r, self, nodes)

    def total_number_of_sigs(self, level: int) -> int:
        """Sum of the best cached sig at each level >= `level`, + own sig
        (SanFerminCappos.java:351-358)."""
        return (
            sum(max(v) for lvl, v in self.signature_cache.items() if lvl >= level) + 1
        )

    def _transition(self, type_: str, from_id: str, level: int, to_aggregate: int) -> None:
        p, net = self._p, self._p.network()
        self.is_swapping = True

        def do_aggregate():
            self._print(f" received {type_} lvl={level} from {from_id}")
            self._put_cached_sig(level, to_aggregate)
            self.go_next_level()

        net.register_task(do_aggregate, net.time + p.params.pairing_time, self)

    def _get_best_cached_sig(self, level: int) -> int:
        return max(self.signature_cache.get(level, []))

    def _put_cached_sig(self, level: int, value: int) -> None:
        self.signature_cache.setdefault(level, []).append(value)
        enough_sigs = self.total_number_of_sigs(self.current_prefix_length) >= self._p.params.threshold
        if enough_sigs and not self.threshold_done:
            self._print(" --- THRESHOLD REACHED --- ")
            self.threshold_done = True
            self.threshold_at = self._p.network().time + self._p.params.pairing_time * 2

    def _print(self, s: str) -> None:
        if self._p.params.verbose:
            net = self._p.network()
            print(
                f"t={net.time}, id={self.binary_id}, lvl={self.current_prefix_length}, "
                f"sent={self.msg_sent} -> {s}"
            )

    def __repr__(self) -> str:
        return (
            f"SanFerminNode{{nodeId={self.binary_id}, thresholdAt={self.threshold_at}, "
            f"doneAt={self.done_at}, sigs={self.total_number_of_sigs(-1)}, "
            f"msgReceived={self.msg_received}, msgSent={self.msg_sent}, "
            f"KBytesSent={self.bytes_sent // 1024}, KBytesReceived={self.bytes_received // 1024}}}"
        )


@register_protocol("SanFerminCappos", SanFerminParameters)
class SanFerminCappos(Protocol):
    def __init__(self, params: SanFerminParameters):
        self.params = params
        self._network: Network[SanFerminNode] = Network()
        self.nb = registry_node_builders.get_by_name(params.node_builder_name)
        self._network.set_network_latency(
            registry_network_latencies.get_by_name(params.network_latency_name)
        )
        self.all_nodes: List[SanFerminNode] = []
        self.finished_nodes: List[SanFerminNode] = []

    def network(self) -> Network:
        return self._network

    def init(self) -> None:
        """Nodes are built in init (unlike SanFerminSignature, which builds
        them in the constructor — a reference quirk; SanFerminCappos.java:120-134)."""
        self.all_nodes = []
        for _ in range(self.params.node_count):
            n = SanFerminNode(self, self.nb)
            self.all_nodes.append(n)
            self._network.add_node(n)
        for n in self.all_nodes:
            n.helper = SanFerminHelper(n, self.all_nodes, self._network.rd)
        self.finished_nodes = []
        for n in self.all_nodes:
            self._network.register_task(n.go_next_level, 1, n)

    def copy(self) -> "SanFerminCappos":
        return SanFerminCappos(self.params)


def sigs_per_time(node_ct: int = 1024, limit: int = 6000, graph_path: Optional[str] = None):
    """Scenario main (SanFerminCappos.java:465-518)."""
    from ..core.registries import RANDOM, builder_name

    nl = "NetworkLatencyByDistanceWJitter"
    nb = builder_name(RANDOM, True, 0)
    ps1 = SanFerminCappos(SanFerminParameters(node_ct, node_ct // 2, 2, 48, 150, 50, nb, nl))
    ps1.init()
    while ps1.network().time < limit:
        ps1.network().run_ms(10)
    print("bytes sent:", SH.get_stats_on(ps1.all_nodes, lambda n: n.bytes_sent))
    print("msg sent:", SH.get_stats_on(ps1.all_nodes, lambda n: n.msg_sent))
    print(
        "done at:",
        SH.get_stats_on(
            ps1.network().all_nodes, lambda n: limit if n.done_at == 0 else n.done_at
        ),
    )
    return ps1


if __name__ == "__main__":
    sigs_per_time()
